"""Derived-datatype equivalents — mpi7/mpi8/mpi-complex-types parity.

Three reference programs in one example, each using a slice spec instead
of a committed MPI datatype:
- indexed blocks of a 16-float array broadcast to all ranks (mpi7);
- Particle records {4 floats; 2 ints} scattered from root (mpi8) — the
  struct type is a pytree, struct-of-arrays;
- runs of three separately-allocated arrays sent as one payload
  (mpi-complex-types) — pointer displacements become list indices.
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main() -> None:
    ensure_devices()
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from tpuscratch.comm import broadcast, run_spmd, scatter_from_root
    from tpuscratch.dtypes import HIndexedSpec, IndexedSpec, StructSpec
    from tpuscratch.runtime.mesh import make_mesh_1d

    mesh = make_mesh_1d("x")
    n = mesh.devices.size

    banner("indexed blocks (mpi7)")
    spec = IndexedSpec(((5, 4), (12, 2)))  # len 4 @ 5, len 2 @ 12
    data = jnp.arange(16.0)
    f = run_spmd(mesh, lambda x: broadcast(spec.pack(x), "x"), P(), P(None))
    print("root's blocks as 6 plain floats on every rank:", np.asarray(f(data))[:6])

    banner("struct scatter (mpi8)")
    particles = {
        "pos": jnp.arange(2 * n, dtype=jnp.float32),
        "vel": jnp.arange(2 * n, dtype=jnp.float32) * 2,
        "id": jnp.arange(2 * n, dtype=jnp.int32),
    }
    sspec = StructSpec(("pos", "vel", "id"))
    sspec.validate(particles)
    g = run_spmd(
        mesh,
        lambda t: jax.tree.map(lambda a: scatter_from_root(a, "x"), t),
        P(),
        P("x"),
    )
    out = g(particles)
    print(f"2 particles per rank; rank 1 got ids {np.asarray(out['id'])[2:4]}")

    banner("nested slices of separate arrays (mpi-complex-types)")
    a, b, c = jnp.arange(10.0), jnp.arange(10.0, 20.0), jnp.arange(20.0, 30.0)
    hspec = HIndexedSpec(
        (
            (0, IndexedSpec(((2, 3),))),
            (1, IndexedSpec(((0, 3),))),
            (2, IndexedSpec(((5, 3),))),
        )
    )
    payload = hspec.pack([a, b, c])
    print("one payload from 3 arrays:", np.asarray(payload))


if __name__ == "__main__":
    main()
