"""Flight recorder + goodput: every second of a chaotic run, accounted.

The reference's performance lens is hand-placed clock() brackets printed
per segment; this example flies the second observability layer over a
deliberately messy training run — a chaos-injected NaN forces a guard
rollback mid-run — and shows the artifacts a production fleet debugs
from:

1. the **flight recorder**: train/chunk, ckpt/save, and rollback spans
   in a bounded ring, exported as Chrome trace-event JSON (open the
   printed file in Perfetto / chrome://tracing) and schema-validated;
2. the **goodput report**: the run's JSONL event stream partitioned into
   goodput vs badput buckets — compile, checkpoint, rollback replay —
   that provably sum to the wall time, plus MFU from the static ledger
   FLOPs against a stated peak;
3. the **straggler lens**: per-phase per-rank skew through the mesh
   collectives (mesh_reduce max/min), naming a seeded slow rank.

argv tier:  ex27_tracing.py [--steps=N]
"""

import json
import pathlib
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main(argv=None) -> None:
    ensure_devices()
    import numpy as np

    from tpuscratch.ft import ChaosPlan, Fault, GuardPolicy
    from tpuscratch.models import TransformerConfig
    from tpuscratch.models.trainer import train
    from tpuscratch.models.transformer import init_params, train_step
    from tpuscratch.obs import (
        FlightRecorder,
        Sink,
        analyze,
        goodput_report,
        mesh_straggler,
        validate_chrome_trace,
    )
    from tpuscratch.obs import report as obs_report
    from tpuscratch.runtime.config import Config
    from tpuscratch.runtime.mesh import make_mesh

    cli = Config.load(argv)
    # two chunks of 3 steps; the NaN at step 4 rolls the second chunk back
    steps = max(cli.steps, 6) if "steps" in cli.explicit else 6
    mesh = make_mesh((1, 1), ("dp", "sp"))
    cfg = TransformerConfig(d_model=16, n_heads=2, n_experts=2, d_ff=32,
                            n_layers=1, capacity_factor=2.0)
    workdir = tempfile.mkdtemp(prefix="tpuscratch_trace_")
    path = f"{workdir}/run.jsonl"

    banner("1. chaotic training under the flight recorder")
    rec = FlightRecorder()
    plan = ChaosPlan(0, [Fault("train/grad", at=(4,), kind="nan")])
    with Sink(path, run={"example": "ex27"}) as sink:
        _, rep = train(
            mesh, cfg, steps=steps, save_every=3,
            ckpt_dir=f"{workdir}/ckpt", seed=3, obs=sink, recorder=rec,
            chaos=plan, guard=GuardPolicy(max_skips=0, max_rollbacks=1),
        )
    print(f"ran {rep.steps_run} steps, skipped {rep.skipped}, "
          f"rollbacks {rep.rollbacks}")
    assert rep.rollbacks == 1, "the injected NaN should have rolled back"

    banner("2. Chrome trace export (load in Perfetto)")
    trace = rec.chrome_trace(pid=0, label="trainer")
    n = validate_chrome_trace(trace)
    trace_path = f"{workdir}/trace.json"
    with open(trace_path, "w") as f:
        json.dump(trace, f)
    phases = rec.phase_totals()
    for name in sorted(phases):
        ph = phases[name]
        print(f"  {name:<16} {ph.count:3d} span(s)  "
              f"{ph.seconds * 1e3:8.2f} ms total")
    print(f"{n} trace events validated (paired B/E, monotonic ts)")
    print(f"trace written to {trace_path} — open it at ui.perfetto.dev")

    banner("3. goodput report: MFU + the badput breakdown")
    params = init_params(3, cfg)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 8, cfg.d_model)).astype(np.float32)
    led = analyze(train_step(mesh, cfg), params, x, x)
    events = obs_report.load_events([path])
    gp = goodput_report(events, flops_per_step=led.flops,
                        peak_flops_per_s=1e12)
    print(gp.summary())
    gp.check()  # buckets partition the wall exactly, by construction
    assert gp.buckets["rollback"] > 0, "rollback badput must be visible"
    assert gp.buckets["checkpoint"] > 0
    assert gp.steps == steps
    print("buckets sum to wall time: PASSED")

    banner("4. straggler detection on a 2x2 mesh (seeded slow rank)")
    mesh22 = make_mesh((2, 2), ("dp", "sp"))
    per_rank = [0.101, 0.100, 0.502, 0.099]  # rank 2 is the straggler
    sr = mesh_straggler(mesh22, "train/chunk", per_rank)
    print(f"  {sr.summary()}")
    assert sr.slowest == 2 and sr.skew > 4.0
    print("\ntracing & goodput loop PASSED")


if __name__ == "__main__":
    main()
