"""The generalized remote-DMA halo kernel: corners on the wire, steps in
the kernel.

The reference's exchange serves any stencil width — ghost depth is
``stencil/2`` and the plan carries the 8 edge + corner transfers
(/root/reference/stencil2d/stencil2D.h:116-117, 381-437). This driver
shows the framework's structural equivalent, ``ops.halo_dma``: ONE
Pallas kernel per device holding the core VMEM-resident for the whole
run, moving ghost traffic by double-buffered async remote DMA under the
interior compute, in its two generalized forms:

1. ``impl='dma'`` with 9-point coefficients — the corner blocks ride
   four diagonal DMA channels next to the edge strips;
2. ``impl='dma-deep:k'`` — one k-deep exchange buys k fused substeps
   inside the kernel (the communication-avoiding trapezoid, with the
   messages on the DMA engine instead of XLA-scheduled collectives).

Both are checked against the plain exchange-then-compute trajectory.

argv tier:  ex20_dma_halo.py [--steps=N] [--depth=K]
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main(argv=None) -> None:
    ensure_devices()
    import numpy as np

    from tpuscratch.halo.driver import distributed_stencil
    from tpuscratch.runtime.config import Config
    from tpuscratch.runtime.mesh import make_mesh_2d

    cfg = Config.load(argv)
    steps = cfg.steps if "steps" in cfg.explicit else 5
    depth = cfg.depth if "depth" in cfg.explicit else 2
    mesh = make_mesh_2d((2, 4))
    rng = np.random.default_rng(20)
    world = rng.standard_normal((16, 32)).astype(np.float32)
    c9 = (0.125, 0.125, 0.125, 0.125, 0.0625, 0.0625, 0.0625, 0.0625, 0.0)

    banner(f"remote-DMA halo: 9-point corners + depth-{depth} fold, "
           f"{steps} steps on 2x4")

    nine_dma = distributed_stencil(world, steps, mesh, coeffs=c9, impl="dma")
    nine_ref = distributed_stencil(world, steps, mesh, coeffs=c9, impl="xla")
    err9 = np.abs(nine_dma - nine_ref).max()
    print(f"9-point, corners on the DMA channels: max err {err9:.2e}")

    deep = distributed_stencil(world, steps, mesh, impl=f"dma-deep:{depth}")
    ref = distributed_stencil(world, steps, mesh, impl="xla")
    errd = np.abs(deep - ref).max()
    print(f"5-point, {depth} substeps folded per exchange: "
          f"max err {errd:.2e}")

    ok = err9 < 1e-5 and errd < 1e-5
    print("both match the plain exchange trajectory "
          f"({'PASSED' if ok else 'FAILED'})")


if __name__ == "__main__":
    main()
