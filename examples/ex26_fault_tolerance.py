"""Fault tolerance: chaos-injected faults, guarded training, supervision.

The reference's only answer to failure is raise-or-MPI_Abort (mpierr.h);
tpuscratch.ft treats failure as the steady state.  This example injects
a deterministic fault schedule into one training run — a NaN'd gradient
step, a transient checkpoint-IO failure, and a simulated preemption —
and shows the stack absorb ALL of it: the guard skips and rolls the NaN
chunk back, the retry policy absorbs the IO fault, the supervisor
restarts through the preemption and resumes from the last checkpoint —
finishing with params BIT-IDENTICAL to a fault-free run (the rollback
replays the consumed one-shot fault cleanly).

argv tier:  ex26_fault_tolerance.py [--steps=N]
"""

import pathlib
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main(argv=None) -> None:
    ensure_devices()
    import jax
    import numpy as np

    from tpuscratch.ft import (
        ChaosPlan,
        Fault,
        GuardPolicy,
        supervise_train,
    )
    from tpuscratch.models import TransformerConfig
    from tpuscratch.models.trainer import train
    from tpuscratch.obs.metrics import MetricsRegistry
    from tpuscratch.runtime.config import Config
    from tpuscratch.runtime.mesh import make_mesh

    cli = Config.load(argv)
    # the injected schedule below pins faults to steps 3 and 4, so the
    # demo needs at least two chunks past them
    steps = max(cli.steps, 6) if "steps" in cli.explicit else 6
    mesh = make_mesh((1, 2), ("dp", "sp"), jax.devices()[:2])
    cfg = TransformerConfig(d_model=16, n_heads=2, n_experts=2, d_ff=32,
                            n_layers=1, capacity_factor=2.0)
    workdir = tempfile.mkdtemp(prefix="tpuscratch_ft_")

    banner("fault tolerance: chaos -> guard -> retry -> supervisor")

    clean, _ = train(mesh, cfg, steps, f"{workdir}/clean", save_every=3,
                     seed=3)
    print(f"oracle: {steps} fault-free steps trained")

    plan = ChaosPlan(0, [
        # one poisoned batch: NaN flows through the unmodified compiled
        # step into the loss and every gradient leaf
        Fault("train/grad", at=(4,), kind="nan"),
        # one transient checkpoint-IO failure at the manifest stage
        Fault("ckpt/save", stage="manifest", at=(0,)),
        # one preemption at the first chunk boundary (after its save)
        Fault("train/preempt", at=(3,), kind="preempt"),
    ])
    metrics = MetricsRegistry()
    params, rep = supervise_train(
        mesh, cfg, steps, f"{workdir}/chaos", save_every=3, seed=3,
        chaos=plan, guard=GuardPolicy(max_skips=0, max_rollbacks=2),
        metrics=metrics,
        log=lambda s: print(f"  [ft] {s}"),
    )
    restarts = int(metrics.counter("ft/restarts").value)
    print(f"faults injected: {plan.stats()}")
    print(f"survived: skipped={rep.skipped} rollbacks={rep.rollbacks} "
          f"restarts={restarts} final_step={rep.final_step}")
    assert sum(plan.stats().values()) == 3
    assert restarts == 1 and rep.rollbacks >= 1

    identical = all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(clean), jax.tree.leaves(params))
    )
    assert identical, "chaos run diverged from the fault-free oracle"
    print("chaos-run params bit-identical to the fault-free run: PASSED")


if __name__ == "__main__":
    main()
