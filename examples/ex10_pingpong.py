"""Pingpong latency/bandwidth probe — test-benchmark parity.

The reference times one round trip of N doubles between two GPUs through
GPU-direct MPI, verifies the echo, and prints PASSED with times
(/root/reference/test-benchmark/mpi-pingpong-gpu.cpp). Here the round trip
is a ppermute pair over the mesh interconnect (ICI on TPU); the host
staging ablation shows what device-resident arrays save.
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main(argv=None) -> None:
    ensure_devices()
    from tpuscratch.bench.pingpong import host_staging_roundtrip, sweep, verify_echo
    from tpuscratch.runtime.config import Config
    from tpuscratch.runtime.mesh import make_mesh_1d

    # argv tier: ex10_pingpong.py [max_message_bytes]
    # (message size from argv = mpi-pingpong-gpu.cpp:31)
    cfg = Config.load(argv)
    sizes = (8, 1024, 65536, 1 << 20)
    if "elements" in cfg.explicit:
        # sweep the presets below the requested size AND the size itself
        sizes = tuple(
            sorted({s for s in sizes if s < cfg.elements} | {cfg.elements})
        )
    banner("pingpong (test-benchmark)")
    mesh = make_mesh_1d("x")
    ok = verify_echo(mesh, "x", 4096)
    print(f"echo self-check: {'PASSED' if ok else 'FAILED'}")
    for res in sweep(mesh, sizes_bytes=sizes, iters=5):
        print(" ", res.summary())
    print(" ", host_staging_roundtrip(1 << 18, iters=5).summary(), "(ablation)")


if __name__ == "__main__":
    main()
