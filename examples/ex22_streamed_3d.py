"""Deep-z streamed 3D stencil: k substeps per HBM pass (impl='stream:k').

Round 4's flagship kernel (ops/stencil_stream.py): the measured ~330
GB/s DMA-fabric copy bound caps every per-step Pallas form, so this
kernel folds ``depth`` Jacobi substeps into each manual double-buffered
DMA pass — per-step HBM traffic divides by ``depth`` (1.062e11 cells/s
on v5e at 256x512x512, 2.72x the per-step compact-asm kernel, BASELINE
row 9).  Serves z-slab decompositions: one depth-k ghost-slab exchange
per k steps (the 2D deep:k trapezoid one dimension up; ghost depth as a
parameter ≙ /root/reference/stencil2d/stencil2D.h:116-117), periodic or
open z, 7-point AND 27-point coefficients — the full-extent slabs carry
the edge/corner neighbor data a 27-point stencil needs with no extra
machinery.

Self-checks: stream trajectories equal the compact core-carry path for
7-point periodic, 7-point open-z, and 27-point.

argv tier:  ex22_streamed_3d.py [--steps=S] [--impl=stream:K]
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main(argv=None) -> None:
    ensure_devices()
    import numpy as np

    from tpuscratch.halo.halo3d import distributed_stencil3d
    from tpuscratch.runtime.config import Config
    from tpuscratch.runtime.mesh import make_mesh

    cfg = Config.load(argv)
    n = 16
    steps = cfg.steps if "steps" in cfg.explicit else 5
    impl = cfg.impl if "impl" in cfg.explicit else "stream:2"
    banner(
        f"deep-z streamed 3D stencil, {2 * n}x{n}x{n} over 2 z-slabs, "
        f"{steps} steps, impl {impl}"
    )

    rng = np.random.default_rng(22)
    world = rng.standard_normal((2 * n, n, n)).astype(np.float32)
    mesh = make_mesh((2, 1, 1), ("z", "row", "col"))

    ok = True
    a = distributed_stencil3d(world, steps, mesh, impl=impl)
    b = distributed_stencil3d(world, steps, mesh, impl="compact")
    err = np.abs(a - b).max()
    ok &= err < 1e-4
    print(f"7-point periodic: stream vs compact max err {err:.2e}")

    a = distributed_stencil3d(world, steps, mesh, impl=impl,
                              periodic=(False, True, True))
    b = distributed_stencil3d(world, steps, mesh, impl="compact",
                              periodic=(False, True, True))
    err = np.abs(a - b).max()
    ok &= err < 1e-4
    print(f"7-point open-z:   stream vs compact max err {err:.2e} "
          "(zero ghosts re-imposed every folded substep)")

    c27 = tuple(np.linspace(0.01, 0.26, 26)) + (0.3,)
    a = distributed_stencil3d(world, steps, mesh, coeffs=c27, impl=impl)
    b = distributed_stencil3d(world, steps, mesh, coeffs=c27,
                              impl="compact")
    err = np.abs(a - b).max()
    ok &= err < 1e-4
    print(f"27-point:         stream vs compact max err {err:.2e} "
          "(corners implicit in the full-extent slabs)")

    print("PASSED" if ok else "FAILED")


if __name__ == "__main__":
    main()
