"""Distributed dot product with kernel strategy selection and timing —
mpicuda2/3/4 parity.

The reference shards two big vectors across ranks, reduces each shard on
the GPU (three kernel strategies), then MPI_Reduces to rank 0, timing the
whole thing with the max-min convention (SURVEY.md §2.3). Here: shard via
in_specs, Pallas kernel per shard ('partials' = two-phase,
'full' = single-kernel accumulator — no atomics needed, TPU grids are
sequential), one psum, block_until_ready-bracketed timing.
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main(argv=None) -> None:
    ensure_devices()
    from tpuscratch.bench.dot_bench import bench_dot
    from tpuscratch.runtime.config import Config
    from tpuscratch.runtime.mesh import make_mesh_1d

    # argv tier (mpi-pingpong-gpu.cpp:31 / mpicuda argv parity):
    #   ex08_dot_product.py [elements] [--impl=full|partials|xla]
    cfg = Config.load(argv)
    banner("distributed dot product (mpicuda2-4)")
    mesh = make_mesh_1d("x")
    n = cfg.elements if "elements" in cfg.explicit else 1 << 22
    methods = (cfg.impl,) if cfg.impl else ("full", "partials", "xla")
    for method in methods:
        res = bench_dot(mesh, n_elems=n, method=method, iters=3)
        print(res.summary())
    print("self-check vs n*1.0: PASSED (bench_dot asserts internally)")


if __name__ == "__main__":
    main()
