"""Shared example plumbing: device bring-up and pretty printing.

Every example mirrors one reference program (the mpi1..mpi10 progression,
the CUDA dot products, the stencil drivers, the pingpong benchmarks —
SURVEY.md §2). Most need several devices; by default each example runs on
a virtual CPU mesh of 8 devices — the same single-box testing trick the
reference uses by running many MPI ranks on one node (mpicuda2.cu:31-32).
Set TPUSCRATCH_ON_DEVICE=1 on a real multi-chip host to use the hardware
mesh instead.
"""

from __future__ import annotations

N_DEVICES = 8


def ensure_devices(n: int = N_DEVICES):
    """Return jax with >= n devices (virtual CPU mesh unless opted out)."""
    from tpuscratch.runtime import hostenv

    try:
        return hostenv.ensure_devices(n)
    except RuntimeError as e:
        raise SystemExit(str(e)) from None


def banner(title: str) -> None:
    print(f"== {title} ==")
