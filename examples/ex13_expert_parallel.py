"""Expert parallelism: routed MoE FFN with all_to_all dispatch/combine.

Beyond-parity capability (SURVEY.md §2.7 lists EP as absent from the
reference): tokens are scored by a gate, packed into per-expert capacity
slots, exchanged over the expert axis with one all_to_all each way, and
combined back weighted by gate probability. Checked against a dense
no-drop oracle; the load-balance loss is printed for a uniform and a
collapsed router.
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main(argv=None) -> None:
    jax = ensure_devices()
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from tpuscratch.comm import run_spmd
    from tpuscratch.parallel.expert import expert_parallel_ffn
    from tpuscratch.runtime.config import Config
    from tpuscratch.runtime.mesh import make_mesh_1d

    # argv tier: ex13_expert_parallel.py [tokens_per_rank]
    cfg = Config.load(argv)
    banner("expert parallelism (routed MoE over an expert axis)")
    mesh = make_mesh_1d("ep")
    n = mesh.devices.size
    per_rank = cfg.elements if "elements" in cfg.explicit else 8
    T, D, F = per_rank * n, 16, 32  # T/n tokens per rank, one expert per rank
    rng = np.random.default_rng(0)
    x = rng.standard_normal((T, D)).astype(np.float32)
    gate_w = rng.standard_normal((D, n)).astype(np.float32)
    w_in = (rng.standard_normal((n, D, F)) * 0.1).astype(np.float32)
    w_out = (rng.standard_normal((n, F, D)) * 0.1).astype(np.float32)

    def body(x, gate_w, w_in, w_out):
        out, aux = expert_parallel_ffn(
            x, gate_w, w_in, w_out, "ep", capacity_factor=float(n), k=1
        )
        return out, jax.lax.pmean(aux, "ep")

    f = run_spmd(
        mesh, body, (P("ep"), P(), P("ep"), P("ep")), (P("ep"), P())
    )
    got, aux = f(x, gate_w, w_in, w_out)

    # dense no-drop oracle: top-1 expert applied per token
    logits = x @ gate_w
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    choice = probs.argmax(-1)
    want = np.stack(
        [
            probs[t, choice[t]]
            * (np.maximum(x[t] @ w_in[choice[t]], 0.0) @ w_out[choice[t]])
            for t in range(T)
        ]
    )
    err = float(np.max(np.abs(np.asarray(got) - want)))
    counts = np.bincount(choice, minlength=n)
    print(f"{T} tokens -> {n} experts, routed counts {counts.tolist()}")
    print(f"aux load-balance loss {float(np.asarray(aux)):.3f} (1.0 = uniform)")
    print(f"max |EP - dense oracle| = {err:.2e} -> "
          f"{'PASSED' if err < 1e-4 else 'FAILED'}")


if __name__ == "__main__":
    main()
