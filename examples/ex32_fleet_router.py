"""Fleet router: prefix-affine routing over N engine replicas, demonstrated.

Everything below one engine is built (paged int8/fp8 KV, prefix
sharing, chunked prefill, disaggregation, tiered host KV) — but
"millions of users" means MANY engines, and without a front end every
replica is an island: a request landing on the wrong replica
re-prefills a prefix another replica already holds.  ISSUE 14's
``serve.router.FleetRouter`` owns the fleet queue and dispatches by
longest held prefix (falling back to least-loaded), tags requests with
per-tenant SLO classes, and — on disagg fleets — re-roles replicas
between the prefill and decode pools from the staged-handoff backlog.

Demonstrated and self-checked here:

1. **routing bit-identity** — the same multi-tenant stream through 1
   replica, 3 replicas with affinity, and 3 without emits IDENTICAL
   greedy tokens: routing moves WHERE work runs, never what comes out;
2. **affinity savings, statically proven** — fleet counters reconcile
   exactly (``prefill + shared == submitted`` prompt tokens) and
   ``prefill_frac`` drops when affinity concentrates tenants; the
   shared total is NOT page-quantized (sub-page boundary sharing);
3. **per-class SLO reporting** — a latency-tagged and a
   throughput-tagged tenant drain together, and the report carries
   each class's p50/p99 TTFT and token rate.

argv tier:  ex32_fleet_router.py [--replicas=N]
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main(argv=None) -> None:
    ensure_devices()
    import jax

    from tpuscratch.bench.decode_bench import arrival_mix_requests
    from tpuscratch.models import TransformerConfig
    from tpuscratch.runtime.mesh import make_mesh
    from tpuscratch.serve import (
        FleetRouter,
        RouterConfig,
        SLOClass,
        ServeConfig,
        ServeEngine,
    )

    argv = list(sys.argv[1:]) if argv is None else list(argv)
    n_replicas = 3
    for a in argv:
        if a.startswith("--replicas="):
            n_replicas = int(a.split("=", 1)[1])

    banner("ex32: fleet router — prefix-affine routing over "
           f"{n_replicas} replicas")
    cfg = TransformerConfig(d_model=32, n_heads=4, n_experts=4, d_ff=48,
                            n_layers=1, capacity_factor=4.0)
    mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
    scfg = ServeConfig(n_slots=4, n_pages=32, page_size=4, max_seq=32,
                      vocab=32, prefix_share=True)
    classes = (SLOClass("latency", target="ttft"),
               SLOClass("batch", target="throughput"))

    # two tenants, each drawing from its own shared-prefix pool, the
    # latency tenant arriving 3x as often (the config-17 workload)
    tagged = arrival_mix_requests(
        (("latency", 3.0), ("batch", 1.0)),
        n_requests=12, length=13, vocab=scfg.vocab, max_new=4,
    )

    def fleet(n, affinity):
        return FleetRouter(
            [ServeEngine(mesh, cfg, scfg) for _ in range(n)],
            RouterConfig(affinity=affinity, classes=classes),
        )

    # 1. routing bit-identity: 1 replica == N affinity == N least-loaded
    one = fleet(1, True).run(tagged)
    aff = fleet(n_replicas, True).run(tagged)
    off = fleet(n_replicas, False).run(tagged)
    assert aff.outputs == one.outputs, "affinity routing changed output!"
    assert off.outputs == one.outputs, "least-loaded routing changed output!"
    print(f"bit-identity: {one.completed} requests emit identical "
          f"streams on 1 and {n_replicas} replicas, affinity on/off")

    # 2. the static sharing proof, fleet-wide
    for rep in (one, aff, off):
        assert rep.prefill_tokens + rep.shared_tokens == \
            rep.submitted_prompt_tokens, "fleet counter law violated"
    print(f"counter law: {aff.prefill_tokens} prefilled + "
          f"{aff.shared_tokens} shared == "
          f"{aff.submitted_prompt_tokens} submitted, on every arm")
    assert aff.prefill_frac <= off.prefill_frac, \
        "affinity failed to concentrate sharing"
    print(f"affinity: prefill_frac {off.prefill_frac:.3f} -> "
          f"{aff.prefill_frac:.3f} ({aff.affinity_hits} prefix-routed "
          f"dispatches, {aff.affinity_tokens} matched tokens, "
          f"dispatch {list(aff.dispatched)})")
    # sub-page sharing: the 9-token (2 pages + 1) tenant prefixes end
    # mid-page, and the boundary token is still shared
    assert aff.subpage_tokens > 0, "sub-page rung never exercised"
    print(f"sub-page: {aff.subpage_tokens} boundary tokens shared past "
          f"page-aligned matches — savings not quantized to page_size")

    # 3. per-class SLO reporting
    for c in aff.classes:
        assert c.completed > 0 and c.ttft_p99_s >= c.ttft_p50_s > 0
        print(f"class {c.name:8s}: {c.completed} done, "
              f"TTFT p50 {c.ttft_p50_s * 1e3:7.2f} ms / "
              f"p99 {c.ttft_p99_s * 1e3:7.2f} ms, "
              f"{c.tokens_per_s:8.1f} tok/s")

    # 4. macro-step replicas (ISSUE 15): the same fleet contract with
    # each replica fusing 4 engine ticks into one compiled scan —
    # outputs identical, and with one decoding stream per replica the
    # dispatch identity holds exactly per replica and fleet-wide:
    # dispatches == sum over replicas of ceil(slot_steps / T).
    import dataclasses as _dc
    import math

    from tpuscratch.serve import Request

    T = 4
    macro_reqs = [Request(rid=2000 + i, prompt=(1 + i, 2, 3), max_new=10)
                  for i in range(2)]

    def duo(macro_steps):
        reps = [ServeEngine(mesh, cfg,
                            _dc.replace(scfg, macro_steps=macro_steps))
                for _ in range(2)]
        rtr = FleetRouter(reps, RouterConfig(affinity=False,
                                             classes=classes))
        return reps, rtr.run([("batch", r) for r in macro_reqs])

    _, m1 = duo(1)
    reps4, m4 = duo(T)
    assert m4.outputs == m1.outputs, "macro fleet output diverged"
    want = sum(math.ceil(r.slot_steps / T) for r in reps4)
    assert m4.dispatches == want, (m4.dispatches, want)
    assert m4.host_syncs == m4.dispatches
    assert m4.dispatches < m1.dispatches, "macro saved no dispatches"
    print(f"macro T={T}: fleet outputs identical; decode dispatches "
          f"{m1.dispatches} -> {m4.dispatches} "
          f"(= sum per-replica ceil(slot_steps/{T}))")

    print("PASSED")


if __name__ == "__main__":
    main()
