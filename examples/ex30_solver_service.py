"""Solver service: a supervised, communication-avoiding multigrid run.

ROADMAP item 5 closed end-to-end: the reference repo's actual workload
(3D stencil solve) operated the way the serving stack is — the solve
runs in checkpointed chunks under the ft supervisor, a chaos plan
injects a preemption AND a transient comm fault mid-run, and the result
is BIT-IDENTICAL to the fault-free run.  The obs sink's event stream
then yields the goodput breakdown (solver chunks -> step bucket,
checkpoint saves -> checkpoint bucket, buckets summing to wall exactly),
and a config-15-style measurement records the communication-avoiding
ablation: s-step smoothing halves the per-sweep ppermute launches
(ledger-read off the compiled HLO) at an unchanged cycle count.
"""

import pathlib
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main(argv=None) -> None:
    ensure_devices()
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from tpuscratch.comm import run_spmd
    from tpuscratch.ft import ChaosPlan, Fault
    from tpuscratch.halo.halo3d import HaloSpec3D, TileLayout3D
    from tpuscratch.obs import ledger as obs_ledger
    from tpuscratch.obs.goodput import goodput_report
    from tpuscratch.obs.metrics import MetricsRegistry
    from tpuscratch.obs.report import load_events
    from tpuscratch.obs.sink import open_sink
    from tpuscratch.runtime.mesh import make_mesh, topology_of
    from tpuscratch.solvers import (
        checkpointed_mg3d_solve,
        supervised_mg3d_solve,
    )
    from tpuscratch.solvers.multigrid3d import rbgs_smooth3, rbgs_smooth3_deep

    n = 16  # 8^3 per rank on the 2x2x2 mesh
    mesh = make_mesh((2, 2, 2), ("z", "row", "col"), jax.devices()[:8])
    rng = np.random.default_rng(0)
    b = rng.standard_normal((n, n, n)).astype(np.float32)
    b -= b.mean()
    workdir = tempfile.mkdtemp(prefix="tpuscratch_ex30_")

    banner("solver service: supervised + communication-avoiding multigrid")

    # 1. the fault-free oracle (chunked, checkpointed, s-step smoothing)
    clean, rep0 = checkpointed_mg3d_solve(
        b, f"{workdir}/clean", mesh=mesh, tol=1e-6, chunk_cycles=3, s_step=2
    )
    print(f"oracle: {rep0.cycles} cycles to relres {rep0.relres:.2e} "
          f"in {rep0.chunks} chunks")

    # 2. the same solve through chaos: preempted after the first chunk's
    #    save, then a transient CommError before the third chunk
    plan = ChaosPlan(0, [
        Fault("solver/preempt", at=(3,), kind="preempt"),
        Fault("comm/solver_chunk", at=(6,)),
    ])
    metrics = MetricsRegistry()
    sink_path = f"{workdir}/obs.jsonl"
    sink = open_sink(sink_path)
    chaotic, rep = supervised_mg3d_solve(
        b, f"{workdir}/chaos", mesh=mesh, tol=1e-6, chunk_cycles=3,
        s_step=2, chaos=plan, metrics=metrics, sink=sink,
        log=lambda s: print(f"  [ft] {s}"),
    )
    restarts = int(metrics.counter("ft/restarts").value)
    print(f"faults injected: {plan.stats()}  restarts: {restarts}")
    assert sum(plan.stats().values()) == 2 and restarts == 2
    assert rep.converged and rep.resumed_at > 0
    assert np.array_equal(clean, chaotic), "chaos run diverged from oracle"
    print("preempted+faulted run bit-identical to the fault-free oracle")

    # 3. what the wall time bought: the solver's goodput breakdown
    gp = goodput_report(load_events([sink_path]))
    gp.check()  # buckets sum to wall EXACTLY, by construction
    print(f"goodput: {100 * gp.goodput_fraction:.1f}% of "
          f"{gp.wall_s:.3f}s wall; badput "
          + ", ".join(f"{k}={v:.3f}s" for k, v in gp.badput.items()))

    # 4. config-15-style CA measurement: the s-step smoother's collective
    #    budget, ledger-read off the compiled HLO (per-sweep launches)
    topo = topology_of(mesh, periodic=True)
    spec = HaloSpec3D(
        layout=TileLayout3D((n // 2,) * 3, (1, 1, 1)), topology=topo,
        axes=("z", "row", "col"), neighbors=6,
    )
    sp = P("z", "row", "col", None, None, None)
    arg = jnp.zeros((2, 2, 2) + (n // 2,) * 3, jnp.float32)

    def permutes(fn, sweeps):
        prog = run_spmd(
            mesh, lambda a, f: fn(a[0, 0, 0], f[0, 0, 0])[None, None, None],
            (sp, sp), sp,
        )
        led = obs_ledger.analyze(prog, arg, arg)
        return led.count("collective-permute") / sweeps

    per_sweep = permutes(lambda u, f: rbgs_smooth3(u, f, spec, 1), 1)
    deep = permutes(lambda u, f: rbgs_smooth3_deep(u, f, spec, 2, 2), 2)
    print(f"rbgs smoothing ppermute launches/sweep: {per_sweep:.0f} "
          f"(exchange-every-half-sweep) -> {deep:.0f} (s-step, s=2)")
    assert per_sweep == 12 and deep == 6

    print("solver service survived chaos bit-identically, goodput "
          "accounted, CA launch drop ledger-proven: PASSED")


if __name__ == "__main__":
    main()
