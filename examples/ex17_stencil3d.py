"""3D halo exchange + 7-point stencil over a 2x2x2 device torus.

The flagship one dimension up (the reference stops at 2D,
/root/reference/stencil2d/): per-face slab ppermutes over a 3-axis mesh,
7-point Jacobi diffusion, checked against the undecomposed-grid oracle.

argv tier:  ex17_stencil3d.py [--steps=N]
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main(argv=None) -> None:
    ensure_devices()
    import numpy as np

    from tpuscratch.halo.halo3d import distributed_stencil3d
    from tpuscratch.runtime.config import Config
    from tpuscratch.runtime.mesh import make_mesh

    cfg = Config.load(argv)
    steps = cfg.steps
    mesh = make_mesh((2, 2, 2), ("z", "row", "col"))
    Z, Y, X = 8, 16, 16
    banner(f"3D stencil: {Z}x{Y}x{X} world on a 2x2x2 torus, {steps} steps")

    rng = np.random.default_rng(0)
    world = rng.standard_normal((Z, Y, X)).astype(np.float32)
    got = distributed_stencil3d(world, steps, mesh)
    expect = world.astype(np.float64)
    for _ in range(steps):
        expect = (
            np.roll(expect, 1, 0) + np.roll(expect, -1, 0)
            + np.roll(expect, 1, 1) + np.roll(expect, -1, 1)
            + np.roll(expect, 1, 2) + np.roll(expect, -1, 2)
        ) / 6.0
    err = np.abs(got - expect).max()
    print(f"max |distributed - global| after {steps} steps: {err:.2e} "
          f"({'PASSED' if err < 1e-5 else 'FAILED'})")

    banner("27-point stencil over the 26-neighbor exchange")
    from tpuscratch.halo.halo3d import OFFSETS26

    w = np.linspace(0.005, 0.05, 26)
    coeffs = tuple(w) + (0.2,)
    got27 = distributed_stencil3d(world, 2, mesh, coeffs=coeffs)
    expect = world.astype(np.float64)
    for _ in range(2):
        new = 0.2 * expect
        for (dz, dy, dx), ww in zip(OFFSETS26, w):
            new = new + ww * np.roll(
                np.roll(np.roll(expect, -dz, 0), -dy, 1), -dx, 2
            )
        expect = new
    err27 = np.abs(got27 - expect).max()
    print(f"27-point (edges + corners travel too): err {err27:.2e} "
          f"({'PASSED' if err27 < 1e-4 else 'FAILED'})")

    banner("3D multigrid: periodic Poisson in O(1) V-cycles")
    from tpuscratch.solvers import mg_poisson3d_solve

    b = rng.standard_normal((Z, Y, X)).astype(np.float32)
    b -= b.mean()
    x, cycles, relres = mg_poisson3d_solve(b, mesh, tol=1e-6)
    resid = np.abs(
        6 * x.astype(np.float64)
        - sum(np.roll(x.astype(np.float64), s, a)
              for a in range(3) for s in (1, -1))
        - b
    ).max()
    print(f"{Z}x{Y}x{X} solved in {cycles} cycles, relres {relres:.1e}, "
          f"|Ax-b| {resid:.1e} "
          f"({'PASSED' if cycles <= 14 and resid < 1e-4 else 'FAILED'})")


if __name__ == "__main__":
    main()
