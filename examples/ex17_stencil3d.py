"""3D halo exchange + 7-point stencil over a 2x2x2 device torus.

The flagship one dimension up (the reference stops at 2D,
/root/reference/stencil2d/): per-face slab ppermutes over a 3-axis mesh,
7-point Jacobi diffusion, checked against the undecomposed-grid oracle.

argv tier:  ex17_stencil3d.py [--steps=N]
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main(argv=None) -> None:
    ensure_devices()
    import numpy as np

    from tpuscratch.halo.halo3d import distributed_stencil3d
    from tpuscratch.runtime.config import Config
    from tpuscratch.runtime.mesh import make_mesh

    cfg = Config.load(argv)
    steps = cfg.steps
    mesh = make_mesh((2, 2, 2), ("z", "row", "col"))
    Z, Y, X = 8, 16, 16
    banner(f"3D stencil: {Z}x{Y}x{X} world on a 2x2x2 torus, {steps} steps")

    rng = np.random.default_rng(0)
    world = rng.standard_normal((Z, Y, X)).astype(np.float32)
    got = distributed_stencil3d(world, steps, mesh)
    expect = world.astype(np.float64)
    for _ in range(steps):
        expect = (
            np.roll(expect, 1, 0) + np.roll(expect, -1, 0)
            + np.roll(expect, 1, 1) + np.roll(expect, -1, 1)
            + np.roll(expect, 1, 2) + np.roll(expect, -1, 2)
        ) / 6.0
    err = np.abs(got - expect).max()
    print(f"max |distributed - global| after {steps} steps: {err:.2e} "
          f"({'PASSED' if err < 1e-5 else 'FAILED'})")


if __name__ == "__main__":
    main()
