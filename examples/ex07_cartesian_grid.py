"""2D cartesian topology with 4-neighbor exchange — mpi10 parity.

The reference builds a sqrt(N) x sqrt(N) non-periodic grid, finds each
rank's 4-neighborhood with MPI_Cart_shift, and exchanges ids with 8
nonblocking ops + waitall (/root/reference/mpi10.cpp:27-54). Here the
topology is a value object whose shift tables compile into four ppermutes.
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main() -> None:
    ensure_devices()
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from tpuscratch.comm import run_spmd
    from tpuscratch.runtime.mesh import make_mesh_2d, topology_of
    from tpuscratch.runtime.topology import Direction

    banner("cartesian 4-neighborhood (mpi10)")
    mesh = make_mesh_2d((2, 4))
    topo = topology_of(mesh, periodic=False)
    print("grid (rank map):")
    print(topo.grid_string())

    def body(x):
        received = []
        for d in (Direction.TOP, Direction.BOTTOM, Direction.LEFT, Direction.RIGHT):
            perm = topo.send_permutation(d.opposite)  # receive from d
            # send rank+1: the zero fill decodes to -1, distinct from rank 0
            received.append(lax.ppermute(x + 1.0, ("row", "col"), perm) - 1.0)
        return tuple(received)

    ids = jnp.arange(topo.size, dtype=jnp.float32).reshape(topo.dims)
    f = run_spmd(
        mesh, body, P("row", "col"), tuple(P("row", "col") for _ in range(4))
    )
    top, bottom, left, right = (np.asarray(o) for o in f(ids))
    for r in range(topo.size):
        rr, cc = topo.coords(r)
        print(
            f"rank {r} ({rr},{cc}): top={top[rr, cc]:.0f} bottom={bottom[rr, cc]:.0f} "
            f"left={left[rr, cc]:.0f} right={right[rr, cc]:.0f}  [-1 = none]"
        )


if __name__ == "__main__":
    main()
