"""The flagship: 2D stencil with periodic halo exchange — stencil2d parity.

Mirrors the reference drivers (/root/reference/stencil2d/
mpi-2d-stencil-subarray{.cpp,-cuda.cu}): a periodic process grid, per-rank
tiles with ghost borders initialized to the rank id (halo = -1), one
exchange, and per-rank dumps named by grid coordinates — then goes beyond
the reference's no-op Compute: several real 5-point iterations, checked
against the undecomposed-grid oracle.
"""

import os
import pathlib
import shutil
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main(argv=None) -> None:
    ensure_devices()
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from tpuscratch.comm import run_spmd
    from tpuscratch.halo import HaloSpec, TileLayout, halo_exchange
    from tpuscratch.halo.driver import distributed_stencil
    from tpuscratch.runtime.config import Config
    from tpuscratch.runtime.log import coord_filename
    from tpuscratch.runtime.mesh import make_mesh_2d, topology_of

    # argv tier, the reference driver's CLI (-cuda.cu:131-138):
    #   ex09_stencil2d.py [tile_w tile_h [stencil_w stencil_h]]
    #                     [--steps=N] [--impl=xla|pallas|blocked|overlap]
    cfg = Config.load(argv)
    tile_h = cfg.tile_height if "tile_height" in cfg.explicit else 8
    tile_w = cfg.tile_width if "tile_width" in cfg.explicit else 8
    banner("stencil2d halo exchange (flagship)")
    mesh = make_mesh_2d((2, 4))
    topo = topology_of(mesh, periodic=True)
    if cfg.stencil_height // 2 < 1 or cfg.stencil_width // 2 < 1:
        raise SystemExit(
            f"stencil {cfg.stencil_height}x{cfg.stencil_width} has no ghost "
            "ring (halo = stencil//2 = 0); use >= 2x2"
        )
    lay = TileLayout.for_stencil(
        tile_h, tile_w, cfg.stencil_height, cfg.stencil_width
    )
    spec = HaloSpec(layout=lay, topology=topo, axes=tuple(mesh.axis_names))

    hy, hx = lay.halo_y, lay.halo_x
    tiles = np.full((2, 4) + lay.padded_shape, -1.0, dtype=np.float32)
    for r in topo.ranks():
        rr, cc = topo.coords(r)
        tiles[rr, cc, hy:-hy, hx:-hx] = r

    f = run_spmd(
        mesh,
        lambda x: halo_exchange(x[0, 0], spec)[None, None],
        P("row", "col", None, None),
        P("row", "col", None, None),
    )
    out = np.asarray(f(jnp.asarray(tiles)))

    outdir = pathlib.Path(tempfile.mkdtemp(prefix="stencil2d_"))
    for r in topo.ranks():
        rr, cc = topo.coords(r)
        path = outdir / coord_filename((rr, cc))
        with path.open("w") as fh:
            fh.write(f"Rank: {r}\nCoord: {rr}, {cc}\n\nArray after exchange\n")
            for row in out[rr, cc]:
                fh.write(" ".join(f"{v:.0f}" for v in row) + "\n")
    print(f"per-rank dumps written to {outdir} (cf. stencil2d/sample-output)")
    if "PYTEST_CURRENT_TEST" in os.environ:  # don't leak dumps from CI runs
        shutil.rmtree(outdir, ignore_errors=True)
    print("rank 0 tile after exchange (core=0, halo=neighbor ids):")
    print(np.array2string(out[0, 0], precision=0))

    steps = cfg.steps
    banner(f"real compute: {steps} Jacobi iterations vs global oracle")
    rng = np.random.default_rng(0)
    world = rng.standard_normal((2 * tile_h * 4, 4 * tile_w * 2)).astype(np.float32)
    got = distributed_stencil(world, steps=steps, mesh=mesh,
                              impl=cfg.impl or "xla")
    expect = world
    for _ in range(steps):
        expect = 0.25 * (
            np.roll(expect, 1, 0) + np.roll(expect, -1, 0)
            + np.roll(expect, 1, 1) + np.roll(expect, -1, 1)
        )
    err = np.abs(got - expect).max()
    print(f"max |distributed - global| after {steps} steps: {err:.2e} "
          f"({'PASSED' if err < 1e-5 else 'FAILED'})")


if __name__ == "__main__":
    main()
