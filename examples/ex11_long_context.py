"""Long-context sequence parallelism: ring attention and Ulysses.

Beyond-parity capability (the reference has no attention anywhere —
SURVEY.md §2.7 maps its ring/neighbor exchange and blockwise reduction as
the structural ancestors): the sequence dimension is sharded over the
mesh, KV blocks rotate by ppermute (ring) or heads swap by all_to_all
(Ulysses), and both must agree with single-device attention on the
gathered sequence.
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main(argv=None) -> None:
    jax = ensure_devices()
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from tpuscratch.comm import run_spmd
    from tpuscratch.parallel.ring_attention import ring_attention
    from tpuscratch.parallel.ulysses import ulysses_attention
    from tpuscratch.runtime.config import Config
    from tpuscratch.runtime.mesh import make_mesh_1d

    # argv tier: ex11_long_context.py [per_rank_seq_len]
    cfg = Config.load(argv)
    banner("long-context sequence parallelism (ring + Ulysses)")
    mesh = make_mesh_1d("seq")
    n = mesh.devices.size
    S = cfg.elements if "elements" in cfg.explicit else 16
    H, D = 8, 32  # per-rank block: global sequence = n*S
    rng = np.random.default_rng(0)
    q, k, v = (
        jnp.asarray(rng.standard_normal((n * S, H, D)).astype(np.float32))
        for _ in range(3)
    )

    # single-device oracle on the gathered sequence
    def oracle(q, k, v, causal):
        s = jnp.einsum("shd,thd->hst", q, k) / np.sqrt(D)
        if causal:
            mask = jnp.tril(jnp.ones((n * S, n * S), dtype=bool))
            s = jnp.where(mask[None], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("hst,thd->shd", p, v)

    for causal in (False, True):
        want = oracle(q, k, v, causal)
        ring = run_spmd(
            mesh,
            lambda q, k, v, c=causal: ring_attention(q, k, v, "seq", causal=c),
            (P("seq"), P("seq"), P("seq")),
            P("seq"),
        )(q, k, v)
        err_r = float(jnp.max(jnp.abs(ring - want)))
        uly = run_spmd(
            mesh,
            lambda q, k, v, c=causal: ulysses_attention(q, k, v, "seq", causal=c),
            (P("seq"), P("seq"), P("seq")),
            P("seq"),
        )(q, k, v)
        err_u = float(jnp.max(jnp.abs(uly - want)))
        # the same schemes with the Pallas flash kernel doing the math:
        # per-hop for the ring, post-all_to_all for Ulysses
        errs = {}
        for label, fn in (
            ("ring+flash", lambda q, k, v, c=causal: ring_attention(
                q, k, v, "seq", causal=c, impl="pallas")),
            ("ulysses+flash", lambda q, k, v, c=causal: ulysses_attention(
                q, k, v, "seq", causal=c, impl="pallas")),
        ):
            got = run_spmd(
                mesh, fn, (P("seq"), P("seq"), P("seq")), P("seq")
            )(q, k, v)
            errs[label] = float(jnp.max(jnp.abs(got - want)))
        worst = max(err_r, err_u, *errs.values())
        tag = "causal" if causal else "full"
        ok = "PASSED" if worst < 1e-4 else "FAILED"
        print(
            f"{tag:7s} seq={n * S} over {n} ranks: ring err {err_r:.2e}, "
            f"ulysses err {err_u:.2e}, "
            + ", ".join(f"{k} err {v:.2e}" for k, v in errs.items())
            + f" -> {ok}"
        )

    banner("sequence-parallel SSM recurrence (aggregate exchange)")
    from tpuscratch.parallel.ssm import ssm_scan

    T, D = n * 16, 8
    rng = np.random.default_rng(7)
    a = rng.uniform(0.2, 0.99, (T, D)).astype(np.float32)
    b = rng.standard_normal((T, D)).astype(np.float32)
    got = np.asarray(run_spmd(
        mesh, lambda aa, bb: ssm_scan(aa, bb, "seq"),
        (P("seq"), P("seq")), P("seq"),
    )(jnp.asarray(a), jnp.asarray(b)))
    h = np.zeros(D, dtype=np.float64)
    expect = np.empty((T, D))
    for t in range(T):
        h = a[t] * h + b[t]
        expect[t] = h
    err = np.abs(got - expect).max()
    print(f"h_t = a_t h_(t-1) + b_t, seq={T} over {n} ranks: err {err:.2e} "
          f"({'PASSED' if err < 1e-4 else 'FAILED'})")


if __name__ == "__main__":
    main()
