"""Lock-step token passing — mpi4 parity, generalized to the full ring.

The reference bounces an incrementing counter between two ranks for 10
rounds (/root/reference/mpi4.cpp:24-44). Here the token circulates the
whole ring inside one compiled lax.scan — no per-hop dispatch.
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main() -> None:
    ensure_devices()
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from tpuscratch.comm import run_spmd, token_ring
    from tpuscratch.runtime.mesh import make_mesh_1d

    banner("token ring (mpi4)")
    mesh = make_mesh_1d("x")
    n = mesh.devices.size
    hops = 10
    f = run_spmd(mesh, lambda x: token_ring(x, "x", hops=hops), P("x"), P("x"))
    out = np.asarray(f(jnp.zeros(n)))
    print(f"{hops} hops around a {n}-ring, +1 per hop:")
    print("final tokens per rank:", out)


if __name__ == "__main__":
    main()
