"""3D spectral Poisson: the four-step matmul FFT meets the 3-axis torus.

The solver catalog's closing composition — the same 7-point periodic
system ex17 solves by 3D multigrid V-cycles, solved DIRECTLY here: one
pencil-decomposed 3D FFT round trip (z-slabs over a 1D mesh, ONE
all_to_all per transform direction) and a pointwise eigenvalue divide.
The local transforms run the complex-free (re, im) pair path on the MXU,
with the four-step N=N1*N2 matmul FFT under it at sizes where it wins
(BASELINE row 8). Reference lineage: the strided complex-typed exchanges
of /root/reference/mpi-complex-types.cpp are the communication shape the
pencil transpose dissolves into one collective.

Self-checks: residual against the numpy 7-point Laplacian, and
cross-validation against the 3D multigrid solver (two unrelated
algorithms, same answer).

argv tier:  ex21_spectral3d.py [--n=N]
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main(argv=None) -> None:
    ensure_devices()
    import numpy as np

    from tpuscratch.runtime.config import Config
    from tpuscratch.runtime.mesh import make_mesh, make_mesh_1d
    from tpuscratch.solvers import mg_poisson3d_solve, periodic_poisson3d_fft

    cfg = Config.load(argv)
    n = cfg.n if "n" in cfg.explicit else 16
    banner(f"3D spectral Poisson, {n}^3 torus, 8 z-slabs")

    rng = np.random.default_rng(21)
    b = rng.standard_normal((n, n, n)).astype(np.float32)
    b -= b.mean()

    x = periodic_poisson3d_fft(b, make_mesh_1d("x", 8))
    lap = 6 * x.astype(np.float64) - sum(
        np.roll(x.astype(np.float64), s, a) for a in range(3) for s in (1, -1)
    )
    resid = np.abs(lap - b).max()
    print(f"spectral: one FFT round trip, residual {resid:.2e}")

    x_mg, cycles, relres = mg_poisson3d_solve(
        b, make_mesh((2, 2, 2), ("z", "row", "col")), tol=1e-6
    )
    gap = np.abs(x - x_mg).max()
    print(f"multigrid: {cycles} V-cycles to relres {relres:.1e}")
    print(f"max |x_spectral - x_multigrid| = {gap:.2e} "
          f"({'PASSED' if resid < 1e-3 and gap < 1e-3 else 'FAILED'})")


if __name__ == "__main__":
    main()
