"""Mesh co-scheduling: heterogeneous workloads time-slicing ONE slice.

The reference repo's top layer is PBS/SLURM job scripts — every binary
ships with its batch submission, and the CLUSTER scheduler multiplexes
jobs onto nodes.  Its TPU-native reproduction (ISSUE 16) is
``runtime.scheduler.MeshScheduler`` over ``runtime.chunked``
ChunkedPrograms: the unit of preemption is the chunk boundary (the
state was just checkpointed), so N workloads can interleave on one
mesh without any of them knowing — a walltime kill between
checkpoints, minus the kill.

Demonstrated and self-checked here:

1. **co-scheduling is invisible** — a transformer training run and an
   MG3D multigrid solve, round-robin time-slicing one device pool,
   finish with params/losses/solution BIT-identical to solo runs;
2. **priority preemption at the boundary** — a high-priority burst job
   arriving MID-RUN preempts background training at the very next
   chunk boundary, runs to completion, and the background job resumes
   (the serving-burst-over-training policy);
3. **the goodput arbitration table** — ``obs.goodput.by_workload``
   splits the ONE shared JSONL stream on the workload tag into
   per-workload goodput reports whose buckets sum to per-workload
   walls and whose walls sum to the scheduler wall exactly.

argv tier:  ex33_coscheduling.py [--steps=N]
"""

import pathlib
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main(argv=None) -> None:
    ensure_devices()
    import jax
    import numpy as np

    from tpuscratch.models.trainer import train_program
    from tpuscratch.models.transformer import TransformerConfig
    from tpuscratch.obs.goodput import by_workload
    from tpuscratch.obs.report import load_events
    from tpuscratch.obs.sink import NullSink, Sink
    from tpuscratch.runtime.chunked import ChunkResult, ChunkedProgram
    from tpuscratch.runtime.mesh import make_mesh
    from tpuscratch.runtime.scheduler import (
        MeshScheduler,
        Priority,
        RoundRobin,
    )
    from tpuscratch.solvers.runner import mg3d_solve_program

    argv = list(sys.argv[1:]) if argv is None else list(argv)
    steps = 4
    for a in argv:
        if a.startswith("--steps="):
            steps = int(a.split("=", 1)[1])

    banner("ex33: mesh co-scheduling — train + solver time-slicing "
           "one slice")

    # the ex25 training setup and the ex30 solver setup, verbatim —
    # under the suite's one process the compiled steps are already hot,
    # so this example pays runtime only
    mesh = make_mesh((2, 2), ("dp", "sp"))
    cfg = TransformerConfig(d_model=16, n_heads=2, n_experts=2, d_ff=32,
                            n_layers=1, capacity_factor=2.0)
    rng = np.random.default_rng(5)
    b = rng.standard_normal((16, 16, 16)).astype(np.float32)
    b -= b.mean()
    smesh = make_mesh((2, 2, 2), ("z", "row", "col"), jax.devices()[:8])

    def tprog(ckpt, sink=None):
        # always attach a sink: the instrumented step is one compile
        # shared by the solo, co-scheduled and preempted runs alike
        return train_program(mesh, cfg, steps, ckpt, save_every=2,
                             obs=sink if sink is not None else NullSink())

    def sprog(ckpt, sink=None):
        return mg3d_solve_program(b, ckpt, mesh=smesh, tol=1e-10,
                                  max_cycles=6, chunk_cycles=2, s_step=2,
                                  sink=sink)

    def params_equal(x, y):
        return all(np.array_equal(np.asarray(p), np.asarray(q))
                   for p, q in zip(jax.tree.leaves(x), jax.tree.leaves(y)))

    with tempfile.TemporaryDirectory() as wd:
        # 1. the solo reference runs (same programs, run to completion
        # alone), then the same two workloads co-scheduled round-robin
        # on the same device pool, sharing one JSONL stream
        p_solo, rep_solo = tprog(f"{wd}/solo_t").run()
        x_solo, srep_solo = sprog(f"{wd}/solo_s").run()

        path = f"{wd}/cosched.jsonl"
        with Sink(path) as sink:
            sched = MeshScheduler(policy=RoundRobin(), sink=sink)
            sched.add(tprog(f"{wd}/co_t", sink))
            sched.add(sprog(f"{wd}/co_s", sink))
            res = sched.run()
        p_co, rep_co = res["train"]
        x_co, srep_co = res["solver"]
        assert params_equal(p_solo, p_co), "co-scheduled params diverged!"
        assert rep_solo.losses == rep_co.losses, "loss trace diverged!"
        assert np.array_equal(x_solo, x_co), "solver solution diverged!"
        print(f"bit-identity: {steps}-step train and "
              f"{srep_co.cycles}-cycle solve, co-scheduled vs solo — "
              f"params, losses and solution identical")

        # 2. priority preemption: background training; a high-priority
        # burst job arrives after 2 ticks and preempts at the boundary
        order = []

        def burst_prog():
            def run_chunk(cp, pos):
                order.append(("burst", pos))
                return pos

            return ChunkedProgram(
                workload="burst", total=2, run_chunk=run_chunk,
                make_event=lambda cp, pos, payload, sp: ChunkResult(
                    pos=pos + 1, event={"step": pos + 1}),
                epilogue=lambda cp: cp.pos,
            )

        bg_trace = []

        def spy(name, prog):
            inner = prog._run_chunk

            def wrapped(cp, pos):
                order.append((name, pos))
                bg_trace.append(pos)
                return inner(cp, pos)

            prog._run_chunk = wrapped
            return prog

        arrived = {"done": False}

        def arrival(s):
            if s.ticks == 1 and not arrived["done"]:
                arrived["done"] = True
                s.add(burst_prog(), priority=10)

        sched2 = MeshScheduler(policy=Priority(), on_tick=arrival)
        sched2.add(spy("train", tprog(f"{wd}/pre_t")), priority=0)
        res2 = sched2.run()
        burst_at = [i for i, (n, _) in enumerate(order) if n == "burst"]
        assert burst_at == [1, 2], f"burst did not preempt: {order}"
        assert order[-1][0] == "train", f"train never resumed: {order}"
        p_pre, _ = res2["train"]
        assert params_equal(p_solo, p_pre), "preempted train diverged!"
        print(f"priority: burst arrived at tick 1, preempted training "
              f"at the chunk boundary (ran ticks {burst_at}), and the "
              f"resumed train still matches solo bit for bit "
              f"(order {order})")

        # 3. the arbitration table over the shared stream
        events = load_events([path])
        wg = by_workload(events)
        wg.check()  # buckets sum per workload; walls sum to the wall
        assert set(wg.reports) == {"train", "solver"}
        assert wg.switches >= 1
        print(wg.summary())
        walls = sum(r.wall_s for r in wg.reports.values())
        print(f"partition: per-workload walls sum {walls:.3f} s == "
              f"scheduler wall {wg.wall_s:.3f} s "
              f"({wg.switches} switches)")

    print("PASSED")


if __name__ == "__main__":
    main()
