"""Geometric multigrid: O(1)-cycle Poisson solve on the periodic torus.

Three solvers over the reference's flagship operator family now exist —
CG (ex14, Dirichlet, O(sqrt(cond)) halo-matvecs), spectral (ex15,
periodic, one FFT round trip), and this V-cycle (periodic, ~10 cycles at
ANY grid size). The demo solves the same right-hand side at several grid
sizes to show the cycle count not growing, then cross-checks the answer
against the spectral solver — two independent numerical methods agreeing
through the same halo/collective machinery.

argv tier:  ex16_multigrid.py [--steps=MAX_CYCLES]
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main(argv=None) -> None:
    ensure_devices()
    import numpy as np

    from tpuscratch.runtime.config import Config
    from tpuscratch.runtime.mesh import make_mesh_1d, make_mesh_2d
    from tpuscratch.solvers import periodic_poisson_fft
    from tpuscratch.solvers.multigrid import mg_poisson_solve
    from tpuscratch.solvers.spectral import periodic_laplacian_np

    cfg = Config.load(argv)
    max_cycles = cfg.steps if "steps" in cfg.explicit else 50
    banner("multigrid V-cycles: iteration count vs grid size")
    rng = np.random.default_rng(0)
    mesh = make_mesh_2d((2, 4))
    counts = {}
    for n in (32, 64, 128):
        b = rng.standard_normal((n, n)).astype(np.float32)
        b -= b.mean()
        x, cycles, relres = mg_poisson_solve(
            b, mesh, tol=1e-6, max_cycles=max_cycles
        )
        resid = np.abs(periodic_laplacian_np(x.astype(np.float64)) - b).max()
        counts[n] = cycles
        print(f"{n:4d}x{n}: {cycles:2d} cycles, relres {relres:.2e}, "
              f"|Ax-b| {resid:.2e}")
    flat = max(counts.values()) <= 14
    print(f"cycle count flat in grid size: "
          f"{'PASSED' if flat else 'FAILED'} ({counts})")

    banner("cross-check: multigrid vs spectral on the same system")
    b = rng.standard_normal((64, 64)).astype(np.float32)
    b -= b.mean()
    x_mg, cycles, _ = mg_poisson_solve(b, mesh, tol=1e-6)
    x_sp = periodic_poisson_fft(b, make_mesh_1d("x", 8))
    gap = np.abs(x_mg - x_sp).max()
    print(f"max |x_mg - x_fft| = {gap:.2e} after {cycles} cycles "
          f"({'PASSED' if gap < 1e-3 else 'FAILED'})")

    banner("composed: CG preconditioned by one V-cycle")
    from tpuscratch.solvers import pcg_poisson_solve

    x_pcg, iters, relres = pcg_poisson_solve(b, mesh, tol=1e-6)
    gap2 = np.abs(x_pcg - x_sp).max()
    print(f"PCG: {iters} iterations (vs {cycles} V-cycles), relres "
          f"{relres:.2e}, max |x_pcg - x_fft| = {gap2:.2e} "
          f"({'PASSED' if iters < cycles and gap2 < 1e-3 else 'FAILED'})")


if __name__ == "__main__":
    main()
