"""Disaggregated serving: the same request stream four ways.

The serving subsystem's three composable layers (ISSUE 8), demonstrated
and self-checked against the monolithic engine:

1. **monolithic** — the PR-1 ServeEngine: every admission pays its full
   prefill inside one tick;
2. **prefix-shared** — ``ServeConfig(prefix_share=True)``: admissions
   whose prompts share a full-page-aligned prefix attach to LIVE pages
   (allocator refcounts + the PrefixCache trie) and prefill only their
   tails — watch ``prefill_tokens`` and ``fresh_kv_bytes`` drop while
   the greedy outputs stay IDENTICAL;
3. **chunked prefill** — ``ServeConfig(chunk_prefill=N)``: a long
   prompt advances N tokens per tick through the context-prefill
   program instead of monopolizing one tick, bounding the resident
   streams' per-token cadence (the ticks-to-first-token law is checked
   live below; the latency side is record config 12's long-mix row);
4. **disaggregated** — ``DisaggEngine``: prompts prefill into a staging
   pool on the prefill dp-group, finished KV pages ship to the decode
   groups through ``comm/p2p`` (one ppermute pair per cache leaf —
   mpi5.cpp's nonblocking neighbor exchange as cache migration), and
   the unchanged decode engine continues from the migrated pages.

Self-checks: greedy outputs BIT-IDENTICAL across all four paths, the
prompt-token conservation law (prefilled + shared == submitted), the
monotone share saving, and the chunk scheduling law — plus the
p99-vs-share table read straight off the engines' own tick metrics.

argv tier:  ex29_disagg_serving.py [--share-ratio=R] [--chunk=N]
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main(argv=None) -> None:
    ensure_devices()
    import dataclasses

    import jax

    from tpuscratch.bench.decode_bench import shared_prefix_prompts
    from tpuscratch.models import TransformerConfig
    from tpuscratch.runtime.mesh import make_mesh
    from tpuscratch.serve import (
        DisaggEngine,
        Request,
        ServeConfig,
        ServeEngine,
    )

    argv = list(sys.argv[1:]) if argv is None else list(argv)
    share_ratio, chunk = 0.5, 4
    for a in argv:
        if a.startswith("--share-ratio="):
            share_ratio = float(a.split("=", 1)[1])
        elif a.startswith("--chunk="):
            chunk = int(a.split("=", 1)[1])

    # dp=2 keeps the cross-group migration real; sp=1 keeps the demo
    # fast (the 2x2 head-sharded case is test-gated in tier-1)
    mesh = make_mesh((2, 1), ("dp", "sp"), jax.devices()[:2])
    cfg = TransformerConfig(
        d_model=32, n_heads=4, n_experts=2, d_ff=64, n_layers=2,
        capacity_factor=2.0,
    )
    scfg = ServeConfig(n_slots=4, n_pages=32, page_size=4, max_seq=48,
                       vocab=64, seed=0)
    prompts = shared_prefix_prompts(6, 12, share_ratio, scfg.vocab)
    reqs = [Request(rid=i, prompt=p, max_new=4)
            for i, p in enumerate(prompts)]

    def tick_p50_ms(eng):
        # median over the drain's ticks: robust to the compile tick the
        # lifetime histogram necessarily contains (the warmed p99 curve
        # is record config 12's serve_prefix_share row)
        snap = eng.metrics.snapshot().get("serve/tick_s", {})
        return 1e3 * snap.get("p50", 0.0)

    banner(
        f"one stream, four engines — 2x1 (dp x sp) mesh, "
        f"{len(reqs)} requests, share ratio {share_ratio}"
    )

    mono_eng = ServeEngine(mesh, cfg, scfg)
    mono = mono_eng.run(reqs)
    print(f"monolithic:    {mono.prefill_tokens:3d} prompt tokens "
          f"prefilled, {mono.fresh_kv_bytes:7.0f} fresh KV B")

    shared_eng = ServeEngine(
        mesh, cfg, dataclasses.replace(scfg, prefix_share=True))
    shared = shared_eng.run(reqs)
    print(f"prefix-shared: {shared.prefill_tokens:3d} prompt tokens "
          f"prefilled, {shared.fresh_kv_bytes:7.0f} fresh KV B "
          f"({shared.shared_tokens} shared, {shared.cow_pages} CoW)")

    chunk_eng = ServeEngine(
        mesh, cfg, dataclasses.replace(scfg, chunk_prefill=chunk))
    chunked = chunk_eng.run(reqs)
    print(f"chunked({chunk}):    {chunked.prefill_tokens:3d} prompt "
          f"tokens prefilled, one chunk per tick per admission")

    deng = DisaggEngine(mesh, cfg, scfg)
    disagg = deng.run(reqs)
    print(f"disaggregated: {disagg.stage_prefill_tokens:3d} prompt "
          f"tokens staged, {disagg.handoffs} handoffs, "
          f"{disagg.migrated_pages} pages migrated "
          f"({deng.handoff_wire_bytes:.0f} B/handoff), "
          f"{disagg.degraded} degraded")

    identical = (
        shared.outputs == mono.outputs
        and chunked.outputs == mono.outputs
        and disagg.outputs == mono.outputs
    )
    conserved = (
        shared.prefill_tokens + shared.shared_tokens
        == sum(len(r.prompt) for r in reqs)
    )
    saved = (shared.prefill_tokens < mono.prefill_tokens
             and shared.fresh_kv_bytes < mono.fresh_kv_bytes)

    banner("tick p50 / share-ratio (each engine's serve/tick_s metrics)")
    print(f"  share 0.0: prefill frac 1.000, "
          f"tick p50 {tick_p50_ms(mono_eng):6.2f} ms")
    frac = shared.prefill_tokens / (shared.prefill_tokens
                                    + shared.shared_tokens)
    print(f"  share {share_ratio}: prefill frac {frac:.3f}, "
          f"tick p50 {tick_p50_ms(shared_eng):6.2f} ms")

    banner("chunk scheduling law — a long arrival on the WARM engine")
    # reuse the chunked engine (programs compiled): a resident stream
    # decodes while a 16-token prompt arrives; the arrival reaches its
    # first token in exactly ceil(16 / chunk) ticks and the resident
    # advances one token EVERY tick meanwhile
    long_prompt = tuple(1 + t % (scfg.vocab - 1) for t in range(16))
    chunk_eng.submit(Request(rid=100, prompt=(1, 2), max_new=12))
    chunk_eng.step()
    resident = chunk_eng._slots[0]
    chunk_eng.submit(Request(rid=101, prompt=long_prompt, max_new=2))
    ticks, advanced, first_tick = 0, True, None
    while first_tick is None:
        before = len(resident.generated)
        for rid, _toks in chunk_eng.step():
            if rid == 101:     # may finish-and-evict inside one tick
                first_tick = ticks + 1
        ticks += 1
        advanced = advanced and len(resident.generated) == before + 1
        if any(st is not None and st.rid == 101 and st.generated
               for st in chunk_eng._slots):
            first_tick = ticks
    expect = -(-len(long_prompt) // chunk)
    print(f"first token after {first_tick} ticks (= ceil(16/{chunk}) = "
          f"{expect}); resident advanced every tick: {advanced}")
    bounded = first_tick == expect and advanced
    chunk_eng.run([])

    ok = identical and conserved and saved and bounded
    print("PASSED" if ok else "FAILED:"
          f" identical={identical} conserved={conserved}"
          f" saved={saved} bounded={bounded}")
    if not ok:
        raise SystemExit(1)


if __name__ == "__main__":
    main(sys.argv[1:])
