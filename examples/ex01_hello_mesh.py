"""Runtime bring-up: process identity, devices, mesh — mpi1/mpi2 parity.

The reference's hello world prints rank, size, and processor name after
MPI_Init (/root/reference/mpi1.cpp), and mpi2 adds error-handler
installation. Here: initialize(), the per-process hello line, a mesh over
every device, and the error-policy guard around the whole bring-up.
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main() -> None:
    ensure_devices()
    from tpuscratch import initialize, make_mesh_1d
    from tpuscratch.runtime.errors import ErrorPolicy, guarded
    from tpuscratch.runtime.log import RankLogger

    banner("hello mesh")
    with guarded("bring-up", ErrorPolicy.RAISE):
        ctx = initialize()
        print(ctx.hello())
        mesh = make_mesh_1d("world")
        log = RankLogger(rank=ctx.process_index)
        log(f"mesh axes {mesh.axis_names}, {mesh.devices.size} devices:")
        for d in mesh.devices.flat:
            log("  device", d)


if __name__ == "__main__":
    main()
