"""Any-cartesian-layout streamed stencils: ghost-strip modes (round 5).

Round 4's deep-streamed kernels served only self-wrapping column axes
(2D row-slab / 3D z-slab meshes); round 5 removed the restriction
(≙ the reference's exchange serving any cartesian layout,
/root/reference/stencil2d/mpi10.cpp:27, stencil2D.h:232-244).
Distributed (or open) columns ride ghost slabs — the x/y neighbors'
edge data with the DIAGONAL neighbors' corner blocks, the 8-channel
(2D) / 26-neighbor (3D) transfer set at ghost depth k — kept OFF the
core window in narrow strips that age by their own small substeps each
fold (lane-concatenating ghosts onto the window cost 0.33 ms/step in
Mosaic relayouts, chip-raced and rejected; the strip form runs 1.29e11
cells/s at 8192^2 on v5e, 4.6x the best previously-admissible kernel
for 2D-decomposed meshes — BASELINE row 4).

Self-checks: 2D ghost-column mode (2x2 mesh, 9-point, periodic + fully
open) and 3D ghost-strip mode ((2,2,2) mesh, 7-point) against the
plain exchange paths.

argv tier:  ex23_any_layout_stream.py [--steps=S] [--impl=stream:K]
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main(argv=None) -> None:
    ensure_devices()
    import numpy as np

    from tpuscratch.halo.driver import distributed_stencil
    from tpuscratch.halo.halo3d import distributed_stencil3d
    from tpuscratch.runtime.config import Config
    from tpuscratch.runtime.mesh import make_mesh, make_mesh_2d

    cfg = Config.load(argv)
    steps = cfg.steps if "steps" in cfg.explicit else 5
    impl = cfg.impl if "impl" in cfg.explicit else "stream:2"
    banner(
        f"any-layout streamed stencils: 2D ghost columns on 2x2, 3D "
        f"ghost strips on (2,2,2), {steps} steps, impl {impl}"
    )

    rng = np.random.default_rng(23)
    ok = True

    # 2D: distributed columns, 9-point (the corner blocks are read)
    world2 = rng.standard_normal((64, 64)).astype(np.float32)
    mesh2 = make_mesh_2d((2, 2))
    c9 = (0.15, 0.15, 0.1, 0.1, 0.05, 0.05, 0.08, 0.07, 0.25)
    a = distributed_stencil(world2, steps, mesh=mesh2, impl=impl,
                            coeffs=c9)
    b = distributed_stencil(world2, steps, mesh=mesh2, impl="xla",
                            coeffs=c9)
    err = np.abs(a - b).max()
    ok &= err < 1e-4
    print(f"2D 9-point, 2x2 periodic:   ghost-columns vs xla max err "
          f"{err:.2e}")

    a = distributed_stencil(world2, steps, mesh=mesh2, impl=impl,
                            coeffs=c9, periodic=False)
    b = distributed_stencil(world2, steps, mesh=mesh2, impl="xla",
                            coeffs=c9, periodic=False)
    err = np.abs(a - b).max()
    ok &= err < 1e-4
    print(f"2D 9-point, 2x2 fully-open: ghost-columns vs xla max err "
          f"{err:.2e} (ppermute zero-fill + per-substep flag zeroing)")

    # 3D: y AND x distributed — the full 26-neighbor strip set
    world3 = rng.standard_normal((16, 16, 16)).astype(np.float32)
    mesh3 = make_mesh((2, 2, 2), ("z", "row", "col"))
    a = distributed_stencil3d(world3, steps, mesh3, impl=impl)
    b = distributed_stencil3d(world3, steps, mesh3, impl="compact")
    err = np.abs(a - b).max()
    ok &= err < 1e-4
    print(f"3D 7-point, (2,2,2):        ghost-strips vs compact max "
          f"err {err:.2e} (gy + gx + xy-corner strips aged in-kernel)")

    print("PASSED" if ok else "FAILED")


if __name__ == "__main__":
    main()
