"""Continuous-batching inference: the train-and-serve split, demonstrated.

The serving capstone (tpuscratch.serve): the SAME parameter pytree and
(dp x sp) mesh the training step uses now serves autoregressive
generation — block-paged KV cache sharded pages-over-dp / heads-over-sp,
cached single-token decode numerically equal to the full forward, and an
Orca-style continuous-batching engine: more requests than decode slots,
mixed prompt lengths and budgets, admission gated on each group's free
pages, finished sequences evicted mid-stream so queued work back-fills
their slots.  Watch the report: ONE decode compile no matter how many
requests churn through, and every page back on the free list at drain.

argv tier:  ex24_serving.py [--decode-slots=N] [--kv-pages=N] [--page-size=N]
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main(argv=None) -> None:
    ensure_devices()
    import jax

    from tpuscratch.models import TransformerConfig
    from tpuscratch.runtime.config import Config
    from tpuscratch.runtime.mesh import make_mesh
    from tpuscratch.serve import Request, ServeConfig, ServeEngine

    cli = Config.load(argv)
    mesh = make_mesh((2, 4), ("dp", "sp"))
    cfg = TransformerConfig(
        d_model=32, n_heads=4, n_experts=2, d_ff=64, n_layers=2,
        capacity_factor=2.0,
    )
    scfg = ServeConfig(
        n_slots=cli.decode_slots, n_pages=cli.kv_pages,
        page_size=cli.page_size, max_seq=48, vocab=64, temperature=0.7,
        top_k=8, seed=0,
    )
    banner(
        f"serving on a 2x4 (dp x sp) mesh: {scfg.n_slots} decode slots, "
        f"{scfg.n_pages} pages/group x {scfg.page_size} tokens"
    )

    engine = ServeEngine(mesh, cfg, scfg)
    free0 = engine.free_pages()
    rng_prompts = [
        tuple((3 * i + j) % scfg.vocab for j in range(2 + (5 * i) % 9))
        for i in range(2 * scfg.n_slots)  # 2x oversubscribed: queueing is real
    ]
    requests = [
        Request(rid=i, prompt=p, max_new=3 + (7 * i) % 10)
        for i, p in enumerate(rng_prompts)
    ]
    report = engine.run(requests)

    for rid, toks in report.outputs:
        print(f"request {rid:2d}: prompt {len(rng_prompts[rid]):2d} tokens "
              f"-> {list(toks)}")
    banner("report")
    print(f"completed {report.completed} requests, "
          f"{report.tokens_generated} tokens in {report.decode_steps} decode "
          f"steps + {report.prefills} prefills")
    print(f"compiles: decode {report.decode_compiles} (steady state never "
          f"recompiles), prefill {report.prefill_compiles} (one per prompt "
          "shape bucket)")
    print(f"wall: prefill {report.prefill_s:.3f}s, decode {report.decode_s:.3f}s")
    print(f"pages: {free0} free before, {engine.free_pages()} after drain")
    assert engine.free_pages() == free0, "page leak!"
    assert report.decode_compiles == 1
    print(f"[{jax.default_backend()}] serving demo PASSED")


if __name__ == "__main__":
    main()
