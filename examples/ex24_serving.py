"""Continuous-batching inference: the train-and-serve split, demonstrated.

The serving capstone (tpuscratch.serve): the SAME parameter pytree and
(dp x sp) mesh the training step uses now serves autoregressive
generation — block-paged KV cache sharded pages-over-dp / heads-over-sp,
cached single-token decode numerically equal to the full forward, and an
Orca-style continuous-batching engine: more requests than decode slots,
mixed prompt lengths and budgets, admission gated on each group's free
pages, finished sequences evicted mid-stream so queued work back-fills
their slots.  Watch the report: ONE decode compile no matter how many
requests churn through, and every page back on the free list at drain.

Two serving hot-path levers ride the same engine contract:
``--int8`` stores KV pages quantized (int8 + per-page scales, ~1/4 the
cache bytes per token — the decode-gather roofline), ``--spec[=K]``
turns on self-drafting speculative decoding (K draft tokens verified
per cache sweep; the report's accepted/drafted counters show how many
sweeps the drafts saved, and the accounting identity
``tokens == prefills + slot_steps + accepted`` is asserted live).
A closing section demonstrates device-resident MACRO-STEP decode
(``ServeConfig(macro_steps=4)``): the whole engine tick fused into one
compiled ``lax.scan`` — one dispatch and one host-sync per 4 tokens,
identical output, with the dispatch identity
``dispatches == ceil(slot_steps / macro_steps)`` asserted live on a
single decoding stream.

argv tier:  ex24_serving.py [--decode-slots=N] [--kv-pages=N]
            [--page-size=N] [--spec[=K]] [--int8]
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main(argv=None) -> None:
    ensure_devices()
    import jax

    from tpuscratch.models import TransformerConfig
    from tpuscratch.runtime.config import Config
    from tpuscratch.runtime.mesh import make_mesh
    from tpuscratch.serve import Request, ServeConfig, ServeEngine

    argv = list(sys.argv[1:]) if argv is None else list(argv)
    # sugar over the Config flag tier: bare --int8 / --spec spellings
    argv = ["--kv-dtype=int8" if a == "--int8"
            else "--spec=3" if a == "--spec" else a for a in argv]
    cli = Config.load(argv)
    mesh = make_mesh((2, 4), ("dp", "sp"))
    cfg = TransformerConfig(
        d_model=32, n_heads=4, n_experts=2, d_ff=64, n_layers=2,
        capacity_factor=2.0,
    )
    scfg = ServeConfig(
        n_slots=cli.decode_slots, n_pages=cli.kv_pages,
        page_size=cli.page_size, max_seq=48, vocab=64, temperature=0.7,
        top_k=8, seed=0, kv_dtype=cli.kv_dtype, spec_k=cli.spec,
    )
    banner(
        f"serving on a 2x4 (dp x sp) mesh: {scfg.n_slots} decode slots, "
        f"{scfg.n_pages} pages/group x {scfg.page_size} tokens, "
        f"kv={scfg.kv_dtype}"
        + (f", speculative k={scfg.spec_k}" if scfg.spec_k else "")
    )

    engine = ServeEngine(mesh, cfg, scfg)
    free0 = engine.free_pages()
    # periodic prompts when speculating (the draftable regime the
    # prompt-lookup proposer exists for), mixed-length arbitrary ones
    # otherwise — both 2x oversubscribed so queueing is real
    if scfg.spec_k:
        rng_prompts = [
            tuple((j % (2 + i % 3)) + 1 for j in range(4 + (3 * i) % 7))
            for i in range(2 * scfg.n_slots)
        ]
    else:
        rng_prompts = [
            tuple((3 * i + j) % scfg.vocab for j in range(2 + (5 * i) % 9))
            for i in range(2 * scfg.n_slots)
        ]
    requests = [
        Request(rid=i, prompt=p, max_new=3 + (7 * i) % 10)
        for i, p in enumerate(rng_prompts)
    ]
    report = engine.run(requests)

    for rid, toks in report.outputs:
        print(f"request {rid:2d}: prompt {len(rng_prompts[rid]):2d} tokens "
              f"-> {list(toks)}")
    banner("report")
    print(f"completed {report.completed} requests, "
          f"{report.tokens_generated} tokens in {report.decode_steps} decode "
          f"steps + {report.prefills} prefills")
    print(f"compiles: decode {report.decode_compiles} (steady state never "
          f"recompiles), prefill {report.prefill_compiles} (one per prompt "
          "shape bucket)")
    if scfg.spec_k:
        print(f"speculation: {report.drafted} drafted, {report.accepted} "
              f"accepted (mean accept {report.accept_len_mean:.2f}/"
              f"{scfg.spec_k} per sweep) — {report.slot_steps} sweeps for "
              f"{report.tokens_generated - report.prefills} decoded tokens")
    if scfg.kv_dtype == "int8":
        print(f"kv cache: int8 pages, {engine.kv_bytes_per_token:.0f} "
              "B/token of pool capacity (fp32 would be "
              f"{2 * cfg.n_layers * cfg.n_heads * cfg.d_head * 4:.0f})")
    print(f"wall: prefill {report.prefill_s:.3f}s, decode {report.decode_s:.3f}s")
    print(f"pages: {free0} free before, {engine.free_pages()} after drain")
    assert engine.free_pages() == free0, "page leak!"
    assert report.decode_compiles == 1
    # the speculative token-accounting identity: every emitted token is
    # a prefill token, a sweep's base token, or an accepted draft
    assert report.tokens_generated == (
        report.prefills + report.slot_steps + report.accepted
    ), "accepted-token counters do not reconcile with emitted tokens"

    # device-resident macro-step decode (ISSUE 15): the same engine
    # contract at macro_steps=4 — ONE compiled lax.scan dispatch and
    # ONE host sync per 4 tokens.  A single decoding stream makes the
    # dispatch identity exact: dispatches == ceil(slot_steps / T).
    banner("macro-step decode (macro_steps=4)")
    import math
    import dataclasses as _dc

    macro_req = Request(rid=1000, prompt=(1, 2, 3), max_new=10)
    m1 = ServeEngine(
        mesh, cfg, _dc.replace(scfg, spec_k=0)
    ).run([macro_req])
    m4 = ServeEngine(
        mesh, cfg, _dc.replace(scfg, spec_k=0, macro_steps=4)
    ).run([macro_req])
    assert m4.outputs == m1.outputs, "macro output diverged from per-token"
    assert m4.dispatches == math.ceil(m4.slot_steps / 4), (
        f"dispatch identity broke: {m4.dispatches} != "
        f"ceil({m4.slot_steps}/4)"
    )
    assert m1.dispatches == m1.slot_steps  # per-token: one each
    assert m4.host_syncs == m4.dispatches
    print(f"per-token: {m1.slot_steps} decode steps = {m1.dispatches} "
          f"dispatches / {m1.host_syncs} host syncs")
    print(f"macro T=4: same {m4.slot_steps} token steps in "
          f"{m4.dispatches} dispatches / {m4.host_syncs} host syncs "
          f"(= ceil({m4.slot_steps}/4)), outputs identical")
    print(f"[{jax.default_backend()}] serving demo PASSED")


if __name__ == "__main__":
    main()
