"""Traffic harness: trace-driven load, burst, and fleet-scale chaos.

The reference repo's programs live under PBS/SLURM batch job streams —
arrivals the cluster scheduler shapes into bursts and diurnal waves —
and its fault story is ``MPI_Abort`` (mpierr.h): a dead rank kills the
job.  The serving-stack reproduction (ISSUE 17) is
``bench.traffic``: a seeded deterministic trace (tenants, Zipf
shared-prefix reuse, diurnal + Poisson-burst arrivals, long-tail
lengths) streamed OPEN-loop through the fleet router, with a
``ChaosPlan`` killing whole replicas mid-stream — and the router
re-admitting every victim instead of aborting the world.

Demonstrated and self-checked here:

1. **burst arrival -> backpressure holds** — the trace's burst crest
   out-runs the per-class ``max_queue`` bound, the router HOLDS
   dispatches (``backpressure_holds > 0``) and the open loop's byte
   budget caps what is ever materialized (``peak_open <=
   open_budget``);
2. **replica kill -> re-admission** — a fixed-plan kill tears a
   replica down mid-stream; its in-flight + queued requests re-enter
   the fleet queue, ZERO are dropped, and the output digest is
   bit-identical to the chaos-free run of the same trace;
3. **the SLO table under churn** — per-class p50/p99 TTFT (bounded
   reservoir) and the MegaScale-style goodput fraction: 1.0 on the
   clean run, and exactly the re-prefilled + killed-decode waste
   below 1.0 under chaos — reconciled by the generalized counter law
   ``prefill + shared == submitted + readmitted``.

argv tier:  ex34_traffic.py [--requests=N]
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main(argv=None) -> None:
    ensure_devices()
    import jax

    from tpuscratch.bench.traffic import (
        TenantSpec,
        TraceGenerator,
        TrafficConfig,
        run_traffic,
    )
    from tpuscratch.ft.chaos import ChaosPlan, Fault
    from tpuscratch.models import TransformerConfig
    from tpuscratch.runtime.mesh import make_mesh
    from tpuscratch.serve import (
        FleetRouter,
        RouterConfig,
        SLOClass,
        ServeConfig,
        ServeEngine,
    )

    argv = list(sys.argv[1:]) if argv is None else list(argv)
    n_requests = 72
    for a in argv:
        if a.startswith("--requests="):
            n_requests = int(a.split("=", 1)[1])

    banner("ex34: traffic harness — trace-driven load + fleet chaos")
    cfg = TransformerConfig(d_model=32, n_heads=4, n_experts=4, d_ff=48,
                            n_layers=1, capacity_factor=4.0)
    mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
    scfg = ServeConfig(n_slots=4, n_pages=32, page_size=4, max_seq=32,
                       vocab=16, prefix_share=True)
    # max_queue bounds each class's per-replica in-flight depth: the
    # burst crest must HOLD in the router queue, not pile onto replicas
    classes = (SLOClass("latency", target="ttft", max_queue=3),
               SLOClass("batch", target="throughput", max_queue=3))
    tcfg = TrafficConfig(
        seed=34, tenants=(
            TenantSpec("acme", cls="latency", weight=3.0, n_prefixes=4),
            TenantSpec("globex", cls="batch", weight=1.0, n_prefixes=2),
        ), vocab=16, prompt_len=21, tail_cap=4, out_cap=4,
        base_rate=3.0, diurnal_period=64, diurnal_amp=0.5,
        burst_p=0.10, burst_len=8, burst_mult=3.0,
    )
    assert tcfg.max_total_len <= scfg.max_seq
    gen = TraceGenerator(tcfg)
    bursty = [t for t in range(40) if gen.burst_active(t)]
    print(f"trace: {n_requests} requests, 2 tenants, burst windows "
          f"cover ticks {bursty[:8]}{'...' if len(bursty) > 8 else ''} "
          f"(rate {gen.rate_at(0):.1f} -> "
          f"{max(gen.rate_at(t) for t in range(40)):.1f}/tick at crest)")

    def fleet(chaos=None):
        return FleetRouter(
            [ServeEngine(mesh, cfg, scfg) for _ in range(3)],
            RouterConfig(classes=classes), chaos=chaos,
        )

    # 1. clean run: burst -> backpressure holds, byte budget holds
    clean = run_traffic(fleet(), TraceGenerator(tcfg), n_requests,
                        open_budget=16)
    assert clean.peak_open <= 16, "open budget violated"
    assert clean.report.backpressure_holds > 0, \
        "burst never hit the max_queue bound"
    print(f"burst: {clean.report.backpressure_holds} dispatch holds at "
          f"max_queue={classes[0].max_queue}, peak {clean.peak_open} "
          f"open <= budget 16, {clean.ticks} ticks")

    # 2. replica kill mid-burst -> re-admission, zero loss, digest
    # identical to the clean run
    plan = ChaosPlan(seed=17, faults=(
        Fault(site="serve/replica", at=(8,), key=0, kind="kill",
              down_ticks=6),
        Fault(site="serve/replica", at=(10,), key=1, kind="stall",
              down_ticks=4),
    ))
    chaos = run_traffic(fleet(plan), TraceGenerator(tcfg), n_requests,
                        open_budget=16)
    rep = chaos.report
    assert rep.kills == 1 and rep.stalls == 1
    assert rep.readmitted > 0, "the kill found an empty replica"
    assert rep.dropped == 0, "requests were lost!"
    assert chaos.digest == clean.digest, \
        "replica churn changed emitted tokens"
    assert rep.prefill_tokens + rep.shared_tokens == \
        rep.submitted_prompt_tokens + rep.readmitted_tokens, \
        "generalized counter law violated"
    print(f"chaos: 1 kill + 1 stall mid-stream -> {rep.readmitted} "
          f"re-admitted ({rep.readmitted_tokens} prompt tok "
          f"re-prefilled, {rep.lost_tokens} generated tok lost), "
          f"0 dropped, digest identical to clean run")
    print(f"counter law: {rep.prefill_tokens} prefilled + "
          f"{rep.shared_tokens} shared == {rep.submitted_prompt_tokens} "
          f"submitted + {rep.readmitted_tokens} readmitted")

    # 3. the SLO table under churn
    print(f"{'class':8s} {'done':>5s} {'p50 TTFT':>10s} {'p99 TTFT':>10s} "
          f"{'goodput':>8s} {'readm':>6s}")
    for c in rep.classes:
        assert 0.0 < c.goodput_frac <= 1.0
        print(f"{c.name:8s} {c.completed:5d} "
              f"{c.ttft_p50_s * 1e3:8.2f} ms {c.ttft_p99_s * 1e3:8.2f} ms "
              f"{c.goodput_frac:8.3f} {c.readmitted:6d}")
    for c in clean.report.classes:
        assert c.goodput_frac == 1.0, "clean run charged waste"
    assert any(c.goodput_frac < 1.0 for c in rep.classes) or \
        rep.readmitted_tokens + rep.lost_tokens == 0
    print("goodput: clean run 1.000 on every class; chaos charges the "
          "re-prefilled legs and killed decodes to the victim classes")

    print("PASSED")


if __name__ == "__main__":
    main()
