"""Two ranks exchange messages — mpi3 parity.

The reference sizes the receive buffer at runtime with MPI_Probe +
MPI_Get_count (/root/reference/mpi3.cpp:28-32). Under XLA the probe is a
trace-time fact: shapes are static, so the "probe" is the abstract value
of the traced payload. The exchange itself is one ppermute with the pair
table [(0,1),(1,0)].
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main() -> None:
    ensure_devices()
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from tpuscratch.comm import run_spmd, send_pairs
    from tpuscratch.runtime.mesh import make_mesh_1d

    banner("pair exchange (mpi3)")
    mesh = make_mesh_1d("x")
    n = mesh.devices.size
    f = run_spmd(
        mesh, lambda x: send_pairs(x, "x", [(0, 1), (1, 0)]), P("x"), P("x")
    )
    # rank 0 holds 100, rank 1 holds 200; after the exchange they swap
    vals = jnp.asarray([100.0, 200.0] + [0.0] * (n - 2))
    out = np.asarray(f(vals))
    print(f"before: rank0={vals[0]}, rank1={vals[1]}")
    print(f"after : rank0={out[0]}, rank1={out[1]} (swapped)")


if __name__ == "__main__":
    main()
