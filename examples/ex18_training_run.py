"""Checkpointed distributed training run: kill it, rerun it, it resumes.

The capstone composition: the mini MoE transformer's train step (ring
attention over sp, expert all_to_all over dp, grad + SGD in one compiled
program) driven by the checkpointing trainer. The demo trains in two
invocations sharing one checkpoint directory — the second resumes at the
saved step and lands bit-identical to a straight-through run, the
contract a walltime-killed job needs (the reference runs under PBS
walltime kills with no way to continue, SURVEY.md §5).

argv tier:  ex18_training_run.py [--steps=N]
"""

import pathlib
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main(argv=None) -> None:
    ensure_devices()
    import jax
    import numpy as np

    from tpuscratch.models import TransformerConfig
    from tpuscratch.models.trainer import train
    from tpuscratch.runtime.config import Config
    from tpuscratch.runtime.mesh import make_mesh

    cfg_cli = Config.load(argv)
    steps = cfg_cli.steps if "steps" in cfg_cli.explicit else 20
    # the resume demo needs >= 2 save points before AND after the cut
    steps = max(10, (steps + 4) // 5 * 5)
    mesh = make_mesh((2, 4), ("dp", "sp"))
    mcfg = TransformerConfig(
        d_model=16, n_heads=2, n_experts=2, d_ff=32, capacity_factor=2.0
    )
    banner(f"checkpointed training, {steps} steps on a 2x4 (dp x sp) mesh")

    with tempfile.TemporaryDirectory(prefix="trainer_") as tmp:
        straight, rep = train(
            mesh, mcfg, steps, f"{tmp}/straight", save_every=5, log=print
        )
        print(f"straight run: {rep.steps_run} steps, "
              f"loss {rep.losses[0]:.4f} -> {rep.losses[-1]:.4f}")

        banner("interrupted at the halfway save, then resumed")
        half = min(max(5, steps // 2 // 5 * 5), steps - 5)
        train(mesh, mcfg, half, f"{tmp}/resumed", save_every=5)
        resumed, rep2 = train(
            mesh, mcfg, steps, f"{tmp}/resumed", save_every=5, log=print
        )
        exact = all(
            np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(jax.tree.leaves(straight), jax.tree.leaves(resumed))
        )
        improving = rep.losses[-1] < rep.losses[0]
        print(f"resumed run executed {rep2.steps_run} steps; params "
              f"bit-identical to straight run: {exact}")
        print("PASSED" if exact and improving else "FAILED")


if __name__ == "__main__":
    main()
