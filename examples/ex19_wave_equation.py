"""2D wave equation by leapfrog — a second PDE family on the halo engine.

Every stencil driver so far advances a single diffusion field; the wave
equation carries TWO coupled fields (u, u_prev) through the scan and
mixes them each step:

    u_next = 2 u - u_prev + c^2 dt^2 * laplacian(u)

The halo machinery doesn't change at all — one exchange per step on the
current field — which is the point: the exchange/compute separation the
reference's library establishes (/root/reference/stencil2d/stencil2D.h)
carries any explicit time-stepper, not just the Jacobi placeholder
family. Checked against the undecomposed-grid oracle, plus an energy
sanity check (leapfrog is symplectic: the discrete energy stays bounded,
it doesn't decay like diffusion).

argv tier:  ex19_wave_equation.py [--steps=N]
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main(argv=None) -> None:
    ensure_devices()
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from tpuscratch.comm import run_spmd
    from tpuscratch.halo import HaloSpec, TileLayout, halo_exchange
    from tpuscratch.halo.driver import assemble, decompose
    from tpuscratch.halo.stencil import rebuild
    from tpuscratch.runtime.config import Config
    from tpuscratch.runtime.mesh import make_mesh_2d, topology_of

    cfg = Config.load(argv)
    steps = cfg.steps if "steps" in cfg.explicit else 20
    mesh = make_mesh_2d((2, 4))
    topo = topology_of(mesh, periodic=True)
    lay = TileLayout(16, 16, 1, 1)
    spec = HaloSpec(layout=lay, topology=topo)
    c2 = 0.2  # c^2 dt^2 / h^2, inside the CFL bound
    banner(f"wave equation, 32x64 torus, leapfrog x{steps} steps")

    def lap(t):
        u = halo_exchange(t, spec)
        return (
            u[:-2, 1:-1] + u[2:, 1:-1] + u[1:-1, :-2] + u[1:-1, 2:]
            - 4.0 * u[1:-1, 1:-1]
        )

    def run(tiles):
        u, up = tiles[0, 0, 0], tiles[1, 0, 0]

        def body(carry, _):
            u, up = carry
            new = 2.0 * u[1:-1, 1:-1] - up[1:-1, 1:-1] + c2 * lap(u)
            return (rebuild(u, new, lay), u), ()

        (u, up), _ = jax.lax.scan(body, (u, up), None, length=steps)
        return jnp.stack([u, up])[:, None, None]

    # a Gaussian bump, initially at rest
    yy, xx = np.mgrid[0:32, 0:64]
    world = np.exp(-((yy - 16.0) ** 2 + (xx - 32.0) ** 2) / 18.0).astype(
        np.float32
    )
    tiles = np.stack([decompose(world, topo, lay)] * 2)
    prog = run_spmd(
        mesh, run,
        P(None, "row", "col", None, None),
        P(None, "row", "col", None, None),
    )
    out = np.asarray(prog(jnp.asarray(tiles)))
    got = assemble(out[0], topo, lay)

    u, up = world.astype(np.float64), world.astype(np.float64)
    for _ in range(steps):
        lap_np = (
            np.roll(u, 1, 0) + np.roll(u, -1, 0)
            + np.roll(u, 1, 1) + np.roll(u, -1, 1) - 4 * u
        )
        u, up = 2 * u - up + c2 * lap_np, u
    err = np.abs(got - u).max()
    # symplectic sanity: the wave DISPERSES but does not dissipate —
    # a diffusion update at this rate would have decayed the max norm
    # by ~(1-4*c2)^steps ~ 1e-14; a dispersing wave keeps O(0.1) of it
    alive = np.abs(got).max() > 0.1 * np.abs(world).max()
    print(f"max |distributed - global| after {steps} steps: {err:.2e}")
    print(f"wave amplitude preserved: {np.abs(got).max():.3f} "
          f"({'PASSED' if err < 1e-4 and alive else 'FAILED'})")


if __name__ == "__main__":
    main()
