"""4D parallelism behind one ShardingPlan, with comm/compute overlap.

The reference composes every kernel against ONE cartesian topology
(mpi10.cpp / stencil2D.h); this demo is that idea on the training hot
path: a single ``ShardingPlan`` names the mesh axes (dp x sp x pp, with
experts riding dp) and ``train(plan=...)`` composes GPipe pipeline
stages, data/sequence parallelism, and dp-sharded ZeRO moments in one
compiled step.  The plan's ``overlap`` flag decomposes the flat
gradient reduce-scatter and the trailing param all-gather into
independent per-block chains — the obs ledger proves the decomposition
moves the collective COUNT and never the wire bytes, and the pp=2 run
trains to a descending loss with the moments sharded over dp.
"""

import pathlib
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main(argv=None) -> None:
    ensure_devices()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpuscratch.models import TransformerConfig
    from tpuscratch.models.trainer import train
    from tpuscratch.models.transformer import init_params, stack_layers
    from tpuscratch.models.zero import init_plan_zero_state, train_step_plan
    from tpuscratch.obs import ledger as obs_ledger
    from tpuscratch.parallel import ShardingPlan, bubble_fraction
    from tpuscratch.runtime.mesh import make_mesh

    mesh = make_mesh((2, 1, 2), ("dp", "sp", "pp"))
    cfg = TransformerConfig(
        d_model=16, n_heads=2, n_experts=2, d_ff=32, n_layers=2,
        capacity_factor=2.0,
    )
    plan = ShardingPlan(mesh, pp="pp", n_micro=2)
    banner(
        f"ShardingPlan over dp{plan.dp_size} x sp{plan.sp_size} x "
        f"pp{plan.pp_size}, n_micro={plan.n_micro} "
        f"(bubble {bubble_fraction(plan.pp_size, plan.n_micro):.2f})"
    )

    # the static proof: overlap changes the collective schedule, never
    # the wire bytes
    stacked = stack_layers(init_params(0, cfg))
    x = jnp.zeros((4, 16, cfg.d_model), jnp.float32)
    rows = {}
    for ov in (False, True):
        p = ShardingPlan(mesh, pp="pp", n_micro=2, overlap=ov)
        led = obs_ledger.analyze(
            train_step_plan(p, cfg, donate=False), stacked,
            init_plan_zero_state(stacked, p), x, x,
        )
        rows[ov] = (led.counts(), led.total_wire_bytes())
        print(f"overlap={ov}: RS x{led.counts().get('reduce-scatter', 0)}"
              f" AG x{led.counts().get('all-gather', 0)}, "
              f"total wire {led.total_wire_bytes():.0f} B/device")
    bytes_equal = rows[False][1] == rows[True][1]
    count_moved = (rows[True][0]["reduce-scatter"]
                   > rows[False][0]["reduce-scatter"])

    banner("train(plan=...) — pp=2 GPipe + ZeRO moments sharded over dp")
    with tempfile.TemporaryDirectory(prefix="plan_") as tmp:
        params, rep = train(
            mesh, cfg, steps=6, ckpt_dir=f"{tmp}/run", save_every=3,
            optimizer="adam", zero=True, batch=4, seq=16, lr=0.005,
            plan=plan, log=print,
        )
        improving = rep.losses[-1] < rep.losses[0]
        print(f"loss {rep.losses[0]:.4f} -> {rep.losses[-1]:.4f}")
        # the stacked params live stage-sharded; sanity: finite leaves
        finite = all(
            np.isfinite(np.asarray(leaf)).all()
            for leaf in jax.tree.leaves(params)
        )
    ok = bytes_equal and count_moved and improving and finite
    print("PASSED" if ok else "FAILED")


if __name__ == "__main__":
    main(sys.argv[1:])
