"""Mesh-wide observability: one telemetry loop over train AND serve.

The reference instruments by hand — clock() spans gathered to rank 0
max-min, MPI_Wtime segment brackets, a carve-out for setup cost.
tpuscratch.obs is that discipline as a subsystem; this example runs the
whole loop on one JSONL artifact:

1. train a few checkpointed steps with a Sink attached — per-chunk
   loss / grad-norm / tokens/s / compile-count events;
2. serve a batch of requests through the SAME sink — per-tick latency,
   queue depth, free-page watermark, insert/evict, compile counts;
3. statically ledger the compiled train step (collectives + FLOPs from
   the HLO the partitioner actually emitted) and diff it against the
   measured step time into an achieved-fraction roofline line;
4. aggregate per-rank metrics ACROSS the mesh via comm.collectives
   (the mpicuda3 max-min gather as one compiled program);
5. collapse the artifact with obs.report — the table rank 0 used to
   print, reconstructed from the file alone.

argv tier:  ex25_observability.py [--steps=N]
"""

import pathlib
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main(argv=None) -> None:
    ensure_devices()
    import jax
    import numpy as np

    from tpuscratch.models import TransformerConfig
    from tpuscratch.models.trainer import train
    from tpuscratch.models.transformer import init_params, train_step
    from tpuscratch.obs import Sink, analyze, mesh_reduce, roofline
    from tpuscratch.obs import report as obs_report
    from tpuscratch.runtime.config import Config
    from tpuscratch.runtime.mesh import make_mesh
    from tpuscratch.serve import Request, ServeConfig, ServeEngine

    cli = Config.load(argv)
    steps = cli.steps if "steps" in cli.explicit else 6
    mesh = make_mesh((2, 2), ("dp", "sp"))
    cfg = TransformerConfig(d_model=16, n_heads=2, n_experts=2, d_ff=32,
                            n_layers=1, capacity_factor=2.0)
    workdir = tempfile.mkdtemp(prefix="tpuscratch_obs_")
    path = f"{workdir}/run.jsonl"

    banner("1. instrumented training (train/chunk events)")
    with Sink(path, run={"example": "ex25", "mesh": "2x2"}) as sink:
        _, tr = train(mesh, cfg, steps=steps, save_every=max(1, steps // 2),
                      ckpt_dir=f"{workdir}/ckpt", obs=sink)
        print(f"ran {tr.steps_run} steps, losses {tr.losses}")

        banner("2. instrumented serving (serve/tick events)")
        scfg = ServeConfig(n_slots=4, n_pages=16, page_size=4, max_seq=16,
                           vocab=32)
        engine = ServeEngine(mesh, cfg, scfg, sink=sink)
        rep = engine.run([
            Request(rid=i, prompt=tuple(1 + (i + j) % scfg.vocab
                                        for j in range(3)),
                    max_new=2 + i % 3)
            for i in range(6)
        ])
        print(f"served {rep.completed} requests, {rep.tokens_generated} "
              f"tokens, decode compiles {rep.decode_compiles}")
        assert rep.decode_compiles == 1  # zero steady-state recompiles

    banner("3. static comm/FLOP ledger of the compiled train step")
    import time

    fn = train_step(mesh, cfg)
    params = init_params(0, cfg)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((4, 16, cfg.d_model)).astype(np.float32)
    y = rng.standard_normal((4, 16, cfg.d_model)).astype(np.float32)
    led = analyze(fn, params, x, y)
    print(led.summary())
    assert led.counts(), "a dp x sp train step must emit collectives"
    params, loss = fn(params, x, y)  # compile
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    params, loss = fn(params, x, y)
    jax.block_until_ready(loss)
    rl = roofline(led, time.perf_counter() - t0,
                  peak_flops_per_s=1e12, peak_hbm_bytes_per_s=1e11)
    print(rl.summary())

    banner("4. cross-rank aggregation through the mesh collectives")
    # pretend each rank measured its own step time; reduce on the mesh
    per_rank = [0.010, 0.012, 0.011, 0.013]
    red = mesh_reduce(mesh, per_rank, ops=("sum", "max", "min"))
    print(f"per-rank step_s {per_rank}: worst {float(red['max']):.3f}, "
          f"best {float(red['min']):.3f}, "
          f"mean {float(red['sum']) / len(per_rank):.4f}")
    assert float(red["max"]) >= float(red["min"])

    banner("5. the artifact, collapsed (obs.report)")
    summary = obs_report.summarize(obs_report.load_events([path]))
    print(obs_report.format_table(summary))
    assert summary["events"]["train/chunk"]["count"] >= 1
    assert summary["events"]["serve/tick"]["count"] >= 1
    # the trainer's recompile detector, read back from the file
    assert summary["events"]["train/chunk"]["fields"]["compiles"]["max"] == 1
    print(f"\n[{jax.default_backend()}] observability loop PASSED")


if __name__ == "__main__":
    main()
