"""Nonblocking neighbor exchange with open boundaries — mpi5/mpi6 parity.

mpi5: every rank sends its id to rank+-1 and receives theirs, with
boundary ranks skipping the missing side. mpi6 adds a root gather of each
rank's (left, self, right) triple and a pretty print
(/root/reference/mpi5.cpp:34-75, mpi6.cpp:89-106). One shard_map program
does both: the neighbor ppermutes and the gather are a single compiled
collective schedule — the Waitall is implicit in dataflow.
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main() -> None:
    ensure_devices()
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from tpuscratch.comm import gather_to_root, neighbor_exchange, run_spmd
    from tpuscratch.runtime.mesh import make_mesh_1d

    banner("neighbor exchange + gather (mpi5/mpi6)")
    mesh = make_mesh_1d("x")
    n = mesh.devices.size

    def body(x):
        # exchange rank+1 so ppermute's zero fill decodes to -1 ("no
        # neighbor") and is never confused with rank 0's real id
        left, right = neighbor_exchange(x + 1.0, "x", periodic=False)
        triple = jnp.stack([left - 1.0, x, right - 1.0])  # (3, 1) per rank
        return gather_to_root(triple, "x")                # (n, 3, 1) on root

    f = run_spmd(mesh, body, P("x"), P("x", None))
    out = np.asarray(f(jnp.arange(n, dtype=jnp.float32)))
    root_view = out[:n, :, 0]  # root rank's gathered block
    print("rank: (from-left, self, from-right)  [-1 = open boundary]")
    for r, (left, me, right) in enumerate(root_view):
        print(f"  {r}: ({left:.0f}, {me:.0f}, {right:.0f})")


if __name__ == "__main__":
    main()
