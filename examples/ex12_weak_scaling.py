"""Weak-scaling stencil sweep: fixed per-chip tile, growing mesh.

BASELINE config 5's harness as a runnable driver (the reference's scaling
story is the capacity anecdote at mpicuda2.cu:44-47; this measures what it
eyeballs). On one box the mesh is virtual CPU devices, so the efficiency
numbers measure host-core contention, not ICI — run on a real slice for
chip numbers (BASELINE.md).
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main(argv=None) -> None:
    ensure_devices()
    from tpuscratch.bench.weak_scaling import bench_weak_scaling, report
    from tpuscratch.runtime.config import Config

    # argv tier: ex12_weak_scaling.py [tile_w tile_h] [--steps=N]
    cfg = Config.load(argv)
    th = cfg.tile_height if "tile_height" in cfg.explicit else 128
    tw = cfg.tile_width if "tile_width" in cfg.explicit else 128
    banner("weak-scaling stencil (BASELINE config 5)")
    pts = bench_weak_scaling(
        per_chip=(th, tw), steps=cfg.steps if "steps" in cfg.explicit else 10,
        device_counts=None, iters=3,
        fence="readback",
    )
    print(report(pts))


if __name__ == "__main__":
    main()
