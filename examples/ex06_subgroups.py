"""Sub-communicators via mesh axes — mpi9 parity.

The reference splits the world into two halves with MPI groups, allreduces
within each half AND across the world, and shows the rank renumbering
(/root/reference/mpi9.cpp:27-73). Here the split is a second mesh axis:
no group objects, no Comm_create, nothing to free — psum over 'local' is
the per-half reduce, psum over both axes is the world reduce, and the
"renumbered rank" is just lax.axis_index('local').
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main() -> None:
    ensure_devices()
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from tpuscratch.comm import allreduce_sum, run_spmd
    from tpuscratch.runtime.mesh import make_mesh

    banner("sub-group allreduce (mpi9)")
    mesh = make_mesh((2, 4), ("half", "local"))

    def body(x):
        per_half = allreduce_sum(x, "local")
        world = allreduce_sum(x, ("half", "local"))
        my_local_rank = lax.axis_index("local")  # renumbered rank
        return per_half, world, my_local_rank.astype(jnp.float32)[None]

    f = run_spmd(
        mesh, body, P("half", "local"),
        (P("half", "local"), P("half", "local"), P(("half", "local"))),
    )
    vals = jnp.arange(8, dtype=jnp.float32).reshape(2, 4)
    per_half, world, local_ranks = (np.asarray(o) for o in f(vals))
    print("values:", vals.tolist())
    print("per-half sums:", per_half[:, 0].tolist(), "(each half concurrent)")
    print("world sum:", world[0, 0])
    print("renumbered local ranks:", local_ranks.tolist())


if __name__ == "__main__":
    main()
