"""Distributed 2D FFT and the spectral Poisson solve.

The pencil-decomposition FFT: each rank transforms its locally-contiguous
axis, one all_to_all transposes the grid across the mesh, and the other
axis is transformed locally. Under MPI this is hand-packed
``MPI_Alltoall`` of strided blocks — the machinery the reference builds
with derived datatypes (/root/reference/mpi-complex-types.cpp); here the
packing dissolves into one collective. The demo then solves periodic
Poisson spectrally and cross-checks the answer against the 5-point
operator — the same operator ex14 solves iteratively with CG.

argv tier:  ex15_distributed_fft.py [tile_w tile_h]
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main(argv=None) -> None:
    ensure_devices()
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from tpuscratch.comm import run_spmd
    from tpuscratch.parallel.fft import (
        complex_supported,
        fft2_sharded,
        fft2_sharded_pair,
    )
    from tpuscratch.runtime.config import Config
    from tpuscratch.runtime.mesh import make_mesh_1d
    from tpuscratch.solvers.spectral import (
        periodic_laplacian_np,
        periodic_poisson_fft,
    )

    cfg = Config.load(argv)
    n = 8
    mesh = make_mesh_1d("x", n)
    gh = n * (cfg.tile_height if "tile_height" in cfg.explicit else 4)
    gw = n * (cfg.tile_width if "tile_width" in cfg.explicit else 6)
    banner(f"distributed 2D FFT, {gh}x{gw} grid row-sharded over {n} devices")

    rng = np.random.default_rng(0)
    x = (rng.standard_normal((gh, gw)) + 1j * rng.standard_normal((gh, gw)))
    x = x.astype(np.complex64)
    expect = np.fft.fft2(x)
    if complex_supported():
        prog = run_spmd(mesh, lambda s: fft2_sharded(s, "x"), P("x"), P("x"))
        got = np.asarray(prog(jnp.asarray(x)))
        err = np.abs(got - expect).max() / np.abs(expect).max()
        print(f"fft2 (complex jnp.fft) vs numpy oracle: rel err {err:.2e} "
              f"({'PASSED' if err < 1e-5 else 'FAILED'})")
    else:
        print("backend has no complex dtype; skipping the jnp.fft path")

    # the MXU path: matmul-form DFT on (re, im) planes — the one that
    # runs on TPU backends without complex support
    pair = run_spmd(
        mesh,
        lambda r, i: fft2_sharded_pair(r, i, "x"),
        (P("x"), P("x")),
        (P("x"), P("x")),
    )
    re, im = pair(jnp.asarray(x.real), jnp.asarray(x.imag))
    got = np.asarray(re) + 1j * np.asarray(im)
    err = np.abs(got - expect).max() / np.abs(expect).max()
    print(f"fft2 (matmul DFT pair) vs numpy oracle: rel err {err:.2e} "
          f"({'PASSED' if err < 1e-4 else 'FAILED'})")

    banner("spectral periodic Poisson solve (one FFT round trip)")
    b = rng.standard_normal((gh, gw)).astype(np.float32)
    b -= b.mean()
    sol = periodic_poisson_fft(b, mesh)
    resid = np.abs(periodic_laplacian_np(sol.astype(np.float64)) - b).max()
    print(f"max |A x - b| = {resid:.2e} "
          f"({'PASSED' if resid < 1e-4 else 'FAILED'})")


if __name__ == "__main__":
    main()
