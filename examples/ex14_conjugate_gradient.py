"""Distributed conjugate gradient: the reference's primitives composed.

The reference builds a halo exchange with a no-op Compute
(/root/reference/stencil2d/mpi-2d-stencil-subarray.cpp:27) and a
distributed dot product (/root/reference/mpicuda2.cu) as separate
end-point programs. This example runs the algorithm they add up to: CG on
the zero-Dirichlet 5-point Laplacian, matvec = halo exchange + stencil,
inner products = psum — one compiled program, every iteration on device.

argv tier:  ex14_conjugate_gradient.py [tile_w tile_h] [--steps=MAX_ITERS]
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main(argv=None) -> None:
    ensure_devices()
    import numpy as np

    from tpuscratch.runtime.config import Config
    from tpuscratch.runtime.mesh import make_mesh_2d
    from tpuscratch.solvers import poisson_solve
    from tpuscratch.solvers.cg import laplacian_apply_np

    cfg = Config.load(argv)
    mesh = make_mesh_2d((2, 4))
    gh, gw = 2 * cfg.tile_height, 4 * cfg.tile_width
    max_iters = cfg.steps if "steps" in cfg.explicit else gh * gw
    banner(f"conjugate gradient, {gh}x{gw} Poisson grid on a 2x4 mesh")

    # manufactured solution: b = A x_true, then recover x_true
    rng = np.random.default_rng(0)
    x_true = rng.standard_normal((gh, gw)).astype(np.float32)
    b = laplacian_apply_np(x_true.astype(np.float64)).astype(np.float32)

    x, iters, relres = poisson_solve(b, mesh, tol=1e-6, max_iters=max_iters)
    err = np.abs(x - x_true).max()
    print(f"converged in {iters} iterations, relative residual {relres:.2e}")
    print(f"max |x - x_true| = {err:.2e} "
          f"({'PASSED' if err < 1e-3 and relres <= 1e-6 else 'FAILED'})")


if __name__ == "__main__":
    main()
