"""Tiered KV memory: host-offloaded cold pages, demonstrated.

Residency per chip is capped by HBM — the device page pool bounds how
many users' KV state can be resident at once.  ISSUE 13 extends the
paged cache ONE level down the memory hierarchy (the reference's L2
``host_allocator`` lineage, ``native/hostpool.py``, finally on the
serving hot path): cold pages spill into page-shaped pinned-host
buffers and prefetch back AHEAD of the decode sweep, wave-scheduled and
double-buffered like the halo driver's exchange/compute overlap, so
admission capacity becomes device + host pages at fixed HBM.

Demonstrated and self-checked here:

1. **forced spill, identical output** — a device pool several times
   smaller than the working set drains the same request stream as an
   untiered engine with plenty of room: greedy outputs BIT-identical,
   real spill/prefetch traffic on the counters;
2. **residency at fixed HBM** — the untiered watermark caps concurrent
   residents at what the device pool seats; the tier lifts the cap
   (the config-12 ``serve_kv_tiered`` row, live);
3. **the traffic ledger** — host↔device bytes are STATIC accounting:
   exact page-move counters x the exact per-page byte form
   (``obs.ledger.kv_host_traffic_bytes``), agreeing with the host
   store's actually-moved byte counters;
4. **warm-prefix parking** — an evicted shared-prefix chain parks in
   the host tier instead of dying with its last holder; a later trie
   hit restores it, so sharing no longer needs a concurrently-live
   holder.

argv tier:  ex31_tiered_kv.py [--host-pages=N]
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from examples._common import banner, ensure_devices


def main(argv=None) -> None:
    ensure_devices()
    import dataclasses

    import jax

    from tpuscratch.models import TransformerConfig
    from tpuscratch.obs.ledger import kv_host_traffic_bytes
    from tpuscratch.runtime.mesh import make_mesh
    from tpuscratch.serve import Request, ServeConfig, ServeEngine

    argv = list(sys.argv[1:]) if argv is None else list(argv)
    host_pages = 16
    for a in argv:
        if a.startswith("--host-pages="):
            host_pages = int(a.split("=", 1)[1])

    banner("ex31: tiered KV memory — host-offloaded cold pages")
    cfg = TransformerConfig(d_model=32, n_heads=4, n_experts=4, d_ff=48,
                            n_layers=1, capacity_factor=4.0)
    mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
    scfg = ServeConfig(n_slots=4, n_pages=6, page_size=4, max_seq=24,
                       vocab=16)
    reqs = [Request(rid=i, prompt=(1 + i % 3, 2, 3, 4, 5),
                    max_new=4 + i % 3) for i in range(6)]

    # 1. forced spill vs a roomy untiered engine: identical outputs
    base_eng = ServeEngine(mesh, cfg, scfg)
    base = base_eng.run(reqs)
    tier_eng = ServeEngine(
        mesh, cfg, dataclasses.replace(scfg, kv_host_pages=host_pages)
    )
    tier = tier_eng.run(reqs)
    assert tier.outputs == base.outputs, "tiered output diverged!"
    print(f"forced spill: {tier.spilled_pages} pages out, "
          f"{tier.prefetched_pages} back, {tier.cold_hits} cold hits — "
          f"outputs identical")

    # 2. the traffic ledger: three accountings, one number
    traffic = kv_host_traffic_bytes(
        tier_eng._kv, tier_eng.host_spilled_pages,
        tier_eng.host_prefetched_pages,
    )
    store = tier_eng._allocators[0].store
    assert traffic.total_bytes == tier.host_bytes
    assert store.stats()["spill_bytes"] == traffic.spill_bytes
    print(f"traffic ledger: {traffic.page_bytes:.0f} B/page x "
          f"{traffic.spilled_pages + traffic.prefetched_pages} moves = "
          f"{traffic.total_bytes:.0f} B "
          f"({traffic.per_token(tier.tokens_generated):.0f} B/token) — "
          f"counters x analytic form == store bytes")

    # 3. residency at fixed HBM: peak concurrent residents — re-drive
    # the SAME drained engines (their compiled programs are warm), and
    # watch the watermark cap the untiered one below the slot bank
    def peak_residents(eng, rid0):
        for i, r in enumerate(reqs[:4]):
            eng.submit(dataclasses.replace(r, rid=rid0 + i))
        peak = 0
        while eng.n_queued or eng.n_active:
            eng.step()
            peak = max(peak, eng.n_active)
        return peak

    cap_base = peak_residents(base_eng, 100)
    cap_tier = peak_residents(tier_eng, 200)
    print(f"resident users at a fixed {scfg.n_pages}-page device pool: "
          f"{cap_base} untiered -> {cap_tier} tiered")
    assert cap_tier > cap_base

    # 4. warm-prefix parking: sharing without a live holder
    share_cfg = dataclasses.replace(scfg, n_slots=2, n_pages=8,
                                    prefix_share=True,
                                    kv_host_pages=host_pages)
    eng = ServeEngine(mesh, cfg, share_cfg)
    pr = (1, 2, 3, 4, 5, 6, 7, 8)
    eng.run([Request(rid=0, prompt=pr, max_new=3)])
    parked = eng._allocators[0].n_parked
    rep = eng.run([Request(rid=1, prompt=pr + (9,), max_new=3)])
    print(f"warm prefix: {parked} pages parked after the last holder "
          f"left; revisit shared {rep.shared_tokens} tokens from the "
          f"host tier ({eng._allocators[0].parked_hits} restores)")
    assert parked > 0 and rep.shared_tokens >= len(pr)

    print("PASSED")


if __name__ == "__main__":
    main()
