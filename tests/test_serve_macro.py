"""Device-resident macro-step decode (ISSUE 15, marker ``macro``).

The correctness anchors:

- **greedy bit-identity across T**: ``ServeConfig(macro_steps=T)``
  fuses T whole engine ticks into one compiled ``lax.scan`` — same
  outputs at T in {1, 4, 16}, composed with the dtype ladder
  (fp32/int8/fp8), prefix sharing, chunked prefill, disaggregation,
  and the fleet router, on the 1x1 and 2x2 meshes (``macro_steps=1``
  builds the EXACT legacy per-token program — no loop program exists);
- **boundary laws**: a request whose budget ends mid-scan emits
  exactly ``max_new`` tokens (the done-mask suppresses its writes and
  flips it to the legacy idle contract for the scan tail), TTFT is
  stamped correctly when tokens land inside a macro tick, and chaos
  recovery (``serve/prefill`` fault at T=16) replays bit-identically;
- **dispatch accounting**: ``GenerateReport.dispatches``/``host_syncs``
  drop ~T× at fixed token count, with the single-stream identity
  ``dispatches == ceil(slot_steps / macro_steps)`` exact;
- **no clamping** (ISSUE 19 lift): speculative decode rides the scan
  carry (device propose/verify/accept) and the tiered wave prefetch
  overlaps the running scan, so ``spec_k > 0`` and
  ``kv_host_pages > 0`` compose with ``macro_steps > 1`` at full T —
  ``macro_steps_effective`` reports the configured T and
  ``macro_clamped_by`` is always ``None`` (the stale ``"spec_k"`` /
  ``"kv_host_pages"`` reasons must never reappear);
- **one compiled sweep, reused**: the scan program's optimized HLO
  carries ONE copy of the sweep's collective pattern regardless of T
  (``obs.ledger`` instruction counts equal at T=4 and T=16), and
  steady-state serving at any T still compiles the decode side exactly
  once (CompileCounter);
- **roofline accounting** (the decode_bench fix): the static
  swept-byte accounting scales by the per-tick round delta, so a
  macro window books the same sweep traffic as the per-token window
  for the same tokens instead of ~T× less.
"""

import dataclasses
import math

import pytest
import jax

from tpuscratch.models.transformer import TransformerConfig
from tpuscratch.runtime.mesh import make_mesh
from tpuscratch.serve import (
    DisaggEngine,
    FleetRouter,
    Request,
    RouterConfig,
    ServeConfig,
    ServeEngine,
)

pytestmark = pytest.mark.macro


def cfg_for(**kw):
    # capacity_factor == n_experts: the no-drop MoE regime every other
    # serve equivalence test runs under (test_serve's rule)
    kw.setdefault("n_layers", 1)
    kw.setdefault("capacity_factor", 4.0)
    return TransformerConfig(
        d_model=32, n_heads=4, n_experts=4, d_ff=48, **kw
    )


SCFG = ServeConfig(n_slots=4, n_pages=16, page_size=4, max_seq=24,
                   vocab=16)

#: staggered budgets + mixed lengths: evictions land mid-scan at every
#: T and queued requests back-fill at macro boundaries
REQS = [
    Request(rid=i, prompt=tuple((3 * i + j) % 16 for j in range(2 + i % 5)),
            max_new=2 + (i * 3) % 6)
    for i in range(6)
]


def run_engine(dims=(1, 1), reqs=REQS, cfg=None, **scfg_kw):
    cfg = cfg or cfg_for()
    n = dims[0] * dims[1]
    mesh = make_mesh(dims, ("dp", "sp"), jax.devices()[:n])
    scfg = dataclasses.replace(SCFG, **scfg_kw)
    eng = ServeEngine(mesh, cfg, scfg)
    return eng, eng.run(reqs)


class TestMacroBitIdentity:
    def test_identical_across_T_and_legacy_program_at_1(self):
        cfg = cfg_for(n_layers=2)
        eng1, r1 = run_engine(cfg=cfg)
        # macro_steps=1 IS the legacy engine: no scan program is built
        assert eng1._decode_loop is None and eng1._decode is not None
        for T in (4, 16):
            engT, rT = run_engine(cfg=cfg, macro_steps=T)
            assert engT._decode is None and engT._decode_loop is not None
            assert rT.outputs == r1.outputs
            assert rT.tokens_generated == r1.tokens_generated
            assert rT.slot_steps == r1.slot_steps
            assert rT.decode_compiles == 1   # one scan program, ever
            assert engT.free_pages() == eng1.free_pages()  # no leaks

    @pytest.mark.parametrize(
        "kv_dtype",
        ["int8",
         # the fp8 rung rides the identical dtype-generic write/scale
         # path (one mechanism, test_serve's ladder contract) — kept
         # out of the tier-1 wall like PR-14's fp8+spec composition
         pytest.param("fp8", marks=pytest.mark.slow)],
    )
    def test_identical_on_quantized_rungs(self, kv_dtype):
        _, r1 = run_engine(kv_dtype=kv_dtype)
        _, r4 = run_engine(kv_dtype=kv_dtype, macro_steps=4)
        assert r4.outputs == r1.outputs

    def test_identical_with_share_and_chunk(self):
        kw = dict(prefix_share=True, chunk_prefill=2, kv_dtype="int8")
        _, r1 = run_engine(**kw)
        _, r4 = run_engine(macro_steps=4, **kw)
        assert r4.outputs == r1.outputs
        # the sharing counters are scheduling-independent too
        assert (r4.prefill_tokens, r4.shared_tokens) == (
            r1.prefill_tokens, r1.shared_tokens
        )

    def test_identical_on_2x2_mesh_composed(self):
        kw = dict(prefix_share=True, kv_dtype="int8")
        _, r1 = run_engine(dims=(2, 2), **kw)
        _, r16 = run_engine(dims=(2, 2), macro_steps=16, **kw)
        assert r16.outputs == r1.outputs

    def test_identical_at_temperature(self):
        # the in-scan fold_in chain must reproduce the host-side
        # request_keys stream draw-for-draw, not just under argmax
        kw = dict(temperature=0.8, top_k=5, seed=7)
        _, r1 = run_engine(**kw)
        _, r4 = run_engine(macro_steps=4, **kw)
        assert r4.outputs == r1.outputs

    def test_identical_under_disagg(self):
        cfg = cfg_for()
        mesh = make_mesh((2, 2), ("dp", "sp"), jax.devices()[:4])
        reqs = [Request(rid=i, prompt=(1 + i, 2), max_new=4)
                for i in range(4)]

        def run(T):
            eng = DisaggEngine(mesh, cfg,
                               dataclasses.replace(SCFG, macro_steps=T))
            return eng, eng.run(reqs)

        eng1, r1 = run(1)
        eng4, r4 = run(4)
        assert r4.outputs == r1.outputs
        assert eng4.dispatches < eng1.dispatches

    def test_identical_under_router(self):
        cfg = cfg_for()
        mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        reqs = [Request(rid=i, prompt=(1 + i, 2, 3), max_new=5)
                for i in range(4)]

        def run(T):
            reps = [ServeEngine(mesh, cfg,
                                dataclasses.replace(SCFG, macro_steps=T))
                    for _ in range(2)]
            return FleetRouter(reps, RouterConfig(affinity=False)).run(reqs)

        r1, r4 = run(1), run(4)
        assert r4.outputs == r1.outputs
        assert 0 < r4.dispatches < r1.dispatches
        assert r4.host_syncs == r4.dispatches


class TestMacroBoundaryLaws:
    def test_budget_ends_mid_scan_emits_exactly_max_new(self):
        # max_new - 1 decode steps not divisible by T: the done-mask
        # must suppress the scan tail, never emit past the budget
        for T, max_new in ((4, 4), (16, 6), (16, 2)):
            req = Request(rid=0, prompt=(1, 2, 3), max_new=max_new)
            eng, rep = run_engine(reqs=[req], macro_steps=T)
            assert rep.completed == 1
            assert len(dict(rep.outputs)[0]) == max_new
            assert rep.tokens_generated == max_new
            assert eng.free_pages() == [16]  # evicted, pages returned

    def test_mixed_budgets_one_bank(self):
        # slots finish at different scan iterations of the SAME
        # dispatch; each stream must stop at its own budget and the
        # finished slots ride the tail write-suppressed
        reqs = [Request(rid=i, prompt=(1 + i,), max_new=1 + i)
                for i in range(4)]
        _, r1 = run_engine(reqs=reqs)
        _, r16 = run_engine(reqs=reqs, macro_steps=16)
        assert r16.outputs == r1.outputs
        for rid, toks in r16.outputs:
            assert len(toks) == 1 + rid

    def test_ttft_stamped_inside_macro_tick(self):
        # first tokens land at prefill/admission — stamping must
        # survive the macro scheduling (completions inside macro ticks)
        eng, rep = run_engine(macro_steps=16)
        stamped = dict(rep.ttft_s)
        assert set(stamped) == {r.rid for r in REQS}
        assert all(t >= 0.0 for t in stamped.values())
        # chunked-prefill admissions sample their first token at
        # tail-drain INSIDE the tick stream — stamp must still exist
        _, rep_c = run_engine(macro_steps=4, chunk_prefill=2)
        assert set(dict(rep_c.ttft_s)) == {r.rid for r in REQS}

    def test_recover_replay_bit_identical_under_chaos_t16(self):
        from tpuscratch.ft.chaos import ChaosPlan, Fault

        reqs = [Request(rid=i, prompt=(1 + i, 2), max_new=4)
                for i in range(3)]
        _, clean = run_engine(reqs=reqs, macro_steps=16)

        # a mid-drain prefill fault raises through (retry_budget=0):
        # _recover_cache resets the donated pool and requeues every
        # in-flight request; the replay through macro ticks must
        # reproduce the fault-free run bit-for-bit
        cfg = cfg_for()
        mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        plan = ChaosPlan(0, [Fault("serve/prefill", key=1, at=(0,),
                                   times=1)])
        eng = ServeEngine(mesh, cfg,
                          dataclasses.replace(SCFG, macro_steps=16),
                          chaos=plan)
        for r in reqs:
            eng.submit(r)
        outputs = {}
        raised = 0
        for _ in range(100):
            if not (eng.n_queued or eng.n_active):
                break
            try:
                for rid, toks in eng.step():
                    outputs[rid] = toks
            except Exception:
                raised += 1
        assert raised == 1
        assert tuple(sorted(outputs.items())) == clean.outputs
        assert eng.free_pages() == [16]

    def test_failed_macro_dispatch_recovers_and_replays(self):
        # the scan program's donated cache may be consumed by a raise:
        # the legacy recovery contract, through the macro path.
        # max_new > T + 1 so the bank is still mid-stream after the
        # first macro tick — the raise lands with slots active.
        reqs = [Request(rid=i, prompt=(1 + i, 2), max_new=10)
                for i in range(3)]
        _, clean = run_engine(reqs=reqs, macro_steps=4)
        cfg = cfg_for()
        mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        eng = ServeEngine(mesh, cfg,
                          dataclasses.replace(SCFG, macro_steps=4))
        for r in reqs:
            eng.submit(r)
        eng.step()

        class Boom(RuntimeError):
            pass

        real = eng._decode_loop

        def exploding(*a, **k):
            raise Boom("mid-flight device error")

        eng._decode_loop = exploding
        with pytest.raises(Boom):
            eng.step()
        assert eng.n_active == 0 and eng.n_queued == 3
        assert eng.free_pages() == [16]
        eng._decode_loop = real
        rep = eng.run([])
        assert rep.outputs == clean.outputs


class TestDispatchAccounting:
    def test_single_stream_identity(self):
        # ONE decoding stream: dispatches == ceil(slot_steps / T),
        # host_syncs == dispatches — the ex24/ex32 live identity
        req = Request(rid=0, prompt=(1, 2, 3), max_new=10)
        for T in (1, 4, 16):
            _, rep = run_engine(reqs=[req], macro_steps=T)
            assert rep.slot_steps == 9       # max_new - 1 (prefill emits 1)
            assert rep.dispatches == math.ceil(9 / T)
            assert rep.host_syncs == rep.dispatches

    def test_dispatches_drop_T_fold_at_fixed_tokens(self):
        _, r1 = run_engine()
        _, r16 = run_engine(macro_steps=16)
        assert r16.tokens_generated == r1.tokens_generated
        assert r1.dispatches == r1.decode_steps  # per-token: 1 per sweep
        assert r16.dispatches < r1.dispatches
        assert r16.host_syncs == r16.dispatches

    def test_decode_rounds_scale_with_span(self):
        # rounds = token rounds the bank ran: per macro dispatch, the
        # longest active span (the roofline multiplier)
        req = Request(rid=0, prompt=(1, 2, 3), max_new=10)
        eng1, _ = run_engine(reqs=[req])
        eng4, _ = run_engine(reqs=[req], macro_steps=4)
        assert eng1.decode_rounds == 9
        assert eng4.decode_rounds == 9       # same rounds, fewer dispatches
        assert eng4.dispatches == 3

    def test_no_clamp_under_spec_and_tier(self):
        # ISSUE 19: drafting moved into the scan carry and wave staging
        # overlaps the running scan, so neither spec_k nor
        # kv_host_pages clamps the macro width any more — the effective
        # T is the configured T, the clamp reason is gone, and the
        # composed outputs still match the T=1 spelling bit-for-bit
        eng_s, rep_s = run_engine(macro_steps=4, spec_k=3)
        assert eng_s.macro_steps_effective == 4
        assert eng_s.macro_clamped_by is None
        _, base_s = run_engine(spec_k=3)
        assert rep_s.outputs == base_s.outputs
        assert rep_s.dispatches < base_s.dispatches

        eng_t, rep_t = run_engine(macro_steps=4, kv_host_pages=4)
        assert eng_t.macro_steps_effective == 4
        assert eng_t.macro_clamped_by is None
        _, base_t = run_engine(kv_host_pages=4)
        assert rep_t.outputs == base_t.outputs
        assert rep_t.dispatches < base_t.dispatches
        # ledger-visible: the gauge carries the FULL configured T
        assert eng_t.metrics.gauge("serve/macro_steps").value == 4

    def test_macro_steps_validation(self):
        cfg = cfg_for()
        mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        with pytest.raises(ValueError):
            ServeEngine(mesh, cfg,
                        dataclasses.replace(SCFG, macro_steps=0))


class TestMacroPrograms:
    def test_zero_steady_state_recompiles_across_waves_of_requests(self):
        # two full admission waves through one engine: the scan
        # program must compile exactly once, ever
        eng, _ = run_engine(macro_steps=4)
        more = [Request(rid=100 + i, prompt=(2 + i, 1), max_new=5)
                for i in range(6)]
        rep2 = eng.run(more)
        assert rep2.completed == 6
        assert eng.decode_compiles == 1

    def test_scan_reuses_one_sweep_pattern(self):
        # the ledger proof: a lax.scan body appears ONCE in the
        # optimized HLO (a while loop), so the sweep's collective
        # pattern is reused T times — instruction counts must be equal
        # at T=4 and T=16 and must NOT scale with T.  2x2 mesh so the
        # sp psum / dp MoE collectives actually exist.
        import numpy as np
        import jax.numpy as jnp

        from tpuscratch.models.transformer import init_params
        from tpuscratch.obs.ledger import analyze
        from tpuscratch.serve.decode import (
            build_decode_loop,
            build_decode_step,
        )
        from tpuscratch.serve.kvcache import CacheGeometry, init_kv_cache

        cfg = cfg_for()
        mesh = make_mesh((2, 2), ("dp", "sp"), jax.devices()[:4])
        geom = CacheGeometry(cfg.n_layers, SCFG.n_pages, SCFG.page_size,
                             cfg.n_heads, cfg.d_head)
        params = init_params(0, cfg)
        kv = init_kv_cache(geom, 2)
        n = SCFG.n_slots
        embed = jnp.zeros((SCFG.vocab, cfg.d_model), jnp.float32)
        kd = jax.random.key_data(jax.random.key(0))
        i32 = lambda *s: jnp.zeros(s, jnp.int32)  # noqa: E731
        args = (params, kv, embed, kd,
                i32(n, SCFG.max_pages), i32(n), i32(n), i32(n),
                i32(n), i32(n),
                # ISSUE 19 carry: stop-token mask + in-carry
                # stopped/emitted state (the host-free EOS path)
                jnp.zeros((n, SCFG.vocab), bool), jnp.zeros((n,), bool),
                i32(n))

        counts = {}
        for T in (4, 16):
            prog = build_decode_loop(mesh, cfg, geom, T)
            counts[T] = analyze(prog, *args).counts()
        assert counts[4] == counts[16], (
            "scan collectives scale with T — the loop unrolled"
        )
        # and against the single-step program: the scan adds only the
        # early-exit mask's one scalar reduce, never a second sweep
        step_counts = analyze(
            build_decode_step(mesh, cfg, geom),
            params, kv, jnp.zeros((n, cfg.d_model), np.float32),
            i32(n, SCFG.max_pages), i32(n), i32(n), i32(n),
        ).counts()
        for kind, c in counts[16].items():
            assert c <= step_counts.get(kind, 0) + 2, (
                f"{kind}: {c} in the scan vs {step_counts.get(kind, 0)} "
                "in one step — the sweep pattern is not being reused"
            )


class TestMacroRoofline:
    def test_swept_bytes_scale_by_round_delta_at_t4(self):
        # the decode_bench fix (ISSUE 15 satellite): static swept-byte
        # accounting must multiply the sampled page footprint by the
        # tick's ROUND delta — at T=4 a tick sweeps its pages 4 times,
        # and the unscaled per-tick sample would understate the sweep
        # traffic (hence mis-state achieved_frac) ~T×.  The bench
        # methodology: warm past admission, account a steady-state
        # window with every slot live (no insert/evict inside it).
        # Warmups are ROUND-aligned, so both engines account the
        # identical rounds-5..12 footprint trajectory and the ledger-
        # exact per-round sum must agree across T.
        def accounted(T, warm_steps, steps):
            cfg = cfg_for()
            mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
            eng = ServeEngine(mesh, cfg,
                              dataclasses.replace(SCFG, macro_steps=T))
            for i in range(4):
                eng.submit(Request(rid=i, prompt=(1 + i, 2), max_new=14))
            for _ in range(warm_steps):
                eng.step()
            assert eng.n_active == 4
            page_bytes = eng.scfg.page_size * eng.kv_bytes_per_token
            swept, rprev = 0.0, eng.decode_rounds
            for _ in range(steps):
                before = eng.cached_pages * page_bytes
                eng.step()
                after = eng.cached_pages * page_bytes
                swept += 0.5 * (before + after) * (
                    eng.decode_rounds - rprev
                )
                rprev = eng.decode_rounds
            assert eng.n_active == 4         # window stayed steady-state
            return swept, eng.decode_rounds

        s1, rounds1 = accounted(1, warm_steps=4, steps=8)
        s4, rounds4 = accounted(4, warm_steps=1, steps=2)
        assert rounds1 == rounds4            # same token rounds ran
        assert s4 == pytest.approx(s1, rel=0.10)
        # and nowhere near the unscaled ~4x understatement
        assert s4 > 0.5 * s1

    def test_bench_decode_macro_fields(self):
        from tpuscratch.bench.decode_bench import bench_decode

        cfg = cfg_for()
        mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        scfg = dataclasses.replace(SCFG, n_slots=1, n_pages=64,
                                   max_seq=64, macro_steps=4)
        r = bench_decode(mesh, cfg, scfg, prompt_len=4, measure_steps=4,
                         warmup_steps=2)
        assert r.macro_steps == 4
        assert r.dispatches_per_token == pytest.approx(0.25)
        assert r.host_syncs_per_token == pytest.approx(0.25)
        assert r.swept_bytes > 0


class TestMacroRegressGate:
    def test_macro_row_direction_gated(self):
        # the config-12 serve_decode_macro row through the regression
        # gate: a clean same-code pair passes; dispatches/token creeping
        # back up (the scan losing coverage) or tokens/s collapsing
        # past the CPU noise floor regresses.  Static dispatch fields
        # keep the TIGHT band (no noise floor matches them).
        from tpuscratch.obs import regress

        row = {
            "config": 12, "metric": "serve_decode_macro",
            "platform": "cpu", "value": 1.5e4,
            "tokens_per_s_t1": 1.2e3, "tokens_per_s_t16": 1.5e4,
            "macro_speedup": 12.5,
            "dispatches_per_token_t1": 1.0,
            "dispatches_per_token_t16": 0.0625,
            "host_syncs_per_token_t16": 0.0625,
        }
        base = regress.index_rows([dict(row)])
        clean = regress.compare(base, regress.index_rows([dict(row)]),
                                noise=0.05)
        assert not regress.has_regression(clean)

        # injected: dispatches/token back to ~1 (static field, tight
        # band — a 2% drift would already flag)
        bad = dict(row, dispatches_per_token_t16=1.0)
        findings = regress.compare(base, regress.index_rows([bad]),
                                   noise=0.05)
        assert regress.has_regression(findings)
        names = {f.field for f in findings if f.status == "regressed"}
        assert "dispatches_per_token_t16" in names

        # injected: T=16 rate collapsing past the 40% CPU floor
        slow = dict(row, tokens_per_s_t16=1.5e4 * 0.4,
                    macro_speedup=12.5 * 0.4)
        findings = regress.compare(base, regress.index_rows([slow]),
                                   noise=0.05)
        assert regress.has_regression(findings)

        # directions as registered: dispatches/host_syncs LOWER,
        # speedup/tokens HIGHER
        assert regress.direction("dispatches_per_token_t16") == "lower"
        assert regress.direction("host_syncs_per_token_t16") == "lower"
        assert regress.direction("macro_speedup") == "higher"
        assert regress.direction("tokens_per_s_t16") == "higher"
        # the wall-clock fields carry CPU noise floors; the static
        # dispatch counters must NOT (the PR-14 floor discipline)
        assert regress.noise_floor("tokens_per_s_t16", "cpu") > 0
        assert regress.noise_floor("dispatches_per_token_t16", "cpu") == 0
        assert regress.noise_floor("tokens_per_s_t16", "tpu") == 0

    def test_macro_row_through_check_cli(self, tmp_path):
        # the full record.py --check path (it runs regress.main
        # in-process on two artifacts): a clean same-code pair exits
        # 0, an injected dispatches-per-token regression exits 1
        import json

        from tpuscratch.obs import regress

        row = {
            "config": 12, "metric": "serve_decode_macro",
            "platform": "cpu", "value": 1.5e4,
            "tokens_per_s_t1": 1.2e3, "tokens_per_s_t16": 1.5e4,
            "macro_speedup": 12.5,
            "dispatches_per_token_t16": 0.0625,
            "host_syncs_per_token_t16": 0.0625,
        }
        base = tmp_path / "base.json"
        base.write_text(json.dumps(row) + "\n")
        clean = tmp_path / "clean.json"
        clean.write_text(json.dumps(row) + "\n")
        assert regress.main([str(base), str(clean)]) == 0
        bad = tmp_path / "bad.json"
        bad.write_text(
            json.dumps(dict(row, dispatches_per_token_t16=1.0)) + "\n"
        )
        assert regress.main([str(base), str(bad)]) == 1
