"""Tests for the flagship halo-exchange library.

Four oracle layers, mirroring the reference's own strategy (SURVEY.md §4):
1. pure region-geometry unit tests (TestSubRegionExtraction parity);
2. the golden-file oracle — core = own rank id, each halo piece = the
   periodic neighbor's rank id (stencil2d/sample-output semantics), run
   live on a 2x4 CPU mesh AND cross-checked against the reference's
   checked-in 3x3 golden dumps by pure geometry;
3. a dual-backend oracle: K distributed stencil steps == K steps of a
   plain single-array jnp stencil on the undecomposed grid;
4. a deliberate-miswiring ablation (the NO_SYNC negative-test idea,
   ref_parallel-dot-product-atomics.cu:26-32): a wrong permutation must be
   caught by the golden oracle.
"""

import pathlib
import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpuscratch.comm import run_spmd
from tpuscratch.dtypes import SubarraySpec
from tpuscratch.halo import HaloSpec, Region, TileLayout, halo_exchange, sub_region
from tpuscratch.halo.stencil import five_point, run_stencil, stencil_step
from tpuscratch.runtime.mesh import make_mesh_2d
from tpuscratch.runtime.topology import ALL_DIRECTIONS, CartTopology, Direction

REF_SAMPLES = pathlib.Path("/root/reference/stencil2d/sample-output")


class TestRegionGeometry:
    """13-region math on a 32x32 grid with a 5x5 stencil (halo 2) — the
    same configuration the reference's in-header self-test exercises."""

    BASE = SubarraySpec((0, 0), (32, 32))

    def test_center(self):
        c = sub_region(self.BASE, 2, 2, Region.CENTER)
        assert c.offsets == (2, 2) and c.shape == (28, 28)

    def test_corners(self):
        tl = sub_region(self.BASE, 2, 2, Region.TOP_LEFT)
        br = sub_region(self.BASE, 2, 2, Region.BOTTOM_RIGHT)
        assert tl.offsets == (0, 0) and tl.shape == (2, 2)
        assert br.offsets == (30, 30) and br.shape == (2, 2)

    def test_edges(self):
        top = sub_region(self.BASE, 2, 2, Region.TOP)
        left = sub_region(self.BASE, 2, 2, Region.LEFT)
        assert top.offsets == (0, 2) and top.shape == (2, 28)
        assert left.offsets == (2, 0) and left.shape == (28, 2)

    def test_strips_full_length(self):
        ts = sub_region(self.BASE, 2, 2, Region.TOP_STRIP)
        rs = sub_region(self.BASE, 2, 2, Region.RIGHT_STRIP)
        assert ts.offsets == (0, 0) and ts.shape == (2, 32)
        assert rs.offsets == (0, 30) and rs.shape == (32, 2)

    #: every Region id's (offsets, shape) on the 32x32/halo-2 grid — the
    #: exhaustive 13-case table the reference's in-header self-test walks
    #: (stencil2D.h:441-510 exercises all 13 RegionIDs on this config)
    ALL_13 = {
        Region.CENTER: ((2, 2), (28, 28)),
        Region.TOP: ((0, 2), (2, 28)),
        Region.BOTTOM: ((30, 2), (2, 28)),
        Region.LEFT: ((2, 0), (28, 2)),
        Region.RIGHT: ((2, 30), (28, 2)),
        Region.TOP_LEFT: ((0, 0), (2, 2)),
        Region.TOP_RIGHT: ((0, 30), (2, 2)),
        Region.BOTTOM_LEFT: ((30, 0), (2, 2)),
        Region.BOTTOM_RIGHT: ((30, 30), (2, 2)),
        Region.TOP_STRIP: ((0, 0), (2, 32)),
        Region.BOTTOM_STRIP: ((30, 0), (2, 32)),
        Region.LEFT_STRIP: ((0, 0), (32, 2)),
        Region.RIGHT_STRIP: ((0, 30), (32, 2)),
    }

    def test_all_thirteen_regions(self):
        assert set(self.ALL_13) == set(Region)  # table is exhaustive
        for region, (offsets, shape) in self.ALL_13.items():
            r = sub_region(self.BASE, 2, 2, region)
            assert r.offsets == offsets and r.shape == shape, region

    def test_composition_grid_core_region(self):
        # double application: grid -> CENTER -> TOP of core
        core = sub_region(self.BASE, 2, 2, Region.CENTER)
        top_of_core = sub_region(core, 2, 2, Region.TOP)
        assert top_of_core.offsets == (2, 4)
        assert top_of_core.shape == (2, 24)

    def test_asymmetric_halo(self):
        r = sub_region(self.BASE, 1, 3, Region.BOTTOM_LEFT)
        assert r.offsets == (31, 0) and r.shape == (1, 3)

    def test_halo_swallows_base(self):
        with pytest.raises(ValueError):
            sub_region(SubarraySpec((0, 0), (4, 4)), 2, 2, Region.CENTER)


class TestTileLayout:
    def test_for_stencil(self):
        lay = TileLayout.for_stencil(16, 16, 5, 5)
        assert (lay.halo_y, lay.halo_x) == (2, 2)
        assert lay.padded_shape == (20, 20)
        assert lay.core.offsets == (2, 2) and lay.core.shape == (16, 16)

    def test_send_recv_sizes_match(self):
        lay = TileLayout(8, 12, 2, 3)
        for d in ALL_DIRECTIONS:
            # my send strip toward d must fit the receiver's opposite halo
            assert lay.send_region(d).shape == lay.halo_region(d.opposite).shape

    def test_border_partition_tiles_border(self):
        lay = TileLayout(6, 7, 2, 1)
        cover = np.zeros(lay.padded_shape, dtype=int)
        for d in ALL_DIRECTIONS:
            r = lay.halo_region(d)
            cover[
                r.offsets[0] : r.offsets[0] + r.shape[0],
                r.offsets[1] : r.offsets[1] + r.shape[1],
            ] += 1
        core = lay.core
        cover[
            core.offsets[0] : core.offsets[0] + core.shape[0],
            core.offsets[1] : core.offsets[1] + core.shape[1],
        ] += 1
        np.testing.assert_array_equal(cover, np.ones_like(cover))

    def test_validation(self):
        with pytest.raises(ValueError):
            TileLayout(0, 4, 1, 1)
        with pytest.raises(ValueError):
            TileLayout(4, 4, 5, 1)  # halo deeper than core


def _exchange_on_mesh(neighbors=8, periodic=True, init_halo=-1.0):
    """Run one live exchange on a 2x4 CPU mesh, tiles = rank ids."""
    mesh = make_mesh_2d((2, 4))
    topo = CartTopology((2, 4), (periodic, periodic))
    lay = TileLayout.for_stencil(4, 4, 3, 3)  # halo 1
    spec = HaloSpec(layout=lay, topology=topo, neighbors=neighbors)

    def body(x):
        tile = x[0, 0]
        return halo_exchange(tile, spec)[None, None]

    f = run_spmd(
        mesh, body, P("row", "col", None, None), P("row", "col", None, None)
    )
    tiles = np.full((2, 4) + lay.padded_shape, init_halo, dtype=np.float32)
    for r in range(2):
        for c in range(4):
            tiles[r, c, 1:-1, 1:-1] = r * 4 + c
    return np.asarray(f(jnp.asarray(tiles))), topo, lay, spec


class TestHaloExchangeLive:
    def test_golden_semantics_periodic(self):
        # the sample-output oracle on a 2x4 grid: every halo piece holds
        # the periodic neighbor's rank id
        out, topo, lay, spec = _exchange_on_mesh()
        for rank in topo.ranks():
            r, c = topo.coords(rank)
            tile = out[r, c]
            for d in ALL_DIRECTIONS:
                region = lay.halo_region(d)
                block = tile[
                    region.offsets[0] : region.offsets[0] + region.shape[0],
                    region.offsets[1] : region.offsets[1] + region.shape[1],
                ]
                expected = topo.neighbor(rank, d)
                assert (block == expected).all(), (rank, d, block)

    def test_core_untouched(self):
        out, topo, lay, _ = _exchange_on_mesh()
        for rank in topo.ranks():
            r, c = topo.coords(rank)
            core = out[r, c, 1:-1, 1:-1]
            assert (core == rank).all()

    def test_open_boundary_keeps_initial_halo(self):
        out, topo, lay, _ = _exchange_on_mesh(periodic=False, init_halo=-1.0)
        # rank 0 sits in the top-left corner: TOP/LEFT/diagonal halos have
        # no sender and must keep the -1 fill (MPI_PROC_NULL semantics)
        tile = out[0, 0]
        for d in (Direction.TOP, Direction.LEFT, Direction.TOP_LEFT,
                  Direction.TOP_RIGHT, Direction.BOTTOM_LEFT):
            region = lay.halo_region(d)
            block = tile[
                region.offsets[0] : region.offsets[0] + region.shape[0],
                region.offsets[1] : region.offsets[1] + region.shape[1],
            ]
            assert (block == -1.0).all(), d
        # while the interior-facing halos are filled
        right = lay.halo_region(Direction.RIGHT)
        assert (
            tile[right.offsets[0] : right.offsets[0] + right.shape[0],
                 right.offsets[1] : right.offsets[1] + right.shape[1]] == 1
        ).all()

    def test_four_neighbor_mode(self):
        out, topo, lay, _ = _exchange_on_mesh(neighbors=4)
        tile = out[0, 0]
        top = lay.halo_region(Direction.TOP)
        assert (
            tile[top.offsets[0] : top.offsets[0] + top.shape[0],
                 top.offsets[1] : top.offsets[1] + top.shape[1]]
            == topo.neighbor(0, Direction.TOP)
        ).all()
        # corners not exchanged in 4-neighbor mode
        tl = lay.halo_region(Direction.TOP_LEFT)
        assert (
            tile[tl.offsets[0] : tl.offsets[0] + tl.shape[0],
                 tl.offsets[1] : tl.offsets[1] + tl.shape[1]] == -1.0
        ).all()

    def test_miswiring_ablation_caught(self):
        # NO_SYNC-style negative test: wire the plan with the direction
        # tables NOT mirrored (send toward d landing in halo d) — the
        # golden oracle must reject it. Proves the oracle detects
        # topology miswiring, the class of bug the reference demos.
        mesh = make_mesh_2d((2, 4))
        topo = CartTopology((2, 4), (True, True))
        lay = TileLayout.for_stencil(4, 4, 3, 3)
        spec = HaloSpec(layout=lay, topology=topo)

        def miswired(tile):
            from jax import lax as _lax
            out = tile
            for t in spec.plan():
                payload = t.send.region(tile)
                # BUG under test: permutation for d instead of opposite(d)
                wrong = tuple(topo.send_permutation(t.direction))
                arrived = _lax.ppermute(payload, spec.axes, list(wrong))
                out = _lax.dynamic_update_slice(out, arrived, t.recv.offsets)
            return out

        f = run_spmd(
            mesh,
            lambda x: miswired(x[0, 0])[None, None],
            P("row", "col", None, None),
            P("row", "col", None, None),
        )
        tiles = np.full((2, 4) + lay.padded_shape, -1.0, dtype=np.float32)
        for r in range(2):
            for c in range(4):
                tiles[r, c, 1:-1, 1:-1] = r * 4 + c
        out = np.asarray(f(jnp.asarray(tiles)))
        # check LEFT: on 4 columns, +1 and -1 shifts differ (on the 2-row
        # axis the miswiring is invisible — shift ±1 mod 2 coincide)
        left = lay.halo_region(Direction.LEFT)
        block = out[0, 0][
            left.offsets[0] : left.offsets[0] + left.shape[0],
            left.offsets[1] : left.offsets[1] + left.shape[1],
        ]
        assert not (block == topo.neighbor(0, Direction.LEFT)).all()


@pytest.mark.skipif(not REF_SAMPLES.exists(), reason="reference not mounted")
class TestGoldenFiles:
    """Cross-check against the reference's checked-in 3x3 golden dumps:
    parse each rank's post-exchange 20x20 array and assert every halo piece
    equals the neighbor id OUR topology + region geometry predict. Pure
    host logic — validates the same math the live 2x4 test runs, against
    the reference's actual recorded output."""

    LAYOUT = TileLayout.for_stencil(16, 16, 5, 5)
    TOPO = CartTopology((3, 3), (True, True))

    @staticmethod
    def _parse(path):
        text = path.read_text()
        rank = int(re.search(r"Rank:\s+(\d+)", text).group(1))
        after = text.split("Array after exchange")[1]
        rows = []
        for line in after.strip().splitlines():
            vals = line.split()
            if len(vals) == 20:
                rows.append([int(v) for v in vals])
        assert len(rows) == 20, path
        return rank, np.array(rows)

    def test_all_nine_ranks(self):
        files = [p for p in REF_SAMPLES.iterdir() if re.fullmatch(r"\d_\d", p.name)]
        assert len(files) == 9
        for path in files:
            rank, arr = self._parse(path)
            core = self.LAYOUT.core
            assert (
                arr[core.offsets[0] : core.offsets[0] + core.shape[0],
                    core.offsets[1] : core.offsets[1] + core.shape[1]] == rank
            ).all()
            for d in ALL_DIRECTIONS:
                region = self.LAYOUT.halo_region(d)
                block = arr[
                    region.offsets[0] : region.offsets[0] + region.shape[0],
                    region.offsets[1] : region.offsets[1] + region.shape[1],
                ]
                expected = self.TOPO.neighbor(rank, d)
                assert (block == expected).all(), (path.name, d)


class TestStencilCompute:
    def test_five_point_matches_numpy(self):
        lay = TileLayout(4, 4, 1, 1)
        rng = np.random.default_rng(2)
        tile = rng.standard_normal(lay.padded_shape).astype(np.float32)
        out = np.asarray(five_point(jnp.asarray(tile), lay))
        expect = tile.copy()
        expect[1:-1, 1:-1] = 0.25 * (
            tile[:-2, 1:-1] + tile[2:, 1:-1] + tile[1:-1, :-2] + tile[1:-1, 2:]
        )
        np.testing.assert_allclose(out, expect, rtol=1e-6)

    def test_distributed_matches_global_oracle(self):
        # Dual-backend oracle at distributed scale: K steps on a 2x4
        # decomposition == K steps on the undecomposed periodic grid.
        R, C, TH, TW, K = 2, 4, 4, 4, 3
        mesh = make_mesh_2d((R, C))
        topo = CartTopology((R, C), (True, True))
        lay = TileLayout(TH, TW, 1, 1)
        spec = HaloSpec(layout=lay, topology=topo)

        rng = np.random.default_rng(3)
        world = rng.standard_normal((R * TH, C * TW)).astype(np.float32)

        tiles = np.zeros((R, C) + lay.padded_shape, dtype=np.float32)
        for r in range(R):
            for c in range(C):
                tiles[r, c, 1:-1, 1:-1] = world[
                    r * TH : (r + 1) * TH, c * TW : (c + 1) * TW
                ]

        f = run_spmd(
            mesh,
            lambda x: run_stencil(x[0, 0], spec, steps=K)[None, None],
            P("row", "col", None, None),
            P("row", "col", None, None),
        )
        out = np.asarray(f(jnp.asarray(tiles)))

        expect = world
        for _ in range(K):
            expect = 0.25 * (
                np.roll(expect, 1, 0) + np.roll(expect, -1, 0)
                + np.roll(expect, 1, 1) + np.roll(expect, -1, 1)
            )

        got = np.zeros_like(world)
        for r in range(R):
            for c in range(C):
                got[r * TH : (r + 1) * TH, c * TW : (c + 1) * TW] = out[
                    r, c, 1:-1, 1:-1
                ]
        np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)


class TestOverlapImpl:
    """The async-halo variant must agree exactly with the plain step."""

    @pytest.mark.parametrize("steps", [1, 3])
    def test_overlap_matches_xla(self, steps):
        mesh = make_mesh_2d((2, 4))
        topo = CartTopology((2, 4), (True, True))
        lay = TileLayout(6, 5, 1, 1)
        spec = HaloSpec(layout=lay, topology=topo)
        rng = np.random.default_rng(11)
        tiles = jnp.asarray(
            rng.standard_normal((2, 4) + lay.padded_shape).astype(np.float32)
        )
        outs = {}
        for impl in ("xla", "overlap"):
            f = run_spmd(
                mesh,
                lambda x, impl=impl: run_stencil(x[0, 0], spec, steps, impl=impl)[None, None],
                P("row", "col", None, None),
                P("row", "col", None, None),
            )
            outs[impl] = np.asarray(f(tiles))
        np.testing.assert_allclose(outs["xla"], outs["overlap"], rtol=1e-6)

    def test_tiny_core_falls_back(self):
        # 2x2 core has no interior: the overlap path must still be correct
        mesh = make_mesh_2d((2, 4))
        topo = CartTopology((2, 4), (True, True))
        lay = TileLayout(2, 2, 1, 1)
        spec = HaloSpec(layout=lay, topology=topo)
        rng = np.random.default_rng(12)
        tiles = jnp.asarray(
            rng.standard_normal((2, 4) + lay.padded_shape).astype(np.float32)
        )
        outs = {}
        for impl in ("xla", "overlap"):
            f = run_spmd(
                mesh,
                lambda x, impl=impl: run_stencil(x[0, 0], spec, 1, impl=impl)[None, None],
                P("row", "col", None, None),
                P("row", "col", None, None),
            )
            outs[impl] = np.asarray(f(tiles))
        np.testing.assert_allclose(outs["xla"], outs["overlap"], rtol=1e-6)


class TestDeepImpl:
    """The communication-avoiding trapezoid scheme must compute the exact
    same Jacobi trajectory as the one-exchange-per-step path — the core
    after K steps is identical; only the exchange cadence differs."""

    @pytest.mark.parametrize("depth,steps", [(2, 4), (2, 5), (3, 3), (3, 7)])
    @pytest.mark.parametrize("deep_impl", ["xla", "pallas"])
    def test_deep_matches_plain_core(self, depth, steps, deep_impl):
        from tpuscratch.halo.stencil import run_stencil_deep

        from tpuscratch.halo.driver import decompose

        R, C, TH, TW = 2, 4, 6, 5
        mesh = make_mesh_2d((R, C))
        topo = CartTopology((R, C), (True, True))
        rng = np.random.default_rng(21)
        world = rng.standard_normal((R * TH, C * TW)).astype(np.float32)

        def tiles_for(lay):
            return jnp.asarray(decompose(world, topo, lay))

        lay1 = TileLayout(TH, TW, 1, 1)
        spec1 = HaloSpec(layout=lay1, topology=topo)
        plain = run_spmd(
            mesh,
            lambda x: run_stencil(x[0, 0], spec1, steps)[None, None],
            P("row", "col", None, None),
            P("row", "col", None, None),
        )
        out_plain = np.asarray(plain(tiles_for(lay1)))[:, :, 1:-1, 1:-1]

        layk = TileLayout(TH, TW, depth, depth)
        speck = HaloSpec(layout=layk, topology=topo)
        deep = run_spmd(
            mesh,
            lambda x: run_stencil_deep(x[0, 0], speck, steps, impl=deep_impl)[None, None],
            P("row", "col", None, None),
            P("row", "col", None, None),
        )
        k = depth
        out_deep = np.asarray(deep(tiles_for(layk)))[:, :, k:-k, k:-k]
        np.testing.assert_allclose(out_deep, out_plain, rtol=1e-5, atol=1e-6)

    def test_deep_pallas_rejects_open_boundary(self):
        # the in-kernel trapezoid stays periodic-only; the error names
        # the open-boundary-aware xla fallback
        from tpuscratch.halo.stencil import run_stencil_deep

        topo = CartTopology((2, 4), (True, False))
        lay = TileLayout(4, 4, 2, 2)
        spec = HaloSpec(layout=lay, topology=topo)
        with pytest.raises(ValueError, match="periodic-only"):
            run_stencil_deep(jnp.zeros(lay.padded_shape), spec, 4,
                             impl="pallas")

    @pytest.mark.parametrize("periodic", [(False, False), (True, False),
                                          (False, True)])
    @pytest.mark.parametrize("depth,steps", [(2, 4), (2, 5), (3, 7)])
    def test_deep_open_boundary_matches_plain(self, periodic, depth, steps):
        # open edges keep MPI_PROC_NULL semantics (ghosts pinned at
        # zero every substep): the trapezoid trajectory must equal the
        # one-exchange-per-step path on the same open topology
        from tpuscratch.halo.driver import decompose
        from tpuscratch.halo.stencil import run_stencil_deep

        R, C, TH, TW = 2, 4, 6, 5
        mesh = make_mesh_2d((R, C))
        topo = CartTopology((R, C), periodic)
        rng = np.random.default_rng(23)
        world = rng.standard_normal((R * TH, C * TW)).astype(np.float32)

        def tiles_for(lay):
            return jnp.asarray(decompose(world, topo, lay))

        lay1 = TileLayout(TH, TW, 1, 1)
        spec1 = HaloSpec(layout=lay1, topology=topo)
        plain = run_spmd(
            mesh,
            lambda x: run_stencil(x[0, 0], spec1, steps)[None, None],
            P("row", "col", None, None),
            P("row", "col", None, None),
        )
        out_plain = np.asarray(plain(tiles_for(lay1)))[:, :, 1:-1, 1:-1]

        layk = TileLayout(TH, TW, depth, depth)
        speck = HaloSpec(layout=layk, topology=topo)
        deep = run_spmd(
            mesh,
            lambda x: run_stencil_deep(x[0, 0], speck, steps)[None, None],
            P("row", "col", None, None),
            P("row", "col", None, None),
        )
        k = depth
        out_deep = np.asarray(deep(tiles_for(layk)))[:, :, k:-k, k:-k]
        np.testing.assert_allclose(out_deep, out_plain, rtol=1e-5, atol=1e-6)

    def test_deep_rejects_asymmetric_halo(self):
        from tpuscratch.halo.stencil import run_stencil_deep

        topo = CartTopology((2, 4), (True, True))
        lay = TileLayout(4, 4, 2, 1)
        spec = HaloSpec(layout=lay, topology=topo)
        with pytest.raises(ValueError, match="square"):
            run_stencil_deep(jnp.zeros(lay.padded_shape), spec, 4)

    def test_single_device_deep_matches_roll_oracle(self):
        # 1x1 periodic mesh: deep == plain == numpy roll stencil.
        from tpuscratch.halo.driver import distributed_stencil

        rng = np.random.default_rng(22)
        world = rng.standard_normal((16, 16)).astype(np.float32)
        mesh = make_mesh_2d((1, 1))
        got = distributed_stencil(world, steps=4, mesh=mesh, halo=(4, 4), impl="deep")
        expect = world
        for _ in range(4):
            expect = 0.25 * (
                np.roll(expect, 1, 0) + np.roll(expect, -1, 0)
                + np.roll(expect, 1, 1) + np.roll(expect, -1, 1)
            )
        np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)

    def test_banded_kernel_matches_single_block(self):
        # force the Element-indexed band grid (the path the 1024^2 bench
        # exercises) with a tiny VMEM budget and compare against the
        # single-block kernel and the pure-jnp pyramid.
        from tpuscratch.halo.stencil import shrink_step
        from tpuscratch.ops.stencil_kernel import deep_trapezoid_pallas

        lay = TileLayout(32, 24, 3, 3)
        rng = np.random.default_rng(31)
        t = jnp.asarray(rng.standard_normal(lay.padded_shape).astype(np.float32))
        one_block = deep_trapezoid_pallas(t, lay, 3)
        banded = deep_trapezoid_pallas(t, lay, 3, budget_bytes=(8 + 6) * 30 * 4)
        a = t
        for _ in range(3):
            a = shrink_step(a, (0.25, 0.25, 0.25, 0.25, 0.0))
        np.testing.assert_allclose(np.asarray(banded), np.asarray(a), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(banded), np.asarray(one_block), rtol=1e-6)

    def test_banded_kernel_partial_substeps(self):
        # banded + substeps < halo: crop must recover exactly the core
        from tpuscratch.halo.stencil import shrink_step
        from tpuscratch.ops.stencil_kernel import deep_trapezoid_pallas

        lay = TileLayout(32, 24, 4, 4)
        rng = np.random.default_rng(32)
        t = jnp.asarray(rng.standard_normal(lay.padded_shape).astype(np.float32))
        got = deep_trapezoid_pallas(t, lay, 2, budget_bytes=(8 + 8) * 32 * 4)
        a = t
        for _ in range(2):
            a = shrink_step(a, (0.25, 0.25, 0.25, 0.25, 0.0))
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(a)[2:-2, 2:-2], rtol=1e-6
        )

    @pytest.mark.parametrize("deep_impl", ["xla", "pallas"])
    def test_depth_below_halo(self, deep_impl):
        # depth < halo is documented as valid: a halo-4 layout stepping
        # 2 steps per exchange must match the plain path too.
        from tpuscratch.halo.driver import decompose, distributed_stencil

        rng = np.random.default_rng(33)
        world = rng.standard_normal((16, 16)).astype(np.float32)
        mesh = make_mesh_2d((1, 1))
        topo = CartTopology((1, 1), (True, True))
        lay = TileLayout(16, 16, 4, 4)
        spec = HaloSpec(layout=lay, topology=topo, axes=tuple(mesh.axis_names))
        from tpuscratch.halo.stencil import run_stencil_deep

        f = run_spmd(
            mesh,
            lambda x: run_stencil_deep(
                x[0, 0], spec, 6, depth=2, impl=deep_impl
            )[None, None],
            P("row", "col", None, None),
            P("row", "col", None, None),
        )
        out = np.asarray(f(jnp.asarray(decompose(world, topo, lay))))[0, 0, 4:-4, 4:-4]
        expect = world
        for _ in range(6):
            expect = 0.25 * (
                np.roll(expect, 1, 0) + np.roll(expect, -1, 0)
                + np.roll(expect, 1, 1) + np.roll(expect, -1, 1)
            )
        np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-6)


class TestResidentStencil:
    """run_stencil_resident: the 1x1-mesh VMEM-resident fast path."""

    def test_matches_plain_path(self):
        from tpuscratch.halo.driver import distributed_stencil

        rng = np.random.default_rng(50)
        world = rng.standard_normal((32, 128)).astype(np.float32)
        mesh = make_mesh_2d((1, 1))
        got = distributed_stencil(world, steps=5, mesh=mesh, impl="resident")
        plain = distributed_stencil(world, steps=5, mesh=mesh, impl="xla")
        np.testing.assert_allclose(got, plain, rtol=1e-5, atol=1e-6)

    def test_rejects_multi_device_topology(self):
        from tpuscratch.halo.stencil import run_stencil_resident

        lay = TileLayout(8, 8, 1, 1)
        topo = CartTopology((2, 4), (True, True))
        spec = HaloSpec(layout=lay, topology=topo)
        with pytest.raises(ValueError, match="single-device"):
            run_stencil_resident(jnp.zeros(lay.padded_shape), spec, 2)

    def test_rejects_open_boundary(self):
        from tpuscratch.halo.stencil import run_stencil_resident

        lay = TileLayout(8, 8, 1, 1)
        topo = CartTopology((1, 1), (False, False))
        spec = HaloSpec(layout=lay, topology=topo)
        with pytest.raises(ValueError, match="periodic"):
            run_stencil_resident(jnp.zeros(lay.padded_shape), spec, 2)


class TestDmaImpl:
    """ops.halo_dma.run_stencil_dma: the double-buffered remote-DMA halo
    kernel must compute the exact Jacobi trajectory of the plain
    exchange-then-compute path on every mesh shape, including the
    degenerate self-wrap axes (where its channels become local copies).

    Step counts cover every branch of the static schedule: inline head
    (1..4), head+epilogue (5, 6), head+remainder+epilogue (7), and
    head+pairs+epilogue (12)."""

    @pytest.mark.parametrize("dims", [(2, 4), (1, 4), (2, 1), (1, 1)])
    @pytest.mark.parametrize("steps", [1, 3, 5, 7, 12])
    def test_matches_plain_core(self, dims, steps):
        from tpuscratch.halo.driver import decompose
        from tpuscratch.ops.halo_dma import run_stencil_dma

        R, C = dims
        TH, TW = 4, 5
        mesh = make_mesh_2d((R, C))
        topo = CartTopology((R, C), (True, True))
        lay = TileLayout(TH, TW, 1, 1)
        spec = HaloSpec(layout=lay, topology=topo)
        rng = np.random.default_rng(61)
        world = rng.standard_normal((R * TH, C * TW)).astype(np.float32)
        tiles = jnp.asarray(decompose(world, topo, lay))

        outs = {}
        for name, fn in (
            ("xla", lambda t: run_stencil(t, spec, steps)),
            ("dma", lambda t: run_stencil_dma(t, spec, steps)),
        ):
            f = run_spmd(
                mesh,
                lambda x, fn=fn: fn(x[0, 0])[None, None],
                P("row", "col", None, None),
                P("row", "col", None, None),
            )
            outs[name] = np.asarray(f(tiles))[:, :, 1:-1, 1:-1]
        np.testing.assert_allclose(outs["dma"], outs["xla"], rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("dims", [(2, 4), (1, 4), (2, 1), (1, 1)])
    @pytest.mark.parametrize("steps", [1, 3, 5])
    def test_hbm_banded_matches_plain_core(self, dims, steps):
        # the HBM-resident banded variant (round 4): core streams in
        # row bands, strips still on the DMA engine, one invocation per
        # step with entry-barrier ordering; column stages carried
        # between steps
        from tpuscratch.halo.driver import decompose
        from tpuscratch.ops.halo_dma import run_stencil_dma_hbm

        R, C = dims
        # TH=32 with band=8 gives nb=4: the steady-state branches (slot
        # repost under compute, b>=2 write waits, interior carry+next
        # rows) all execute — nb=2 alone would leave them untested
        TH, TW = 32, 8
        mesh = make_mesh_2d((R, C))
        topo = CartTopology((R, C), (True, True))
        lay = TileLayout(TH, TW, 1, 1)
        spec = HaloSpec(layout=lay, topology=topo)
        rng = np.random.default_rng(63)
        world = rng.standard_normal((R * TH, C * TW)).astype(np.float32)
        tiles = jnp.asarray(decompose(world, topo, lay))

        outs = {}
        for name, fn in (
            ("xla", lambda t: run_stencil(t, spec, steps)),
            ("hbm", lambda t: run_stencil_dma_hbm(t, spec, steps, band=8)),
        ):
            f = run_spmd(
                mesh,
                lambda x, fn=fn: fn(x[0, 0])[None, None],
                P("row", "col", None, None),
                P("row", "col", None, None),
            )
            outs[name] = np.asarray(f(tiles))[:, :, 1:-1, 1:-1]
        np.testing.assert_allclose(outs["hbm"], outs["xla"], rtol=1e-5,
                                   atol=1e-6)

    @pytest.mark.parametrize("dims", [(2, 4), (2, 1), (1, 1)])
    @pytest.mark.parametrize("steps", [1, 3])
    def test_hbm_banded_nine_point(self, dims, steps):
        # round 5: the corner values ride the row channels (columns
        # sent and received first, rows staged extended by the fresh
        # ghost columns' end cells) — VERDICT r4 missing #2
        from tpuscratch.halo.driver import decompose
        from tpuscratch.ops.halo_dma import run_stencil_dma_hbm

        R, C = dims
        TH, TW = 32, 8
        c9 = (0.15, 0.15, 0.1, 0.1, 0.05, 0.05, 0.08, 0.07, 0.25)
        mesh = make_mesh_2d((R, C))
        topo = CartTopology((R, C), (True, True))
        lay = TileLayout(TH, TW, 1, 1)
        spec = HaloSpec(layout=lay, topology=topo, neighbors=8)
        rng = np.random.default_rng(64)
        world = rng.standard_normal((R * TH, C * TW)).astype(np.float32)
        tiles = jnp.asarray(decompose(world, topo, lay))

        outs = {}
        for name, fn in (
            ("xla", lambda t: run_stencil(t, spec, steps, c9)),
            ("hbm", lambda t: run_stencil_dma_hbm(t, spec, steps, c9,
                                                  band=8)),
        ):
            f = run_spmd(
                mesh,
                lambda x, fn=fn: fn(x[0, 0])[None, None],
                P("row", "col", None, None),
                P("row", "col", None, None),
            )
            outs[name] = np.asarray(f(tiles))[:, :, 1:-1, 1:-1]
        np.testing.assert_allclose(outs["hbm"], outs["xla"], rtol=1e-5,
                                   atol=1e-6)

    def test_hbm_banded_rejects_open_and_bad_spec(self):
        from tpuscratch.ops.halo_dma import run_stencil_dma_hbm

        lay = TileLayout(8, 8, 1, 1)
        spec4 = HaloSpec(layout=lay,
                         topology=CartTopology((1, 1), (True, True)),
                         neighbors=4)
        # 9-point needs neighbors=8 (the trailing re-wrap fills corners)
        with pytest.raises(ValueError, match="neighbors=8"):
            run_stencil_dma_hbm(jnp.zeros(lay.padded_shape), spec4, 2,
                                coeffs=(0.1,) * 9)
        open_spec = HaloSpec(
            layout=lay, topology=CartTopology((1, 1), (True, False))
        )
        with pytest.raises(ValueError, match="periodic-only"):
            run_stencil_dma_hbm(jnp.zeros(lay.padded_shape), open_spec, 2)

    def test_halo_refreshed_like_exchange(self):
        # The returned padded tile carries a POST-run exchange (the
        # resident-impl convention): halo == exchange of the final cores.
        from tpuscratch.halo.driver import decompose
        from tpuscratch.ops.halo_dma import run_stencil_dma

        R, C, TH, TW = 2, 4, 4, 4
        mesh = make_mesh_2d((R, C))
        topo = CartTopology((R, C), (True, True))
        lay = TileLayout(TH, TW, 1, 1)
        spec = HaloSpec(layout=lay, topology=topo)
        rng = np.random.default_rng(62)
        world = rng.standard_normal((R * TH, C * TW)).astype(np.float32)
        tiles = jnp.asarray(decompose(world, topo, lay))

        f = run_spmd(
            mesh,
            lambda x: run_stencil_dma(x[0, 0], spec, 3)[None, None],
            P("row", "col", None, None),
            P("row", "col", None, None),
        )
        out = np.asarray(f(tiles))
        g = run_spmd(
            mesh,
            lambda x: halo_exchange(x[0, 0], spec)[None, None],
            P("row", "col", None, None),
            P("row", "col", None, None),
        )
        refreshed = np.asarray(g(jnp.asarray(out)))
        np.testing.assert_allclose(out, refreshed, rtol=1e-6)

    def test_driver_dispatch(self):
        from tpuscratch.halo.driver import distributed_stencil

        rng = np.random.default_rng(63)
        world = rng.standard_normal((8, 16)).astype(np.float32)
        mesh = make_mesh_2d((2, 4))
        got = distributed_stencil(world, steps=4, mesh=mesh, impl="dma")
        plain = distributed_stencil(world, steps=4, mesh=mesh, impl="xla")
        np.testing.assert_allclose(got, plain, rtol=1e-5, atol=1e-6)

    def test_rejects_open_boundary(self):
        from tpuscratch.ops.halo_dma import run_stencil_dma

        lay = TileLayout(4, 4, 1, 1)
        topo = CartTopology((2, 4), (True, False))
        spec = HaloSpec(layout=lay, topology=topo)
        with pytest.raises(ValueError, match="periodic"):
            run_stencil_dma(jnp.zeros(lay.padded_shape), spec, 2)

    def test_rejects_tiny_core(self):
        from tpuscratch.ops.halo_dma import run_stencil_dma

        lay = TileLayout(2, 8, 1, 1)
        topo = CartTopology((1, 1), (True, True))
        spec = HaloSpec(layout=lay, topology=topo)
        with pytest.raises(ValueError, match="too small"):
            run_stencil_dma(jnp.zeros(lay.padded_shape), spec, 2)


class TestDmaDeepImpl:
    """The generalized remote-DMA kernel: corner strips (9-point) and
    in-kernel depth-k folding must reproduce the plain exchange-compute
    trajectory bit-for-bit on every mesh shape, including self-wrap axes.

    Step/depth combos cover uneven fold tails (7 = 3+3+1), the
    steady-state pairs loop (12 rounds at depth 1), and odd depths
    (buffer parity alternates per round)."""

    C9 = (0.125, 0.125, 0.125, 0.125, 0.0625, 0.0625, 0.0625, 0.0625, 0.0)
    C5 = (0.25, 0.25, 0.25, 0.25, 0.0)

    @pytest.mark.parametrize("dims", [(2, 4), (1, 4), (1, 1)])
    @pytest.mark.parametrize("coeffs,depth,steps", [
        ("C9", 1, 3),    # corners ride the DMA, one substep per round
        ("C9", 1, 12),   # ...through the pairs loop
        ("C5", 2, 5),    # deep fold, uneven tail (2+2+1)
        ("C5", 3, 7),    # odd depth: buffer parity alternates per round
        ("C9", 2, 4),    # corners + fold together
    ])
    def test_matches_plain_core(self, dims, coeffs, depth, steps):
        from tpuscratch.halo.driver import decompose
        from tpuscratch.ops.halo_dma import run_stencil_dma

        c = getattr(self, coeffs)
        R, C = dims
        TH, TW = 4, 5
        mesh = make_mesh_2d((R, C))
        topo = CartTopology((R, C), (True, True))
        lay = TileLayout(TH, TW, 1, 1)
        spec = HaloSpec(layout=lay, topology=topo, neighbors=8)
        rng = np.random.default_rng(64)
        world = rng.standard_normal((R * TH, C * TW)).astype(np.float32)
        tiles = jnp.asarray(decompose(world, topo, lay))

        outs = {}
        for name, fn in (
            ("xla", lambda t: run_stencil(t, spec, steps, c)),
            ("dma", lambda t: run_stencil_dma(t, spec, steps, c, depth)),
        ):
            f = run_spmd(
                mesh,
                lambda x, fn=fn: fn(x[0, 0])[None, None],
                P("row", "col", None, None),
                P("row", "col", None, None),
            )
            outs[name] = np.asarray(f(tiles))[:, :, 1:-1, 1:-1]
        np.testing.assert_allclose(outs["dma"], outs["xla"], rtol=1e-5, atol=1e-6)

    def test_driver_dispatch_deep_and_nine_point(self):
        from tpuscratch.halo.driver import distributed_stencil

        rng = np.random.default_rng(65)
        world = rng.standard_normal((8, 16)).astype(np.float32)
        mesh = make_mesh_2d((2, 4))
        deep = distributed_stencil(world, steps=5, mesh=mesh, impl="dma-deep:2")
        plain = distributed_stencil(world, steps=5, mesh=mesh, impl="xla")
        np.testing.assert_allclose(deep, plain, rtol=1e-5, atol=1e-6)
        nine = distributed_stencil(
            world, steps=3, mesh=mesh, impl="dma", coeffs=self.C9
        )
        nine_ref = distributed_stencil(
            world, steps=3, mesh=mesh, impl="xla", coeffs=self.C9
        )
        np.testing.assert_allclose(nine, nine_ref, rtol=1e-5, atol=1e-6)

    def test_rejects_nine_point_without_corner_spec(self):
        from tpuscratch.ops.halo_dma import run_stencil_dma

        lay = TileLayout(4, 4, 1, 1)
        topo = CartTopology((2, 4), (True, True))
        spec = HaloSpec(layout=lay, topology=topo, neighbors=4)
        with pytest.raises(ValueError, match="neighbors=8"):
            run_stencil_dma(jnp.zeros(lay.padded_shape), spec, 2, self.C9)

    def test_rejects_depth_beyond_core(self):
        from tpuscratch.ops.halo_dma import run_stencil_dma

        lay = TileLayout(4, 4, 1, 1)
        topo = CartTopology((1, 1), (True, True))
        spec = HaloSpec(layout=lay, topology=topo)
        with pytest.raises(ValueError, match="too small"):
            run_stencil_dma(jnp.zeros(lay.padded_shape), spec, 8, depth=6)


class TestPlanNativeParity:
    """HaloSpec.plan() must be byte-identical whichever planner built it —
    the native fast path is an accelerator, never a semantic fork."""

    @pytest.mark.parametrize("dims,periodic", [
        ((2, 4), (True, True)),
        ((3, 3), (True, False)),
        ((1, 4), (False, False)),
    ])
    @pytest.mark.parametrize("neighbors", [4, 8])
    def test_native_and_python_plans_equal(self, dims, periodic, neighbors):
        import tpuscratch.native as native
        from tpuscratch.halo import exchange

        if not native.available():
            pytest.skip("native library not built")
        spec = HaloSpec(
            layout=TileLayout(8, 6, 2, 1),
            topology=CartTopology(dims, periodic),
            neighbors=neighbors,
        )
        exchange._cached_plan.cache_clear()
        native_plan = spec.plan()
        exchange._cached_plan.cache_clear()
        orig = native.available
        native.available = lambda: False
        try:
            python_plan = spec.plan()
        finally:
            native.available = orig
            exchange._cached_plan.cache_clear()
        assert native_plan == python_plan

    def test_plan_is_cached(self):
        spec = HaloSpec(
            layout=TileLayout(4, 4, 1, 1),
            topology=CartTopology((2, 4), (True, True)),
        )
        assert spec.plan() is spec.plan()


class TestBlockedImpl:
    """impl='blocked' (row-band kernel) must be reachable end-to-end from
    the driver dispatch and agree with the plain path."""

    def test_blocked_matches_xla(self):
        from tpuscratch.halo.driver import distributed_stencil

        rng = np.random.default_rng(71)
        world = rng.standard_normal((16, 16)).astype(np.float32)
        mesh = make_mesh_2d((2, 2))
        got = distributed_stencil(world, steps=3, mesh=mesh, impl="blocked")
        plain = distributed_stencil(world, steps=3, mesh=mesh, impl="xla")
        np.testing.assert_allclose(got, plain, rtol=1e-5, atol=1e-6)


class TestNinePoint:
    """The stencil shape that actually reads the corner ghosts."""

    def test_distributed_matches_roll_oracle(self, devices):
        from tpuscratch.halo.driver import distributed_stencil
        from tpuscratch.runtime.mesh import make_mesh_2d

        rng = np.random.default_rng(0)
        world = rng.standard_normal((16, 32)).astype(np.float32)
        c = (0.125, 0.125, 0.125, 0.125, 0.0625, 0.0625, 0.0625, 0.0625, 0.0)
        got = distributed_stencil(
            world, steps=3, mesh=make_mesh_2d((2, 4)), coeffs=c
        )
        expect = world.astype(np.float64)
        for _ in range(3):
            r = lambda dy, dx: np.roll(np.roll(expect, -dy, 0), -dx, 1)
            expect = (
                c[0] * r(-1, 0) + c[1] * r(1, 0) + c[2] * r(0, -1)
                + c[3] * r(0, 1) + c[4] * r(-1, -1) + c[5] * r(-1, 1)
                + c[6] * r(1, -1) + c[7] * r(1, 1) + c[8] * expect
            )
        assert np.allclose(got, expect, atol=1e-5)

    def test_pure_diagonal_reads_corner_ghosts(self, devices):
        """Weight ONLY the nw corner: the result is the diagonal shift,
        which crosses rank boundaries through the corner transfers."""
        from tpuscratch.halo.driver import distributed_stencil
        from tpuscratch.runtime.mesh import make_mesh_2d

        rng = np.random.default_rng(1)
        world = rng.standard_normal((8, 16)).astype(np.float32)
        c = (0.0, 0.0, 0.0, 0.0, 1.0, 0.0, 0.0, 0.0, 0.0)
        got = distributed_stencil(
            world, steps=1, mesh=make_mesh_2d((2, 4)), coeffs=c
        )
        expect = np.roll(np.roll(world, 1, 0), 1, 1)
        assert np.allclose(got, expect, atol=1e-6)

    def test_nine_point_rejects_non_xla_impls(self, devices):
        from tpuscratch.halo.driver import distributed_stencil
        from tpuscratch.runtime.mesh import make_mesh_2d

        c = (0.125,) * 4 + (0.0625,) * 4 + (0.0,)
        with pytest.raises(ValueError, match="9-point coeffs need"):
            distributed_stencil(
                np.zeros((8, 8), np.float32), steps=1,
                mesh=make_mesh_2d((1, 1)), coeffs=c, impl="pallas",
            )

    def test_nine_point_rejects_four_neighbor_spec(self, devices):
        import jax.numpy as jnp

        from tpuscratch.halo.stencil import stencil_step
        from tpuscratch.runtime.topology import CartTopology

        spec = HaloSpec(
            layout=TileLayout(4, 4, 1, 1),
            topology=CartTopology((1, 1), (True, True)),
            neighbors=4,
        )
        c = (0.125,) * 4 + (0.0625,) * 4 + (0.0,)
        with pytest.raises(ValueError, match="neighbors=8"):
            stencil_step(jnp.zeros((6, 6)), spec, coeffs=c)

    def test_nine_point_rejects_deep_and_resident_impls(self, devices):
        from tpuscratch.halo.driver import distributed_stencil
        from tpuscratch.runtime.mesh import make_mesh_2d

        c = (0.125,) * 4 + (0.0625,) * 4 + (0.0,)
        for impl in ("deep:2", "resident"):
            with pytest.raises(ValueError, match="9-point coeffs need"):
                distributed_stencil(
                    np.zeros((8, 8), np.float32), steps=2,
                    mesh=make_mesh_2d((1, 1)), coeffs=c, impl=impl,
                )


class TestVmapExchange:
    """The exchange's documented batching contract: vmap over it."""

    def test_vmapped_exchange_matches_per_field(self, devices):
        from tpuscratch.runtime.mesh import make_mesh_2d, topology_of

        mesh = make_mesh_2d((2, 4))
        topo = topology_of(mesh, periodic=True)
        lay = TileLayout(4, 4, 1, 1)
        spec = HaloSpec(layout=lay, topology=topo)
        rng = np.random.default_rng(0)
        fields = rng.standard_normal((3, 2, 4) + lay.padded_shape).astype(
            np.float32
        )  # 3 fields x mesh tiles

        prog = run_spmd(
            mesh,
            lambda t: jax.vmap(lambda a: halo_exchange(a, spec))(
                t[:, 0, 0]
            )[:, None, None],
            P(None, "row", "col", None, None),
            P(None, "row", "col", None, None),
        )
        got = np.asarray(prog(jnp.asarray(fields)))
        one = run_spmd(
            mesh,
            lambda t: halo_exchange(t[0, 0], spec)[None, None],
            P("row", "col", None, None),
            P("row", "col", None, None),
        )
        for i in range(3):
            expect = np.asarray(one(jnp.asarray(fields[i])))
            assert np.allclose(got[i], expect), i

    def test_wave_equation_leapfrog(self, devices):
        """Two coupled fields (u, u_prev) advanced by the leapfrog wave
        update over the halo machinery — a second PDE family beyond the
        Jacobi diffusion the drivers default to."""
        from tpuscratch.halo.driver import assemble, decompose
        from tpuscratch.halo.stencil import rebuild
        from tpuscratch.runtime.mesh import make_mesh_2d, topology_of

        mesh = make_mesh_2d((2, 2))
        topo = topology_of(mesh, periodic=True)
        lay = TileLayout(8, 8, 1, 1)
        spec = HaloSpec(layout=lay, topology=topo)
        c2, steps = 0.25, 5

        def lap(t):
            u = halo_exchange(t, spec)
            return (
                u[:-2, 1:-1] + u[2:, 1:-1] + u[1:-1, :-2] + u[1:-1, 2:]
                - 4.0 * u[1:-1, 1:-1]
            )

        def step_pair(tiles):
            u, up = tiles[0, 0, 0], tiles[1, 0, 0]

            def body(carry, _):
                u, up = carry
                new_core = (
                    2.0 * u[1:-1, 1:-1] - up[1:-1, 1:-1] + c2 * lap(u)
                )
                return (rebuild(u, new_core, lay), u), ()

            (u, up), _ = jax.lax.scan(body, (u, up), None, length=steps)
            return jnp.stack([u, up])[:, None, None]

        rng = np.random.default_rng(1)
        world = rng.standard_normal((16, 16)).astype(np.float32)
        tiles0 = decompose(world, topo, lay)
        pair = np.stack([tiles0, tiles0])  # u_prev = u (zero velocity)
        prog = run_spmd(
            mesh, step_pair,
            P(None, "row", "col", None, None),
            P(None, "row", "col", None, None),
        )
        out = np.asarray(prog(jnp.asarray(pair)))
        got = assemble(out[0], topo, lay)

        # numpy leapfrog oracle on the undecomposed grid
        u, up = world.astype(np.float64), world.astype(np.float64)
        for _ in range(steps):
            lap_np = (
                np.roll(u, 1, 0) + np.roll(u, -1, 0)
                + np.roll(u, 1, 1) + np.roll(u, -1, 1) - 4 * u
            )
            u, up = 2 * u - up + c2 * lap_np, u
        assert np.allclose(got, u, atol=1e-4)


class TestStream2D:
    """The row-banded streamed kernel (2D twin of the 3D stream:k):
    k substeps per manual-DMA pass over row-slab decompositions."""

    @pytest.mark.parametrize("dims", [(1, 1), (2, 1), (4, 1)])
    @pytest.mark.parametrize("impl,steps", [
        ("stream:2", 5), ("stream:4", 7), ("stream:8", 8),
    ])
    def test_stream2d_equals_plain(self, dims, impl, steps):
        from tpuscratch.halo.driver import distributed_stencil

        rng = np.random.default_rng(71)
        # 64 rows: the per-rank slab at 4x1 still fits depth 8
        # (band >= depth needs H_local >= 2 * depth)
        world = rng.standard_normal((64, 32)).astype(np.float32)
        mesh = make_mesh_2d(dims)
        a = distributed_stencil(world, steps, mesh=mesh, impl=impl)
        b = distributed_stencil(world, steps, mesh=mesh, impl="xla")
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("dims", [(1, 1), (2, 1)])
    def test_stream2d_nine_point(self, dims):
        # full-extent rows carry the diagonal neighbors implicitly
        from tpuscratch.halo.driver import distributed_stencil

        rng = np.random.default_rng(72)
        world = rng.standard_normal((32, 32)).astype(np.float32)
        c9 = (0.15, 0.15, 0.1, 0.1, 0.05, 0.05, 0.08, 0.07, 0.25)
        mesh = make_mesh_2d(dims)
        a = distributed_stencil(world, 5, mesh=mesh, impl="stream:2",
                                coeffs=c9)
        b = distributed_stencil(world, 5, mesh=mesh, impl="xla", coeffs=c9)
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)

    def test_stream2d_open_rows(self, ):
        # open row ends re-impose zero ghosts each folded substep;
        # columns stay periodic (the wrap-mode column axis)
        from tpuscratch.halo.driver import distributed_stencil

        rng = np.random.default_rng(73)
        world = rng.standard_normal((64, 32)).astype(np.float32)
        mesh = make_mesh_2d((4, 1))
        a = distributed_stencil(world, 5, mesh=mesh, impl="stream:2",
                                periodic=(False, True))
        b = distributed_stencil(world, 5, mesh=mesh, impl="xla",
                                periodic=(False, True))
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)

    # ---- ghost mode: distributed / open COLUMNS (round 5) -------------

    @pytest.mark.parametrize("dims", [(1, 2), (2, 2), (1, 4), (2, 4)])
    @pytest.mark.parametrize("impl,steps", [
        ("stream:2", 5), ("stream:4", 9),
    ])
    def test_stream2d_ghost_columns_equals_plain(self, dims, impl, steps):
        # distributed columns ride the (H+2k, k) ghost-column slabs
        # (x-neighbor edge columns + diagonal corner blocks)
        from tpuscratch.halo.driver import distributed_stencil

        rng = np.random.default_rng(75)
        world = rng.standard_normal((64, 64)).astype(np.float32)
        mesh = make_mesh_2d(dims)
        a = distributed_stencil(world, steps, mesh=mesh, impl=impl)
        b = distributed_stencil(world, steps, mesh=mesh, impl="xla")
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("dims", [(1, 2), (2, 2)])
    def test_stream2d_ghost_columns_nine_point(self, dims):
        # the corner blocks carry the diagonal neighbor values the
        # 9-point stencil actually reads
        from tpuscratch.halo.driver import distributed_stencil

        rng = np.random.default_rng(76)
        world = rng.standard_normal((64, 64)).astype(np.float32)
        c9 = (0.15, 0.15, 0.1, 0.1, 0.05, 0.05, 0.08, 0.07, 0.25)
        mesh = make_mesh_2d(dims)
        a = distributed_stencil(world, 5, mesh=mesh, impl="stream:2",
                                coeffs=c9)
        b = distributed_stencil(world, 5, mesh=mesh, impl="xla", coeffs=c9)
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("periodic", [
        (True, False), (False, False),
    ])
    def test_stream2d_ghost_columns_open(self, periodic):
        # open column (and row) ends: ppermute zero-fill supplies the
        # initial zero ghosts, per-substep flag zeroing keeps them zero
        from tpuscratch.halo.driver import distributed_stencil

        rng = np.random.default_rng(77)
        world = rng.standard_normal((64, 64)).astype(np.float32)
        mesh = make_mesh_2d((2, 2))
        a = distributed_stencil(world, 5, mesh=mesh, impl="stream:2",
                                periodic=periodic)
        b = distributed_stencil(world, 5, mesh=mesh, impl="xla",
                                periodic=periodic)
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)

    def test_stream2d_single_rank_open_columns(self):
        # 1x1 fully open: zero ghosts on every side, no ppermutes
        from tpuscratch.halo.driver import distributed_stencil

        rng = np.random.default_rng(78)
        world = rng.standard_normal((32, 32)).astype(np.float32)
        a = distributed_stencil(world, 4, mesh=make_mesh_2d((1, 1)),
                                impl="stream:2", periodic=False)
        b = distributed_stencil(world, 4, mesh=make_mesh_2d((1, 1)),
                                impl="xla", periodic=False)
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)

    def test_stream2d_rejects_unaligned_h(self):
        # H must be 8-aligned (chip DMA-window rule, BASELINE row 4) —
        # enforced on the CPU path too so interpret-mode tests catch
        # what silicon would reject
        from tpuscratch.halo.driver import distributed_stencil

        rng = np.random.default_rng(79)
        world = rng.standard_normal((12, 32)).astype(np.float32)
        with pytest.raises(ValueError, match="multiple of 8"):
            distributed_stencil(world, 2, mesh=make_mesh_2d((1, 1)),
                                impl="stream:2")
