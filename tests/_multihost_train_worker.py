"""Worker process for the multi-host TRAINING test (not a test module).

Two OS processes x two virtual CPU devices each = a 2x2 (dp x sp) global
mesh whose sp axis crosses the process boundary: the composed train step
(models/transformer — ring attention over sp, expert all_to_all over dp,
grad + SGD) runs with its collectives spanning hosts, the way a real
pod-slice training job does. Run:

    python tests/_multihost_train_worker.py <port> <rank> <nprocs>
"""

import sys

port, rank, nprocs = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])

from tpuscratch.runtime.hostenv import force_cpu_devices

force_cpu_devices(2)  # two local devices per process

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from tpuscratch.models import TransformerConfig, init_params
from tpuscratch.models.transformer import train_step
from tpuscratch.runtime.context import initialize
from tpuscratch.runtime.mesh import make_mesh

ctx = initialize(
    coordinator_address=f"127.0.0.1:{port}",
    num_processes=nprocs,
    process_id=rank,
)
assert ctx.global_device_count == 2 * nprocs, ctx

cfg = TransformerConfig(
    d_model=16, n_heads=2, n_experts=2, d_ff=32, capacity_factor=2.0
)
# the LEADING mesh axis spans processes (jax.devices() is process-major):
# make it sp, so the ring-attention ppermutes genuinely cross hosts
mesh = make_mesh((nprocs, 2), ("sp", "dp"))


def globalize(np_val, spec):
    return jax.make_array_from_callback(
        np_val.shape, NamedSharding(mesh, spec), lambda idx: np_val[idx]
    )


# identical data + params on every host (deterministic seeds), turned
# into GLOBAL arrays shard-by-shard — the multi-host input contract
rng = np.random.default_rng(0)
B, S = 4, 8 * nprocs  # batch over dp (intra-host), sequence over sp (cross)
x = globalize(
    rng.standard_normal((B, S, cfg.d_model)).astype(np.float32), P("dp", "sp")
)
y = globalize(
    rng.standard_normal((B, S, cfg.d_model)).astype(np.float32), P("dp", "sp")
)
params = jax.tree.map(
    lambda p: globalize(np.asarray(p, np.float32), P()), init_params(7, cfg)
)

step = train_step(mesh, cfg, lr=0.05)
losses = []
for _ in range(3):
    params, loss = step(params, x, y)
    losses.append(float(loss))  # replicated scalar: every host may read it
assert losses[-1] < losses[0], losses
print(
    f"WORKER{rank} TRAIN OK losses={losses[0]:.4f}->{losses[-1]:.4f} "
    f"devices={ctx.global_device_count}",
    flush=True,
)
