"""Worker for the kill/resume checkpoint tests (not a test module).

Runs ``checkpointed_stencil`` and dies mid-flight when asked:

- ``TPUSCRATCH_DIE_AFTER_SAVES=<n>`` hard-exits (os._exit — no cleanup,
  the deterministic stand-in for a scheduler SIGKILL) after the n-th
  checkpoint save completes;
- ``TPUSCRATCH_CHAOS_KILL=<stage>:<save_idx>`` SIGKILLs the process AT a
  named stage INSIDE ``checkpoint.save`` on the given save occurrence,
  through the ft chaos hook — the kill-mid-save matrix (every internal
  stage must leave a valid resumable step behind).  The ``write:``
  prefix (``write:<stage>:<idx>``) targets the ``ckpt/write`` site
  instead — the ASYNC background writer's stages;
- ``TPUSCRATCH_ASYNC_CKPT=1`` runs the driver with async checkpointing
  (snapshot-then-publish) instead of blocking saves.

Usage:

    python tests/_ckpt_worker.py <ckpt_dir> <steps> <save_every>
"""

import os
import sys

ckpt_dir, steps, save_every = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
die_after = int(os.environ.get("TPUSCRATCH_DIE_AFTER_SAVES", "0"))
chaos_kill = os.environ.get("TPUSCRATCH_CHAOS_KILL", "")
async_ckpt = bool(int(os.environ.get("TPUSCRATCH_ASYNC_CKPT", "0")))

from tpuscratch.runtime.hostenv import force_cpu_devices

force_cpu_devices(4)

import numpy as np

from tpuscratch.halo import driver
from tpuscratch.runtime import checkpoint
from tpuscratch.runtime.mesh import make_mesh_2d

if die_after:
    real_save = checkpoint.save
    calls = {"n": 0}

    def killing_save(*args, **kw):
        path = real_save(*args, **kw)
        calls["n"] += 1
        if calls["n"] >= die_after:
            print(f"WORKER dying after save #{calls['n']}", flush=True)
            os._exit(17)  # preemption: no cleanup, no further saves
        return path

    checkpoint.save = killing_save

chaos = None
if chaos_kill:
    from tpuscratch.ft.chaos import ChaosPlan, Fault

    site, spec = "ckpt/save", chaos_kill
    if spec.startswith("write:"):
        site, spec = "ckpt/write", spec[len("write:"):]
    stage, save_idx = spec.rsplit(":", 1)
    chaos = ChaosPlan(0, [
        Fault(site, stage=stage, at=(int(save_idx),), kind="kill"),
    ])

rng = np.random.default_rng(123)  # same world every invocation
world = rng.standard_normal((16, 16)).astype(np.float32)
out = driver.checkpointed_stencil(
    world, steps=steps, ckpt_dir=ckpt_dir, save_every=save_every,
    mesh=make_mesh_2d((2, 2)), chaos=chaos, async_ckpt=async_ckpt,
)
np.save(os.path.join(ckpt_dir, "result.npy"), out)
print(f"WORKER done at step {checkpoint.latest_step(ckpt_dir)}", flush=True)
