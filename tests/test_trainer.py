"""Checkpointed training driver: convergence + bit-exact resume."""

import jax
import numpy as np

from tpuscratch.models import TransformerConfig
from tpuscratch.models.trainer import train
from tpuscratch.runtime.mesh import make_mesh


def _mesh():
    return make_mesh((2, 2), ("dp", "sp"))


def _cfg():
    return TransformerConfig(
        d_model=16, n_heads=2, n_experts=2, d_ff=32, capacity_factor=2.0
    )


def test_training_reduces_loss(devices, tmp_path):
    _, rep = train(
        _mesh(), _cfg(), steps=20, ckpt_dir=str(tmp_path / "a"), save_every=5
    )
    assert rep.steps_run == 20 and rep.final_step == 20
    assert len(rep.losses) == 4
    assert rep.losses[-1] < rep.losses[0]


def test_resume_is_bit_identical(devices, tmp_path):
    mesh, cfg = _mesh(), _cfg()
    kw = dict(save_every=5, lr=0.05, seed=3)
    params_straight, _ = train(
        mesh, cfg, steps=20, ckpt_dir=str(tmp_path / "straight"), **kw
    )
    # interrupted run: first invocation stops at 10 (as if killed after
    # the step-10 save), second resumes from the checkpoint
    inter = str(tmp_path / "inter")
    train(mesh, cfg, steps=10, ckpt_dir=inter, **kw)
    params_resumed, rep = train(mesh, cfg, steps=20, ckpt_dir=inter, **kw)
    assert rep.steps_run == 10  # only the remaining half ran
    for a, b in zip(
        jax.tree.leaves(params_straight), jax.tree.leaves(params_resumed)
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_already_complete_run_is_a_no_op(devices, tmp_path):
    mesh, cfg = _mesh(), _cfg()
    d = str(tmp_path / "done")
    p1, _ = train(mesh, cfg, steps=10, ckpt_dir=d, save_every=5)
    p2, rep = train(mesh, cfg, steps=10, ckpt_dir=d, save_every=5)
    assert rep.steps_run == 0
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_mismatched_resume_rejected(devices, tmp_path):
    import pytest

    mesh, cfg = _mesh(), _cfg()
    d = str(tmp_path / "mm")
    train(mesh, cfg, steps=5, ckpt_dir=d, save_every=5, lr=0.05, seed=0)
    with pytest.raises(ValueError, match="resume mismatch"):
        train(mesh, cfg, steps=10, ckpt_dir=d, save_every=5, lr=0.1, seed=0)


def test_mismatched_shape_or_config_resume_rejected(devices, tmp_path):
    """batch/seq/architecture changes divert the data stream or the
    model itself — the bit-identical contract requires rejecting them
    just like lr/seed (ADVICE r2)."""
    import pytest

    mesh, cfg = _mesh(), _cfg()
    d = str(tmp_path / "mm2")
    train(mesh, cfg, steps=5, ckpt_dir=d, save_every=5, batch=4, seq=16)
    with pytest.raises(ValueError, match="resume mismatch"):
        train(mesh, cfg, steps=10, ckpt_dir=d, save_every=5, batch=8, seq=16)
    with pytest.raises(ValueError, match="resume mismatch"):
        train(mesh, cfg, steps=10, ckpt_dir=d, save_every=5, batch=4, seq=32)
    cfg2 = TransformerConfig(
        d_model=16, n_heads=4, n_experts=2, d_ff=32, capacity_factor=2.0
    )
    with pytest.raises(ValueError, match="resume mismatch"):
        train(mesh, cfg2, steps=10, ckpt_dir=d, save_every=5, batch=4, seq=16)


def test_adam_trains_and_resumes_bit_identical(devices, tmp_path):
    """Adam: moments shard like their params, descend the loss, and the
    FULL training state (params + moments + step count) round-trips
    through the checkpoint so resume is bit-identical."""
    mesh, cfg = _mesh(), _cfg()
    kw = dict(save_every=5, lr=0.005, seed=5, optimizer="adam")
    params_straight, rep = train(
        mesh, cfg, steps=20, ckpt_dir=str(tmp_path / "as"), **kw
    )
    assert rep.losses[-1] < rep.losses[0]
    inter = str(tmp_path / "ai")
    train(mesh, cfg, steps=10, ckpt_dir=inter, **kw)
    params_resumed, rep2 = train(mesh, cfg, steps=20, ckpt_dir=inter, **kw)
    assert rep2.steps_run == 10
    for a, b in zip(
        jax.tree.leaves(params_straight), jax.tree.leaves(params_resumed)
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_optimizer_mismatch_rejected(devices, tmp_path):
    import pytest

    mesh, cfg = _mesh(), _cfg()
    d = str(tmp_path / "om")
    train(mesh, cfg, steps=5, ckpt_dir=d, save_every=5, optimizer="adam",
          lr=0.005)
    with pytest.raises(ValueError, match="resume mismatch"):
        train(mesh, cfg, steps=10, ckpt_dir=d, save_every=5,
              optimizer="sgd", lr=0.005)
