"""Second observability layer: flight recorder + Chrome trace export,
goodput/MFU accounting, straggler detection, and the bench regression
gate (ISSUE 5)."""

import json
import math
import os
import subprocess
import sys
import threading
import time

import pytest

from tpuscratch.obs.trace import (
    FlightRecorder,
    StragglerReport,
    detect_stragglers,
    merge_chrome_traces,
    mesh_straggler,
    span_stamps,
    validate_chrome_trace,
)
from tpuscratch.obs import goodput, regress, report
from tpuscratch.runtime.mesh import make_mesh


@pytest.mark.trace
class TestFlightRecorder:
    def test_span_records_and_aggregates(self):
        rec = FlightRecorder()
        with rec.span("phase", step=1) as ev:
            time.sleep(0.002)
        assert ev.end is not None and ev.seconds >= 0.002
        evs = rec.events()
        assert len(evs) == 1 and evs[0].name == "phase"
        ph = rec.phase_totals()["phase"]
        assert ph.count == 1 and ph.seconds == pytest.approx(ev.seconds)
        assert ph.max_s == pytest.approx(ev.seconds)

    def test_span_survives_exception(self):
        rec = FlightRecorder()
        with pytest.raises(RuntimeError):
            with rec.span("boom"):
                raise RuntimeError("x")
        assert rec.phase_totals()["boom"].count == 1
        assert rec.events()[0].end is not None

    def test_instant(self):
        rec = FlightRecorder()
        rec.instant("mark", k=3)
        ev = rec.events()[0]
        assert ev.name == "mark" and ev.args == {"k": 3}

    def test_ring_bounded_but_totals_exact(self):
        rec = FlightRecorder(capacity=16)
        for i in range(100):
            rec.close_span(rec.open_span("p"))
        assert len(rec.events()) <= 16
        assert rec.dropped > 0
        # eviction loses detail, never accounting
        assert rec.phase_totals()["p"].count == 100

    def test_thread_safety(self):
        rec = FlightRecorder(capacity=64)

        def worker():
            for _ in range(200):
                rec.close_span(rec.open_span("t"))
                rec.instant("i")

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert rec.phase_totals()["t"].count == 800
        assert len(rec.events()) <= 64

    def test_span_sync_fences_device_values(self, devices):
        import jax
        import jax.numpy as jnp

        rec = FlightRecorder()
        y = jax.jit(lambda a: a * 2)(jnp.ones(1 << 12))
        with rec.span("fenced", sync=(y,)):
            pass
        assert rec.phase_totals()["fenced"].count == 1

    def test_span_stamps(self):
        rec = FlightRecorder()
        for _ in range(3):
            rec.close_span(rec.open_span("a"))
        rec.close_span(rec.open_span("b"))
        begins, ends = span_stamps(rec, "a")
        assert len(begins) == len(ends) == 3
        assert all(e >= b for b, e in zip(begins, ends))

    def test_close_open_spans_commits_partial_wall(self):
        """A span left open by a crashed invocation still counts its
        partial wall once close_open_spans runs (the failure-path
        filing); balanced spans are untouched."""
        rec = FlightRecorder()
        rec.close_span(rec.open_span("done"))
        rec.open_span("leaked")
        time.sleep(0.002)
        assert rec.close_open_spans() == 1
        ph = rec.phase_totals()
        assert ph["leaked"].count == 1 and ph["leaked"].seconds >= 0.002
        assert ph["done"].count == 1
        assert rec.close_open_spans() == 0  # idempotent

    def test_file_flight_data_on_raise(self, tmp_path):
        """file_flight_data closes in-flight spans and emits the
        trace/phase totals + buffered tail when the body raises — the
        mid-chunk-crash accounting the trainer and halo driver share."""
        from tpuscratch.obs.sink import Sink
        from tpuscratch.obs.trace import file_flight_data

        p = str(tmp_path / "crash.jsonl")
        rec = FlightRecorder()
        with pytest.raises(RuntimeError):
            with Sink(p, flush_every=1000) as sink:
                with file_flight_data(sink, rec):
                    rec.close_span(rec.open_span("train/chunk"))
                    rec.open_span("train/chunk")  # mid-chunk crash
                    time.sleep(0.002)
                    raise RuntimeError("boom")
        events = [json.loads(l) for l in open(p)]
        phases = [e for e in events if e["event"] == "trace/phase"]
        assert len(phases) == 1 and phases[0]["phase"] == "train/chunk"
        # BOTH spans counted — the in-flight one at its partial wall
        assert phases[0]["count"] == 2
        assert phases[0]["seconds"] >= 0.002


@pytest.mark.trace
class TestChromeTrace:
    @staticmethod
    def _recorder():
        rec = FlightRecorder()
        with rec.span("outer", step=1):
            with rec.span("inner"):
                time.sleep(0.001)
        rec.instant("mark")
        return rec

    def test_golden_schema(self):
        """Valid JSON, paired B/E events, monotonic ts — the golden
        check the acceptance criteria gate on."""
        trace = self._recorder().chrome_trace(pid=0, label="t")
        text = json.dumps(trace)          # serializable as-is
        assert json.loads(text) == trace  # and round-trips
        n = validate_chrome_trace(trace)
        assert n == 5  # outer B/E, inner B/E, one instant
        phs = [e["ph"] for e in trace["traceEvents"]]
        assert phs.count("B") == 2 and phs.count("E") == 2
        assert phs.count("i") == 1

    def test_nesting_order(self):
        """inner opens after outer's B and closes before outer's E."""
        trace = self._recorder().chrome_trace()
        seq = [(e["name"], e["ph"]) for e in trace["traceEvents"]
               if e["ph"] in ("B", "E")]
        assert seq == [("outer", "B"), ("inner", "B"),
                       ("inner", "E"), ("outer", "E")]

    def test_validator_rejects_mispaired(self):
        trace = self._recorder().chrome_trace()
        bad = dict(trace, traceEvents=[
            e for e in trace["traceEvents"]
            if not (e["ph"] == "E" and e["name"] == "inner")
        ])
        with pytest.raises(ValueError, match="mispaired|unclosed"):
            validate_chrome_trace(bad)

    def test_validator_rejects_nonmonotonic(self):
        trace = self._recorder().chrome_trace()
        evs = [dict(e) for e in trace["traceEvents"]]
        data = [e for e in evs if e["ph"] != "M"]
        data[-1]["ts"] = -1.0
        with pytest.raises(ValueError, match="non-monotonic"):
            validate_chrome_trace(dict(trace, traceEvents=evs))

    def test_merge_per_host_lanes(self):
        traces = {h: self._recorder().chrome_trace(pid=0) for h in (0, 1)}
        merged = merge_chrome_traces(traces)
        pids = {e["pid"] for e in merged["traceEvents"]}
        assert pids == {0, 1}
        # merged file still serializes
        json.dumps(merged)

    def test_equal_timestamp_nesting_exports_in_true_order(self):
        """A frozen clock makes every stamp tie: the op-seq tiebreak
        still exports B-outer before B-inner (and E-inner before
        E-outer), so the exporter can never produce a trace its own
        validator rejects."""
        t = [1.0]
        rec = FlightRecorder(clock=lambda: t[0])
        outer = rec.open_span("outer")
        inner = rec.open_span("inner")
        rec.close_span(inner)
        rec.close_span(outer)
        trace = rec.chrome_trace()
        validate_chrome_trace(trace)
        seq = [(e["name"], e["ph"]) for e in trace["traceEvents"]
               if e["ph"] in ("B", "E")]
        assert seq == [("outer", "B"), ("inner", "B"),
                       ("inner", "E"), ("outer", "E")]

    def test_open_span_not_exported(self):
        rec = FlightRecorder()
        ev = rec.open_span("open")
        rec.close_span(rec.open_span("closed"))
        # the still-open span is not in the ring (pushed at close), so
        # the export holds only complete pairs
        trace = rec.chrome_trace()
        names = {e["name"] for e in trace["traceEvents"]
                 if e["ph"] in ("B", "E")}
        assert names == {"closed"}
        rec.close_span(ev)


@pytest.mark.trace
class TestTimelineDelegation:
    def test_one_span_implementation(self):
        """Timeline.span is the recorder bracket: the same span lands in
        both the legacy list and the shared recorder's ring."""
        from tpuscratch.runtime.profiling import Timeline

        rec = FlightRecorder()
        tl = Timeline(rec)
        with tl.span("work"):
            time.sleep(0.001)
        assert tl.seconds("work") >= 0.001
        assert rec.phase_totals()["work"].count == 1
        sp = rec.events()[0]
        assert (sp.begin, sp.end) == (tl.spans[0].begin, tl.spans[0].end)

    def test_default_recorder_created(self):
        from tpuscratch.runtime.profiling import Timeline

        tl = Timeline()
        with tl.span("x"):
            pass
        assert tl.recorder.phase_totals()["x"].count == 1

    def test_exception_path_still_records(self):
        from tpuscratch.runtime.profiling import Timeline

        tl = Timeline()
        with pytest.raises(RuntimeError):
            with tl.span("bad"):
                raise RuntimeError("x")
        assert len(tl.spans) == 1
        assert tl.recorder.phase_totals()["bad"].count == 1


@pytest.mark.trace
class TestStraggler:
    def test_detect_pure(self):
        per_host = {"train/chunk": {0: 0.1, 1: 0.5, 2: 0.1},
                    "ckpt/save": {0: 0.01, 1: 0.01},
                    "solo": {0: 9.9}}
        reps = detect_stragglers(per_host, min_skew=1.2)
        assert [r.phase for r in reps] == ["train/chunk"]
        r = reps[0]
        assert r.slowest == 1 and r.fastest in (0, 2)
        assert r.skew == pytest.approx(5.0)
        assert "host 1 slowest" in r.summary()

    def test_skew_guards_zero(self):
        r = StragglerReport("p", 1, 0, 0.5, 0.0)
        assert r.skew == math.inf
        assert StragglerReport("p", 0, 0, 0.0, 0.0).skew == 1.0

    def test_mesh_straggler_fingers_seeded_slow_rank(self, devices):
        """The acceptance gate: a deliberate slow rank on a 2x2 CPU mesh
        is named, with its skew ratio, through mesh_reduce max/min."""
        mesh = make_mesh((2, 2), ("dp", "sp"))
        per_rank = [0.101, 0.100, 0.502, 0.099]  # rank 2 seeded slow
        r = mesh_straggler(mesh, "train/chunk", per_rank)
        assert r.slowest == 2 and r.fastest == 3
        assert r.max_s == pytest.approx(0.502, rel=1e-3)
        assert r.skew == pytest.approx(0.502 / 0.099, rel=1e-2)

    def test_report_stragglers_table(self, tmp_path):
        """trace/phase events from two hosts -> the stragglers table
        names the slow host; cumulative semantics (newest wins)."""
        p = str(tmp_path / "run.jsonl")
        events = [
            {"event": "run", "t": 0.0},
            # host 0 emits twice: the SECOND (cumulative) total wins
            {"event": "trace/phase", "t": 1.0, "phase": "train/chunk",
             "host": 0, "seconds": 0.05, "count": 1},
            {"event": "trace/phase", "t": 2.0, "phase": "train/chunk",
             "host": 0, "seconds": 0.10, "count": 2},
            {"event": "trace/phase", "t": 2.0, "phase": "train/chunk",
             "host": 1, "seconds": 0.40, "count": 2},
        ]
        with open(p, "w") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")
        summ = report.summarize(report.load_events([p]))
        rows = summ["stragglers"]
        assert len(rows) == 1
        assert rows[0]["slowest"] == 1 and rows[0]["fastest"] == 0
        assert rows[0]["skew"] == pytest.approx(4.0)
        table = report.format_table(summ)
        assert "stragglers" in table and "host 1 slowest" in table
        # trace/phase stays out of the per-event stat blocks
        assert "trace/phase" not in summ["events"]

    def test_distinct_recorders_in_one_file_add(self, tmp_path):
        """A sweep's per-engine recorders share one sink file: their
        trace/phase events carry distinct scopes, so one host's totals
        ADD instead of last-wins (the scoped-snapshot rule), while a
        duplicated artifact (same scope, two files) still dedups."""
        from tpuscratch.obs.trace import fold_phase_events

        events = [
            # engine A then engine B, same file, same host
            {"event": "trace/phase", "_file": "f", "host": 0,
             "scope": "rec-a", "phase": "serve/decode", "seconds": 0.3},
            {"event": "trace/phase", "_file": "f", "host": 0,
             "scope": "rec-b", "phase": "serve/decode", "seconds": 0.2},
            # the same rec-a totals loaded again from a copied file
            {"event": "trace/phase", "_file": "f2", "host": 0,
             "scope": "rec-a", "phase": "serve/decode", "seconds": 0.3},
        ]
        folded = fold_phase_events(events)
        assert folded["serve/decode"] == {0: pytest.approx(0.5)}

    @pytest.mark.slow
    def test_restart_recorders_do_not_last_win(self, devices, tmp_path):
        """supervise_train without an explicit recorder: each restarted
        train() flies a fresh recorder into ONE sink file; every
        invocation's chunks stay in the folded totals (the cheap fold
        semantics live in test_distinct_recorders_in_one_file_add)."""
        from tpuscratch.ft import ChaosPlan, Fault, supervise_train
        from tpuscratch.models.transformer import TransformerConfig
        from tpuscratch.obs.sink import Sink

        mesh = make_mesh((1, 1), ("dp", "sp"))
        cfg = TransformerConfig(d_model=16, n_heads=2, n_experts=2,
                                d_ff=32, n_layers=1)
        plan = ChaosPlan(0, [Fault("train/preempt", at=(2,),
                                   kind="preempt")])
        p = str(tmp_path / "sup.jsonl")
        with Sink(p) as s:
            supervise_train(mesh, cfg, 4, str(tmp_path / "ck"),
                            save_every=2, chaos=plan, sink=s, obs=s,
                            sleep=lambda d: None)
        from tpuscratch.obs.trace import fold_phase_events

        events = report.load_events([p])
        scopes = {e.get("scope") for e in events
                  if e["event"] == "trace/phase"}
        assert len(scopes) == 2  # one recorder per invocation
        folded = fold_phase_events(events)
        chunks = [e for e in events if e["event"] == "train/chunk"]
        total = sum(e["chunk_s"] for e in chunks)
        assert folded["train/chunk"][0] == pytest.approx(total, rel=0.01)

    def test_infinite_skew_exports_json_safe(self, tmp_path):
        """A 0.0-rounded fastest host must not leak ``Infinity`` into
        the --json artifact (non-standard JSON): skew exports as None
        and the table prints 'inf'."""
        p = str(tmp_path / "run.jsonl")
        events = [
            {"event": "trace/phase", "t": 1.0, "phase": "train/chunk",
             "host": 0, "seconds": 0.0, "count": 1},
            {"event": "trace/phase", "t": 1.0, "phase": "train/chunk",
             "host": 1, "seconds": 0.4, "count": 1},
        ]
        with open(p, "w") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")
        summ = report.summarize(report.load_events([p]))
        rows = summ["stragglers"]
        assert rows[0]["skew"] is None
        json.dumps(summ, allow_nan=False)  # strict-JSON clean
        assert "(skew inf)" in report.format_table(summ)

    def test_event_filter_suppresses_stragglers(self, tmp_path):
        """--event views must not smuggle the cross-stream skew table."""
        p = str(tmp_path / "run.jsonl")
        events = [
            {"event": "serve/tick", "t": 0.5, "tick_s": 0.01},
            {"event": "trace/phase", "t": 1.0, "phase": "train/chunk",
             "host": 0, "seconds": 0.1, "count": 1},
            {"event": "trace/phase", "t": 1.0, "phase": "train/chunk",
             "host": 1, "seconds": 0.4, "count": 1},
        ]
        with open(p, "w") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")
        loaded = report.load_events([p])
        assert "stragglers" in report.summarize(loaded)
        filtered = report.summarize(loaded, only_event="serve/tick")
        assert "stragglers" not in filtered
        # an EXPLICIT --event trace/phase request is not an empty view:
        # the raw events get their per-kind stat block
        raw = report.summarize(loaded, only_event="trace/phase")
        assert raw["events"]["trace/phase"]["count"] == 2

    def test_trainer_emits_trace_phase(self, devices, tmp_path):
        from tpuscratch.models.trainer import train
        from tpuscratch.models.transformer import TransformerConfig
        from tpuscratch.obs.sink import Sink

        mesh = make_mesh((1, 1), ("dp", "sp"))
        cfg = TransformerConfig(d_model=16, n_heads=2, n_experts=2,
                                d_ff=32, n_layers=1)
        p = str(tmp_path / "t.jsonl")
        with Sink(p) as s:
            train(mesh, cfg, steps=2, save_every=2,
                  ckpt_dir=str(tmp_path / "ck"), obs=s)
        phases = {e["phase"] for e in report.load_events([p])
                  if e["event"] == "trace/phase"}
        assert {"train/chunk", "ckpt/save"} <= phases


@pytest.mark.trace
class TestGoodput:
    @staticmethod
    def _canned_events():
        """An ft-heavy stream: chunks, saves, a rollback, a restart
        backoff — every duration placed so the intervals don't overlap."""
        return [
            {"event": "run", "t": 0.0},
            {"event": "train/config", "t": 0.1},
            # chunk 1 (3 steps), ends at 2.0, 1.9 s long, 1.0 s compile
            {"event": "train/chunk", "t": 2.0, "step": 3, "steps": 3,
             "tokens": 48, "chunk_s": 1.9, "compile_s": 1.0,
             "tokens_per_s": 25.0},
            {"event": "ckpt/save", "t": 2.2, "step": 3, "wall_s": 0.2},
            # a rolled-back chunk: 0.8 s of lost compute + restore
            {"event": "ft/guard", "t": 3.0, "step": 6, "skipped": 1},
            {"event": "ft/rollback", "t": 3.0, "from_step": 6,
             "to_step": 3, "lost_s": 0.8},
            # supervisor backoff after a preemption
            {"event": "ft/restart", "t": 3.5, "restart": 1,
             "backoff_s": 0.5},
            # replayed chunk commits, ends at 4.3
            {"event": "train/chunk", "t": 4.3, "step": 6, "steps": 3,
             "tokens": 48, "chunk_s": 0.7, "compile_s": 0.0,
             "tokens_per_s": 68.0},
            {"event": "ckpt/save", "t": 4.5, "step": 6, "wall_s": 0.2},
            {"event": "train/run", "t": 4.5, "steps_run": 6,
             "wall_s": 4.4},
        ]

    def test_canned_buckets_sum_exactly(self):
        gp = goodput.goodput_report(self._canned_events())
        assert gp.wall_s == pytest.approx(4.5)
        gp.check()
        b = gp.buckets
        assert b["step"] == pytest.approx(1.9 - 1.0 + 0.7)
        assert b["compile"] == pytest.approx(1.0)
        assert b["checkpoint"] == pytest.approx(0.4)
        assert b["rollback"] == pytest.approx(0.8)
        assert b["restart"] == pytest.approx(0.5)
        assert b["other"] == pytest.approx(4.5 - 1.6 - 1.0 - 0.4 - 0.8 - 0.5)
        assert gp.steps == 6 and gp.tokens == 96
        assert sum(b.values()) == pytest.approx(gp.wall_s, rel=1e-9)

    def test_mfu_from_flops(self):
        gp = goodput.goodput_report(
            self._canned_events(),
            flops_per_step=1e9, peak_flops_per_s=1e10,
        )
        # 6 steps x 1e9 over 4.5 s of wall at 1e10 peak
        assert gp.model_flops_per_s == pytest.approx(6e9 / 4.5)
        assert gp.mfu == pytest.approx(6e9 / 4.5 / 1e10)
        assert "MFU" in gp.summary()

    def test_flops_per_token_path(self):
        gp = goodput.goodput_report(self._canned_events(),
                                    flops_per_token=1e6,
                                    peak_flops_per_s=1e9)
        assert gp.mfu == pytest.approx(96e6 / 4.5 / 1e9)

    def test_overlapping_durations_clip(self):
        """Overhanging durations never push the sum past the wall."""
        events = [
            {"event": "run", "t": 0.0},
            {"event": "serve/tick", "t": 1.0, "tick_s": 0.9},
            {"event": "serve/tick", "t": 1.5, "tick_s": 0.9},  # overlaps
            {"event": "train/run", "t": 2.0},
        ]
        gp = goodput.goodput_report(events)
        gp.check()
        assert gp.buckets["step"] == pytest.approx(1.4)  # clipped

    def test_resumed_file_splits_sink_sessions(self):
        """A crashed run resumed by a NEW process appends to the same
        JSONL path with a reset sink clock (its own ``run`` header at
        t~0).  The sessions must be accounted as separate windows — one
        merged window would collapse the two clocks, shrink the wall,
        and let the sessions' intervals overlap-clip each other."""
        session1 = [
            {"event": "run", "t": 0.0, "_file": "a.jsonl"},
            {"event": "train/chunk", "t": 100.0, "steps": 3, "tokens": 48,
             "chunk_s": 90.0, "compile_s": 0.0, "_file": "a.jsonl"},
        ]
        session2 = [  # reopened after a SIGKILL: clock restarts
            {"event": "run", "t": 0.0, "_file": "a.jsonl"},
            {"event": "train/chunk", "t": 50.0, "steps": 3, "tokens": 48,
             "chunk_s": 40.0, "compile_s": 0.0, "_file": "a.jsonl"},
        ]
        gp = goodput.goodput_report(session1 + session2)
        gp.check()
        assert gp.wall_s == pytest.approx(150.0)   # 100 + 50, not 100
        assert gp.buckets["step"] == pytest.approx(130.0)  # 90 + 40
        assert gp.steps == 6
        # and the wall_s override refuses the multi-session ambiguity
        with pytest.raises(ValueError, match="single-session"):
            goodput.goodput_report(session1 + session2, wall_s=200.0)

    def test_halo_chunk_compile_carved(self):
        """halo/chunk carries compile_s like train/chunk: the fresh
        chunk's compile-dominated wall is badput, not goodput."""
        events = [
            {"event": "run", "t": 0.0},
            {"event": "halo/chunk", "t": 1.5, "wall_s": 1.4,
             "compile_s": 1.4},
            {"event": "halo/chunk", "t": 2.0, "wall_s": 0.4,
             "compile_s": 0.0},
            {"event": "halo/run", "t": 2.5},
        ]
        gp = goodput.goodput_report(events)
        gp.check()
        assert gp.buckets["compile"] == pytest.approx(1.4)
        assert gp.buckets["step"] == pytest.approx(0.4)

    def test_serve_compile_tick_booked_as_compile(self):
        """A serve/tick whose cumulative compile counters moved is a
        compile-dominated bracket; steady-state ticks stay goodput."""
        events = [
            {"event": "run", "t": 0.0},
            # first tick compiles prefill + decode
            {"event": "serve/tick", "t": 1.0, "tick_s": 0.9,
             "decode_compiles": 1, "prefill_compiles": 1},
            {"event": "serve/tick", "t": 1.4, "tick_s": 0.3,
             "decode_compiles": 1, "prefill_compiles": 1},
            # a fresh engine in the same file: counters RESET, recompile
            {"event": "serve/tick", "t": 2.4, "tick_s": 0.9,
             "decode_compiles": 0, "prefill_compiles": 1},
            {"event": "serve/tick", "t": 2.8, "tick_s": 0.3,
             "decode_compiles": 0, "prefill_compiles": 1},
            {"event": "serve/report", "t": 3.0},
        ]
        gp = goodput.goodput_report(events)
        gp.check()
        assert gp.buckets["compile"] == pytest.approx(1.8)
        assert gp.buckets["step"] == pytest.approx(0.6)

    def test_wall_override(self):
        gp = goodput.goodput_report(self._canned_events(), wall_s=5.0)
        assert gp.wall_s == pytest.approx(5.0)
        gp.check()

    def test_straggler_wait_carved_from_other(self):
        events = self._canned_events() + [
            {"event": "trace/phase", "t": 4.5, "phase": "train/chunk",
             "host": 0, "seconds": 0.5, "count": 2, "_file": "a"},
            {"event": "trace/phase", "t": 4.5, "phase": "train/chunk",
             "host": 1, "seconds": 0.4, "count": 2, "_file": "b"},
        ]
        gp = goodput.goodput_report(events)
        gp.check()
        assert gp.buckets["straggler_wait"] == pytest.approx(0.1)

    def test_same_host_two_files_is_not_a_straggler_pair(self):
        """One host writing two sink files (a sweep's two engines, a
        re-opened sink) folds to one host — no phantom straggler_wait."""
        events = self._canned_events() + [
            {"event": "trace/phase", "t": 4.5, "phase": "serve/decode",
             "host": 0, "seconds": 0.5, "count": 2, "_file": "a"},
            {"event": "trace/phase", "t": 4.5, "phase": "serve/decode",
             "host": 0, "seconds": 0.3, "count": 1, "_file": "b"},
        ]
        gp = goodput.goodput_report(events)
        gp.check()
        assert gp.buckets["straggler_wait"] == 0.0

    @pytest.mark.chaos
    def test_live_guarded_chaos_run_sums_to_wall(self, devices, tmp_path):
        """The acceptance gate: on a real guarded+chaos CPU run the
        buckets sum to the measured wall (the partition is exact by
        construction; +-1%% is the stated criterion) and the rollback /
        checkpoint badput is visible."""
        from tpuscratch.ft import ChaosPlan, Fault, GuardPolicy
        from tpuscratch.models.trainer import train
        from tpuscratch.models.transformer import TransformerConfig
        from tpuscratch.obs.sink import Sink

        mesh = make_mesh((1, 1), ("dp", "sp"))
        cfg = TransformerConfig(d_model=16, n_heads=2, n_experts=2,
                                d_ff=32, n_layers=1)
        p = str(tmp_path / "run.jsonl")
        plan = ChaosPlan(0, [Fault("train/grad", at=(4,), kind="nan")])
        with Sink(p) as sink:
            _, rep = train(
                mesh, cfg, steps=6, save_every=3, seed=3,
                ckpt_dir=str(tmp_path / "ck"), obs=sink, chaos=plan,
                guard=GuardPolicy(max_skips=0, max_rollbacks=1),
            )
        assert rep.rollbacks == 1
        gp = goodput.goodput_report(report.load_events([p]))
        total = sum(gp.buckets.values())
        assert abs(total - gp.wall_s) <= 0.01 * gp.wall_s
        assert gp.buckets["rollback"] > 0
        assert gp.buckets["checkpoint"] > 0
        assert gp.buckets["step"] > 0
        assert gp.steps == 6
        # the wall the report accounts is the event window, which sits
        # inside the run (sink opened before, flushed after)
        run_wall = [e for e in report.load_events([p])
                    if e["event"] == "train/run"][0]["wall_s"]
        assert gp.wall_s <= run_wall * 1.5 + 0.5


@pytest.mark.trace
class TestRegress:
    BASE = [
        {"config": 11, "metric": "train_tokens_per_s_float32",
         "value": 100000.0, "p50_s": 0.5, "platform": "cpu"},
        {"config": 12, "metric": "serve_decode_tokens_per_s",
         "value": 5000.0, "p50_s": 0.002, "p99_s": 0.004,
         "platform": "cpu"},
        {"config": 13, "metric": "zero_vs_replicated_dp4", "dp": 4,
         "grad_sync_bytes_zero": 12864.0, "grad_ratio": 0.5,
         "platform": "cpu"},
    ]

    @staticmethod
    def _write(tmp_path, name, rows):
        p = str(tmp_path / name)
        with open(p, "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
        return p

    def test_clean_pair_passes(self, tmp_path):
        base = regress.index_rows(self.BASE)
        new = regress.index_rows([
            dict(self.BASE[0], value=98000.0),          # -2%: in band
            dict(self.BASE[1], p99_s=0.0042),           # +5%: in band
            dict(self.BASE[2]),
        ])
        findings = regress.compare(base, new, noise=0.1)
        assert not regress.has_regression(findings)
        assert all(f.status in ("ok",) for f in findings)

    def test_tokens_drop_regresses_and_latency_rise_regresses(self):
        base = regress.index_rows(self.BASE)
        new = regress.index_rows([
            # -55% tokens/s: past the CPU-proxy rate floor (0.40) — a
            # -30% injection would now be absorbed as measured noise
            dict(self.BASE[0], value=45000.0),
            dict(self.BASE[1], p99_s=0.012),            # 3x p99
            dict(self.BASE[2], grad_sync_bytes_zero=25728.0),  # 2x wire
        ])
        findings = regress.compare(base, new, noise=0.1)
        bad = {(f.metric, f.field) for f in findings
               if f.status == "regressed"}
        assert ("train_tokens_per_s_float32", "value") in bad
        assert ("serve_decode_tokens_per_s", "p99_s") in bad
        assert ("zero_vs_replicated_dp4", "grad_sync_bytes_zero") in bad

    def test_solver_field_directions(self):
        """Config 15's solver fields: counts/bytes/times/iterations
        regress UPWARD, rates/efficiency/speedups DOWNWARD — including
        the per-SWEEP collective-budget fields, which must not be
        mislabeled by _HIGHER's "per_s" (per-second) substring."""
        lower = ("ppermutes_per_sweep_s2", "halo_bytes_per_sweep_s2",
                 "psums_per_iter_pipelined", "iterations_pipelined",
                 "cycles", "comm_ratio", "solve_s_classic")
        higher = ("cells_per_s", "efficiency", "deep_speedup",
                  "pipelined_speedup")
        for name in lower:
            assert regress.direction(name) == "lower", name
        for name in higher:
            assert regress.direction(name) == "higher", name

    def test_roofline_field_directions(self):
        """Config 12's decode-sweep roofline row (ISSUE 12): the
        achieved fraction/rate and the fused-vs-dense speedup gate
        UPWARD (the kernel pin), while the stated peak denominator is
        configuration — no direction, never compared (restating the
        peak must not read as a kernel change)."""
        for name in ("achieved_frac", "achieved_hbm_gbps",
                     "fused_speedup"):
            assert regress.direction(name) == "higher", name
        assert "peak_hbm_gbps" in regress._SKIP

    def test_noise_floors_absorb_tail_swings_but_not_2x(self):
        """The measured-noise floors (ISSUE 14): wall-clock fields
        swing on SAME-CODE control runs (+11.6–27.5% in PR 13's
        ``--check`` pairs; a PR-14 three-run config-12 control on the
        1-core proxy measured tails to ~52% and rates to ~34% even
        median-of-3), so a +45% p99 / +30% rate drift must stay in
        band on CPU rows, while a true >2x regression still gates —
        fields WITHOUT a floor (exact-counter fractions like
        prefill_frac) keep the tight default band, and TPU rows skip
        the floors entirely (chip noise has no CPU-proxy excuse)."""
        c17 = {"config": 17, "metric": "serve_router_tokens_per_s",
               "value": 1000.0, "prefill_frac": 0.4,
               "ttft_p99_s_latency": 0.030, "platform": "cpu"}
        base = regress.index_rows(self.BASE + [c17])
        drifted = regress.index_rows([
            self.BASE[0],
            dict(self.BASE[1], p99_s=0.004 * 1.45),     # +45%: in floor
            self.BASE[2],
            dict(c17, value=1000.0 * 0.70,              # -30%: in floor
                 ttft_p99_s_latency=0.030 * 1.5),       # +50%: in floor
        ])
        assert not regress.has_regression(
            regress.compare(base, drifted, noise=0.1)
        )
        worse = regress.index_rows([
            self.BASE[0],
            dict(self.BASE[1], p99_s=0.012),            # 3x: regressed
            self.BASE[2],
            dict(c17, value=450.0,                      # -55%: past floor
                 prefill_frac=0.48,                     # +20%: no floor
                 ttft_p99_s_latency=0.075),             # 2.5x: past floor
        ])
        bad = {(f.metric, f.field) for f in
               regress.compare(base, worse, noise=0.1)
               if f.status == "regressed"}
        assert ("serve_decode_tokens_per_s", "p99_s") in bad
        assert ("serve_router_tokens_per_s", "value") in bad
        assert ("serve_router_tokens_per_s", "prefill_frac") in bad
        assert ("serve_router_tokens_per_s", "ttft_p99_s_latency") in bad
        # the same tail drift on a CHIP row is NOT noise: floors are
        # CPU-proxy-scoped, tpu rows keep the tight band
        chip = dict(self.BASE[1], platform="tpu")
        chip_drift = dict(chip, p99_s=0.004 * 1.45)
        assert regress.has_regression(regress.compare(
            regress.index_rows([chip]),
            regress.index_rows([chip_drift]), noise=0.1,
        ))

    def test_router_field_directions(self):
        """Config 17's fleet-router fields: TTFT tails and the
        prefill fraction regress UPWARD, rates/sharing counters
        DOWNWARD — and the affinity-off CONTROL fields must not be
        dragged into _HIGHER by an over-broad "affinity" substring
        (the decode_spec latent-inversion lesson)."""
        lower = ("ttft_p99_s_latency", "ttft_p50_s_batch",
                 "prefill_frac", "prefill_frac_affinity_off")
        higher = ("serve_router_tokens_per_s", "affinity_speedup",
                  "tokens_per_s_affinity_off", "shared_tokens",
                  "subpage_tokens", "affinity_hits", "affinity_tokens")
        for name in lower:
            assert regress.direction(name) == "lower", name
        for name in higher:
            assert regress.direction(name) == "higher", name
        assert "replicas" in regress._SKIP

    def test_improvement_and_missing_are_not_failures(self):
        base = regress.index_rows(self.BASE)
        new = regress.index_rows([dict(self.BASE[0], value=200000.0)])
        findings = regress.compare(base, new, noise=0.1)
        assert not regress.has_regression(findings)
        statuses = {f.status for f in findings}
        assert "improved" in statuses and "missing" in statuses

    def test_dropped_field_surfaces_as_missing(self):
        """A renamed/dropped FIELD (not a whole metric) must not
        silently disable its gate."""
        base = regress.index_rows(self.BASE)
        row = {k: v for k, v in self.BASE[1].items() if k != "p99_s"}
        new = regress.index_rows([self.BASE[0], row, self.BASE[2]])
        findings = regress.compare(base, new, noise=0.1)
        assert not regress.has_regression(findings)
        missing = [f for f in findings if f.status == "missing"]
        assert [(f.metric, f.field) for f in missing] == [
            ("serve_decode_tokens_per_s", "p99_s")
        ]

    def test_nonfinite_new_value_regresses(self):
        """A field PRESENT in the new row but NaN/inf is a degenerated
        measurement — a failing state, not a 'missing' warning (that
        escape is for configs legitimately skipped on absent hardware)."""
        base = regress.index_rows(self.BASE)
        new = regress.index_rows([
            dict(self.BASE[0], value=float("nan")),
            dict(self.BASE[1], p50_s=float("inf")),
            self.BASE[2],
        ])
        findings = regress.compare(base, new, noise=0.1)
        assert regress.has_regression(findings)
        bad = {(f.metric, f.field) for f in findings
               if f.status == "regressed"}
        assert ("train_tokens_per_s_float32", "value") in bad
        assert ("serve_decode_tokens_per_s", "p50_s") in bad

    def test_last_row_wins(self, tmp_path):
        p = self._write(tmp_path, "b.json",
                        [dict(self.BASE[0], value=1.0), self.BASE[0]])
        rows = regress.load_rows(p)
        assert rows[(11, "train_tokens_per_s_float32")]["value"] == 100000.0

    def test_load_rows_tolerates_torn_and_nonobject_lines(self, tmp_path):
        """load_rows goes through obs.report.load_events — corrupt AND
        non-object lines (a bare number would have crashed the old
        loader's indexing) are skipped with a located warning."""
        p = self._write(tmp_path, "torn.json", [self.BASE[0]])
        with open(p, "a") as f:
            f.write('42\n{"config": 12, "metric": "tr')  # torn tail
        with pytest.warns(RuntimeWarning, match="torn.json"):
            rows = regress.load_rows(p)
        assert set(rows) == {(11, "train_tokens_per_s_float32")}

    def test_cli_smoke(self, tmp_path):
        """The acceptance gate as a subprocess: clean pair exits 0, an
        injected 55%% tokens/s regression (past the CPU-proxy rate
        floor) exits nonzero."""
        base = self._write(tmp_path, "base.json", self.BASE)
        good = self._write(tmp_path, "good.json",
                           [dict(self.BASE[0], value=97000.0),
                            self.BASE[1], self.BASE[2]])
        bad = self._write(tmp_path, "bad.json",
                          [dict(self.BASE[0], value=45000.0),
                           self.BASE[1], self.BASE[2]])
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-m", "tpuscratch.obs.regress", base, good],
            capture_output=True, text=True, env=env, timeout=120,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        r = subprocess.run(
            [sys.executable, "-m", "tpuscratch.obs.regress", base, bad],
            capture_output=True, text=True, env=env, timeout=120,
        )
        assert r.returncode == 1, r.stdout + r.stderr
        assert "REGRESSED" in r.stdout

    def test_json_output_strict_on_zero_base(self, tmp_path, capsys):
        """A 0 -> nonzero comparison (delta=inf) must not leak the
        non-standard ``Infinity`` token into --json output."""
        base = self._write(tmp_path, "zb.json", [
            {"config": 13, "metric": "zero_vs_replicated_dp1",
             "grad_sync_bytes_zero": 0.0, "platform": "cpu"},
        ])
        new = self._write(tmp_path, "zn.json", [
            {"config": 13, "metric": "zero_vs_replicated_dp1",
             "grad_sync_bytes_zero": 6432.0, "platform": "cpu"},
        ])
        rc = regress.main([base, new, "--json"])
        assert rc == 1  # 0 -> nonzero bytes is a regression
        rows = json.loads(
            capsys.readouterr().out,
            parse_constant=lambda c: (_ for _ in ()).throw(ValueError(c)),
        )
        bad = [x for x in rows if x["status"] == "regressed"]
        assert bad and bad[0]["delta"] is None

    def test_record_check_mode(self, tmp_path, monkeypatch, capsys):
        """record.py --check BASE.json wires the gate in-process."""
        from tpuscratch.bench import record

        def fake_config(out):
            # -55%: past the CPU-proxy tokens_per_s noise floor
            record._emit(out, config=99, metric="fake_tokens_per_s",
                         value=45000.0)

        monkeypatch.setitem(record.CONFIGS, 99, fake_config)
        base = self._write(
            tmp_path, "base.json",
            [{"config": 99, "metric": "fake_tokens_per_s",
              "value": 100000.0, "platform": "cpu"}],
        )
        rc = record.main(["--configs", "99", "--check", base])
        assert rc == 1
        base_ok = self._write(
            tmp_path, "ok.json",
            [{"config": 99, "metric": "fake_tokens_per_s",
              "value": 71000.0, "platform": "cpu"}],
        )
        rc = record.main(["--configs", "99", "--check", base_ok])
        assert rc == 0


@pytest.mark.trace
class TestTrainerTraceWiring:
    def test_recorder_spans_and_goodput_fields(self, devices, tmp_path):
        from tpuscratch.models.trainer import train
        from tpuscratch.models.transformer import TransformerConfig
        from tpuscratch.obs.sink import Sink

        mesh = make_mesh((1, 1), ("dp", "sp"))
        cfg = TransformerConfig(d_model=16, n_heads=2, n_experts=2,
                                d_ff=32, n_layers=1)
        rec = FlightRecorder()
        p = str(tmp_path / "t.jsonl")
        with Sink(p) as s:
            train(mesh, cfg, steps=4, save_every=2,
                  ckpt_dir=str(tmp_path / "ck"), obs=s, recorder=rec)
        totals = rec.phase_totals()
        assert totals["train/chunk"].count == 2
        assert totals["ckpt/save"].count == 2
        validate_chrome_trace(rec.chrome_trace())
        chunks = [e for e in report.load_events([p])
                  if e["event"] == "train/chunk"]
        for ev in chunks:
            for key in ("steps", "tokens", "chunk_s", "compile_s"):
                assert key in ev, key
        # the first chunk traced the program: its compile share is real
        assert chunks[0]["compile_s"] > 0
        assert chunks[1]["compile_s"] == 0
        saves = [e for e in report.load_events([p])
                 if e["event"] == "ckpt/save"]
        assert len(saves) == 2 and all(e["wall_s"] > 0 for e in saves)

    def test_always_on_without_sink(self, devices, tmp_path):
        """No sink, no recorder passed: the trainer still flies its own
        bounded recorder (always-on) and the program is unchanged."""
        from tpuscratch.models.trainer import train
        from tpuscratch.models.transformer import TransformerConfig

        mesh = make_mesh((1, 1), ("dp", "sp"))
        cfg = TransformerConfig(d_model=16, n_heads=2, n_experts=2,
                                d_ff=32, n_layers=1)
        _, rep = train(mesh, cfg, steps=2, save_every=2,
                       ckpt_dir=str(tmp_path / "ck"))
        assert rep.steps_run == 2


@pytest.mark.trace
class TestEngineTraceWiring:
    def test_engine_spans_share_recorder(self, devices, tmp_path):
        from tpuscratch.models.transformer import TransformerConfig
        from tpuscratch.obs.sink import Sink
        from tpuscratch.serve import Request, ServeConfig, ServeEngine

        mesh = make_mesh((1, 1), ("dp", "sp"))
        cfg = TransformerConfig(d_model=32, n_heads=2, n_experts=2,
                                d_ff=64, n_layers=1)
        scfg = ServeConfig(n_slots=2, n_pages=16, page_size=4, max_seq=16,
                           vocab=16)
        rec = FlightRecorder()
        p = str(tmp_path / "s.jsonl")
        with Sink(p) as s:
            eng = ServeEngine(mesh, cfg, scfg, sink=s, recorder=rec)
            eng.run([Request(rid=0, prompt=(1, 2), max_new=3)])
        totals = rec.phase_totals()
        assert totals["serve/prefill"].count == 1
        assert totals["serve/decode"].count >= 2
        validate_chrome_trace(rec.chrome_trace())
        phases = {e["phase"] for e in report.load_events([p])
                  if e["event"] == "trace/phase"}
        assert {"serve/prefill", "serve/decode"} <= phases

    def test_halo_preempted_run_files_flight_data(self, devices, tmp_path):
        """A preemption mid-run must not discard the invocation's phase
        totals (the trainer's failure-path hardening, on the halo side)."""
        import numpy as np

        from tpuscratch.ft import ChaosPlan, Fault, Preempted
        from tpuscratch.halo.driver import checkpointed_stencil
        from tpuscratch.obs.sink import Sink
        from tpuscratch.runtime.mesh import make_mesh_2d

        world = np.random.default_rng(0).standard_normal(
            (8, 8)).astype(np.float32)
        plan = ChaosPlan(0, [Fault("halo/preempt", at=(2,),
                                   kind="preempt")])
        p = str(tmp_path / "hp.jsonl")
        with Sink(p) as s:
            with pytest.raises(Preempted):
                checkpointed_stencil(world, steps=4, save_every=2,
                                     ckpt_dir=str(tmp_path / "ck"),
                                     mesh=make_mesh_2d((1, 1)), sink=s,
                                     chaos=plan)
        phases = {e["phase"] for e in report.load_events([p])
                  if e["event"] == "trace/phase"}
        assert {"halo/chunk", "ckpt/save"} <= phases

    def test_halo_driver_emits_save_events(self, devices, tmp_path):
        import numpy as np

        from tpuscratch.halo.driver import checkpointed_stencil
        from tpuscratch.obs.sink import Sink
        from tpuscratch.runtime.mesh import make_mesh_2d

        world = np.random.default_rng(0).standard_normal(
            (8, 8)).astype(np.float32)
        rec = FlightRecorder()
        p = str(tmp_path / "h.jsonl")
        with Sink(p) as s:
            checkpointed_stencil(world, steps=4, save_every=2,
                                 ckpt_dir=str(tmp_path / "ck"),
                                 mesh=make_mesh_2d((1, 1)), sink=s,
                                 recorder=rec)
        totals = rec.phase_totals()
        assert totals["halo/chunk"].count == 2
        assert totals["ckpt/save"].count == 2
        events = report.load_events([p])
        assert sum(e["event"] == "ckpt/save" for e in events) == 2
        assert {"halo/chunk", "ckpt/save"} <= {
            e["phase"] for e in events if e["event"] == "trace/phase"
        }
        # both chunks share one program (chunk size 2): the first chunk
        # absorbed the jit compile and says so; the second is pure step,
        # so goodput's compile carve-out sees the halo layer too
        chunks = [e for e in events if e["event"] == "halo/chunk"]
        assert chunks[0]["compile_s"] == chunks[0]["wall_s"] > 0
        assert chunks[1]["compile_s"] == 0.0


@pytest.mark.trace
class TestSupervisorBackoff:
    def test_restart_event_carries_backoff(self, devices, tmp_path):
        from tpuscratch.ft import ChaosPlan, Fault, supervise_train
        from tpuscratch.models.transformer import TransformerConfig
        from tpuscratch.obs.sink import Sink

        mesh = make_mesh((1, 1), ("dp", "sp"))
        cfg = TransformerConfig(d_model=16, n_heads=2, n_experts=2,
                                d_ff=32, n_layers=1)
        rec = FlightRecorder()
        plan = ChaosPlan(0, [Fault("train/preempt", at=(2,),
                                   kind="preempt")])
        p = str(tmp_path / "sup.jsonl")
        with Sink(p) as s:
            supervise_train(mesh, cfg, 4, str(tmp_path / "ck"),
                            save_every=2, chaos=plan, sink=s,
                            recorder=rec, sleep=lambda d: None)
        restarts = [e for e in report.load_events([p])
                    if e["event"] == "ft/restart"]
        assert len(restarts) == 1
        assert "backoff_s" in restarts[0]
        # the shared recorder carries the trainer's chunks AND the
        # supervisor's restart instant on one timeline
        assert rec.phase_totals()["train/chunk"].count >= 2
        assert any(
            getattr(e, "name", "") == "ft/restart" for e in rec.events()
        )


class TestSinkAtexit:
    def test_tail_flushed_at_interpreter_exit(self, tmp_path):
        """A sink that is never closed still writes its buffered tail
        when the interpreter exits (the atexit satellite)."""
        p = str(tmp_path / "orphan.jsonl")
        code = (
            "from tpuscratch.obs.sink import Sink\n"
            f"s = Sink({p!r}, flush_every=1000)\n"
            "s.emit('tick', n=1)\n"
            # no close(), no flush(): fall off the end of the script
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, env=env,
                           timeout=120)
        assert r.returncode == 0, r.stderr
        lines = [json.loads(ln) for ln in open(p) if ln.strip()]
        assert [ln["event"] for ln in lines] == ["run", "tick"]

    def test_crashing_run_keeps_tail(self, tmp_path):
        p = str(tmp_path / "crash.jsonl")
        code = (
            "from tpuscratch.obs.sink import Sink\n"
            f"s = Sink({p!r}, flush_every=1000)\n"
            "s.emit('tick', n=1)\n"
            "raise RuntimeError('boom')\n"
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, env=env,
                           timeout=120)
        assert r.returncode != 0
        lines = [json.loads(ln) for ln in open(p) if ln.strip()]
        assert [ln["event"] for ln in lines] == ["run", "tick"]

    def test_close_idempotent_after_atexit_unregister(self, tmp_path):
        from tpuscratch.obs.sink import Sink

        s = Sink(str(tmp_path / "x.jsonl"))
        s.close()
        s.close()  # no raise

    def test_dropped_sink_closes_at_gc_not_pinned(self, tmp_path):
        """An unclosed sink that goes out of scope is collectable (the
        finalizer holds no reference to it) and closes at GC — a sweep
        building one sink per engine does not leak file descriptors."""
        import gc
        import weakref

        from tpuscratch.obs.sink import Sink

        p = str(tmp_path / "g.jsonl")
        s = Sink(p, flush_every=1000)
        s.emit("tick", n=1)
        ref = weakref.ref(s)
        del s
        gc.collect()
        assert ref() is None  # not pinned by the exit hook
        lines = [json.loads(ln) for ln in open(p) if ln.strip()]
        assert [ln["event"] for ln in lines] == ["run", "tick"]


class TestReportCorruptLines:
    def test_torn_final_line_skipped_with_warning(self, tmp_path):
        """The post-SIGKILL artifact: a truncated last line is skipped
        with a warning, the surviving events still summarize."""
        p = str(tmp_path / "torn.jsonl")
        with open(p, "w") as f:
            f.write('{"event": "run", "t": 0.0}\n')
            f.write('{"event": "serve/tick", "t": 0.1, "tick_s": 0.01}\n')
            f.write('{"event": "serve/tick", "t": 0.2, "tick_')  # torn
        with pytest.warns(RuntimeWarning, match="torn.jsonl:3"):
            events = report.load_events([p])
        assert len(events) == 2
        summ = report.summarize(events)
        assert summ["events"]["serve/tick"]["count"] == 1

    def test_non_object_line_skipped(self, tmp_path):
        p = str(tmp_path / "odd.jsonl")
        with open(p, "w") as f:
            f.write('{"event": "run"}\n[1, 2]\n42\n')
        with pytest.warns(RuntimeWarning):
            events = report.load_events([p])
        assert len(events) == 1

    def test_cli_survives_corrupt_file(self, tmp_path):
        p = str(tmp_path / "bad.jsonl")
        with open(p, "w") as f:
            f.write('{"event": "run"}\nnot json\n'
                    '{"event": "tick", "t": 0.1, "x": 1}\n')
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-m", "tpuscratch.obs.report", p],
            capture_output=True, text=True, env=env, timeout=120,
        )
        assert r.returncode == 0, r.stderr
        assert "tick" in r.stdout
