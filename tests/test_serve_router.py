"""tpuscratch.serve.router: the fleet front end (ISSUE 14).

The correctness anchors:

- **routing bit-identity**: the SAME request stream drained through 1
  replica, N replicas with affinity on, N replicas with affinity off,
  and an autoscaled disagg fleet that re-roles replicas MID-stream all
  emit identical greedy outputs (1x1 and 2x2 CPU meshes) — a request's
  stream depends only on ``(seed, rid, prompt)``, so routing moves
  WHERE work runs, never what is emitted; composed with int8/fp8 x
  prefix-share/spec/chunked-prefill/disagg/tiered;
- **fleet counter laws**: over a fault-free drain,
  ``prefill_tokens + shared_tokens == submitted prompt tokens``
  fleet-wide, dispatch counts sum to the request count, and
  ``prefill_frac`` with affinity on never exceeds affinity off on a
  shared-prefix workload (concentrating tenants can only INCREASE
  sharing);
- **sub-page sharing** (the PR-8 remainder): a matched prefix ending
  mid-page shares its exact token length — ``page_size + 1`` shared
  tokens share ``page_size + 1``, not ``page_size`` — across the
  boundary cases (match ends at 1, page_size - 1, page_size + 1,
  mid-page) and the quantized rungs (int8/fp8 scale planes ride the
  boundary-page copy), with the sharer's output bit-identical to a
  share-free engine's;
- **SLO classes**: per-class completion/TTFT/token-rate reports,
  TTFT-class traffic preferring chunked-prefill replicas, and
  ``max_queue`` backpressure holding (not dropping) requests;
- **autoscale hysteresis**: re-roling fires from staged-handoff
  backlog, the prefill pool never empties, and outputs stay identical.

Equivalence holds in the no-token-dropped MoE regime (capacity_factor
>= n_experts, the test_serve rule), since capacity-bound routing is
the one component whose per-token output depends on batch composition.
"""

import dataclasses

import pytest
import jax

from tpuscratch.models.transformer import TransformerConfig
from tpuscratch.runtime.mesh import make_mesh
from tpuscratch.serve import (
    DisaggEngine,
    FleetRouter,
    Request,
    RouterConfig,
    SLOClass,
    ServeConfig,
    ServeEngine,
)

pytestmark = pytest.mark.router

D = 32

#: single-engine baselines shared across tests — every routing variant
#: compares against the same reference drain, so it runs ONCE per
#: (dims, scfg overrides) instead of once per test (tier-1 time budget)
_BASE_CACHE: dict = {}


def cfg_for(**kw):
    kw.setdefault("capacity_factor", 4.0)
    return TransformerConfig(
        d_model=D, n_heads=4, n_experts=4, d_ff=48, n_layers=1, **kw
    )


def scfg_for(**kw):
    kw.setdefault("n_slots", 4)
    kw.setdefault("n_pages", 16)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_seq", 24)
    kw.setdefault("vocab", 16)
    kw.setdefault("prefix_share", True)
    return ServeConfig(**kw)


def mesh_for(dims=(1, 1)):
    return make_mesh(dims, ("dp", "sp"),
                     jax.devices()[: dims[0] * dims[1]])


def tenant_requests(n=6, max_new=3):
    """Two tenants' prompts: each tenant's requests share a 9-token
    (2 full pages + 1 boundary token at page_size=4) tenant prefix and
    diverge after — the shared-prefix workload cross-replica affinity
    exists for."""
    pre = {0: (1, 2, 3, 4, 5, 6, 7, 8, 9), 1: (9, 8, 7, 6, 5, 4, 3, 2, 1)}
    return [
        Request(rid=i, prompt=pre[i % 2] + (10 + i % 5,), max_new=max_new)
        for i in range(n)
    ]


def baseline(dims=(1, 1), reqs=None, **scfg_kw):
    """Cached single-ServeEngine drain of the canonical workload."""
    key = (dims, tuple(sorted(scfg_kw.items())),
           tuple(reqs or ()) and tuple((r.rid, r.prompt, r.max_new)
                                       for r in reqs))
    if key not in _BASE_CACHE:
        _BASE_CACHE[key] = ServeEngine(
            mesh_for(dims), cfg_for(), scfg_for(**scfg_kw)
        ).run(reqs or tenant_requests())
    return _BASE_CACHE[key]


def fleet(n, dims=(1, 1), rcfg=None, disagg=False, **scfg_kw):
    cfg, scfg = cfg_for(), scfg_for(**scfg_kw)
    mesh = mesh_for(dims)
    cls = DisaggEngine if disagg else ServeEngine
    return FleetRouter([cls(mesh, cfg, scfg) for _ in range(n)],
                       rcfg=rcfg)


def check_counter_law(rep):
    assert rep.prefill_tokens + rep.shared_tokens == \
        rep.submitted_prompt_tokens
    assert sum(rep.dispatched) == rep.completed
    assert 0.0 <= rep.prefill_frac <= 1.0
    assert abs(rep.prefill_frac + rep.shared_frac - 1.0) < 1e-12


class TestRoutingBitIdentity:
    @pytest.mark.parametrize("dims", [(1, 1), (2, 2)])
    def test_one_vs_n_vs_affinity_off(self, dims):
        base = baseline(dims)
        on = fleet(3, dims).run(tenant_requests())
        off = fleet(3, dims, RouterConfig(affinity=False)).run(
            tenant_requests()
        )
        assert on.outputs == base.outputs
        assert off.outputs == base.outputs
        for rep in (on, off):
            check_counter_law(rep)

    def test_int8_chunked_composes(self):
        kw = dict(kv_dtype="int8", chunk_prefill=3)
        base = baseline(**kw)
        rep = fleet(2, **kw).run(tenant_requests())
        assert rep.outputs == base.outputs
        check_counter_law(rep)

    @pytest.mark.slow
    def test_fp8_speculative_composes(self):
        kw = dict(kv_dtype="fp8", spec_k=2, n_pages=32, max_seq=32)
        base = baseline(**kw)
        rep = fleet(2, **kw).run(tenant_requests())
        assert rep.outputs == base.outputs
        check_counter_law(rep)

    def test_tiered_composes(self):
        # a device pool tight against the working set: routing composes
        # with forced spill/prefetch (and the parked-prefix retention)
        kw = dict(n_pages=8, kv_host_pages=16)
        base = baseline(**{k: v for k, v in kw.items()
                           if k != "kv_host_pages"})
        rep = fleet(2, **kw).run(tenant_requests())
        assert rep.outputs == base.outputs
        check_counter_law(rep)

    def test_disagg_fleet_matches_monolithic(self):
        # disagg stages monolithic prefills (no prefix_share), so the
        # router's affinity probe returns 0 and dispatch is least-
        # loaded — outputs must still match the share-free baseline
        base = baseline(prefix_share=False)
        rep = fleet(2, disagg=True, prefix_share=False).run(
            tenant_requests()
        )
        assert rep.outputs == base.outputs
        # disagg prefill tokens are the STAGING slice's; the law holds
        # with them counted (fault-free drain: no degraded re-prefills)
        check_counter_law(rep)
        # share-incapable replicas never score affinity: a "matched"
        # dispatch would save nothing (every prompt re-prefills in
        # full), so the planned index must not concentrate load or
        # report fictitious wins
        assert rep.affinity_hits == 0 and rep.affinity_tokens == 0
        assert all(d > 0 for d in rep.dispatched)  # least-loaded spread

    def test_midstream_reroling_is_invisible(self):
        # 2 decode slots per replica against a 10-request stream keeps
        # the staged-handoff backlog crossing both hysteresis bounds:
        # replicas re-role prefill<->decode MID-stream (both directions)
        base = baseline(prefix_share=False, n_slots=2,
                        reqs=tenant_requests(10))
        r = fleet(
            2, disagg=True, prefix_share=False, n_slots=2,
            rcfg=RouterConfig(autoscale=True, scale_down_backlog=0.5,
                              scale_up_backlog=0.25, cooldown_ticks=0),
        )
        rep = r.run(tenant_requests(10))
        assert rep.reroles > 0, "workload never exercised a re-role"
        assert rep.outputs == base.outputs
        assert r.n_prefill_pool >= 1
        check_counter_law(rep)


class TestFleetCounters:
    def test_affinity_concentrates_sharing(self):
        on = fleet(3).run(tenant_requests(8))
        off = fleet(3, rcfg=RouterConfig(affinity=False)).run(
            tenant_requests(8)
        )
        for rep in (on, off):
            check_counter_law(rep)
        # concentrating a tenant's requests on one replica can only
        # increase page reuse: prefill_frac monotone in affinity
        assert on.prefill_frac <= off.prefill_frac
        assert on.affinity_hits > 0
        assert on.affinity_tokens > 0
        assert off.affinity_hits == 0

    def test_shared_tokens_not_page_quantized(self):
        # the acceptance criterion: a (page_size + 1)-token shared
        # prefix shares page_size + 1 tokens, not page_size
        scfg = scfg_for(n_slots=2)
        ps = scfg.page_size
        eng = ServeEngine(mesh_for(), cfg_for(), scfg)
        donor = Request(rid=0, prompt=(1, 2, 3, 4, 5, 6, 7, 8),
                        max_new=6)
        eng.submit(donor)
        eng.step()   # donor admitted + its pages trie-registered
        s0, sub0 = eng.shared_tokens, eng.subpage_tokens
        # shares exactly ps + 1 = 5 tokens, diverges at position 5
        eng.submit(Request(rid=1, prompt=(1, 2, 3, 4, 5, 9, 9, 9),
                           max_new=2))
        eng.run()
        assert eng.shared_tokens - s0 == ps + 1
        assert eng.subpage_tokens - sub0 == 1

    def test_report_deltas_survive_reuse(self):
        # counters in a report are the DRAIN's deltas: a reused router
        # reports each drain independently
        r = fleet(2)
        first = r.run(tenant_requests(4))
        more = [Request(rid=100 + i, prompt=(1, 2, 3, 4, 5, 6, 7, 8, 9,
                                             10 + i), max_new=3)
                for i in range(4)]
        second = r.run(more)
        for rep in (first, second):
            check_counter_law(rep)
        assert second.completed == 4
        assert second.submitted_prompt_tokens == sum(
            len(q.prompt) for q in more
        )

    def test_planned_index_eviction_keeps_longer_keys_reachable(self):
        # the cap evicts oldest-first, which for any prompt family is
        # its SHORTEST aligned key — the family's surviving longer keys
        # must stay matchable, not become dead entries behind the gap
        r = fleet(2, rcfg=RouterConfig(index_cap=2))
        p = (1, 2, 3, 4, 5, 6, 7, 8)
        k4, k8 = r._block_keys(p)
        r._register([k4, k8], 0)
        r._register(r._block_keys((9, 9, 9, 9)), 1)
        assert k4 not in r._index and k8 in r._index
        assert r._planned_match([k4, k8], 0) == 8

    def test_counter_law_survives_predispatched_requests(self):
        # submit + step() BEFORE run(): some requests land in replica
        # queues (dispatched, not yet admitted — n_slots bounds the
        # first tick's admissions).  Their prompts prefill during the
        # drain, so the law's "submitted" leg must count them even
        # though they left the ROUTER queue before run() started.
        r = fleet(1, n_slots=2)
        for q in tenant_requests(6):
            r.submit(q)
        r.step()
        assert r.replicas[0].n_queued > 0  # some really are replica-held
        rep = r.run()
        assert rep.completed == 6
        # the prefill law's "submitted" leg (dispatch-count deltas are
        # legitimately pre-drain here, so check_counter_law's
        # dispatched == completed does not apply)
        assert rep.prefill_tokens + rep.shared_tokens == \
            rep.submitted_prompt_tokens


class TestSubpageBoundaries:
    def drive_pair(self, donor_prompt, sharer_prompt, **scfg_kw):
        """(shared_delta, subpage_delta, sharer_tokens): donor admitted
        first (pages registered), sharer drains against it."""
        scfg = scfg_for(n_slots=2, **scfg_kw)
        eng = ServeEngine(mesh_for(), cfg_for(), scfg)
        eng.submit(Request(rid=0, prompt=donor_prompt, max_new=6))
        eng.step()
        s0, sub0 = eng.shared_tokens, eng.subpage_tokens
        eng.submit(Request(rid=1, prompt=sharer_prompt, max_new=3))
        rep = eng.run()
        out = dict(rep.outputs)
        # the donor finishes inside run() too; the sharer's stream is
        # rid 1's
        return (eng.shared_tokens - s0, eng.subpage_tokens - sub0,
                out[1])

    def solo_tokens(self, prompt, **scfg_kw):
        """The sharer's stream on a fresh, share-free engine — the
        bit-identity oracle (same rid, so the same PRNG stream)."""
        scfg = scfg_for(n_slots=2, prefix_share=False, **scfg_kw)
        eng = ServeEngine(mesh_for(), cfg_for(), scfg)
        rep = eng.run([Request(rid=1, prompt=prompt, max_new=3)])
        return dict(rep.outputs)[1]

    DONOR = (1, 2, 3, 4, 5, 6, 7, 8)

    @pytest.mark.parametrize("shared_len", [1, 3, 6])
    def test_match_frontier_is_token_exact(self, shared_len):
        # boundary cases: 1, page_size - 1 (whole match sub-page),
        # mid-page past a full page — shared tokens == the exact match
        # length, never rounded down to a page multiple (the
        # page_size + 1 acceptance case is pinned exactly by
        # TestFleetCounters.test_shared_tokens_not_page_quantized)
        sharer = self.DONOR[:shared_len] + tuple(
            9 for _ in range(len(self.DONOR) - shared_len)
        )
        shared, sub, toks = self.drive_pair(self.DONOR, sharer)
        assert shared == shared_len
        assert sub == shared_len % 4   # the mid-page remainder exactly
        assert toks == self.solo_tokens(sharer)

    @pytest.mark.parametrize("kv", ["int8", "fp8"])
    def test_mid_page_frontier_quantized(self, kv):
        # the boundary-page copy carries the quantized rungs' scale
        # planes; the sharer's first write past the frontier re-zeroes
        # and requantizes (the chunked-prefill write contract), so the
        # stream stays bit-identical to a share-free engine
        sharer = self.DONOR[:6] + (9, 9)
        shared, sub, toks = self.drive_pair(self.DONOR, sharer,
                                            kv_dtype=kv)
        assert shared == 6 and sub == 2
        assert toks == self.solo_tokens(sharer, kv_dtype=kv)

    def test_full_prompt_match_still_rescores_one_position(self):
        # an identical prompt caps at len - 1 shared tokens: the tail
        # must re-score at least one position for its own logits
        shared, _sub, toks = self.drive_pair(self.DONOR, self.DONOR)
        assert shared == len(self.DONOR) - 1
        assert toks == self.solo_tokens(self.DONOR)

    def test_router_subpage_tokens_surface_fleet_wide(self):
        rep = fleet(1).run(tenant_requests(6))
        check_counter_law(rep)
        # the 9-token tenant prefix ends 1 token past page 2: affinity
        # followers pick up that boundary token sub-page, so the fleet
        # report's shared total is not page-quantized
        assert rep.subpage_tokens > 0
        assert rep.shared_tokens > 0


class TestSLOClasses:
    RCFG = RouterConfig(classes=(
        SLOClass("latency", target="ttft"),
        SLOClass("batch", target="throughput"),
    ))

    def tagged(self, n=6):
        return [("latency" if i % 2 else "batch", r)
                for i, r in enumerate(tenant_requests(n))]

    def test_per_class_reports(self):
        rep = fleet(2, rcfg=self.RCFG).run(self.tagged(6))
        check_counter_law(rep)
        by = {c.name: c for c in rep.classes}
        assert by["latency"].completed == 3
        assert by["batch"].completed == 3
        for c in rep.classes:
            assert c.tokens > 0 and c.tokens_per_s > 0
            assert 0 < c.ttft_p50_s <= c.ttft_p99_s

    def test_ttft_class_prefers_chunked_replicas(self):
        cfg, mesh = cfg_for(), mesh_for()
        chunked = ServeEngine(mesh, cfg, scfg_for(chunk_prefill=3))
        resident = ServeEngine(mesh, cfg, scfg_for())
        r = FleetRouter([chunked, resident],
                        dataclasses.replace(self.RCFG, affinity=False))
        rep = r.run(self.tagged(6))
        check_counter_law(rep)
        for rid, cls in r._class_of.items():
            want = 0 if cls == "latency" else 1
            assert r._replica_of[rid] == want, (rid, cls)

    def test_max_queue_backpressure_holds_not_drops(self):
        rcfg = RouterConfig(classes=(
            SLOClass("only", max_queue=1),
        ))
        rep = fleet(1, rcfg=rcfg).run(
            [("only", r) for r in tenant_requests(5)]
        )
        check_counter_law(rep)
        assert rep.completed == 5          # held, never dropped
        assert rep.backpressure_holds > 0  # the bound actually bit

    def test_ttft_clock_starts_at_router_submit(self):
        # the TTFT the report carries must include ROUTER-queue wait
        # (backpressure must never look free): after dispatch, the
        # engine's submit stamp is the router-submit time, not the
        # later dispatch time
        import time

        r = fleet(1)
        r.submit(Request(rid=0, prompt=(1, 2, 3), max_new=2))
        time.sleep(0.05)          # router-held wall the clock must see
        rep = r.run()             # dispatch + first token in-drain
        assert rep.classes[0].ttft_p99_s >= 0.05

    def test_quarantine_releases_backpressure_depth(self):
        # a poison request (prefill fails every attempt, retry budget
        # 0) quarantines engine-side and never reaches the finish
        # list; its max_queue slot must free, or every later request
        # of the class holds forever
        from tpuscratch.ft.chaos import ChaosPlan, Fault

        cfg, mesh = cfg_for(), mesh_for()
        plan = ChaosPlan(0, [Fault("serve/prefill", key=0, at=(0,),
                                   times=1000)])
        eng = ServeEngine(mesh, cfg, scfg_for(retry_budget=0),
                          chaos=plan)
        r = FleetRouter([eng], RouterConfig(classes=(
            SLOClass("only", max_queue=1),
        )))
        reqs = [("only", q) for q in tenant_requests(3)]  # rid 0 poison
        rep = r.run(reqs)
        assert rep.completed == 2                  # poison never emits
        assert eng._quarantined and 0 in eng._quarantined
        assert r._depth[(0, "only")] == 0          # slot freed

    def test_quarantine_targets_the_poison_not_the_queue_head(self):
        # monolithic admission (prefix_share off): the poison fails
        # mid-tick with OTHER requests already in flight, so the
        # engine's _recover_cache requeues those ahead of it — the
        # queue head is a HEALTHY replaying request.  The router must
        # quarantine the stamped poison (rid 2), never the head, and
        # every other request must finish bit-identical to a clean run.
        from tpuscratch.ft.chaos import ChaosPlan, Fault

        cfg, mesh = cfg_for(), mesh_for()
        reqs = tenant_requests(4)
        clean = ServeEngine(mesh_for(), cfg, scfg_for(
            prefix_share=False)).run([r for r in reqs if r.rid != 2])
        plan = ChaosPlan(0, [Fault("serve/prefill", key=2, p=1.0,
                                   at=None, times=None)])
        eng = ServeEngine(mesh, cfg, scfg_for(prefix_share=False),
                          chaos=plan)
        rep = FleetRouter([eng]).run(reqs)
        assert set(eng._quarantined) == {2}
        assert rep.outputs == clean.outputs

    def test_finishes_survive_a_poison_tick(self):
        # rid 0 (max_new=1) finishes INSIDE the same tick whose later
        # admission (rid 1, poison) raises through: at that moment its
        # tokens exist only in the engine's finish buffer (the slot was
        # already evicted), so they must re-emerge from the next tick
        # instead of vanishing with the exception
        from tpuscratch.ft.chaos import ChaosPlan, Fault

        cfg, mesh = cfg_for(), mesh_for()
        reqs = [Request(rid=0, prompt=(1, 2, 3), max_new=1),
                Request(rid=1, prompt=(2, 3, 4), max_new=2),
                Request(rid=2, prompt=(3, 4, 5), max_new=2)]
        clean = ServeEngine(mesh_for(), cfg, scfg_for(
            prefix_share=False)).run([reqs[0], reqs[2]])
        plan = ChaosPlan(0, [Fault("serve/prefill", key=1, p=1.0,
                                   at=None, times=None)])
        eng = ServeEngine(mesh, cfg, scfg_for(prefix_share=False),
                          chaos=plan)
        rep = FleetRouter([eng]).run(reqs)
        assert set(eng.quarantined) == {1}
        assert rep.outputs == clean.outputs  # rid 0 not lost

    def test_unknown_tenant_rejected(self):
        r = fleet(1)
        with pytest.raises(ValueError, match="unknown tenant"):
            r.submit(Request(rid=0, prompt=(1, 2), max_new=2),
                     tenant="nope")

    def test_fleet_wide_rid_uniqueness(self):
        r = fleet(2)
        r.submit(Request(rid=7, prompt=(1, 2), max_new=2))
        with pytest.raises(ValueError, match="already used"):
            r.submit(Request(rid=7, prompt=(3, 4), max_new=2))
        r.run()


class TestConfigValidation:
    def test_inverted_hysteresis_band_rejected(self):
        with pytest.raises(ValueError, match="hysteresis"):
            RouterConfig(autoscale=True, scale_down_backlog=1.0,
                         scale_up_backlog=2.0)

    def test_bad_slo_target_rejected(self):
        with pytest.raises(ValueError, match="target"):
            SLOClass("x", target="speed")

    def test_duplicate_class_names_rejected(self):
        with pytest.raises(ValueError, match="duplicate"):
            RouterConfig(classes=(SLOClass("a"), SLOClass("a")))

    def test_autoscale_needs_disagg_fleet(self):
        with pytest.raises(ValueError, match="DisaggEngine"):
            fleet(2, rcfg=RouterConfig(autoscale=True))

    def test_output_affecting_mismatch_rejected(self):
        cfg, mesh = cfg_for(), mesh_for()
        a = ServeEngine(mesh, cfg, scfg_for())
        b = ServeEngine(mesh, cfg, scfg_for(vocab=32))
        with pytest.raises(ValueError, match="vocab"):
            FleetRouter([a, b])

    def test_scheduling_knob_mismatch_allowed(self):
        cfg, mesh = cfg_for(), mesh_for()
        a = ServeEngine(mesh, cfg, scfg_for(n_slots=2))
        b = ServeEngine(mesh, cfg, scfg_for(chunk_prefill=3))
        rep = FleetRouter([a, b]).run(tenant_requests(4))
        assert rep.outputs == baseline(reqs=tenant_requests(4)).outputs

    def test_malformed_request_fails_at_the_front_door(self):
        # the engine rules enforced at router.submit: a bad request
        # must never reach dispatch, where a mid-loop raise once left
        # an already-dispatched request queued in two places (wedge)
        r = fleet(2)
        r.submit(Request(rid=0, prompt=(1, 2), max_new=2))
        with pytest.raises(ValueError, match="max_new"):
            r.submit(Request(rid=1, prompt=(1, 2), max_new=0))
        with pytest.raises(ValueError, match="vocab"):
            r.submit(Request(rid=2, prompt=(999,), max_new=2))
        rep = r.run()  # the good request still drains cleanly
        assert rep.completed == 1

    def test_disagg_staging_bound_enforced_at_front_door(self):
        # replica-SPECIFIC admission rules (here the disagg staging
        # pool bound, stricter than max_seq) reach the router front
        # door too: routing may send the request anywhere, so every
        # replica's validate() must accept it at submit time
        eng = DisaggEngine(mesh_for(), cfg_for(),
                           scfg_for(prefix_share=False), stage_pages=2)
        r = FleetRouter([eng])
        with pytest.raises(ValueError, match="staging"):
            r.submit(Request(rid=0, prompt=tuple(range(1, 13)),
                             max_new=2))

    def test_empty_fleet_rejected(self):
        with pytest.raises(ValueError, match="at least one"):
            FleetRouter([])


class TestOverloadShedding:
    """ISSUE 18: SLO-aware load shedding — deadline sheds, displacement
    protecting the top class, the ``max_open`` pressure valve, explicit
    ``RequestShed`` outcomes, the request-count law, and window-delta
    report semantics on a reused router.  All on the LOGICAL shed clock
    (``tick_s=1.0``): the shed schedule is a pure function of the
    workload, never of wall time."""

    def one_class(self, **kw):
        return RouterConfig(classes=(
            SLOClass("batch", target="throughput", **kw),
        ), tick_s=1.0)

    def test_deadline_shed_explicit_outcome(self):
        rcfg = self.one_class(shed_after_s=3.0, max_queue=1)
        r = fleet(1, rcfg=rcfg)
        rep = r.run([("batch", q) for q in tenant_requests(6)])
        assert rep.shed > 0
        assert rep.completed + rep.shed == 6
        assert rep.shed_tokens > 0
        outs = dict(rep.outputs)
        log = r.take_shed()
        assert len(log) == rep.shed
        for s in log:
            assert s.reason == "deadline" and s.cls == "batch"
            assert s.waited_s > 3.0       # it really blew the budget
            assert s.rid not in outs      # shed work never emits
        assert r.take_shed() == []        # drain-and-swap
        # the request-count law at drain: nothing open, nothing lost
        assert r.open_requests == 0
        assert r.submitted_requests == \
            r.finished_requests + r.shed_requests
        check_counter_law(rep)            # token law, shed leg excluded

    def test_displacement_protects_top_class(self):
        rcfg = RouterConfig(classes=(
            SLOClass("latency", target="ttft", shed_after_s=2.0,
                     max_queue=1),
            SLOClass("batch", target="throughput", max_queue=1),
        ), tick_s=1.0)
        r = fleet(1, rcfg=rcfg)
        # 3 queued top-class requests behind a deep batch backlog: the
        # top class blows its deadline while batch has work to give up
        reqs = [("latency" if i < 3 else "batch", q)
                for i, q in enumerate(tenant_requests(11))]
        rep = r.run(reqs)
        by = {c.name: c for c in rep.classes}
        # the top class blew deadlines — but BATCH paid, explicitly
        assert by["latency"].shed == 0
        assert by["batch"].shed > 0
        assert {s.reason for s in r.take_shed()} == {"displaced"}
        outs = dict(rep.outputs)
        for tenant, q in reqs:
            if tenant == "latency":
                assert q.rid in outs      # every top request completed

    def test_lowest_class_sheds_itself_without_lower_work(self):
        # the inverse: when the deadline-blown class IS the lowest,
        # there is nobody to displace — it sheds its own longest waiter
        rcfg = RouterConfig(classes=(
            SLOClass("latency", target="ttft", max_queue=1),
            SLOClass("batch", target="throughput", shed_after_s=2.0,
                     max_queue=1),
        ), tick_s=1.0)
        r = fleet(1, rcfg=rcfg)
        rep = r.run([("batch", q) for q in tenant_requests(6)])
        assert rep.shed > 0
        assert {s.reason for s in r.take_shed()} == {"deadline"}

    def test_max_open_pressure_valve(self):
        rcfg = self.one_class(max_open=2)
        r = fleet(1, rcfg=rcfg)
        rep = r.run([("batch", q) for q in tenant_requests(6)])
        # 6 submitted against a cap of 2: the first shed pass drops the
        # 4 oldest queued excess before anything dispatches
        assert rep.shed == 4 and rep.completed == 2
        log = r.take_shed()
        assert {s.reason for s in log} == {"over_open"}
        assert sorted(s.rid for s in log) == [0, 1, 2, 3]

    def test_inflight_work_never_sheds(self):
        # max_open bites with everything already dispatched: nothing
        # queued to give up, so the valve waits for the drain instead
        # of killing in-flight work
        rcfg = self.one_class(max_open=1)
        r = fleet(1, rcfg=rcfg)
        r.submit(Request(rid=0, prompt=(1, 2, 3), max_new=6),
                 tenant="batch")
        outs = dict(r.step())             # rid 0 dispatched, in flight
        assert r._inflight == {0}         # mid-generation, not done
        r.submit(Request(rid=1, prompt=(2, 3, 4), max_new=2),
                 tenant="batch")
        # over cap with rid 0 IN FLIGHT: only the queued rid 1 sheds
        while r.busy:
            outs.update(r.step())
        assert sorted(outs) == [0]        # rid 0 completed untouched
        log = r.take_shed()
        assert [s.rid for s in log] == [1]
        assert log[0].reason == "over_open"
        assert r.submitted_requests == \
            r.finished_requests + r.shed_requests == 2

    def test_logical_clock_makes_sheds_deterministic(self):
        def go():
            r = fleet(1, rcfg=self.one_class(shed_after_s=3.0,
                                             max_queue=1))
            rep = r.run([("batch", q) for q in tenant_requests(6)])
            return (dict(rep.outputs),
                    [(s.rid, s.reason, s.waited_s)
                     for s in r.take_shed()])
        assert go() == go()

    def test_shed_rid_can_resubmit_bit_identically(self):
        # the retry contract: a shed rid leaves the seen-set, and the
        # rid keys the PRNG stream — the retry leg emits the tokens the
        # original would have
        reqs = tenant_requests(3, max_new=3)
        baseline_ = dict(fleet(1).run(reqs).outputs)
        r = fleet(1, rcfg=self.one_class(max_open=0, shed_after_s=1.0,
                                         max_queue=1))
        r.run([("batch", q) for q in reqs])
        shed_rids = [s.rid for s in r.take_shed()]
        assert shed_rids, "workload drifted: nothing shed"
        # retry ONE shed leg on the now-idle fleet: same rid => same
        # PRNG stream => the tokens the original would have emitted
        rid = shed_rids[0]
        retry = next(q for q in reqs if q.rid == rid)
        rep2 = r.run([("batch", retry)])
        assert rep2.completed == 1 and rep2.shed == 0
        assert dict(rep2.outputs)[rid] == baseline_[rid]

    def test_reused_router_reports_window_deltas(self):
        """ISSUE 18 satellite: shed/readmitted in a RouterReport are
        THIS window's deltas — a reused router's second report does not
        re-count the first window's storm."""
        from tpuscratch.ft.chaos import ChaosPlan, Fault

        rcfg = self.one_class(shed_after_s=3.0, max_queue=1)
        plan = ChaosPlan(seed=2, faults=(
            Fault(site="serve/replica", at=(1,), key=0, kind="kill",
                  down_ticks=2),
        ))
        cfg, scfg, mesh = cfg_for(), scfg_for(), mesh_for()
        r = FleetRouter([ServeEngine(mesh, cfg, scfg)
                         for _ in range(2)], rcfg=rcfg, chaos=plan)
        first = r.run([("batch", q) for q in tenant_requests(8)])
        assert first.kills == 1 and first.readmitted > 0
        assert first.shed > 0
        assert len(r.take_shed()) == first.shed
        lifetime = r.shed_requests
        # second window: light load, the fault budget is spent
        more = [("batch", Request(rid=50 + i, prompt=(1 + i, 2, 3),
                                  max_new=2)) for i in range(2)]
        second = r.run(more)
        assert second.completed == 2
        assert second.shed == 0 and second.shed_tokens == 0
        assert second.kills == 0 and second.readmitted == 0
        assert r.take_shed() == []
        assert r.shed_requests == lifetime   # lifetime stays monotone
        check_counter_law(second)

    def test_shed_knob_validation(self):
        with pytest.raises(ValueError, match="shed_after_s"):
            SLOClass("x", shed_after_s=-1.0)
        with pytest.raises(ValueError, match="max_open"):
            SLOClass("x", max_open=-1)
        with pytest.raises(ValueError, match="tick_s"):
            RouterConfig(classes=(SLOClass("a"),), tick_s=-0.5)
