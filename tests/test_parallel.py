"""Tests for sequence/context parallelism: ring pipeline, ring attention,
Ulysses all-to-all attention — each against a single-array oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpuscratch.comm import run_spmd
from tpuscratch.parallel import ring_attention, ring_scan, ulysses_attention
from tpuscratch.runtime.mesh import make_mesh_1d

N = 8


def _oracle_attention(q, k, v, causal):
    """Plain softmax attention on the full (S, H, D) arrays, fp32."""
    d = q.shape[-1]
    s = np.einsum("shd,thd->hst", q.astype(np.float64), k.astype(np.float64))
    s = s / np.sqrt(d)
    if causal:
        S, T = s.shape[1], s.shape[2]
        s = np.where(np.arange(S)[:, None] >= np.arange(T)[None, :], s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("hst,thd->shd", p, v.astype(np.float64))


@pytest.fixture(scope="module")
def mesh():
    return make_mesh_1d("sp")


class TestRingScan:
    def test_ring_allreduce(self, mesh):
        # rotate-and-add == allreduce: the simplest ring pipeline
        def body(x):
            carry, _ = ring_scan(
                lambda c, blk, i: c + blk, jnp.zeros_like(x), x, "sp"
            )
            return carry

        f = run_spmd(mesh, body, P("sp"), P("sp"))
        out = np.asarray(f(jnp.arange(N, dtype=jnp.float32)))
        np.testing.assert_array_equal(out, np.full(N, 28.0))

    def test_payload_returns_home(self, mesh):
        def body(x):
            _, payload = ring_scan(lambda c, b, i: c, 0.0, x, "sp")
            return payload

        f = run_spmd(mesh, body, P("sp"), P("sp"))
        out = np.asarray(f(jnp.arange(N, dtype=jnp.float32)))
        np.testing.assert_array_equal(out, np.arange(N))

    def test_hop_origin_order(self, mesh):
        # at hop i the block originates from rank (me - i) mod n: collect
        # origins on rank 0 by recording block values
        def body(x):
            def combine(c, blk, i):
                return c.at[i].set(blk[0])

            carry, _ = ring_scan(combine, jnp.zeros(N), x, "sp")
            return carry[None]

        f = run_spmd(mesh, body, P("sp"), P("sp", None))
        out = np.asarray(f(jnp.arange(N, dtype=jnp.float32)))
        np.testing.assert_array_equal(out[0], (0 - np.arange(N)) % N)


class TestRingAttention:
    @pytest.mark.parametrize("impl", ["xla", "pallas"])
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_oracle(self, mesh, causal, impl):
        # S=8 so the interpret-mode flash kernel gets full sublane blocks
        S, H, D = 8, 2, 8  # global seq = 64
        rng = np.random.default_rng(0)
        q = rng.standard_normal((N * S, H, D)).astype(np.float32)
        k = rng.standard_normal((N * S, H, D)).astype(np.float32)
        v = rng.standard_normal((N * S, H, D)).astype(np.float32)

        f = run_spmd(
            mesh,
            lambda a, b, c: ring_attention(
                a, b, c, "sp", causal=causal, impl=impl
            ),
            (P("sp"), P("sp"), P("sp")),
            P("sp"),
        )
        got = np.asarray(f(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
        expect = _oracle_attention(q, k, v, causal)
        np.testing.assert_allclose(got, expect, rtol=2e-4, atol=2e-5)

    def test_unknown_impl_rejected(self, mesh):
        x = jnp.ones((N * 2, 1, 4), jnp.float32)
        f = run_spmd(
            mesh,
            lambda a, b, c: ring_attention(a, b, c, "sp", impl="cuda"),
            (P("sp"), P("sp"), P("sp")),
            P("sp"),
        )
        with pytest.raises(ValueError, match="unknown ring attention impl"):
            f(x, x, x)

    @pytest.mark.parametrize("causal", [False, True])
    def test_pallas_gradients_match_xla(self, mesh, causal):
        # the custom-VJP ring backward (second KV rotation accumulating
        # dk/dv home) vs autodiff through the dense ring path
        S, H, D = 8, 2, 8
        rng = np.random.default_rng(11)
        q = jnp.asarray(rng.standard_normal((N * S, H, D)).astype(np.float32))
        k = jnp.asarray(rng.standard_normal((N * S, H, D)).astype(np.float32))
        v = jnp.asarray(rng.standard_normal((N * S, H, D)).astype(np.float32))
        w = jnp.asarray(rng.standard_normal((N * S, H, D)).astype(np.float32))

        def grads(impl):
            def body(a, b, c, wt):
                def loss(a, b, c):
                    out = ring_attention(a, b, c, "sp", causal=causal, impl=impl)
                    # psum: the global scalar objective, so per-rank
                    # grads are comparable across impls
                    return jax.lax.psum(jnp.sum(out * wt), "sp")

                return jax.grad(loss, argnums=(0, 1, 2))(a, b, c)

            f = run_spmd(
                mesh, body,
                (P("sp"), P("sp"), P("sp"), P("sp")),
                (P("sp"), P("sp"), P("sp")),
            )
            return f(q, k, v, w)

        gx = grads("xla")
        gp = grads("pallas")
        for a, b, name in zip(gx, gp, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5,
                err_msg=f"d{name}",
            )

    def test_pallas_bf16_gradients_accumulate_fp32(self, mesh):
        # hop partials are fp32 (out_dtype override in the ring
        # backward): bf16-input grads must stay close to the fp32 oracle
        # grads, not drift with ring size
        S, H, D = 8, 1, 8
        rng = np.random.default_rng(12)
        q32 = jnp.asarray(rng.standard_normal((N * S, H, D)).astype(np.float32))
        w = jnp.asarray(rng.standard_normal((N * S, H, D)).astype(np.float32))

        def grads(x, impl):
            def body(a, b, c, wt):
                def loss(a, b, c):
                    out = ring_attention(a, b, c, "sp", impl=impl)
                    return jax.lax.psum(
                        jnp.sum(out.astype(jnp.float32) * wt), "sp"
                    )

                return jax.grad(loss, argnums=(0, 1, 2))(a, b, c)

            f = run_spmd(
                mesh, body,
                (P("sp"), P("sp"), P("sp"), P("sp")),
                (P("sp"), P("sp"), P("sp")),
            )
            return f(x, x, x, w)

        gb = grads(q32.astype(jnp.bfloat16), "pallas")
        g32 = grads(q32, "xla")
        for a, b, name in zip(gb, g32, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a, dtype=np.float32), np.asarray(b),
                rtol=0.05, atol=0.05, err_msg=f"d{name}",
            )

    @pytest.mark.parametrize("impl", ["xla", "pallas"])
    def test_bf16_inputs(self, mesh, impl):
        # bf16 is the motivating case for the pallas path's raw-fp32
        # accumulator state (no per-hop round trip through the dtype)
        S, H, D = 8, 1, 8
        rng = np.random.default_rng(1)
        q = rng.standard_normal((N * S, H, D)).astype(np.float32)
        f = run_spmd(
            mesh,
            lambda a, b, c: ring_attention(a, b, c, "sp", impl=impl),
            (P("sp"), P("sp"), P("sp")),
            P("sp"),
        )
        qb = jnp.asarray(q, dtype=jnp.bfloat16)
        out = f(qb, qb, qb)
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out, dtype=np.float32),
            _oracle_attention(q, q, q, False),
            rtol=0.05, atol=0.05,
        )

    def test_shape_validation(self, mesh):
        f = run_spmd(
            mesh,
            lambda a, b, c: ring_attention(a, b, c, "sp"),
            (P("sp"), P("sp"), P("sp")),
            P("sp"),
        )
        with pytest.raises(ValueError):
            bad = jnp.ones((N * 2, 3, 4))
            f(bad, jnp.ones((N * 2, 3, 5)), bad)


class TestUlysses:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_oracle(self, mesh, causal):
        S, H, D = 4, 8, 8  # H divisible by N
        rng = np.random.default_rng(2)
        q = rng.standard_normal((N * S, H, D)).astype(np.float32)
        k = rng.standard_normal((N * S, H, D)).astype(np.float32)
        v = rng.standard_normal((N * S, H, D)).astype(np.float32)

        f = run_spmd(
            mesh,
            lambda a, b, c: ulysses_attention(a, b, c, "sp", causal=causal),
            (P("sp"), P("sp"), P("sp")),
            P("sp"),
        )
        got = np.asarray(f(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
        expect = _oracle_attention(q, k, v, causal)
        np.testing.assert_allclose(got, expect, rtol=2e-4, atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_pallas_impl_matches_oracle(self, mesh, causal):
        # the flash-attention local step (ops.attention) behind the same
        # all_to_all re-sharding — interpret mode on the CPU mesh
        S, H, D = 4, 8, 8
        rng = np.random.default_rng(6)
        q = rng.standard_normal((N * S, H, D)).astype(np.float32)
        k = rng.standard_normal((N * S, H, D)).astype(np.float32)
        v = rng.standard_normal((N * S, H, D)).astype(np.float32)
        f = run_spmd(
            mesh,
            lambda a, b, c: ulysses_attention(
                a, b, c, "sp", causal=causal, impl="pallas"
            ),
            (P("sp"), P("sp"), P("sp")),
            P("sp"),
        )
        got = np.asarray(f(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
        expect = _oracle_attention(q, k, v, causal)
        np.testing.assert_allclose(got, expect, rtol=2e-4, atol=2e-5)

    def test_unknown_impl_rejected(self, mesh):
        S, H, D = 2, 8, 4
        x = jnp.ones((N * S, H, D), jnp.float32)
        f = run_spmd(
            mesh,
            lambda a, b, c: ulysses_attention(a, b, c, "sp", impl="nope"),
            (P("sp"), P("sp"), P("sp")),
            P("sp"),
        )
        with pytest.raises(ValueError, match="unknown ulysses impl"):
            f(x, x, x)

    def test_ring_and_ulysses_agree(self, mesh):
        S, H, D = 2, 8, 4
        rng = np.random.default_rng(3)
        q = rng.standard_normal((N * S, H, D)).astype(np.float32)
        fr = run_spmd(
            mesh,
            lambda a, b, c: ring_attention(a, b, c, "sp", causal=True),
            (P("sp"), P("sp"), P("sp")),
            P("sp"),
        )
        fu = run_spmd(
            mesh,
            lambda a, b, c: ulysses_attention(a, b, c, "sp", causal=True),
            (P("sp"), P("sp"), P("sp")),
            P("sp"),
        )
        a = np.asarray(fr(jnp.asarray(q), jnp.asarray(q), jnp.asarray(q)))
        b = np.asarray(fu(jnp.asarray(q), jnp.asarray(q), jnp.asarray(q)))
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)

    def test_indivisible_heads_rejected(self, mesh):
        f = run_spmd(
            mesh,
            lambda a, b, c: ulysses_attention(a, b, c, "sp"),
            (P("sp"), P("sp"), P("sp")),
            P("sp"),
        )
        x = jnp.ones((N * 2, 3, 4))  # 3 heads % 8 != 0
        with pytest.raises(ValueError):
            f(x, x, x)


class TestPipeline:
    """GPipe-style staged pipeline vs sequential stage application."""

    def _run(self, mesh, M, feature=6):
        from tpuscratch.parallel import pipeline_apply

        n = mesh.devices.size
        rng = np.random.default_rng(7)
        # stage s: x -> tanh(x @ W_s + b_s), stacked over the stage axis
        Ws = rng.standard_normal((n, feature, feature)).astype(np.float32) * 0.3
        bs = rng.standard_normal((n, feature)).astype(np.float32) * 0.1
        micro = rng.standard_normal((M, feature)).astype(np.float32)

        def stage(params, x):
            W, b = params
            return jnp.tanh(x @ W[0] + b[0])

        f = run_spmd(
            mesh,
            lambda W, b, m: pipeline_apply(stage, (W, b), m, "sp"),
            (P("sp"), P("sp"), P()),
            P(),
        )
        got = np.asarray(f(jnp.asarray(Ws), jnp.asarray(bs), jnp.asarray(micro)))

        expect = micro.copy()
        for s in range(n):
            expect = np.tanh(expect @ Ws[s] + bs[s])
        return got, expect

    @pytest.mark.parametrize("M", [1, 3, 8])
    def test_matches_sequential(self, mesh, M):
        got, expect = self._run(mesh, M)
        np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)

    def test_single_stage_mesh(self):
        mesh1 = make_mesh_1d("sp", n=1)
        got, expect = self._run(mesh1, 4)
        np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)

    def test_bubble_fraction(self):
        from tpuscratch.parallel import bubble_fraction

        assert bubble_fraction(1, 4) == 0.0
        assert bubble_fraction(4, 1) == 0.75
        assert abs(bubble_fraction(8, 56) - 7 / 63) < 1e-12
        with pytest.raises(ValueError):
            bubble_fraction(0, 4)


class TestPipelineGrad:
    """Pipeline parallelism is trainable: the gradient THROUGH the GPipe
    schedule (scan + ppermute + masked psum) must equal the gradient of
    the plain sequential stage chain, for stage params and microbatches
    alike."""

    def test_gradient_matches_sequential(self, mesh):
        from tpuscratch.parallel import pipeline_apply

        n = mesh.devices.size
        F, M = 6, 5
        rng = np.random.default_rng(23)
        Ws = jnp.asarray(rng.standard_normal((n, F, F)).astype(np.float32) * 0.3)
        bs = jnp.asarray(rng.standard_normal((n, F)).astype(np.float32) * 0.1)
        micro = jnp.asarray(rng.standard_normal((M, F)).astype(np.float32))

        def stage(params, x):
            W, b = params
            return jnp.tanh(x @ W[0] + b[0])

        pipe = jax.shard_map(
            lambda W, b, m: pipeline_apply(stage, (W, b), m, "sp"),
            mesh=mesh,
            in_specs=(P("sp"), P("sp"), P()),
            out_specs=P(),
            check_vma=False,
        )

        def loss_pipe(W, b, m):
            return (pipe(W, b, m) ** 2).sum()

        def loss_seq(W, b, m):
            x = m
            for s in range(n):
                x = jnp.tanh(x @ W[s] + b[s])
            return (x ** 2).sum()

        gp = jax.jit(jax.grad(loss_pipe, argnums=(0, 1, 2)))(Ws, bs, micro)
        gs = jax.jit(jax.grad(loss_seq, argnums=(0, 1, 2)))(Ws, bs, micro)
        for got, want, name in zip(gp, gs, ("dW", "db", "dmicro")):
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-5,
                err_msg=name,
            )

    def test_sgd_step_decreases_loss(self, mesh):
        # one end-to-end training step through the pipeline
        from tpuscratch.parallel import pipeline_apply

        n = mesh.devices.size
        F, M = 6, 4
        rng = np.random.default_rng(29)
        Ws = jnp.asarray(rng.standard_normal((n, F, F)).astype(np.float32) * 0.3)
        micro = jnp.asarray(rng.standard_normal((M, F)).astype(np.float32))
        target = jnp.asarray(rng.standard_normal((M, F)).astype(np.float32))

        pipe = jax.shard_map(
            lambda W, m: pipeline_apply(
                lambda Wp, x: jnp.tanh(x @ Wp[0]), W, m, "sp"
            ),
            mesh=mesh, in_specs=(P("sp"), P()), out_specs=P(),
            check_vma=False,
        )

        def loss(W):
            return ((pipe(W, micro) - target) ** 2).mean()

        l0, g = jax.jit(jax.value_and_grad(loss))(Ws)
        l1 = jax.jit(loss)(Ws - 0.1 * g)
        assert float(l1) < float(l0)
