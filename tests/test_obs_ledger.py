"""Communication/compute ledger: HLO collective walk validated against
the analytic wire-byte formulas on known collectives (psum, all-gather,
all-to-all, reduce-scatter, ppermute), cost_analysis plumbing, and the
roofline diff."""

import jax
import jax.numpy as jnp
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from tpuscratch.comm import run_spmd
from tpuscratch.obs.ledger import (
    CollectiveOp,
    all_gather_wire_bytes,
    all_to_all_wire_bytes,
    analyze,
    parse_collectives,
    reduce_scatter_wire_bytes,
    ring_all_reduce_wire_bytes,
    roofline,
)
from tpuscratch.runtime.mesh import make_mesh


class TestAnalyticFormulas:
    def test_ring_all_reduce(self):
        # the canonical 2*(n-1)/n: at n=4, 1 MB costs 1.5 MB on the wire
        assert ring_all_reduce_wire_bytes(4, 1 << 20) == pytest.approx(
            1.5 * (1 << 20)
        )
        assert ring_all_reduce_wire_bytes(2, 100) == pytest.approx(100.0)

    def test_all_gather(self):
        assert all_gather_wire_bytes(4, 128) == 384.0

    def test_reduce_scatter(self):
        assert reduce_scatter_wire_bytes(4, 128) == 384.0

    def test_all_to_all(self):
        assert all_to_all_wire_bytes(4, 128) == 96.0


class TestParseCollectives:
    """Parsing straight from canned HLO lines (no jax involved)."""

    def test_sync_form(self):
        ops = parse_collectives(
            "  ROOT %all-reduce.1 = f32[4,8]{1,0} all-reduce(f32[4,8]{1,0}"
            " %p), channel_id=1, replica_groups={{0,1,2,3}},"
            " use_global_device_ids=true, to_apply=%region_0.4\n"
        )
        assert ops == (CollectiveOp("all-reduce", 128, 4),)

    def test_async_start_counts_once(self):
        text = (
            "  %ag = f32[16,8]{1,0} all-gather-start(f32[4,8]{1,0} %p),"
            " replica_groups={{0,1},{2,3}}, dimensions={0}\n"
            "  %agd = f32[16,8]{1,0} all-gather-done(f32[16,8]{1,0} %ag)\n"
        )
        ops = parse_collectives(text)
        assert len(ops) == 1
        assert ops[0].kind == "all-gather"
        assert ops[0].group_size == 2  # two groups of two

    def test_async_tuple_results_not_double_counted(self):
        """Real TPU async spellings return (operand, result[, contexts]);
        payload must be the RESULT buffer, not the tuple sum."""
        ag = parse_collectives(
            "  %ag = (f32[4,8]{1,0}, f32[16,8]{1,0}) all-gather-start("
            "f32[4,8]{1,0} %p), replica_groups={{0,1,2,3}}, dimensions={0}\n"
        )
        assert ag[0].payload_bytes == 512  # the gathered result, alone
        rs = parse_collectives(
            "  %rs = (f32[16,8]{1,0}, f32[4,8]{1,0}) reduce-scatter-start("
            "f32[16,8]{1,0} %p), replica_groups={{0,1,2,3}}, dimensions={0}\n"
        )
        assert rs[0].payload_bytes == 128  # the scattered shard, alone
        cp = parse_collectives(
            "  %cp = (f32[4,8]{1,0}, f32[4,8]{1,0}, u32[], u32[])"
            " collective-permute-start(f32[4,8]{1,0} %p),"
            " source_target_pairs={{0,1},{1,0}}\n"
        )
        assert cp[0].payload_bytes == 128  # contexts are not payload
        ar = parse_collectives(
            "  %ar = (f32[4,8]{1,0}, f32[4,8]{1,0}) all-reduce-start("
            "f32[4,8]{1,0} %p), replica_groups={{0,1,2,3}},"
            " to_apply=%add\n"
        )
        assert ar[0].payload_bytes == 128

    def test_iota_replica_groups(self):
        ops = parse_collectives(
            "  %rs = f32[4]{0} reduce-scatter(f32[16]{0} %p),"
            " replica_groups=[2,4]<=[8], dimensions={0}\n"
        )
        assert ops[0].group_size == 4

    def test_tuple_shape_and_gte_not_double_counted(self):
        text = (
            "  %all-to-all.2 = (f32[4,2]{1,0}, f32[4,2]{1,0}) all-to-all("
            "f32[4,2]{1,0} %s0, f32[4,2]{1,0} %s1),"
            " replica_groups={{0,1}}, dimensions={0}\n"
            "  %gte = f32[4,2]{1,0} get-tuple-element((f32[4,2]{1,0},"
            " f32[4,2]{1,0}) %all-to-all.2), index=0\n"
        )
        ops = parse_collectives(text)
        assert len(ops) == 1
        assert ops[0].payload_bytes == 2 * 4 * 2 * 4

    def test_collective_permute_pairs(self):
        ops = parse_collectives(
            "  ROOT %collective-permute.1 = bf16[4,8]{1,0}"
            " collective-permute(bf16[4,8]{1,0} %p), channel_id=1,"
            " source_target_pairs={{0,1},{1,2},{2,3},{3,0}}\n"
        )
        assert ops[0].kind == "collective-permute"
        assert ops[0].payload_bytes == 64  # bf16 is 2 bytes
        assert ops[0].group_size == 4
        assert ops[0].wire_bytes == 64.0  # one hop, whole buffer

    def test_combined_variadic_all_reduce_sums(self):
        """XLA's AllReduceCombiner fuses many gradient psums into one
        variadic instruction; the payload is the SUM of the fused
        buffers, not the largest."""
        ops = parse_collectives(
            "  %ar = (f32[1024]{0}, f32[256]{0}) all-reduce(f32[1024]{0}"
            " %a, f32[256]{0} %b), replica_groups={{0,1,2,3}},"
            " to_apply=%add\n"
        )
        assert ops[0].payload_bytes == (1024 + 256) * 4

    def test_plain_compute_lines_ignored(self):
        assert parse_collectives(
            "  %dot.1 = f32[8,8]{1,0} dot(f32[8,8]{1,0} %a, f32[8,8]{1,0}"
            " %b), lhs_contracting_dims={1}, rhs_contracting_dims={0}\n"
        ) == ()


class TestAnalyzeOnMesh:
    """Compiled-program ledgers on the virtual CPU mesh: byte counts must
    match the analytic formulas exactly."""

    def test_psum_all_reduce(self, devices):
        mesh = make_mesh((4,), ("x",))
        f = run_spmd(mesh, lambda v: lax.psum(v, "x"), P("x"), P("x"))
        led = analyze(f, jnp.ones((16, 8), jnp.float32))
        assert led.counts() == {"all-reduce": 1}
        # per-shard payload: (4, 8) f32 = 128 B
        assert led.payload_bytes() == {"all-reduce": 128}
        assert led.wire_bytes()["all-reduce"] == pytest.approx(
            ring_all_reduce_wire_bytes(4, 128)
        )

    def test_all_gather(self, devices):
        mesh = make_mesh((4,), ("x",))
        f = run_spmd(
            mesh, lambda v: lax.all_gather(v, "x", tiled=True), P("x"), P()
        )
        led = analyze(f, jnp.ones((16, 8), jnp.float32))
        assert led.counts() == {"all-gather": 1}
        assert led.payload_bytes() == {"all-gather": 512}  # full result
        assert led.wire_bytes()["all-gather"] == pytest.approx(
            all_gather_wire_bytes(4, 128)
        )

    def test_all_to_all(self, devices):
        mesh = make_mesh((4,), ("x",))
        f = run_spmd(
            mesh,
            lambda v: lax.all_to_all(v, "x", 1, 0, tiled=True),
            P("x"), P("x"),
        )
        led = analyze(f, jnp.ones((16, 8), jnp.float32))
        assert led.counts() == {"all-to-all": 1}
        assert led.payload_bytes() == {"all-to-all": 128}
        assert led.wire_bytes()["all-to-all"] == pytest.approx(
            all_to_all_wire_bytes(4, 128)
        )

    def test_reduce_scatter(self, devices):
        mesh = make_mesh((4,), ("x",))
        f = run_spmd(
            mesh, lambda v: lax.psum_scatter(v, "x", tiled=True), P(), P("x")
        )
        led = analyze(f, jnp.ones((16, 8), jnp.float32))
        assert led.counts() == {"reduce-scatter": 1}
        assert led.payload_bytes() == {"reduce-scatter": 128}  # one shard
        assert led.wire_bytes()["reduce-scatter"] == pytest.approx(
            reduce_scatter_wire_bytes(4, 128)
        )

    def test_psum_2x2_group_size(self, devices):
        """A both-axes psum on a 2x2 mesh reduces over ONE group of 4."""
        mesh = make_mesh((2, 2), ("dp", "sp"))
        f = run_spmd(
            mesh, lambda v: lax.psum(v, ("dp", "sp")),
            P(("dp", "sp")), P(("dp", "sp")),
        )
        led = analyze(f, jnp.ones((16, 8), jnp.float32))
        assert [(o.kind, o.group_size) for o in led.collectives] == [
            ("all-reduce", 4)
        ]

    def test_single_axis_psum_on_2x2_groups_of_two(self, devices):
        mesh = make_mesh((2, 2), ("dp", "sp"))
        f = run_spmd(
            mesh, lambda v: lax.psum(v, "sp"),
            P(("dp", "sp")), P(("dp", "sp")),
        )
        led = analyze(f, jnp.ones((16, 8), jnp.float32))
        assert [o.group_size for o in led.collectives] == [2]

    def test_flops_from_cost_analysis(self, devices):
        led = analyze(
            jax.jit(lambda a, b: a @ b),
            jnp.ones((8, 8), jnp.float32), jnp.ones((8, 8), jnp.float32),
        )
        assert led.flops == pytest.approx(2 * 8 * 8 * 8)  # 2mnk
        assert led.bytes_accessed > 0
        assert led.collectives == ()

    def test_unjitted_callable_accepted(self, devices):
        led = analyze(lambda a: a + 1.0, jnp.ones((4,), jnp.float32))
        assert led.collectives == ()

    def test_summary_renders(self, devices):
        mesh = make_mesh((4,), ("x",))
        f = run_spmd(mesh, lambda v: lax.psum(v, "x"), P("x"), P("x"))
        led = analyze(f, jnp.ones((16, 8), jnp.float32))
        s = led.summary()
        assert "all-reduce" in s and "wire" in s


class TestRoofline:
    def _ledger(self):
        return analyze(
            jax.jit(lambda a, b: a @ b),
            jnp.ones((64, 64), jnp.float32), jnp.ones((64, 64), jnp.float32),
        )

    def test_fractions(self):
        led = self._ledger()
        # pretend the measured span was 1 ms for 10 executions
        r = roofline(led, 1e-3, executions=10,
                     peak_flops_per_s=1e12, peak_hbm_bytes_per_s=1e11)
        assert r.flops_per_s == pytest.approx(led.flops * 10 / 1e-3)
        assert r.flops_fraction == pytest.approx(r.flops_per_s / 1e12)
        assert r.hbm_fraction == pytest.approx(r.hbm_bytes_per_s / 1e11)
        assert r.wire_fraction is None  # no link peak stated
        assert r.bound in ("compute", "memory")
        assert "TFLOP/s" in r.summary()

    def test_network_bound(self, devices):
        mesh = make_mesh((4,), ("x",))
        f = run_spmd(mesh, lambda v: lax.psum(v, "x"), P("x"), P("x"))
        led = analyze(f, jnp.ones((1024, 8), jnp.float32))
        r = roofline(led, 1e-3, peak_flops_per_s=1e15,
                     peak_wire_bytes_per_s=1e6)
        assert r.bound == "network"

    def test_bad_measurement_raises(self):
        with pytest.raises(ValueError):
            roofline(self._ledger(), 0.0)
