"""Tiered KV memory (ISSUE 13): host-offloaded cold pages.

The correctness anchors:

- **HostPageStore laws**: the PageAllocator refcount contract extended
  to the host tier (put grants 1, share adds, free reclaims at zero;
  all-or-nothing batches; double-free rejected), byte-exact payload
  roundtrips, one bulk extent per spill batch (not per page), and
  empty (unwritten) reservations costing zero backing bytes;
- **TieredPageAllocator laws**: cross-tier refcounts (a spilled shared
  page counts one holder per sharer), the residency policy (LRU by
  last-attended, pinned hot window never spilled except as correctness
  fallback), spill/prefetch byte exactness, degraded == device-only
  arithmetic, and full capacity restored after drain;
- **forced-spill bit-identity**: greedy engine output IDENTICAL with
  the tier forced into heavy spilling (a device pool several times
  smaller than the working set), across the dtype ladder and composed
  with prefix sharing, speculative decode, chunked prefill, and
  disaggregation, on the 1x1 and 2x2 CPU meshes — per-slot streams
  depend only on their own pages and PRNG draws, so wave scheduling
  and page placement must be invisible;
- **cold-hit fallback**: with prefetch-ahead unable to hide the
  rotation (thrash regime), the synchronous path completes correctly
  and counts every cold page;
- **warm-prefix parking** (the PR-8 retention remainder): an evicted
  shared chain parks in the host tier, a later trie hit restores it —
  sharing without a concurrently-live holder — and output still
  matches the untiered engine;
- **serve/spill chaos**: transient host-tier faults retry through
  ft.retry; a TOTAL host-tier outage degrades to no-spill with output
  BYTE-identical to the untiered engine;
- **traffic ledger**: host↔device bytes per token from exact page-move
  counters x the analytic per-page byte form, agreeing exactly with
  the store's actually-moved byte counters (three independent
  accountings).

Equivalence holds in the no-token-dropped MoE regime (capacity_factor
>= n_experts, the test_serve rule), since capacity-bound routing is
the one component whose per-token output depends on batch composition.
"""

import dataclasses

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from tpuscratch.ft.chaos import ChaosPlan, Fault
from tpuscratch.models.transformer import TransformerConfig
from tpuscratch.obs.ledger import (
    kv_cache_bytes,
    kv_host_traffic_bytes,
    kv_page_bytes,
)
from tpuscratch.runtime.mesh import make_mesh
from tpuscratch.serve import (
    CacheGeometry,
    DisaggEngine,
    HostPageStore,
    HostTierError,
    Request,
    ResidencyPolicy,
    ServeConfig,
    ServeEngine,
    TieredPageAllocator,
    host_leaf_shapes,
    init_kv_cache,
)
from tpuscratch.serve.decode import plan_sweep_waves

pytestmark = pytest.mark.tiered

GEOM = CacheGeometry(n_layers=1, n_pages=8, page_size=4, n_heads=2,
                     d_head=4)


def store_for(n_pages=8, dtype=jnp.int8, **kw):
    return HostPageStore(n_pages, host_leaf_shapes(GEOM, dtype), **kw)


def payload_batch(store, n, seed=0):
    rng = np.random.default_rng(seed)
    out = {}
    for name, (shape, dt, _off) in store._leaves.items():
        vals = rng.integers(-100, 100, size=(n,) + shape)
        out[name] = vals.astype(dt)
    return out


class TestHostPageStore:
    def test_put_read_roundtrip_is_byte_exact(self):
        st = store_for()
        pl = payload_batch(st, 3)
        slots = st.put(pl)
        assert len(slots) == 3 and st.n_live == 3
        back = st.read_batch(slots)
        for name in pl:
            assert back[name].dtype == pl[name].dtype
            assert np.array_equal(
                back[name].view(np.uint8), pl[name].view(np.uint8)
            )

    def test_refcount_laws(self):
        st = store_for()
        slots = st.put(payload_batch(st, 2))
        st.share(slots)
        assert st.free(slots) == []          # one holder each remains
        assert sorted(st.free(slots)) == sorted(slots)
        assert st.n_free == st.n_pages
        with pytest.raises(ValueError):
            st.free([slots[0]])              # double free
        with pytest.raises(ValueError):
            st.share([slots[0]])             # share of a freed slot

    def test_all_or_nothing_capacity(self):
        st = store_for(n_pages=2)
        assert st.put(payload_batch(st, 3)) is None
        assert st.n_live == 0 and st.n_free == 2
        assert st.put_empty(3) is None
        assert st.put_empty(2) is not None

    def test_one_extent_per_spill_batch_and_region_reuse(self):
        st = store_for()
        slots = st.put(payload_batch(st, 4))
        assert len(st._extents) == 1         # ONE bulk buffer for 4 pages
        st.free(slots)
        again = st.put(payload_batch(st, 4, seed=1))
        assert len(st._extents) == 1         # freed regions reused
        assert st.stats()["backed_bytes"] == 4 * st.page_nbytes
        st.free(again)

    def test_empty_reservation_costs_no_backing(self):
        st = store_for()
        slots = st.put_empty(5)
        assert st.is_empty(slots[0])
        assert st.stats()["backed_bytes"] == 0
        assert st.stats()["spill_bytes"] == 0
        with pytest.raises(ValueError):
            st.read_batch([slots[0]])        # nothing to read

    def test_alloc_hook_failure_is_hosttiererror_and_atomic(self):
        def boom(nbytes):
            raise MemoryError("no pinned pages")

        st = store_for(alloc_hook=boom)
        with pytest.raises(HostTierError):
            st.put(payload_batch(st, 2))
        assert st.n_live == 0 and st.n_free == st.n_pages

    def test_close_restarts_cold_and_refuses_live_pages(self):
        st = store_for()
        slots = st.put(payload_batch(st, 3))
        with pytest.raises(ValueError):
            st.close()                       # live pages pin the backing
        st.free(slots)
        st.close()
        assert st.n_free == st.n_pages
        assert st.stats()["backed_bytes"] == 0
        again = st.put(payload_batch(st, 2, seed=1))  # cold restart works
        assert len(again) == 2 and len(st._extents) == 1
        st.free(again)


def fake_device(store):
    """A dict-backed 'device pool' for allocator-level law tests."""
    dev = {}

    def reader(dids):
        return {name: np.stack([dev[d][name] for d in dids])
                for name in store._leaves}

    def writer(dids, payload):
        for i, d in enumerate(dids):
            dev[d] = {name: np.array(payload[name][i])
                      for name in payload}

    return dev, reader, writer


def write_dev(store, dev, alloc, lps, seed=0):
    rng = np.random.default_rng(seed)
    for lp in lps:
        dev[alloc.device_page(lp)] = {
            name: rng.integers(-50, 50, size=shape).astype(dt)
            for name, (shape, dt, _o) in store._leaves.items()
        }
    alloc.mark_written(lps)


class TestTieredAllocator:
    def test_spill_prefetch_roundtrip_is_byte_exact(self):
        st = store_for()
        dev, reader, writer = fake_device(st)
        al = TieredPageAllocator(4, st, reader, writer)
        lps = al.alloc(3)
        write_dev(st, dev, al, lps)
        before = {lp: {n: np.array(v) for n, v in
                       dev[al.device_page(lp)].items()} for lp in lps}
        # force all three out, then back
        more = al.alloc(4, keep=[])          # spills the cold three
        assert more is not None
        assert not any(al.is_resident(lp) for lp in lps)
        assert al.refcount(lps[0]) == 1      # holders survive the tier
        al.ensure_resident(lps)
        for lp in lps:
            after = dev[al.device_page(lp)]
            for name in after:
                assert np.array_equal(
                    after[name].view(np.uint8),
                    before[lp][name].view(np.uint8),
                )
        al.free(lps)
        al.free(more)
        assert al.n_free == 4 + 8            # both tiers fully restored

    def test_spilled_shared_page_counts_one_holder_per_sharer(self):
        st = store_for()
        dev, reader, writer = fake_device(st)
        al = TieredPageAllocator(2, st, reader, writer)
        lps = al.alloc(2)
        write_dev(st, dev, al, lps)
        al.share(lps)                        # two holders each
        al.alloc(2)                          # spills both
        assert not al.is_resident(lps[0])
        assert al.refcount(lps[0]) == 2      # the cross-tier law
        assert al.free(lps) == []            # first holder: nothing dies
        released = al.free(lps)
        assert sorted(released) == sorted(lps)

    def test_pinned_hot_window_never_spills_before_cold(self):
        st = store_for()
        dev, reader, writer = fake_device(st)
        al = TieredPageAllocator(4, st, reader, writer)
        lps = al.alloc(4)
        write_dev(st, dev, al, lps)
        al.set_pins([lps[3]])
        al.tick()
        al.touch([lps[2]])                   # recently attended
        al.alloc(2)                          # needs 2 victims
        # LRU order among unpinned: lps[0], lps[1] (stale) go first;
        # the pinned tail and the freshly-touched page stay
        assert not al.is_resident(lps[0]) and not al.is_resident(lps[1])
        assert al.is_resident(lps[2]) and al.is_resident(lps[3])

    def test_unwritten_spill_moves_zero_bytes(self):
        st = store_for()
        dev, reader, writer = fake_device(st)
        al = TieredPageAllocator(2, st, reader, writer)
        lps = al.alloc(2)                    # never written
        al.alloc(2)                          # spills both reservations
        assert al.spilled_pages == 0 and al.spilled_empty == 2
        assert st.stats()["spill_bytes"] == 0
        al.ensure_resident(lps)              # comes back copy-free
        assert al.prefetched_pages == 0

    def test_degraded_is_device_only(self):
        st = store_for()
        dev, reader, writer = fake_device(st)
        al = TieredPageAllocator(4, st, reader, writer)
        al.degrade()
        assert al.n_free == 4                # host capacity gone
        assert al.can_alloc(4) and not al.can_alloc(5)
        lps = al.alloc(4, resident=1)        # norm: everything resident
        assert all(al.is_resident(lp) for lp in lps)
        assert al.alloc(1) is None

    def test_parked_chain_restores_and_evicts_lru(self):
        st = store_for(n_pages=2)
        dev, reader, writer = fake_device(st)
        evicted = []
        al = TieredPageAllocator(4, st, reader, writer,
                                 on_parked_evict=evicted.extend)
        lps = al.alloc(2)
        write_dev(st, dev, al, lps)
        assert al.free(lps, park=lps) == []  # both park, nothing dies
        assert al.n_parked == 2 and al.n_live == 0
        al.tick()                            # the restore refreshes lps[0]
        fresh = al.restore_parked(lps[0])
        assert fresh is not None and al.refcount(fresh) == 1
        assert al.is_parked(lps[0])          # the original stays parked
        assert np.array_equal(
            dev[al.device_page(fresh)]["k"], dev[al.device_page(fresh)]["k"]
        )
        # host pressure evicts the LRU parked page (lps[1]: older stamp)
        more = al.alloc(3)
        write_dev(st, dev, al, more, seed=2)
        al.alloc(1)                          # forces spill -> host room
        assert lps[1] in evicted

    def test_failed_restore_keeps_traffic_accounting_exact(self):
        # a transient extent fault inside restore_parked's room-making
        # alloc must un-count the speculative host read, or ft.retry's
        # re-entry double-counts prefetch bytes and breaks the
        # three-way agreement (page counters x page bytes == store bytes)
        arm = {"on": False}

        def hook(nbytes):
            if arm["on"]:
                raise MemoryError("transient pinned-page outage")

        st = store_for(n_pages=4, alloc_hook=hook)
        dev, reader, writer = fake_device(st)
        al = TieredPageAllocator(2, st, reader, writer)
        lps = al.alloc(1)
        write_dev(st, dev, al, lps)
        assert al.free(lps, park=lps) == []   # parks: spills to the host
        live = al.alloc(2)
        write_dev(st, dev, al, live, seed=5)  # device pool fully live
        before = st.stats()["prefetch_bytes"]
        arm["on"] = True
        with pytest.raises(HostTierError):
            al.restore_parked(lps[0])         # room-making spill faults
        assert st.stats()["prefetch_bytes"] == before
        arm["on"] = False
        fresh = al.restore_parked(lps[0])     # the ft.retry re-entry
        assert fresh is not None
        assert st.spill_bytes == al.spilled_pages * st.page_nbytes
        assert st.prefetch_bytes == al.prefetched_pages * st.page_nbytes

    def test_restore_parked_survives_evicting_itself(self):
        # regression: both tiers exactly full and the restored page is
        # the host LRU — the restore's own room-making spill evicts the
        # parked original.  The payload must be read BEFORE the alloc,
        # or the relocation lands on a dead host slot (KeyError).
        st = store_for(n_pages=1)
        dev, reader, writer = fake_device(st)
        al = TieredPageAllocator(2, st, reader, writer)
        lps = al.alloc(1)
        write_dev(st, dev, al, lps)
        before = {n: np.array(v)
                  for n, v in dev[al.device_page(lps[0])].items()}
        assert al.free(lps, park=lps) == []   # parked: fills the host slot
        live = al.alloc(2)
        write_dev(st, dev, al, live, seed=3)  # device pool fully live
        fresh = al.restore_parked(lps[0])
        assert fresh is not None and al.refcount(fresh) == 1
        after = dev[al.device_page(fresh)]
        for name in after:
            assert np.array_equal(after[name].view(np.uint8),
                                  before[name].view(np.uint8))

    def test_wave_planner_packs_unique_pages_first_fit(self):
        needs = [(0, 0, frozenset({1, 2})), (1, 0, frozenset({2, 3})),
                 (2, 0, frozenset({4, 5, 6})), (3, 1, frozenset({1, 2}))]
        # capacity 4, legacy slot order: slots 0+1 share page 2 (union
        # 3), slot 2 would push group 0 to 6 -> new wave; slot 3 is
        # group 1 (own pool)
        assert plan_sweep_waves(needs, 4, reorder=False) == [[0, 1], [2, 3]]
        # wave-aware reorder (ISSUE 14) pulls slot 3 (group 1, its own
        # pool) forward into the first wave instead of splitting on
        # slot order; waves come back slot-sorted
        assert plan_sweep_waves(needs, 4) == [[0, 1, 3], [2]]
        assert plan_sweep_waves(needs, 16) == [[0, 1, 2, 3]]
        assert plan_sweep_waves([], 4) == []

    def test_wave_reorder_packs_coresident_slots_together(self):
        # slot order interleaves two share-groups: legacy first-fit
        # splits every boundary (4 waves), the affinity reorder seats
        # each share-group in one wave (2) — the saved waves are saved
        # H2D/D2H round trips under the tier
        needs = [(0, 0, frozenset({1, 2})), (1, 0, frozenset({3, 4})),
                 (2, 0, frozenset({1, 2})), (3, 0, frozenset({3, 4}))]
        assert plan_sweep_waves(needs, 2) == [[0, 2], [1, 3]]
        assert plan_sweep_waves(needs, 2, reorder=False) == \
            [[0], [1], [2], [3]]
        # determinism: a replayed tick partitions identically
        assert plan_sweep_waves(needs, 2) == plan_sweep_waves(needs, 2)
        # every slot appears exactly once regardless of packing
        flat = sorted(s for w in plan_sweep_waves(needs, 2) for s in w)
        assert flat == [0, 1, 2, 3]


D = 32


def cfg_for(**kw):
    kw.setdefault("capacity_factor", 4.0)
    return TransformerConfig(
        d_model=D, n_heads=4, n_experts=4, d_ff=48, n_layers=1, **kw
    )


BASE_KW = dict(n_slots=4, n_pages=6, page_size=4, max_seq=24, vocab=16)


def engines(dims, tier_pages=16, **kw):
    """(untiered, forced-spill tiered) engine pair on one mesh."""
    cfg = cfg_for()
    n = dims[0] * dims[1]
    mesh = make_mesh(dims, ("dp", "sp"), jax.devices()[:n])
    base = ServeEngine(mesh, cfg, ServeConfig(**kw))
    tier = ServeEngine(mesh, cfg,
                       ServeConfig(**kw, kv_host_pages=tier_pages))
    return base, tier


@pytest.fixture(scope="module")
def base_plain():
    """ONE untiered fp32 drain of the plain workload — the baseline
    several gates compare against (wall discipline: compile once)."""
    cfg = cfg_for()
    mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
    return ServeEngine(mesh, cfg, ServeConfig(**BASE_KW)).run(reqs_plain())


@pytest.fixture(scope="module")
def tiered_plain():
    """ONE forced-spill fp32 drain of the plain workload: (engine,
    report), read-only for the tests that share it."""
    cfg = cfg_for()
    mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
    eng = ServeEngine(mesh, cfg, ServeConfig(**BASE_KW, kv_host_pages=16))
    return eng, eng.run(reqs_plain())


def reqs_plain(n=6):
    return [Request(rid=i, prompt=(1 + i % 3, 2, 3, 4, 5),
                    max_new=4 + i % 3) for i in range(n)]


def reqs_shared(n=6):
    return [Request(rid=i, prompt=(1, 2, 3, 4, 5, 6, 7, 8, 9 + i % 4),
                    max_new=3 + i % 3) for i in range(n)]


def reqs_periodic(n=6):
    return [Request(rid=i, prompt=(1 + i % 2, 2, 1 + i % 2, 2,
                                   1 + i % 2, 2), max_new=5)
            for i in range(n)]


class TestForcedSpillBitIdentity:
    """THE tier gate: a device pool several times smaller than the
    working set (6 pages vs ~18 pages of admitted footprint) forces
    heavy spill/prefetch, and greedy output must not move a bit."""

    def test_fp32_plain(self, base_plain, tiered_plain):
        tier, rt = tiered_plain
        assert rt.outputs == base_plain.outputs
        assert rt.spilled_pages > 0 and rt.prefetched_pages > 0
        assert rt.host_bytes == (
            (rt.spilled_pages + rt.prefetched_pages) * tier.kv_page_bytes
        )
        # drain restores BOTH tiers' capacity
        assert tier.free_pages() == [BASE_KW["n_pages"] + 16]

    def test_prefix_share_composes(self):
        base, tier = engines(
            (1, 1), **dict(BASE_KW, kv_dtype="int8", prefix_share=True)
        )
        rb = base.run(reqs_shared())
        rt = tier.run(reqs_shared())
        assert rt.outputs == rb.outputs
        assert rt.shared_tokens > 0 and rt.spilled_pages > 0
        # conservation still holds across tiers
        assert (rt.prefill_tokens + rt.shared_tokens
                == sum(len(r.prompt) for r in reqs_shared()))

    def test_chunked_prefill_composes(self):
        base, tier = engines(
            (1, 1), **dict(BASE_KW, kv_dtype="fp8", chunk_prefill=3)
        )
        rb = base.run(reqs_shared())
        rt = tier.run(reqs_shared())
        assert rt.outputs == rb.outputs and rt.spilled_pages > 0

    def test_speculative_composes(self):
        kw = dict(BASE_KW, kv_dtype="int8", spec_k=3, n_pages=8,
                  max_seq=32)
        base, tier = engines((1, 1), **kw)
        rb = base.run(reqs_periodic())
        rt = tier.run(reqs_periodic())
        assert rt.outputs == rb.outputs
        assert rt.accepted > 0 and rt.spilled_pages > 0

    def test_disagg_composes(self):
        cfg = cfg_for()
        mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        kw = dict(BASE_KW, kv_dtype="int8")
        rb = DisaggEngine(mesh, cfg, ServeConfig(**kw)).run(reqs_plain())
        eng = DisaggEngine(
            mesh, cfg, ServeConfig(**kw, kv_host_pages=16)
        )
        rt = eng.run(reqs_plain())
        assert rt.engine.outputs == rb.engine.outputs
        assert eng.engine.host_spilled_pages > 0
        assert rt.handoffs > 0               # migration ran, not degrade

    def test_2x2_mesh_composed(self):
        kw = dict(BASE_KW, kv_dtype="fp8", prefix_share=True,
                  chunk_prefill=3, n_pages=8)
        base, tier = engines((2, 2), **kw)
        rb = base.run(reqs_shared())
        rt = tier.run(reqs_shared())
        assert rt.outputs == rb.outputs
        assert tier.host_spilled_pages > 0

    def test_cold_hit_fallback_counts_and_stays_correct(
        self, base_plain, tiered_plain
    ):
        # thrash regime: the working set rotates every tick, so the
        # prefetch-ahead cannot hide everything — the synchronous path
        # must absorb the misses and count every one
        tier, rt = tiered_plain
        assert rt.outputs == base_plain.outputs
        assert rt.cold_hits > 0
        assert tier.metrics.histogram("serve/cold_hit_s").count > 0
        # a roomy tier at steady state takes no cold hits at all
        roomy = ServeEngine(
            make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1]), cfg_for(),
            ServeConfig(**dict(BASE_KW, n_pages=64), kv_host_pages=16),
        )
        rr = roomy.run(reqs_plain())
        assert rr.outputs == base_plain.outputs and rr.cold_hits == 0


class TestWarmPrefixParking:
    def test_shared_chain_survives_its_last_holder(self):
        kw = dict(BASE_KW, n_slots=2, n_pages=8, prefix_share=True)
        base, tier = engines((1, 1), **kw)
        pr = (1, 2, 3, 4, 5, 6, 7, 8)
        first = Request(rid=0, prompt=pr, max_new=3)
        second = Request(rid=1, prompt=pr + (9,), max_new=3)
        rb1, rb2 = base.run([first]), base.run([second])
        rt1 = tier.run([first])
        assert tier._allocators[0].n_parked > 0   # the chain parked
        rt2 = tier.run([second])
        assert rt1.outputs == rb1.outputs
        assert rt2.outputs == rb2.outputs
        # sharing WITHOUT a concurrently-live holder: the untiered
        # engine re-prefills everything, the tier serves the prefix
        assert rb2.shared_tokens == 0
        assert rt2.shared_tokens >= 8
        assert tier._allocators[0].parked_hits >= 2
        assert rt2.prefill_tokens < rb2.prefill_tokens

    def test_fully_aligned_parked_prompt_rescores_privately(self):
        # the second, IDENTICAL page-aligned prompt hits a fully parked
        # chain: its restore is already private, so the last-position
        # re-score needs no copy-on-write — and must not corrupt the
        # parked original (a third hit still matches)
        kw = dict(BASE_KW, n_slots=2, n_pages=8, prefix_share=True)
        base, tier = engines((1, 1), **kw)
        pr = (1, 2, 3, 4, 5, 6, 7, 8)
        for i in range(3):
            r = Request(rid=i, prompt=pr, max_new=3)
            assert tier.run([r]).outputs == base.run([r]).outputs
        assert tier._allocators[0].parked_hits >= 4


class TestSpillChaos:
    def scfg(self, **kw):
        return ServeConfig(**dict(BASE_KW, **kw))

    def test_total_outage_degrades_byte_identical(self, base_plain):
        cfg = cfg_for()
        mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        rb = base_plain
        plan = ChaosPlan(7, [Fault(site="serve/spill", p=1.0,
                                   times=None)])
        eng = ServeEngine(mesh, cfg, self.scfg(kv_host_pages=16),
                          chaos=plan)
        rt = eng.run(reqs_plain())
        assert rt.outputs == rb.outputs
        assert all(a.degraded for a in eng._allocators)
        assert rt.spilled_pages == 0         # nothing ever crossed
        assert plan.fired.get("serve/spill", 0) > 0

    def test_transient_fault_retries_and_tier_survives(self, base_plain):
        cfg = cfg_for()
        mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        rb = base_plain
        plan = ChaosPlan(7, [Fault(site="serve/spill", p=1.0, times=1)])
        eng = ServeEngine(mesh, cfg, self.scfg(kv_host_pages=16),
                          chaos=plan)
        rt = eng.run(reqs_plain())
        assert rt.outputs == rb.outputs
        assert not any(a.degraded for a in eng._allocators)
        assert rt.spilled_pages > 0          # the retry carried on
        assert plan.fired.get("serve/spill", 0) == 1


class TestTrafficLedger:
    def test_page_bytes_matches_analytic_form_and_store_record(self):
        for dtype, ebytes, srow in ((jnp.float32, 4, 0), (jnp.int8, 1, 8),
                                    (jnp.float8_e4m3fn, 1, 8)):
            cache = init_kv_cache(GEOM, dtype=dtype)
            g = GEOM
            analytic = g.n_layers * (
                2 * g.page_size * g.n_heads * g.d_head * ebytes
                + srow * g.n_heads  # 2 fp32 scale rows x 4 B when quantized
            )
            assert kv_page_bytes(cache) == analytic
            assert kv_page_bytes(cache) * g.n_pages == kv_cache_bytes(cache)
            st = HostPageStore(2, host_leaf_shapes(g, dtype))
            assert st.page_nbytes == analytic

    def test_engine_traffic_three_way_agreement(self, tiered_plain):
        # exact counters x analytic page bytes == report bytes ==
        # the store's actually-moved byte counters
        tier, rt = tiered_plain
        traffic = kv_host_traffic_bytes(
            tier._kv, tier.host_spilled_pages, tier.host_prefetched_pages
        )
        assert traffic.total_bytes == rt.host_bytes
        store = tier._allocators[0].store
        assert store.stats()["spill_bytes"] == traffic.spill_bytes
        assert store.stats()["prefetch_bytes"] == traffic.prefetch_bytes
        assert traffic.per_token(rt.tokens_generated) > 0

    def test_steady_fit_moves_zero_bytes(self):
        # everything fits the device pool: the tier must be free
        tier = ServeEngine(
            make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1]), cfg_for(),
            ServeConfig(**dict(BASE_KW, n_pages=64), kv_host_pages=16),
        )
        rt = tier.run(reqs_plain())
        assert rt.spilled_pages == 0 and rt.prefetched_pages == 0
        assert rt.host_bytes == 0.0 and rt.cold_hits == 0


class TestTieredConfig:
    def test_negative_host_pages_rejected(self):
        cfg = cfg_for()
        mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        with pytest.raises(ValueError):
            ServeEngine(mesh, cfg,
                        ServeConfig(**BASE_KW, kv_host_pages=-1))

    def test_off_by_default_builds_no_tier(self):
        cfg = cfg_for()
        mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        eng = ServeEngine(mesh, cfg, ServeConfig(**BASE_KW))
        assert not eng._tiered
        assert not hasattr(eng._allocators[0], "store")

    def test_residency_policy_validation(self):
        with pytest.raises(ValueError):
            ResidencyPolicy(pin_tail=-1)
