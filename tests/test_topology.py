"""Unit tests for the pure topology math (no devices needed).

Parity targets: MPI_Cart_coords/rank/shift round-trips (mpi10.cpp:27-42),
periodic wrap + 8-neighborhood (stencil2D.h:232-299), and the golden-output
fact that on a periodic 3x3 grid rank (0,0)'s top-left neighbor is rank 8
(stencil2d/sample-output/0_0).
"""

import pytest

from tpuscratch.runtime.topology import (
    ALL_DIRECTIONS,
    CartTopology,
    Direction,
    factor2d,
    square_grid,
)


class TestRankCoords:
    def test_roundtrip_exhaustive(self):
        topo = CartTopology((3, 4), (False, False))
        for r in topo.ranks():
            assert topo.rank_at(topo.coords(r)) == r

    def test_row_major(self):
        topo = CartTopology((2, 3))
        assert topo.coords(0) == (0, 0)
        assert topo.coords(2) == (0, 2)
        assert topo.coords(3) == (1, 0)
        assert topo.coords(5) == (1, 2)

    def test_3d(self):
        topo = CartTopology((2, 3, 4))
        assert topo.size == 24
        for r in topo.ranks():
            assert topo.rank_at(topo.coords(r)) == r

    def test_invalid(self):
        with pytest.raises(ValueError):
            CartTopology(())
        with pytest.raises(ValueError):
            CartTopology((2, 0))
        with pytest.raises(ValueError):
            CartTopology((2, 2), (True,))
        with pytest.raises(ValueError):
            CartTopology((4,)).coords(4)


class TestNeighbors:
    def test_open_boundary_is_none(self):
        # mpi5/mpi10 semantics: off-grid neighbor = MPI_PROC_NULL
        topo = CartTopology((3, 3), (False, False))
        assert topo.neighbor(0, Direction.TOP) is None
        assert topo.neighbor(0, Direction.LEFT) is None
        assert topo.neighbor(8, Direction.BOTTOM_RIGHT) is None
        assert topo.neighbor(4, Direction.TOP) == 1

    def test_periodic_wrap_corners(self):
        # Golden-output oracle: on periodic 3x3, rank 0's 8-neighborhood
        # wraps so its TOP_LEFT neighbor is rank 8 (sample-output/0_0).
        topo = square_grid(9, periodic=True)
        n = topo.neighbors8(0)
        assert n[Direction.TOP_LEFT] == 8
        assert n[Direction.TOP] == 6
        assert n[Direction.TOP_RIGHT] == 7
        assert n[Direction.LEFT] == 2
        assert n[Direction.RIGHT] == 1
        assert n[Direction.BOTTOM_LEFT] == 5
        assert n[Direction.BOTTOM] == 3
        assert n[Direction.BOTTOM_RIGHT] == 4

    def test_center_rank_neighbors(self):
        # sample-output/1_1: rank 4 (center) sees 0..8 minus itself
        topo = square_grid(9, periodic=True)
        n = topo.neighbors8(4)
        assert sorted(v for v in n.values()) == [0, 1, 2, 3, 5, 6, 7, 8]

    def test_shift_matches_mpi_cart_shift(self):
        topo = CartTopology((3, 3), (False, False))
        # rank 4 center: shifting along rows by +1 -> source above, dest below
        src, dst = topo.shift(4, axis=0, disp=1)
        assert (src, dst) == (1, 7)
        # open boundary: rank 0 shifted along cols by -1 has no dest
        src, dst = topo.shift(0, axis=1, disp=-1)
        assert dst is None and src == 1

    def test_opposite(self):
        for d in ALL_DIRECTIONS:
            assert d.opposite.opposite is d
        assert Direction.TOP_LEFT.opposite is Direction.BOTTOM_RIGHT


class TestPermutations:
    def test_ring_is_full_cycle(self):
        topo = CartTopology((8,), (True,))
        perm = topo.ring_permutation(0, 1)
        assert sorted(perm) == [(i, (i + 1) % 8) for i in range(8)]

    def test_open_ring_drops_boundary(self):
        # mpi5 semantics: non-periodic 1D, endpoints skip the missing side
        topo = CartTopology((4,), (False,))
        perm = topo.send_permutation((1,))
        assert perm == [(0, 1), (1, 2), (2, 3)]

    def test_diagonal_permutation_is_single_hop(self):
        topo = square_grid(9, periodic=True)
        perm = dict(topo.send_permutation(Direction.BOTTOM_RIGHT))
        # every rank sends somewhere; bijection on periodic grids
        assert len(perm) == 9
        assert sorted(perm.values()) == list(range(9))
        assert perm[0] == 4
        assert perm[8] == 0

    def test_permutation_srcs_and_dsts_unique(self):
        topo = CartTopology((2, 4), (True, True))
        for d in ALL_DIRECTIONS:
            pairs = topo.send_permutation(d)
            srcs = [s for s, _ in pairs]
            dsts = [t for _, t in pairs]
            assert len(set(srcs)) == len(srcs)
            assert len(set(dsts)) == len(dsts)


class TestFactor2D:
    def test_square(self):
        assert factor2d(16) == (4, 4)

    def test_mostly_square(self):
        assert factor2d(8) == (2, 4)
        assert factor2d(12) == (3, 4)

    def test_prime(self):
        assert factor2d(7) == (1, 7)

    def test_square_grid_rejects_nonsquare(self):
        with pytest.raises(ValueError):
            square_grid(8)


class TestGridString:
    def test_3x3(self):
        topo = square_grid(9)
        assert topo.grid_string() == "0 1 2\n3 4 5\n6 7 8"
