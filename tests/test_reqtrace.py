"""Fleet-wide request tracing (ISSUE 20): causal span trees with an
EXACT per-request latency decomposition.

The correctness anchors:
- the per-request goodput law: every collected ``RequestTrace``'s
  bucket decomposition (queue, shed_wait, prefill, handoff, decode,
  waste, other) sums to its end-to-end wall EXACTLY
  (``RequestTrace.check``), under overlap clipping, failed legs,
  shed->retry resubmits, kill->re-admission, and handoff degrade;
- observes-never-perturbs: a fully traced fleet drain (chaos
  included) emits BIT-identical outputs to the untraced drain;
- chaos lineage (the satellite): a rack-kill victim's trace carries
  the kill mark, the evacuation/re-admission wait (waste), and the
  re-prefill leg on a fresh attempt — decomposition still exact;
  shed->retry->complete chains link attempts across resubmits;
- the Perfetto export: the span forest passes the EXTENDED
  ``validate_chrome_trace`` (async b/e roots, s/f flow chains with
  pid+tid on every step), plus the golden-schema subprocess proof;
- seeded per-rid sampling is a pure function of (rid, rate, salt),
  and the rotating sink bounds the JSONL artifact's disk footprint;
- the config-22 regress directions (``decomp_*`` lower via
  _LOWER_FIRST so a tenant class named "throughput" cannot invert
  its buckets) and the clean-pair-0 / injected-1 subprocess proof.

The fleet tests reuse test_traffic's compile-light shapes (same
cfg/scfg values -> same jit cache entries within a tier-1 run)."""

import dataclasses
import json
import os
import subprocess
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from tpuscratch.ft.chaos import ChaosPlan, Fault, bind_tracer
from tpuscratch.models.transformer import TransformerConfig
from tpuscratch.obs import regress
from tpuscratch.obs.report import (
    decompose,
    load_events,
    request_waterfall,
    summarize,
)
from tpuscratch.obs.reqtrace import (
    REQ_BUCKETS,
    NullReqTracer,
    ReqTracer,
    RequestTrace,
    rid_sampled,
)
from tpuscratch.obs.sink import Sink
from tpuscratch.obs.trace import validate_chrome_trace
from tpuscratch.runtime.mesh import make_mesh
from tpuscratch.serve import (
    DisaggEngine,
    FleetRouter,
    Request,
    RouterConfig,
    SLOClass,
    ServeConfig,
    ServeEngine,
)
from tpuscratch.serve.decode import macro_occupancy

pytestmark = pytest.mark.reqtrace

D = 32


def cfg_for(**kw):
    kw.setdefault("capacity_factor", 4.0)
    return TransformerConfig(
        d_model=D, n_heads=4, n_experts=4, d_ff=48, n_layers=1, **kw
    )


def scfg_for(**kw):
    kw.setdefault("n_slots", 4)
    kw.setdefault("n_pages", 16)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_seq", 24)
    kw.setdefault("vocab", 16)
    kw.setdefault("prefix_share", True)
    return ServeConfig(**kw)


def mesh_for(dims=(1, 1)):
    return make_mesh(dims, ("dp", "sp"),
                     jax.devices()[: dims[0] * dims[1]])


def tenant_requests(n=6, max_new=3):
    pre = {0: (1, 2, 3, 4, 5, 6, 7, 8, 9), 1: (9, 8, 7, 6, 5, 4, 3, 2, 1)}
    return [
        Request(rid=i, prompt=pre[i % 2] + (10 + i % 5,), max_new=max_new)
        for i in range(n)
    ]


def tagged(n=10, max_new=3):
    return [("latency" if i % 3 else "batch", r)
            for i, r in enumerate(tenant_requests(n, max_new))]


def fleet(n=3, rcfg=None, chaos=None, tracer=None, disagg=False,
          **scfg_kw):
    cfg, scfg = cfg_for(), scfg_for(**scfg_kw)
    mesh = mesh_for()
    cls = DisaggEngine if disagg else ServeEngine
    return FleetRouter([cls(mesh, cfg, scfg) for _ in range(n)],
                       rcfg=rcfg, chaos=chaos, tracer=tracer)


TWO_CLASSES = RouterConfig(classes=(SLOClass("latency", target="ttft"),
                                    SLOClass("batch")))


def buckets_of(tr):
    return {b: tr.buckets.get(b, 0.0) for b in REQ_BUCKETS}


class TestSampling:
    def test_pure_function_of_rid(self):
        for rid in range(64):
            assert rid_sampled(rid, 0.3) == rid_sampled(rid, 0.3)
        assert all(rid_sampled(r, 1.0) for r in range(100))
        assert not any(rid_sampled(r, 0.0) for r in range(100))

    def test_rate_is_approximately_honored(self):
        n = 4000
        hit = sum(rid_sampled(r, 0.25) for r in range(n))
        assert 0.18 < hit / n < 0.32

    def test_salt_reshuffles_selection(self):
        a = [rid_sampled(r, 0.5, salt=0) for r in range(256)]
        b = [rid_sampled(r, 0.5, salt=1) for r in range(256)]
        assert a != b

    def test_tracer_skips_unsampled_rids(self):
        tr = ReqTracer(sample_rate=0.5, salt=3)
        for rid in range(40):
            tr.begin(rid, 0.0, cls="x")
            tr.finish(rid, 1.0)
        got = {t.rid for t in tr.collect()}
        want = {r for r in range(40) if rid_sampled(r, 0.5, salt=3)}
        assert got == want and 0 < len(got) < 40

    def test_validates_rate(self):
        with pytest.raises(ValueError):
            ReqTracer(sample_rate=1.5)
        with pytest.raises(ValueError):
            ReqTracer(sample_rate=-0.1)

    def test_null_tracer_is_inert(self):
        nt = NullReqTracer()
        assert not nt.enabled and not nt.sampled(1)
        nt.begin(1, 0.0)
        nt.work(1, "prefill", 0.0, 1.0)
        nt.finish(1, 2.0)
        assert nt.collect() == []


class TestExactDecomposition:
    """Pure-tracer laws on synthetic stamps: the cursor-clipping
    attribution sweep makes the buckets sum to the wall by
    construction — ``other`` is the exact remainder."""

    def _collect_one(self, tr, rid):
        got = {t.rid: t for t in tr.collect()}
        t = got[rid]
        t.check()
        return t

    def test_simple_lifecycle(self):
        tr = ReqTracer()
        tr.begin(1, 0.0, cls="latency")
        tr.work(1, "prefill", 1.0, 2.0, tokens=8)
        tr.mark(1, "first_token", 2.0)
        tr.work(1, "decode", 2.0, 3.5)
        tr.finish(1, 4.0)
        t = self._collect_one(tr, 1)
        b = buckets_of(t)
        assert b["queue"] == pytest.approx(1.0)
        assert b["prefill"] == pytest.approx(1.0)
        assert b["decode"] == pytest.approx(1.5)
        assert b["other"] == pytest.approx(0.5)  # exact remainder
        assert b["shed_wait"] == b["handoff"] == b["waste"] == 0.0
        assert t.e2e_s == pytest.approx(4.0)
        assert t.ttft_s == pytest.approx(2.0)
        assert t.attempts == 1 and t.killed == ()
        assert sum(b.values()) == pytest.approx(t.e2e_s)

    def test_overlapping_claims_are_clipped_disjoint(self):
        tr = ReqTracer()
        tr.begin(2, 0.0)
        tr.work(2, "prefill", 1.0, 2.0)
        tr.work(2, "decode", 2.0, 3.0)
        tr.work(2, "decode", 2.5, 3.5)  # overlaps the previous claim
        tr.finish(2, 4.0)
        t = self._collect_one(tr, 2)
        b = buckets_of(t)
        assert b["decode"] == pytest.approx(1.5)  # NOT 2.0
        assert sum(b.values()) == pytest.approx(4.0)
        # segments are disjoint and time-ordered
        segs = [(s, e) for _a, _b, s, e in t.segments]
        for (s0, e0), (s1, e1) in zip(segs, segs[1:]):
            assert e0 <= s1

    def test_shed_retry_complete_chain(self):
        tr = ReqTracer()
        tr.begin(7, 0.0, cls="latency")
        tr.shed(7, 2.0, reason="deadline")
        tr.begin(7, 5.0)          # the closed-loop resubmit
        tr.work(7, "prefill", 6.0, 7.0)
        tr.finish(7, 8.0)
        t = self._collect_one(tr, 7)
        b = buckets_of(t)
        # pre-shed queue wait AND the shed->resubmit gap both charge
        # shed_wait: 2.0 + 3.0
        assert b["shed_wait"] == pytest.approx(5.0)
        assert b["queue"] == pytest.approx(1.0)   # resubmit->prefill
        assert b["prefill"] == pytest.approx(1.0)
        assert b["other"] == pytest.approx(1.0)
        assert t.attempts == 2
        assert [k for k, _t, _a in t.marks if k == "shed"] == ["shed"]
        # the shed mark carries its reason
        assert any(k == "shed" and (a or {}).get("reason") == "deadline"
                   for k, _t, a in t.marks)
        # the retry's prefill rides the SECOND attempt
        assert any(a == 1 and bk == "prefill"
                   for a, bk, _s, _e in t.segments)

    def test_kill_readmit_lineage(self):
        tr = ReqTracer()
        tr.begin(3, 0.0, cls="batch")
        tr.work(3, "prefill", 1.0, 2.0)
        tr.killed(3, 3.0, lost_tokens=2)
        tr.work(3, "prefill", 4.0, 5.0)   # the re-prefill leg
        tr.work(3, "decode", 5.0, 6.0)
        tr.finish(3, 6.0)
        t = self._collect_one(tr, 3)
        b = buckets_of(t)
        # waste = the killed attempt's prefill (1.0) + the
        # kill->re-prefill re-admission wait (1.0)
        assert b["waste"] == pytest.approx(2.0)
        assert b["prefill"] == pytest.approx(1.0)  # surviving leg only
        assert b["decode"] == pytest.approx(1.0)
        assert b["queue"] == pytest.approx(1.0)
        assert b["other"] == pytest.approx(1.0)    # prefill-end -> kill
        assert t.killed == (0,) and t.attempts == 2
        assert "kill" in [k for k, _t, _a in t.marks]
        assert any(a == 1 and bk == "prefill"
                   for a, bk, _s, _e in t.segments)
        assert sum(b.values()) == pytest.approx(t.e2e_s)

    def test_killed_idempotent_per_attempt(self):
        tr = ReqTracer()
        tr.begin(4, 0.0)
        tr.killed(4, 1.0)
        tr.killed(4, 1.5)  # same attempt observed by a second layer
        tr.work(4, "prefill", 2.0, 3.0)
        tr.finish(4, 3.0)
        t = self._collect_one(tr, 4)
        assert [k for k, _t, _a in t.marks].count("kill") == 1
        assert t.attempts == 2 and t.killed == (0,)

    def test_failed_work_is_waste(self):
        tr = ReqTracer()
        tr.begin(5, 0.0)
        tr.work(5, "handoff", 1.0, 2.0, failed=True, try_n=1)
        tr.work(5, "handoff", 2.0, 3.0, try_n=2)
        tr.finish(5, 3.0)
        t = self._collect_one(tr, 5)
        b = buckets_of(t)
        assert b["waste"] == pytest.approx(1.0)
        assert b["handoff"] == pytest.approx(1.0)

    def test_degrade_retags_the_attempt(self):
        tr = ReqTracer()
        tr.begin(9, 0.0)
        tr.work(9, "handoff", 1.0, 2.0, failed=True)
        tr.degrade(9, 2.5)
        tr.work(9, "prefill", 3.0, 4.0)   # local monolithic re-prefill
        tr.work(9, "decode", 4.0, 5.0)
        tr.finish(9, 5.0)
        t = self._collect_one(tr, 9)
        b = buckets_of(t)
        # failed handoff (1.0) + degrade->re-prefill wait (0.5)
        assert b["waste"] == pytest.approx(1.5)
        assert b["prefill"] == pytest.approx(1.0)
        assert b["decode"] == pytest.approx(1.0)
        assert b["other"] == pytest.approx(0.5)
        assert t.attempts == 2
        assert "degrade" in [k for k, _t, _a in t.marks]

    def test_work_batch_traces_every_rid(self):
        tr = ReqTracer()
        for rid in (1, 2, 3):
            tr.begin(rid, 0.0)
        tr.work_batch((1, 2, 3), "decode", 1.0, 2.0)
        for rid in (1, 2, 3):
            tr.finish(rid, 2.0)
        for t in tr.collect():
            t.check()
            assert buckets_of(t)["decode"] == pytest.approx(1.0)

    def test_check_rejects_broken_decomposition(self):
        with pytest.raises(ValueError, match="buckets sum"):
            RequestTrace(1, "x", 0.0, 2.0, "finished", 1, (),
                         {"queue": 1.0}, (), ()).check()
        with pytest.raises(ValueError, match="negative bucket"):
            RequestTrace(1, "x", 0.0, 2.0, "finished", 1, (),
                         {"queue": -0.5, "other": 2.5}, (), ()).check()

    def test_decomposition_stats_per_class(self):
        tr = ReqTracer()
        for rid, cls in ((1, "latency"), (2, "latency"), (3, "batch")):
            tr.begin(rid, 0.0, cls=cls)
            tr.work(rid, "prefill", 1.0, 2.0)
            tr.finish(rid, 2.0)
        tr.collect()
        d = tr.decomposition()
        assert set(d) == {"latency", "batch"}
        st = d["latency"]["queue"]
        assert st["count"] == 2
        assert st["mean"] == pytest.approx(1.0)
        assert st["p50"] == pytest.approx(1.0)
        assert d["latency"]["e2e"]["mean"] == pytest.approx(2.0)


class TestChromeExport:
    def _lineage_tracer(self):
        tr = ReqTracer()
        tr.begin(1, 0.0, cls="latency")
        tr.shed(1, 1.0, reason="deadline")
        tr.begin(1, 2.0)
        tr.work(1, "prefill", 3.0, 4.0)
        tr.killed(1, 5.0)
        tr.work(1, "prefill", 6.0, 7.0)
        tr.mark(1, "first_token", 7.0)
        tr.work(1, "decode", 7.0, 8.0)
        tr.finish(1, 9.0)
        tr.begin(2, 0.5, cls="batch")
        tr.work(2, "decode", 1.5, 2.5)
        tr.finish(2, 3.0)
        tr.collect()
        return tr

    def test_export_passes_extended_validator(self):
        trace = self._lineage_tracer().chrome_trace(pid=3)
        assert json.loads(json.dumps(trace)) == trace  # round-trips
        validate_chrome_trace(trace)
        evs = trace["traceEvents"]
        phs = [e["ph"] for e in evs]
        # async roots, stack segments, instant marks, flow edges
        assert phs.count("b") == 2 and phs.count("e") == 2
        assert phs.count("B") == phs.count("E") >= 4
        assert "i" in phs
        # one s->f flow per attempt transition (shed + kill for rid 1)
        flows = [e for e in evs if e["ph"] in ("s", "f")]
        assert {e["name"] for e in flows} == {"shed", "kill"}
        assert {e["id"] for e in flows} == {"1.0", "1.1"}
        for e in flows:
            assert e.get("pid") is not None and e.get("tid") is not None
        # one lane (tid) per rid; the root carries the outcome
        roots = {e["id"]: e for e in evs if e["ph"] == "b"}
        assert roots[1]["args"]["attempts"] == 3
        assert roots[2]["tid"] == 2

    def test_empty_tracer_exports_meta_only(self):
        trace = ReqTracer().chrome_trace()
        validate_chrome_trace(trace)
        assert [e["ph"] for e in trace["traceEvents"]] == ["M"]

    def test_validator_rejects_broken_flow_chains(self):
        trace = self._lineage_tracer().chrome_trace()
        evs = trace["traceEvents"]
        # a flow started but never finished
        no_f = [e for e in evs if not (e["ph"] == "f"
                                       and e["id"] == "1.0")]
        with pytest.raises(ValueError, match="unterminated flow"):
            validate_chrome_trace(dict(trace, traceEvents=no_f))
        # a finish without its start
        no_s = [e for e in evs if not (e["ph"] == "s"
                                       and e["id"] == "1.0")]
        with pytest.raises(ValueError, match="without open s"):
            validate_chrome_trace(dict(trace, traceEvents=no_s))
        # a flow step missing its lane anchor
        naked = [dict(e) for e in evs]
        for e in naked:
            if e["ph"] == "s":
                del e["tid"]
                break
        with pytest.raises(ValueError, match="without pid/tid"):
            validate_chrome_trace(dict(trace, traceEvents=naked))

    def test_validator_rejects_unclosed_async_root(self):
        trace = self._lineage_tracer().chrome_trace()
        evs = [e for e in trace["traceEvents"]
               if not (e["ph"] == "e" and e.get("id") == 2)]
        with pytest.raises(ValueError, match="unclosed async"):
            validate_chrome_trace(dict(trace, traceEvents=evs))

    def test_golden_schema_subprocess_proof(self, tmp_path):
        """The acceptance gate as a subprocess: the exported span
        forest validates from a cold interpreter; a corrupted copy is
        rejected nonzero."""
        good = tmp_path / "good.json"
        good.write_text(json.dumps(self._lineage_tracer().chrome_trace()))
        bad_trace = self._lineage_tracer().chrome_trace()
        bad_trace["traceEvents"] = [
            e for e in bad_trace["traceEvents"] if e["ph"] != "E"
        ]
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps(bad_trace))
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        prog = ("import json, sys; "
                "from tpuscratch.obs.trace import validate_chrome_trace; "
                "validate_chrome_trace(json.load(open(sys.argv[1])))")
        r = subprocess.run([sys.executable, "-c", prog, str(good)],
                           capture_output=True, text=True, env=env,
                           timeout=120)
        assert r.returncode == 0, r.stdout + r.stderr
        r = subprocess.run([sys.executable, "-c", prog, str(bad)],
                           capture_output=True, text=True, env=env,
                           timeout=120)
        assert r.returncode != 0
        assert "unclosed" in r.stderr


class TestEngineLineage:
    def test_engine_drain_traces_every_request(self):
        tracer = ReqTracer()
        eng = ServeEngine(mesh_for(), cfg_for(), scfg_for(),
                          tracer=tracer)
        reqs = tenant_requests()
        rep = eng.run(reqs)
        assert rep.completed == len(reqs)
        tracer.collect()
        assert set(tracer.traces) == {r.rid for r in reqs}
        for t in tracer.traces.values():
            t.check()
            b = buckets_of(t)
            assert b["prefill"] > 0 and b["decode"] > 0
            assert b["waste"] == 0.0 and b["shed_wait"] == 0.0
            assert t.outcome == "finished" and t.attempts == 1
            assert t.ttft_s is not None and 0 < t.ttft_s <= t.e2e_s
        validate_chrome_trace(tracer.chrome_trace())

    def test_traced_engine_output_identical(self):
        reqs = tenant_requests()
        base = ServeEngine(mesh_for(), cfg_for(), scfg_for()).run(reqs)
        rep = ServeEngine(mesh_for(), cfg_for(), scfg_for(),
                          tracer=ReqTracer()).run(reqs)
        assert rep.outputs == base.outputs

    def test_disagg_handoff_spans(self):
        tracer = ReqTracer()
        d = DisaggEngine(mesh_for(), cfg_for(),
                         scfg_for(prefix_share=False), tracer=tracer)
        reqs = tenant_requests()
        rep = d.run(reqs)
        assert rep.handoffs > 0
        tracer.collect()
        assert set(tracer.traces) == {r.rid for r in reqs}
        for t in tracer.traces.values():
            t.check()
            b = buckets_of(t)
            assert b["prefill"] > 0 and b["handoff"] > 0

    def test_disagg_degrade_lineage(self):
        """A never-healing serve/handoff fault for ONE rid: its trace
        carries the chaos fault marks, the degrade edge, the wasted
        staged/handoff legs, and still sums exactly."""
        tracer = ReqTracer()
        plan = ChaosPlan(0, [Fault(site="serve/handoff", key=2, p=1.0,
                                   times=None)])
        d = DisaggEngine(mesh_for(), cfg_for(),
                         scfg_for(prefix_share=False), chaos=plan,
                         tracer=tracer)
        rep = d.run(tenant_requests())
        assert rep.degraded == 1
        tracer.collect()
        t = tracer.traces[2]
        t.check()
        kinds = [k for k, _t, _a in t.marks]
        assert "degrade" in kinds and "fault" in kinds
        assert t.attempts >= 2
        assert buckets_of(t)["waste"] > 0
        # the post-degrade attempt re-prefilled locally
        assert any(a >= 1 and bk == "prefill"
                   for a, bk, _s, _e in t.segments)
        # everyone else was untouched
        for rid, tr in tracer.traces.items():
            if rid != 2:
                assert tr.attempts == 1 and buckets_of(tr)["waste"] == 0

    def test_bind_tracer_respects_existing_and_disabled(self):
        plan = ChaosPlan(0, [])
        bind_tracer(plan, NullReqTracer())
        assert plan.tracer is None          # disabled never binds
        tr = ReqTracer()
        bind_tracer(plan, tr)
        assert plan.tracer is tr
        bind_tracer(plan, ReqTracer())
        assert plan.tracer is tr            # first binding wins

    def test_macro_occupancy_helper(self):
        mask = np.array([[True, False], [True, False], [False, False]])
        rounds, occ = macro_occupancy(mask)
        assert rounds == 2
        assert occ.tolist() == [2, 0]


class TestFleetLineage:
    KILL = dict(site="serve/replica", at=(1,), key=0, kind="kill",
                down_ticks=4)

    def test_traced_chaos_drain_bit_identical(self):
        """Observes-never-perturbs, live: the fully traced chaos drain
        emits exactly the untraced drain's tokens."""
        plan = lambda: ChaosPlan(seed=11, faults=(Fault(**self.KILL),))
        base = fleet(3, rcfg=TWO_CLASSES, chaos=plan()).run(tagged())
        tracer = ReqTracer()
        rep = fleet(3, rcfg=TWO_CLASSES, chaos=plan(),
                    tracer=tracer).run(tagged())
        assert rep.outputs == base.outputs
        assert rep.kills == 1 and rep.readmitted > 0
        tracer.collect()
        assert len(tracer.traces) == len(tagged())
        for t in tracer.traces.values():
            t.check()

    def test_rack_kill_victim_lineage(self):
        """ISSUE 20 satellite: the kill victim's trace carries the
        kill, the evacuation/re-admission wait, and the re-prefill
        span — and its decomposition still sums exactly."""
        tracer = ReqTracer()
        plan = ChaosPlan(seed=11, faults=(Fault(**self.KILL),))
        router = fleet(3, rcfg=TWO_CLASSES, chaos=plan, tracer=tracer)
        rep = router.run(tagged())
        assert rep.kills == 1 and rep.readmitted > 0
        tracer.collect()
        victims = [t for t in tracer.traces.values() if t.killed]
        assert victims
        for t in victims:
            t.check()
            kinds = [k for k, _t, _a in t.marks]
            assert "kill" in kinds and "dispatch" in kinds
            assert t.attempts >= 2
            b = buckets_of(t)
            # the evacuated leg + re-admission wait charge waste
            assert b["waste"] > 0
            # the re-prefill leg rides a post-kill attempt
            assert any(a > max(t.killed) and bk == "prefill"
                       for a, bk, _s, _e in t.segments)
            assert t.outcome == "finished"
        # the survivors paid nothing
        clean = [t for t in tracer.traces.values() if not t.killed]
        assert all(buckets_of(t)["waste"] == 0.0 for t in clean)
        validate_chrome_trace(tracer.chrome_trace())

    def test_shed_retry_complete_links_across_resubmits(self):
        """A deadline-shed request resubmitted by its client completes
        with ONE trace spanning both attempts: the shed mark, the
        charged shed_wait, and the retry flow edge in the export."""
        tracer = ReqTracer()
        rcfg = RouterConfig(
            classes=(SLOClass("latency", target="ttft", max_queue=1,
                              shed_after_s=2.0),),
            tick_s=1.0,
        )
        router = fleet(1, rcfg=rcfg, tracer=tracer)
        reqs = tenant_requests(3, max_new=6)
        by_rid = {r.rid: r for r in reqs}
        pending = [("latency", r) for r in reqs]
        done, shed_rids = 0, set()
        for _round in range(8):
            rep = router.run(pending)
            done += rep.completed
            shed = router.take_shed()
            if not shed:
                break
            shed_rids |= {s.rid for s in shed}
            pending = [("latency", by_rid[s.rid]) for s in shed]
        assert done == len(reqs) and shed_rids
        tracer.collect()
        for rid in shed_rids:
            t = tracer.traces[rid]
            t.check()
            assert t.outcome == "finished"
            assert t.attempts >= 2
            assert "shed" in [k for k, _t, _a in t.marks]
            assert buckets_of(t)["shed_wait"] > 0
        trace = tracer.chrome_trace()
        validate_chrome_trace(trace)
        rid = min(shed_rids)
        assert any(e["ph"] == "s" and e["name"] == "shed"
                   and str(e["id"]).startswith(f"{rid}.")
                   for e in trace["traceEvents"])

    def test_sampled_fleet_traces_subset_only(self):
        tracer = ReqTracer(sample_rate=0.5, salt=10)
        rep = fleet(2, rcfg=TWO_CLASSES, tracer=tracer).run(tagged())
        assert rep.completed == len(tagged())
        tracer.collect()
        want = {r.rid for _c, r in tagged()
                if rid_sampled(r.rid, 0.5, salt=10)}
        assert set(tracer.traces) == want
        assert 0 < len(want) < len(tagged())
        for t in tracer.traces.values():
            t.check()


class TestSinkRotation:
    def test_rotation_bounds_disk_and_keeps_run_lines(self, tmp_path):
        p = str(tmp_path / "ev.jsonl")
        s = Sink(p, run={"job": "rot"}, flush_every=1,
                 rotate_bytes=400, max_segments=3)
        for i in range(200):
            s.emit("x", i=i, pad="p" * 40)
        s.close()
        assert s.rotations > 3
        segs = [f"{p}.{i}" for i in (1, 2, 3)]
        assert all(os.path.exists(q) for q in segs)
        assert not os.path.exists(f"{p}.4")  # oldest dropped
        for q in segs + [p]:
            with open(q) as f:
                first = json.loads(f.readline())
            assert first["event"] == "run" and first["job"] == "rot"
        # newest rotated segment holds the newest rotated data
        with open(f"{p}.1") as f:
            rows = [json.loads(x) for x in f][1:]
        with open(f"{p}.3") as f:
            older = [json.loads(x) for x in f][1:]
        assert rows[0]["i"] > older[0]["i"]
        total = sum(os.path.getsize(q) for q in segs + [p])
        assert total <= 4 * (400 + 120)  # (max_segments+1) segments

    def test_rotation_off_by_default(self, tmp_path):
        p = str(tmp_path / "ev.jsonl")
        s = Sink(p, flush_every=1)
        for i in range(500):
            s.emit("x", i=i)
        s.close()
        assert s.rotations == 0 and not os.path.exists(f"{p}.1")

    def test_max_segments_clamped_to_one(self, tmp_path):
        p = str(tmp_path / "e.jsonl")
        s = Sink(p, flush_every=1, rotate_bytes=200, max_segments=0)
        for i in range(100):
            s.emit("x", i=i)
        s.close()
        assert s.rotations > 1
        assert os.path.exists(f"{p}.1") and not os.path.exists(f"{p}.2")


class TestReportDecomposition:
    def _sinked_run(self, path):
        s = Sink(str(path), run={"job": "rt"})
        tr = ReqTracer(sink=s)
        tr.begin(5, 0.0, cls="latency")
        tr.work(5, "prefill", 1.0, 2.0, tokens=8)
        tr.mark(5, "first_token", 2.0)
        tr.work(5, "decode", 2.0, 3.0)
        tr.finish(5, 4.0)
        tr.begin(6, 0.5, cls="batch")
        tr.work(6, "decode", 1.0, 3.0)
        tr.finish(6, 3.0)
        tr.collect()
        s.close()

    def test_decompose_and_summary_table(self, tmp_path):
        p = tmp_path / "run.jsonl"
        self._sinked_run(p)
        events = load_events([str(p)])
        d = decompose(events)
        assert set(d) == {"latency", "batch"}
        assert d["latency"]["prefill_s"]["mean"] == pytest.approx(1.0)
        assert d["latency"]["e2e_s"]["mean"] == pytest.approx(4.0)
        out = summarize(events)
        assert out["decomposition"]["batch"]["decode_s"]["count"] == 1

    def test_waterfall_is_exact(self, tmp_path):
        p = tmp_path / "run.jsonl"
        self._sinked_run(p)
        events = load_events([str(p)])
        text = request_waterfall(events, 5)
        assert "request 5" in text and "latency" in text
        assert "prefill" in text and "decode" in text
        assert "exact" in text and "BROKEN" not in text
        assert "no reqtrace/request event" in request_waterfall(events,
                                                                99)

    def test_cli_request_flag(self, tmp_path):
        p = tmp_path / "run.jsonl"
        self._sinked_run(p)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-m", "tpuscratch.obs.report", str(p),
             "--request", "5"],
            capture_output=True, text=True, env=env, timeout=120,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        assert "request 5" in r.stdout and "exact" in r.stdout


class TestConfig22Regress:
    ROW = {
        "config": 22, "metric": "request_trace_decomposition",
        "value": 41.8, "tokens_per_s_untraced": 42.4,
        "trace_overhead_frac": 0.011, "n_traces": 96,
        "waste_traces": 35, "kills": 2, "readmitted": 8,
        "requests": 96, "replicas": 3, "ticks": 44,
        "wall_s_traced": 6.41, "wall_s_untraced": 6.33,
        "decomp_queue_s_latency": 0.021, "decomp_shed_wait_s_latency": 0.0,
        "decomp_prefill_s_latency": 0.105, "decomp_handoff_s_latency": 0.0,
        "decomp_decode_s_latency": 0.388, "decomp_waste_s_latency": 0.033,
        "decomp_other_s_latency": 0.061, "decomp_queue_s_batch": 0.030,
        "decomp_shed_wait_s_batch": 0.0, "decomp_prefill_s_batch": 0.117,
        "decomp_handoff_s_batch": 0.0, "decomp_decode_s_batch": 0.401,
        "decomp_waste_s_batch": 0.050, "decomp_other_s_batch": 0.066,
        "platform": "cpu",
    }

    def test_field_directions(self):
        for name in ("decomp_queue_s_latency", "decomp_waste_s_batch",
                     "decomp_handoff_s_batch", "decomp_other_s_latency",
                     # _LOWER_FIRST: a tenant class named "throughput"
                     # must not drag its buckets into _HIGHER
                     "decomp_decode_s_throughput"):
            assert regress.direction(name) == "lower", name
        for name in ("tokens_per_s_untraced", "readmitted"):
            assert regress.direction(name) == "higher", name
        for name in ("n_traces", "waste_traces", "ticks", "kills",
                     "requests", "replicas", "wall_s_traced",
                     "wall_s_untraced", "trace_overhead_frac"):
            assert name in regress._SKIP, name
        # the headline rides the untraced-rate gate plus the in-config
        # hard gates (digest identity, overhead < 2%) — its own name
        # carries no direction
        assert regress.direction("request_trace_decomposition") is None
        # bucket means sit on the wall-clock noise floor
        assert regress.noise_floor("decomp_waste_s_latency") >= 0.5

    def test_canned_row_gates(self):
        base = regress.index_rows([self.ROW])
        ok = regress.index_rows([dict(
            self.ROW, tokens_per_s_untraced=43.0,
            decomp_waste_s_latency=0.040,   # inside the 55% floor
        )])
        assert not regress.has_regression(
            regress.compare(base, ok, noise=0.1)
        )
        bad = regress.index_rows([dict(
            self.ROW, decomp_waste_s_latency=0.20,   # 6x the base
            decomp_shed_wait_s_latency=0.05,         # zero-base gate
            tokens_per_s_untraced=20.0,
        )])
        bad_fields = {(f.metric, f.field) for f in
                      regress.compare(base, bad, noise=0.1)
                      if f.status == "regressed"}
        m = "request_trace_decomposition"
        assert (m, "decomp_waste_s_latency") in bad_fields
        assert (m, "decomp_shed_wait_s_latency") in bad_fields
        assert (m, "tokens_per_s_untraced") in bad_fields
        # walls/shape/overhead are context, never gated
        wild = regress.index_rows([dict(self.ROW, wall_s_traced=500.0,
                                        trace_overhead_frac=0.9,
                                        n_traces=1)])
        assert not regress.has_regression(
            regress.compare(base, wild, noise=0.1)
        )

    def test_cli_subprocess_proof(self, tmp_path):
        """The acceptance gate as a subprocess: config-22 clean pair
        exits 0, injected waste-bucket/throughput regression exits 1."""

        def write(name, rows):
            p = str(tmp_path / name)
            with open(p, "w") as f:
                for r in rows:
                    f.write(json.dumps(r) + "\n")
            return p

        base = write("base.json", [self.ROW])
        good = write("good.json", [dict(self.ROW, value=42.3,
                                        decomp_decode_s_latency=0.41)])
        bad = write("bad.json", [dict(self.ROW,
                                      decomp_waste_s_latency=0.25,
                                      tokens_per_s_untraced=19.0)])
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-m", "tpuscratch.obs.regress", base, good],
            capture_output=True, text=True, env=env, timeout=120,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        r = subprocess.run(
            [sys.executable, "-m", "tpuscratch.obs.regress", base, bad],
            capture_output=True, text=True, env=env, timeout=120,
        )
        assert r.returncode == 1, r.stdout + r.stderr
        assert "REGRESSED" in r.stdout


@pytest.mark.slow
class TestConfig22Acceptance:
    def test_traced_pair_overhead_and_exactness(self):
        """One config-22 pair end to end on the chaos workload: digest
        identity, every decomposition exact (asserted inside
        bench_reqtrace), the decomp_* fields populated per class."""
        from tpuscratch.bench.traffic import (
            bench_reqtrace,
            traffic_chaos_setup,
        )

        setup = traffic_chaos_setup(False, 16)
        cfg = cfg_for()
        scfg = scfg_for(max_seq=max(scfg_for().max_seq,
                                    setup["tcfg"].max_total_len))
        mesh = mesh_for()
        un = bench_reqtrace(mesh, cfg, scfg, setup, traced=False)
        td = bench_reqtrace(mesh, cfg, scfg, setup, traced=True)
        assert td["digest"] == un["digest"]
        assert td["n_traces"] > 0 and td["waste_traces"] > 0
        assert any(k.startswith("decomp_") and k.endswith("_latency")
                   for k in td)
        assert any(k.startswith("decomp_") and k.endswith("_batch")
                   for k in td)
