"""Distributed FFT and the spectral Poisson solver vs numpy oracles."""

import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpuscratch.comm import run_spmd
from tpuscratch.parallel.fft import fft2_sharded, ifft2_sharded
from tpuscratch.runtime.mesh import make_mesh_1d
from tpuscratch.solvers.spectral import (
    periodic_laplacian_np,
    periodic_poisson_fft,
)


def _grid(h, w, seed=0, complex_=False):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((h, w)).astype(np.float32)
    if complex_:
        x = (x + 1j * rng.standard_normal((h, w))).astype(np.complex64)
    return x


@pytest.mark.parametrize("n,complex_", [(2, False), (8, True)])
def test_fft2_sharded_matches_numpy(devices, n, complex_):
    mesh = make_mesh_1d("x", n)
    x = _grid(16, 8 * n, complex_=complex_)
    prog = run_spmd(mesh, lambda s: fft2_sharded(s, "x"), P("x"), P("x"))
    got = np.asarray(prog(jnp.asarray(x)))
    expect = np.fft.fft2(x)
    assert np.allclose(got, expect, atol=1e-3 * np.abs(expect).max())


def test_fft2_pencil_layout_is_column_blocks(devices):
    n = 4
    mesh = make_mesh_1d("x", n)
    x = _grid(8, 16)
    # without the restoring transpose the global result comes out as the
    # (W-sharded) transpose-of-blocks layout: out[d] = fft2(x)[:, d-th cols]
    prog = run_spmd(
        mesh,
        lambda s: fft2_sharded(s, "x", restore_layout=False),
        P("x"),
        P(None, "x"),
    )
    got = np.asarray(prog(jnp.asarray(x)))
    assert got.shape == x.shape
    assert np.allclose(got, np.fft.fft2(x), atol=1e-4 * np.abs(x).sum())


def test_fft_round_trip(devices):
    mesh = make_mesh_1d("x", 8)
    x = _grid(16, 24, complex_=True)
    prog = run_spmd(
        mesh,
        lambda s: ifft2_sharded(fft2_sharded(s, "x"), "x"),
        P("x"),
        P("x"),
    )
    assert np.allclose(np.asarray(prog(jnp.asarray(x))), x, atol=1e-5)


@pytest.mark.parametrize("n", [1, 8])
@pytest.mark.parametrize("impl", ["xla", "dft"])
def test_periodic_poisson_fft_solves(devices, n, impl):
    h, w = 32, 16
    b = _grid(h, w, seed=3)
    b -= b.mean()
    x = periodic_poisson_fft(b, make_mesh_1d("x", n), impl=impl)
    assert abs(x.mean()) < 1e-5  # zero-mean branch of the singular system
    resid = periodic_laplacian_np(x.astype(np.float64)) - b
    assert np.abs(resid).max() < 1e-4
    # nonzero-mean b: only the projected part is solvable
    b2 = b + 1.0
    x2 = periodic_poisson_fft(b2, make_mesh_1d("x", n), impl=impl)
    assert np.abs(x2 - x).max() < 1e-4


@pytest.mark.parametrize("inverse", [False, True])
def test_pair_dft_matches_complex_fft(devices, inverse):
    from tpuscratch.parallel.fft import fft2_sharded_pair

    n = 8
    mesh = make_mesh_1d("x", n)
    x = _grid(16, 24, seed=4, complex_=True)
    prog = run_spmd(
        mesh,
        lambda r, i: fft2_sharded_pair(r, i, "x", inverse=inverse),
        (P("x"), P("x")),
        (P("x"), P("x")),
    )
    re, im = prog(jnp.asarray(x.real), jnp.asarray(x.imag))
    got = np.asarray(re) + 1j * np.asarray(im)
    expect = np.fft.ifft2(x) if inverse else np.fft.fft2(x)
    scale = max(np.abs(expect).max(), 1e-6)
    assert np.allclose(got, expect, atol=1e-4 * scale)


def test_pair_pencil_round_trip(devices):
    from tpuscratch.parallel.fft import (
        fft2_sharded_pair,
        ifft2_from_pencil_pair,
    )

    mesh = make_mesh_1d("x", 4)
    x = _grid(8, 16, seed=5)

    def round_trip(b):
        re, im = fft2_sharded_pair(
            b, jnp.zeros_like(b), "x", restore_layout=False
        )
        re, _ = ifft2_from_pencil_pair(re, im, "x")
        return re

    prog = run_spmd(mesh, round_trip, P("x"), P("x"))
    assert np.allclose(np.asarray(prog(jnp.asarray(x))), x, atol=1e-4)


class TestComplexOverrideParsing:
    """TPUSCRATCH_COMPLEX must treat every plausible spelling of 'no' as
    falsy — a truthy-by-accident 'False' would enable the complex path on
    a backend that wedges on it (ADVICE r2)."""

    def test_falsy_spellings(self, monkeypatch):
        from tpuscratch.parallel.fft import complex_supported

        for v in ("0", "false", "False", "FALSE", "no", "No", "off",
                  "OFF", "", "  false  "):
            monkeypatch.setenv("TPUSCRATCH_COMPLEX", v)
            assert complex_supported() is False, v

    def test_truthy_spellings(self, monkeypatch):
        from tpuscratch.parallel.fft import complex_supported

        for v in ("1", "true", "True", "yes", "on"):
            monkeypatch.setenv("TPUSCRATCH_COMPLEX", v)
            assert complex_supported() is True, v


class TestFourStep:
    """Four-step (N = N1*N2 Cooley-Tukey) matmul FFT: must equal the
    dense DFT / numpy to f32 accuracy at a fraction of the MACs."""

    @pytest.mark.parametrize("inverse", [False, True])
    def test_sharded_four_step_matches_numpy(self, devices, inverse):
        from tpuscratch.parallel.fft import fft2_sharded_pair

        n = 8
        mesh = make_mesh_1d("x", n)
        x = _grid(32, 64, seed=6, complex_=True)
        prog = run_spmd(
            mesh,
            lambda r, i: fft2_sharded_pair(
                r, i, "x", inverse=inverse, method="four-step"
            ),
            (P("x"), P("x")),
            (P("x"), P("x")),
        )
        re, im = prog(jnp.asarray(x.real), jnp.asarray(x.imag))
        got = np.asarray(re) + 1j * np.asarray(im)
        expect = np.fft.ifft2(x) if inverse else np.fft.fft2(x)
        scale = max(np.abs(expect).max(), 1e-6)
        assert np.allclose(got, expect, atol=1e-4 * scale)

    def test_auto_threshold_dispatch(self):
        from tpuscratch.parallel import fft as F

        # below FOUR_STEP_MIN auto stays direct; at/above it goes
        # four-step when the length is composite
        assert F._split(F.FOUR_STEP_MIN) is not None  # threshold composite
        assert F.resolve_method(F.FOUR_STEP_MIN, "auto") == "four-step"
        assert F.resolve_method(F.FOUR_STEP_MIN // 2, "auto") == "direct"
        # ...and both routes compute the same transform at a length
        # where they genuinely differ
        rng = np.random.default_rng(7)
        xr = jnp.asarray(rng.standard_normal((4, 64)).astype(np.float32))
        xi = jnp.zeros_like(xr)
        a = F._pair_axis(xr, xi, 1, False, "four-step")
        d = F._pair_axis(xr, xi, 1, False, "direct")
        np.testing.assert_allclose(
            np.asarray(a[0]), np.asarray(d[0]), rtol=1e-5, atol=1e-4
        )

    def test_explicit_four_step_on_prime_raises(self):
        from tpuscratch.parallel import fft as F

        with pytest.raises(ValueError, match="composite"):
            F.resolve_method(13, "four-step")
        with pytest.raises(ValueError, match="unknown"):
            F.resolve_method(64, "stockham")

    def test_split_balanced_and_prime(self):
        from tpuscratch.parallel.fft import _split

        # >= 1024 with 128 | n: lane-perfect n2=128 (chip-raced winner);
        # balanced otherwise
        assert _split(1024) == (8, 128)
        assert _split(4096) == (32, 128)
        assert _split(8192) == (64, 128)
        assert _split(96) == (8, 12)
        assert _split(13) is None

    def test_four_step_rejects_prime_via_auto_fallback(self):
        from tpuscratch.parallel import fft as F

        rng = np.random.default_rng(8)
        xr = jnp.asarray(rng.standard_normal((4, 13)).astype(np.float32))
        xi = jnp.zeros_like(xr)
        # auto on a prime length must fall back to direct, not crash
        yr, yi = F._pair_axis(xr, xi, 1, False, "auto")
        want = np.fft.fft(np.asarray(xr), axis=1)
        np.testing.assert_allclose(np.asarray(yr), want.real, atol=1e-4)

    @pytest.mark.parametrize("axis", [0, 1])
    @pytest.mark.parametrize("inverse", [False, True])
    def test_eight_step_recursion_matches_numpy(self, monkeypatch, axis,
                                                inverse):
        # force the n1-side recursion at a small size (n=1024 -> n1=8 ->
        # (2,4)) and check the full transform against numpy on both axes
        from tpuscratch.parallel import fft as F

        monkeypatch.setattr(F, "EIGHT_STEP_MIN", 4)
        assert F._sub_split(8) == (2, 4)
        rng = np.random.default_rng(9)
        shape = (4, 1024) if axis == 1 else (1024, 4)
        xr = rng.standard_normal(shape).astype(np.float32)
        xi = rng.standard_normal(shape).astype(np.float32)
        yr, yi = F._four_step_axis(
            jnp.asarray(xr), jnp.asarray(xi), axis, inverse
        )
        z = xr + 1j * xi
        want = (np.fft.ifft if inverse else np.fft.fft)(z, axis=axis)
        scale = np.abs(want).max()
        assert np.allclose(np.asarray(yr), want.real,
                           atol=1e-5 * max(scale, 1.0))
        assert np.allclose(np.asarray(yi), want.imag,
                           atol=1e-5 * max(scale, 1.0))

    def test_sub_split_threshold(self):
        from tpuscratch.parallel import fft as F

        # the chip race disabled the recursion by default...
        assert F.EIGHT_STEP_MIN == 0
        assert F._sub_split(64) is None
        # ...but an explicit threshold re-enables it
        assert F._sub_split(64, min_n=64) == (8, 8)
        assert F._sub_split(128, min_n=64) == (8, 16)
        assert F._sub_split(63, min_n=64) is None
        assert F._sub_split(13, min_n=4) is None  # prime


class TestFFT3:
    """3D pencil FFT (complex + pair paths) and the spectral 3D solver."""

    def test_fft3_pair_matches_numpy(self, devices):
        from tpuscratch.parallel.fft import fft3_sharded_pair

        n = 8
        mesh = make_mesh_1d("x", n)
        rng = np.random.default_rng(10)
        x = (rng.standard_normal((16, 8, 12))
             + 1j * rng.standard_normal((16, 8, 12))).astype(np.complex64)
        prog = run_spmd(
            mesh,
            lambda r, i: fft3_sharded_pair(r, i, "x"),
            (P("x"), P("x")),
            (P("x"), P("x")),
        )
        re, im = prog(jnp.asarray(x.real), jnp.asarray(x.imag))
        got = np.asarray(re) + 1j * np.asarray(im)
        expect = np.fft.fftn(x)
        scale = max(np.abs(expect).max(), 1e-6)
        assert np.allclose(got, expect, atol=1e-4 * scale)

    def test_fft3_complex_matches_numpy(self, devices):
        from tpuscratch.parallel.fft import fft3_sharded

        n = 4
        mesh = make_mesh_1d("x", n)
        rng = np.random.default_rng(11)
        x = rng.standard_normal((8, 8, 8)).astype(np.float32)
        prog = run_spmd(mesh, lambda b: fft3_sharded(b, "x"), P("x"), P("x"))
        got = np.asarray(prog(jnp.asarray(x)))
        expect = np.fft.fftn(x).astype(np.complex64)
        scale = max(np.abs(expect).max(), 1e-6)
        assert np.allclose(got, expect, atol=1e-4 * scale)

    def test_fft3_pair_round_trip_from_pencil(self, devices):
        from tpuscratch.parallel.fft import (
            fft3_sharded_pair,
            ifft3_from_pencil_pair,
        )

        mesh = make_mesh_1d("x", 4)
        rng = np.random.default_rng(12)
        x = rng.standard_normal((8, 8, 16)).astype(np.float32)

        def round_trip(b):
            re, im = fft3_sharded_pair(
                b, jnp.zeros_like(b), "x", restore_layout=False
            )
            re, _ = ifft3_from_pencil_pair(re, im, "x")
            return re

        prog = run_spmd(mesh, round_trip, P("x"), P("x"))
        assert np.allclose(np.asarray(prog(jnp.asarray(x))), x, atol=1e-4)

    @pytest.mark.parametrize("impl", ["xla", "dft"])
    def test_poisson3d_fft_solves_and_matches_multigrid(self, devices, impl):
        from tpuscratch.runtime.mesh import make_mesh
        from tpuscratch.solvers import periodic_poisson3d_fft
        from tpuscratch.solvers.multigrid3d import mg_poisson3d_solve

        rng = np.random.default_rng(13)
        b = rng.standard_normal((16, 16, 16)).astype(np.float32)
        b -= b.mean()
        x_sp = periodic_poisson3d_fft(b, make_mesh_1d("x", 8), impl=impl)
        # residual oracle: 7-point periodic Laplacian
        lap = 6 * x_sp.astype(np.float64) - sum(
            np.roll(x_sp.astype(np.float64), s, a)
            for a in range(3) for s in (1, -1)
        )
        assert np.abs(lap - b).max() < 1e-3
        assert abs(x_sp.mean()) < 1e-5
        x_mg, _, _ = mg_poisson3d_solve(
            b, make_mesh((2, 2, 2), ("z", "row", "col")), tol=1e-6
        )
        assert np.abs(x_sp - x_mg).max() < 1e-3
