"""Parity tests for the collectives + p2p layer on an 8-device CPU mesh.

Each test mirrors one reference program's observable behavior (SURVEY.md
§2.2): mpi3 pair exchange, mpi4 token passing, mpi5 neighbor exchange with
open boundaries, mpi6 gather of neighbor triples, mpi9 sub-communicator
allreduce, mpi10 cartesian 4-neighborhood, plus the collectives the CUDA
programs use (Reduce/Bcast/Scatter).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpuscratch.comm import (
    all_gather,
    all_to_all,
    allreduce_max,
    allreduce_sum,
    broadcast,
    gather_to_root,
    neighbor_exchange,
    pingpong,
    reduce_scatter,
    reduce_to_root,
    ring_shift,
    run_spmd,
    scatter_from_root,
    send_pairs,
    token_ring,
)
from tpuscratch.runtime.mesh import make_mesh, make_mesh_1d, make_mesh_2d
from tpuscratch.runtime.topology import CartTopology, Direction

N = 8


@pytest.fixture(scope="module")
def mesh1d():
    return make_mesh_1d("x")


@pytest.fixture(scope="module")
def ranks():
    return jnp.arange(N, dtype=jnp.float32)


class TestCollectives:
    def test_allreduce_sum(self, mesh1d, ranks):
        f = run_spmd(mesh1d, lambda x: allreduce_sum(x, "x"), P("x"), P("x"))
        np.testing.assert_array_equal(f(ranks), np.full(N, 28.0))

    def test_allreduce_max(self, mesh1d, ranks):
        f = run_spmd(mesh1d, lambda x: allreduce_max(x, "x"), P("x"), P("x"))
        np.testing.assert_array_equal(f(ranks), np.full(N, 7.0))

    def test_reduce_to_root(self, mesh1d, ranks):
        # mpicuda2.cu:293 — MPI_Reduce SUM to rank 0
        f = run_spmd(mesh1d, lambda x: reduce_to_root(x, "x"), P("x"), P("x"))
        np.testing.assert_array_equal(f(ranks), [28, 0, 0, 0, 0, 0, 0, 0])

    def test_broadcast(self, mesh1d, ranks):
        # mpicuda2.cu:154 — Bcast node count from rank 0; here from rank 3
        f = run_spmd(
            mesh1d, lambda x: broadcast(x, "x", root=3), P("x"), P("x")
        )
        np.testing.assert_array_equal(f(ranks), np.full(N, 3.0))

    def test_all_gather(self, mesh1d, ranks):
        # tiled: every rank ends up holding the full concatenated vector
        f = run_spmd(
            mesh1d, lambda x: all_gather(x, "x", tiled=True), P("x"), P("x")
        )
        out = np.asarray(f(ranks)).reshape(N, N)  # row i = rank i's copy
        for row in out:
            np.testing.assert_array_equal(row, np.arange(N))

    def test_gather_to_root(self, mesh1d, ranks):
        # mpi6.cpp:89-100 — root holds everyone's data, others don't
        f = run_spmd(
            mesh1d,
            lambda x: gather_to_root(x, "x", tiled=True),
            P("x"),
            P("x"),
        )
        out = np.asarray(f(ranks)).reshape(N, N)
        np.testing.assert_array_equal(out[0], np.arange(N))
        assert (out[1:] == 0).all()

    def test_scatter_from_root(self, mesh1d):
        # mpicuda2.cu:145-152 — root's array split evenly, piece i to rank i
        data = jnp.arange(16.0)
        f = run_spmd(
            mesh1d, lambda x: scatter_from_root(x, "x"), P(), P("x")
        )
        np.testing.assert_array_equal(f(data), np.arange(16.0))

    def test_reduce_scatter(self, mesh1d):
        # every rank holds an 8-vector of ones; rank i receives sum of slot i
        data = jnp.ones(N * N, dtype=jnp.float32)
        f = run_spmd(
            mesh1d,
            lambda x: reduce_scatter(x, "x", tiled=True),
            P("x"),
            P("x"),
        )
        np.testing.assert_array_equal(f(data), np.full(N, 8.0))

    def test_all_to_all(self, mesh1d):
        # transpose of ownership: rank i's slot j -> rank j's slot i
        data = jnp.arange(N * N, dtype=jnp.float32).reshape(N, N)
        f = run_spmd(
            mesh1d,
            lambda x: all_to_all(x, "x", split_axis=1, concat_axis=0, tiled=True),
            P("x", None),
            P("x", None),
        )
        out = np.asarray(f(data)).reshape(N, N)
        np.testing.assert_array_equal(out, np.arange(64.0).reshape(N, N).T)


class TestSubCommunicators:
    """mpi9 parity: world split in halves; concurrent per-half allreduce
    plus whole-world allreduce, via a ('half','local') 2-axis mesh instead
    of MPI groups/Comm_create."""

    def test_half_vs_world_allreduce(self):
        mesh = make_mesh((2, 4), ("half", "local"))
        vals = jnp.arange(8, dtype=jnp.float32).reshape(2, 4)

        def body(x):
            per_half = allreduce_sum(x, "local")
            world = allreduce_sum(x, ("half", "local"))
            return per_half, world

        f = run_spmd(
            mesh, body, P("half", "local"),
            (P("half", "local"), P("half", "local")),
        )
        per_half, world = f(vals)
        np.testing.assert_array_equal(
            np.asarray(per_half), [[6, 6, 6, 6], [22, 22, 22, 22]]
        )
        np.testing.assert_array_equal(np.asarray(world), np.full((2, 4), 28.0))

    def test_reduce_to_root_within_half(self):
        mesh = make_mesh((2, 4), ("half", "local"))
        vals = jnp.ones((2, 4), dtype=jnp.float32)
        f = run_spmd(
            mesh,
            lambda x: reduce_to_root(x, "local"),
            P("half", "local"),
            P("half", "local"),
        )
        np.testing.assert_array_equal(
            np.asarray(f(vals)), [[4, 0, 0, 0], [4, 0, 0, 0]]
        )


class TestP2P:
    def test_send_pairs_exchange(self, mesh1d, ranks):
        # mpi3: two ranks swap values (everyone else gets zeros)
        f = run_spmd(
            mesh1d,
            lambda x: send_pairs(x, "x", [(0, 1), (1, 0)]),
            P("x"),
            P("x"),
        )
        np.testing.assert_array_equal(f(ranks), [1, 0, 0, 0, 0, 0, 0, 0])

    def test_neighbor_exchange_open(self, mesh1d, ranks):
        # mpi5: rank i learns (i-1, i+1); boundaries get zeros
        f = run_spmd(
            mesh1d,
            lambda x: neighbor_exchange(x, "x", periodic=False),
            P("x"),
            (P("x"), P("x")),
        )
        from_left, from_right = f(ranks)
        np.testing.assert_array_equal(from_left, [0, 0, 1, 2, 3, 4, 5, 6])
        np.testing.assert_array_equal(from_right, [1, 2, 3, 4, 5, 6, 7, 0])

    def test_ring_shift_periodic(self, mesh1d, ranks):
        f = run_spmd(
            mesh1d, lambda x: ring_shift(x, "x", 1), P("x"), P("x")
        )
        np.testing.assert_array_equal(f(ranks), [7, 0, 1, 2, 3, 4, 5, 6])

    def test_pingpong_round_trip(self, mesh1d, ranks):
        # test-benchmark parity: data echoed back must equal original on A.
        # Nonzero start + nonzero rank pair so the echo is distinguishable
        # from ppermute's zero fill.
        f = run_spmd(
            mesh1d,
            lambda x: pingpong(x + 10.0, "x", a=2, b=5, rounds=3),
            P("x"),
            P("x"),
        )
        out = np.asarray(f(ranks))
        assert out[2] == 12.0  # rank 2's value (2+10) returned home
        assert (out[[0, 1, 3, 4, 6, 7]] == 0.0).all()

    def test_token_ring(self, mesh1d, ranks):
        # mpi4 generalized: token hops the ring, +1 per hop; after N hops
        # every rank holds its own starting value + N
        f = run_spmd(
            mesh1d, lambda x: token_ring(x, "x", hops=N), P("x"), P("x")
        )
        np.testing.assert_array_equal(f(ranks), np.arange(N) + N)

    def test_token_ring_partial(self, mesh1d, ranks):
        # after 3 hops rank i holds rank (i-3)'s token + 3
        f = run_spmd(
            mesh1d, lambda x: token_ring(x, "x", hops=3), P("x"), P("x")
        )
        np.testing.assert_array_equal(
            f(ranks), (np.arange(N) - 3) % N + 3
        )


class TestCartesian2D:
    """mpi10 parity: 4-neighborhood exchange on a 2D periodic grid, plus the
    diagonal single-hop permutes the halo library depends on."""

    def test_four_neighbor_ids(self):
        mesh = make_mesh_2d((2, 4))
        topo = CartTopology((2, 4), (True, True))

        def body(x):
            out = {}
            for d in (Direction.TOP, Direction.BOTTOM, Direction.LEFT, Direction.RIGHT):
                # receive from direction d == everyone sends toward opposite
                perm = topo.send_permutation(d.opposite)
                out[d.name] = jax.lax.ppermute(x, ("row", "col"), perm)
            return out["TOP"], out["BOTTOM"], out["LEFT"], out["RIGHT"]

        ids = jnp.arange(8, dtype=jnp.float32).reshape(2, 4)
        f = run_spmd(
            mesh, body, P("row", "col"), tuple(P("row", "col") for _ in range(4))
        )
        top, bottom, left, right = (np.asarray(a) for a in f(ids))
        # rank (0,1)=1: top neighbor wraps to (1,1)=5, bottom=5, left=0, right=2
        assert top[0, 1] == 5 and bottom[0, 1] == 5
        assert left[0, 1] == 0 and right[0, 1] == 2
        # full maps
        np.testing.assert_array_equal(top, [[4, 5, 6, 7], [0, 1, 2, 3]])
        np.testing.assert_array_equal(left, [[3, 0, 1, 2], [7, 4, 5, 6]])

    def test_diagonal_single_hop(self):
        mesh = make_mesh_2d((2, 4))
        topo = CartTopology((2, 4), (True, True))
        perm = topo.send_permutation(Direction.BOTTOM_RIGHT)
        f = run_spmd(
            mesh,
            lambda x: jax.lax.ppermute(x, ("row", "col"), perm),
            P("row", "col"),
            P("row", "col"),
        )
        out = np.asarray(f(jnp.arange(8.0).reshape(2, 4)))
        # value v of rank r lands on r's bottom-right neighbor
        np.testing.assert_array_equal(out, [[7, 4, 5, 6], [3, 0, 1, 2]])
