"""Expert parallelism tests: routed MoE over an expert axis vs a dense
no-drop oracle, capacity dropping, load-balance loss, differentiability."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpuscratch.comm import run_spmd
from tpuscratch.parallel.expert import (
    capacity,
    expert_parallel_ffn,
    topk_routing,
)
from tpuscratch.runtime.mesh import make_mesh_1d

N = 8  # mesh size (conftest provisions 8 virtual devices)


def _oracle_moe(x, gate_w, w_in, w_out, k):
    """Dense no-drop MoE: every token reaches its top-k experts."""
    x64 = x.astype(np.float64)
    logits = x64 @ gate_w.astype(np.float64)
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs = probs / probs.sum(-1, keepdims=True)
    out = np.zeros_like(x64)
    rem = probs.copy()
    for _ in range(k):
        choice = rem.argmax(-1)
        for t in range(x.shape[0]):
            e = choice[t]
            h = np.maximum(x64[t] @ w_in[e].astype(np.float64), 0.0)
            out[t] += rem[t, e] * (h @ w_out[e].astype(np.float64))
        rem[np.arange(x.shape[0]), choice] = 0.0
    return out


@pytest.fixture(scope="module")
def mesh():
    return make_mesh_1d("ep")


def _params(rng, e_total, d, f):
    gate_w = rng.standard_normal((d, e_total)).astype(np.float32)
    w_in = (rng.standard_normal((e_total, d, f)) * 0.1).astype(np.float32)
    w_out = (rng.standard_normal((e_total, f, d)) * 0.1).astype(np.float32)
    return gate_w, w_in, w_out


class TestRouting:
    def test_capacity_helper(self):
        assert capacity(64, 8, 1.25) == 10
        assert capacity(2, 64, 1.0) == 1  # never zero

    def test_top1_dispatch_slots_unique(self):
        rng = np.random.default_rng(0)
        logits = jnp.asarray(rng.standard_normal((16, 4)).astype(np.float32))
        r = topk_routing(logits, cap=8, k=1)
        d = np.asarray(r.dispatch)
        # each token occupies at most one (expert, slot); each (expert,
        # slot) holds at most one token
        assert d.sum(axis=(1, 2)).max() <= 1
        assert d.sum(axis=0).max() <= 1

    def test_capacity_drops_excess(self):
        # all 6 tokens want expert 0; cap 2 keeps exactly the first 2
        logits = jnp.tile(jnp.asarray([[10.0, 0.0, 0.0]]), (6, 1))
        r = topk_routing(logits, cap=2, k=1)
        d = np.asarray(r.dispatch)
        np.testing.assert_array_equal(d[:, 0, :].sum(axis=1), [1, 1, 0, 0, 0, 0])

    def test_top2_uses_two_experts(self):
        logits = jnp.asarray([[5.0, 4.0, -5.0]] * 3, dtype=jnp.float32)
        r = topk_routing(logits, cap=4, k=2)
        d = np.asarray(r.dispatch)
        np.testing.assert_array_equal(d.sum(axis=(0, 2)), [3, 3, 0])

    def test_aux_loss_uniform_is_one(self):
        # perfectly uniform top-1 routing -> loss == 1
        logits = jnp.eye(8, dtype=jnp.float32) * 5.0
        r = topk_routing(logits, cap=2, k=1)
        assert np.asarray(r.aux_loss) == pytest.approx(1.0, abs=0.05)


class TestExpertParallelFFN:
    @pytest.mark.parametrize("k,e_local", [(1, 1), (1, 2), (2, 1)])
    def test_matches_dense_oracle_no_drops(self, mesh, k, e_local):
        e_total = N * e_local
        T, D, F = 64, 16, 32  # per-rank tokens = 8
        rng = np.random.default_rng(1 + k + e_local)
        x = rng.standard_normal((T, D)).astype(np.float32)
        gate_w, w_in, w_out = _params(rng, e_total, D, F)

        def body(x, gate_w, w_in, w_out):
            out, aux = expert_parallel_ffn(
                x, gate_w, w_in, w_out, "ep",
                capacity_factor=float(e_total), k=k,  # no drops
            )
            return out

        f = run_spmd(
            mesh, body,
            (P("ep"), P(), P("ep"), P("ep")),
            P("ep"),
        )
        got = np.asarray(f(x, gate_w, w_in, w_out))
        want = _oracle_moe(x, gate_w, w_in, w_out, k)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)

    def test_capacity_drop_zeroes_excess_tokens(self, mesh):
        T, D, F = 64, 8, 16
        rng = np.random.default_rng(2)
        x = np.abs(rng.standard_normal((T, D))).astype(np.float32)
        gate_w = np.zeros((D, N), dtype=np.float32)
        gate_w[:, 0] = 1.0  # every token routes to expert 0
        w_in = (rng.standard_normal((N, D, F)) * 0.1).astype(np.float32)
        w_out = (rng.standard_normal((N, F, D)) * 0.1).astype(np.float32)

        def body(x, gate_w, w_in, w_out):
            out, _ = expert_parallel_ffn(
                x, gate_w, w_in, w_out, "ep", capacity_factor=0.125, k=1
            )
            return out

        f = run_spmd(mesh, body, (P("ep"), P(), P("ep"), P("ep")), P("ep"))
        got = np.asarray(f(x, gate_w, w_in, w_out))
        # cap = max(1, 8*0.125/8) = 1: one surviving token per rank block
        per_rank = got.reshape(N, T // N, D)
        nonzero_rows = (np.abs(per_rank).sum(-1) > 0).sum(axis=1)
        np.testing.assert_array_equal(nonzero_rows, np.ones(N))
        # and the survivor is each block's first token
        assert (np.abs(per_rank[:, 0, :]).sum(-1) > 0).all()

    def test_differentiable(self, mesh):
        T, D, F, e_local = 32, 8, 16, 1
        rng = np.random.default_rng(3)
        x = rng.standard_normal((T, D)).astype(np.float32)
        gate_w, w_in, w_out = _params(rng, N * e_local, D, F)

        def loss_fn(x, gate_w, w_in, w_out):
            out, aux = expert_parallel_ffn(
                x, gate_w, w_in, w_out, "ep", capacity_factor=8.0, k=1
            )
            return jnp.sum(out**2) + 0.01 * aux

        def body(x, gate_w, w_in, w_out):
            loss, grads = jax.value_and_grad(loss_fn, argnums=(2, 3))(
                x, gate_w, w_in, w_out
            )
            return jax.lax.psum(loss, "ep"), grads

        f = run_spmd(
            mesh, body,
            (P("ep"), P(), P("ep"), P("ep")),
            (P(), (P("ep"), P("ep"))),
        )
        loss, (g_in, g_out) = f(x, gate_w, w_in, w_out)
        assert np.isfinite(np.asarray(loss))
        assert np.isfinite(np.asarray(g_in)).all()
        assert np.abs(np.asarray(g_out)).sum() > 0


class TestSparseImpl:
    """sparse (gather/scatter) routing must compute the IDENTICAL
    assignment as the one-hot einsum formulation — forward and
    gradients."""

    @pytest.mark.parametrize("k", [1, 2])
    def test_sparse_equals_einsum_forward(self, devices, k):
        from tpuscratch.comm import run_spmd
        from tpuscratch.parallel.expert import expert_parallel_ffn
        from tpuscratch.runtime.mesh import make_mesh_1d

        n = 4
        mesh = make_mesh_1d("ep", n)
        rng = np.random.default_rng(40)
        T, D, F = 8 * n, 16, 32
        x = jnp.asarray(rng.standard_normal((T, D)).astype(np.float32))
        gw = jnp.asarray(rng.standard_normal((D, n)).astype(np.float32))
        wi = jnp.asarray(
            (rng.standard_normal((n, D, F)) * 0.1).astype(np.float32)
        )
        wo = jnp.asarray(
            (rng.standard_normal((n, F, D)) * 0.1).astype(np.float32)
        )
        outs = {}
        for impl in ("sparse", "einsum"):
            prog = run_spmd(
                mesh,
                lambda x_, g, a, b, impl=impl: expert_parallel_ffn(
                    x_, g, a, b, "ep", capacity_factor=1.5, k=k, impl=impl
                ),
                (P("ep"), P(), P("ep"), P("ep")),
                (P("ep"), P()),
            )
            out, aux = prog(x, gw, wi, wo)
            outs[impl] = (np.asarray(out), float(aux))
        np.testing.assert_allclose(
            outs["sparse"][0], outs["einsum"][0], rtol=1e-5, atol=1e-6
        )
        assert abs(outs["sparse"][1] - outs["einsum"][1]) < 1e-6

    @pytest.mark.parametrize("k", [1, 2])
    def test_sparse_equals_einsum_gradients(self, devices, k):
        import jax

        from tpuscratch.comm import run_spmd
        from tpuscratch.parallel.expert import expert_parallel_ffn
        from tpuscratch.runtime.mesh import make_mesh_1d

        n = 4
        mesh = make_mesh_1d("ep", n)
        rng = np.random.default_rng(41)
        T, D, F = 8 * n, 16, 32
        x = jnp.asarray(rng.standard_normal((T, D)).astype(np.float32))
        gw = jnp.asarray(rng.standard_normal((D, n)).astype(np.float32))
        wi = jnp.asarray(
            (rng.standard_normal((n, D, F)) * 0.1).astype(np.float32)
        )
        wo = jnp.asarray(
            (rng.standard_normal((n, F, D)) * 0.1).astype(np.float32)
        )
        grads = {}
        for impl in ("sparse", "einsum"):
            def loss(x_, g, a, b, impl=impl):
                body = jax.shard_map(
                    lambda xx, gg, aa, bb: expert_parallel_ffn(
                        xx, gg, aa, bb, "ep", capacity_factor=1.5,
                        k=k, impl=impl
                    )[0],
                    mesh=mesh,
                    in_specs=(P("ep"), P(), P("ep"), P("ep")),
                    out_specs=P("ep"),
                    check_vma=False,
                )
                return (body(x_, g, a, b) ** 2).sum()

            # all four inputs, gate_w included: the gate-weight backward
            # goes through take_along_axis in both paths
            grads[impl] = jax.jit(jax.grad(loss, argnums=(0, 1, 2, 3)))(
                x, gw, wi, wo
            )
        for a, b in zip(grads["sparse"], grads["einsum"]):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
            )
