"""Tests for auxiliary subsystems: profiling spans + checkpoint/resume."""

import time

import jax.numpy as jnp
import numpy as np
import pytest

from tpuscratch.runtime import checkpoint
from tpuscratch.runtime.profiling import Timeline, cross_rank_span


class TestTimeline:
    def test_span_records(self):
        tl = Timeline()
        with tl.span("work"):
            time.sleep(0.01)
        assert tl.seconds("work") >= 0.01
        assert "work" in tl.report()

    def test_span_blocks_on_sync_values(self):
        import jax

        tl = Timeline()
        x = jnp.ones(1 << 16)
        y = jax.jit(lambda a: a * 2)(x)  # async dispatch in flight
        with tl.span("sync", y):
            pass
        assert tl.seconds("sync") >= 0.0

    def test_missing_name(self):
        with pytest.raises(KeyError):
            Timeline().seconds("nope")

    def test_cross_rank_max_min(self):
        # mpicuda3 convention over synthetic per-rank timelines
        from tpuscratch.runtime.profiling import Span

        a, b = Timeline(), Timeline()
        a.spans.append(Span("step", 1.0, 2.0))
        b.spans.append(Span("step", 1.2, 2.5))
        assert cross_rank_span([a, b], "step") == pytest.approx(1.5)


class TestCheckpoint:
    def _tree(self, scale=1.0):
        return {
            "grid": jnp.arange(12.0).reshape(3, 4) * scale,
            "opt": {"count": jnp.asarray(7, dtype=jnp.int32)},
        }

    def test_roundtrip(self, tmp_path):
        tree = self._tree()
        checkpoint.save(tmp_path, 5, tree, metadata={"note": "hi"})
        got, step, meta = checkpoint.restore(tmp_path, tree)
        assert step == 5 and meta == {"note": "hi"}
        np.testing.assert_array_equal(got["grid"], np.asarray(tree["grid"]))
        assert int(got["opt"]["count"]) == 7

    def test_latest_and_prune(self, tmp_path):
        for s in (1, 3, 2):
            checkpoint.save(tmp_path, s, self._tree(s))
        assert checkpoint.latest_step(tmp_path) == 3
        got, step, _ = checkpoint.restore(tmp_path, self._tree())
        assert step == 3
        np.testing.assert_array_equal(
            got["grid"], np.arange(12.0).reshape(3, 4) * 3
        )
        checkpoint.prune(tmp_path, keep=1)
        assert checkpoint.steps(tmp_path) == [3]

    def test_structure_drift_rejected(self, tmp_path):
        checkpoint.save(tmp_path, 1, self._tree())
        with pytest.raises(ValueError):
            checkpoint.restore(tmp_path, {"only": jnp.zeros(2)})

    def test_empty_dir(self, tmp_path):
        assert checkpoint.latest_step(tmp_path) is None
        with pytest.raises(FileNotFoundError):
            checkpoint.restore(tmp_path, self._tree())

    def test_overwrite_same_step(self, tmp_path):
        checkpoint.save(tmp_path, 1, self._tree(1.0))
        checkpoint.save(tmp_path, 1, self._tree(2.0))
        got, _, _ = checkpoint.restore(tmp_path, self._tree())
        np.testing.assert_array_equal(
            got["grid"], np.arange(12.0).reshape(3, 4) * 2
        )
