"""Worker process for the multi-host rendezvous test (not a test module).

Each OS process plays one 'host': 1 virtual CPU device, rendezvous via a
localhost coordinator — the same shape as N TPU-VM workers joining a pod
slice, and the TPU-native analogue of one MPI rank under mpiexec
(/root/reference/mpi_pbs_sample.sh:18). Run:

    python tests/_multihost_worker.py <port> <rank> <nprocs>
"""

import sys

port, rank, nprocs = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])

from tpuscratch.runtime.hostenv import force_cpu_devices

force_cpu_devices(1)  # one local device per process, like one chip per host

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpuscratch.runtime.context import initialize

ctx = initialize(
    coordinator_address=f"127.0.0.1:{port}",
    num_processes=nprocs,
    process_id=rank,
)
assert ctx.process_count == nprocs, ctx
assert ctx.process_index == rank, ctx
assert ctx.local_device_count == 1, ctx
assert ctx.global_device_count == nprocs, ctx
print(ctx.hello(), flush=True)

# cross-process data-plane check: a psum over the global mesh must see
# every process's contribution (sum of 1..nprocs)
mesh = Mesh(np.array(jax.devices()), ("x",))
local = jnp.full((1, 4), float(rank + 1), jnp.float32)
garr = jax.make_array_from_single_device_arrays(
    (nprocs, 4),
    NamedSharding(mesh, P("x")),
    [jax.device_put(local, jax.local_devices()[0])],
)
f = jax.jit(
    shard_map(
        lambda x: jax.lax.psum(x, "x"), mesh=mesh,
        in_specs=P("x"), out_specs=P("x"),
    )
)
out = f(garr)
got = np.asarray(out.addressable_shards[0].data)
want = nprocs * (nprocs + 1) / 2
np.testing.assert_allclose(got, want)
print(f"WORKER{rank} OK process_count={ctx.process_count} psum={float(got[0, 0])}", flush=True)
