"""Flash-attention kernel (ops/attention.py) vs a dense oracle.

Runs in Pallas interpret mode on the CPU mesh (conftest forces CPU
devices); the same kernel source runs compiled on real TPUs, where it
was probed at S=4096, H=8, D=128 (~99 TFLOP/s non-causal, ~69 causal).
"""

import numpy as np
import pytest
import jax.numpy as jnp

from tpuscratch.ops.attention import flash_attention
from tpuscratch.parallel.scores import masked_scores


def dense_oracle(q, k, v, causal, q_offset=0, kv_offset=0):
    S, H, D = q.shape
    T = k.shape[0]
    rows = q_offset + np.arange(S)
    cols = kv_offset + np.arange(T)
    mask = (
        rows[:, None] >= cols[None, :]
        if causal
        else np.ones((S, T), bool)
    )
    s = np.asarray(
        masked_scores(jnp.asarray(q), jnp.asarray(k), jnp.asarray(mask))
    )
    m = s.max(-1, keepdims=True)
    p = np.exp(s - m) * (s > -1e29)
    l = p.sum(-1, keepdims=True)
    l[l == 0] = 1.0
    return np.einsum("hst,thd->shd", p / l, v)


def rand_qkv(rng, S, T, H, D):
    return (
        rng.standard_normal((S, H, D)).astype(np.float32),
        rng.standard_normal((T, H, D)).astype(np.float32),
        rng.standard_normal((T, H, D)).astype(np.float32),
    )


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("S,T,H,D", [(16, 16, 2, 8), (32, 16, 1, 8), (8, 24, 3, 16)])
    def test_matches_dense(self, causal, S, T, H, D):
        rng = np.random.default_rng(0)
        q, k, v = rand_qkv(rng, S, T, H, D)
        got = np.asarray(
            flash_attention(
                jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                causal=causal, block_q=8, block_k=8,
            )
        )
        np.testing.assert_allclose(
            got, dense_oracle(q, k, v, causal), rtol=1e-5, atol=1e-6
        )

    def test_global_offsets_for_ring_style_blocks(self):
        # a Q block at rows [16,48) attending a K block at cols [0,16):
        # fully visible under causal; and the mirrored case fully masked
        rng = np.random.default_rng(1)
        q, k, v = rand_qkv(rng, 32, 16, 2, 8)
        got = np.asarray(
            flash_attention(
                jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                causal=True, q_offset=16, kv_offset=0, block_q=8, block_k=8,
            )
        )
        np.testing.assert_allclose(
            got, dense_oracle(q, k, v, True, 16, 0), rtol=1e-5, atol=1e-6
        )

    def test_fully_masked_rows_are_zero_not_nan(self):
        # kv strictly in the future of every query row
        rng = np.random.default_rng(2)
        q, k, v = rand_qkv(rng, 8, 16, 1, 8)
        got = np.asarray(
            flash_attention(
                jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                causal=True, q_offset=0, kv_offset=100, block_q=8, block_k=8,
            )
        )
        assert np.isfinite(got).all()
        np.testing.assert_array_equal(got, np.zeros_like(got))

    def test_uneven_block_shrink(self):
        # S=T=24 with requested blocks 128 -> shrinks to a divisor
        rng = np.random.default_rng(3)
        q, k, v = rand_qkv(rng, 24, 24, 2, 8)
        got = np.asarray(
            flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        )
        np.testing.assert_allclose(
            got, dense_oracle(q, k, v, False), rtol=1e-5, atol=1e-6
        )

    def test_bad_shapes_rejected(self):
        q = jnp.ones((8, 2, 4), jnp.float32)
        k = jnp.ones((8, 2, 6), jnp.float32)
        with pytest.raises(ValueError, match="bad attention shapes"):
            flash_attention(q, k, k)

    def test_unblockable_length_rejected(self):
        # 17 has no power-of-two divisor >= 8: refuse rather than
        # silently degrade to per-row grid steps
        q = jnp.ones((17, 2, 8), jnp.float32)
        with pytest.raises(ValueError, match="power-of-two block divisor"):
            flash_attention(q, q, q)

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("qo,ko", [(0, 0), (16, 0)])
    def test_gradients_match_dense(self, causal, qo, ko):
        # custom-vjp backward kernels vs jax.grad through the dense path
        rng = np.random.default_rng(7)
        S, T, H, D = 16, 16, 2, 8
        q, k, v = rand_qkv(rng, S, T, H, D)
        w = rng.standard_normal((S, H, D)).astype(np.float32)

        def flash_loss(q, k, v):
            out = flash_attention(
                jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                causal=causal, q_offset=qo, kv_offset=ko,
                block_q=8, block_k=8,
            )
            return jnp.sum(out * jnp.asarray(w))

        def dense_loss(q, k, v):
            S_, T_ = q.shape[0], k.shape[0]
            rows = qo + jnp.arange(S_)
            cols = ko + jnp.arange(T_)
            mask = (
                rows[:, None] >= cols[None, :]
                if causal
                else jnp.ones((S_, T_), bool)
            )
            s = masked_scores(q, k, mask)
            p = jax.nn.softmax(s, axis=-1)
            out = jnp.einsum("hst,thd->shd", p, v)
            return jnp.sum(out * jnp.asarray(w))

        import jax

        gf = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(dense_loss, argnums=(0, 1, 2))(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)
        )
        for a, b, name in zip(gf, gd, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5,
                err_msg=f"d{name}",
            )

    def test_gradient_fully_masked_rows_zero(self):
        # kv entirely in the future: output is 0 and so are all grads
        import jax

        rng = np.random.default_rng(8)
        q, k, v = rand_qkv(rng, 8, 16, 1, 8)

        def loss(q, k, v):
            out = flash_attention(
                jnp.asarray(q), k, v, causal=True,
                q_offset=0, kv_offset=100, block_q=8, block_k=8,
            )
            return jnp.sum(out**2)

        gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)
        )
        for g in (gq, gk, gv):
            assert np.isfinite(np.asarray(g)).all()
            np.testing.assert_array_equal(np.asarray(g), 0.0)

    def test_bf16_inputs(self):
        rng = np.random.default_rng(4)
        q, k, v = rand_qkv(rng, 16, 16, 2, 8)
        got = np.asarray(
            flash_attention(
                jnp.asarray(q, jnp.bfloat16),
                jnp.asarray(k, jnp.bfloat16),
                jnp.asarray(v, jnp.bfloat16),
                block_q=8, block_k=8,
            ).astype(jnp.float32)
        )
        np.testing.assert_allclose(
            got, dense_oracle(q, k, v, False), rtol=0.05, atol=0.05
        )
