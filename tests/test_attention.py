"""Flash-attention kernel (ops/attention.py) vs a dense oracle.

Runs in Pallas interpret mode on the CPU mesh (conftest forces CPU
devices); the same kernel source runs compiled on real TPUs, where it
was probed at S=4096, H=8, D=128 (~108 TFLOP/s non-causal / ~75
causal f32, ~110/~77 bf16 — BASELINE.md row 6).
"""

import jax
import numpy as np
import pytest
import jax.numpy as jnp

from tpuscratch.ops.attention import flash_attention
from tpuscratch.parallel.scores import masked_scores


def dense_oracle(q, k, v, causal, q_offset=0, kv_offset=0):
    S, H, D = q.shape
    T = k.shape[0]
    rows = q_offset + np.arange(S)
    cols = kv_offset + np.arange(T)
    mask = (
        rows[:, None] >= cols[None, :]
        if causal
        else np.ones((S, T), bool)
    )
    s = np.asarray(
        masked_scores(jnp.asarray(q), jnp.asarray(k), jnp.asarray(mask))
    )
    m = s.max(-1, keepdims=True)
    p = np.exp(s - m) * (s > -1e29)
    l = p.sum(-1, keepdims=True)
    l[l == 0] = 1.0
    return np.einsum("hst,thd->shd", p / l, v)


def rand_qkv(rng, S, T, H, D):
    return (
        rng.standard_normal((S, H, D)).astype(np.float32),
        rng.standard_normal((T, H, D)).astype(np.float32),
        rng.standard_normal((T, H, D)).astype(np.float32),
    )


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("S,T,H,D", [(16, 16, 2, 8), (32, 16, 1, 8), (8, 24, 3, 16)])
    def test_matches_dense(self, causal, S, T, H, D):
        rng = np.random.default_rng(0)
        q, k, v = rand_qkv(rng, S, T, H, D)
        got = np.asarray(
            flash_attention(
                jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                causal=causal, block_q=8, block_k=8,
            )
        )
        np.testing.assert_allclose(
            got, dense_oracle(q, k, v, causal), rtol=1e-5, atol=1e-6
        )

    def test_global_offsets_for_ring_style_blocks(self):
        # a Q block at rows [16,48) attending a K block at cols [0,16):
        # fully visible under causal; and the mirrored case fully masked
        rng = np.random.default_rng(1)
        q, k, v = rand_qkv(rng, 32, 16, 2, 8)
        got = np.asarray(
            flash_attention(
                jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                causal=True, q_offset=16, kv_offset=0, block_q=8, block_k=8,
            )
        )
        np.testing.assert_allclose(
            got, dense_oracle(q, k, v, True, 16, 0), rtol=1e-5, atol=1e-6
        )

    def test_fully_masked_rows_are_zero_not_nan(self):
        # kv strictly in the future of every query row
        rng = np.random.default_rng(2)
        q, k, v = rand_qkv(rng, 8, 16, 1, 8)
        got = np.asarray(
            flash_attention(
                jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                causal=True, q_offset=0, kv_offset=100, block_q=8, block_k=8,
            )
        )
        assert np.isfinite(got).all()
        np.testing.assert_array_equal(got, np.zeros_like(got))

    def test_uneven_block_shrink(self):
        # S=T=24 with requested blocks 128 -> shrinks to a divisor
        rng = np.random.default_rng(3)
        q, k, v = rand_qkv(rng, 24, 24, 2, 8)
        got = np.asarray(
            flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        )
        np.testing.assert_allclose(
            got, dense_oracle(q, k, v, False), rtol=1e-5, atol=1e-6
        )

    def test_bad_shapes_rejected(self):
        q = jnp.ones((8, 2, 4), jnp.float32)
        k = jnp.ones((8, 2, 6), jnp.float32)
        with pytest.raises(ValueError, match="bad attention shapes"):
            flash_attention(q, k, k)

    def test_unblockable_length_rejected(self):
        # 17 has no power-of-two divisor >= 8: refuse rather than
        # silently degrade to per-row grid steps
        q = jnp.ones((17, 2, 8), jnp.float32)
        with pytest.raises(ValueError, match="power-of-two block divisor"):
            flash_attention(q, q, q)

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("qo,ko", [(0, 0), (16, 0)])
    def test_gradients_match_dense(self, causal, qo, ko):
        # custom-vjp backward kernels vs jax.grad through the dense path
        rng = np.random.default_rng(7)
        S, T, H, D = 16, 16, 2, 8
        q, k, v = rand_qkv(rng, S, T, H, D)
        w = rng.standard_normal((S, H, D)).astype(np.float32)

        def flash_loss(q, k, v):
            out = flash_attention(
                jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                causal=causal, q_offset=qo, kv_offset=ko,
                block_q=8, block_k=8,
            )
            return jnp.sum(out * jnp.asarray(w))

        def dense_loss(q, k, v):
            S_, T_ = q.shape[0], k.shape[0]
            rows = qo + jnp.arange(S_)
            cols = ko + jnp.arange(T_)
            mask = (
                rows[:, None] >= cols[None, :]
                if causal
                else jnp.ones((S_, T_), bool)
            )
            s = masked_scores(q, k, mask)
            p = jax.nn.softmax(s, axis=-1)
            out = jnp.einsum("hst,thd->shd", p, v)
            return jnp.sum(out * jnp.asarray(w))

        import jax

        gf = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(dense_loss, argnums=(0, 1, 2))(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)
        )
        for a, b, name in zip(gf, gd, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5,
                err_msg=f"d{name}",
            )

    def test_gradient_fully_masked_rows_zero(self):
        # kv entirely in the future: output is 0 and so are all grads
        import jax

        rng = np.random.default_rng(8)
        q, k, v = rand_qkv(rng, 8, 16, 1, 8)

        def loss(q, k, v):
            out = flash_attention(
                jnp.asarray(q), k, v, causal=True,
                q_offset=0, kv_offset=100, block_q=8, block_k=8,
            )
            return jnp.sum(out**2)

        gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)
        )
        for g in (gq, gk, gv):
            assert np.isfinite(np.asarray(g)).all()
            np.testing.assert_array_equal(np.asarray(g), 0.0)

    def test_bf16_inputs(self):
        rng = np.random.default_rng(4)
        q, k, v = rand_qkv(rng, 16, 16, 2, 8)
        got = np.asarray(
            flash_attention(
                jnp.asarray(q, jnp.bfloat16),
                jnp.asarray(k, jnp.bfloat16),
                jnp.asarray(v, jnp.bfloat16),
                block_q=8, block_k=8,
            ).astype(jnp.float32)
        )
        np.testing.assert_allclose(
            got, dense_oracle(q, k, v, False), rtol=0.05, atol=0.05
        )


class TestCompactSquareAndBf16:
    """The compact causal grid with square blocks (the tuned default
    shape) and the bf16 MXU path must both match the dense oracle."""

    def _oracle(self, q, k, v, causal):
        S, H, D = q.shape
        s = jnp.einsum("shd,thd->hst", q.astype(jnp.float32),
                       k.astype(jnp.float32)) / float(D) ** 0.5
        if causal:
            mask = np.tril(np.ones((S, S), bool))
            s = jnp.where(mask[None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("hst,thd->shd", p, v.astype(jnp.float32))

    def test_square_blocks(self):
        rng = np.random.default_rng(7)
        S, H, D = 128, 2, 128
        q, k, v = (jnp.asarray(rng.standard_normal((S, H, D)), jnp.float32)
                   for _ in range(3))
        out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(self._oracle(q, k, v, True)),
            rtol=1e-4, atol=1e-5,
        )

    def test_square_blocks_grad(self):
        rng = np.random.default_rng(8)
        S, H, D = 64, 2, 128
        q, k, v = (jnp.asarray(rng.standard_normal((S, H, D)), jnp.float32)
                   for _ in range(3))
        g = jax.grad(
            lambda q: flash_attention(
                q, k, v, causal=True, block_q=32, block_k=32
            ).sum()
        )(q)
        go = jax.grad(lambda q: self._oracle(q, k, v, True).sum())(q)
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(go), rtol=1e-4, atol=1e-4
        )

    @pytest.mark.parametrize("causal", [False, True])
    def test_bf16_inputs(self, causal):
        rng = np.random.default_rng(9)
        S, H, D = 128, 2, 128
        qf, kf, vf = (rng.standard_normal((S, H, D)).astype(np.float32)
                      for _ in range(3))
        q, k, v = (jnp.asarray(x, jnp.bfloat16) for x in (qf, kf, vf))
        out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
        ref = self._oracle(
            jnp.asarray(qf), jnp.asarray(kf), jnp.asarray(vf), causal
        )
        assert out.dtype == jnp.bfloat16
        # bf16 has ~3 decimal digits; attention outputs are O(1)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref), rtol=0.05, atol=0.05
        )


# ---- fused paged-attention kernel family (ISSUE 12) -----------------------
#
# The fused kernel (ops.attention.paged_attention) runs here in Pallas
# interpret mode against the dense XLA formulation — the ORACLE the
# dispatch keeps as the CPU/fallback path — across the serve dtype
# ladder (fp32 / int8 / fp8-e4m3) and the operand edge cases the engine
# produces: ragged seq_lens, idle seq_len == 0 slots, sentinel
# page-table tails, and page-count boundaries.  The two formulations
# differ only in summation order (online-softmax accumulation vs one
# dense softmax), so equivalence is pinned at reassociation-ulp
# tolerance (FUSED_PAGED_ATOL; measured ~2e-7 at these geometries).

from tpuscratch.ops.attention import (  # noqa: E402
    decode_attention,
    fused_attention_default,
    paged_attention,
    paged_attention_supported,
    verify_attention,
)
from tpuscratch.serve.kvcache import quantize_pages  # noqa: E402

#: fused-vs-dense bound: fp32 reassociation only (both paths dequantize
#: identically before their contractions), measured ~2e-7
FUSED_PAGED_ATOL = 1e-5


def _paged_case(rng, n_pages=8, page=4, H=2, Dh=8, B=3, max_pages=4,
                dtype=None):
    """Pools + a table exercising scrambled page order, sentinel tails,
    and (via the lens the callers pick) ragged/idle/page-edge slots."""
    kf = rng.standard_normal((n_pages, page, H, Dh)).astype(np.float32)
    vf = rng.standard_normal((n_pages, page, H, Dh)).astype(np.float32)
    table = np.full((B, max_pages), n_pages, np.int32)  # sentinel tails
    order = rng.permutation(n_pages)
    used = 0
    for b in range(B):
        n = min(max_pages, 1 + (b * 2) % max_pages)
        table[b, :n] = order[used:used + n] if used + n <= n_pages else (
            order[:n]
        )
        used = (used + n) % max(1, n_pages - max_pages)
    if dtype is None:
        return jnp.asarray(kf), jnp.asarray(vf), jnp.asarray(table), None, None
    qk, sk = quantize_pages(jnp.asarray(kf), dtype)
    qv, sv = quantize_pages(jnp.asarray(vf), dtype)
    return qk, qv, jnp.asarray(table), sk, sv


PAGED_DTYPES = (None, jnp.int8, jnp.float8_e4m3fn)  # None = fp32 rung


class TestPagedFusedOracle:
    """Interpret-mode fused kernel == dense oracle, the dtype ladder x
    the engine's operand edge cases."""

    @pytest.mark.parametrize("dtype", PAGED_DTYPES)
    def test_decode_matches_oracle_ragged_idle_sentinel(self, dtype):
        rng = np.random.default_rng(3)
        k_p, v_p, table, sk, sv = _paged_case(rng, dtype=dtype)
        B, H, Dh, page = 3, 2, 8, 4
        # ragged: mid-page, exactly at the table's full capacity
        # (16 == max_pages * page), and an idle slot
        lens = jnp.asarray([9, 16, 0], jnp.int32)
        q = jnp.asarray(rng.standard_normal((B, H, Dh)).astype(np.float32))
        dense = decode_attention(q, k_p, v_p, table, lens, sk, sv,
                                 fused=False)
        fused = decode_attention(q, k_p, v_p, table, lens, sk, sv,
                                 fused=True)
        np.testing.assert_allclose(np.asarray(fused), np.asarray(dense),
                                   atol=FUSED_PAGED_ATOL)
        assert float(jnp.abs(fused[2]).max()) == 0.0  # idle -> zeros

    @pytest.mark.parametrize("dtype", PAGED_DTYPES)
    def test_verify_matches_oracle_ragged_causal(self, dtype):
        """The verify/context-prefill shape: K queries ride one sweep,
        position j attending seq_len + j entries (ragged-causal), with
        lens straddling page boundaries (3 + K - 1 crosses into a
        fresh page mid-sweep) and a len exactly one page in."""
        rng = np.random.default_rng(4)
        k_p, v_p, table, sk, sv = _paged_case(rng, dtype=dtype)
        B, H, Dh, K = 3, 2, 8, 3
        lens = jnp.asarray([3, 4, 0], jnp.int32)
        q = jnp.asarray(
            rng.standard_normal((B, K, H, Dh)).astype(np.float32)
        )
        dense = verify_attention(q, k_p, v_p, table, lens, sk, sv,
                                 fused=False)
        fused = verify_attention(q, k_p, v_p, table, lens, sk, sv,
                                 fused=True)
        np.testing.assert_allclose(np.asarray(fused), np.asarray(dense),
                                   atol=FUSED_PAGED_ATOL)
        assert float(jnp.abs(fused[2]).max()) == 0.0

    def test_idle_slot_zeros_when_K_exceeds_page(self):
        """Review regression: an idle slot whose K exceeds page_size+1
        has n_need > 1 even with nothing cached (the ragged frontier
        reaches past page 0), and the update branch must keep the
        seq_len > 0 guard — without it the kernel accumulated garbage
        from the sentinel-clamped page while the oracle returns
        zeros."""
        rng = np.random.default_rng(8)
        k_p, v_p, table, sk, sv = _paged_case(rng)
        K = 6  # > page_size + 1 = 5
        lens = jnp.asarray([5, 0, 0], jnp.int32)
        q = jnp.asarray(
            rng.standard_normal((3, K, 2, 8)).astype(np.float32)
        )
        dense = verify_attention(q, k_p, v_p, table, lens, fused=False)
        fused = verify_attention(q, k_p, v_p, table, lens, fused=True)
        assert float(jnp.abs(fused[1:]).max()) == 0.0
        np.testing.assert_allclose(np.asarray(fused), np.asarray(dense),
                                   atol=FUSED_PAGED_ATOL)

    def test_single_page_single_slot(self):
        """Page-count lower edge: one page, one slot, len == 1."""
        rng = np.random.default_rng(5)
        kf = jnp.asarray(rng.standard_normal((1, 4, 2, 8)).astype(np.float32))
        vf = jnp.asarray(rng.standard_normal((1, 4, 2, 8)).astype(np.float32))
        table = jnp.zeros((1, 1), jnp.int32)
        lens = jnp.ones((1,), jnp.int32)
        q = jnp.asarray(rng.standard_normal((1, 2, 8)).astype(np.float32))
        dense = decode_attention(q, kf, vf, table, lens, fused=False)
        fused = decode_attention(q, kf, vf, table, lens, fused=True)
        np.testing.assert_allclose(np.asarray(fused), np.asarray(dense),
                                   atol=FUSED_PAGED_ATOL)

    def test_paged_attention_property_random_ragged_lens(self):
        """Property draw: random ragged lens (idles included) stay
        within the stated bound for every dtype rung, through the
        public :func:`paged_attention` entry directly."""
        rng = np.random.default_rng(6)
        for dtype in PAGED_DTYPES:
            k_p, v_p, table, sk, sv = _paged_case(rng, dtype=dtype)
            lens = jnp.asarray(rng.integers(0, 16, size=3).astype(np.int32))
            q = jnp.asarray(
                rng.standard_normal((3, 1, 2, 8)).astype(np.float32)
            )
            dense = verify_attention(q, k_p, v_p, table, lens, sk, sv,
                                     fused=False)
            fused = paged_attention(q, k_p, v_p, table, lens, sk, sv)
            np.testing.assert_allclose(
                np.asarray(fused), np.asarray(dense),
                atol=FUSED_PAGED_ATOL,
            )

    def test_dispatch_policy(self, monkeypatch):
        """The gating contract: env override wins, otherwise the dense
        oracle off-TPU; fused=True forces the kernel anywhere."""
        monkeypatch.delenv("TPUSCRATCH_FUSED_ATTN", raising=False)
        assert fused_attention_default() == (
            jax.default_backend() == "tpu"
        )
        monkeypatch.setenv("TPUSCRATCH_FUSED_ATTN", "on")
        assert fused_attention_default() is True
        monkeypatch.setenv("TPUSCRATCH_FUSED_ATTN", "off")
        assert fused_attention_default() is False
        # interpret mode supports any geometry
        assert paged_attention_supported(2, 8, 4, jnp.float32) is None


class TestPagedHeadGrid:
    """The large-H head-grid variant (ISSUE 15, the PR-12 remainder):
    when ``H*K`` online-softmax state rows overflow the VMEM scratch
    budget, the grid gains a head-block axis — each (sequence, head
    block) pair sweeps the pages with its own scratch.  Heads are
    independent in attention, so the split must be invisible: oracle
    equivalence at the same FUSED_PAGED_ATOL, every dtype rung."""

    def _force_budget(self, monkeypatch, rows):
        monkeypatch.setenv("TPUSCRATCH_PAGED_STATE_ROWS", str(rows))

    def test_head_block_selection(self, monkeypatch):
        from tpuscratch.ops.attention import _head_block

        self._force_budget(monkeypatch, 4)
        assert _head_block(2, 1) == 2      # under budget: no split
        assert _head_block(4, 2) == 2      # 4*2 > 4 -> blocks of 2
        assert _head_block(8, 4) == 1      # only H=1 fits 1*4 <= 4
        self._force_budget(monkeypatch, 512)
        assert _head_block(8, 16) == 8     # default geometries: whole H

    @pytest.mark.parametrize("dtype", PAGED_DTYPES)
    def test_decode_head_grid_matches_oracle(self, dtype, monkeypatch):
        # H*K = 2 > 1: the grid splits to per-head sweeps
        self._force_budget(monkeypatch, 1)
        rng = np.random.default_rng(9)
        k_p, v_p, table, sk, sv = _paged_case(rng, dtype=dtype)
        lens = jnp.asarray([9, 16, 0], jnp.int32)
        q = jnp.asarray(rng.standard_normal((3, 2, 8)).astype(np.float32))
        dense = decode_attention(q, k_p, v_p, table, lens, sk, sv,
                                 fused=False)
        fused = decode_attention(q, k_p, v_p, table, lens, sk, sv,
                                 fused=True)
        np.testing.assert_allclose(np.asarray(fused), np.asarray(dense),
                                   atol=FUSED_PAGED_ATOL)
        assert float(jnp.abs(fused[2]).max()) == 0.0  # idle -> zeros

    @pytest.mark.parametrize("dtype", PAGED_DTYPES)
    def test_verify_head_grid_matches_oracle(self, dtype, monkeypatch):
        # K=3 rides the head-split sweep: ragged-causal masking and the
        # idle-slot guard must hold per head block exactly as unsplit
        self._force_budget(monkeypatch, 3)
        rng = np.random.default_rng(10)
        k_p, v_p, table, sk, sv = _paged_case(rng, dtype=dtype)
        K = 3
        lens = jnp.asarray([3, 4, 0], jnp.int32)
        q = jnp.asarray(
            rng.standard_normal((3, K, 2, 8)).astype(np.float32)
        )
        dense = verify_attention(q, k_p, v_p, table, lens, sk, sv,
                                 fused=False)
        fused = verify_attention(q, k_p, v_p, table, lens, sk, sv,
                                 fused=True)
        np.testing.assert_allclose(np.asarray(fused), np.asarray(dense),
                                   atol=FUSED_PAGED_ATOL)
        assert float(jnp.abs(fused[2]).max()) == 0.0

    def test_head_grid_identical_to_unsplit_kernel(self, monkeypatch):
        """The split changes the schedule, not the algebra: the same
        inputs through the unsplit kernel and the head-grid kernel
        agree bit-for-bit in interpret mode (identical per-head op
        order — only the grid iteration is reshaped)."""
        rng = np.random.default_rng(11)
        k_p, v_p, table, sk, sv = _paged_case(rng, dtype=jnp.int8)
        lens = jnp.asarray([9, 7, 13], jnp.int32)
        q = jnp.asarray(
            rng.standard_normal((3, 2, 2, 8)).astype(np.float32)
        )
        monkeypatch.setenv("TPUSCRATCH_PAGED_STATE_ROWS", "512")
        whole = paged_attention(q, k_p, v_p, table, lens, sk, sv)
        monkeypatch.setenv("TPUSCRATCH_PAGED_STATE_ROWS", "2")
        split = paged_attention(q, k_p, v_p, table, lens, sk, sv)
        np.testing.assert_array_equal(np.asarray(whole),
                                      np.asarray(split))


@pytest.mark.pallas_tpu
@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="compiled Mosaic paged kernel needs a TPU")
class TestPagedFusedChip:
    """Chip-geometry fused kernel (collected-but-skipped under the
    JAX_PLATFORMS=cpu tier-1 run; interpret-mode equivalence above
    covers the same kernel source — the one-source contract of
    ops/common.use_interpret)."""

    @pytest.mark.parametrize("dtype", PAGED_DTYPES)
    def test_chip_geometry_matches_oracle(self, dtype):
        rng = np.random.default_rng(7)
        n_pages, page, H, Dh = 32, 16, 8, 128
        kf = rng.standard_normal((n_pages, page, H, Dh)).astype(np.float32)
        vf = rng.standard_normal((n_pages, page, H, Dh)).astype(np.float32)
        if dtype is None:
            k_p, v_p, sk, sv = jnp.asarray(kf), jnp.asarray(vf), None, None
        else:
            k_p, sk = quantize_pages(jnp.asarray(kf), dtype)
            v_p, sv = quantize_pages(jnp.asarray(vf), dtype)
        B, max_pages = 8, 8
        table = jnp.asarray(
            rng.permutation(n_pages)[: B * max_pages].reshape(B, max_pages)
        ).astype(jnp.int32)
        lens = jnp.asarray(rng.integers(0, 128, size=B).astype(np.int32))
        q = jnp.asarray(rng.standard_normal((B, H, Dh)).astype(np.float32))
        assert paged_attention_supported(H, Dh, page, k_p.dtype) is None
        dense = decode_attention(q, k_p, v_p, table, lens, sk, sv,
                                 fused=False)
        fused = decode_attention(q, k_p, v_p, table, lens, sk, sv,
                                 fused=True)
        np.testing.assert_allclose(np.asarray(fused), np.asarray(dense),
                                   atol=FUSED_PAGED_ATOL)
