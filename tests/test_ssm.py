"""Sequence-parallel SSM scan + prefix_sum collective vs oracles."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpuscratch.comm import prefix_sum, run_spmd
from tpuscratch.models.ssm import SSMConfig, init_params, ssm_block
from tpuscratch.parallel.ssm import ssm_scan
from tpuscratch.runtime.mesh import make_mesh_1d


def recurrence_np(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    h = np.zeros_like(b[0], dtype=np.float64)
    out = []
    for t in range(a.shape[0]):
        h = a[t].astype(np.float64) * h + b[t].astype(np.float64)
        out.append(h.copy())
    return np.stack(out)


class TestPrefixSum:
    @pytest.mark.parametrize("exclusive", [False, True])
    def test_matches_cumsum(self, devices, exclusive):
        mesh = make_mesh_1d("x", 8)
        vals = np.arange(8 * 3, dtype=np.float32).reshape(8, 3)
        prog = run_spmd(
            mesh,
            lambda v: prefix_sum(v[0], "x", exclusive=exclusive)[None],
            P("x"),
            P("x"),
        )
        got = np.asarray(prog(jnp.asarray(vals)))
        cum = np.cumsum(vals, axis=0)
        expect = np.concatenate([np.zeros((1, 3)), cum[:-1]]) if exclusive else cum
        assert np.allclose(got, expect)


class TestSSMScan:
    @pytest.mark.parametrize("n", [2, 8])
    def test_matches_sequential_recurrence(self, devices, n):
        mesh = make_mesh_1d("seq", n)
        T, D = 8 * n, 5
        rng = np.random.default_rng(0)
        a = rng.uniform(0.2, 0.99, (T, D)).astype(np.float32)
        b = rng.standard_normal((T, D)).astype(np.float32)
        prog = run_spmd(
            mesh, lambda aa, bb: ssm_scan(aa, bb, "seq"),
            (P("seq"), P("seq")), P("seq"),
        )
        got = np.asarray(prog(jnp.asarray(a), jnp.asarray(b)))
        assert np.allclose(got, recurrence_np(a, b), atol=1e-4)

    def test_gradient_matches_single_device(self, devices):
        mesh = make_mesh_1d("seq", 4)
        T, D = 16, 4
        rng = np.random.default_rng(1)
        a = jnp.asarray(rng.uniform(0.3, 0.95, (T, D)).astype(np.float32))
        b = jnp.asarray(rng.standard_normal((T, D)).astype(np.float32))

        sharded = jax.shard_map(
            lambda aa, bb: ssm_scan(aa, bb, "seq"),
            mesh=mesh, in_specs=(P("seq"), P("seq")), out_specs=P("seq"),
            check_vma=False,
        )
        g_sh = jax.jit(jax.grad(lambda aa: (sharded(aa, b) ** 2).sum()))(a)

        def seq_loss(aa):
            def step(h, ab):
                h = ab[0] * h + ab[1]
                return h, h
            _, hs = jax.lax.scan(step, jnp.zeros(D), (aa, b))
            return (hs ** 2).sum()

        g_seq = jax.jit(jax.grad(seq_loss))(a)
        assert np.allclose(np.asarray(g_sh), np.asarray(g_seq), atol=1e-4)


class TestSSMBlock:
    def test_sharded_block_matches_local_oracle(self, devices):
        cfg = SSMConfig(d_model=8, d_state=16)
        params = init_params(0, cfg)
        mesh = make_mesh_1d("seq", 8)
        T = 32
        x = jnp.asarray(
            np.random.default_rng(2).standard_normal((T, cfg.d_model))
            .astype(np.float32)
        )
        prog = run_spmd(
            mesh, lambda xx: ssm_block(params, xx, "seq"), P("seq"), P("seq")
        )
        got = np.asarray(prog(x))
        oracle = np.asarray(jax.jit(
            lambda xx: ssm_block(params, xx, None)
        )(x))
        assert np.allclose(got, oracle, atol=1e-4)

    def test_block_trains_sharded(self, devices):
        cfg = SSMConfig(d_model=8, d_state=16)
        params = init_params(0, cfg)
        mesh = make_mesh_1d("seq", 4)
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.standard_normal((16, cfg.d_model)).astype(np.float32))
        y = jnp.asarray(rng.standard_normal((16, cfg.d_model)).astype(np.float32))

        fwd = jax.shard_map(
            lambda p, xx: ssm_block(p, xx, "seq"),
            mesh=mesh, in_specs=(P(), P("seq")), out_specs=P("seq"),
            check_vma=False,
        )

        def loss(p):
            return ((fwd(p, x) - y) ** 2).mean()

        l0 = float(jax.jit(loss)(params))
        grads = jax.jit(jax.grad(loss))(params)
        params2 = jax.tree.map(lambda p, g: p - 0.1 * g, params, grads)
        l1 = float(jax.jit(loss)(params2))
        assert np.isfinite(l0) and l1 < l0
