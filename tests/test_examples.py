"""Smoke test: every example's main() runs clean on the CPU mesh.

The examples are the L5' program catalog (SURVEY.md §2.2/§7.6); running
them end-to-end is the closest analogue of the reference's self-checking
mains.
"""

import importlib
import pathlib
import sys

import pytest

EXAMPLES_DIR = pathlib.Path(__file__).parent.parent / "examples"
sys.path.insert(0, str(EXAMPLES_DIR.parent))

EXAMPLES = sorted(p.stem for p in EXAMPLES_DIR.glob("ex*.py"))


@pytest.mark.parametrize("name", EXAMPLES)
def test_example_runs(name, capsys):
    import inspect

    mod = importlib.import_module(f"examples.{name}")
    # argv-capable examples (ex08+) get an empty CLI — defaults — rather
    # than pytest's own argv
    if "argv" in inspect.signature(mod.main).parameters:
        mod.main([])
    else:
        mod.main()
    out = capsys.readouterr().out
    assert "==" in out  # banner printed
    assert "FAILED" not in out
    # self-checking examples must actually REACH their check: an example
    # that silently skipped it would otherwise pass this smoke test
    src = (EXAMPLES_DIR / f"{name}.py").read_text()
    if '"PASSED"' in src or "'PASSED'" in src:
        assert "PASSED" in out, f"{name} never printed its self-check"
