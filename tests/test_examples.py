"""Smoke test: every example's main() runs clean on the CPU mesh.

The examples are the L5' program catalog (SURVEY.md §2.2/§7.6); running
them end-to-end is the closest analogue of the reference's self-checking
mains.
"""

import importlib
import pathlib
import sys

import pytest

EXAMPLES_DIR = pathlib.Path(__file__).parent.parent / "examples"
sys.path.insert(0, str(EXAMPLES_DIR.parent))

EXAMPLES = sorted(p.stem for p in EXAMPLES_DIR.glob("ex*.py"))


@pytest.mark.parametrize("name", EXAMPLES)
def test_example_runs(name, capsys):
    mod = importlib.import_module(f"examples.{name}")
    mod.main()
    out = capsys.readouterr().out
    assert "==" in out  # banner printed
    assert "FAILED" not in out
