"""Host-free decode (ISSUE 19, marker ``hostfree``).

The lift: neither ``spec_k > 0`` nor ``kv_host_pages > 0`` clamps
``macro_steps`` any more — speculation's propose/verify/accept rides
the scan carry (``serve.decode.propose_draft_batch`` +
``serve.sampling.accept_batch``), stop-token EOS is an in-carry mask
folded into the early-exit psum, and the tiered wave prefetch is
issued behind the running scan.  The correctness anchors:

- **in-carry EOS**: a per-request ``stop_tokens`` hit mid-scan
  truncates the stream bit-identically to the host-side budget path —
  the stop token is EMITTED (closes the output) and nothing follows
  it, across macro_steps x spec_k x the dtype ladder on the 1x1 and
  2x2 meshes; garbage positions past a stop never reach the KV pool
  (pages return to the free list exactly);
- **composed bit-identity**: spec x macro, tiered x macro, and
  spec x tiered x macro all reproduce the T=1 engine's greedy outputs,
  with FEWER dispatches (the clamp is gone, not hidden);
- **async macro tick** (``ServeConfig(async_macro=True)``): chaining
  the next scan's dispatch behind the running one changes WHEN host
  syncs happen, never what is computed — outputs and the dispatch /
  host-sync counters are identical to the synchronous macro engine;
- **device == host speculation**: ``propose_draft_batch`` matches the
  host ``propose_draft`` rule position for position, and
  ``accept_batch`` matches ``accept_speculative`` (greedy bit-pinned;
  temperature draws off the same fold_in chains);
- **config-21 regress gate**: the spec-x-macro and tiered-x-macro
  record rows are direction-registered (dispatches/host-syncs LOWER
  on the tight static band, tokens/s HIGHER behind the CPU noise
  floor), a clean pair exits 0, an injected dispatch regression exits
  1 (subprocess proof), and a ``--check`` against a PRE-PR artifact
  reports the new rows as ``added`` only — never a failure.

Shapes reuse test_serve_macro's cfg/scfg values (same jit cache
entries within a tier-1 run).
"""

import dataclasses
import json
import math
import os
import subprocess
import sys

import pytest
import jax

from tpuscratch.models.transformer import TransformerConfig
from tpuscratch.runtime.mesh import make_mesh
from tpuscratch.obs import regress
from tpuscratch.serve import Request, ServeConfig, ServeEngine

pytestmark = pytest.mark.hostfree


def cfg_for(**kw):
    kw.setdefault("n_layers", 1)
    kw.setdefault("capacity_factor", 4.0)
    return TransformerConfig(
        d_model=32, n_heads=4, n_experts=4, d_ff=48, **kw
    )


SCFG = ServeConfig(n_slots=4, n_pages=16, page_size=4, max_seq=24,
                   vocab=16)

REQS = [
    Request(rid=i, prompt=tuple((3 * i + j) % 16 for j in range(2 + i % 5)),
            max_new=2 + (i * 3) % 6)
    for i in range(6)
]


def run_engine(dims=(1, 1), reqs=REQS, cfg=None, **scfg_kw):
    cfg = cfg or cfg_for()
    n = dims[0] * dims[1]
    mesh = make_mesh(dims, ("dp", "sp"), jax.devices()[:n])
    scfg = dataclasses.replace(SCFG, **scfg_kw)
    eng = ServeEngine(mesh, cfg, scfg)
    return eng, eng.run(reqs)


_STOP_CACHE = {}


def stop_reqs():
    """REQS with deterministic per-request stop tokens: even rids stop
    on a token their greedy stream actually emits mid-way (truncation
    fires), odd rids stop on a token absent from their stream (the
    mask rides along without firing) — derived once from the T=1
    no-stop baseline, so every engine under test faces the same mix of
    hit and miss stops."""
    if "reqs" not in _STOP_CACHE:
        _, base = run_engine()
        outs = dict(base.outputs)
        reqs = []
        for r in REQS:
            toks = outs[r.rid]
            if r.rid % 2 == 0 and len(toks) >= 2:
                stop = (toks[len(toks) // 2],)
            else:
                missing = next(
                    (t for t in range(SCFG.vocab) if t not in toks), None
                )
                stop = (missing,) if missing is not None else ()
            reqs.append(dataclasses.replace(r, stop_tokens=stop))
        _STOP_CACHE["reqs"] = reqs
    return _STOP_CACHE["reqs"]


def stop_ref():
    """The T=1 host-path reference run for :func:`stop_reqs` — one
    engine run shared by every matrix cell."""
    if "ref" not in _STOP_CACHE:
        _, _STOP_CACHE["ref"] = run_engine(reqs=stop_reqs())
    return _STOP_CACHE["ref"]


class TestInCarryEOS:
    def test_stop_truncates_like_the_budget_path(self):
        # the EOS contract stated as an identity: stopping on the token
        # at generated-index j produces EXACTLY the output of the same
        # request budget-limited to max_new = j + 1 — the two "stop
        # decoding here" mechanisms are one path
        _, base = run_engine()
        outs = dict(base.outputs)
        rid = max(outs, key=lambda r: len(outs[r]))
        toks = outs[rid]
        assert len(toks) >= 3
        tok = toks[len(toks) // 2]
        idx = toks.index(tok)
        req = next(r for r in REQS if r.rid == rid)
        _, r_stop = run_engine(
            reqs=[dataclasses.replace(req, stop_tokens=(tok,))]
        )
        _, r_budget = run_engine(
            reqs=[dataclasses.replace(req, max_new=idx + 1)]
        )
        assert dict(r_stop.outputs)[rid] == toks[:idx + 1]
        assert dict(r_budget.outputs)[rid] == toks[:idx + 1]
        assert dict(r_stop.outputs)[rid][-1] == tok

    @pytest.mark.parametrize(
        "T,spec_k",
        [(4, 0), (16, 0), (1, 3), (4, 3),
         pytest.param(16, 3, marks=pytest.mark.slow)],
    )
    def test_eos_matrix_matches_t1(self, T, spec_k):
        # the in-carry stop mask (macro scan / spec carry) truncates
        # bit-identically to the T=1 host-side rule, hit and miss stops
        # mixed in one bank
        sreqs = stop_reqs()
        ref = stop_ref()
        eng, rep = run_engine(reqs=sreqs, macro_steps=T, spec_k=spec_k)
        assert rep.outputs == ref.outputs
        assert rep.tokens_generated == ref.tokens_generated
        assert eng.free_pages() == [SCFG.n_pages]

    @pytest.mark.parametrize(
        "kv_dtype",
        ["int8", pytest.param("fp8", marks=pytest.mark.slow)],
    )
    def test_eos_on_quantized_rungs(self, kv_dtype):
        sreqs = stop_reqs()
        _, ref = run_engine(reqs=sreqs, kv_dtype=kv_dtype)
        _, rep = run_engine(reqs=sreqs, kv_dtype=kv_dtype, macro_steps=4,
                            spec_k=3)
        assert rep.outputs == ref.outputs

    @pytest.mark.slow
    def test_eos_on_2x2_mesh(self):
        sreqs = stop_reqs()
        _, ref = run_engine(dims=(2, 2), reqs=sreqs)
        _, rep = run_engine(dims=(2, 2), reqs=sreqs, macro_steps=16,
                            spec_k=3)
        assert rep.outputs == ref.outputs

    def test_garbage_never_escapes(self):
        # positions past a mid-scan stop are write-suppressed: the stop
        # token is the LAST emitted token of its stream, the full page
        # pool returns to the free list, and no cached KV survives the
        # drain — a leaked garbage write would hold pages or extend an
        # output past its stop
        sreqs = stop_reqs()
        stops = {r.rid: set(r.stop_tokens) for r in sreqs}
        eng, rep = run_engine(reqs=sreqs, macro_steps=4, spec_k=3)
        hit = 0
        for rid, toks in rep.outputs:
            hits = [j for j, t in enumerate(toks) if t in stops[rid]]
            if hits:
                hit += 1
                assert hits[0] == len(toks) - 1, (
                    f"rid {rid}: tokens emitted past the stop token"
                )
        assert hit >= 1              # the derived mix truncates someone
        assert eng.free_pages() == [SCFG.n_pages]
        assert eng.cached_pages == 0

    def test_out_of_vocab_stop_token_rejected(self):
        with pytest.raises(ValueError):
            run_engine(reqs=[dataclasses.replace(
                REQS[0], stop_tokens=(SCFG.vocab,)
            )])


class TestHostfreeCompose:
    def test_spec_tiered_macro_all_composed(self):
        # the full composition the clamp used to forbid twice over:
        # draft + verify in the scan carry AND wave prefetch behind the
        # running scan, still bit-identical to the plain T=1 engine
        sreqs = stop_reqs()
        ref = stop_ref()
        _, rep = run_engine(reqs=sreqs, macro_steps=4, spec_k=3,
                            kv_host_pages=4)
        assert rep.outputs == ref.outputs
        assert rep.dispatches < ref.dispatches

    def test_engine_event_reports_full_T_and_no_clamp_reason(self, tmp_path):
        # satellite 1: the serve/engine event's macro_steps_effective
        # is the configured T and the stale clamp reasons ("spec_k",
        # "kv_host_pages") never appear — the key is OMITTED, not None
        from tpuscratch.obs.sink import Sink

        path = str(tmp_path / "ev.jsonl")
        cfg = cfg_for()
        mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        # construction alone emits the event (jit programs compile
        # lazily — no run needed, so the odd T=8 shape costs nothing)
        scfg = dataclasses.replace(SCFG, macro_steps=8, spec_k=2,
                                   kv_host_pages=4)
        with Sink(path) as sink:
            eng = ServeEngine(mesh, cfg, scfg, sink=sink)
        events = [json.loads(l) for l in open(path)]
        ev = next(e for e in events if e["event"] == "serve/engine")
        assert ev["macro_steps_effective"] == 8
        assert "macro_clamped_by" not in ev
        assert eng.macro_steps_effective == 8
        assert eng.macro_clamped_by is None
        assert eng.metrics.gauge("serve/macro_steps").value == 8

    def test_async_macro_bit_identical(self):
        eng_s, r_s = run_engine(macro_steps=4)
        eng_a, r_a = run_engine(macro_steps=4, async_macro=True)
        assert r_a.outputs == r_s.outputs
        assert r_a.dispatches == r_s.dispatches
        assert r_a.host_syncs == r_s.host_syncs
        assert eng_a.free_pages() == eng_s.free_pages()

    def test_async_macro_single_stream_identity(self):
        # the ex24/ex32 dispatch identity survives chaining: the async
        # engine issues the same ceil(slot_steps / T) dispatches, just
        # without a host sync between them
        req = Request(rid=0, prompt=(1, 2, 3), max_new=10)
        for T in (4, 16):
            _, rep = run_engine(reqs=[req], macro_steps=T,
                                async_macro=True)
            assert rep.slot_steps == 9
            assert rep.dispatches == math.ceil(9 / T)
            assert rep.host_syncs == rep.dispatches

    def test_spec_macro_with_share_and_chunk(self):
        kw = dict(prefix_share=True, chunk_prefill=2, kv_dtype="int8")
        _, r1 = run_engine(**kw)
        _, r4 = run_engine(macro_steps=4, spec_k=3, **kw)
        assert r4.outputs == r1.outputs
        assert (r4.prefill_tokens, r4.shared_tokens) == (
            r1.prefill_tokens, r1.shared_tokens
        )

    def test_spec_macro_under_router(self):
        from tpuscratch.serve import FleetRouter, RouterConfig

        cfg = cfg_for()
        mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        reqs = [Request(rid=i, prompt=(1 + i, 2, 3), max_new=5)
                for i in range(4)]

        def run(**kw):
            reps = [ServeEngine(mesh, cfg,
                                dataclasses.replace(SCFG, **kw))
                    for _ in range(2)]
            return FleetRouter(reps, RouterConfig(affinity=False)).run(reqs)

        r1 = run()
        rc = run(macro_steps=4, spec_k=3)
        assert rc.outputs == r1.outputs
        assert 0 < rc.dispatches < r1.dispatches

    def test_stops_under_disagg_macro(self):
        from tpuscratch.serve import DisaggEngine

        cfg = cfg_for()
        mesh = make_mesh((2, 2), ("dp", "sp"), jax.devices()[:4])
        reqs = [Request(rid=i, prompt=(1 + i, 2), max_new=4)
                for i in range(4)]

        def run(reqs, T):
            eng = DisaggEngine(mesh, cfg,
                               dataclasses.replace(SCFG, macro_steps=T))
            return eng.run(reqs)

        base = dict(run(reqs, 1).outputs)
        # stop each stream on its second token: truncation crosses the
        # prefill->decode handoff and the macro scan alike
        sreqs = [dataclasses.replace(r, stop_tokens=(base[r.rid][1],))
                 for r in reqs]
        want = {r.rid: base[r.rid][:base[r.rid].index(r.stop_tokens[0]) + 1]
                for r in sreqs}
        for T in (1, 4):
            got = dict(run(sreqs, T).outputs)
            assert got == want, f"T={T}"

    def test_async_macro_with_stops_falls_back_identically(self):
        # stop-token slots disable the chain (their early exit needs
        # the sync) — the fallback must be invisible in outputs
        sreqs = stop_reqs()
        _, r_s = run_engine(reqs=sreqs, macro_steps=4)
        _, r_a = run_engine(reqs=sreqs, macro_steps=4, async_macro=True)
        assert r_a.outputs == r_s.outputs


class TestDeviceSpecHelpers:
    def test_propose_draft_batch_matches_host_rule(self):
        import numpy as np
        import jax.numpy as jnp

        from tpuscratch.serve.decode import (
            propose_draft,
            propose_draft_batch,
        )

        rng = np.random.default_rng(0)
        B, S, k, ngram = 8, 24, 3, 2
        hist = np.zeros((B, S), np.int32)
        lens = np.zeros((B,), np.int32)
        for b in range(B):
            n = int(rng.integers(1, S + 1))
            # vocab 5: suffixes repeat often enough that full matches,
            # partial matches, and no-match all occur across the bank
            hist[b, :n] = rng.integers(0, 5, size=n)
            lens[b] = n
        drafts, dlen = propose_draft_batch(
            jnp.asarray(hist), jnp.asarray(lens), k, ngram=ngram
        )
        for b in range(B):
            want = propose_draft(tuple(hist[b, :lens[b]]), k, ngram=ngram)
            got = tuple(int(t) for t in drafts[b, :int(dlen[b])])
            assert got == want, f"slot {b}: {got} != host {want}"
            assert all(int(t) == 0 for t in drafts[b, int(dlen[b]):])

    @pytest.mark.parametrize("temperature,top_k", [(0.0, 0), (0.8, 0),
                                                   (0.7, 3)])
    def test_accept_batch_matches_host_rule(self, temperature, top_k):
        import numpy as np
        import jax.numpy as jnp

        from tpuscratch.serve.sampling import (
            accept_batch,
            accept_speculative,
        )

        rng = np.random.default_rng(1)
        seed, B, K, V = 11, 6, 4, 16
        logits = rng.normal(size=(B, K, V)).astype(np.float32)
        drafts = rng.integers(0, V, size=(B, K - 1)).astype(np.int32)
        dlen = np.array([0, 1, 2, 3, 3, 2], np.int32)
        rids = np.arange(B, dtype=np.int32)
        pos0 = rng.integers(0, 8, size=(B,)).astype(np.int32)
        n_acc, term = accept_batch(
            jax.random.key(seed), jnp.asarray(rids), jnp.asarray(pos0),
            jnp.asarray(logits), jnp.asarray(drafts), jnp.asarray(dlen),
            temperature=temperature, top_k=top_k,
        )
        for b in range(B):
            dl = int(dlen[b])
            a, toks = accept_speculative(
                seed, int(rids[b]), int(pos0[b]),
                logits[b, :dl + 1], tuple(drafts[b, :dl]),
                temperature=temperature, top_k=top_k,
            )
            assert int(n_acc[b]) == a, f"slot {b}: accept count"
            assert int(term[b]) == toks[-1], f"slot {b}: terminal token"


class TestHostfreeRegressGate:
    ROW_SPEC = {
        "config": 21, "metric": "serve_decode_spec_macro",
        "platform": "cpu", "value": 7.8e3,
        "tokens_per_s_t1": 2.1e3, "tokens_per_s_t4": 7.8e3,
        "dispatches_per_token_t1": 0.2963,
        "dispatches_per_token_t4": 0.0625,
        "host_syncs_per_token_t4": 0.0625,
        "accept_len_mean_t4": 3.0,
    }
    ROW_TIER = {
        "config": 21, "metric": "serve_decode_macro_tiered",
        "platform": "cpu", "value": 7.0e3,
        "tokens_per_s_t1": 2.2e3, "tokens_per_s_t4": 7.0e3,
        "dispatches_per_token_t1": 0.25,
        "dispatches_per_token_t4": 0.0625,
        "host_syncs_per_token_t4": 0.0625,
    }

    def test_directions_and_floors_registered(self):
        for m in ("serve_decode_spec_macro", "serve_decode_macro_tiered"):
            assert regress.direction(m) == "higher"
            assert regress.noise_floor(m, "cpu") > 0
            assert regress.noise_floor(m, "tpu") == 0
        for f in ("dispatches_per_token_t1", "dispatches_per_token_t4",
                  "host_syncs_per_token_t4"):
            assert regress.direction(f) == "lower"
            # static counters keep the TIGHT band (no floor)
            assert regress.noise_floor(f, "cpu") == 0
        assert regress.direction("accept_len_mean_t4") == "higher"
        assert regress.direction("tokens_per_s_t4") == "higher"
        assert regress.noise_floor("tokens_per_s_t4", "cpu") > 0

    def test_clean_pair_passes_injected_fails(self):
        rows = [dict(self.ROW_SPEC), dict(self.ROW_TIER)]
        base = regress.index_rows([dict(r) for r in rows])
        clean = regress.compare(
            base, regress.index_rows([dict(r) for r in rows]), noise=0.05
        )
        assert not regress.has_regression(clean)

        # the clamp coming back reads as dispatches/token at ~T=1
        # levels: a static field, tight band, regresses immediately
        bad = [dict(self.ROW_SPEC, dispatches_per_token_t4=0.2963),
               dict(self.ROW_TIER)]
        findings = regress.compare(base, regress.index_rows(bad),
                                   noise=0.05)
        assert regress.has_regression(findings)
        names = {f.field for f in findings if f.status == "regressed"}
        assert "dispatches_per_token_t4" in names

        # accepted length collapsing (device proposer broken) regresses
        worse = [dict(self.ROW_SPEC, accept_len_mean_t4=0.2),
                 dict(self.ROW_TIER)]
        assert regress.has_regression(
            regress.compare(base, regress.index_rows(worse), noise=0.05)
        )

    def test_pre_pr_artifact_reports_added_only(self):
        # --check against an artifact recorded BEFORE this PR: the two
        # config-21 rows have no baseline — every finding they produce
        # must be status "added" (informational), never a failure
        pre = [{
            "config": 12, "metric": "serve_decode_macro",
            "platform": "cpu", "value": 1.5e4,
            "tokens_per_s_t1": 1.2e3, "tokens_per_s_t16": 1.5e4,
            "dispatches_per_token_t16": 0.0625,
        }]
        cur = [dict(r) for r in pre] + [dict(self.ROW_SPEC),
                                        dict(self.ROW_TIER)]
        findings = regress.compare(regress.index_rows(pre),
                                   regress.index_rows(cur), noise=0.05)
        assert not regress.has_regression(findings)
        new = [f for f in findings
               if f.metric in ("serve_decode_spec_macro",
                               "serve_decode_macro_tiered")]
        assert new and all(f.status == "added" for f in new)

    def test_cli_subprocess_proof(self, tmp_path):
        """The config-21 gate as a subprocess: a clean pair exits 0, an
        injected dispatches-per-token regression exits 1."""

        def write(name, rows):
            p = str(tmp_path / name)
            with open(p, "w") as f:
                for r in rows:
                    f.write(json.dumps(r) + "\n")
            return p

        base = write("base.json", [self.ROW_SPEC, self.ROW_TIER])
        good = write("good.json", [
            dict(self.ROW_SPEC, value=8.0e3, tokens_per_s_t4=8.0e3),
            dict(self.ROW_TIER),
        ])
        bad = write("bad.json", [
            dict(self.ROW_SPEC),
            dict(self.ROW_TIER, dispatches_per_token_t4=0.25),
        ])
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-m", "tpuscratch.obs.regress", base, good],
            capture_output=True, text=True, env=env, timeout=120,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        r = subprocess.run(
            [sys.executable, "-m", "tpuscratch.obs.regress", base, bad],
            capture_output=True, text=True, env=env, timeout=120,
        )
        assert r.returncode == 1, r.stdout + r.stderr
        assert "REGRESSED" in r.stdout
