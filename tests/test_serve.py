"""tpuscratch.serve: paged KV cache, cached decode, continuous batching.

The correctness anchors:
- allocator invariants: unique in-range ids, all-or-nothing grants,
  double-free rejection, free list restored after drain;
- decode-vs-full equivalence: prefill + cached single-token decode
  reproduce ``model_apply``'s output at EVERY position, on the 1x1 mesh
  and on a dp x sp mesh (pages sharded over dp, heads over sp), with
  ragged per-slot lengths exercising the true-length masking;
- engine: staggered arrival/completion with more requests than slots,
  free-page-watermark admission, no page leaks after drain, and ZERO
  decode recompiles after warmup (the CompileCounter hook);
- sampling determinism under fixed per-request keys.

Equivalence holds in the no-token-dropped MoE regime (capacity_factor
== n_experts, as in test_models), since capacity-bound routing is the
one component whose per-token output depends on batch composition.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tpuscratch.comm import run_spmd
from tpuscratch.models.transformer import (
    TransformerConfig,
    init_params,
    model_apply,
    param_spec,
)
from tpuscratch.ops.attention import decode_attention
from tpuscratch.runtime.mesh import make_mesh
from tpuscratch.serve import (
    CacheGeometry,
    PageAllocator,
    Request,
    ServeConfig,
    ServeEngine,
    init_kv_cache,
    request_key,
    sample_batch,
    sample_logits,
)
from tpuscratch.serve.decode import CompileCounter, build_decode_step, build_prefill

D = 32


def cfg_for(**kw):
    # capacity_factor == n_experts: nothing dropped, so cached-decode
    # outputs are batch-composition-independent (same rule as test_models)
    kw.setdefault("capacity_factor", 4.0)
    return TransformerConfig(
        d_model=D, n_heads=4, n_experts=4, d_ff=48, n_layers=2, **kw
    )


class TestPageAllocator:
    def test_ids_unique_and_in_range(self):
        a = PageAllocator(6)
        got = a.alloc(6)
        assert sorted(got) == list(range(6))
        assert a.n_free == 0 and a.n_live == 6

    def test_all_or_nothing(self):
        a = PageAllocator(4)
        assert a.alloc(3) is not None
        assert a.alloc(2) is None          # only 1 free: grant nothing
        assert a.n_free == 1               # the failed request took nothing
        assert a.alloc(1) is not None

    def test_double_free_and_foreign_free_raise(self):
        a = PageAllocator(4)
        pages = a.alloc(2)
        a.free(pages)
        with pytest.raises(ValueError):
            a.free([pages[0]])             # double free
        b = a.alloc(1)
        with pytest.raises(ValueError):
            a.free([(b[0] + 1) % 4])       # not a live id

    def test_drain_restores_free_list(self):
        a = PageAllocator(8)
        held = [a.alloc(2) for _ in range(3)]
        a.free(held[1])
        held[1] = a.alloc(2)
        for h in held:
            a.free(h)
        assert a.n_free == 8 and a.n_live == 0


class TestDecodeAttention:
    def test_matches_dense_reference_with_ragged_lengths(self):
        rng = np.random.default_rng(0)
        n_pages, page, H, Dh = 6, 4, 2, 8
        B, max_pages = 3, 4
        k_pages = rng.standard_normal((n_pages, page, H, Dh)).astype(np.float32)
        v_pages = rng.standard_normal((n_pages, page, H, Dh)).astype(np.float32)
        # scrambled page order per sequence; sentinel tail entries
        table = np.array([[2, 0, 5, n_pages],
                          [1, 4, n_pages, n_pages],
                          [3, n_pages, n_pages, n_pages]], np.int32)
        lens = np.array([9, 6, 2], np.int32)
        q = rng.standard_normal((B, H, Dh)).astype(np.float32)
        out = np.asarray(decode_attention(
            jnp.asarray(q), jnp.asarray(k_pages), jnp.asarray(v_pages),
            jnp.asarray(table), jnp.asarray(lens),
        ))
        for b in range(B):
            n_pg = -(-int(lens[b]) // page)
            ks = k_pages[table[b, :n_pg]].reshape(-1, H, Dh)[: lens[b]]
            vs = v_pages[table[b, :n_pg]].reshape(-1, H, Dh)[: lens[b]]
            s = np.einsum("hd,thd->ht", q[b], ks) / np.sqrt(Dh)
            p = np.exp(s - s.max(-1, keepdims=True))
            ref = np.einsum("ht,thd->hd", p / p.sum(-1, keepdims=True), vs)
            np.testing.assert_allclose(out[b], ref, atol=1e-5)

    def test_empty_slot_returns_zeros(self):
        z = decode_attention(
            jnp.ones((1, 2, 8)), jnp.ones((2, 4, 2, 8)), jnp.ones((2, 4, 2, 8)),
            jnp.full((1, 2), 2, jnp.int32), jnp.zeros((1,), jnp.int32),
        )
        assert float(jnp.abs(z).max()) == 0.0


class TestDecodeEquivalence:
    @pytest.mark.parametrize("dims", [(1, 1), (2, 2)])
    def test_prefill_and_decode_match_model_apply(self, dims):
        cfg = cfg_for()
        n = dims[0] * dims[1]
        mesh = make_mesh(dims, ("dp", "sp"), jax.devices()[:n])
        m1 = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        full = run_spmd(
            m1, lambda p, x: model_apply(p, x, cfg)[0],
            (param_spec(cfg), P("dp", "sp")), P("dp", "sp"),
        )
        params = init_params(1, cfg)
        geom = CacheGeometry(cfg.n_layers, n_pages=16, page_size=4,
                             n_heads=cfg.n_heads, d_head=cfg.d_head)
        dp_size = dims[0]
        kv = init_kv_cache(geom, dp_size)
        counter = CompileCounter()
        decode = build_decode_step(mesh, cfg, geom, counter=counter)
        prefill = build_prefill(mesh, cfg, geom)

        rng = np.random.default_rng(0)
        B, T = 2, 3
        lens = [3, 5]                     # ragged prompts
        max_pages = 4
        seq = rng.standard_normal((B, max(lens) + T, D)).astype(np.float32)
        pages = {0: [0, 1], 1: [0, 1] if dp_size == 2 else [2, 3]}
        slots_per_group = B // dp_size

        for b in range(B):
            s0 = lens[b]
            x = np.zeros((8, D), np.float32)
            x[:s0] = seq[b, :s0]
            rows = np.full((dp_size, max_pages), geom.n_pages, np.int32)
            rows[b // slots_per_group, : len(pages[b])] = pages[b]
            out, kv = prefill(params, kv, jnp.asarray(x), jnp.asarray(rows),
                              jnp.int32(s0))
            ref = np.asarray(full(params, jnp.asarray(seq[b:b + 1, :s0])))[0]
            # every prompt position, not just the last
            np.testing.assert_allclose(np.asarray(out)[:s0], ref, atol=2e-4)

        for t in range(T):
            positions = [lens[b] + t for b in range(B)]
            x = np.stack([seq[b, positions[b]] for b in range(B)])
            tables = np.full((B, max_pages), geom.n_pages, np.int32)
            wp = np.zeros((B,), np.int32)
            wo = np.zeros((B,), np.int32)
            sl = np.zeros((B,), np.int32)
            for b in range(B):
                tables[b, : len(pages[b])] = pages[b]
                wp[b] = pages[b][positions[b] // geom.page_size]
                wo[b] = positions[b] % geom.page_size
                sl[b] = positions[b] + 1
            out, kv = decode(params, kv, jnp.asarray(x), jnp.asarray(tables),
                             jnp.asarray(wp), jnp.asarray(wo), jnp.asarray(sl))
            out = np.asarray(out)
            for b in range(B):
                pos = positions[b]
                ref = np.asarray(
                    full(params, jnp.asarray(seq[b:b + 1, : pos + 1]))
                )[0, pos]
                np.testing.assert_allclose(out[b], ref, atol=2e-4)
        # one compiled decode program covered every step
        assert counter.count == 1


class TestIdleSlotIsolation:
    def test_idle_slots_never_perturb_real_tokens(self):
        # capacity_factor=2.0 < n_experts: MoE capacity BINDS.  Idle
        # slots' zero vectors must not consume expert capacity ahead of
        # real tokens — the same token in slot 0 (no idles ahead) and
        # slot 7 (seven idles ahead) must produce identical outputs.
        cfg = cfg_for(capacity_factor=2.0)
        mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        geom = CacheGeometry(cfg.n_layers, n_pages=8, page_size=4,
                             n_heads=cfg.n_heads, d_head=cfg.d_head)
        decode = build_decode_step(mesh, cfg, geom)
        params = init_params(0, cfg)
        vec = np.random.default_rng(1).standard_normal((D,)).astype(np.float32)
        B, MP = 8, 2

        def run(slot):
            kv = init_kv_cache(geom, 1)
            x = np.zeros((B, D), np.float32)
            x[slot] = vec
            tables = np.full((B, MP), geom.n_pages, np.int32)
            tables[slot, 0] = 0
            wp = np.full((B,), geom.n_pages, np.int32)
            wp[slot] = 0
            wo = np.zeros((B,), np.int32)
            lens = np.zeros((B,), np.int32)
            lens[slot] = 1
            out, _ = decode(params, kv, jnp.asarray(x), jnp.asarray(tables),
                            jnp.asarray(wp), jnp.asarray(wo),
                            jnp.asarray(lens))
            return np.asarray(out)[slot]

        np.testing.assert_allclose(run(0), run(7), atol=1e-6)


class TestEngine:
    def make(self, scfg=None, dims=(2, 2), **cfg_kw):
        cfg = cfg_for(**cfg_kw)
        n = dims[0] * dims[1]
        mesh = make_mesh(dims, ("dp", "sp"), jax.devices()[:n])
        scfg = scfg or ServeConfig(n_slots=4, n_pages=16, page_size=4,
                                   max_seq=24, vocab=16)
        return ServeEngine(mesh, cfg, scfg)

    def test_staggered_drain_no_leaks_no_recompiles(self):
        eng = self.make()
        free0 = eng.free_pages()
        reqs = [
            Request(rid=i, prompt=tuple(range(1, 2 + i % 5)),
                    max_new=1 + (i * 3) % 6)
            for i in range(7)          # > n_slots: queueing is exercised
        ]
        rep = eng.run(reqs)
        assert rep.completed == 7
        by_rid = dict(rep.outputs)
        for r in reqs:
            assert len(by_rid[r.rid]) == r.max_new
            assert all(0 <= t < 16 for t in by_rid[r.rid])
        assert eng.free_pages() == free0               # no page leaks
        assert rep.decode_compiles == 1                # zero steady-state recompiles
        assert rep.prefill_compiles <= 2               # one per shape bucket
        assert rep.tokens_generated == sum(r.max_new for r in reqs)

    def test_midstream_submission_backfills_slots(self):
        eng = self.make()
        eng.submit(Request(rid=0, prompt=(1, 2), max_new=8))
        eng.submit(Request(rid=1, prompt=(3,), max_new=2))
        for _ in range(3):
            eng.step()
        compiles_warm = eng.decode_compiles
        # rid=1 finished and its slot is free again; feed new work mid-run
        eng.submit(Request(rid=2, prompt=(4, 5, 6), max_new=3))
        rep = eng.run([])
        assert {rid for rid, _ in rep.outputs} >= {2}
        assert eng.n_active == 0 and eng.n_queued == 0
        assert eng.decode_compiles == compiles_warm    # warm == forever
        assert eng.free_pages() == [16, 16]

    def test_watermark_serializes_when_pool_is_tight(self):
        # one request's footprint == one group's WHOLE pool: each group
        # has 2 slots but pages for only 1 request, so admission must
        # hold half the slots idle (free slot, no pages) yet still drain
        scfg = ServeConfig(n_slots=4, n_pages=4, page_size=4, max_seq=16,
                           vocab=16)
        eng = self.make(scfg=scfg)
        reqs = [Request(rid=i, prompt=(1, 2, 3), max_new=13) for i in range(5)]
        for r in reqs:
            eng.submit(r)
        peak = 0
        outputs = {}
        for _ in range(200):
            if not (eng.n_queued or eng.n_active):
                break
            for rid, toks in eng.step():
                outputs[rid] = toks
            peak = max(peak, eng.n_active)
        assert sorted(outputs) == [0, 1, 2, 3, 4]
        assert peak == 2          # 1 per dp group, never the 4 slots
        assert eng.free_pages() == [4, 4]

    def test_failed_prefill_returns_pages_and_requeues(self):
        eng = self.make()
        free0 = eng.free_pages()

        class Boom(RuntimeError):
            pass

        def exploding_prefill(*a, **k):
            raise Boom("transient device error")

        # pre-seed the bucket cache so _admit uses the exploding program
        eng._prefills = {8: exploding_prefill}
        eng.submit(Request(rid=0, prompt=(1, 2), max_new=2))
        with pytest.raises(Boom):
            eng.step()
        assert eng.free_pages() == free0     # the grant came back
        assert eng.n_queued == 1             # the request is retryable
        assert eng.n_active == 0

    def test_failed_decode_recovers_and_replays_identically(self):
        # a raising compiled decode may have consumed the DONATED cache:
        # recovery must reset the pool, requeue in-flight requests, and
        # the replay must reproduce the uninterrupted run bit-for-bit
        scfg = ServeConfig(n_slots=4, n_pages=16, page_size=4, max_seq=24,
                           vocab=16)
        reqs = [Request(rid=i, prompt=(1 + i, 2), max_new=4)
                for i in range(3)]
        clean = self.make(scfg=scfg).run(reqs)

        eng = self.make(scfg=scfg)
        for r in reqs:
            eng.submit(r)
        eng.step()                           # slots active mid-stream

        class Boom(RuntimeError):
            pass

        real_decode = eng._decode

        def exploding_decode(*a, **k):
            raise Boom("mid-flight device error")

        eng._decode = exploding_decode
        with pytest.raises(Boom):
            eng.step()
        assert eng.n_active == 0 and eng.n_queued == 3
        assert eng.free_pages() == [16, 16]
        eng._decode = real_decode
        rep = eng.run([])
        assert rep.outputs == clean.outputs  # deterministic replay

    def test_deterministic_replay(self):
        scfg = ServeConfig(n_slots=4, n_pages=16, page_size=4, max_seq=24,
                           vocab=16, temperature=0.8, top_k=5, seed=7)
        reqs = [Request(rid=i, prompt=(1 + i, 2), max_new=4) for i in range(5)]
        rep1 = self.make(scfg=scfg).run(reqs)
        rep2 = self.make(scfg=scfg).run(reqs)
        assert rep1.outputs == rep2.outputs

    def test_request_validation(self):
        eng = self.make()
        with pytest.raises(ValueError):
            eng.submit(Request(rid=0, prompt=(), max_new=2))
        with pytest.raises(ValueError):
            eng.submit(Request(rid=0, prompt=(1,), max_new=0))
        with pytest.raises(ValueError):
            eng.submit(Request(rid=0, prompt=(99,), max_new=2))  # vocab
        with pytest.raises(ValueError):
            eng.submit(Request(rid=0, prompt=(1,) * 23, max_new=2))  # max_seq
        with pytest.raises(ValueError):
            eng.submit(Request(rid=-1, prompt=(1,), max_new=2))  # rid sign
        eng.submit(Request(rid=5, prompt=(1,), max_new=2))
        with pytest.raises(ValueError):
            # rids key PRNG streams and the outputs map: reuse is rejected
            eng.submit(Request(rid=5, prompt=(2,), max_new=2))


class TestSampling:
    def test_greedy_is_argmax(self):
        logits = jnp.asarray([[0.1, 3.0, -1.0], [2.0, 0.0, 1.0]])
        keys = jnp.stack([request_key(0, 0, 0), request_key(0, 1, 0)])
        toks = sample_batch(keys, logits, 0.0, 0)
        assert toks.tolist() == [1, 0]

    def test_fixed_keys_are_deterministic(self):
        logits = jnp.asarray(np.random.default_rng(0).standard_normal((4, 8)),
                             jnp.float32)
        keys = jnp.stack([request_key(3, i, 2) for i in range(4)])
        a = sample_batch(keys, logits, 0.9, 0)
        b = sample_batch(keys, logits, 0.9, 0)
        assert a.tolist() == b.tolist()

    def test_top_k_restricts_support(self):
        logits = jnp.asarray([5.0, 4.0, -10.0, -10.0, -10.0])
        draws = {
            int(sample_logits(request_key(0, 0, i), logits, 1.0, 2))
            for i in range(32)
        }
        assert draws <= {0, 1} and len(draws) == 2

    def test_negative_temperature_rejected(self):
        with pytest.raises(ValueError):
            sample_logits(request_key(0, 0, 0), jnp.zeros((4,)), -1.0)
