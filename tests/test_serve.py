"""tpuscratch.serve: paged KV cache, cached decode, continuous batching.

The correctness anchors:
- allocator invariants: unique in-range ids, all-or-nothing grants,
  double-free rejection, free list restored after drain, and a
  LIFO-reuse watermark law under interleaved request churn;
- decode-vs-full equivalence: prefill + cached single-token decode
  reproduce ``model_apply``'s output at EVERY position, on the 1x1 mesh
  and on a dp x sp mesh (pages sharded over dp, heads over sp), with
  ragged per-slot lengths exercising the true-length masking;
- engine: staggered arrival/completion with more requests than slots,
  free-page-watermark admission, no page leaks after drain, and ZERO
  decode recompiles after warmup (the CompileCounter hook);
- sampling determinism under fixed per-request keys;
- quantized KV pages (marker ``spec``): int8 decode within a STATED
  tolerance of fp32 decode at every position (``INT8_KV_DECODE_ATOL``),
  the exact-dequantization contract of ``decode_attention``'s scale
  path, and the static ≤ 0.55x cache-byte pin at the record-config-12
  geometry (the ZeRO grad-leg regression-guard pattern);
- speculative decoding (marker ``spec``): greedy speculative output
  BIT-IDENTICAL to non-speculative on the 1x1 and 2x2 meshes, the
  verify step's logit equivalence to step-by-step decode, proposer
  unit laws, accept/reject draw determinism across runs, and the
  token-accounting identity tokens == prefills + slot_steps + accepted.

Equivalence holds in the no-token-dropped MoE regime (capacity_factor
== n_experts, as in test_models), since capacity-bound routing is the
one component whose per-token output depends on batch composition.
"""

import dataclasses

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tpuscratch.comm import run_spmd
from tpuscratch.models.transformer import (
    TransformerConfig,
    init_params,
    model_apply,
    param_spec,
)
from tpuscratch.obs.ledger import kv_cache_bytes
from tpuscratch.ops.attention import decode_attention, verify_attention
from tpuscratch.runtime.mesh import make_mesh
from tpuscratch.serve import (
    CacheGeometry,
    PageAllocator,
    PrefixCache,
    Request,
    ServeConfig,
    ServeEngine,
    accept_speculative,
    dequantize_pages,
    init_kv_cache,
    propose_draft,
    quantize_pages,
    request_key,
    sample_batch,
    sample_logits,
    target_probs,
)
from tpuscratch.serve.decode import (
    CompileCounter,
    build_decode_step,
    build_prefill,
    build_verify_step,
)

D = 32


def cfg_for(**kw):
    # capacity_factor == n_experts: nothing dropped, so cached-decode
    # outputs are batch-composition-independent (same rule as test_models)
    kw.setdefault("capacity_factor", 4.0)
    return TransformerConfig(
        d_model=D, n_heads=4, n_experts=4, d_ff=48, n_layers=2, **kw
    )


class TestPageAllocator:
    def test_ids_unique_and_in_range(self):
        a = PageAllocator(6)
        got = a.alloc(6)
        assert sorted(got) == list(range(6))
        assert a.n_free == 0 and a.n_live == 6

    def test_all_or_nothing(self):
        a = PageAllocator(4)
        assert a.alloc(3) is not None
        assert a.alloc(2) is None          # only 1 free: grant nothing
        assert a.n_free == 1               # the failed request took nothing
        assert a.alloc(1) is not None

    def test_double_free_and_foreign_free_raise(self):
        a = PageAllocator(4)
        pages = a.alloc(2)
        a.free(pages)
        with pytest.raises(ValueError):
            a.free([pages[0]])             # double free
        b = a.alloc(1)
        with pytest.raises(ValueError):
            a.free([(b[0] + 1) % 4])       # not a live id

    def test_drain_restores_free_list(self):
        a = PageAllocator(8)
        held = [a.alloc(2) for _ in range(3)]
        a.free(held[1])
        held[1] = a.alloc(2)
        for h in held:
            a.free(h)
        assert a.n_free == 8 and a.n_live == 0

    def test_watermark_monotone_under_interleaved_churn(self):
        """Fragmentation/watermark law of the LIFO free list: running N
        requests through an interleaved admit/evict schedule, the
        free-page watermark (min free over the run) is monotone
        NON-INCREASING in the concurrent-request count and never worse
        than pool minus peak live footprint — i.e. interleaved
        evictions fragment nothing: freed pages stack and are reused
        before untouched ones, so the pool behaves like a depth gauge,
        which is exactly what the engine's admission watermark assumes.
        Also pins the LIFO reuse itself: the distinct ids touched by a
        churn equal its peak footprint, not its total traffic."""

        def churn(concurrent, n_requests, a):
            live = []
            watermark = a.n_free
            touched = set()
            for r in range(n_requests):
                need = 1 + r % 3
                if len(live) == concurrent:
                    # evict an INTERIOR request, not the newest: the
                    # interleaving that would fragment a non-LIFO list
                    a.free(live.pop(r % concurrent))
                got = a.alloc(need)
                assert got is not None
                touched.update(got)
                live.append(got)
                watermark = min(watermark, a.n_free)
            for h in live:
                a.free(h)
            return watermark, touched

        marks = []
        for concurrent in (1, 2, 4, 6):
            a = PageAllocator(32)
            w, touched = churn(concurrent, 24, a)
            assert a.n_free == 32 and a.n_live == 0   # drain restores
            # LIFO reuse: ids touched == what was ever simultaneously
            # live (3 pages/request max), NOT one id per grant
            assert len(touched) <= 3 * concurrent
            assert w >= 32 - 3 * concurrent
            marks.append(w)
        # more concurrency digs the watermark monotonically deeper
        assert all(m1 >= m2 for m1, m2 in zip(marks, marks[1:]))


class TestDecodeAttention:
    def test_matches_dense_reference_with_ragged_lengths(self):
        rng = np.random.default_rng(0)
        n_pages, page, H, Dh = 6, 4, 2, 8
        B, max_pages = 3, 4
        k_pages = rng.standard_normal((n_pages, page, H, Dh)).astype(np.float32)
        v_pages = rng.standard_normal((n_pages, page, H, Dh)).astype(np.float32)
        # scrambled page order per sequence; sentinel tail entries
        table = np.array([[2, 0, 5, n_pages],
                          [1, 4, n_pages, n_pages],
                          [3, n_pages, n_pages, n_pages]], np.int32)
        lens = np.array([9, 6, 2], np.int32)
        q = rng.standard_normal((B, H, Dh)).astype(np.float32)
        out = np.asarray(decode_attention(
            jnp.asarray(q), jnp.asarray(k_pages), jnp.asarray(v_pages),
            jnp.asarray(table), jnp.asarray(lens),
        ))
        for b in range(B):
            n_pg = -(-int(lens[b]) // page)
            ks = k_pages[table[b, :n_pg]].reshape(-1, H, Dh)[: lens[b]]
            vs = v_pages[table[b, :n_pg]].reshape(-1, H, Dh)[: lens[b]]
            s = np.einsum("hd,thd->ht", q[b], ks) / np.sqrt(Dh)
            p = np.exp(s - s.max(-1, keepdims=True))
            ref = np.einsum("ht,thd->hd", p / p.sum(-1, keepdims=True), vs)
            np.testing.assert_allclose(out[b], ref, atol=1e-5)

    def test_empty_slot_returns_zeros(self):
        z = decode_attention(
            jnp.ones((1, 2, 8)), jnp.ones((2, 4, 2, 8)), jnp.ones((2, 4, 2, 8)),
            jnp.full((1, 2), 2, jnp.int32), jnp.zeros((1,), jnp.int32),
        )
        assert float(jnp.abs(z).max()) == 0.0


class TestDecodeEquivalence:
    @pytest.mark.parametrize("dims", [(1, 1), (2, 2)])
    def test_prefill_and_decode_match_model_apply(self, dims):
        cfg = cfg_for()
        n = dims[0] * dims[1]
        mesh = make_mesh(dims, ("dp", "sp"), jax.devices()[:n])
        m1 = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        full = run_spmd(
            m1, lambda p, x: model_apply(p, x, cfg)[0],
            (param_spec(cfg), P("dp", "sp")), P("dp", "sp"),
        )
        params = init_params(1, cfg)
        geom = CacheGeometry(cfg.n_layers, n_pages=16, page_size=4,
                             n_heads=cfg.n_heads, d_head=cfg.d_head)
        dp_size = dims[0]
        kv = init_kv_cache(geom, dp_size)
        counter = CompileCounter()
        decode = build_decode_step(mesh, cfg, geom, counter=counter)
        prefill = build_prefill(mesh, cfg, geom)

        rng = np.random.default_rng(0)
        B, T = 2, 3
        lens = [3, 5]                     # ragged prompts
        max_pages = 4
        seq = rng.standard_normal((B, max(lens) + T, D)).astype(np.float32)
        pages = {0: [0, 1], 1: [0, 1] if dp_size == 2 else [2, 3]}
        slots_per_group = B // dp_size

        for b in range(B):
            s0 = lens[b]
            x = np.zeros((8, D), np.float32)
            x[:s0] = seq[b, :s0]
            rows = np.full((dp_size, max_pages), geom.n_pages, np.int32)
            rows[b // slots_per_group, : len(pages[b])] = pages[b]
            out, kv = prefill(params, kv, jnp.asarray(x), jnp.asarray(rows),
                              jnp.int32(s0))
            ref = np.asarray(full(params, jnp.asarray(seq[b:b + 1, :s0])))[0]
            # every prompt position, not just the last
            np.testing.assert_allclose(np.asarray(out)[:s0], ref, atol=2e-4)

        for t in range(T):
            positions = [lens[b] + t for b in range(B)]
            x = np.stack([seq[b, positions[b]] for b in range(B)])
            tables = np.full((B, max_pages), geom.n_pages, np.int32)
            wp = np.zeros((B,), np.int32)
            wo = np.zeros((B,), np.int32)
            sl = np.zeros((B,), np.int32)
            for b in range(B):
                tables[b, : len(pages[b])] = pages[b]
                wp[b] = pages[b][positions[b] // geom.page_size]
                wo[b] = positions[b] % geom.page_size
                sl[b] = positions[b] + 1
            out, kv = decode(params, kv, jnp.asarray(x), jnp.asarray(tables),
                             jnp.asarray(wp), jnp.asarray(wo), jnp.asarray(sl))
            out = np.asarray(out)
            for b in range(B):
                pos = positions[b]
                ref = np.asarray(
                    full(params, jnp.asarray(seq[b:b + 1, : pos + 1]))
                )[0, pos]
                np.testing.assert_allclose(out[b], ref, atol=2e-4)
        # one compiled decode program covered every step
        assert counter.count == 1


class TestIdleSlotIsolation:
    def test_idle_slots_never_perturb_real_tokens(self):
        # capacity_factor=2.0 < n_experts: MoE capacity BINDS.  Idle
        # slots' zero vectors must not consume expert capacity ahead of
        # real tokens — the same token in slot 0 (no idles ahead) and
        # slot 7 (seven idles ahead) must produce identical outputs.
        cfg = cfg_for(capacity_factor=2.0)
        mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        geom = CacheGeometry(cfg.n_layers, n_pages=8, page_size=4,
                             n_heads=cfg.n_heads, d_head=cfg.d_head)
        decode = build_decode_step(mesh, cfg, geom)
        params = init_params(0, cfg)
        vec = np.random.default_rng(1).standard_normal((D,)).astype(np.float32)
        B, MP = 8, 2

        def run(slot):
            kv = init_kv_cache(geom, 1)
            x = np.zeros((B, D), np.float32)
            x[slot] = vec
            tables = np.full((B, MP), geom.n_pages, np.int32)
            tables[slot, 0] = 0
            wp = np.full((B,), geom.n_pages, np.int32)
            wp[slot] = 0
            wo = np.zeros((B,), np.int32)
            lens = np.zeros((B,), np.int32)
            lens[slot] = 1
            out, _ = decode(params, kv, jnp.asarray(x), jnp.asarray(tables),
                            jnp.asarray(wp), jnp.asarray(wo),
                            jnp.asarray(lens))
            return np.asarray(out)[slot]

        np.testing.assert_allclose(run(0), run(7), atol=1e-6)


class TestEngine:
    def make(self, scfg=None, dims=(2, 2), **cfg_kw):
        cfg = cfg_for(**cfg_kw)
        n = dims[0] * dims[1]
        mesh = make_mesh(dims, ("dp", "sp"), jax.devices()[:n])
        scfg = scfg or ServeConfig(n_slots=4, n_pages=16, page_size=4,
                                   max_seq=24, vocab=16)
        return ServeEngine(mesh, cfg, scfg)

    def test_staggered_drain_no_leaks_no_recompiles(self):
        eng = self.make()
        free0 = eng.free_pages()
        reqs = [
            Request(rid=i, prompt=tuple(range(1, 2 + i % 5)),
                    max_new=1 + (i * 3) % 6)
            for i in range(7)          # > n_slots: queueing is exercised
        ]
        rep = eng.run(reqs)
        assert rep.completed == 7
        by_rid = dict(rep.outputs)
        for r in reqs:
            assert len(by_rid[r.rid]) == r.max_new
            assert all(0 <= t < 16 for t in by_rid[r.rid])
        assert eng.free_pages() == free0               # no page leaks
        assert rep.decode_compiles == 1                # zero steady-state recompiles
        assert rep.prefill_compiles <= 2               # one per shape bucket
        assert rep.tokens_generated == sum(r.max_new for r in reqs)

    def test_midstream_submission_backfills_slots(self):
        eng = self.make()
        eng.submit(Request(rid=0, prompt=(1, 2), max_new=8))
        eng.submit(Request(rid=1, prompt=(3,), max_new=2))
        for _ in range(3):
            eng.step()
        compiles_warm = eng.decode_compiles
        # rid=1 finished and its slot is free again; feed new work mid-run
        eng.submit(Request(rid=2, prompt=(4, 5, 6), max_new=3))
        rep = eng.run([])
        assert {rid for rid, _ in rep.outputs} >= {2}
        assert eng.n_active == 0 and eng.n_queued == 0
        assert eng.decode_compiles == compiles_warm    # warm == forever
        assert eng.free_pages() == [16, 16]

    def test_watermark_serializes_when_pool_is_tight(self):
        # one request's footprint == one group's WHOLE pool: each group
        # has 2 slots but pages for only 1 request, so admission must
        # hold half the slots idle (free slot, no pages) yet still drain
        scfg = ServeConfig(n_slots=4, n_pages=4, page_size=4, max_seq=16,
                           vocab=16)
        eng = self.make(scfg=scfg)
        reqs = [Request(rid=i, prompt=(1, 2, 3), max_new=13) for i in range(5)]
        for r in reqs:
            eng.submit(r)
        peak = 0
        outputs = {}
        for _ in range(200):
            if not (eng.n_queued or eng.n_active):
                break
            for rid, toks in eng.step():
                outputs[rid] = toks
            peak = max(peak, eng.n_active)
        assert sorted(outputs) == [0, 1, 2, 3, 4]
        assert peak == 2          # 1 per dp group, never the 4 slots
        assert eng.free_pages() == [4, 4]

    def test_failed_prefill_returns_pages_and_requeues(self):
        eng = self.make()
        free0 = eng.free_pages()

        class Boom(RuntimeError):
            pass

        def exploding_prefill(*a, **k):
            raise Boom("transient device error")

        # pre-seed the bucket cache so _admit uses the exploding program
        eng._prefills = {8: exploding_prefill}
        eng.submit(Request(rid=0, prompt=(1, 2), max_new=2))
        with pytest.raises(Boom):
            eng.step()
        assert eng.free_pages() == free0     # the grant came back
        assert eng.n_queued == 1             # the request is retryable
        assert eng.n_active == 0

    def test_failed_decode_recovers_and_replays_identically(self):
        # a raising compiled decode may have consumed the DONATED cache:
        # recovery must reset the pool, requeue in-flight requests, and
        # the replay must reproduce the uninterrupted run bit-for-bit
        scfg = ServeConfig(n_slots=4, n_pages=16, page_size=4, max_seq=24,
                           vocab=16)
        reqs = [Request(rid=i, prompt=(1 + i, 2), max_new=4)
                for i in range(3)]
        clean = self.make(scfg=scfg).run(reqs)

        eng = self.make(scfg=scfg)
        for r in reqs:
            eng.submit(r)
        eng.step()                           # slots active mid-stream

        class Boom(RuntimeError):
            pass

        real_decode = eng._decode

        def exploding_decode(*a, **k):
            raise Boom("mid-flight device error")

        eng._decode = exploding_decode
        with pytest.raises(Boom):
            eng.step()
        assert eng.n_active == 0 and eng.n_queued == 3
        assert eng.free_pages() == [16, 16]
        eng._decode = real_decode
        rep = eng.run([])
        assert rep.outputs == clean.outputs  # deterministic replay

    def test_deterministic_replay(self):
        scfg = ServeConfig(n_slots=4, n_pages=16, page_size=4, max_seq=24,
                           vocab=16, temperature=0.8, top_k=5, seed=7)
        reqs = [Request(rid=i, prompt=(1 + i, 2), max_new=4) for i in range(5)]
        rep1 = self.make(scfg=scfg).run(reqs)
        rep2 = self.make(scfg=scfg).run(reqs)
        assert rep1.outputs == rep2.outputs

    def test_request_validation(self):
        eng = self.make()
        with pytest.raises(ValueError):
            eng.submit(Request(rid=0, prompt=(), max_new=2))
        with pytest.raises(ValueError):
            eng.submit(Request(rid=0, prompt=(1,), max_new=0))
        with pytest.raises(ValueError):
            eng.submit(Request(rid=0, prompt=(99,), max_new=2))  # vocab
        with pytest.raises(ValueError):
            eng.submit(Request(rid=0, prompt=(1,) * 23, max_new=2))  # max_seq
        with pytest.raises(ValueError):
            eng.submit(Request(rid=-1, prompt=(1,), max_new=2))  # rid sign
        eng.submit(Request(rid=5, prompt=(1,), max_new=2))
        with pytest.raises(ValueError):
            # rids key PRNG streams and the outputs map: reuse is rejected
            eng.submit(Request(rid=5, prompt=(2,), max_new=2))


class TestSampling:
    def test_greedy_is_argmax(self):
        logits = jnp.asarray([[0.1, 3.0, -1.0], [2.0, 0.0, 1.0]])
        keys = jnp.stack([request_key(0, 0, 0), request_key(0, 1, 0)])
        toks = sample_batch(keys, logits, 0.0, 0)
        assert toks.tolist() == [1, 0]

    def test_fixed_keys_are_deterministic(self):
        logits = jnp.asarray(np.random.default_rng(0).standard_normal((4, 8)),
                             jnp.float32)
        keys = jnp.stack([request_key(3, i, 2) for i in range(4)])
        a = sample_batch(keys, logits, 0.9, 0)
        b = sample_batch(keys, logits, 0.9, 0)
        assert a.tolist() == b.tolist()

    def test_top_k_restricts_support(self):
        logits = jnp.asarray([5.0, 4.0, -10.0, -10.0, -10.0])
        draws = {
            int(sample_logits(request_key(0, 0, i), logits, 1.0, 2))
            for i in range(32)
        }
        assert draws <= {0, 1} and len(draws) == 2

    def test_negative_temperature_rejected(self):
        with pytest.raises(ValueError):
            sample_logits(request_key(0, 0, 0), jnp.zeros((4,)), -1.0)


# ---- quantized KV pages --------------------------------------------------

#: the STATED int8-KV decode tolerance: max |int8 - f32| over every
#: output element at every position of the layered decode gates below.
#: Per-element quantization error is <= scale/2 = absmax/254 per cache
#: entry; through attention (convex combination of V rows + score
#: perturbation) and the residual stream it lands ~1e-2 at these shapes
#: (measured 0.012-0.021 across seeds/meshes); 0.05 gives ~3x headroom.
#: MoE routing is EXCLUDED from this gate by construction (n_experts
#: chosen so the gate's argmax is stable): a knife-edge router can turn
#: an O(1e-2) perturbation into an O(1) output change, which is a
#: property of routing discontinuity, not of the cache — the engine-
#: level greedy test covers quantization under real MoE routing.
INT8_KV_DECODE_ATOL = 0.05


@pytest.mark.spec
class TestQuantizedKV:
    def test_quantize_roundtrip_error_bound(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(
            rng.standard_normal((5, 4, 3, 8)).astype(np.float32) * 3.0
        )
        q, s = quantize_pages(x)
        assert q.dtype == jnp.int8 and s.shape == (5, 3)
        err = np.abs(np.asarray(dequantize_pages(q, s)) - np.asarray(x))
        # symmetric absmax: error <= scale/2 everywhere, exact at amax
        bound = np.asarray(s)[:, None, :, None] / 2 + 1e-7
        assert (err <= bound).all()
        amax = np.abs(np.asarray(x)).max(axis=(1, 3))
        np.testing.assert_allclose(np.asarray(s) * 127.0, amax, rtol=1e-6)

    def test_zero_page_quantizes_to_zero(self):
        q, s = quantize_pages(jnp.zeros((2, 4, 2, 8)))
        assert float(jnp.abs(dequantize_pages(q, s)).max()) == 0.0

    def test_decode_attention_scale_path_is_dequantization_to_ulp(self):
        """int8 pools + scales through decode_attention == fp32 pools
        holding the dequantized values, to reassociation ulp: the scale
        now FOLDS into the score/output contractions (the per-page
        per-head scale is constant across d_head, so
        ``q . (k * s) == (q . k) * s`` exactly in algebra and to one
        fp rounding per product in float) — the dense oracle stops
        materializing a fp32 (B, T, H, D) expansion of the pool it
        reads, at the cost of the bit-exactness the pre-fold
        formulation had.  The quantization ERROR itself is ~1e-2
        (INT8_KV_DECODE_ATOL), four orders above this bound, so the
        fold is free at the contract level."""
        rng = np.random.default_rng(1)
        n_pages, page, H, Dh = 6, 4, 2, 8
        kf = rng.standard_normal((n_pages, page, H, Dh)).astype(np.float32)
        vf = rng.standard_normal((n_pages, page, H, Dh)).astype(np.float32)
        qk, sk = quantize_pages(jnp.asarray(kf))
        qv, sv = quantize_pages(jnp.asarray(vf))
        table = np.array([[2, 0, 5, n_pages], [1, 4, n_pages, n_pages]],
                         np.int32)
        lens = np.array([9, 6], np.int32)
        q = jnp.asarray(rng.standard_normal((2, H, Dh)).astype(np.float32))
        out_q = decode_attention(q, qk, qv, jnp.asarray(table),
                                 jnp.asarray(lens), sk, sv)
        out_f = decode_attention(
            q, dequantize_pages(qk, sk), dequantize_pages(qv, sv),
            jnp.asarray(table), jnp.asarray(lens),
        )
        np.testing.assert_allclose(np.asarray(out_q), np.asarray(out_f),
                                   atol=1e-6)

    @pytest.mark.parametrize("dims,n_experts", [((1, 1), 1), ((2, 2), 2)])
    def test_int8_decode_within_tolerance_at_every_position(
        self, dims, n_experts
    ):
        """The logit-equivalence gate for quantization: the SAME prompt
        + decode trajectory through fp32 and int8 caches stays within
        ``INT8_KV_DECODE_ATOL`` at every position, on the 1x1 and 2x2
        meshes (prefill positions are exactly equal — prompt compute is
        fp32 either way — so this really gates the decode reads)."""
        cfg = TransformerConfig(
            d_model=D, n_heads=4, n_experts=n_experts, d_ff=48,
            n_layers=2, capacity_factor=float(n_experts),
        )
        n = dims[0] * dims[1]
        mesh = make_mesh(dims, ("dp", "sp"), jax.devices()[:n])
        geom = CacheGeometry(cfg.n_layers, n_pages=16, page_size=4,
                             n_heads=cfg.n_heads, d_head=cfg.d_head)
        params = init_params(1, cfg)
        rng = np.random.default_rng(0)
        S0, T = 5, 12
        seq = rng.standard_normal((S0 + T, D)).astype(np.float32)
        dp_size = dims[0]
        pages = [0, 1, 2, 3, 4]
        outs = {}
        for dtype in (jnp.float32, jnp.int8):
            quant = dtype == jnp.int8
            kv = init_kv_cache(geom, dp_size, dtype)
            prefill = build_prefill(mesh, cfg, geom, quantized=quant)
            decode = build_decode_step(mesh, cfg, geom, quantized=quant)
            x = np.zeros((8, D), np.float32)
            x[:S0] = seq[:S0]
            rows = np.full((dp_size, 6), geom.n_pages, np.int32)
            rows[0, : len(pages)] = pages
            out, kv = prefill(params, kv, jnp.asarray(x),
                              jnp.asarray(rows), jnp.int32(S0))
            res = [np.asarray(out)[:S0]]
            for t in range(T):
                pos = S0 + t
                xb = np.zeros((dp_size, D), np.float32)
                xb[0] = seq[pos]
                tables = np.full((dp_size, 6), geom.n_pages, np.int32)
                tables[0, : len(pages)] = pages
                wp = np.full((dp_size,), geom.n_pages, np.int32)
                wp[0] = pages[pos // geom.page_size]
                wo = np.zeros((dp_size,), np.int32)
                wo[0] = pos % geom.page_size
                sl = np.zeros((dp_size,), np.int32)
                sl[0] = pos + 1
                o, kv = decode(params, kv, jnp.asarray(xb),
                               jnp.asarray(tables), jnp.asarray(wp),
                               jnp.asarray(wo), jnp.asarray(sl))
                res.append(np.asarray(o)[:1])
            outs[quant] = np.concatenate(res)
        err = np.abs(outs[False] - outs[True])
        # prefill positions: fp32 compute both ways, exactly equal
        np.testing.assert_array_equal(err[:S0], 0.0)
        assert err.max() <= INT8_KV_DECODE_ATOL, (
            f"int8 decode drifted {err.max():.4f} > {INT8_KV_DECODE_ATOL}"
        )

    def test_engine_int8_drains_cleanly(self):
        cfg = cfg_for()
        mesh = make_mesh((2, 2), ("dp", "sp"), jax.devices()[:4])
        scfg = ServeConfig(n_slots=4, n_pages=16, page_size=4, max_seq=24,
                           vocab=16, kv_dtype="int8")
        eng = ServeEngine(mesh, cfg, scfg)
        free0 = eng.free_pages()
        reqs = [Request(rid=i, prompt=(1 + i, 2, 1 + i, 2), max_new=6)
                for i in range(6)]
        rep = eng.run(reqs)
        assert rep.completed == 6
        assert eng.free_pages() == free0
        assert rep.decode_compiles == 1
        assert all(0 <= t < 16 for _, toks in rep.outputs for t in toks)

    def test_kv_cache_bytes_pinned_below_055x(self):
        """Regression guard (the ZeRO 0.5x grad-leg pattern): at the
        record-config-12 CPU geometry AND the TPU geometry, int8 pages
        + scales must stay ≤ 0.55x the fp32 cache bytes.  The ratio is
        analytic — 1/4 + 1/(page_size * d_head) — so a change that
        silently fattens the quantized cache (scales per token, a
        wider scale dtype) fails this regardless of timing noise."""
        from tpuscratch.bench.decode_bench import default_decode_setup

        for on_tpu in (False, True):
            cfg, scfg, _, _ = default_decode_setup(on_tpu)
            geom = CacheGeometry(cfg.n_layers, scfg.n_pages,
                                 scfg.page_size, cfg.n_heads, cfg.d_head)
            b_f32 = kv_cache_bytes(init_kv_cache(geom))
            b_int8 = kv_cache_bytes(init_kv_cache(geom, dtype=jnp.int8))
            ratio = b_int8 / b_f32
            analytic = 0.25 + 1.0 / (geom.page_size * geom.d_head)
            assert abs(ratio - analytic) < 1e-9
            assert ratio <= 0.55, f"int8 cache ratio {ratio:.3f} > 0.55"

    def test_invalid_kv_dtype_rejected(self):
        cfg = cfg_for()
        mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        with pytest.raises(ValueError):
            ServeEngine(mesh, cfg, ServeConfig(kv_dtype="int4"))


#: fp8-e4m3 per-position decode bound, STATED like INT8_KV_DECODE_ATOL
#: (measured 0.089 at this geometry/seed).  Looser than int8's: e4m3's
#: floating grid carries 3 mantissa bits (~2^-4 relative) at EVERY
#: magnitude, while int8's uniform grid resolves outlier-free pages at
#: ~7 effective bits — on Gaussian test data (no outliers) int8 wins.
#: fp8's value is the opposite regime: a page with one large outlier
#: costs int8 its whole-page resolution (scale/2 everywhere) but costs
#: fp8 nothing — same bytes, complementary error profile, which is why
#: it is a ladder RUNG and not a replacement.
FP8_KV_DECODE_ATOL = 0.15


@pytest.mark.spec
class TestFp8KV:
    """The fp8 (e4m3) rung of the KV dtype ladder — PR-6's int8
    plumbing (scale planes, ``_quant_write``, whole-page prefill
    quantization, the ledger byte proof) exercised at the new dtype;
    engine-level coverage mirrors TestQuantizedKV's."""

    def test_engine_fp8_drains_cleanly(self):
        # one layer (vs the int8 twin's two): the sharded fp8 write/
        # read path is layer-count-independent and tier-1 has a wall
        # budget — depth coverage lives in the int8 twin above
        cfg = TransformerConfig(d_model=D, n_heads=4, n_experts=4,
                                d_ff=48, n_layers=1, capacity_factor=4.0)
        mesh = make_mesh((2, 2), ("dp", "sp"), jax.devices()[:4])
        scfg = ServeConfig(n_slots=4, n_pages=16, page_size=4, max_seq=24,
                           vocab=16, kv_dtype="fp8")
        eng = ServeEngine(mesh, cfg, scfg)
        free0 = eng.free_pages()
        reqs = [Request(rid=i, prompt=(1 + i, 2, 1 + i, 2), max_new=6)
                for i in range(6)]
        rep = eng.run(reqs)
        assert rep.completed == 6
        assert eng.free_pages() == free0
        assert rep.decode_compiles == 1
        assert all(0 <= t < 16 for _, toks in rep.outputs for t in toks)

    def test_fp8_decode_within_tolerance(self):
        """fp32 vs fp8 cache through the same prefill + decode
        trajectory: within the stated per-position bound (the int8
        gate's shape at the new rung)."""
        cfg = TransformerConfig(d_model=D, n_heads=4, n_experts=1,
                                d_ff=48, n_layers=2, capacity_factor=1.0)
        mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        geom = CacheGeometry(cfg.n_layers, n_pages=16, page_size=4,
                             n_heads=cfg.n_heads, d_head=cfg.d_head)
        params = init_params(1, cfg)
        rng = np.random.default_rng(0)
        S0, T = 5, 6
        seq = rng.standard_normal((S0 + T, D)).astype(np.float32)
        pages = [0, 1, 2]
        outs = {}
        for dtype in (jnp.float32, jnp.float8_e4m3fn):
            quant = dtype != jnp.float32
            kv = init_kv_cache(geom, 1, dtype)
            prefill = build_prefill(mesh, cfg, geom, quantized=quant)
            decode = build_decode_step(mesh, cfg, geom, quantized=quant)
            x = np.zeros((8, D), np.float32)
            x[:S0] = seq[:S0]
            rows = np.full((1, 6), geom.n_pages, np.int32)
            rows[0, : len(pages)] = pages
            out, kv = prefill(params, kv, jnp.asarray(x),
                              jnp.asarray(rows), jnp.int32(S0))
            res = [np.asarray(out)[:S0]]
            for t in range(T):
                pos = S0 + t
                xb = seq[pos:pos + 1]
                tables = np.full((1, 6), geom.n_pages, np.int32)
                tables[0, : len(pages)] = pages
                wp = np.asarray([pages[pos // geom.page_size]], np.int32)
                wo = np.asarray([pos % geom.page_size], np.int32)
                sl = np.asarray([pos + 1], np.int32)
                o, kv = decode(params, kv, jnp.asarray(xb),
                               jnp.asarray(tables), jnp.asarray(wp),
                               jnp.asarray(wo), jnp.asarray(sl))
                res.append(np.asarray(o))
            outs[quant] = np.concatenate(res)
        err = np.abs(outs[False] - outs[True])
        np.testing.assert_array_equal(err[:S0], 0.0)  # prefill fp32 both
        assert err.max() <= FP8_KV_DECODE_ATOL, (
            f"fp8 decode drifted {err.max():.4f} > {FP8_KV_DECODE_ATOL}"
        )


@pytest.mark.spec
class TestFusedEngine:
    """The fused Pallas paged-attention kernel behind the engine
    (interpret mode on CPU): greedy output must be BIT-identical to the
    dense-oracle engine — token ids are argmax decisions, robust to the
    kernel's reassociation ulp, so any mismatch is a real kernel bug,
    not numerics."""

    def _drain(self, scfg_kw, spec_k=0):
        # one layer, two heads: the smallest engine that still runs
        # every serve path — these tests compile interpret-mode Pallas
        # programs, and tier-1 has a wall budget to respect
        cfg = TransformerConfig(d_model=16, n_heads=2, n_experts=2,
                                d_ff=32, n_layers=1, capacity_factor=2.0)
        mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        scfg = ServeConfig(n_slots=2, n_pages=16, page_size=4, max_seq=20,
                           vocab=16, spec_k=spec_k, **scfg_kw)
        eng = ServeEngine(mesh, cfg, scfg)
        reqs = [Request(rid=i, prompt=(1 + i, 2, 3, 2, 3), max_new=5)
                for i in range(3)]
        return eng.run(reqs).outputs

    def test_fused_decode_engine_greedy_bit_identical(self):
        """Plain fp32 decode (K=1) through the fused kernel."""
        dense = self._drain({"fused_attention": "off"})
        fused = self._drain({"fused_attention": "on"})
        assert fused == dense

    def test_fused_verify_chunk_quantized_engine_bit_identical(self):
        """The other two entry points AND the quantized read path in
        ONE engine: spec_k > 0 routes decode through the verify sweep,
        chunk_prefill routes admission through the context-prefill
        program, and int8 pages exercise the kernel's in-VMEM
        dequantization — all three composed, fused vs dense, greedy
        bit-identity.  (Per-dtype fused READ equivalence incl. fp8 is
        gated at the ops layer in tests/test_attention.py — this is
        the engine-composition gate, kept to two engine builds for the
        tier-1 wall budget.)"""
        kw = {"kv_dtype": "int8", "chunk_prefill": 2}
        dense = self._drain(dict(kw, fused_attention="off"), spec_k=2)
        fused = self._drain(dict(kw, fused_attention="on"), spec_k=2)
        assert fused == dense

    def test_invalid_fused_mode_rejected(self):
        cfg = cfg_for()
        mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        with pytest.raises(ValueError):
            ServeEngine(mesh, cfg, ServeConfig(fused_attention="maybe"))


# ---- speculative decoding ------------------------------------------------


@pytest.mark.spec
class TestDraftProposer:
    def test_full_continuation_preferred(self):
        # period-3 context: nearest match truncates, earlier match
        # yields the full k — the full one must win
        assert propose_draft((1, 2, 3, 1, 2, 3, 1, 2), 3) == (3, 1, 2)

    def test_partial_fallback(self):
        assert propose_draft((7, 7, 7), 4) == (7,)

    def test_no_match_is_empty(self):
        assert propose_draft((1, 2, 3, 4, 5), 3) == ()
        assert propose_draft((1, 2), 3) == ()          # too short
        assert propose_draft((1, 2, 3), 0) == ()       # k=0

    def test_most_recent_full_match_wins(self):
        # (9, 1) occurs twice with full continuations: 9,1,[5..] early,
        # 9,1,[8..] late — the late one predicts the suffix
        ctx = (9, 1, 5, 5, 5, 9, 1, 8, 8, 8, 9, 1)
        assert propose_draft(ctx, 2) == (8, 8)

    def test_ngram_length_respected(self):
        ctx = (4, 1, 2, 9, 1, 2)
        assert propose_draft(ctx, 1, ngram=2) == (9,)
        assert propose_draft(ctx, 1, ngram=3) == ()


@pytest.mark.spec
class TestAcceptSpeculative:
    def test_greedy_accepts_matching_prefix(self):
        logits = np.full((4, 8), -1.0, np.float32)
        logits[0, 3] = 1.0   # predicts 3
        logits[1, 5] = 1.0   # predicts 5
        logits[2, 2] = 1.0   # predicts 2 but draft says 6: reject here
        a, toks = accept_speculative(0, 0, 0, logits, (3, 5, 6))
        assert (a, toks) == (2, (3, 5, 2))

    def test_greedy_full_accept_emits_bonus(self):
        logits = np.full((3, 8), -1.0, np.float32)
        logits[0, 3] = 1.0
        logits[1, 5] = 1.0
        logits[2, 7] = 1.0   # the bonus token after a fully-held draft
        a, toks = accept_speculative(0, 0, 0, logits, (3, 5))
        assert (a, toks) == (2, (3, 5, 7))

    def test_greedy_empty_draft_is_plain_argmax(self):
        logits = np.full((1, 8), -1.0, np.float32)
        logits[0, 4] = 1.0
        assert accept_speculative(0, 0, 0, logits, ()) == (0, (4,))

    def test_draws_identical_across_runs(self):
        """The accept/reject path consumes seeded draws only: the same
        (seed, rid, position, logits, draft) produces the same accepted
        length and tokens on every run."""
        rng = np.random.default_rng(3)
        logits = rng.standard_normal((5, 16)).astype(np.float32)
        draft = (3, 9, 1, 12)
        runs = {
            accept_speculative(7, 11, 4, logits, draft,
                               temperature=0.9, top_k=6)
            for _ in range(3)
        }
        assert len(runs) == 1
        a, toks = runs.pop()
        assert len(toks) == a + 1

    def test_empty_draft_matches_base_sampler_at_temperature(self):
        """A slot with no draft must consume exactly the non-speculative
        draw: accept_speculative's terminal token == sample_logits under
        the plain request_key for that position."""
        rng = np.random.default_rng(5)
        logits = rng.standard_normal((1, 32)).astype(np.float32)
        for position in (0, 3, 17):
            a, toks = accept_speculative(2, 9, position, logits, (),
                                         temperature=0.7, top_k=4)
            ref = int(sample_logits(request_key(2, 9, position),
                                    jnp.asarray(logits[0]), 0.7, 4))
            assert (a, toks) == (0, (ref,))

    def test_rejection_never_resamples_the_rejected_token(self):
        # target puts tiny mass on the draft token: rejection is near
        # certain, and the residual draw must never return it
        logits = np.zeros((2, 6), np.float32)
        logits[0, 2] = -20.0
        for trial in range(20):
            a, toks = accept_speculative(trial, 0, 0, logits, (2,),
                                         temperature=1.0)
            if a == 0:
                assert toks[0] != 2
        # and the acceptance probability is honest: near-zero mass ->
        # essentially always rejected
        rejected = sum(
            accept_speculative(t, 0, 0, logits, (2,), temperature=1.0)[0]
            == 0
            for t in range(20)
        )
        assert rejected == 20

    def test_target_probs_matches_sampler_support(self):
        logits = np.asarray([5.0, 4.0, -10.0, -10.0, -10.0], np.float32)
        p = target_probs(logits, 1.0, top_k=2)
        assert p[2:].sum() == 0.0 and abs(p.sum() - 1.0) < 1e-6
        draws = {
            int(sample_logits(request_key(0, 0, i), jnp.asarray(logits),
                              1.0, 2))
            for i in range(32)
        }
        assert draws <= {i for i in range(5) if p[i] > 0}

    def test_too_few_logit_rows_rejected(self):
        with pytest.raises(ValueError):
            accept_speculative(0, 0, 0, np.zeros((2, 8), np.float32),
                               (1, 2, 3))


@pytest.mark.spec
class TestSpeculativeEngine:
    def _reqs(self, n=6):
        # mixed: periodic prompts (draftable) and arbitrary ones
        return [
            Request(
                rid=i,
                prompt=(1 + i % 3, 2, 1 + i % 3, 2) if i % 2 == 0
                else (5 + i % 4, 3, 7),
                max_new=4 + (i * 3) % 5,
            )
            for i in range(n)
        ]

    @pytest.mark.parametrize("dims", [(1, 1), (2, 2)])
    def test_greedy_spec_bit_identical_to_plain(self, dims):
        """THE speculative logit-equivalence gate: same seed, same
        requests, greedy — speculation on vs off produce identical
        outputs on the 1x1 and 2x2 meshes.  Draft acceptance under
        greedy is ``argmax == draft``, so any drift in the verify
        forward (masking, write placement, MoE token ordering) breaks
        this immediately."""
        cfg = cfg_for()
        n = dims[0] * dims[1]
        mesh = make_mesh(dims, ("dp", "sp"), jax.devices()[:n])
        scfg = ServeConfig(n_slots=4, n_pages=16, page_size=4, max_seq=32,
                           vocab=16)
        reqs = self._reqs()
        plain = ServeEngine(mesh, cfg, scfg).run(reqs)
        spec = ServeEngine(
            mesh, cfg, dataclasses.replace(scfg, spec_k=3)
        ).run(reqs)
        assert spec.outputs == plain.outputs
        assert spec.decode_compiles == 1       # ONE verify program
        assert spec.tokens_generated == plain.tokens_generated
        # speculation actually engaged on the periodic prompts
        assert spec.drafted > 0 and spec.accepted > 0
        # and saved sweeps: fewer decode ticks than tokens decoded
        assert spec.decode_steps < plain.decode_steps

    def test_accounting_identity_and_histogram(self):
        cfg = cfg_for()
        mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        scfg = ServeConfig(n_slots=4, n_pages=16, page_size=4, max_seq=32,
                           vocab=16, spec_k=3)
        eng = ServeEngine(mesh, cfg, scfg)
        rep = eng.run(self._reqs())
        assert rep.tokens_generated == (
            rep.prefills + rep.slot_steps + rep.accepted
        )
        assert rep.accepted <= rep.drafted
        assert rep.accept_len_mean == rep.accepted / rep.slot_steps
        # every request's output length is exactly its budget
        for r in self._reqs():
            assert len(dict(rep.outputs)[r.rid]) == r.max_new
        assert eng.free_pages() == [16]        # no leaks through spec
        h = eng.metrics.histogram("serve/accept_len")
        assert h.count == rep.slot_steps

    def test_spec_with_temperature_is_deterministic(self):
        cfg = cfg_for()
        mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        scfg = ServeConfig(n_slots=4, n_pages=16, page_size=4, max_seq=32,
                           vocab=16, spec_k=3, temperature=0.8, top_k=5,
                           seed=7)
        reqs = self._reqs()
        rep1 = ServeEngine(mesh, cfg, scfg).run(reqs)
        rep2 = ServeEngine(mesh, cfg, scfg).run(reqs)
        assert rep1.outputs == rep2.outputs

    def test_spec_composes_with_int8(self):
        cfg = cfg_for()
        mesh = make_mesh((2, 2), ("dp", "sp"), jax.devices()[:4])
        scfg = ServeConfig(n_slots=4, n_pages=16, page_size=4, max_seq=32,
                           vocab=16, spec_k=3, kv_dtype="int8")
        eng = ServeEngine(mesh, cfg, scfg)
        rep = eng.run(self._reqs())
        assert rep.completed == 6
        assert rep.tokens_generated == (
            rep.prefills + rep.slot_steps + rep.accepted
        )
        assert eng.free_pages() == [16, 16]
        assert rep.decode_compiles == 1

    def test_verify_step_logits_match_stepwise_decode(self):
        """The verify forward's per-position outputs equal running the
        plain decode step token by token — the compiled-program-level
        equivalence behind the engine-level greedy gate."""
        cfg = cfg_for()
        mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        geom = CacheGeometry(cfg.n_layers, n_pages=16, page_size=4,
                             n_heads=cfg.n_heads, d_head=cfg.d_head)
        params = init_params(1, cfg)
        rng = np.random.default_rng(0)
        n_ctx, k = 6, 3
        seq = rng.standard_normal((n_ctx + k + 1, D)).astype(np.float32)
        pages = [0, 1, 2]

        def prefill_ctx(kv):
            prefill = build_prefill(mesh, cfg, geom)
            x = np.zeros((8, D), np.float32)
            x[:n_ctx] = seq[:n_ctx]
            rows = np.full((1, 3), geom.n_pages, np.int32)
            rows[0] = pages
            _, kv = prefill(params, kv, jnp.asarray(x), jnp.asarray(rows),
                            jnp.int32(n_ctx))
            return kv

        # stepwise: decode positions n_ctx .. n_ctx+k one at a time
        kv = prefill_ctx(init_kv_cache(geom, 1))
        decode = build_decode_step(mesh, cfg, geom)
        stepwise = []
        for j in range(k + 1):
            pos = n_ctx + j
            tables = np.full((1, 3), geom.n_pages, np.int32)
            tables[0] = pages
            o, kv = decode(
                params, kv, jnp.asarray(seq[pos][None]),
                jnp.asarray(tables),
                jnp.asarray([pages[pos // geom.page_size]], np.int32),
                jnp.asarray([pos % geom.page_size], np.int32),
                jnp.asarray([pos + 1], np.int32),
            )
            stepwise.append(np.asarray(o)[0])

        # one verify sweep over the same k+1 tokens
        kv = prefill_ctx(init_kv_cache(geom, 1))
        verify = build_verify_step(mesh, cfg, geom, k)
        x = seq[n_ctx: n_ctx + k + 1][None]             # (1, k+1, D)
        tables = np.full((1, 3), geom.n_pages, np.int32)
        tables[0] = pages
        wp = np.asarray(
            [[pages[(n_ctx + j) // geom.page_size] for j in range(k + 1)]],
            np.int32,
        )
        wo = np.asarray(
            [[(n_ctx + j) % geom.page_size for j in range(k + 1)]], np.int32
        )
        out, _ = verify(params, kv, jnp.asarray(x), jnp.asarray(tables),
                        jnp.asarray(wp), jnp.asarray(wo),
                        jnp.asarray([n_ctx + 1], np.int32))
        np.testing.assert_allclose(
            np.asarray(out)[0], np.stack(stepwise), atol=1e-5
        )

    def test_verify_attention_masks_idle_and_ragged(self):
        rng = np.random.default_rng(0)
        n_pages, page, H, Dh, K = 4, 4, 2, 8, 3
        kp = rng.standard_normal((n_pages, page, H, Dh)).astype(np.float32)
        vp = rng.standard_normal((n_pages, page, H, Dh)).astype(np.float32)
        q = rng.standard_normal((2, K, H, Dh)).astype(np.float32)
        table = np.array([[0, 1], [2, 3]], np.int32)
        lens = np.array([3, 0], np.int32)      # slot 1 idle
        out = np.asarray(verify_attention(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(table), jnp.asarray(lens),
        ))
        assert np.abs(out[1]).max() == 0.0     # idle slot: zeros at all K
        # position j of slot 0 == decode_attention with length 3 + j
        for j in range(K):
            ref = np.asarray(decode_attention(
                jnp.asarray(q[0, j][None]), jnp.asarray(kp),
                jnp.asarray(vp), jnp.asarray(table[:1]),
                jnp.asarray([3 + j], np.int32),
            ))[0]
            np.testing.assert_allclose(out[0, j], ref, atol=1e-6)

    def test_invalid_spec_config_rejected(self):
        cfg = cfg_for()
        mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        with pytest.raises(ValueError):
            ServeEngine(mesh, cfg, ServeConfig(spec_k=-1))
        with pytest.raises(ValueError):
            ServeEngine(mesh, cfg, ServeConfig(spec_ngram=0))
        with pytest.raises(ValueError):
            build_verify_step(mesh, cfg, CacheGeometry(
                cfg.n_layers, 8, 4, cfg.n_heads, cfg.d_head), 0)


# ---- refcounted prefix caching + chunked prefill (ISSUE 8) ---------------


class TestPageRefcounts:
    def test_share_adds_holders_free_releases_at_zero(self):
        a = PageAllocator(8)
        p = a.alloc(3)
        a.share(p[:2])                      # p0, p1 now held twice
        assert a.refcount(p[0]) == 2 and a.refcount(p[2]) == 1
        assert a.n_free == 5                # sharing consumes no capacity
        assert a.n_live == 3                # unique live pages
        rel = a.free(p)                     # drops ONE holder each
        assert rel == [p[2]]                # only the unshared page died
        assert a.n_free == 6
        rel = a.free(p[:2])
        assert sorted(rel) == sorted(p[:2])
        assert a.n_free == 8 and a.n_live == 0

    def test_share_of_freed_page_raises(self):
        a = PageAllocator(4)
        p = a.alloc(1)
        a.free(p)
        with pytest.raises(ValueError):
            a.share(p)

    def test_overfree_of_shared_page_raises(self):
        a = PageAllocator(4)
        p = a.alloc(1)
        a.share(p)
        a.free(p)
        a.free(p)                           # second holder
        with pytest.raises(ValueError):
            a.free(p)                       # third free: page is dead

    def test_watermark_counts_unique_pages_not_holders(self):
        # the refcount-aware admission law: k requests sharing one page
        # draw the pool down by ONE page, not k
        a = PageAllocator(4)
        p = a.alloc(1)
        for _ in range(5):
            a.share(p)
        assert a.n_free == 3 and a.n_live == 1


class TestPrefixCache:
    def test_match_walks_full_page_blocks(self):
        t = PrefixCache(4)
        t.insert((1, 2, 3, 4, 5, 6, 7, 8, 9), [10, 11])
        assert t.match((1, 2, 3, 4, 5, 6, 7, 8)) == [10, 11]
        assert t.match((1, 2, 3, 4, 9, 9, 9, 9)) == [10]   # diverged block
        assert t.match((1, 2, 3)) == []                    # sub-page: no match
        assert t.match((2, 2, 3, 4)) == []

    def test_oldest_copy_wins_and_alternates_survive_drop(self):
        t = PrefixCache(2)
        t.insert((1, 2), [5])
        t.insert((1, 2), [7])           # duplicate prompt, other copy
        assert t.match((1, 2)) == [5]   # oldest live copy
        t.drop([5])                     # its owner died...
        assert t.match((1, 2)) == [7]   # ...the alternate takes over
        t.drop([7])
        assert t.match((1, 2)) == []

    def test_drop_and_clear(self):
        t = PrefixCache(2)
        t.insert((1, 2, 3, 4), [5, 6])
        t.drop([5])
        assert t.match((1, 2)) == [] and t.match((1, 2, 3, 4)) == []
        t.insert((1, 2), [8])
        t.clear()
        assert t.n_blocks == 0

    def test_chain_extension_across_owners(self):
        # B matches A's first block and registers its own continuation:
        # a later C matches the COMBINED chain
        t = PrefixCache(2)
        t.insert((1, 2), [3])
        t.insert((1, 2, 5, 6), [3, 9])
        assert t.match((1, 2, 5, 6)) == [3, 9]


@pytest.mark.disagg
class TestPrefixShareEngine:
    def scfg(self, **kw):
        kw.setdefault("n_slots", 4)
        kw.setdefault("n_pages", 16)
        kw.setdefault("page_size", 4)
        kw.setdefault("max_seq", 32)
        kw.setdefault("vocab", 16)
        return ServeConfig(**kw)

    def engines(self, dims, **kw):
        cfg = cfg_for()
        n = dims[0] * dims[1]
        mesh = make_mesh(dims, ("dp", "sp"), jax.devices()[:n])
        return (
            ServeEngine(mesh, cfg, self.scfg(**kw)),
            ServeEngine(mesh, cfg, self.scfg(prefix_share=True, **kw)),
        )

    @pytest.mark.parametrize("dims", [(1, 1), (2, 2)])
    def test_greedy_bit_identical_and_flops_drop(self, dims):
        # common 2-page system prefix + private tails: shared admissions
        # must emit the SAME tokens while prefilling fewer prompt tokens
        # and writing fewer fresh KV bytes
        reqs = [
            Request(rid=i, prompt=(1, 2, 3, 4, 5, 6, 7, 8, 9 + i % 4),
                    max_new=3 + i % 3)
            for i in range(6)
        ]
        mono, shared = self.engines(dims)
        rep_m = mono.run(reqs)
        rep_s = shared.run(reqs)
        assert rep_s.outputs == rep_m.outputs
        assert rep_s.prefill_tokens < rep_m.prefill_tokens
        assert rep_s.shared_tokens > 0
        assert rep_s.fresh_kv_bytes < rep_m.fresh_kv_bytes
        # conservation: every admitted prompt token is prefilled XOR shared
        assert (rep_s.prefill_tokens + rep_s.shared_tokens
                == sum(len(r.prompt) for r in reqs))
        # refcount-aware free: drain returns every page exactly once
        assert shared.free_pages() == mono.free_pages()
        assert all(t.n_blocks == 0 for t in shared._tries)

    def test_cow_on_fully_shared_aligned_prompt(self):
        # identical page-aligned prompts: the whole prompt matches, the
        # last-position re-score write hits a shared page, and the
        # engine must copy-on-write it instead of corrupting the
        # original holder's view (outputs prove both streams intact)
        reqs = [Request(rid=i, prompt=(1, 2, 3, 4, 5, 6, 7, 8),
                        max_new=4) for i in range(4)]
        mono, shared = self.engines((1, 1))
        rep_m = mono.run(reqs)
        rep_s = shared.run(reqs)
        assert rep_s.outputs == rep_m.outputs
        assert rep_s.cow_pages > 0
        # the re-score is ONE token; everything else of later prompts
        # is served shared
        assert rep_s.prefill_tokens < rep_m.prefill_tokens
        assert shared.free_pages() == mono.free_pages()

    def test_watermark_admission_is_refcount_aware(self):
        # pool sized so two full footprints DON'T fit, but a shared
        # admission (which allocates only its tail + budget) does: the
        # watermark gate must admit the second request concurrently
        cfg = cfg_for()
        mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        # footprint: prompt 8 (2 pages) + max_new 4 (1 page) = 3 pages;
        # pool of 5 fits one request fully, and a second ONLY when its
        # 2 prompt pages are shared (needs 1 more: 3 + 1 <= 5... the
        # shared admission allocates 1 page vs 3)
        scfg = ServeConfig(n_slots=2, n_pages=5, page_size=4, max_seq=16,
                           vocab=16, prefix_share=True)
        eng = ServeEngine(mesh, cfg, scfg)
        reqs = [Request(rid=i, prompt=(1, 2, 3, 4, 5, 6, 7, 8),
                        max_new=4) for i in range(2)]
        for r in reqs:
            eng.submit(r)
        peak = 0
        outputs = {}
        for _ in range(50):
            if not (eng.n_queued or eng.n_active):
                break
            for rid, toks in eng.step():
                outputs[rid] = toks
            peak = max(peak, eng.n_active)
        assert sorted(outputs) == [0, 1]
        assert peak == 2          # concurrent: the share made it fit
        # the unshared engine CANNOT run these concurrently (3 + 3 > 5)
        eng2 = ServeEngine(mesh, cfg, dataclasses.replace(
            scfg, prefix_share=False))
        for r in reqs:
            eng2.submit(r)
        peak2 = 0
        for _ in range(50):
            if not (eng2.n_queued or eng2.n_active):
                break
            eng2.step()
            peak2 = max(peak2, eng2.n_active)
        assert peak2 == 1

    def test_share_ratio_monotone_static_proof(self):
        # the engine-level static proof of the sharing claim: prefill
        # tokens and fresh KV bytes are EXACT counters, and both drop
        # monotonically as the prompt share ratio rises
        from tpuscratch.bench.decode_bench import shared_prefix_prompts

        cfg = cfg_for()
        mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        scfg = self.scfg(prefix_share=True, n_pages=32)
        prefill, fresh = [], []
        for ratio in (0.0, 0.5, 0.9):
            prompts = shared_prefix_prompts(6, 16, ratio, scfg.vocab)
            eng = ServeEngine(mesh, cfg, scfg)
            rep = eng.run([
                Request(rid=i, prompt=p, max_new=4)
                for i, p in enumerate(prompts)
            ])
            prefill.append(rep.prefill_tokens)
            fresh.append(rep.fresh_kv_bytes)
        assert prefill[0] > prefill[1] > prefill[2]
        assert fresh[0] > fresh[1] > fresh[2]

    def test_retry_budget_rejected_with_ctx_admission(self):
        cfg = cfg_for()
        mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        with pytest.raises(ValueError):
            ServeEngine(mesh, cfg, self.scfg(prefix_share=True,
                                             retry_budget=1))
        with pytest.raises(ValueError):
            ServeEngine(mesh, cfg, self.scfg(chunk_prefill=2,
                                             retry_budget=1))


@pytest.mark.disagg
class TestChunkedPrefill:
    def scfg(self, **kw):
        kw.setdefault("n_slots", 4)
        kw.setdefault("n_pages", 32)
        kw.setdefault("page_size", 4)
        kw.setdefault("max_seq", 32)
        kw.setdefault("vocab", 16)
        return ServeConfig(**kw)

    # chunk 1 (the re-score shape) and a non-dividing chunk on 1x1;
    # the mesh-sharded case once at chunk 4 — every chunk size shares
    # ONE compiled program shape, so the matrix adds compile cost, not
    # coverage (chunk=1 rides the slow tier: the CoW re-score test
    # already drives a 1-token chunk through the same program)
    @pytest.mark.parametrize("dims,chunk", [
        pytest.param((1, 1), 1, marks=pytest.mark.slow),
        ((1, 1), 3),
        ((2, 2), 4),
    ])
    def test_greedy_bit_identical_to_monolithic(self, dims, chunk):
        cfg = cfg_for()
        n = dims[0] * dims[1]
        mesh = make_mesh(dims, ("dp", "sp"), jax.devices()[:n])
        reqs = [
            Request(rid=i, prompt=tuple(1 + (i + t) % 9
                                        for t in range(3 + 3 * i % 11)),
                    max_new=2 + i % 4)
            for i in range(6)
        ]
        rep_m = ServeEngine(mesh, cfg, self.scfg()).run(reqs)
        eng = ServeEngine(mesh, cfg, self.scfg(chunk_prefill=chunk))
        rep_c = eng.run(reqs)
        assert rep_c.outputs == rep_m.outputs
        assert eng.free_pages() == [self.scfg().n_pages] * dims[0]
        # chunking recomputes nothing: same prompt tokens prefilled
        assert rep_c.prefill_tokens == rep_m.prefill_tokens

    def test_long_admission_advances_one_chunk_per_tick(self):
        # ticks-to-first-token == ceil(prompt / chunk): the long prompt
        # costs each tick at most `chunk` prefill tokens, which is the
        # p99-bounding property (the bench measures the latency side)
        cfg = cfg_for()
        mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        chunk = 4
        eng = ServeEngine(mesh, cfg, self.scfg(chunk_prefill=chunk))
        prompt = tuple(1 + t % 9 for t in range(19))
        eng.submit(Request(rid=0, prompt=prompt, max_new=4))
        ticks = 0
        while not (eng._slots[0] and eng._slots[0].generated):
            eng.step()
            ticks += 1
        assert ticks == -(-len(prompt) // chunk)
        eng.run([])   # drains cleanly

    def test_resident_stream_advances_during_long_prefill(self):
        # the disaggregation motivation, behaviorally: a resident
        # stream emits one token EVERY tick while a long prompt
        # chunk-prefills beside it
        cfg = cfg_for()
        mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        eng = ServeEngine(mesh, cfg, self.scfg(chunk_prefill=2))
        eng.submit(Request(rid=0, prompt=(1, 2), max_new=20))
        eng.step()                       # resident admitted + token 2
        resident = eng._slots[0]
        eng.submit(Request(rid=1, prompt=tuple(1 + t % 9
                                               for t in range(16)),
                           max_new=2))
        for _ in range(8):               # long prompt needs 8 chunk ticks
            before = len(resident.generated)
            eng.step()
            assert len(resident.generated) == before + 1
        eng.run([])

    @pytest.mark.slow
    def test_chunk_composes_with_share_and_int8(self):
        cfg = cfg_for()
        mesh = make_mesh((2, 2), ("dp", "sp"), jax.devices()[:4])
        # staggered budgets: under chunked prefill a prompt becomes
        # shareable only once FULLY prefilled, so sharing needs late
        # arrivals to overlap still-live early residents
        reqs = [
            Request(rid=i, prompt=(1, 2, 3, 4, 5, 6, 7, 8, 9 + i % 3),
                    max_new=4 + 2 * i)
            for i in range(5)
        ]
        rep_m = ServeEngine(mesh, cfg, self.scfg()).run(reqs)
        both = ServeEngine(mesh, cfg, self.scfg(
            prefix_share=True, chunk_prefill=3))
        rep_b = both.run(reqs)
        assert rep_b.outputs == rep_m.outputs
        assert rep_b.shared_tokens > 0
        # int8 chunked == int8 monolithic (engine-level greedy gate;
        # 1x1 — the quantized write path has no mesh dependence the
        # fp32 2x2 case above doesn't already exercise)
        mesh1 = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        rep_m8 = ServeEngine(mesh1, cfg, self.scfg(kv_dtype="int8")).run(reqs)
        rep_c8 = ServeEngine(mesh1, cfg, self.scfg(
            kv_dtype="int8", chunk_prefill=3)).run(reqs)
        assert rep_c8.outputs == rep_m8.outputs

    def test_off_by_default_builds_no_context_program(self):
        # the off-switch proof: a default-config engine constructs
        # exactly the legacy programs (no context prefill anywhere)
        cfg = cfg_for()
        mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        eng = ServeEngine(mesh, cfg, self.scfg())
        assert eng._ctx is None and eng._tries is None
        eng2 = ServeEngine(mesh, cfg, self.scfg(chunk_prefill=2))
        assert eng2._ctx is not None

    @pytest.mark.slow
    def test_temperature_stream_identical_across_chunking(self):
        # sampling keys are (rid, position)-addressed, so chunking must
        # not move any request off its stream even at temperature
        cfg = cfg_for()
        mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        scfg = self.scfg(temperature=0.8, top_k=5, seed=7)
        reqs = [Request(rid=i, prompt=(1 + i, 2, 3), max_new=4)
                for i in range(5)]
        rep_m = ServeEngine(mesh, cfg, scfg).run(reqs)
        rep_c = ServeEngine(mesh, cfg, dataclasses.replace(
            scfg, chunk_prefill=2)).run(reqs)
        assert rep_c.outputs == rep_m.outputs
