"""Native pooled host-staging allocator (native/src/host_pool.cpp).

The contract mirrors the reference's ``host_allocator`` semantics
(host_allocator.h:58-93): aligned allocation, reuse, and clean release —
plus the pooling/stats surface the TPU build adds. Builds the native
library on demand like test_native.py.
"""

import threading

import numpy as np
import pytest

from tpuscratch import native

pytestmark = pytest.mark.skipif(
    not (native.available() or native.build()), reason="native toolchain absent"
)


@pytest.fixture()
def pool():
    from tpuscratch.native import hostpool

    with hostpool.HostPool(lock_pages=False) as p:
        yield p


def test_alloc_is_page_aligned(pool):
    with pool.alloc(100) as buf:
        assert buf.ptr % 4096 == 0
        assert buf.nbytes == 100


def test_data_roundtrip_through_view(pool):
    rng = np.random.default_rng(0)
    data = rng.standard_normal(1000).astype(np.float32)
    with pool.alloc(data.nbytes) as buf:
        view = buf.view(np.float32, (1000,))
        np.copyto(view, data)
        np.testing.assert_array_equal(buf.view(np.float32, (1000,)), data)
        del view  # free() (the with-exit) refuses while views are alive


def test_free_then_alloc_reuses_buffer(pool):
    buf = pool.alloc(5000)
    first_ptr = buf.ptr
    buf.free()
    buf2 = pool.alloc(6000)  # same 8192-byte size class
    assert buf2.ptr == first_ptr
    assert pool.stats()["reuse_hits"] == 1
    buf2.free()


def test_stats_accounting(pool):
    assert pool.stats()["bytes_in_use"] == 0
    a = pool.alloc(4096)
    b = pool.alloc(4096)
    s = pool.stats()
    assert s["bytes_in_use"] == 8192
    assert s["high_water"] == 8192
    assert s["alloc_calls"] == 2
    a.free()
    s = pool.stats()
    assert s["bytes_in_use"] == 4096
    assert s["bytes_cached"] == 4096
    assert s["high_water"] == 8192
    b.free()
    pool.trim()
    s = pool.stats()
    assert s["bytes_in_use"] == 0
    assert s["bytes_cached"] == 0
    assert s["page_class"] == 4096


def test_alloc_pages_bulk_buffer_and_traffic_counters(pool):
    # the KV paging tier's shape: ONE buffer per spill batch, with the
    # lock-guarded spill/prefetch byte counters + live high-water the
    # tier reports through (footprint observable, not silent)
    with pool.alloc_pages(4, 1000) as buf:
        assert buf.nbytes == 4000
        assert pool.stats()["live_buffers"] == 1
        assert pool.stats()["live_buffers_hw"] >= 1
        with pool.alloc_pages(2, 1000) as _b2:
            assert pool.stats()["live_buffers_hw"] >= 2
    pool.note_spill(4000)
    pool.note_spill(1000)
    pool.note_prefetch(2500)
    s = pool.stats()
    assert s["spill_bytes"] == 5000
    assert s["prefetch_bytes"] == 2500
    with pytest.raises(ValueError):
        pool.alloc_pages(0, 1000)


def test_traffic_counters_are_thread_safe(pool):
    def worker():
        for _ in range(500):
            pool.note_spill(2)
            pool.note_prefetch(3)

    ts = [threading.Thread(target=worker) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert pool.stats()["spill_bytes"] == 4 * 500 * 2
    assert pool.stats()["prefetch_bytes"] == 4 * 500 * 3


def test_size_class_rounding(pool):
    with pool.alloc(4097) as buf:
        assert buf.nbytes == 4097  # logical size preserved
    assert pool.stats()["bytes_cached"] == 8192  # physical class size


def test_double_free_and_stale_view_guard(pool):
    buf = pool.alloc(64)
    buf.free()
    buf.free()  # idempotent
    with pytest.raises(ValueError):
        buf.view(np.uint8)
    with pytest.raises(ValueError):
        _ = buf.ptr


def test_oversized_view_rejected(pool):
    with pool.alloc(100) as buf:
        with pytest.raises(ValueError):
            buf.view(np.float32, (1000,))


def test_bad_alloc_size_rejected(pool):
    with pytest.raises(ValueError):
        pool.alloc(0)


def test_absurd_alloc_size_fails_cleanly(pool):
    with pytest.raises(MemoryError):
        pool.alloc(2**63 + 1)


def test_lock_pages_graceful_fallback():
    """mlock either succeeds (locked_bytes > 0) or falls back
    (lock_failures > 0) — never crashes."""
    from tpuscratch.native import hostpool

    with hostpool.HostPool(lock_pages=True) as p:
        with p.alloc(4096):
            s = p.stats()
            assert s["locked_bytes"] > 0 or s["lock_failures"] > 0


def test_concurrent_alloc_free(pool):
    errors = []

    def churn():
        try:
            for _ in range(200):
                with pool.alloc(2048) as buf:
                    buf.view(np.uint8)[0] = 1
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=churn) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert pool.stats()["bytes_in_use"] == 0


def test_default_pool_singleton_and_staging_bench():
    from tpuscratch.bench.pingpong import (
        native_pool_staging_roundtrip,
        pageable_buffer_staging_roundtrip,
    )
    from tpuscratch.native import hostpool

    assert hostpool.default_pool() is hostpool.default_pool()
    res = native_pool_staging_roundtrip(1024, iters=2)
    control = pageable_buffer_staging_roundtrip(1024, iters=2)
    assert res.p50 > 0 and control.p50 > 0
    assert res.bytes_moved == control.bytes_moved == 2 * 1024 * 4


def test_free_refuses_while_view_alive():
    hostpool = pytest.importorskip("tpuscratch.native.hostpool")
    if not hostpool.available():
        pytest.skip("native library not built")
    pool = hostpool.HostPool(lock_pages=False)
    buf = pool.alloc(4096)
    v = buf.view(np.float32)
    with pytest.raises(ValueError, match="live view"):
        buf.free()
    del v
    buf.free()  # now fine
    assert buf._ptr is None
    pool.close()


def test_abandoned_pool_finalized():
    hostpool = pytest.importorskip("tpuscratch.native.hostpool")
    if not hostpool.available():
        pytest.skip("native library not built")
    import weakref

    pool = hostpool.HostPool(lock_pages=False)
    fin = pool._finalizer
    assert fin.alive
    del pool  # no close(): the finalizer must reclaim the native pool
    import gc

    gc.collect()
    assert not fin.alive


def test_close_then_finalizer_single_destroy():
    hostpool = pytest.importorskip("tpuscratch.native.hostpool")
    if not hostpool.available():
        pytest.skip("native library not built")
    pool = hostpool.HostPool(lock_pages=False)
    pool.close()
    assert pool._handle is None
    assert not pool._finalizer.alive
    pool.close()  # idempotent


def test_view_keeps_pool_alive():
    # the use-after-free guard: a live view must pin the buffer AND the
    # pool, or the pool finalizer would free the pages under the view
    hostpool = pytest.importorskip("tpuscratch.native.hostpool")
    if not hostpool.available():
        pytest.skip("native library not built")
    import gc

    v = hostpool.HostPool(lock_pages=False).alloc(4096).view(np.float32)
    gc.collect()
    v[:] = 1.0  # would be a write into freed heap without the anchor
    assert float(v[0]) == 1.0
    # walk to the ctypes block at the root of the view chain: the anchor
    # there must be keeping the pool's finalizer alive
    base = v
    while getattr(base, "base", None) is not None:
        base = base.base
    assert base._tpuscratch_buffer._pool._finalizer.alive
    del v, base
    gc.collect()
