"""Parity tests for the slice-spec algebra (MPI derived datatypes).

mpi7 (indexed), mpi8 (struct scatter), mpi-complex-types (hindexed over
subarrays of separate arrays), stencil2D.h subarray types — each reference
program's observable data movement reproduced with specs + collectives.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpuscratch.comm import broadcast, run_spmd, scatter_from_root
from tpuscratch.dtypes import (
    HIndexedSpec,
    IndexedSpec,
    StructSpec,
    SubarraySpec,
    exchange_packed,
)
from tpuscratch.runtime.mesh import make_mesh_1d

N = 8


class TestIndexedSpec:
    def test_mpi7_blocks(self):
        # mpi7.cpp:36-41 — 2 blocks: len 4 @ disp 5, len 2 @ disp 12 of 16
        spec = IndexedSpec(((5, 4), (12, 2)))
        assert spec.size == 6
        x = jnp.arange(16.0)
        np.testing.assert_array_equal(
            spec.pack(x), [5, 6, 7, 8, 12, 13]
        )

    def test_roundtrip(self):
        spec = IndexedSpec(((0, 2), (6, 3)))
        x = jnp.zeros(10)
        got = spec.unpack(jnp.arange(1.0, 6.0), x)
        np.testing.assert_array_equal(
            got, [1, 2, 0, 0, 0, 0, 3, 4, 5, 0]
        )
        np.testing.assert_array_equal(spec.pack(got), np.arange(1.0, 6.0))

    def test_validation(self):
        with pytest.raises(ValueError):
            IndexedSpec(((0, 0),))
        with pytest.raises(ValueError):
            IndexedSpec(((-1, 3),))

    def test_distributed_indexed_send(self):
        # mpi7 end-to-end: root broadcasts; every rank unpacks root's two
        # blocks as 6 plain floats (receivers need no datatype: mpi7.cpp:58)
        mesh = make_mesh_1d("x")
        spec = IndexedSpec(((5, 4), (12, 2)))

        def body(x):
            return broadcast(spec.pack(x), "x", root=0)

        f = run_spmd(mesh, body, P(), P(None))
        out = np.asarray(f(jnp.arange(16.0)))
        np.testing.assert_array_equal(out, [5, 6, 7, 8, 12, 13])


class TestSubarraySpec:
    def test_region_extraction(self):
        spec = SubarraySpec(offsets=(1, 2), shape=(2, 3))
        x = jnp.arange(30.0).reshape(5, 6)
        np.testing.assert_array_equal(
            spec.region(x), [[8, 9, 10], [14, 15, 16]]
        )
        assert spec.size == 6

    def test_roundtrip(self):
        spec = SubarraySpec(offsets=(0, 1), shape=(2, 2))
        x = jnp.zeros((3, 4))
        y = spec.unpack(jnp.array([1.0, 2, 3, 4]), x)
        np.testing.assert_array_equal(
            y, [[0, 1, 2, 0], [0, 3, 4, 0], [0, 0, 0, 0]]
        )

    def test_rank_mismatch(self):
        with pytest.raises(ValueError):
            SubarraySpec((0,), (2, 2))

    def test_exchange_packed_ring(self):
        # each rank sends a 2x2 corner of its tile one step around the ring,
        # landing in a different region on the receiver (send/recv datatypes
        # differ, as in halo exchange)
        mesh = make_mesh_1d("x")
        send = SubarraySpec(offsets=(0, 0), shape=(2, 2))
        recv = SubarraySpec(offsets=(2, 2), shape=(2, 2))
        perm = [(i, (i + 1) % N) for i in range(N)]

        def body(x):
            tile = x[0]  # strip leading shard dim
            out = exchange_packed(send, tile, "x", perm, dest_spec=recv)
            return out[None]

        f = run_spmd(mesh, body, P("x", None, None), P("x", None, None))
        tiles = jnp.stack(
            [jnp.full((4, 4), float(i)) for i in range(N)]
        )
        out = np.asarray(f(tiles))
        # rank 1's bottom-right 2x2 now holds rank 0's id, rest unchanged
        assert (out[1][2:, 2:] == 0.0).all()
        assert (out[1][:2, :2] == 1.0).all()
        assert (out[0][2:, 2:] == 7.0).all()


class TestStructSpec:
    SPEC = StructSpec(("pos", "vel", "charge", "mass", "id", "flag"))

    def _particles(self, n):
        # mpi8's Particle {4 floats; 2 ints} as struct-of-arrays
        return {
            "pos": jnp.arange(n, dtype=jnp.float32),
            "vel": jnp.arange(n, dtype=jnp.float32) * 2,
            "charge": jnp.ones(n, dtype=jnp.float32),
            "mass": jnp.full(n, 3.0, dtype=jnp.float32),
            "id": jnp.arange(n, dtype=jnp.int32),
            "flag": jnp.zeros(n, dtype=jnp.int32),
        }

    def test_validate(self):
        tree = self._particles(16)
        assert self.SPEC.validate(tree) == 16
        bad = dict(tree, extra=jnp.zeros(16))
        with pytest.raises(ValueError):
            self.SPEC.validate(bad)
        ragged = dict(tree, pos=jnp.zeros(3))
        with pytest.raises(ValueError):
            self.SPEC.validate(ragged)

    def test_records_slice(self):
        tree = self._particles(16)
        share = self.SPEC.records(tree, 4, 2)
        np.testing.assert_array_equal(share["pos"], [4, 5])
        np.testing.assert_array_equal(share["id"], [4, 5])
        assert share["id"].dtype == jnp.int32  # mixed dtypes preserved

    def test_concat_roundtrip(self):
        tree = self._particles(6)
        parts = [self.SPEC.records(tree, i * 2, 2) for i in range(3)]
        whole = self.SPEC.concat(parts)
        for k in self.SPEC.fields:
            np.testing.assert_array_equal(whole[k], tree[k])

    def test_mpi8_scatter(self):
        # mpi8 end-to-end: root's 16 particles scattered 2 per rank; the
        # "struct datatype" is just the pytree — one collective per field
        mesh = make_mesh_1d("x")
        tree = self._particles(16)

        def body(t):
            return jax.tree.map(lambda a: scatter_from_root(a, "x"), t)

        f = run_spmd(mesh, body, P(), P("x"))
        out = f(tree)
        np.testing.assert_array_equal(
            np.asarray(out["pos"]), np.arange(16, dtype=np.float32)
        )
        assert out["id"].dtype == jnp.int32


class TestHIndexedSpec:
    def test_complex_types_parity(self):
        # mpi-complex-types: 3-element runs of 3 separately-allocated
        # arrays, one message; displacements are list indices, not pointers
        a = jnp.arange(10.0)
        b = jnp.arange(10.0, 20.0)
        c = jnp.arange(20.0, 30.0)
        spec = HIndexedSpec(
            (
                (0, IndexedSpec(((2, 3),))),
                (1, IndexedSpec(((0, 3),))),
                (2, IndexedSpec(((5, 3),))),
            )
        )
        assert spec.size == 9
        payload = spec.pack([a, b, c])
        np.testing.assert_array_equal(
            payload, [2, 3, 4, 10, 11, 12, 25, 26, 27]
        )

    def test_unpack_into_separate_arrays(self):
        spec = HIndexedSpec(
            ((0, IndexedSpec(((0, 2),))), (1, SubarraySpec((1, 1), (1, 2))))
        )
        x0 = jnp.zeros(4)
        x1 = jnp.zeros((3, 3))
        y0, y1 = spec.unpack(jnp.array([1.0, 2, 3, 4]), [x0, x1])
        np.testing.assert_array_equal(y0, [1, 2, 0, 0])
        np.testing.assert_array_equal(
            y1, [[0, 0, 0], [0, 3, 4], [0, 0, 0]]
        )

    def test_pack_unpack_inverse(self):
        spec = HIndexedSpec(
            ((0, IndexedSpec(((1, 2), (5, 1)))), (1, SubarraySpec((0, 0), (2, 2))))
        )
        arrays = [jnp.arange(8.0), jnp.arange(9.0).reshape(3, 3)]
        payload = spec.pack(arrays)
        restored = spec.unpack(payload, arrays)
        for orig, back in zip(arrays, restored):
            np.testing.assert_array_equal(orig, back)


# ---- serve KV dtype ladder (ISSUE 12: the fp8-e4m3 rung) ------------------
#
# Not a slice-spec concern, but this file is the repo's dtype-contract
# home: the KV ladder's quantization round trips and the ledger byte
# proof live beside the wire-format round trips above.

from tpuscratch.obs.ledger import kv_cache_bytes  # noqa: E402
from tpuscratch.serve.kvcache import (  # noqa: E402
    FP8_QMAX,
    CacheGeometry,
    dequantize_pages,
    init_kv_cache,
    is_quantized_kv_dtype,
    quantize_pages,
)


class TestKVDtypeLadder:
    def test_fp8_roundtrip_error_bound(self):
        """e4m3 floating grid: relative error <= 2^-4 at any magnitude
        (3 mantissa bits), absolute error below scale * 2^-9 in the
        subnormal tail; the amax entry scales to exactly 448 and
        round-trips exactly."""
        rng = np.random.default_rng(0)
        x = jnp.asarray(
            rng.standard_normal((5, 4, 3, 8)).astype(np.float32) * 3.0
        )
        q, s = quantize_pages(x, jnp.float8_e4m3fn)
        assert q.dtype == jnp.float8_e4m3fn and s.shape == (5, 3)
        back = np.asarray(dequantize_pages(q, s))
        err = np.abs(back - np.asarray(x))
        bound = (np.abs(np.asarray(x)) * 2.0 ** -4
                 + np.asarray(s)[:, None, :, None] * 2.0 ** -9 + 1e-7)
        assert (err <= bound).all()
        amax = np.abs(np.asarray(x)).max(axis=(1, 3))
        np.testing.assert_allclose(np.asarray(s) * FP8_QMAX, amax,
                                   rtol=1e-6)
        # the amax entry is exact (448 is representable in e4m3)
        per_page_amax_err = np.abs(
            np.abs(back).max(axis=(1, 3)) - amax
        )
        np.testing.assert_allclose(per_page_amax_err, 0.0, atol=1e-6)

    def test_fp8_zero_page_quantizes_to_zero(self):
        q, s = quantize_pages(jnp.zeros((2, 4, 2, 8)), jnp.float8_e4m3fn)
        assert float(jnp.abs(dequantize_pages(q, s)).max()) == 0.0

    def test_fp8_beats_int8_on_outlier_pages(self):
        """The regime fp8 exists for: one large outlier per page costs
        int8's uniform grid its whole-page resolution (error ~scale/2
        everywhere) but costs the e4m3 floating grid nothing for the
        inliers (relative grid).  Same bytes, complementary error."""
        rng = np.random.default_rng(1)
        x = rng.standard_normal((4, 8, 2, 16)).astype(np.float32) * 0.1
        x[:, 0, :, 0] = 50.0  # one outlier entry per (page, head)
        xj = jnp.asarray(x)
        qi, si = quantize_pages(xj, jnp.int8)
        qf, sf = quantize_pages(xj, jnp.float8_e4m3fn)
        inlier = np.ones_like(x, bool)
        inlier[:, 0, :, 0] = False
        err_i = np.abs(np.asarray(dequantize_pages(qi, si)) - x)[inlier]
        err_f = np.abs(np.asarray(dequantize_pages(qf, sf)) - x)[inlier]
        assert err_f.max() < err_i.max() / 5, (
            f"fp8 inlier error {err_f.max():.4f} not well below int8's "
            f"{err_i.max():.4f} on outlier pages"
        )

    def test_quantize_rejects_non_ladder_dtype(self):
        with pytest.raises(ValueError):
            quantize_pages(jnp.zeros((1, 4, 2, 8)), jnp.int4)
        with pytest.raises(ValueError):
            init_kv_cache(CacheGeometry(1, 4, 4, 2, 8), dtype=jnp.bfloat16)

    def test_ladder_predicate(self):
        assert is_quantized_kv_dtype(jnp.int8)
        assert is_quantized_kv_dtype(jnp.float8_e4m3fn)
        assert not is_quantized_kv_dtype(jnp.float32)

    def test_fp8_ledger_bytes_match_int8_and_pin(self):
        """The ledger proof at the new rung: fp8 cache bytes == int8
        cache bytes EXACTLY (both 1 byte/element + identical fp32 scale
        planes) and <= 0.30x fp32 at both record geometries — the
        ISSUE-12 acceptance bound, tighter than int8's 0.55x pin."""
        from tpuscratch.bench.decode_bench import default_decode_setup

        for on_tpu in (False, True):
            cfg, scfg, _, _ = default_decode_setup(on_tpu)
            geom = CacheGeometry(cfg.n_layers, scfg.n_pages,
                                 scfg.page_size, cfg.n_heads, cfg.d_head)
            b_f32 = kv_cache_bytes(init_kv_cache(geom))
            b_int8 = kv_cache_bytes(init_kv_cache(geom, dtype=jnp.int8))
            b_fp8 = kv_cache_bytes(
                init_kv_cache(geom, dtype=jnp.float8_e4m3fn)
            )
            assert b_fp8 == b_int8, "fp8 must not fatten the cache"
            ratio = b_fp8 / b_f32
            analytic = 0.25 + 1.0 / (geom.page_size * geom.d_head)
            assert abs(ratio - analytic) < 1e-9
            assert ratio <= 0.30, f"fp8 cache ratio {ratio:.3f} > 0.30"
