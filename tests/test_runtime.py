"""Tests for mesh construction, runtime context, config, errors, logging.

Parity targets: mpi1 (hello/rank/size), mpi2 (error policies), the config
switch tiers (SURVEY.md §5 config), and the single-write logging pattern
(mpi7.cpp:56-62).
"""

import io

import jax
import pytest

from tpuscratch.runtime.config import Config
from tpuscratch.runtime.context import initialize, node_census
from tpuscratch.runtime.errors import CommError, ErrorPolicy, guarded, guards
from tpuscratch.runtime.log import RankLogger, coord_filename
from tpuscratch.runtime.mesh import (
    make_mesh,
    make_mesh_1d,
    make_mesh_2d,
    shard_along,
    topology_of,
)


class TestMesh:
    def test_1d_all_devices(self, devices):
        mesh = make_mesh_1d("x")
        assert mesh.devices.shape == (len(devices),)
        assert mesh.axis_names == ("x",)

    def test_2d_default_factorization(self, devices):
        mesh = make_mesh_2d()
        assert mesh.devices.shape == (2, 4)
        assert mesh.axis_names == ("row", "col")

    def test_2d_explicit(self):
        mesh = make_mesh_2d((4, 2), ("a", "b"))
        assert mesh.devices.shape == (4, 2)

    def test_device_order_row_major(self, devices):
        # contract: mesh position == CartTopology rank == flat device index
        mesh = make_mesh_2d((2, 4))
        assert mesh.devices[0, 3] == devices[3]
        assert mesh.devices[1, 0] == devices[4]

    def test_too_many(self, devices):
        with pytest.raises(ValueError):
            make_mesh((len(devices) + 1,), ("x",))

    def test_topology_of(self):
        mesh = make_mesh_2d((2, 4))
        topo = topology_of(mesh, periodic=True)
        assert topo.dims == (2, 4)
        assert topo.periodic == (True, True)

    def test_shard_along(self):
        mesh = make_mesh_2d((2, 4))
        s = shard_along(mesh, "row", "col")
        assert s.mesh is not None


class TestContext:
    def test_initialize_single_host(self, devices):
        ctx = initialize()
        assert ctx.process_index == 0
        assert ctx.process_count == 1
        assert ctx.global_device_count == len(devices)
        assert ctx.backend == "cpu"
        assert node_census(ctx) == 1

    def test_hello(self):
        ctx = initialize()
        h = ctx.hello()
        assert "process 0 of 1" in h
        assert ctx.hostname in h


class TestConfig:
    def test_defaults(self):
        cfg = Config()
        assert cfg.halo_width == 2  # 5x5 stencil -> ghost depth 2
        assert cfg.jnp_dtype == jax.numpy.float32

    def test_argv_tile_and_stencil(self):
        cfg = Config.from_argv(["32", "24", "3", "7"])
        assert (cfg.tile_width, cfg.tile_height) == (32, 24)
        # the reference's stencilHeight self-assignment bug is fixed here:
        # CLI stencil height must actually apply (-cuda.cu:137)
        assert (cfg.stencil_width, cfg.stencil_height) == (3, 7)
        assert (cfg.halo_width, cfg.halo_height) == (1, 3)

    def test_argv_elements(self):
        cfg = Config.from_argv(["1048576"])
        assert cfg.elements == 1048576

    def test_env(self):
        cfg = Config.from_env(
            {"TPUSCRATCH_DTYPE": "bfloat16", "TPUSCRATCH_NO_LOG": "1",
             "TPUSCRATCH_MESH": "2x4", "TPUSCRATCH_ABORT_ON_ERROR": "1"}
        )
        assert cfg.dtype == "bfloat16"
        assert cfg.log is False
        assert cfg.mesh_shape == (2, 4)
        assert cfg.error_policy is ErrorPolicy.ABORT

    def test_bad_dtype(self):
        with pytest.raises(ValueError):
            _ = Config(dtype="float16x").jnp_dtype


class TestErrors:
    def test_guarded_raises_comm_error(self):
        with pytest.raises(CommError) as ei:
            with guarded("mesh build", ErrorPolicy.RAISE, rank=3):
                raise ValueError("boom")
        assert "[rank 3] mesh build" in str(ei.value)
        assert "ValueError" in str(ei.value)

    def test_guarded_passthrough(self):
        with guarded("noop"):
            pass

    def test_comm_error_not_double_wrapped(self):
        with pytest.raises(CommError) as ei:
            with guarded("outer"):
                with guarded("inner"):
                    raise RuntimeError("x")
        assert ei.value.op == "inner"

    def test_guards_decorator(self):
        @guards("op-name")
        def f():
            raise KeyError("k")

        with pytest.raises(CommError) as ei:
            f()
        assert ei.value.op == "op-name"

    def test_guarded_attaches_op_to_opless_comm_error(self):
        # a chaos-injected CommError is raised without knowing which op
        # wraps it; the guard fills the op (and rank) in so ft retry
        # logs name the failing op
        with pytest.raises(CommError) as ei:
            with guarded("allreduce", rank=2):
                raise CommError("", "injected error fault")
        assert ei.value.op == "allreduce"
        assert ei.value.rank == 2
        assert "[rank 2] allreduce: injected error fault" in str(ei.value)

    def test_abort_policy_hard_exits_subprocess(self):
        # the os._exit(1) path (MPI_Abort parity) — only testable from
        # outside the process
        import os
        import pathlib
        import subprocess
        import sys

        repo = pathlib.Path(__file__).parent.parent
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env["PYTHONPATH"] = os.pathsep.join(
            [str(repo)]
            + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else [])
        )
        code = (
            "from tpuscratch.runtime.errors import ErrorPolicy, guarded\n"
            "with guarded('mesh build', ErrorPolicy.ABORT, rank=1):\n"
            "    raise ValueError('bad topology')\n"
            "print('UNREACHED')\n"
        )
        p = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=120,
                           env=env, cwd=str(repo))
        assert p.returncode == 1, (p.returncode, p.stderr)
        assert "UNREACHED" not in p.stdout
        assert "[rank 1] mesh build: ValueError: bad topology" in p.stderr


class TestLogging:
    def test_prefix(self):
        out = io.StringIO()
        log = RankLogger(rank=2, coords=(0, 2), stream=out)
        log("hello", 42)
        assert out.getvalue() == "[rank 2 (0,2)] hello 42\n"

    def test_buffered_single_write(self):
        out = io.StringIO()
        with RankLogger(rank=1, buffered=True, stream=out) as log:
            log("a")
            log("b")
            assert out.getvalue() == ""  # nothing until flush
        assert out.getvalue() == "[rank 1] a\n[rank 1] b\n"

    def test_disabled(self):
        out = io.StringIO()
        RankLogger(enabled=False, stream=out)("hidden")
        assert out.getvalue() == ""

    def test_log0(self):
        out = io.StringIO()
        RankLogger(rank=3, stream=out).log0("root only")
        assert out.getvalue() == ""
        RankLogger(rank=0, stream=out).log0("root only")
        assert "root only" in out.getvalue()

    def test_coord_filename(self):
        assert coord_filename((0, 2)) == "0_2"
        assert coord_filename((1, 1), prefix="tile_") == "tile_1_1"


class TestReviewRegressions:
    """Fixes from the first code review pass."""

    def test_dims_coerced_to_tuple(self):
        from tpuscratch.runtime.topology import CartTopology

        t = CartTopology([3, 3])
        assert hash(t) == hash(CartTopology((3, 3)))
        assert t == CartTopology((3, 3))

    def test_argv_three_positionals_apply_stencil_width(self):
        cfg = Config.from_argv(["32", "24", "7"])
        assert cfg.stencil_width == 7
        assert cfg.stencil_height == 5

    def test_abort_env_value_respected(self):
        cfg = Config.from_env({"TPUSCRATCH_ABORT_ON_ERROR": "0"})
        assert cfg.error_policy is ErrorPolicy.RAISE

    def test_system_exit_passes_through_guard(self):
        with pytest.raises(SystemExit) as ei:
            with guarded("clean exit"):
                raise SystemExit(0)
        assert ei.value.code == 0

    def test_initialize_kwargs_not_silently_dropped(self):
        # Asking for a multi-process rendezvous on a single-host test run
        # must fail loudly, not return a bogus 1-process context.
        with pytest.raises(CommError):
            initialize(num_processes=4, process_id=2)
