"""Cross-check the native C++ planner against the pure-Python geometry.

The native library mirrors topology.py + layout.py one-for-one; these
tests are the contract. Builds the library on demand (g++ is baked into
the image); skips only if the toolchain is genuinely absent.
"""

import itertools

import pytest

from tpuscratch import native
from tpuscratch.halo.exchange import HaloSpec
from tpuscratch.halo.layout import TileLayout
from tpuscratch.runtime.topology import ALL_DIRECTIONS, CartTopology

pytestmark = pytest.mark.skipif(
    not (native.available() or native.build()), reason="native toolchain absent"
)

CONFIGS = [
    ((2, 4), (True, True)),
    ((3, 3), (True, True)),
    ((3, 3), (False, False)),
    ((1, 1), (True, True)),
    ((4, 2), (True, False)),
    ((1, 5), (False, True)),
]


@pytest.mark.parametrize("dims,periodic", CONFIGS)
def test_neighbor_matches_python(dims, periodic):
    topo = CartTopology(dims, periodic)
    for rank in topo.ranks():
        for d in ALL_DIRECTIONS:
            assert native.neighbor(dims, periodic, rank, d.offset) == topo.neighbor(
                rank, d
            ), (dims, periodic, rank, d)


@pytest.mark.parametrize("dims,periodic", CONFIGS)
def test_permutation_matches_python(dims, periodic):
    topo = CartTopology(dims, periodic)
    for d in ALL_DIRECTIONS:
        assert native.send_permutation(dims, periodic, d.offset) == list(
            topo.send_permutation(d)
        )


@pytest.mark.parametrize("core,halo", [((16, 16), (2, 2)), ((8, 12), (1, 3)), ((6, 7), (2, 1))])
def test_rects_match_python(core, halo):
    lay = TileLayout(core[0], core[1], halo[0], halo[1])
    for d in ALL_DIRECTIONS:
        hr = native.halo_rect(core[0], core[1], halo[0], halo[1], d.offset)
        r = lay.halo_region(d)
        assert hr == (*r.offsets, *r.shape), ("halo", d)
        sr = native.send_rect(core[0], core[1], halo[0], halo[1], d.offset)
        s = lay.send_region(d)
        assert sr == (*s.offsets, *s.shape), ("send", d)


@pytest.mark.parametrize("dims,periodic", CONFIGS[:4])
@pytest.mark.parametrize("neighbors", [4, 8])
def test_full_plan_matches_python(dims, periodic, neighbors):
    topo = CartTopology(dims, periodic)
    lay = TileLayout(8, 8, 2, 2)
    spec = HaloSpec(layout=lay, topology=topo, neighbors=neighbors)
    py_plan = spec.plan()
    native_plan = native.build_plan(dims, periodic, 8, 8, 2, 2, neighbors)
    assert len(native_plan) == len(py_plan)
    for nat, py in zip(native_plan, py_plan):
        assert nat["direction"] == py.direction.offset
        assert nat["send_rect"] == (*py.send.offsets, *py.send.shape)
        assert nat["recv_rect"] == (*py.recv.offsets, *py.recv.shape)
        assert nat["perm"] == list(py.perm)


def test_native_rejects_bad_config():
    with pytest.raises(ValueError):
        native.build_plan((2, 4), (True, True), 8, 8, 9, 2)  # halo > core
    with pytest.raises(ValueError):
        native.build_plan((2, 4), (True, True), 8, 8, 1, 1, neighbors=5)
