"""Cross-check the native C++ planner against the pure-Python geometry.

The native library mirrors topology.py + layout.py one-for-one; these
tests are the contract. Builds the library on demand (g++ is baked into
the image); skips only if the toolchain is genuinely absent.
"""

import itertools

import pytest

from tpuscratch import native
from tpuscratch.halo.exchange import HaloSpec
from tpuscratch.halo.layout import TileLayout
from tpuscratch.runtime.topology import ALL_DIRECTIONS, CartTopology

pytestmark = pytest.mark.skipif(
    # a loadable but pre-3D .so (stale wheel/package copy) must trigger a
    # rebuild, not short-circuit it — has_plan3d() guards that case
    not ((native.available() and native.has_plan3d()) or native.build()),
    reason="native toolchain absent",
)

CONFIGS = [
    ((2, 4), (True, True)),
    ((3, 3), (True, True)),
    ((3, 3), (False, False)),
    ((1, 1), (True, True)),
    ((4, 2), (True, False)),
    ((1, 5), (False, True)),
]


@pytest.mark.parametrize("dims,periodic", CONFIGS)
def test_neighbor_matches_python(dims, periodic):
    topo = CartTopology(dims, periodic)
    for rank in topo.ranks():
        for d in ALL_DIRECTIONS:
            assert native.neighbor(dims, periodic, rank, d.offset) == topo.neighbor(
                rank, d
            ), (dims, periodic, rank, d)


@pytest.mark.parametrize("dims,periodic", CONFIGS)
def test_permutation_matches_python(dims, periodic):
    topo = CartTopology(dims, periodic)
    for d in ALL_DIRECTIONS:
        assert native.send_permutation(dims, periodic, d.offset) == list(
            topo.send_permutation(d)
        )


@pytest.mark.parametrize("core,halo", [((16, 16), (2, 2)), ((8, 12), (1, 3)), ((6, 7), (2, 1))])
def test_rects_match_python(core, halo):
    lay = TileLayout(core[0], core[1], halo[0], halo[1])
    for d in ALL_DIRECTIONS:
        hr = native.halo_rect(core[0], core[1], halo[0], halo[1], d.offset)
        r = lay.halo_region(d)
        assert hr == (*r.offsets, *r.shape), ("halo", d)
        sr = native.send_rect(core[0], core[1], halo[0], halo[1], d.offset)
        s = lay.send_region(d)
        assert sr == (*s.offsets, *s.shape), ("send", d)


@pytest.mark.parametrize("dims,periodic", CONFIGS[:4])
@pytest.mark.parametrize("neighbors", [4, 8])
def test_full_plan_matches_python(dims, periodic, neighbors):
    topo = CartTopology(dims, periodic)
    lay = TileLayout(8, 8, 2, 2)
    spec = HaloSpec(layout=lay, topology=topo, neighbors=neighbors)
    py_plan = spec.plan()
    native_plan = native.build_plan(dims, periodic, 8, 8, 2, 2, neighbors)
    assert len(native_plan) == len(py_plan)
    for nat, py in zip(native_plan, py_plan):
        assert nat["direction"] == py.direction.offset
        assert nat["send_rect"] == (*py.send.offsets, *py.send.shape)
        assert nat["recv_rect"] == (*py.recv.offsets, *py.recv.shape)
        assert nat["perm"] == list(py.perm)


def test_native_rejects_bad_config():
    with pytest.raises(ValueError):
        native.build_plan((2, 4), (True, True), 8, 8, 9, 2)  # halo > core
    with pytest.raises(ValueError):
        native.build_plan((2, 4), (True, True), 8, 8, 1, 1, neighbors=5)


CONFIGS_3D = [
    ((2, 2, 2), (True, True, True), (4, 6, 8), (1, 1, 1)),
    ((1, 2, 4), (False, True, False), (2, 2, 2), (1, 1, 1)),
    ((3, 2, 2), (True, False, True), (4, 4, 4), (2, 1, 1)),
    ((1, 1, 1), (True, True, True), (2, 2, 2), (1, 1, 1)),
]


@pytest.mark.parametrize("neighbors", [6, 26])
@pytest.mark.parametrize("dims,periodic,core,halo", CONFIGS_3D)
def test_plan3d_matches_python(dims, periodic, core, halo, neighbors):
    """The native 3D plan (faces-only and all-26) equals the pure-Python
    one exactly."""
    from unittest import mock

    from tpuscratch.halo import halo3d

    assert native.has_plan3d()
    topo = CartTopology(dims, periodic)
    lay = halo3d.TileLayout3D(core, halo)
    halo3d._cached_plan3d.cache_clear()
    nat = halo3d._cached_plan3d(lay, topo, neighbors)
    with mock.patch.object(native, "available", lambda: False):
        halo3d._cached_plan3d.cache_clear()
        py = halo3d._cached_plan3d(lay, topo, neighbors)
    halo3d._cached_plan3d.cache_clear()
    assert len(nat) == neighbors
    assert nat == py


def test_neighbor3d_open_boundary():
    lib = native.load()
    # corner rank 0 of a 2x2x2 open grid: -z neighbor is off-grid
    assert lib.ts_neighbor3d(2, 2, 2, 0, 0, 0, 0, -1, 0, 0) == -1
    # periodic wrap: -z from rank 0 lands at z=1 plane, same (y,x)
    assert lib.ts_neighbor3d(2, 2, 2, 1, 0, 0, 0, -1, 0, 0) == 4
