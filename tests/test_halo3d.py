"""3D halo exchange + 7-point stencil vs numpy oracles.

Mirrors the 2D library's test strategy (SURVEY.md §4) one dimension up:
pure region-geometry unit tests, a rank-id "golden" exchange on the
2x2x2 torus, and dual-backend oracles against the undecomposed grid.
"""

import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpuscratch.comm import run_spmd
from tpuscratch.halo.halo3d import (
    FACES,
    HaloSpec3D,
    TileLayout3D,
    decompose3d,
    distributed_stencil3d,
    halo_exchange3d,
)
from tpuscratch.runtime.mesh import make_mesh
from tpuscratch.runtime.topology import CartTopology, factor3d


class TestLayout3D:
    def test_regions(self):
        lay = TileLayout3D((4, 6, 8), (1, 1, 1))
        assert lay.padded_shape == (6, 8, 10)
        up = lay.send_region((-1, 0, 0))  # slab travelling toward -z
        assert up.offsets == (1, 1, 1) and up.shape == (1, 6, 8)
        dn_halo = lay.halo_region((1, 0, 0))  # ghosts fed by the +z neighbor
        assert dn_halo.offsets == (5, 1, 1) and dn_halo.shape == (1, 6, 8)
        rt = lay.send_region((0, 0, 1))
        assert rt.offsets == (1, 1, 8) and rt.shape == (4, 6, 1)

    def test_validation(self):
        with pytest.raises(ValueError):
            TileLayout3D((4, 4), (1, 1, 1))
        with pytest.raises(ValueError):
            TileLayout3D((2, 2, 2), (3, 1, 1))

    def test_factor3d(self):
        assert factor3d(8) == (2, 2, 2)
        assert factor3d(1) == (1, 1, 1)
        assert np.prod(factor3d(12)) == 12


class TestExchange3D:
    def test_rank_id_golden_on_2x2x2_torus(self, devices):
        """core = rank id, one exchange: every face ghost equals the
        correct neighbor's rank (periodic wrap — the 3D analogue of the
        reference's sample-output check)."""
        mesh = make_mesh((2, 2, 2), ("z", "row", "col"))
        topo = CartTopology((2, 2, 2), (True, True, True))
        lay = TileLayout3D((2, 2, 2), (1, 1, 1))
        spec = HaloSpec3D(layout=lay, topology=topo)

        tiles = np.full((2, 2, 2) + lay.padded_shape, -1.0, np.float32)
        for r in topo.ranks():
            z, y, x = topo.coords(r)
            tiles[z, y, x, 1:-1, 1:-1, 1:-1] = r
        prog = run_spmd(
            mesh,
            lambda t: halo_exchange3d(t[0, 0, 0], spec)[None, None, None],
            P("z", "row", "col", None, None, None),
            P("z", "row", "col", None, None, None),
        )
        out = np.asarray(prog(jnp.asarray(tiles)))
        for r in topo.ranks():
            z, y, x = topo.coords(r)
            tile = out[z, y, x]
            for d in FACES:
                n = topo.neighbor(r, d)
                ghost = spec.layout.halo_region(d).region(tile)
                assert (ghost == n).all(), (r, d, n, ghost)
            # corners were never exchanged (face-only plan): still -1
            assert tile[0, 0, 0] == -1.0

    def test_open_boundary_keeps_ghosts(self, devices):
        mesh = make_mesh((2, 2, 2), ("z", "row", "col"))
        topo = CartTopology((2, 2, 2), (False, False, False))
        lay = TileLayout3D((2, 2, 2), (1, 1, 1))
        spec = HaloSpec3D(layout=lay, topology=topo)
        tiles = decompose3d(
            np.ones((4, 4, 4), np.float32), topo, lay
        )  # ghosts start 0
        prog = run_spmd(
            mesh,
            lambda t: halo_exchange3d(t[0, 0, 0], spec)[None, None, None],
            P("z", "row", "col", None, None, None),
            P("z", "row", "col", None, None, None),
        )
        out = np.asarray(prog(jnp.asarray(tiles)))
        # rank (0,0,0): -z/-y/-x ghosts have no sender -> still zero
        t000 = out[0, 0, 0]
        assert (t000[0, 1:-1, 1:-1] == 0).all()
        assert (t000[1:-1, 0, 1:-1] == 0).all()
        assert (t000[1:-1, 1:-1, 0] == 0).all()
        # +z ghost fed by rank (1,0,0)'s core of ones
        assert (t000[-1, 1:-1, 1:-1] == 1).all()


class TestSeqExchange:
    """Axis-sequential deep exchange: 6 ppermutes fill the FULL ghost
    shell at any depth (edges/corners ride the later axes' slabs)."""

    @pytest.mark.parametrize("halo", [(1, 1, 1), (2, 2, 2), (3, 2, 1)])
    def test_matches_26_neighbor_plan(self, devices, halo):
        from tpuscratch.halo.halo3d import halo_exchange3d_seq

        mesh = make_mesh((2, 2, 2), ("z", "row", "col"))
        topo = CartTopology((2, 2, 2), (True, True, True))
        lay = TileLayout3D((4, 4, 4), halo)
        spec26 = HaloSpec3D(layout=lay, topology=topo, neighbors=26)
        spec6 = HaloSpec3D(layout=lay, topology=topo, neighbors=6)
        rng = np.random.default_rng(0)
        world = rng.standard_normal((8, 8, 8)).astype(np.float32)
        tiles = jnp.asarray(decompose3d(world, topo, lay))
        sp = P("z", "row", "col", None, None, None)
        ref = run_spmd(
            mesh,
            lambda t: halo_exchange3d(t[0, 0, 0], spec26)[None, None, None],
            sp, sp,
        )(tiles)
        seq = run_spmd(
            mesh,
            lambda t: halo_exchange3d_seq(t[0, 0, 0], spec6)[None, None,
                                                             None],
            sp, sp,
        )(tiles)
        assert np.array_equal(np.asarray(ref), np.asarray(seq))

    def test_six_ppermutes_at_any_depth_ledger(self, devices):
        from tpuscratch.halo.halo3d import (
            halo_exchange3d_seq,
            seq_exchange_wire_bytes,
        )
        from tpuscratch.obs import ledger as obs_ledger

        mesh = make_mesh((2, 2, 2), ("z", "row", "col"))
        topo = CartTopology((2, 2, 2), (True, True, True))
        sp = P("z", "row", "col", None, None, None)
        for depth in (1, 3):
            lay = TileLayout3D((4, 4, 4), (depth,) * 3)
            spec = HaloSpec3D(layout=lay, topology=topo, neighbors=6)
            prog = run_spmd(
                mesh,
                lambda t, s=spec: halo_exchange3d_seq(t[0, 0, 0], s)[
                    None, None, None],
                sp, sp,
            )
            led = obs_ledger.analyze(
                prog, jnp.zeros((2, 2, 2) + lay.padded_shape, jnp.float32)
            )
            # the launch-count claim: 6 collectives regardless of depth
            # (the 26-region plan pays 26), bytes exactly the analytic
            # axis-sequential formula
            assert led.count("collective-permute") == 6
            assert (led.wire_bytes()["collective-permute"]
                    == seq_exchange_wire_bytes(spec))

    def test_open_boundary_gets_zero_ghosts(self, devices):
        from tpuscratch.halo.halo3d import halo_exchange3d_seq

        mesh = make_mesh((2, 2, 2), ("z", "row", "col"))
        topo = CartTopology((2, 2, 2), (False, False, False))
        lay = TileLayout3D((4, 4, 4), (2, 2, 2))
        spec = HaloSpec3D(layout=lay, topology=topo, neighbors=6)
        tiles = decompose3d(np.ones((8, 8, 8), np.float32), topo, lay)
        tiles += 7.0  # poison the (zero-initialized) ghost shell
        sp = P("z", "row", "col", None, None, None)
        out = np.asarray(run_spmd(
            mesh,
            lambda t: halo_exchange3d_seq(t[0, 0, 0], spec)[None, None,
                                                            None],
            sp, sp,
        )(jnp.asarray(tiles)))
        t000 = out[0, 0, 0]
        # no sender at the physical -z/-y/-x ends: ppermute ZERO-fills
        # (the solvers' zero-Dirichlet convention, unlike
        # halo_exchange3d's keep-existing MPI_PROC_NULL semantics)
        assert (t000[:2, 2:-2, 2:-2] == 0).all()
        assert (t000[2:-2, :2, 2:-2] == 0).all()
        assert (t000[2:-2, 2:-2, :2] == 0).all()
        # interior face fed by the +z neighbor's (poisoned-core) ones
        assert (t000[-2:, 2:-2, 2:-2] == 8).all()

    def test_one_wide_open_axis_zeroed_too(self, devices):
        """A fully-open 1-wide axis has NO permutation pairs at all —
        its ghost slabs must still be zeroed (same no-sender convention
        as the multi-rank open case), not left stale."""
        import jax

        from tpuscratch.halo.halo3d import halo_exchange3d_seq

        mesh = make_mesh((1, 2, 2), ("z", "row", "col"),
                         jax.devices()[:4])
        topo = CartTopology((1, 2, 2), (False, True, True))
        lay = TileLayout3D((4, 4, 4), (2, 2, 2))
        spec = HaloSpec3D(layout=lay, topology=topo, neighbors=6)
        tiles = decompose3d(np.ones((4, 8, 8), np.float32), topo, lay)
        tiles += 3.0  # poison the ghost shell
        sp = P("z", "row", "col", None, None, None)
        out = np.asarray(run_spmd(
            mesh,
            lambda t: halo_exchange3d_seq(t[0, 0, 0], spec)[None, None,
                                                            None],
            sp, sp,
        )(jnp.asarray(tiles)))
        t0 = out[0, 0, 0]
        assert (t0[:2, 2:-2, 2:-2] == 0).all()   # open z-: zeroed
        assert (t0[-2:, 2:-2, 2:-2] == 0).all()  # open z+: zeroed
        assert (t0[2:-2, :2, 2:-2] == 4).all()   # periodic y: wrapped core


class TestStencil3D:
    @pytest.mark.parametrize("mesh_dims", [(1, 1, 1), (2, 2, 2), (1, 2, 4)])
    def test_jacobi_matches_roll_oracle(self, devices, mesh_dims):
        rng = np.random.default_rng(0)
        world = rng.standard_normal((4, 8, 8)).astype(np.float32)
        steps = 3
        got = distributed_stencil3d(
            world, steps, make_mesh(mesh_dims, ("z", "row", "col"))
        )
        expect = world.astype(np.float64)
        for _ in range(steps):
            expect = (
                np.roll(expect, 1, 0) + np.roll(expect, -1, 0)
                + np.roll(expect, 1, 1) + np.roll(expect, -1, 1)
                + np.roll(expect, 1, 2) + np.roll(expect, -1, 2)
            ) / 6.0
        assert np.allclose(got, expect, atol=1e-5)

    def test_open_boundary_matches_zero_padded_oracle(self, devices):
        rng = np.random.default_rng(1)
        world = rng.standard_normal((4, 4, 8)).astype(np.float32)
        got = distributed_stencil3d(
            world, 2, make_mesh((2, 2, 2), ("z", "row", "col")),
            periodic=False,
        )
        expect = world.astype(np.float64)
        for _ in range(2):
            p = np.pad(expect, 1)
            expect = (
                p[:-2, 1:-1, 1:-1] + p[2:, 1:-1, 1:-1]
                + p[1:-1, :-2, 1:-1] + p[1:-1, 2:, 1:-1]
                + p[1:-1, 1:-1, :-2] + p[1:-1, 1:-1, 2:]
            ) / 6.0
        assert np.allclose(got, expect, atol=1e-5)


class TestCompactImpl:
    @pytest.mark.parametrize("impl", ["compact", "compact-pallas",
                                      "compact-strips", "compact-asm"])
    @pytest.mark.parametrize("periodic", [True, False])
    def test_compact_equals_padded(self, devices, periodic, impl):
        rng = np.random.default_rng(5)
        # 8 deep so the per-tile core (4 planes) satisfies compact-asm's
        # two-band minimum; the other impls are size-indifferent
        world = rng.standard_normal((8, 8, 8)).astype(np.float32)
        mesh = make_mesh((2, 2, 2), ("z", "row", "col"))
        a = distributed_stencil3d(world, 3, mesh, periodic=periodic,
                                  impl=impl)
        b = distributed_stencil3d(world, 3, mesh, periodic=periodic,
                                  impl="padded")
        assert np.allclose(a, b, atol=1e-6)

    def test_assembled_multiband_branches(self, devices):
        # >= 3 bands on a single device so the first / middle / last
        # z-branch of the assembled kernel all execute
        rng = np.random.default_rng(9)
        world = rng.standard_normal((12, 8, 8)).astype(np.float32)
        mesh = make_mesh((1, 1, 1), ("z", "row", "col"))
        from tpuscratch.ops import stencil_kernel as sk

        budget = (2 * 6 + 3 * 4) * 8 * 8 * 4 + 4 * 8 * 8 * 4  # band<=4
        got = distributed_stencil3d(world, 2, mesh, impl="compact-asm")
        expect = world.astype(np.float64)
        for _ in range(2):
            expect = sum(
                np.roll(expect, s, a) for a in range(3) for s in (1, -1)
            ) / 6.0
        assert np.allclose(got, expect, atol=1e-5)
        # and directly at a forced small band (3 bands of 4)
        core = jnp.asarray(world)
        a_mz = core[-1:]
        a_pz = core[:1]
        a_my = core[:, -1:, :]
        a_py = core[:, :1, :]
        a_mx = core[:, :, -1:]
        a_px = core[:, :, :1]
        out = sk.seven_point_assembled_pallas(
            core, a_mz, a_pz, a_my, a_py, a_mx, a_px, world.shape,
            (1 / 6,) * 6 + (0.0,), budget_bytes=budget,
        )
        one = world.astype(np.float64)
        one = sum(
            np.roll(one, s, a) for a in range(3) for s in (1, -1)
        ) / 6.0
        assert np.allclose(np.asarray(out), one, atol=1e-5)

    def test_assembled_rejects_tiny_core(self, devices):
        from tpuscratch.ops.stencil_kernel import seven_point_assembled_pallas

        z = jnp.zeros((2, 4, 4))
        with pytest.raises(ValueError, match="too small"):
            seven_point_assembled_pallas(
                z, z[:1], z[:1], z[:, :1], z[:, :1], z[:, :, :1],
                z[:, :, :1], (2, 4, 4), (1 / 6,) * 6 + (0.0,)
            )

    def test_explicit_compact_rejects_deep_halo(self, devices):
        with pytest.raises(ValueError, match="halo \\(1,1,1\\) only"):
            distributed_stencil3d(
                np.zeros((8, 8, 8), np.float32), 1,
                make_mesh((1, 1, 1), ("z", "row", "col")),
                halo=(2, 2, 2), impl="compact",
            )

    def test_default_auto_selects_padded_for_deep_halo(self, devices):
        rng = np.random.default_rng(6)
        world = rng.standard_normal((8, 8, 8)).astype(np.float32)
        mesh = make_mesh((1, 1, 1), ("z", "row", "col"))
        got = distributed_stencil3d(world, 2, mesh, halo=(2, 2, 2))
        expect = world.astype(np.float64)
        for _ in range(2):
            expect = sum(
                np.roll(expect, s, a) for a in range(3) for s in (1, -1)
            ) / 6.0
        assert np.allclose(got, expect, atol=1e-5)


class TestStreamImpl:
    """The deep-z streamed kernel (ops/stencil_stream.py): k substeps
    fold into one manual-DMA pass; z-slab meshes only."""

    @pytest.mark.parametrize("mesh_dims", [(1, 1, 1), (2, 1, 1), (4, 1, 1)])
    @pytest.mark.parametrize("impl,steps", [
        ("stream:2", 4), ("stream:4", 4), ("stream:3", 7), ("stream:2", 5),
    ])
    def test_stream_equals_compact_periodic(self, devices, mesh_dims,
                                            impl, steps):
        rng = np.random.default_rng(11)
        # 32 deep: the per-rank core at mz=4 still fits depth 4
        # (band >= depth needs cz >= 2 * depth)
        world = rng.standard_normal((32, 8, 8)).astype(np.float32)
        mesh = make_mesh(mesh_dims, ("z", "row", "col"))
        a = distributed_stencil3d(world, steps, mesh, impl=impl)
        b = distributed_stencil3d(world, steps, mesh, impl="compact")
        assert np.allclose(a, b, atol=1e-5)

    @pytest.mark.parametrize("mesh_dims", [(1, 1, 1), (2, 1, 1)])
    def test_stream_open_z_equals_padded(self, devices, mesh_dims):
        # an OPEN z end re-imposes its zero ghosts every folded substep
        rng = np.random.default_rng(12)
        world = rng.standard_normal((16, 8, 8)).astype(np.float32)
        mesh = make_mesh(mesh_dims, ("z", "row", "col"))
        a = distributed_stencil3d(world, 5, mesh, impl="stream:2",
                                  periodic=(False, True, True))
        b = distributed_stencil3d(world, 5, mesh, impl="padded",
                                  periodic=(False, True, True))
        assert np.allclose(a, b, atol=1e-5)

    @pytest.mark.parametrize("carry", [False, True])
    def test_stream_explicit_band_two_bands(self, devices, carry):
        # nb == 2: first and last band are the only bands; both the
        # re-read and the carry-tail read schedules must agree
        from tpuscratch.ops.stencil_stream import seven_point_streamed_pallas

        rng = np.random.default_rng(13)
        core = jnp.asarray(rng.standard_normal((8, 8, 8)).astype(np.float32))
        coeffs = (1 / 6,) * 6 + (0.0,)
        got = seven_point_streamed_pallas(
            core, core[-2:], core[:2], (8, 8, 8), coeffs, 2, band=4,
            carry_tail=carry,
        )
        e = np.asarray(core, np.float64)
        for _ in range(2):
            e = sum(np.roll(e, s, a) for a in range(3) for s in (1, -1)) / 6
        assert np.allclose(np.asarray(got), e, atol=1e-5)

    @pytest.mark.parametrize("mesh_dims", [(1, 1, 1), (2, 1, 1)])
    @pytest.mark.parametrize("periodic", [True, (False, True, True)])
    def test_stream_27_point_equals_compact(self, devices, mesh_dims,
                                            periodic):
        # 27 coefficients ride the SAME streamed kernel: three
        # dz-shifted 9-point ring decompositions per substep; on z-slab
        # meshes the full-extent ghost slabs carry the edge/corner
        # neighbor data implicitly
        rng = np.random.default_rng(17)
        world = rng.standard_normal((16, 8, 16)).astype(np.float32)
        c27 = tuple(np.linspace(0.01, 0.26, 26)) + (0.3,)
        mesh = make_mesh(mesh_dims, ("z", "row", "col"))
        a = distributed_stencil3d(world, 5, mesh, coeffs=c27,
                                  impl="stream:2", periodic=periodic)
        b = distributed_stencil3d(world, 5, mesh, coeffs=c27,
                                  impl="compact", periodic=periodic)
        assert np.allclose(a, b, atol=1e-4)

    def test_stream_rejects_bad_coeff_count(self, devices):
        rng = np.random.default_rng(18)
        world = rng.standard_normal((8, 8, 8)).astype(np.float32)
        mesh = make_mesh((1, 1, 1), ("z", "row", "col"))
        with pytest.raises(ValueError, match="7 or 27"):
            distributed_stencil3d(world, 2, mesh, impl="stream:2",
                                  coeffs=(0.1,) * 9, halo=(1, 1, 1))

    def test_stream_carry_rejects_band_not_over_depth(self, devices):
        from tpuscratch.ops.stencil_stream import seven_point_streamed_pallas

        core = jnp.zeros((8, 8, 8), jnp.float32)
        coeffs = (1 / 6,) * 6 + (0.0,)
        with pytest.raises(ValueError, match="carry_tail"):
            seven_point_streamed_pallas(
                core, jnp.zeros((4, 8, 8)), jnp.zeros((4, 8, 8)),
                (8, 8, 8), coeffs, 4, band=4, carry_tail=True,
            )

    # ---- ghost-strip y/x modes (round 5) ------------------------------

    @pytest.mark.parametrize("mesh_dims", [
        (1, 2, 1), (1, 1, 2), (1, 2, 2), (2, 2, 1), (2, 1, 2), (2, 2, 2),
    ])
    @pytest.mark.parametrize("impl,steps", [("stream:2", 5), ("stream:3", 3)])
    def test_stream_ghost_yx_equals_compact(self, devices, mesh_dims,
                                            impl, steps):
        # distributed y/x axes ride ghost strips aged in-kernel — the
        # 2D ghost-column scheme one dimension up
        rng = np.random.default_rng(14)
        world = rng.standard_normal((16, 16, 16)).astype(np.float32)
        mesh = make_mesh(mesh_dims, ("z", "row", "col"))
        a = distributed_stencil3d(world, steps, mesh, impl=impl)
        b = distributed_stencil3d(world, steps, mesh, impl="compact")
        assert np.allclose(a, b, atol=1e-5)

    @pytest.mark.parametrize("periodic", [
        (True, False, True), (True, True, False), (False, False, False),
    ])
    def test_stream_ghost_yx_open(self, devices, periodic):
        # open y/x faces: ppermute zero-fill supplies the initial zero
        # ghosts, per-substep flag zeroing keeps strip cells zero
        rng = np.random.default_rng(15)
        world = rng.standard_normal((16, 16, 16)).astype(np.float32)
        mesh = make_mesh((2, 2, 2), ("z", "row", "col"))
        a = distributed_stencil3d(world, 5, mesh, impl="stream:2",
                                  periodic=periodic)
        b = distributed_stencil3d(world, 5, mesh, impl="padded",
                                  periodic=periodic)
        assert np.allclose(a, b, atol=1e-5)

    def test_stream_27_rejects_distributed_yx(self, devices):
        rng = np.random.default_rng(16)
        world = rng.standard_normal((8, 8, 8)).astype(np.float32)
        mesh = make_mesh((1, 2, 1), ("z", "row", "col"))
        c27 = tuple(np.linspace(0.01, 0.26, 26)) + (0.3,)
        with pytest.raises(ValueError, match="z-slab"):
            distributed_stencil3d(world, 2, mesh, coeffs=c27,
                                  impl="stream:2")

    def test_stream_rejects_depth_over_band(self, devices):
        from tpuscratch.ops.stencil_stream import seven_point_streamed_pallas

        core = jnp.zeros((8, 8, 8), jnp.float32)
        coeffs = (1 / 6,) * 6 + (0.0,)
        with pytest.raises(ValueError, match="depth"):
            seven_point_streamed_pallas(
                core, jnp.zeros((6, 8, 8)), jnp.zeros((6, 8, 8)),
                (8, 8, 8), coeffs, 6, band=4
            )


class Test26Neighbors:
    def test_rank_id_golden_all_26_regions(self, devices):
        from tpuscratch.halo.halo3d import OFFSETS26

        mesh = make_mesh((2, 2, 2), ("z", "row", "col"))
        topo = CartTopology((2, 2, 2), (True, True, True))
        lay = TileLayout3D((2, 2, 2), (1, 1, 1))
        spec = HaloSpec3D(layout=lay, topology=topo, neighbors=26)
        tiles = np.full((2, 2, 2) + lay.padded_shape, -1.0, np.float32)
        for r in topo.ranks():
            z, y, x = topo.coords(r)
            tiles[z, y, x, 1:-1, 1:-1, 1:-1] = r
        prog = run_spmd(
            mesh,
            lambda t: halo_exchange3d(t[0, 0, 0], spec)[None, None, None],
            P("z", "row", "col", None, None, None),
            P("z", "row", "col", None, None, None),
        )
        out = np.asarray(prog(jnp.asarray(tiles)))
        assert len(OFFSETS26) == 26
        for r in topo.ranks():
            z, y, x = topo.coords(r)
            tile = out[z, y, x]
            for d in OFFSETS26:
                n = topo.neighbor(r, d)
                ghost = spec.layout.halo_region(d).region(tile)
                assert (ghost == n).all(), (r, d, n)
        # nothing left unfilled: the 26 regions + core tile everything
        assert (out != -1.0).all()

    def test_27_point_stencil_matches_roll_oracle(self, devices):
        rng = np.random.default_rng(7)
        world = rng.standard_normal((4, 8, 8)).astype(np.float32)
        from tpuscratch.halo.halo3d import OFFSETS26

        w = np.linspace(0.01, 0.26, 26)
        coeffs = tuple(w) + (0.3,)
        got = distributed_stencil3d(
            world, 2, make_mesh((2, 2, 2), ("z", "row", "col")),
            coeffs=coeffs,
        )
        expect = world.astype(np.float64)
        for _ in range(2):
            new = 0.3 * expect
            for (dz, dy, dx), ww in zip(OFFSETS26, w):
                new = new + ww * np.roll(
                    np.roll(np.roll(expect, -dz, 0), -dy, 1), -dx, 2
                )
            expect = new
        assert np.allclose(got, expect, atol=1e-4)

    def test_27_point_rejects_face_only_spec_and_kernel_computes(self, devices):
        import jax.numpy as jnp

        from tpuscratch.halo.halo3d import stencil_step3d

        topo = CartTopology((1, 1, 1), (True,) * 3)
        spec6 = HaloSpec3D(layout=TileLayout3D((2, 2, 2)), topology=topo)
        c27 = (0.01,) * 26 + (0.0,)
        with pytest.raises(ValueError, match="neighbors=26"):
            stencil_step3d(jnp.zeros((4, 4, 4)), spec6, coeffs=c27)
        # impl='compact' (xla compute) now SERVES 27-point (core-carry
        # with edge/corner arrivals); only the 7-point banded kernels
        # reject it
        with pytest.raises(ValueError, match="compute='xla' only"):
            distributed_stencil3d(
                np.zeros((4, 4, 4), np.float32), 1,
                make_mesh((1, 1, 1), ("z", "row", "col")),
                coeffs=c27, impl="compact-strips",
            )


class TestCompact27:
    """27-point core-carry: the compact path's edge/corner arrivals must
    reproduce the padded 26-neighbor executor exactly."""

    @pytest.mark.parametrize("periodic", [True, False])
    def test_compact27_equals_padded(self, devices, periodic):
        rng = np.random.default_rng(27)
        world = rng.standard_normal((8, 8, 8)).astype(np.float32)
        w = np.linspace(0.01, 0.26, 26)
        coeffs = tuple(w) + (0.3,)
        mesh = make_mesh((2, 2, 2), ("z", "row", "col"))
        a = distributed_stencil3d(world, 3, mesh, coeffs=coeffs,
                                  periodic=periodic, impl="compact")
        b = distributed_stencil3d(world, 3, mesh, coeffs=coeffs,
                                  periodic=periodic, impl="padded")
        assert np.allclose(a, b, atol=1e-5)

    def test_compact27_single_device_roll_oracle(self, devices):
        from tpuscratch.halo.halo3d import OFFSETS26

        rng = np.random.default_rng(28)
        world = rng.standard_normal((6, 8, 8)).astype(np.float32)
        w = np.linspace(0.01, 0.26, 26)
        coeffs = tuple(w) + (0.3,)
        got = distributed_stencil3d(
            world, 2, make_mesh((1, 1, 1), ("z", "row", "col")),
            coeffs=coeffs, impl="compact",
        )
        expect = world.astype(np.float64)
        for _ in range(2):
            new = 0.3 * expect
            for (dz, dy, dx), ww in zip(OFFSETS26, w):
                new = new + ww * np.roll(
                    np.roll(np.roll(expect, -dz, 0), -dy, 1), -dx, 2
                )
            expect = new
        assert np.allclose(got, expect, atol=1e-4)

    def test_compact27_rejects_kernel_computes(self, devices):
        rng = np.random.default_rng(29)
        world = rng.standard_normal((8, 8, 8)).astype(np.float32)
        coeffs = (0.01,) * 26 + (0.3,)
        with pytest.raises(ValueError, match="compute='xla' only"):
            distributed_stencil3d(
                world, 1, make_mesh((1, 1, 1), ("z", "row", "col")),
                coeffs=coeffs, impl="compact-asm",
            )
