"""Observability subsystem: metrics registry, cross-rank aggregation on
1x1 and 2x2 CPU meshes, JSONL sink + report CLI, CompileCounter
promotion (trainer + engine recompile coverage), profiling trace guard."""

import json
import os
import subprocess
import sys
import warnings

import pytest

from tpuscratch.obs import (
    CompileCounter,
    MetricsRegistry,
    merge_snapshots,
    mesh_reduce,
    mesh_span,
    span_max_min,
)
from tpuscratch.obs.sink import NullSink, Sink, open_sink
from tpuscratch.obs import report
from tpuscratch.runtime.mesh import make_mesh


class TestMetricsRegistry:
    def test_counter(self):
        reg = MetricsRegistry()
        reg.counter("ticks").inc()
        reg.counter("ticks").inc(3)
        assert reg.counter("ticks").value == 4
        assert reg.snapshot()["ticks"] == {"kind": "counter", "value": 4}

    def test_gauge_watermarks(self):
        reg = MetricsRegistry()
        g = reg.gauge("free_pages")
        for v in (8, 3, 5):
            g.set(v)
        snap = reg.snapshot()["free_pages"]
        assert snap["value"] == 5
        assert snap["min"] == 3  # the watermark admission control reads
        assert snap["max"] == 8

    def test_histogram(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat")
        for v in (0.1, 0.2, 0.3, 0.4):
            h.observe(v)
        assert h.count == 4
        assert h.mean == pytest.approx(0.25)
        assert h.percentile(50) == pytest.approx(0.2, abs=0.11)
        assert h.percentile(100) == pytest.approx(0.4)

    def test_histogram_window_bounded(self):
        h = MetricsRegistry().histogram("lat")
        h.window = type(h.window)(maxlen=4)
        for i in range(100):
            h.observe(float(i))
        assert h.count == 100          # exact lifetime count survives
        assert len(h.window) == 4      # samples stay bounded
        assert h.percentile(0) == 96.0  # window holds the recent tail

    def test_kind_collision_raises(self):
        reg = MetricsRegistry()
        reg.counter("x")
        with pytest.raises(TypeError):
            reg.gauge("x")

    def test_merge_snapshots(self):
        a, b = MetricsRegistry(), MetricsRegistry()
        a.counter("n").inc(2)
        b.counter("n").inc(5)
        a.gauge("q").set(3)
        b.gauge("q").set(7)
        a.histogram("h").observe(1.0)
        b.histogram("h").observe(3.0)
        m = merge_snapshots([a.snapshot(), b.snapshot()])
        assert m["n"]["value"] == 7
        assert m["q"]["value"] == 7 and m["q"]["min"] == 3
        assert m["h"]["count"] == 2 and m["h"]["mean"] == pytest.approx(2.0)

    def test_merge_kind_mismatch_raises(self):
        a, b = MetricsRegistry(), MetricsRegistry()
        a.counter("x")
        b.gauge("x").set(1)
        with pytest.raises(ValueError):
            merge_snapshots([a.snapshot(), b.snapshot()])


class TestMeshAggregation:
    """The mpicuda3 reduce-to-rank-0 convention through comm.collectives."""

    @pytest.mark.parametrize("dims", [(1, 1), (2, 2)])
    def test_mesh_reduce(self, dims, devices):
        mesh = make_mesh(dims, ("dp", "sp"))
        n = dims[0] * dims[1]
        rows = [[float(i + 1), 10.0 * (i + 1)] for i in range(n)]
        red = mesh_reduce(mesh, rows, ops=("sum", "max", "min"))
        assert red["sum"].tolist() == [
            sum(r[0] for r in rows), sum(r[1] for r in rows)
        ]
        assert red["max"].tolist() == [float(n), 10.0 * n]
        assert red["min"].tolist() == [1.0, 10.0]

    def test_mesh_reduce_scalar_rows(self, devices):
        mesh = make_mesh((2, 2), ("dp", "sp"))
        red = mesh_reduce(mesh, [1.0, 2.0, 3.0, 4.0], ops=("sum",))
        assert float(red["sum"]) == 10.0

    def test_mesh_reduce_wrong_rows(self, devices):
        mesh = make_mesh((2, 2), ("dp", "sp"))
        with pytest.raises(ValueError):
            mesh_reduce(mesh, [[1.0]] * 3)

    @pytest.mark.parametrize("dims", [(1, 1), (2, 2)])
    def test_mesh_span_matches_host_merge(self, dims, devices):
        mesh = make_mesh(dims, ("dp", "sp"))
        n = dims[0] * dims[1]
        # perf_counter-scale stamps: the f32 device path must survive them
        begins = [50000.0 + 0.01 * i for i in range(n)]
        ends = [50000.3 + 0.02 * i for i in range(n)]
        dev = mesh_span(mesh, "step", begins, ends)
        host = mesh_span(mesh, "step", begins, ends, use_device=False)
        assert dev.seconds == pytest.approx(host.seconds, abs=1e-4)
        assert dev.seconds == pytest.approx(span_max_min(begins, ends),
                                            abs=1e-4)
        assert dev.rank_seconds_max == pytest.approx(
            max(e - b for b, e in zip(begins, ends)), abs=1e-4
        )

    def test_span_max_min_is_the_mpicuda3_convention(self):
        # rank 0: [0.0, 1.0], rank 1: [0.5, 1.5] -> 1.5, not max duration
        assert span_max_min([0.0, 0.5], [1.0, 1.5]) == pytest.approx(1.5)
        with pytest.raises(ValueError):
            span_max_min([], [])

    def test_profiling_cross_rank_span_delegates(self):
        """runtime.profiling's merge is now obs.metrics' merge."""
        from tpuscratch.runtime.profiling import Span, Timeline, cross_rank_span

        a, b = Timeline(), Timeline()
        a.spans.append(Span("step", 0.0, 1.0))
        b.spans.append(Span("step", 0.5, 1.5))
        assert cross_rank_span([a, b], "step") == pytest.approx(1.5)


class TestCompileCounterPromotion:
    def test_serve_reexports_obs_class(self):
        from tpuscratch.obs.metrics import CompileCounter as obs_cc
        from tpuscratch.serve import CompileCounter as serve_cc
        from tpuscratch.serve.decode import CompileCounter as decode_cc

        assert serve_cc is obs_cc and decode_cc is obs_cc

    def test_trainer_zero_recompiles_after_warmup(self, devices):
        """N same-shape steps trace exactly once — the serving engine's
        zero-steady-state-recompile contract, now held by the trainer."""
        import jax
        import numpy as np

        from tpuscratch.models.transformer import (
            TransformerConfig,
            init_params,
            train_step,
        )

        mesh = make_mesh((1, 1), ("dp", "sp"))
        cfg = TransformerConfig(d_model=16, n_heads=2, n_experts=2,
                                d_ff=32, n_layers=1)
        counter = CompileCounter()
        fn = train_step(mesh, cfg, counter=counter)
        params = init_params(0, cfg)
        rng = np.random.default_rng(0)
        x = rng.standard_normal((2, 8, 16)).astype(np.float32)
        y = rng.standard_normal((2, 8, 16)).astype(np.float32)
        for _ in range(5):
            params, loss = fn(params, x, y)
        jax.block_until_ready(loss)
        assert counter.count == 1

    def test_grad_norm_output(self, devices):
        """with_grad_norm appends a replicated positive scalar and leaves
        loss and params bit-identical to the plain step."""
        import numpy as np

        from tpuscratch.models.transformer import (
            TransformerConfig,
            init_params,
            train_step,
        )

        mesh = make_mesh((1, 1), ("dp", "sp"))
        cfg = TransformerConfig(d_model=16, n_heads=2, n_experts=2,
                                d_ff=32, n_layers=1)
        params = init_params(0, cfg)
        rng = np.random.default_rng(1)
        x = rng.standard_normal((2, 8, 16)).astype(np.float32)
        y = rng.standard_normal((2, 8, 16)).astype(np.float32)
        p1, loss1 = train_step(mesh, cfg)(params, x, y)
        p2, loss2, gnorm = train_step(mesh, cfg, with_grad_norm=True)(
            params, x, y
        )
        assert float(loss1) == float(loss2)
        assert float(gnorm) > 0.0
        np.testing.assert_array_equal(
            np.asarray(p1["layers"][0]["wq"]),
            np.asarray(p2["layers"][0]["wq"]),
        )


class TestTraceGuard:
    """profiling.trace degrades to a warned no-op span when the jax
    profiler is unavailable — instead of killing the instrumented run."""

    def test_degrades_when_api_absent(self, monkeypatch, tmp_path):
        import jax

        from tpuscratch.runtime import profiling

        monkeypatch.delattr(jax.profiler, "start_trace")
        ran = False
        with pytest.warns(RuntimeWarning, match="no-op span"):
            with profiling.trace(str(tmp_path)):
                ran = True
        assert ran

    def test_degrades_when_start_fails(self, monkeypatch, tmp_path):
        import jax

        from tpuscratch.runtime import profiling

        def boom(*a, **k):
            raise RuntimeError("no profiler backend on this image")

        monkeypatch.setattr(jax.profiler, "start_trace", boom)
        ran = False
        with pytest.warns(RuntimeWarning, match="degraded"):
            with profiling.trace(str(tmp_path)):
                ran = True
        assert ran

    def test_supported_predicate(self):
        import jax

        from tpuscratch.runtime import compat

        assert compat.profiler_trace_supported() == (
            hasattr(jax.profiler, "start_trace")
            and hasattr(jax.profiler, "stop_trace")
        )


class TestSink:
    def test_jsonl_shape(self, tmp_path):
        p = str(tmp_path / "run.jsonl")
        with Sink(p, run={"job": "t"}) as s:
            s.emit("tick", n=1)
            s.emit("tick", n=2, note="x")
        lines = [json.loads(l) for l in open(p) if l.strip()]
        assert [l["event"] for l in lines] == ["run", "tick", "tick"]
        assert lines[0]["job"] == "t"  # run metadata is the first event
        assert lines[2]["note"] == "x"
        assert all("t" in l for l in lines)

    def test_host_suffix(self, tmp_path):
        p = str(tmp_path / "run.jsonl")
        s = Sink(p, host=3)
        s.close()
        assert s.path.endswith("run.h3.jsonl")
        assert os.path.exists(s.path)

    def test_null_sink_and_open_sink(self, tmp_path):
        ns = open_sink(None)
        assert isinstance(ns, NullSink) and not ns.enabled
        ns.emit("anything", x=1)  # no-op, no file
        s = open_sink(str(tmp_path / "a.jsonl"))
        assert isinstance(s, Sink) and s.enabled
        s.close()

    def test_buffered_flush(self, tmp_path):
        p = str(tmp_path / "buf.jsonl")
        s = Sink(p, flush_every=1000)
        s.emit("tick")
        # buffered: nothing past the opening flush yet
        n_before = sum(1 for _ in open(p))
        s.flush()
        n_after = sum(1 for _ in open(p))
        assert n_after >= n_before
        assert sum(1 for _ in open(p)) == 2  # run + tick
        s.close()


@pytest.mark.obs
class TestReport:
    @staticmethod
    def _fixture(tmp_path) -> str:
        """A canned two-host serving run (what a Sink writes)."""
        p = str(tmp_path / "run.jsonl")
        events = [
            {"event": "run", "t": 0.0, "job": "serve", "host": 0},
            {"event": "serve/tick", "t": 0.1, "tick": 1, "tick_s": 0.01,
             "queue_depth": 3, "free_pages_min": 10},
            {"event": "serve/tick", "t": 0.2, "tick": 2, "tick_s": 0.03,
             "queue_depth": 1, "free_pages_min": 6},
            {"event": "metrics", "t": 0.3, "metrics": {
                "serve/tokens": {"kind": "counter", "value": 8},
                "serve/free_pages": {"kind": "gauge", "value": 6,
                                     "min": 6, "max": 12},
            }},
        ]
        with open(p, "w") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")
        return p

    def test_summarize(self, tmp_path):
        p = self._fixture(tmp_path)
        s = report.summarize(report.load_events([p]))
        tick = s["events"]["serve/tick"]
        assert tick["count"] == 2
        assert tick["fields"]["tick_s"]["max"] == pytest.approx(0.03)
        assert tick["fields"]["queue_depth"]["min"] == 1
        assert s["metrics"]["serve/tokens"]["value"] == 8
        assert s["runs"][0]["job"] == "serve"

    def test_event_filter(self, tmp_path):
        p = self._fixture(tmp_path)
        s = report.summarize(report.load_events([p]), only_event="nope")
        assert s["events"] == {}

    def test_multi_host_merge(self, tmp_path):
        p0 = self._fixture(tmp_path)
        p1 = str(tmp_path / "run.h1.jsonl")
        with open(p1, "w") as f:
            f.write(json.dumps({"event": "metrics", "t": 0.5, "metrics": {
                "serve/tokens": {"kind": "counter", "value": 5}}}) + "\n")
        s = report.summarize(report.load_events([p0, p1]))
        assert s["metrics"]["serve/tokens"]["value"] == 13  # summed

    def test_snapshot_scopes(self, tmp_path):
        """Same registry (same scope): newest snapshot supersedes.
        Different registries (scopes) in ONE file — e.g. one engine per
        batch size in a sweep — merge instead of last-wins."""
        p = str(tmp_path / "sweep.jsonl")
        tok = {"kind": "counter"}
        with open(p, "w") as f:
            for scope, val in (("a", 1), ("a", 4), ("b", 10), (None, 100)):
                rec = {"event": "metrics",
                       "metrics": {"tok": dict(tok, value=val)}}
                if scope:
                    rec["scope"] = scope
                f.write(json.dumps(rec) + "\n")
        s = report.summarize(report.load_events([p]))
        # a: 4 supersedes 1 (cumulative), then a+b+unscoped merge
        assert s["metrics"]["tok"]["value"] == 4 + 10 + 100

    def test_engine_sweep_snapshots_all_merge(self, tmp_path):
        """Two engines writing into one sink file: the report's metrics
        cover BOTH (the decode_bench sweep shape)."""
        from tpuscratch.models.transformer import TransformerConfig
        from tpuscratch.serve import Request, ServeConfig, ServeEngine

        mesh = make_mesh((1, 1), ("dp", "sp"))
        cfg = TransformerConfig(d_model=32, n_heads=2, n_experts=2,
                                d_ff=64, n_layers=1)
        scfg = ServeConfig(n_slots=2, n_pages=16, page_size=4, max_seq=16,
                           vocab=16)
        p = str(tmp_path / "sweep.jsonl")
        with Sink(p) as s:
            for base_rid in (0, 10):
                eng = ServeEngine(mesh, cfg, scfg, sink=s)
                eng.run([Request(rid=base_rid, prompt=(1, 2), max_new=3)])
        summ = report.summarize(report.load_events([p]))
        assert summ["metrics"]["serve/tokens"]["value"] == 6  # 3 + 3

    def test_malformed_line_skipped_with_location_warning(self, tmp_path):
        """A corrupt line (torn final line after SIGKILL is the normal
        case) is skipped with a located warning — the rest of the
        artifact still loads."""
        p = str(tmp_path / "bad.jsonl")
        with open(p, "w") as f:
            f.write('{"event": "run"}\nnot json\n{"event": "tick"}\n')
        with pytest.warns(RuntimeWarning, match="bad.jsonl:2"):
            events = report.load_events([p])
        assert [e["event"] for e in events] == ["run", "tick"]

    def test_cli_smoke(self, tmp_path):
        """The tier-1-safe CLI gate: ``python -m tpuscratch.obs.report``
        on a canned fixture must exit 0 and print the table."""
        p = self._fixture(tmp_path)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-m", "tpuscratch.obs.report", p],
            capture_output=True, text=True, env=env, timeout=120,
        )
        assert r.returncode == 0, r.stderr
        assert "serve/tick" in r.stdout
        assert "tick_s" in r.stdout
        assert "serve/tokens" in r.stdout

    def test_cli_json_mode(self, tmp_path):
        p = self._fixture(tmp_path)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-m", "tpuscratch.obs.report", p, "--json"],
            capture_output=True, text=True, env=env, timeout=120,
        )
        assert r.returncode == 0, r.stderr
        parsed = json.loads(r.stdout)
        assert parsed["events"]["serve/tick"]["count"] == 2


class TestEngineObs:
    @staticmethod
    def _engine(sink=None):
        from tpuscratch.models.transformer import TransformerConfig
        from tpuscratch.serve import ServeConfig, ServeEngine

        mesh = make_mesh((1, 1), ("dp", "sp"))
        cfg = TransformerConfig(d_model=32, n_heads=2, n_experts=2,
                                d_ff=64, n_layers=1)
        scfg = ServeConfig(n_slots=2, n_pages=16, page_size=4, max_seq=16,
                           vocab=16)
        return ServeEngine(mesh, cfg, scfg, sink=sink)

    def test_tick_metrics_without_sink(self, devices):
        from tpuscratch.serve import Request

        eng = self._engine()
        eng.run([Request(rid=i, prompt=(1, 2, 3), max_new=4)
                 for i in range(3)])
        snap = eng.metrics.snapshot()
        assert snap["serve/inserts"]["value"] == 3
        assert snap["serve/evictions"]["value"] == 3
        assert snap["serve/tokens"]["value"] == 12
        assert snap["serve/tick_s"]["count"] >= 4
        # watermark: pages were consumed at some point
        assert snap["serve/free_pages"]["min"] < 16
        # zero steady-state recompiles, visible in the registry
        assert snap["serve/decode_compiles"]["value"] == 1
        assert snap["serve/queue_depth"]["value"] == 0  # drained

    def test_tick_events_through_sink(self, devices, tmp_path):
        from tpuscratch.serve import Request

        p = str(tmp_path / "serve.jsonl")
        with Sink(p, run={"job": "t"}) as s:
            eng = self._engine(sink=s)
            eng.run([Request(rid=0, prompt=(1, 2), max_new=3)])
        summ = report.summarize(report.load_events([p]))
        assert summ["events"]["serve/engine"]["count"] == 1
        # prefill emits the first token, each tick one more: 2 ticks
        assert summ["events"]["serve/tick"]["count"] >= 2
        assert summ["events"]["serve/report"]["count"] == 1
        fields = summ["events"]["serve/tick"]["fields"]
        for key in ("tick_s", "queue_depth", "free_pages_min", "inserted",
                    "evicted", "decode_compiles"):
            assert key in fields
        assert summ["metrics"]["serve/tokens"]["value"] == 3


class TestTrainerObs:
    def test_train_emits_chunks_and_zero_recompiles(self, devices, tmp_path):
        from tpuscratch.models.trainer import train
        from tpuscratch.models.transformer import TransformerConfig

        mesh = make_mesh((1, 1), ("dp", "sp"))
        cfg = TransformerConfig(d_model=16, n_heads=2, n_experts=2,
                                d_ff=32, n_layers=1)
        p = str(tmp_path / "train.jsonl")
        with Sink(p, run={"job": "t"}) as s:
            _, rep = train(mesh, cfg, steps=6, save_every=3,
                           ckpt_dir=str(tmp_path / "ck"), obs=s)
        assert rep.steps_run == 6
        summ = report.summarize(report.load_events([p]))
        chunk = summ["events"]["train/chunk"]
        assert chunk["count"] == 2
        for key in ("loss", "grad_norm", "tokens_per_s", "step_s",
                    "compiles"):
            assert key in chunk["fields"], key
        # zero recompiles across the run: one trace, ever
        assert chunk["fields"]["compiles"]["max"] == 1
        assert summ["events"]["train/run"]["count"] == 1
        assert summ["metrics"]["train/steps"]["value"] == 6

    def test_train_without_obs_unchanged(self, devices, tmp_path):
        """No sink -> the step compiles WITHOUT the grad-norm output and
        training still works (the uninstrumented program is preserved)."""
        from tpuscratch.models.trainer import train
        from tpuscratch.models.transformer import TransformerConfig

        mesh = make_mesh((1, 1), ("dp", "sp"))
        cfg = TransformerConfig(d_model=16, n_heads=2, n_experts=2,
                                d_ff=32, n_layers=1)
        _, rep = train(mesh, cfg, steps=2, save_every=2,
                       ckpt_dir=str(tmp_path / "ck"))
        assert rep.steps_run == 2


@pytest.mark.slow
@pytest.mark.obs
class TestObsOverhead:
    def test_per_step_overhead_under_budget(self, devices):
        """Full per-step instrumentation (heavier than the real per-chunk
        trainer hooks) must cost < 2% of train-bench steps/s."""
        from tpuscratch.bench.train_bench import bench_obs_overhead

        o = bench_obs_overhead(steps=60, iters=3)
        assert o.overhead < 0.02, o.summary()
