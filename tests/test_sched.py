"""runtime.chunked + runtime.scheduler: the one chunk loop and the
mesh co-scheduler (ISSUE 16).

The correctness anchors:

- drift guard: trainer, halo driver and solver runner ALL advance
  through ``ChunkedProgram.tick`` — the three legacy loop copies are
  gone and cannot silently come back;
- arbitration: RoundRobin honors its quantum, Priority preempts
  background work at the next chunk boundary (a mid-run burst arrival),
  GoodputShare picks the workload furthest below its target share;
- co-scheduling is invisible to the workloads: a train job and an MG3D
  solve time-slicing one mesh produce results BIT-identical to solo
  runs — including when one workload is chaos-preempted mid-run and
  restarted in place by the scheduler's per-entry budget;
- accounting: ``obs.goodput.by_workload`` splits the shared stream into
  per-workload reports whose buckets sum to per-workload walls and
  whose walls sum to the scheduler wall exactly;
- ``supervise_program`` restarts a chunked program through its
  ``remake`` factory under the supervisor's budget/event discipline.
"""

import numpy as np
import pytest
import jax

from tpuscratch.ft import (
    ChaosPlan,
    Fault,
    Preempted,
    RestartBudget,
    RestartsExhausted,
)
from tpuscratch.ft.supervisor import supervise_program
from tpuscratch.models.trainer import train_program
from tpuscratch.models.transformer import TransformerConfig
from tpuscratch.obs.goodput import by_workload
from tpuscratch.obs.report import load_events
from tpuscratch.obs.sink import Sink
from tpuscratch.runtime.chunked import (
    ChunkResult,
    ChunkedProgram,
    WorkloadSink,
)
from tpuscratch.runtime.errors import CommError
from tpuscratch.runtime.mesh import make_mesh
from tpuscratch.runtime.scheduler import (
    GoodputShare,
    MeshScheduler,
    Priority,
    RoundRobin,
)
from tpuscratch.runtime.scheduler import _Entry
from tpuscratch.solvers.runner import mg3d_solve_program


def _tiny_cfg():
    # compile-light model for the real-workload classes below: what's
    # under test is scheduler semantics, shapes only set the compile bill
    return TransformerConfig(d_model=16, n_heads=2, n_experts=2,
                             d_ff=32, n_layers=1, capacity_factor=2.0)

pytestmark = pytest.mark.sched


class _Events:
    """A list-collecting obs sink (the ``Sink`` duck type)."""

    enabled = True

    def __init__(self):
        self.events = []

    def emit(self, event, **fields):
        self.events.append({"event": event, **fields})

    def emit_metrics(self, snapshot, event="metrics", scope=None):
        pass

    def flush(self):
        pass

    def close(self):
        pass

    def of(self, kind):
        return [e for e in self.events if e["event"] == kind]


def _prog(name, total, trace, *, fail=None, sink=None, state=None,
          tick_s=0.0):
    """A synthetic ChunkedProgram: each tick appends ``(name, pos)`` to
    ``trace``; ``fail`` maps pos -> exception, raised ONCE (consumed —
    the replayed chunk succeeds, like a transient comm fault).  The
    shared ``state`` dict stands in for a checkpoint: ``remake`` resumes
    from the last committed position."""
    state = state if state is not None else {"pos": 0}
    fail = fail if fail is not None else {}

    def build():
        def run_chunk(cp, pos):
            if pos in fail:
                raise fail.pop(pos)
            if tick_s:
                import time

                time.sleep(tick_s)
            trace.append((name, pos))
            return pos

        def make_event(cp, pos, payload, sp):
            state["pos"] = pos + 1
            return ChunkResult(pos=pos + 1, event={"step": pos + 1})

        return ChunkedProgram(
            workload=name, total=total, pos=state["pos"],
            run_chunk=run_chunk, make_event=make_event,
            epilogue=lambda cp: cp.pos, sink=sink, remake=build,
        )

    return build()


def _params_equal(a, b):
    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


class TestChunkedProgram:
    def test_run_ticks_to_completion(self):
        trace = []
        p = _prog("a", 3, trace)
        assert not p.started and not p.done
        assert p.run() == 3
        assert trace == [("a", 0), ("a", 1), ("a", 2)]
        assert p.finished and p.done
        assert p.finish() == 3  # idempotent: returns the cached result

    def test_tick_past_end_raises(self):
        p = _prog("a", 1, [])
        p.run()
        with pytest.raises(RuntimeError, match="past the end"):
            p.tick()

    def test_workload_tagging(self):
        sink = _Events()
        _prog("tagged", 2, [], sink=sink).run()
        chunk = sink.of("tagged/chunk")
        assert len(chunk) == 2
        assert all(e["workload"] == "tagged" for e in chunk)

    def test_workload_sink_laws(self):
        inner = _Events()
        ws = WorkloadSink(WorkloadSink(inner, "a"), "b")
        assert ws.inner is inner  # tags never stack
        ws.emit("x")
        ws.emit("y", workload="explicit")  # an explicit tag wins
        assert inner.events == [
            {"event": "x", "workload": "b"},
            {"event": "y", "workload": "explicit"},
        ]


class TestPolicies:
    def test_round_robin_quantum(self):
        trace = []
        sched = MeshScheduler(policy=RoundRobin(quantum=2))
        sched.add(_prog("a", 3, trace))
        sched.add(_prog("b", 3, trace))
        res = sched.run()
        assert trace == [("a", 0), ("a", 1), ("b", 0), ("b", 1),
                         ("a", 2), ("b", 2)]
        assert res == {"a": 3, "b": 3}

    def test_round_robin_rejects_bad_quantum(self):
        with pytest.raises(ValueError):
            RoundRobin(quantum=0)

    def test_priority_burst_preempts_at_the_boundary(self):
        """A higher-priority job added MID-RUN (the serving-burst case)
        runs to completion at the very next chunk boundary, then the
        background workload resumes."""
        trace = []
        burst_state = {"added": False}

        def arrival(s):
            if s.ticks == 2 and not burst_state["added"]:
                burst_state["added"] = True
                s.add(_prog("burst", 2, trace), priority=10)

        sched = MeshScheduler(policy=Priority(), on_tick=arrival)
        sched.add(_prog("bg", 5, trace), priority=0)
        sched.run()
        assert trace == [("bg", 0), ("bg", 1), ("burst", 0), ("burst", 1),
                         ("bg", 2), ("bg", 3), ("bg", 4)]

    def test_goodput_share_picks_the_furthest_below_target(self):
        a = _Entry("a", None, None, 0, None, None, 0)
        b = _Entry("b", None, None, 0, None, None, 1)
        a.busy_s, b.busy_s = 1.0, 9.0
        pol = GoodputShare({"a": 0.5, "b": 0.5})
        assert pol.pick([a, b], "b", 1) == "a"
        # weights renormalize over the READY set: alone, b is on target
        assert pol.pick([b], "b", 1) == "b"
        # per-entry share is the fallback weight when targets omit it
        c = _Entry("c", None, None, 0, 3.0, None, 2)
        assert GoodputShare()._weight(c) == 3.0


class TestScheduler:
    def test_switch_stream_and_run_summary(self):
        sink = _Events()
        sched = MeshScheduler(policy=RoundRobin(), sink=sink)
        sched.add(_prog("a", 2, [], sink=sink))
        sched.add(_prog("b", 2, [], sink=sink))
        sched.run()
        switches = sink.of("sched/switch")
        assert switches[0]["prev"] is None  # first pick: not a switch
        run = sink.of("sched/run")[-1]
        assert run["switches"] == len(switches) - 1
        assert run["ticks"] == 4 and run["workloads"] == 2
        assert run["overhead_s"] >= 0.0
        assert run["policy"] == "RoundRobin"
        finishes = {e["workload"] for e in sink.of("sched/finish")}
        assert finishes == {"a", "b"}

    def test_duplicate_workload_rejected(self):
        sched = MeshScheduler()
        sched.add(_prog("a", 1, []))
        with pytest.raises(ValueError, match="duplicate"):
            sched.add(_prog("a", 1, []))

    def test_per_entry_restart_resumes_while_others_tick(self):
        """A transient CommError in one workload restarts THAT workload
        from its last committed position; the other keeps ticking."""
        sink = _Events()
        trace = []
        sched = MeshScheduler(policy=RoundRobin(), sink=sink)
        sched.add(_prog("flaky", 3, trace, sink=sink,
                        fail={1: CommError("halo", "injected")}),
                  restarts=RestartBudget(max_restarts=2, backoff_s=0.0))
        sched.add(_prog("steady", 3, trace, sink=sink))
        res = sched.run()
        assert res == {"flaky": 3, "steady": 3}
        assert sched.entries["flaky"].restarts == 1
        # the replay re-ran pos 1 (the consumed fault healed)
        assert trace.count(("flaky", 1)) == 1
        assert trace.count(("steady", 2)) == 1
        restarts = sink.of("ft/restart")
        assert len(restarts) == 1
        assert restarts[0]["workload"] == "flaky"

    def test_restarts_exhausted_aborts_the_rest(self):
        sink = _Events()

        def always_fail(cp, pos):
            raise CommError("halo", "hard down")

        doomed = ChunkedProgram(
            workload="doomed", total=2, run_chunk=always_fail,
            make_event=lambda cp, pos, payload, sp: ChunkResult(pos=pos + 1),
            sink=sink, remake=lambda: doomed_fresh(),
        )

        def doomed_fresh():
            return ChunkedProgram(
                workload="doomed", total=2, run_chunk=always_fail,
                make_event=lambda cp, pos, payload, sp: ChunkResult(
                    pos=pos + 1),
                sink=sink, remake=lambda: doomed_fresh(),
            )

        sched = MeshScheduler(policy=RoundRobin(), sink=sink)
        sched.add(doomed, restarts=RestartBudget(max_restarts=1,
                                                 backoff_s=0.0))
        other = _prog("other", 50, [], sink=sink)
        sched.add(other)
        with pytest.raises(RestartsExhausted):
            sched.run()
        assert len(sink.of("ft/give_up")) == 1
        # the survivor was aborted (its contexts unwound), not left open
        assert not sched.entries["other"].program.started
        run = sink.of("sched/run")[-1]
        assert run.get("error") is True

    def test_no_budget_propagates(self):
        sched = MeshScheduler()
        sched.add(_prog("a", 3, [], fail={0: CommError("halo", "boom")}))
        with pytest.raises(CommError):
            sched.run()


class TestByWorkload:
    def test_partition_of_a_synthetic_stream(self):
        events = [
            {"event": "sched/switch", "t": 0.0, "workload": "a",
             "prev": None, "tick": 0},
            {"event": "train/chunk", "t": 8.0, "workload": "a",
             "step": 2, "chunk": 2, "chunk_s": 6.0, "tokens": 64},
            {"event": "sched/switch", "t": 10.0, "workload": "b",
             "prev": "a", "tick": 1},
            {"event": "solver/chunk", "t": 18.0, "workload": "b",
             "cycle": 2, "chunk": 2, "wall_s": 6.0},
            {"event": "sched/run", "t": 20.0, "wall_s": 20.0,
             "ticks": 2, "switches": 1, "workloads": 2,
             "overhead_s": 0.1, "policy": "RoundRobin"},
        ]
        wg = by_workload(events)
        wg.check()
        assert wg.wall_s == pytest.approx(20.0)
        assert wg.switches == 1
        assert wg.reports["a"].wall_s == pytest.approx(10.0)
        assert wg.reports["b"].wall_s == pytest.approx(10.0)
        assert wg.reports["a"].buckets["step"] == pytest.approx(6.0)
        assert wg.shares["a"] == pytest.approx(0.5)
        assert "workload" in wg.table()[0] or wg.table()  # table renders
        assert "a" in wg.summary() and "b" in wg.summary()

    def test_no_switch_fallback_sums_own_windows(self):
        """A stream with no sched/* events (two solo runs back to back)
        still splits by tag: per-workload own-window accounting, the
        combined wall is their sum."""
        events = [
            {"event": "train/chunk", "t": 5.0, "workload": "a",
             "step": 1, "chunk": 1, "chunk_s": 4.0},
            {"event": "solver/chunk", "t": 11.0, "workload": "b",
             "cycle": 1, "chunk": 1, "wall_s": 5.0},
        ]
        wg = by_workload(events)
        wg.check()
        assert wg.switches == 0
        assert wg.wall_s == pytest.approx(
            wg.reports["a"].wall_s + wg.reports["b"].wall_s)


class TestSuperviseProgram:
    def test_program_form_restarts_via_remake(self):
        sink = _Events()
        trace = []
        p = _prog("w", 3, trace, sink=sink,
                  fail={1: Preempted("w/preempt", 1)})
        out = supervise_program(
            p, budget=RestartBudget(max_restarts=2, backoff_s=0.0),
            sleep=lambda s: None)
        assert out == 3
        assert trace == [("w", 0), ("w", 1), ("w", 2)]  # resumed at 1
        restarts = sink.of("ft/restart")
        assert len(restarts) == 1
        assert restarts[0]["workload"] == "w"  # the program's own sink

    def test_factory_form(self):
        trace = []
        state = {"pos": 0}
        out = supervise_program(
            lambda: _prog("w", 2, trace, state=state),
            budget=RestartBudget(max_restarts=1, backoff_s=0.0),
            sleep=lambda s: None)
        assert out == 2

    def test_program_without_remake_rejected(self):
        p = ChunkedProgram(
            workload="w", total=1,
            run_chunk=lambda cp, pos: None,
            make_event=lambda cp, pos, payload, sp: ChunkResult(pos=pos + 1),
        )
        with pytest.raises(ValueError, match="remake"):
            supervise_program(p)


class TestCoschedBitIdentity:
    """The acceptance anchor: co-scheduled == solo, bit for bit."""

    STEPS, SAVE_EVERY, BATCH, SEQ = 4, 2, 4, 8
    CYCLES, CHUNK = 6, 2

    @pytest.fixture(scope="class")
    def tmesh(self):
        return make_mesh((2, 1), ("dp", "sp"), jax.devices()[:2])

    @pytest.fixture(scope="class")
    def smesh(self):
        return make_mesh((1, 1, 1), ("z", "row", "col"),
                         jax.devices()[:1])

    @pytest.fixture(scope="class")
    def b_world(self):
        rng = np.random.default_rng(5)
        b = rng.standard_normal((16, 16, 16)).astype(np.float32)
        return b - b.mean()

    def _train(self, tmesh, ckpt, **kw):
        return train_program(tmesh, _tiny_cfg(), self.STEPS,
                             str(ckpt), save_every=self.SAVE_EVERY,
                             batch=self.BATCH, seq=self.SEQ,
                             optimizer="adam", **kw)

    def _solve(self, smesh, b_world, ckpt, **kw):
        return mg3d_solve_program(b_world, str(ckpt), mesh=smesh,
                                  tol=1e-10, max_cycles=self.CYCLES,
                                  chunk_cycles=self.CHUNK, **kw)

    @pytest.fixture(scope="class")
    def solo(self, tmp_path_factory, tmesh, smesh, b_world):
        d = tmp_path_factory.mktemp("sched_solo")
        params, rep = self._train(tmesh, d / "t").run()
        x, srep = self._solve(smesh, b_world, d / "s").run()
        return params, rep, x, srep

    def test_cosched_bit_identical_and_partitioned(self, tmp_path, tmesh,
                                                   smesh, b_world, solo):
        p_solo, rep_solo, x_solo, srep_solo = solo
        path = str(tmp_path / "obs.jsonl")
        with Sink(path) as sink:
            sched = MeshScheduler(policy=RoundRobin(), sink=sink)
            sched.add(self._train(tmesh, tmp_path / "t", obs=sink))
            sched.add(self._solve(smesh, b_world, tmp_path / "s",
                                  sink=sink))
            res = sched.run()
        p_co, rep_co = res["train"]
        x_co, srep_co = res["solver"]
        assert _params_equal(p_solo, p_co)
        assert rep_solo.losses == rep_co.losses
        assert np.array_equal(x_solo, x_co)
        assert srep_solo.cycles == srep_co.cycles

        events = load_events([path])
        wg = by_workload(events)
        wg.check()  # buckets sum per workload; walls sum to the wall
        assert set(wg.reports) == {"train", "solver"}
        assert wg.switches >= 1
        assert abs(sum(r.wall_s for r in wg.reports.values())
                   - wg.wall_s) <= 1e-6 * max(1.0, wg.wall_s)
        # every workload-tagged event belongs to a registered workload
        tags = {e["workload"] for e in events if "workload" in e}
        assert tags == {"train", "solver"}

    def test_chaos_preempted_workload_restarts_bit_identical(
            self, tmp_path, tmesh, smesh, b_world, solo):
        """Chaos preempts the TRAIN workload mid-co-schedule (after the
        step-2 save); the scheduler restarts it in place from the
        checkpoint, the solver never notices, and the final results
        still match the fault-free solo runs bit for bit."""
        p_solo, _, x_solo, _ = solo
        path = str(tmp_path / "obs.jsonl")
        plan = ChaosPlan(0, [Fault("train/preempt", at=(2,),
                                   kind="preempt")])
        with Sink(path) as sink:
            sched = MeshScheduler(policy=RoundRobin(), sink=sink)
            sched.add(self._train(tmesh, tmp_path / "t", obs=sink,
                                  chaos=plan),
                      restarts=RestartBudget(max_restarts=2,
                                             backoff_s=0.0))
            sched.add(self._solve(smesh, b_world, tmp_path / "s",
                                  sink=sink))
            res = sched.run()
        p_co, _ = res["train"]
        x_co, _ = res["solver"]
        assert sched.entries["train"].restarts == 1
        assert _params_equal(p_solo, p_co)
        assert np.array_equal(x_solo, x_co)
        events = load_events([path])
        by_workload(events).check()
        restarts = [e for e in events if e.get("event") == "ft/restart"]
        assert len(restarts) == 1 and restarts[0]["workload"] == "train"


class TestDriftGuard:
    def test_all_drivers_route_through_the_one_loop(self, tmp_path,
                                                    monkeypatch):
        """The ISSUE 16 guard: trainer, halo driver and solver runner
        advance ONLY via ChunkedProgram.tick — a re-grown private loop
        in any of them stops showing up here."""
        ticked = {}
        real_tick = ChunkedProgram.tick

        def counting_tick(self):
            ticked[self.workload] = ticked.get(self.workload, 0) + 1
            return real_tick(self)

        monkeypatch.setattr(ChunkedProgram, "tick", counting_tick)

        from tpuscratch.halo import driver
        from tpuscratch.models.trainer import train

        mesh = make_mesh((2, 1), ("dp", "sp"), jax.devices()[:2])
        train(mesh, _tiny_cfg(), 2, str(tmp_path / "t"),
              save_every=2, batch=4, seq=8)

        rng = np.random.default_rng(123)
        world = rng.standard_normal((16, 16)).astype(np.float32)
        from tpuscratch.runtime.mesh import make_mesh_2d

        driver.checkpointed_stencil(world, steps=4,
                                    ckpt_dir=str(tmp_path / "h"),
                                    save_every=2,
                                    mesh=make_mesh_2d((2, 2)))

        b = rng.standard_normal((16, 16, 16)).astype(np.float32)
        b -= b.mean()
        smesh = make_mesh((1, 1, 1), ("z", "row", "col"),
                          jax.devices()[:1])
        mg3d_solve_program(b, str(tmp_path / "s"), mesh=smesh,
                           tol=1e-7, max_cycles=2, chunk_cycles=2).run()

        assert ticked.get("train", 0) >= 1
        assert ticked.get("halo", 0) >= 1
        assert ticked.get("solver", 0) >= 1
