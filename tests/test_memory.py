"""Tests for the memory placement/staging layer (host_allocator parity).

Parity targets: host_allocator.h (page-locked staging memory), the
PAGE_LOCKED/HOST_COPY pingpong ablations
(test-benchmark/mpi-pingpong-gpu-async.cpp:43-49,59-70), and the
capacity-probe spirit of mpicuda2.cu:44-47.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpuscratch.runtime import memory
from tpuscratch.runtime.mesh import make_mesh_1d, shard_along


class TestKinds:
    def test_device_kind_reported(self):
        kinds = memory.memory_kinds()
        assert memory.DEVICE in kinds

    def test_supports_kind(self):
        assert memory.supports_kind(memory.DEVICE)
        assert not memory.supports_kind("no_such_space")


needs_host_spaces = pytest.mark.skipif(
    not (
        memory.supports_kind(memory.PINNED_HOST)
        and memory.supports_kind(memory.UNPINNED_HOST)
    ),
    reason="backend lacks host memory spaces",
)


class TestPlacement:
    @needs_host_spaces
    def test_pin_to_host_and_back(self):
        x = jnp.arange(1024, dtype=jnp.float32)
        pinned = memory.pin_to_host(x)
        assert pinned.sharding.memory_kind == memory.PINNED_HOST
        back = memory.to_device(pinned)
        assert back.sharding.memory_kind == memory.DEVICE
        np.testing.assert_array_equal(np.asarray(back), np.asarray(x))

    @needs_host_spaces
    def test_host_roundtrip_both_ablations(self):
        x = jnp.full((256,), 3.0)
        for pinned in (True, False):
            out = memory.host_roundtrip(x, pinned=pinned)
            assert out.sharding.memory_kind == memory.DEVICE
            np.testing.assert_array_equal(np.asarray(out), np.asarray(x))

    @needs_host_spaces
    def test_sharded_placement_preserves_layout(self):
        mesh = make_mesh_1d("x")
        x = jax.device_put(
            jnp.arange(64, dtype=jnp.float32), shard_along(mesh, "x")
        )
        pinned = memory.pin_to_host(x)
        assert pinned.sharding.memory_kind == memory.PINNED_HOST
        assert pinned.sharding.device_set == x.sharding.device_set
        back = memory.to_device(pinned)
        np.testing.assert_array_equal(np.asarray(back), np.arange(64))

    def test_put_accepts_numpy(self):
        out = memory.put(np.ones((8,), dtype=np.float32))
        assert out.sharding.memory_kind in (None, memory.DEVICE)
        np.testing.assert_array_equal(np.asarray(out), np.ones(8))


class TestDonate:
    def test_donated_step_matches_undonated(self):
        def step(x):
            return x * 2.0 + 1.0

        donated = memory.donate(step)
        x = jnp.arange(16, dtype=jnp.float32)
        expected = np.asarray(step(x))
        got = np.asarray(donated(jnp.arange(16, dtype=jnp.float32)))
        np.testing.assert_array_equal(got, expected)

    def test_donation_invalidates_input(self):
        donated = memory.donate(lambda x: x + 1.0)
        x = jnp.zeros((4096,), dtype=jnp.float32)
        out = donated(x)
        jax.block_until_ready(out)
        # donated buffer must be treated as dead; jax marks it deleted
        assert x.is_deleted()


class TestIntrospection:
    def test_live_bytes_sees_new_array(self):
        before = memory.live_bytes()
        keep = jnp.zeros((1 << 18,), dtype=jnp.float32)  # 1 MiB
        jax.block_until_ready(keep)
        after = memory.live_bytes()
        assert after >= before + keep.nbytes

    def test_memory_stats_reports_bytes(self):
        stats = memory.memory_stats()
        assert "bytes_in_use" in stats
        assert stats["bytes_in_use"] >= 0


class TestPinnedStagingBench:
    @needs_host_spaces
    def test_pinned_staging_roundtrip_runs(self):
        from tpuscratch.bench.pingpong import pinned_staging_roundtrip

        res = pinned_staging_roundtrip(1024, pinned=True, iters=2)
        assert res.p50 > 0
        res2 = pinned_staging_roundtrip(1024, pinned=False, iters=2)
        assert res2.p50 > 0
