"""Test harness: an 8-device virtual CPU mesh on one host.

This is the TPU-native version of the reference's own validation trick —
running N MPI ranks on a single node to exercise multi-node code paths
without a cluster (/root/reference/mpicuda2.cu:31-32, SURVEY.md §4.2).
``force_cpu_devices`` must run before jax initializes backends, hence
module scope here; it also defuses this image's axon TPU plugin, which
otherwise makes every ``jax.devices()`` call dial the real chip.
"""

from tpuscratch.runtime.hostenv import force_cpu_devices

force_cpu_devices(8)

import jax  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
