"""Tests for the Pallas kernels + the distributed dot-product slice.

The reference's dual-backend oracle pattern (SURVEY.md §4.2): the same
kernel code runs interpreted on CPU here and compiled on TPU in benchmarks;
a plain-numpy oracle checks the math (ref_parallel-dot-product-atomics.cu's
CPU `dot` loop, :36-42).
"""

import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpuscratch.bench.timing import (
    BenchResult,
    percentile,
    span_max_min,
    time_device,
)
from tpuscratch.comm import run_spmd
from tpuscratch.ops import dot, fill, iota2d
from tpuscratch.ops.common import to_lanes
from tpuscratch.ops.reduction import local_dot_psum
from tpuscratch.runtime.mesh import make_mesh_1d


class TestToLanes:
    def test_exact(self):
        x = jnp.arange(8 * 128.0)
        assert to_lanes(x).shape == (8, 128)

    def test_padding_neutral(self):
        x = jnp.ones(1000)
        x2 = to_lanes(x)
        assert x2.shape == (8, 128)
        assert float(x2.sum()) == 1000.0


class TestDotKernels:
    @pytest.mark.parametrize("method", ["full", "partials", "xla"])
    def test_oracle_small(self, method):
        rng = np.random.default_rng(0)
        x = rng.standard_normal(4096).astype(np.float32)
        y = rng.standard_normal(4096).astype(np.float32)
        got = float(dot(jnp.asarray(x), jnp.asarray(y), method, block_rows=8))
        np.testing.assert_allclose(got, float(np.dot(x, y)), rtol=1e-4)

    @pytest.mark.parametrize("method", ["full", "partials"])
    def test_ragged_length_padded(self, method):
        # length not a multiple of 128*block: zero padding must be neutral
        x = jnp.ones(3000)
        got = float(dot(x, x, method, block_rows=8))
        assert got == 3000.0

    def test_bf16_accumulates_f32(self):
        # fp32-only atomics limitation does NOT carry over (mpicuda2.cu:52)
        x = jnp.ones(8192, dtype=jnp.bfloat16)
        out = dot(x, x, "full", block_rows=8)
        assert out.dtype == jnp.float32
        assert float(out) == 8192.0

    def test_multiblock_accumulation(self):
        # several grid steps must accumulate, not overwrite
        x = jnp.ones(8 * 128 * 4)
        assert float(dot(x, x, "full", block_rows=8)) == 8 * 128 * 4

    def test_unknown_method(self):
        with pytest.raises(ValueError):
            dot(jnp.ones(8), jnp.ones(8), "atomic")

    def test_shape_mismatch(self):
        with pytest.raises(ValueError):
            dot(jnp.ones(8), jnp.ones(9), "full")


class TestDistributedDot:
    def test_end_to_end_psum(self):
        # mpicuda2-4 parity: shard two vectors over 8 ranks, per-shard
        # kernel reduction, one psum; every rank sees the global dot
        mesh = make_mesh_1d("x")
        n = 8 * 2048
        rng = np.random.default_rng(1)
        x = rng.standard_normal(n).astype(np.float32)
        y = rng.standard_normal(n).astype(np.float32)

        f = run_spmd(
            mesh,
            lambda a, b: local_dot_psum(a, b, "x", method="partials", block_rows=2),
            (P("x"), P("x")),
            P(),
        )
        got = float(f(jnp.asarray(x), jnp.asarray(y)))
        np.testing.assert_allclose(got, float(np.dot(x, y)), rtol=1e-4)


class TestFillKernels:
    def test_fill(self):
        out = fill((8, 128), 2.5)
        assert out.shape == (8, 128)
        assert float(out.sum()) == 2.5 * 8 * 128

    def test_iota2d(self):
        out = np.asarray(iota2d((8, 128)))
        np.testing.assert_array_equal(
            out, np.arange(8 * 128, dtype=np.float32).reshape(8, 128)
        )


class TestTiming:
    def test_percentile_and_span(self):
        assert percentile([3.0, 1.0, 2.0], 50) == 2.0
        # mpicuda3 convention: span covers earliest begin to latest end
        assert span_max_min([1.0, 1.5, 0.9], [2.0, 2.2, 1.8]) == pytest.approx(1.3)
        with pytest.raises(ValueError):
            span_max_min([], [])

    def test_time_device_runs(self):
        x = jnp.ones(1024)
        res = time_device(
            lambda a: dot(a, a, "xla"), x, iters=3, warmup=1,
            name="dot", items=1024,
        )
        assert isinstance(res, BenchResult)
        assert len(res.times_s) == 3
        assert res.items_per_s > 0
        assert "dot" in res.summary()


class TestStencilKernels:
    def _oracle(self, tile, hy, hx):
        out = tile.copy()
        out[hy:-hy, hx:-hx] = 0.25 * (
            tile[hy - 1 : -hy - 1, hx:-hx]
            + tile[hy + 1 : -hy + 1 if hy > 1 else None, hx:-hx][: tile.shape[0] - 2 * hy]
            + tile[hy:-hy, hx - 1 : -hx - 1]
            + tile[hy:-hy, hx + 1 : -hx + 1 if hx > 1 else None][:, : tile.shape[1] - 2 * hx]
        )
        return out

    def test_whole_tile_matches_xla(self):
        from tpuscratch.halo import TileLayout
        from tpuscratch.halo.stencil import five_point
        from tpuscratch.ops import five_point_pallas

        lay = TileLayout(16, 128, 1, 1)
        rng = np.random.default_rng(5)
        tile = jnp.asarray(rng.standard_normal(lay.padded_shape).astype(np.float32))
        np.testing.assert_allclose(
            np.asarray(five_point_pallas(tile, lay)),
            np.asarray(five_point(tile, lay)),
            rtol=1e-6,
        )

    def test_blocked_matches_whole(self):
        from tpuscratch.halo import TileLayout
        from tpuscratch.ops import five_point_blocked, five_point_pallas

        lay = TileLayout(32, 128, 2, 2)
        rng = np.random.default_rng(6)
        tile = jnp.asarray(rng.standard_normal(lay.padded_shape).astype(np.float32))
        np.testing.assert_allclose(
            np.asarray(five_point_blocked(tile, lay, band=8)),
            np.asarray(five_point_pallas(tile, lay)),
            rtol=1e-6,
        )

    def test_zero_halo_rejected(self):
        from tpuscratch.halo import TileLayout
        from tpuscratch.ops import five_point_pallas

        with pytest.raises(ValueError):
            five_point_pallas(jnp.ones((4, 4)), TileLayout(4, 4, 0, 0))

    def test_step_impl_dispatch(self):
        from tpuscratch.halo import HaloSpec, TileLayout
        from tpuscratch.halo.stencil import run_stencil
        from tpuscratch.comm import run_spmd
        from tpuscratch.runtime.mesh import make_mesh_2d
        from tpuscratch.runtime.topology import CartTopology

        mesh = make_mesh_2d((2, 4))
        lay = TileLayout(8, 8, 1, 1)
        spec = HaloSpec(layout=lay, topology=CartTopology((2, 4), (True, True)))
        rng = np.random.default_rng(8)
        tiles = jnp.asarray(
            rng.standard_normal((2, 4) + lay.padded_shape).astype(np.float32)
        )
        outs = {}
        for impl in ("xla", "pallas"):
            # steps=1 deliberately: the single-step program is where the
            # XLA:CPU in-place-update miscompile hid (steps>=2 masked it)
            f = run_spmd(
                mesh,
                lambda x, impl=impl: run_stencil(x[0, 0], spec, 1, impl=impl)[None, None],
                P("row", "col", None, None),
                P("row", "col", None, None),
            )
            outs[impl] = np.asarray(f(tiles))
        np.testing.assert_allclose(outs["xla"], outs["pallas"], rtol=1e-6)
        # and against the global periodic oracle, not just each other
        from tpuscratch.halo.driver import assemble

        topo = CartTopology((2, 4), (True, True))
        world = assemble(np.asarray(tiles), topo, lay)
        expect = 0.25 * (
            np.roll(world, 1, 0) + np.roll(world, -1, 0)
            + np.roll(world, 1, 1) + np.roll(world, -1, 1)
        )
        got = assemble(outs["pallas"], topo, lay)
        np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)

        with pytest.raises(ValueError):
            from tpuscratch.halo.stencil import stencil_step
            stencil_step(tiles[0, 0], spec, impl="cuda")


class TestResidentKernel:
    """resident_periodic_pallas: whole grid in VMEM, roll-based torus wrap."""

    def _oracle(self, world, steps, coeffs=(0.25, 0.25, 0.25, 0.25, 0.0)):
        cn, cs, cw, ce, cc = coeffs
        for _ in range(steps):
            world = (
                cn * np.roll(world, 1, 0) + cs * np.roll(world, -1, 0)
                + cw * np.roll(world, 1, 1) + ce * np.roll(world, -1, 1)
                + cc * world
            )
        return world

    @pytest.mark.parametrize("steps", [0, 1, 5, 6, 8])
    def test_matches_roll_oracle(self, steps):
        # unroll=3 with steps in {0,1,5,6,8} covers: empty loop, pure
        # remainder, rounds+remainder, exact-multiple (6), and 2 rounds
        # + remainder paths
        from tpuscratch.ops.stencil_kernel import resident_periodic_pallas

        rng = np.random.default_rng(40)
        world = rng.standard_normal((16, 128)).astype(np.float32)
        got = resident_periodic_pallas(jnp.asarray(world), steps, unroll=3)
        np.testing.assert_allclose(
            np.asarray(got), self._oracle(world, steps), rtol=1e-5, atol=1e-6
        )

    def test_asymmetric_coeffs(self):
        # exercises the generic (non-factored) kernel body incl. center term
        from tpuscratch.ops.stencil_kernel import resident_periodic_pallas

        coeffs = (0.1, 0.2, 0.3, 0.15, 0.25)
        rng = np.random.default_rng(41)
        world = rng.standard_normal((8, 128)).astype(np.float32)
        got = resident_periodic_pallas(jnp.asarray(world), 4, coeffs=coeffs)
        np.testing.assert_allclose(
            np.asarray(got), self._oracle(world, 4, coeffs), rtol=1e-5, atol=1e-6
        )

    def test_rejects_oversized_grid(self):
        from tpuscratch.ops.stencil_kernel import resident_periodic_pallas

        with pytest.raises(ValueError, match="VMEM"):
            resident_periodic_pallas(
                jnp.zeros((512, 512)), 1, vmem_limit_bytes=1 << 20
            )

    def test_rejects_bad_args(self):
        from tpuscratch.ops.stencil_kernel import resident_periodic_pallas

        with pytest.raises(ValueError, match="2D"):
            resident_periodic_pallas(jnp.zeros((4, 4, 4)), 1)
        with pytest.raises(ValueError, match="unroll"):
            resident_periodic_pallas(jnp.zeros((8, 128)), 1, unroll=0)


class TestFusedAdam:
    """ops/adam.py: the fused single-pass optimizer kernel vs the
    trainer's tree-mapped Adam math (round 5)."""

    def _tree(self, rng, dtype=np.float32):
        return {
            "a": jnp.asarray(rng.standard_normal((64, 1024)), dtype),
            "b": jnp.asarray(rng.standard_normal((3, 130, 7)), dtype),
            "c": jnp.asarray(rng.standard_normal((1000,)), dtype),
        }

    def test_matches_tree_map_oracle(self):
        import jax

        from tpuscratch.models.transformer import _adam_update
        from tpuscratch.ops.adam import fused_adam_tree

        rng = np.random.default_rng(31)
        params = self._tree(rng)
        grads = self._tree(rng)
        mu = self._tree(rng)
        nu = jax.tree.map(jnp.abs, self._tree(rng))
        b1, b2, eps, lr = 0.9, 0.999, 1e-8, 1e-3

        opt = {"mu": mu, "nu": nu, "t": jnp.zeros((), jnp.int32)}
        want_p, want_opt = _adam_update(params, opt, grads, lr, b1, b2,
                                        eps)
        alpha = lr * np.sqrt(1.0 - b2) / (1.0 - b1)  # t = 1
        got_p, got_m, got_v = fused_adam_tree(params, grads, mu, nu,
                                              alpha, b1, b2, eps)
        for kk in params:
            np.testing.assert_allclose(
                np.asarray(got_p[kk]), np.asarray(want_p[kk]),
                rtol=1e-6, atol=1e-7,
            )
            np.testing.assert_allclose(
                np.asarray(got_m[kk]), np.asarray(want_opt["mu"][kk]),
                rtol=1e-6, atol=1e-7,
            )
            np.testing.assert_allclose(
                np.asarray(got_v[kk]), np.asarray(want_opt["nu"][kk]),
                rtol=1e-6, atol=1e-7,
            )

    def test_bf16_moments_roundtrip(self):
        # bf16 moment storage: accumulation stays f32, storage
        # quantizes — values must track the f32 oracle to bf16 precision
        import jax

        from tpuscratch.ops.adam import fused_adam_tree

        rng = np.random.default_rng(32)
        params = self._tree(rng)
        grads = self._tree(rng)
        mu = self._tree(rng, np.float32)
        nu = jax.tree.map(jnp.abs, self._tree(rng))
        mu16 = jax.tree.map(lambda x: x.astype(jnp.bfloat16), mu)
        nu16 = jax.tree.map(lambda x: x.astype(jnp.bfloat16), nu)
        p32, m32, _ = fused_adam_tree(params, grads, mu, nu, 1e-3)
        p16, m16, _ = fused_adam_tree(params, grads, mu16, nu16, 1e-3)
        for kk in params:
            assert m16[kk].dtype == jnp.bfloat16
            np.testing.assert_allclose(
                np.asarray(p16[kk]), np.asarray(p32[kk]),
                rtol=1e-2, atol=1e-2,
            )

    def test_flat_donation_updates_in_place(self):
        """ISSUE 4 satellite: the donating flat path consumes its
        w/m/v inputs (outputs alias their HBM — the optimizer never
        holds two live copies of a moment), keeps the gradient buffer,
        leaves the live-array census flat, and matches the
        non-donating program exactly."""
        import jax

        from tpuscratch.ops.adam import _COLS, fused_adam_flat
        from tpuscratch.runtime import memory

        def fresh():
            rng = np.random.default_rng(33)
            mk = lambda: jnp.asarray(  # noqa: E731
                rng.standard_normal((64, _COLS)), jnp.float32
            )
            return mk(), mk(), mk(), jnp.abs(mk())

        w, g, m, v = fresh()
        jax.block_until_ready((w, g, m, v))
        before = memory.live_bytes()
        w2, m2, v2 = fused_adam_flat(w, g, m, v, 1e-3)
        jax.block_until_ready((w2, m2, v2))
        # donated inputs are consumed; the gradient is not donated
        assert w.is_deleted() and m.is_deleted() and v.is_deleted()
        assert not g.is_deleted()
        # census: 3 outputs replaced 3 inputs in place — no growth
        # beyond the (already-counted) gradient buffer
        after = memory.live_bytes()
        assert after <= before + w2.nbytes // 64, (before, after)

        w3, g3, m3, v3 = fresh()
        ref_w, ref_m, ref_v = fused_adam_flat(w3, g3, m3, v3, 1e-3,
                                              donate=False)
        assert not w3.is_deleted()  # donate=False leaves inputs alone
        np.testing.assert_array_equal(np.asarray(w2), np.asarray(ref_w))
        np.testing.assert_array_equal(np.asarray(m2), np.asarray(ref_m))
        np.testing.assert_array_equal(np.asarray(v2), np.asarray(ref_v))

    def test_tree_donation_matches_and_spares_originals(self):
        """fused_adam_tree(donate=True) donates only the flat STAGING
        copies — the caller's leaf arrays survive — and the numbers are
        identical to the non-donating path."""
        import jax

        from tpuscratch.ops.adam import fused_adam_tree

        rng = np.random.default_rng(34)
        params = self._tree(rng)
        grads = self._tree(rng)
        mu = self._tree(rng)
        nu = jax.tree.map(jnp.abs, self._tree(rng))
        p1, m1, v1 = fused_adam_tree(params, grads, mu, nu, 1e-3)
        p2, m2, v2 = fused_adam_tree(params, grads, mu, nu, 1e-3,
                                     donate=True)
        assert not any(x.is_deleted() for x in jax.tree.leaves(params))
        assert not any(x.is_deleted() for x in jax.tree.leaves(mu))
        for a, b in zip(jax.tree.leaves((p1, m1, v1)),
                        jax.tree.leaves((p2, m2, v2))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
