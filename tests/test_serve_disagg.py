"""tpuscratch.serve.disagg: prefill/decode split with KV-page migration.

The correctness anchors:
- greedy bit-identity: the disaggregated engine (staged prefill ->
  ppermute page migration -> decode-side ``admit_prefilled``) emits
  EXACTLY the monolithic engine's tokens on the 1x1 and 2x2 CPU meshes,
  fp32 and int8 (scale planes ride the same permutation as their
  pages), at temperature too;
- pool hygiene: staging and decode pools both drain back to full, with
  queueing exercised (more requests than decode slots);
- the static wire proof: the compiled migration program's
  collective-permute payload equals the engine's analytic
  ``handoff_wire_bytes`` (the ledger pattern the ZeRO grad-leg and the
  int8 cache rows use);
- fault tolerance (the PR 3 idioms): a transient ``CommError`` at the
  ``serve/handoff`` chaos site is retried through ``ft.retry`` and the
  drain stays byte-identical; a persistent fault DEGRADES the handoff
  to a local monolithic re-prefill — byte-identical again, pools clean.
"""

import dataclasses

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from tpuscratch.ft.chaos import ChaosPlan, Fault
from tpuscratch.models.transformer import TransformerConfig
from tpuscratch.obs.ledger import analyze
from tpuscratch.runtime.mesh import make_mesh
from tpuscratch.serve import (
    DisaggEngine,
    Request,
    ServeConfig,
    ServeEngine,
)

pytestmark = pytest.mark.disagg

D = 32

#: monolithic baselines shared across tests — every chaos/identity test
#: compares against the same reference drain, so it runs ONCE per
#: (dims, workload) instead of once per test (tier-1 time budget)
_BASE_CACHE: dict = {}


def cfg_for(**kw):
    kw.setdefault("capacity_factor", 4.0)
    return TransformerConfig(
        d_model=D, n_heads=4, n_experts=4, d_ff=48, n_layers=2, **kw
    )


def mono_baseline(dims, reqs_key):
    """Cached monolithic drain for the canonical workloads."""
    key = (dims, reqs_key)
    if key not in _BASE_CACHE:
        reqs = _WORKLOADS[reqs_key]()
        _BASE_CACHE[key] = ServeEngine(
            mesh_for(dims), cfg_for(), scfg_for()
        ).run(reqs)
    return _BASE_CACHE[key]


def scfg_for(**kw):
    kw.setdefault("n_slots", 4)
    kw.setdefault("n_pages", 16)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_seq", 24)
    kw.setdefault("vocab", 16)
    return ServeConfig(**kw)


def mesh_for(dims):
    return make_mesh(dims, ("dp", "sp"),
                     jax.devices()[: dims[0] * dims[1]])


def mixed_requests(n=7):
    return [
        Request(rid=i, prompt=tuple(range(1, 2 + i % 5)),
                max_new=1 + (i * 3) % 6)
        for i in range(n)
    ]


def short_requests():
    return [Request(rid=i, prompt=(1 + i, 2), max_new=4) for i in range(5)]


_WORKLOADS = {"mixed": mixed_requests, "short": short_requests}


class TestDisaggBitIdentity:
    @pytest.mark.parametrize("dims", [(1, 1), (2, 2)])
    def test_greedy_matches_monolithic_with_queueing(self, dims):
        cfg, scfg = cfg_for(), scfg_for()
        mesh = mesh_for(dims)
        reqs = mixed_requests()          # > n_slots: handoff queue works
        base = mono_baseline(dims, "mixed")
        d = DisaggEngine(mesh, cfg, scfg)
        rep = d.run(reqs)
        assert rep.outputs == base.outputs
        assert rep.tokens_generated == base.tokens_generated
        assert rep.degraded == 0
        # every multi-token request went through the migration path
        assert rep.handoffs == sum(r.max_new > 1 for r in reqs)
        assert rep.stage_prefills == len(reqs)
        assert rep.stage_prefill_tokens == sum(len(r.prompt) for r in reqs)
        # both pools drain back to full
        assert d.engine.free_pages() == [scfg.n_pages] * dims[0]
        assert d.stage_free_pages() == d.stage_geom.n_pages

    def test_int8_scale_planes_migrate(self):
        # 2x2: the cross-group permutation is what must carry the
        # scale planes (the 1x1 self-pair is covered by the fp32 case)
        cfg = cfg_for()
        scfg = scfg_for(kv_dtype="int8")
        mesh = mesh_for((2, 2))
        reqs = mixed_requests(5)
        base = ServeEngine(mesh, cfg, scfg).run(reqs)
        rep = DisaggEngine(mesh, cfg, scfg).run(reqs)
        assert rep.outputs == base.outputs
        assert rep.degraded == 0

    @pytest.mark.slow
    def test_temperature_stream_identical(self):
        cfg = cfg_for()
        scfg = scfg_for(temperature=0.8, top_k=5, seed=7)
        mesh = mesh_for((1, 1))
        reqs = [Request(rid=i, prompt=(1 + i, 2), max_new=4)
                for i in range(5)]
        base = ServeEngine(mesh, cfg, scfg).run(reqs)
        rep = DisaggEngine(mesh, cfg, scfg).run(reqs)
        assert rep.outputs == base.outputs

    @pytest.mark.slow
    def test_small_stage_pool_backpressures_but_drains(self):
        # a staging pool holding ONE prompt at a time serializes the
        # prefill slice without losing anything
        cfg, scfg = cfg_for(), scfg_for()
        mesh = mesh_for((1, 1))
        reqs = mixed_requests()
        base = mono_baseline((1, 1), "mixed")
        d = DisaggEngine(mesh, cfg, scfg, stage_pages=2)
        rep = d.run(reqs)
        assert rep.outputs == base.outputs
        assert d.stage_free_pages() == 2

    def test_failed_stage_prefill_recovers_without_duplicating(self):
        # a raising staged prefill resets the (donated) staging pool;
        # the request stays queued EXACTLY ONCE (the caller never
        # popped it) and the replay matches the monolithic run
        cfg, scfg = cfg_for(), scfg_for()
        mesh = mesh_for((1, 1))
        reqs = short_requests()
        base = mono_baseline((1, 1), "short")
        d = DisaggEngine(mesh, cfg, scfg)

        class Boom(RuntimeError):
            pass

        def exploding(*a, **k):
            raise Boom("transient stage device error")

        d._stage_prefills = {8: exploding}   # reqs' prompts bucket to 8
        for r in reqs:
            d.submit(r)
        with pytest.raises(Boom):
            d.step()
        assert d.n_queued == len(reqs)       # no duplicate requeue
        assert d.stage_free_pages() == d.stage_geom.n_pages
        d._stage_prefills = {}               # heal: real programs rebuild
        rep = d.run([])
        assert rep.outputs == base.outputs

    def test_validation(self):
        cfg, scfg = cfg_for(), scfg_for()
        mesh = mesh_for((1, 1))
        with pytest.raises(ValueError):
            DisaggEngine(mesh, cfg, scfg, prefill_group=3)
        with pytest.raises(ValueError):
            DisaggEngine(mesh, cfg, dataclasses.replace(
                scfg, prefix_share=True))
        with pytest.raises(ValueError):
            DisaggEngine(mesh, cfg, dataclasses.replace(
                scfg, chunk_prefill=2))
        d = DisaggEngine(mesh, cfg, scfg)
        d.submit(Request(rid=0, prompt=(1,), max_new=2))
        with pytest.raises(ValueError):
            d.submit(Request(rid=0, prompt=(2,), max_new=2))
        with pytest.raises(ValueError):
            d.submit(Request(rid=1, prompt=(99,), max_new=2))


class TestMigrationLedger:
    def test_collective_permute_payload_matches_analytic(self):
        # the static half of the handoff claim: the compiled migration
        # program ships exactly the analytic per-device payload — one
        # ppermute per cache leaf (int8: pages AND scale planes), each
        # carrying the footprint-ceiling page table
        cfg = cfg_for()
        mesh = mesh_for((2, 2))
        for kv_dtype, n_leaves in (("float32", 2), ("int8", 4)):
            scfg = scfg_for(kv_dtype=kv_dtype)
            d = DisaggEngine(mesh, cfg, scfg)
            prog = d._migrate_program(1)
            rows = jnp.zeros((2, scfg.max_pages), jnp.int32)
            led = analyze(prog, d.engine._kv, d._stage_kv, rows, rows)
            counts = led.counts()
            assert counts.get("collective-permute") == n_leaves
            payload = led.payload_bytes()["collective-permute"]
            assert payload == d.handoff_wire_bytes

    def test_migrated_pages_hold_identical_bytes(self):
        # migration is a byte copy: the decode pool's migrated pages
        # equal the staging pool's source pages exactly
        cfg, scfg = cfg_for(), scfg_for()
        mesh = mesh_for((1, 1))
        d = DisaggEngine(mesh, cfg, scfg)
        req = Request(rid=0, prompt=(1, 2, 3, 4, 5, 6), max_new=2)
        d.submit(req)
        staged = d._stage_prefill(d._queue[0])
        stage_k = np.asarray(d._stage_kv["k"])
        assert d._try_handoff(staged)
        st = d.engine._slots[0]
        assert st is not None and st.rid == 0
        serve_k = np.asarray(d.engine._kv["k"])
        n_pg = d.stage_geom.pages_for(len(req.prompt))
        for src, dst in zip(staged.pages[:n_pg], st.pages[:n_pg]):
            np.testing.assert_array_equal(serve_k[:, dst], stage_k[:, src])
        d._queue.popleft()
        d.run([])


class TestHandoffChaos:
    def test_transient_commerror_retried_byte_identical(self):
        cfg, scfg = cfg_for(), scfg_for()
        mesh = mesh_for((2, 2))
        reqs = short_requests()
        base = mono_baseline((2, 2), "short")
        plan = ChaosPlan(0, [Fault(site="serve/handoff", at=(0,),
                                   times=2)])
        d = DisaggEngine(mesh, cfg, scfg, chaos=plan)
        rep = d.run(reqs)
        assert rep.outputs == base.outputs
        assert rep.handoff_retries >= 1
        assert rep.degraded == 0
        assert plan.stats().get("serve/handoff") == 2

    def test_persistent_fault_degrades_to_local_prefill(self):
        # a never-healing migration fault for ONE rid: its handoff
        # exhausts the retry budget and falls back to the decode
        # engine's own monolithic prefill — byte-identical output,
        # clean pools, everyone else unaffected
        cfg, scfg = cfg_for(), scfg_for()
        mesh = mesh_for((2, 2))
        reqs = short_requests()
        base = mono_baseline((2, 2), "short")
        plan = ChaosPlan(0, [Fault(site="serve/handoff", key=2, p=1.0,
                                   times=None)])
        d = DisaggEngine(mesh, cfg, scfg, chaos=plan)
        rep = d.run(reqs)
        assert rep.outputs == base.outputs
        assert rep.degraded == 1
        assert rep.handoffs == 4          # the other four migrated
        assert d.engine.free_pages() == [scfg.n_pages] * 2
        assert d.stage_free_pages() == d.stage_geom.n_pages

    @pytest.mark.slow
    def test_all_handoffs_down_still_serves(self):
        # total migration outage: EVERY request degrades — the system
        # gracefully collapses into the monolithic engine
        cfg, scfg = cfg_for(), scfg_for()
        mesh = mesh_for((1, 1))
        reqs = short_requests()
        base = mono_baseline((1, 1), "short")
        plan = ChaosPlan(0, [Fault(site="serve/handoff", p=1.0,
                                   times=None)])
        d = DisaggEngine(mesh, cfg, scfg, chaos=plan)
        rep = d.run(reqs)
        assert rep.outputs == base.outputs
        assert rep.handoffs == 0 and rep.degraded == len(reqs)
        assert d.engine.free_pages() == [scfg.n_pages]
