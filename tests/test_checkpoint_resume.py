"""Kill/resume: the point of having a checkpoint layer.

A checkpointed stencil run is hard-killed mid-flight (os._exit right
after a save — a deterministic scheduler-preemption stand-in), re-invoked
with the same arguments, and must resume from ``latest_step`` and finish
with a BIT-IDENTICAL result to an uninterrupted run. SURVEY.md §5 records
checkpoint/resume as absent from the reference (walltime kills just lose
the work, mpi_pbs_sample.sh:5-6); this is the capability that closes it.
"""

import os
import pathlib
import subprocess
import sys

import numpy as np

WORKER = pathlib.Path(__file__).parent / "_ckpt_worker.py"
REPO = pathlib.Path(__file__).parent.parent


def _run_worker(ckpt_dir, steps, save_every, die_after=0, timeout=180):
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [str(REPO)] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else [])
    )
    if die_after:
        env["TPUSCRATCH_DIE_AFTER_SAVES"] = str(die_after)
    else:
        env.pop("TPUSCRATCH_DIE_AFTER_SAVES", None)
    p = subprocess.run(
        [sys.executable, str(WORKER), str(ckpt_dir), str(steps), str(save_every)],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=str(REPO),
    )
    return p


def test_kill_resume_bitmatches_uninterrupted(tmp_path):
    from tpuscratch.runtime import checkpoint

    steps, save_every = 10, 2

    # 1. the oracle: one uninterrupted run
    clean_dir = tmp_path / "clean"
    p = _run_worker(clean_dir, steps, save_every)
    assert p.returncode == 0, p.stdout + p.stderr
    assert f"WORKER done at step {steps}" in p.stdout
    clean = np.load(clean_dir / "result.npy")

    # 2. a run preempted after its 2nd save (step 4 of 10)
    kill_dir = tmp_path / "killed"
    p = _run_worker(kill_dir, steps, save_every, die_after=2)
    assert p.returncode == 17, p.stdout + p.stderr  # died as instructed
    assert checkpoint.latest_step(kill_dir) == 4
    assert not (kill_dir / "result.npy").exists()

    # 3. same invocation again: resumes at 4, completes, bit-matches
    p = _run_worker(kill_dir, steps, save_every)
    assert p.returncode == 0, p.stdout + p.stderr
    assert f"WORKER done at step {steps}" in p.stdout
    resumed = np.load(kill_dir / "result.npy")
    np.testing.assert_array_equal(resumed, clean)  # BIT-identical

    # prune kept the tail only
    assert checkpoint.latest_step(kill_dir) == steps


def test_restore_past_target_is_noop(tmp_path):
    # resuming a run whose checkpoint already covers the request returns
    # immediately from the restored state
    from tpuscratch.halo import driver
    from tpuscratch.runtime.mesh import make_mesh_2d

    rng = np.random.default_rng(5)
    world = rng.standard_normal((8, 8)).astype(np.float32)
    mesh = make_mesh_2d((2, 2))
    d = tmp_path / "ck"
    full = driver.checkpointed_stencil(world, 6, d, save_every=3, mesh=mesh)
    again = driver.checkpointed_stencil(world, 6, d, save_every=3, mesh=mesh)
    np.testing.assert_array_equal(full, again)
