"""Kill/resume: the point of having a checkpoint layer.

A checkpointed stencil run is hard-killed mid-flight (os._exit right
after a save — a deterministic scheduler-preemption stand-in), re-invoked
with the same arguments, and must resume from ``latest_step`` and finish
with a BIT-IDENTICAL result to an uninterrupted run. SURVEY.md §5 records
checkpoint/resume as absent from the reference (walltime kills just lose
the work, mpi_pbs_sample.sh:5-6); this is the capability that closes it.
"""

import os
import pathlib
import subprocess
import sys

import numpy as np
import pytest

WORKER = pathlib.Path(__file__).parent / "_ckpt_worker.py"
REPO = pathlib.Path(__file__).parent.parent

# Every worker below compiles the SAME tiny stencil program from
# scratch; a shared XLA compile cache collapses that to one compile per
# suite run.  Scoped to the worker subprocesses only (never the pytest
# process): executable deserialization is exercised by exactly this
# program, and a bad cache entry can fail only a worker, not the run.
XLA_CACHE = "/tmp/tpuscratch-ckpt-worker-xla-cache"


def _run_worker(ckpt_dir, steps, save_every, die_after=0, chaos_kill="",
                async_ckpt=False, timeout=180):
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [str(REPO)] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else [])
    )
    env["JAX_COMPILATION_CACHE_DIR"] = XLA_CACHE
    env["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "0"
    env["JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES"] = "0"
    if die_after:
        env["TPUSCRATCH_DIE_AFTER_SAVES"] = str(die_after)
    else:
        env.pop("TPUSCRATCH_DIE_AFTER_SAVES", None)
    if chaos_kill:
        env["TPUSCRATCH_CHAOS_KILL"] = chaos_kill
    else:
        env.pop("TPUSCRATCH_CHAOS_KILL", None)
    if async_ckpt:
        env["TPUSCRATCH_ASYNC_CKPT"] = "1"
    else:
        env.pop("TPUSCRATCH_ASYNC_CKPT", None)
    p = subprocess.run(
        [sys.executable, str(WORKER), str(ckpt_dir), str(steps), str(save_every)],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=str(REPO),
    )
    return p


STEPS, SAVE_EVERY = 10, 2


@pytest.fixture(scope="module")
def clean_result(tmp_path_factory):
    """One uninterrupted worker run — the shared oracle for every
    kill/resume test in this module (subprocesses are the expensive
    part of these tests)."""
    clean_dir = tmp_path_factory.mktemp("clean")
    p = _run_worker(clean_dir, STEPS, SAVE_EVERY)
    assert p.returncode == 0, p.stdout + p.stderr
    assert f"WORKER done at step {STEPS}" in p.stdout
    return np.load(clean_dir / "result.npy")


def test_kill_resume_bitmatches_uninterrupted(tmp_path, clean_result):
    from tpuscratch.runtime import checkpoint

    steps, save_every = STEPS, SAVE_EVERY
    clean = clean_result

    # 2. a run preempted after its 2nd save (step 4 of 10)
    kill_dir = tmp_path / "killed"
    p = _run_worker(kill_dir, steps, save_every, die_after=2)
    assert p.returncode == 17, p.stdout + p.stderr  # died as instructed
    assert checkpoint.latest_step(kill_dir) == 4
    assert not (kill_dir / "result.npy").exists()

    # 3. same invocation again: resumes at 4, completes, bit-matches
    p = _run_worker(kill_dir, steps, save_every)
    assert p.returncode == 0, p.stdout + p.stderr
    assert f"WORKER done at step {steps}" in p.stdout
    resumed = np.load(kill_dir / "result.npy")
    np.testing.assert_array_equal(resumed, clean)  # BIT-identical

    # prune kept the tail only
    assert checkpoint.latest_step(kill_dir) == steps


@pytest.mark.chaos
def test_sigkill_inside_save_always_leaves_valid_step(tmp_path,
                                                      clean_result):
    """The kill-mid-save matrix: SIGKILL the worker AT internal stages of
    ``checkpoint.save`` (via the ft chaos hook) across different save
    occurrences; resume must always find a valid step and finish with
    params bit-identical to an uninterrupted run.  The matrix here keeps
    the endpoints (nothing-on-disk-yet, just-published) in tier-1; the
    interior stages are covered subprocess-free by the hook-crash test
    below."""
    from tpuscratch.runtime import checkpoint

    steps, save_every = STEPS, SAVE_EVERY
    clean = clean_result

    # stage x save-occurrence points: before any leaf hits disk and
    # right after the atomic publish
    for stage, save_idx in [("begin", 0), ("publish", 3)]:
        kill_dir = tmp_path / f"kill_{stage}_{save_idx}"
        p = _run_worker(kill_dir, steps, save_every,
                        chaos_kill=f"{stage}:{save_idx}")
        assert p.returncode == -9, (stage, p.returncode, p.stdout + p.stderr)
        latest = checkpoint.latest_step(kill_dir)
        # a save killed before its publish leaves the PREVIOUS step (none
        # for the very first); killed after publish leaves its own
        expected = save_idx * save_every if stage != "publish" \
            else (save_idx + 1) * save_every
        assert latest == (expected or None), (stage, latest)
        if latest is not None:
            # the surviving step must be fully loadable, not torn
            tiles, s, _ = checkpoint.restore(
                kill_dir, np.zeros((2, 2, 10, 10), np.float32)
            )
            assert s == latest
        p = _run_worker(kill_dir, steps, save_every)
        assert p.returncode == 0, (stage, p.stdout + p.stderr)
        np.testing.assert_array_equal(
            np.load(kill_dir / "result.npy"), clean
        )


@pytest.mark.chaos
@pytest.mark.elastic
def test_sigkill_during_background_write_resumes_bit_identical(
        tmp_path, clean_result):
    """The async half of the kill-mid-save matrix: the worker runs with
    snapshot-then-publish checkpointing and is SIGKILLed AT named stages
    INSIDE the BACKGROUND writer's ``checkpoint.save`` (the ``ckpt/write``
    chaos site) across write occurrences.  Because writes are serialized
    behind the snapshot barrier, a kill at write k's pre-publish stages
    leaves exactly writes 0..k-1 published (its own step after
    ``publish``); resume must always find a valid step and the re-invoked
    async run must finish bit-identical to the uninterrupted blocking
    oracle — published checkpoints are byte-identical across paths, so
    one oracle serves both."""
    from tpuscratch.runtime import checkpoint

    steps, save_every = STEPS, SAVE_EVERY
    clean = clean_result

    for stage, write_idx in [("begin", 1), ("manifest", 2), ("publish", 3)]:
        kill_dir = tmp_path / f"wkill_{stage}_{write_idx}"
        p = _run_worker(kill_dir, steps, save_every,
                        chaos_kill=f"write:{stage}:{write_idx}",
                        async_ckpt=True)
        assert p.returncode == -9, (stage, p.returncode,
                                    p.stdout + p.stderr)
        latest = checkpoint.latest_step(kill_dir)
        expected = write_idx * save_every if stage != "publish" \
            else (write_idx + 1) * save_every
        assert latest == (expected or None), (stage, latest)
        if latest is not None:
            # the surviving step must be fully loadable, not torn
            tiles, s, _ = checkpoint.restore(
                kill_dir, np.zeros((2, 2, 10, 10), np.float32)
            )
            assert s == latest
        p = _run_worker(kill_dir, steps, save_every, async_ckpt=True)
        assert p.returncode == 0, (stage, p.stdout + p.stderr)
        np.testing.assert_array_equal(
            np.load(kill_dir / "result.npy"), clean
        )


@pytest.mark.elastic
def test_async_run_matches_blocking_and_checkpoints_byte_identical(
        tmp_path, clean_result):
    """Async on, no faults: the worker's result bit-matches the blocking
    oracle and the final published checkpoint directory is BYTE-identical
    to a blocking save of the same state (same leaf files, same
    manifest payload modulo nothing — the writer goes through the one
    ``checkpoint.save``)."""
    from tpuscratch.runtime import checkpoint

    d = tmp_path / "async"
    p = _run_worker(d, STEPS, SAVE_EVERY, async_ckpt=True)
    assert p.returncode == 0, p.stdout + p.stderr
    np.testing.assert_array_equal(np.load(d / "result.npy"), clean_result)

    # re-save the restored final step through the BLOCKING path and
    # compare the published bytes file-for-file
    step = checkpoint.latest_step(d)
    tiles, s, meta = checkpoint.restore(
        d, np.zeros((2, 2, 10, 10), np.float32)
    )
    blocking = tmp_path / "blocking_ref"
    checkpoint.save(blocking, s, tiles, metadata=meta)
    a_dir = pathlib.Path(d) / f"step_{s:09d}"
    b_dir = blocking / f"step_{s:09d}"
    for f in sorted(p.name for p in b_dir.iterdir()):
        assert (a_dir / f).read_bytes() == (b_dir / f).read_bytes(), f


def test_save_hook_crash_at_any_stage_keeps_published_step(tmp_path):
    """In-process half of the crash-window fix: a hook that raises at ANY
    stage of an overwriting save leaves the already-published step intact
    and restorable (the aside-publish-delete sequence + _gc recovery)."""
    from tpuscratch.runtime import checkpoint

    d = tmp_path / "ck"
    tree = {"a": np.arange(6, dtype=np.float32).reshape(2, 3),
            "b": np.float32(2.0)}
    checkpoint.save(d, 1, tree)
    checkpoint.save(d, 2, tree)
    for stage in ["begin", "leaf_0", "leaf_1", "manifest", "swap",
                  "publish", "end"]:
        def hook(s, stage=stage):
            if s == stage:
                raise OSError(f"injected crash at {s}")

        with pytest.raises(OSError):
            checkpoint.save(d, 2, tree, hook=hook)
        assert checkpoint.steps(d) == [1, 2], stage
        got, s, _ = checkpoint.restore(d, tree, step=2)
        np.testing.assert_array_equal(got["a"], tree["a"])


def test_reads_see_stranded_aside_and_writer_collects_orphans(tmp_path):
    """A crash between aside-rename and publish strands the published
    step under ``.old_step_*``.  The READ path recognizes it as that
    step without renaming or deleting anything (so a concurrent reader
    can never race an in-flight save); the next save() renames it back
    and collects orphaned ``.tmp_step_*`` write temps."""
    from tpuscratch.runtime import checkpoint

    d = tmp_path / "ck"
    tree = {"a": np.ones((3,), np.float32)}
    checkpoint.save(d, 1, tree)
    checkpoint.save(d, 2, tree)
    (d / "step_000000002").rename(d / ".old_step_2_999")
    (d / ".tmp_step_2_zzz").mkdir()
    assert checkpoint.steps(d) == [1, 2]          # aside recognized
    assert (d / ".old_step_2_999").exists()       # ...but NOT renamed
    assert (d / ".tmp_step_2_zzz").exists()       # reads delete nothing
    got, s, _ = checkpoint.restore(d, tree)       # latest == stranded 2
    assert s == 2
    np.testing.assert_array_equal(got["a"], tree["a"])
    checkpoint.save(d, 3, tree)                   # the writer's _gc runs
    assert (d / "step_000000002").exists()        # aside renamed back
    assert not (d / ".old_step_2_999").exists()
    assert not (d / ".tmp_step_2_zzz").exists()
    assert checkpoint.steps(d) == [1, 2, 3]


def test_restore_rejects_torn_and_drifted_leaves(tmp_path):
    """Per-leaf validation: a truncated .npy fails the manifest
    byte-size check BEFORE the load; a shape/dtype drift against the
    example tree fails loudly instead of mis-loading silently."""
    from tpuscratch.runtime import checkpoint

    d = tmp_path / "ck"
    tree = {"a": np.arange(6, dtype=np.float32).reshape(2, 3),
            "b": np.zeros((), np.int32)}
    checkpoint.save(d, 1, tree)

    leaf = d / "step_000000001" / "leaf_0.npy"
    data = leaf.read_bytes()
    leaf.write_bytes(data[:-4])                   # torn write
    with pytest.raises(ValueError, match="torn or corrupted"):
        checkpoint.restore(d, tree, step=1)
    leaf.write_bytes(data)                        # repaired
    checkpoint.restore(d, tree, step=1)

    with pytest.raises(ValueError, match="structure drifted"):
        checkpoint.restore(
            d, {"a": np.zeros((3, 2), np.float32),
                "b": np.zeros((), np.int32)}, step=1)
    with pytest.raises(ValueError, match="structure drifted"):
        checkpoint.restore(
            d, {"a": np.zeros((2, 3), np.float64),
                "b": np.zeros((), np.int32)}, step=1)


def test_restore_past_target_is_noop(tmp_path):
    # resuming a run whose checkpoint already covers the request returns
    # immediately from the restored state
    from tpuscratch.halo import driver
    from tpuscratch.runtime.mesh import make_mesh_2d

    rng = np.random.default_rng(5)
    world = rng.standard_normal((8, 8)).astype(np.float32)
    mesh = make_mesh_2d((2, 2))
    d = tmp_path / "ck"
    full = driver.checkpointed_stencil(world, 6, d, save_every=3, mesh=mesh)
    again = driver.checkpointed_stencil(world, 6, d, save_every=3, mesh=mesh)
    np.testing.assert_array_equal(full, again)
