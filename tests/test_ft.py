"""tpuscratch.ft: chaos determinism, guarded training, retry, supervisor.

The correctness anchors (ISSUE 3 acceptance):

- chaos determinism: the same ``ChaosPlan(seed)`` produces the same
  fault schedule; a trainer run that suffers an injected NaN step + an
  injected preemption finishes under ``supervise`` with final params
  bit-identical to the same run's replay — and (rollback heals a
  consumed one-shot fault) to the fault-free run;
- serve: transient prefill faults are retried and complete; a
  deterministically-failing request is quarantined after its budget
  while every other request's outputs are byte-identical to a
  fault-free run — no livelock;
- uninstrumented paths unchanged: no chaos + no guard means the
  compiled train step contains no guard ops and both trainer and engine
  stay at one compile (CompileCounter-gated).
"""

import json
import math

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from tpuscratch.ft import (
    ChaosPlan,
    Fault,
    GuardFailure,
    GuardPolicy,
    InjectedFault,
    Preempted,
    RestartBudget,
    RestartsExhausted,
    RetryPolicy,
    WatchdogTimeout,
    retry,
    supervise,
    supervise_train,
)
from tpuscratch.ft.guards import STATUS_CLIPPED, STATUS_OK, STATUS_SKIPPED
from tpuscratch.models.transformer import (
    TransformerConfig,
    init_params,
    train_step,
)
from tpuscratch.models.trainer import train
from tpuscratch.runtime.errors import CommError
from tpuscratch.runtime.mesh import make_mesh
from tpuscratch.serve import Request, ServeConfig, ServeEngine


def _params_equal(a, b) -> bool:
    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


class TestChaosPlan:
    def test_same_seed_same_schedule(self):
        def schedule(seed):
            p = ChaosPlan(seed, [Fault("a/b", p=0.3, times=None)])
            return [i for i in range(200) if p.should_fire("a/b", index=i)]

        s0, s0b, s1 = schedule(7), schedule(7), schedule(8)
        assert s0 == s0b and s0
        assert s0 != s1
        assert 20 < len(s0) < 100  # ~rate 0.3

    def test_times_budget_consumed_across_replays(self):
        # the rollback-replay property: a times-bounded fault at a fixed
        # index stops firing once consumed, so the replay runs clean
        p = ChaosPlan(0, [Fault("a/b", at=(5,), times=1)])
        assert p.should_fire("a/b", index=5) is not None
        assert p.should_fire("a/b", index=5) is None
        assert p.stats() == {"a/b": 1}

    def test_key_and_stage_selectors(self):
        p = ChaosPlan(0, [Fault("s", key=3, p=1.0, at=None, times=None),
                          Fault("ckpt/save", stage="publish", at=(0,))])
        assert p.should_fire("s", index=0, key=2) is None
        assert p.should_fire("s", index=0, key=3) is not None
        assert p.should_fire("ckpt/save", stage="manifest") is None
        # stage occurrences count independently: this is publish's 0th
        assert p.should_fire("ckpt/save", stage="publish") is not None

    def test_maybe_fail_raises_injected_comm_error(self):
        p = ChaosPlan(0, [Fault("comm/x", at=(0,))])
        with pytest.raises(InjectedFault) as ei:
            p.maybe_fail("comm/x", index=0, op="allreduce")
        assert ei.value.op == "allreduce"
        assert isinstance(ei.value, CommError)

    def test_corrupt_batch_poisons_exactly_when_scheduled(self):
        p = ChaosPlan(0, [Fault("train/grad", at=(4,), kind="nan")])
        x = jnp.ones((2, 3))
        assert p.corrupt_batch(x, 3) is x
        bad = p.corrupt_batch(x, 4)
        assert math.isnan(float(bad[0, 0]))

    def test_maybe_preempt(self):
        p = ChaosPlan(0, [Fault("train/preempt", at=(10,), kind="preempt")])
        p.maybe_preempt(index=9)
        with pytest.raises(Preempted):
            p.maybe_preempt(index=10)


class TestRetry:
    def test_transient_failure_recovers(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("transient")
            return "ok"

        slept = []
        out = retry(flaky, RetryPolicy(max_attempts=4, base_s=0.01),
                    sleep=slept.append)
        assert out == "ok" and calls["n"] == 3 and len(slept) == 2

    def test_exhaustion_reraises_last_error(self):
        def always():
            raise OSError("hard")

        with pytest.raises(OSError, match="hard"):
            retry(always, RetryPolicy(max_attempts=2, base_s=0.0),
                  sleep=lambda s: None)

    def test_deterministic_jitter(self):
        a = RetryPolicy(base_s=0.1, jitter=0.5, seed=3)
        b = RetryPolicy(base_s=0.1, jitter=0.5, seed=3)
        c = RetryPolicy(base_s=0.1, jitter=0.5, seed=4)
        assert [a.delay(i) for i in range(5)] == [b.delay(i) for i in range(5)]
        assert a.delay(0) != c.delay(0)
        assert all(0.05 <= a.delay(i) for i in range(5))

    def test_watchdog_abandons_stalled_attempt(self):
        import time

        calls = {"n": 0}

        def stalls_once():
            calls["n"] += 1
            if calls["n"] == 1:
                time.sleep(0.5)
            return "done"

        out = retry(
            stalls_once,
            RetryPolicy(max_attempts=2, base_s=0.0, attempt_timeout_s=0.05),
            sleep=lambda s: None,
        )
        assert out == "done" and calls["n"] == 2

    def test_watchdog_timeout_surfaces_when_exhausted(self):
        import time

        with pytest.raises(WatchdogTimeout):
            retry(lambda: time.sleep(0.5),
                  RetryPolicy(max_attempts=1, attempt_timeout_s=0.05))

    def test_log_names_failing_op_from_comm_error(self):
        lines = []

        def fails():
            raise CommError("ring_shift", "link down")

        with pytest.raises(CommError):
            retry(fails, RetryPolicy(max_attempts=2, base_s=0.0),
                  op="outer", log=lines.append, sleep=lambda s: None)
        assert all("ring_shift" in ln for ln in lines) and len(lines) == 2


class TestSupervisor:
    def test_restarts_then_returns(self):
        calls = {"n": 0}

        def fn():
            calls["n"] += 1
            if calls["n"] < 3:
                raise Preempted("train/preempt", calls["n"])
            return "final"

        assert supervise(fn, budget=RestartBudget(max_restarts=3),
                         sleep=lambda s: None) == "final"
        assert calls["n"] == 3

    def test_budget_exhaustion(self):
        def fn():
            raise Preempted("train/preempt")

        with pytest.raises(RestartsExhausted):
            supervise(fn, budget=RestartBudget(max_restarts=2),
                      sleep=lambda s: None)

    def test_non_restartable_propagates(self):
        def fn():
            raise GuardFailure("poisoned stream")

        with pytest.raises(GuardFailure):
            supervise(fn, sleep=lambda s: None)


def _mesh():
    # (1, 2): ring attention over sp still exercised, compile cost ~40%
    # lower than 2x2 — ft logic is mesh-size-independent (sharding
    # equivalence is test_models' job; bit-identity holds per mesh)
    return make_mesh((1, 2), ("dp", "sp"), jax.devices()[:2])


def _cfg():
    return TransformerConfig(
        d_model=16, n_heads=2, n_experts=2, d_ff=32, capacity_factor=2.0
    )


@pytest.mark.chaos
class TestGuardedStep:
    def test_statuses_and_skip_protection(self):
        mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        cfg = _cfg()
        fn = train_step(mesh, cfg, lr=0.05, guard=(1e30, 4.0))
        plain = train_step(mesh, cfg, lr=0.05)
        params = init_params(0, cfg)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((2, 8, 16)), jnp.float32)
        y = jnp.asarray(rng.standard_normal((2, 8, 16)), jnp.float32)
        nan_ref = jnp.asarray(float("nan"), jnp.float32)

        # clean step: ok, update == the unguarded program's update
        new, loss, gnorm, st = fn(params, x, y, nan_ref)
        ref, ref_loss = plain(params, x, y)
        assert int(st) == STATUS_OK
        assert float(loss) == float(ref_loss)
        assert _params_equal(new, ref)

        # NaN batch: skipped, params bit-identical (the in-program select)
        bad = x.at[0, 0, 0].set(jnp.nan)
        new2, loss2, _, st2 = fn(params, bad, y, nan_ref)
        assert int(st2) == STATUS_SKIPPED
        assert math.isnan(float(loss2))
        assert _params_equal(new2, params)

        # spike: loss far above the fed reference -> skipped
        tiny_ref = jnp.asarray(1e-9, jnp.float32)
        _, _, _, st3 = fn(params, x, y, tiny_ref)
        assert int(st3) == STATUS_SKIPPED

        # clip: a tiny clip_norm marks the step clipped but applies it
        clip_fn = train_step(mesh, cfg, lr=0.05, guard=(1e-3, 1e30))
        new4, _, gnorm4, st4 = clip_fn(params, x, y, nan_ref)
        assert int(st4) == STATUS_CLIPPED
        assert float(gnorm4) > 1e-3
        assert not _params_equal(new4, params)
        assert not _params_equal(new4, ref)  # the update was rescaled

    def test_unguarded_program_contains_no_guard_ops(self):
        # the uninstrumented-unchanged gate: guard=None lowers to a
        # program with no finiteness test; guard=(...) adds it
        mesh = make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1])
        cfg = _cfg()
        params = init_params(0, cfg)
        x = jnp.zeros((2, 8, 16), jnp.float32)
        plain_txt = train_step(mesh, cfg).lower(params, x, x).as_text()
        guarded_txt = train_step(mesh, cfg, guard=(1e30, 1e30)).lower(
            params, x, x, jnp.float32(0)
        ).as_text()
        assert "is_finite" not in plain_txt
        assert "is_finite" in guarded_txt


@pytest.mark.chaos
class TestTrainerChaos:
    def test_nan_rollback_heals_and_preemption_resumes(self, devices,
                                                       tmp_path):
        mesh, cfg = _mesh(), _cfg()
        kw = dict(save_every=3, lr=0.05, seed=3)
        clean, _ = train(mesh, cfg, steps=6,
                         ckpt_dir=str(tmp_path / "clean"), **kw)

        # one-shot NaN at step 4 + guard(max_skips=0): the poisoned chunk
        # rolls back, the replay consumes nothing (times=1 spent), and
        # the final params are bit-identical to the fault-free run —
        # with exactly one compile of the guarded step (sink-gated)
        sink_path = tmp_path / "obs.jsonl"
        from tpuscratch.obs.sink import Sink

        plan = ChaosPlan(0, [Fault("train/grad", at=(4,), kind="nan")])
        with Sink(str(sink_path)) as sink:
            healed, rep = train(
                mesh, cfg, steps=6, ckpt_dir=str(tmp_path / "nan"),
                chaos=plan, guard=GuardPolicy(max_skips=0, max_rollbacks=1),
                obs=sink, **kw,
            )
        assert rep.skipped == 1 and rep.rollbacks == 1
        assert _params_equal(healed, clean)
        events = [json.loads(ln) for ln in sink_path.read_text().splitlines()]
        by_ev = {}
        for e in events:
            by_ev.setdefault(e["event"], []).append(e)
        assert "ft/fault" in by_ev and "ft/rollback" in by_ev
        assert "ft/guard" in by_ev
        # zero steady-state recompiles, rollback replay included
        assert by_ev["train/run"][-1]["compiles"] == 1

        # preemption-only under supervise: bit-identical to fault-free
        plan2 = ChaosPlan(0, [Fault("train/preempt", at=(3,),
                                    kind="preempt")])
        resumed, _ = supervise_train(
            mesh, cfg, 6, str(tmp_path / "pre"), chaos=plan2, **kw)
        assert _params_equal(resumed, clean)

    def test_nan_plus_preemption_replay_is_bit_identical(self, devices,
                                                         tmp_path):
        mesh, cfg = _mesh(), _cfg()
        kw = dict(save_every=3, lr=0.05, seed=3)

        def run(tag):
            plan = ChaosPlan(1, [
                Fault("train/grad", at=(1,), kind="nan"),
                Fault("train/preempt", at=(3,), kind="preempt"),
            ])
            from tpuscratch.obs.metrics import MetricsRegistry

            metrics = MetricsRegistry()
            params, rep = supervise_train(
                mesh, cfg, 6, str(tmp_path / tag), chaos=plan,
                guard=GuardPolicy(max_skips=0, max_rollbacks=2),
                metrics=metrics, **kw,
            )
            return params, plan, int(metrics.counter("ft/restarts").value)

        p1, plan1, restarts1 = run("a")
        p2, plan2, restarts2 = run("b")
        assert _params_equal(p1, p2)                     # replay-identical
        assert plan1.stats() == plan2.stats() != {}      # same schedule
        assert restarts1 == restarts2 == 1

    @pytest.mark.zero
    def test_zero_nan_rollback_heals_bit_identical(self, devices,
                                                   tmp_path):
        """GuardPolicy skip/rollback under ZeRO-SHARDED moments: the
        ladder restores dp-sharded flat optimizer shards from the
        checkpoint and the healed run is bit-identical to the
        fault-free ZeRO run — the same contract the replicated path
        proves above, now over the sharded state layout."""
        mesh, cfg = _mesh(), _cfg()
        kw = dict(save_every=3, lr=0.005, seed=3, optimizer="adam",
                  zero=True)
        clean, _ = train(mesh, cfg, steps=6,
                         ckpt_dir=str(tmp_path / "zclean"), **kw)
        plan = ChaosPlan(0, [Fault("train/grad", at=(4,), kind="nan")])
        healed, rep = train(
            mesh, cfg, steps=6, ckpt_dir=str(tmp_path / "znan"),
            chaos=plan, guard=GuardPolicy(max_skips=0, max_rollbacks=1),
            **kw,
        )
        assert rep.skipped == 1 and rep.rollbacks == 1
        assert _params_equal(healed, clean)

    def test_rollback_budget_exhaustion_raises_guard_failure(self):
        # the ladder's bounded end — pure host logic, no compile needed:
        # a never-healing skip stream burns the rollback budget and
        # raises instead of replaying forever
        from tpuscratch.ft.guards import GuardState

        st = GuardState(GuardPolicy(max_skips=0, max_rollbacks=1))
        assert st.observe([STATUS_SKIPPED])   # rollback needed
        st.rolled_back()                      # 1st: within budget
        assert st.observe([STATUS_OK, STATUS_SKIPPED])
        with pytest.raises(GuardFailure):
            st.rolled_back()                  # 2nd: budget spent
        assert st.skips == 2 and st.rollbacks == 2


@pytest.mark.chaos
class TestServeChaos:
    def _build(self, chaos=None, retry_budget=0):
        # 1 layer: the quarantine/replay logic under test is engine-side;
        # depth only grows compile time (decode equivalence at depth is
        # test_serve's job)
        cfg = TransformerConfig(d_model=32, n_heads=4, n_experts=4,
                                d_ff=48, n_layers=1, capacity_factor=4.0)
        mesh = make_mesh((2, 2), ("dp", "sp"), jax.devices()[:4])
        scfg = ServeConfig(n_slots=4, n_pages=16, page_size=4, max_seq=24,
                           vocab=16, retry_budget=retry_budget)
        return ServeEngine(mesh, cfg, scfg, chaos=chaos)

    def test_transient_and_poison_prefill_faults(self, devices):
        reqs = [Request(rid=i, prompt=(1 + i, 2), max_new=4)
                for i in range(4)]
        clean = self._build().run(reqs)
        assert clean.completed == 4

        # transient: rid 1's first two admissions fail, third succeeds —
        # retried in-engine, outputs byte-identical to the fault-free run
        plan = ChaosPlan(0, [Fault("serve/prefill", key=1, at=(0, 1),
                                   times=2)])
        eng = self._build(chaos=plan, retry_budget=3)
        rep = eng.run(reqs)
        assert rep.outputs == clean.outputs
        assert rep.quarantined == ()
        assert rep.decode_compiles == 1      # tick program unchanged

        # poison: rid 1 fails EVERY admission -> quarantined after the
        # budget; every other request byte-identical; engine drains (no
        # livelock) and leaks no pages
        plan2 = ChaosPlan(0, [Fault("serve/prefill", key=1, p=1.0,
                                    at=None, times=None)])
        eng2 = self._build(chaos=plan2, retry_budget=2)
        rep2 = eng2.run(reqs)
        assert rep2.quarantined == (1,)
        assert 1 in eng2.quarantined
        assert rep2.outputs == tuple(
            (r, t) for r, t in clean.outputs if r != 1
        )
        assert eng2.free_pages() == [16, 16]
        assert eng2.n_queued == 0 and eng2.n_active == 0
        assert rep2.decode_compiles == 1

    def test_default_budget_is_legacy(self):
        # retry_budget defaults to 0 = the raise-through contract test_serve
        # pins (test_failed_prefill_returns_pages_and_requeues); the
        # quarantine machinery is strictly opt-in
        assert ServeConfig(n_slots=4, n_pages=16, page_size=4, max_seq=24,
                           vocab=16).retry_budget == 0


class TestHostpoolRetry:
    def test_alloc_retry_wiring(self):
        hostpool = pytest.importorskip("tpuscratch.native.hostpool")
        if not hostpool.available():
            pytest.skip("native library not built")
        lines = []
        pool = hostpool.HostPool(
            lock_pages=False,
            retry=RetryPolicy(max_attempts=2, base_s=0.0),
        )
        with pool:
            # a sane allocation succeeds untouched by the retry path
            with pool.alloc(4096) as buf:
                assert buf.nbytes == 4096
            # an impossible allocation exercises trim+retry, then fails
            with pytest.raises(MemoryError):
                retry(lambda: pool.alloc(1 << 62),
                      RetryPolicy(max_attempts=2, base_s=0.0),
                      op="hostpool.alloc", log=lines.append,
                      sleep=lambda s: None)
        assert len(lines) == 2 and "hostpool.alloc" in lines[0]
