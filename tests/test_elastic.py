"""Elastic fault tolerance (ISSUE 11 acceptance anchors):

- **reshard-on-resume**: ``models.zero.reshard_state`` regroups flat
  dp-sharded (and pp x dp stage-grouped) moment vectors across plan
  identities exactly (element-identical on the true region, fresh
  alignment padding zeroed), round-trips, and refuses cross-family
  moves; ``checkpoint.restore(mesh_shape=)`` names BOTH identities and
  the ``reshard=True`` escape hatch in its mismatch ``CommError``; a
  run preempted on dp=4 RESUMES on dp=2 where it previously raised —
  with the trainer's internal regroup proven leaf-for-leaf equal to the
  manual ``reshard_state`` path, and the shrunk resume bit-identical to
  its own replay.
- **elastic supervision**: ``ft.supervise_train_elastic`` rebuilds the
  mesh from the surviving devices after a preemption and completes on
  the shrunk plan, replay-deterministically.
- **async checkpointing**: ``runtime.async_ckpt.AsyncCheckpointer``
  publishes checkpoints byte-identical to the blocking path, keeps at
  most one write in flight behind the snapshot barrier, absorbs
  transient ``ckpt/write`` chaos under retry, surfaces persistent
  failures at the drain, and the async trainer run emits the split
  ``ckpt/snapshot``/``ckpt/write`` events that ``obs.goodput`` books
  into an exactly-summing partition.
"""

import shutil

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuscratch.ft.chaos import ChaosPlan, Fault, InjectedFault
from tpuscratch.models.trainer import synthetic_batch, train
from tpuscratch.models.transformer import (
    TransformerConfig,
    init_params,
    nonexpert_size,
    stack_layers,
)
from tpuscratch.models.zero import (
    init_zero_adam_state,
    put_zero_state,
    reshard_state,
    train_step_zero,
    zero_flat_size,
)
from tpuscratch.runtime import checkpoint
from tpuscratch.runtime.async_ckpt import AsyncCheckpointer
from tpuscratch.runtime.errors import CommError
from tpuscratch.runtime.mesh import make_mesh

pytestmark = pytest.mark.elastic


def _cfg(n_experts=2, n_layers=2):
    return TransformerConfig(
        d_model=16, n_heads=2, n_experts=n_experts, d_ff=32,
        n_layers=n_layers, capacity_factor=2.0,
    )


def _mesh(shape):
    return make_mesh(shape, ("dp", "sp"),
                     jax.devices()[:shape[0] * shape[1]])


def _leaves_equal(a, b):
    return all(
        np.array_equal(np.asarray(p), np.asarray(q))
        for p, q in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


def _plan(dp, sp=1, pp=1, n_micro=1):
    return {"dp": dp, "sp": sp, "pp": pp, "n_micro": n_micro}


def _fake_zero_state(params, dp, seed=0):
    """A saved-layout ZeRO state with DISTINCT recognizable moment
    values on the true (non-padding) region — the regroup tests' probe.
    Padding slots are zero, the invariant the real state maintains."""
    n = nonexpert_size(params)
    flat = zero_flat_size(n, dp)
    rng = np.random.default_rng(seed)
    mu = np.zeros((flat,), np.float32)
    nu = np.zeros((flat,), np.float32)
    mu[:n] = rng.standard_normal(n).astype(np.float32)
    nu[:n] = rng.standard_normal(n).astype(np.float32) ** 2
    from tpuscratch.models.transformer import expert_leaves

    exp = expert_leaves(params)
    return {
        "mu_flat": mu, "nu_flat": nu,
        "mu_exp": [rng.standard_normal(x.shape).astype(np.float32)
                   for x in exp],
        "nu_exp": [rng.standard_normal(x.shape).astype(np.float32) ** 2
                   for x in exp],
        "t": np.asarray(7, np.int32),
    }


class TestReshardState:
    def test_flat_dp_regroup_is_exact_and_roundtrips(self, devices):
        """dp=4 -> dp=2: the true region is element-identical (the flat
        vector is layout-invariant modulo alignment padding), fresh
        padding is zero, and the round trip back to dp=4 reproduces the
        original vector bit-for-bit."""
        cfg = _cfg()
        params = init_params(0, cfg)
        n = nonexpert_size(params)
        a = _fake_zero_state(params, dp=4)
        b = reshard_state(a, params, _plan(4), _plan(2))
        assert b["mu_flat"].shape == (zero_flat_size(n, 2),)
        np.testing.assert_array_equal(b["mu_flat"][:n], a["mu_flat"][:n])
        assert not b["mu_flat"][n:].any()
        for x, y in zip(a["mu_exp"], b["mu_exp"]):
            np.testing.assert_array_equal(x, y)
        assert int(b["t"]) == int(a["t"])
        back = reshard_state(b, params, _plan(2), _plan(4))
        np.testing.assert_array_equal(back["mu_flat"], a["mu_flat"])
        np.testing.assert_array_equal(back["nu_flat"], a["nu_flat"])

    def test_identical_plans_pass_through(self, devices):
        cfg = _cfg()
        params = init_params(0, cfg)
        a = _fake_zero_state(params, dp=2)
        assert reshard_state(a, params, _plan(2), _plan(2)) is a

    def test_cross_family_raises(self, devices):
        cfg = _cfg()
        params = init_params(0, cfg)
        a = _fake_zero_state(params, dp=2)
        with pytest.raises(CommError, match="famil"):
            reshard_state(a, params, _plan(2), _plan(2, pp=2, n_micro=2))

    def test_pp_stage_regroup_is_path_independent(self, devices):
        """Within the stage-stacked family, regrouping pp=1 -> pp=2 ->
        pp=4 equals regrouping pp=1 -> pp=4 directly — the flat vector
        is a pure function of the per-leaf moments and the grouping."""
        cfg = _cfg(n_layers=4)
        stacked = stack_layers(init_params(0, cfg))
        # the canonical (one stage group) pipelined layout: n_micro>1
        # keeps it in-family while pp=1 gives a single flat group
        canon = _fake_zero_state(stacked, dp=2)
        p1 = _plan(2, pp=1, n_micro=2)
        p2 = _plan(1, pp=2, n_micro=2)
        p4 = _plan(1, pp=4, n_micro=2)
        via = reshard_state(reshard_state(canon, stacked, p1, p2),
                            stacked, p2, p4)
        direct = reshard_state(canon, stacked, p1, p4)
        np.testing.assert_array_equal(via["mu_flat"], direct["mu_flat"])
        np.testing.assert_array_equal(via["nu_flat"], direct["nu_flat"])
        # and back: pp=4 -> pp=1 reproduces the canonical layout
        back = reshard_state(direct, stacked, p4, p1)
        np.testing.assert_array_equal(back["mu_flat"], canon["mu_flat"])

    def test_wrong_length_vector_raises(self, devices):
        cfg = _cfg()
        params = init_params(0, cfg)
        a = _fake_zero_state(params, dp=2)
        a["mu_flat"] = a["mu_flat"][:-8]  # not plan(2)'s padded length
        with pytest.raises(CommError, match="does not match"):
            reshard_state(a, params, _plan(2), _plan(4))


TRAIN_KW = dict(save_every=5, lr=0.005, seed=5, optimizer="adam",
                zero=True, batch=8, seq=8)


class TestRestoreReshard:
    def test_mismatch_error_names_both_plans_and_escape_hatch(
            self, devices, tmp_path):
        """Satellite: the restore-time mismatch CommError must name the
        saved AND the live identity and point at reshard=True — not
        just say resharding is unsupported."""
        cfg = _cfg(n_experts=4)
        d = str(tmp_path / "mm")
        train(_mesh((4, 1)), cfg, steps=5, ckpt_dir=d, **TRAIN_KW)
        params = init_params(5, cfg)
        ex = {"params": params, "opt": init_zero_adam_state(params, 2)}
        with pytest.raises(CommError) as ei:
            checkpoint.restore(d, ex, mesh_shape={"dp": 2, "sp": 1})
        msg = str(ei.value)
        assert "reshard=True" in msg
        assert "'dp': 4" in msg and "'dp': 2" in msg
        # the trainer-layer error carries the same escape hatch
        with pytest.raises(CommError, match="reshard=True"):
            train(_mesh((2, 1)), cfg, steps=10, ckpt_dir=d, **TRAIN_KW)

    def test_reshard_true_loads_saved_layout(self, devices, tmp_path):
        cfg = _cfg(n_experts=4)
        d = str(tmp_path / "rl")
        train(_mesh((4, 1)), cfg, steps=5, ckpt_dir=d, **TRAIN_KW)
        params = init_params(5, cfg)
        n = nonexpert_size(params)
        ex = {"params": params, "opt": init_zero_adam_state(params, 2)}
        state, step, meta = checkpoint.restore(
            d, ex, mesh_shape={"dp": 2, "sp": 1}, reshard=True
        )
        assert step == 5
        # the leaves come back in their SAVED (dp=4) layout
        assert state["opt"]["mu_flat"].shape == (zero_flat_size(n, 4),)
        assert meta["mesh_shape"] == {"dp": 4, "sp": 1}


class TestShrunkResume:
    def test_dp4_to_dp2_resume_completes_and_matches_manual_regroup(
            self, devices, tmp_path):
        """THE flagship: a run checkpointed on dp=4 resumes on dp=2 via
        reshard=True (previously a hard CommError) and the state it
        trains from is EXACTLY the manual regroup — proven leaf-for-leaf
        by replaying the same 5 steps from the manually-resharded state
        through the raw compiled step and comparing the final params
        bit-for-bit with the trainer's."""
        cfg = _cfg(n_experts=4)
        d = str(tmp_path / "shrink")
        train(_mesh((4, 1)), cfg, steps=10, ckpt_dir=d, **TRAIN_KW)

        live_mesh = _mesh((2, 1))
        resumed, rep = train(live_mesh, cfg, steps=15, ckpt_dir=d,
                             reshard=True, **TRAIN_KW)
        assert rep.steps_run == 5 and rep.final_step == 15

        # --- the manual path: restore saved layout, regroup, replay ---
        params0 = init_params(5, cfg)
        ex = {"params": params0, "opt": init_zero_adam_state(params0, 2)}
        state, step, _ = checkpoint.restore(
            d, ex, step=10, mesh_shape={"dp": 2, "sp": 1}, reshard=True
        )
        opt = reshard_state(state["opt"], state["params"],
                            _plan(4), _plan(2))
        opt = put_zero_state(opt, live_mesh, cfg)
        params = state["params"]
        step_fn = train_step_zero(live_mesh, cfg, lr=TRAIN_KW["lr"])
        for i in range(10, 15):
            x, y = synthetic_batch(TRAIN_KW["seed"], i, TRAIN_KW["batch"],
                                   TRAIN_KW["seq"], cfg.d_model)
            params, opt, _ = step_fn(params, opt, x, y)
        assert _leaves_equal(resumed, params)

    def test_shrunk_resume_is_bit_identical_to_its_replay(
            self, devices, tmp_path):
        cfg = _cfg(n_experts=4)
        src = tmp_path / "src"
        train(_mesh((4, 1)), cfg, steps=10, ckpt_dir=str(src), **TRAIN_KW)
        finals = []
        for tag in ("a", "b"):
            d = tmp_path / f"replay_{tag}"
            shutil.copytree(src, d)
            p, _ = train(_mesh((2, 1)), cfg, steps=20, ckpt_dir=str(d),
                         reshard=True, **TRAIN_KW)
            finals.append(p)
        assert _leaves_equal(finals[0], finals[1])


class TestElasticSupervisor:
    def _run(self, ckpt_dir, metrics=None):
        from tpuscratch.ft.supervisor import (
            RestartBudget,
            supervise_train_elastic,
        )

        cfg = _cfg(n_experts=4)
        calls = {"n": 0}

        def devices_fn():
            # the preemption takes half the slice with it: attempt 1
            # sees 4 devices, every restart sees the surviving 2
            calls["n"] += 1
            return jax.devices()[: (4 if calls["n"] == 1 else 2)]

        def mesh_of(devs):
            return make_mesh((len(devs), 1), ("dp", "sp"), devs)

        chaos = ChaosPlan(0, [Fault("train/preempt", at=(4,),
                                    kind="preempt")])
        return supervise_train_elastic(
            cfg, 8, str(ckpt_dir), mesh_of=mesh_of,
            devices_fn=devices_fn,
            budget=RestartBudget(max_restarts=2, backoff_s=0.0),
            metrics=metrics, chaos=chaos, save_every=2, lr=0.005,
            seed=5, optimizer="adam", zero=True, batch=8, seq=8,
        )

    def test_preempted_and_shrunk_run_completes_under_supervision(
            self, devices, tmp_path):
        from tpuscratch.obs.metrics import MetricsRegistry

        metrics = MetricsRegistry()
        params, rep = self._run(tmp_path / "el", metrics=metrics)
        assert rep.final_step == 8
        snap = metrics.snapshot()
        assert snap["ft/restarts"]["value"] == 1
        assert snap["ft/elastic_reshards"]["value"] == 1
        # the whole elastic scenario replays bit-identically
        params2, _ = self._run(tmp_path / "el2")
        assert _leaves_equal(params, params2)


class TestAsyncCheckpointer:
    def _tree(self, seed=0):
        rng = np.random.default_rng(seed)
        return {
            "a": rng.standard_normal((4, 6)).astype(np.float32),
            "b": rng.integers(0, 100, (3,)).astype(np.int32),
            "t": np.asarray(2, np.int32),
        }

    def test_publishes_byte_identical_to_blocking(self, tmp_path):
        tree = self._tree()
        meta = {"who": "async-test"}
        with AsyncCheckpointer() as ck:
            ck.snapshot(tmp_path / "a", 3, tree, metadata=meta)
        checkpoint.save(tmp_path / "b", 3, tree, metadata=meta)
        a_dir = tmp_path / "a" / "step_000000003"
        b_dir = tmp_path / "b" / "step_000000003"
        names = sorted(p.name for p in b_dir.iterdir())
        assert names == sorted(p.name for p in a_dir.iterdir())
        for f in names:
            assert (a_dir / f).read_bytes() == (b_dir / f).read_bytes()
        got, s, m = checkpoint.restore(tmp_path / "a", tree)
        assert s == 3 and m == meta
        assert _leaves_equal(got, tree)

    def test_barrier_serializes_writes_and_prunes(self, tmp_path):
        ck = AsyncCheckpointer()
        for step in (1, 2, 3, 4, 5):
            ck.snapshot(tmp_path / "ck", step, self._tree(step), keep=3)
        ck.drain()
        assert not ck.in_flight()
        assert checkpoint.steps(tmp_path / "ck") == [3, 4, 5]
        assert ck.writes == 5

    def test_snapshot_is_immune_to_source_mutation(self, tmp_path):
        """The staging copy is OWNED: mutating (or reusing) the source
        buffer after snapshot() returns must not corrupt the published
        bytes — the donation-safety contract of the async path."""
        arr = np.ones((64,), np.float32)
        ck = AsyncCheckpointer()
        ck.snapshot(tmp_path / "ck", 1, {"x": arr})
        arr[:] = -1.0  # the donated-buffer-reuse stand-in
        ck.drain()
        got, _, _ = checkpoint.restore(tmp_path / "ck",
                                       {"x": np.zeros((64,), np.float32)})
        np.testing.assert_array_equal(got["x"], np.ones((64,), np.float32))

    def test_transient_write_fault_absorbed_by_retry(self, tmp_path):
        chaos = ChaosPlan(0, [Fault("ckpt/write", stage="publish",
                                    at=(0,), kind="error", times=1)])
        ck = AsyncCheckpointer(chaos=chaos)
        ck.snapshot(tmp_path / "ck", 1, self._tree())
        ck.drain()  # the retry's second attempt published
        assert checkpoint.latest_step(tmp_path / "ck") == 1

    def test_persistent_write_fault_surfaces_at_drain(self, tmp_path):
        chaos = ChaosPlan(0, [Fault("ckpt/write", stage="begin", p=1.0,
                                    times=None, kind="error")])
        ck = AsyncCheckpointer(chaos=chaos)
        ck.snapshot(tmp_path / "ck", 1, self._tree())
        with pytest.raises(OSError, match="injected"):
            ck.drain()
        # the error is consumed: the checkpointer is reusable
        ck2_tree = self._tree()
        ck._chaos = None
        ck.snapshot(tmp_path / "ck", 2, ck2_tree)
        ck.drain()
        assert checkpoint.latest_step(tmp_path / "ck") == 2

    def test_snapshot_chaos_site_fires(self, tmp_path):
        chaos = ChaosPlan(0, [Fault("ckpt/snapshot", at=(0,),
                                    kind="error")])
        ck = AsyncCheckpointer(chaos=chaos)
        with pytest.raises(InjectedFault):
            ck.snapshot(tmp_path / "ck", 1, self._tree())

    def test_hostpool_footprint_is_observable(self, tmp_path):
        """Satellite: the snapshot-buffer footprint lands in a metrics
        snapshot — HostPool.stats() gauges (live buffers, bytes, trims)
        plus the staged byte count — instead of being silent."""
        from tpuscratch.obs.metrics import MetricsRegistry

        metrics = MetricsRegistry()
        ck = AsyncCheckpointer(metrics=metrics)
        ck.snapshot(tmp_path / "ck", 1, self._tree())
        ck.drain()
        snap = metrics.snapshot()
        assert snap["ckpt/snapshot_bytes"]["value"] > 0
        assert snap["ckpt/async_writes"]["value"] == 1
        from tpuscratch.native import hostpool

        if hostpool.available():
            assert "hostpool/bytes_in_use" in snap
            assert "hostpool/live_buffers" in snap
            st = hostpool.default_pool().stats()
            assert "live_buffers" in st and "trim_calls" in st


class TestAsyncTrainer:
    def test_async_train_matches_blocking_and_books_goodput(
            self, devices, tmp_path):
        """async_ckpt=True changes WHEN the bytes hit disk, nothing
        else: the trajectory and final params equal the blocking run's,
        the sink carries the split ckpt/snapshot + ckpt/write events,
        and obs.goodput books them into an exactly-summing partition."""
        from tpuscratch.obs.goodput import goodput_report
        from tpuscratch.obs.report import load_events
        from tpuscratch.obs.sink import Sink

        cfg = _cfg()
        mesh = _mesh((2, 2))
        kw = dict(save_every=2, lr=0.005, seed=5, optimizer="adam",
                  batch=4, seq=16)
        blocking, _ = train(mesh, cfg, steps=6,
                            ckpt_dir=str(tmp_path / "blk"), **kw)
        path = str(tmp_path / "obs.jsonl")
        with Sink(path) as sink:
            asynced, _ = train(mesh, cfg, steps=6,
                               ckpt_dir=str(tmp_path / "asy"),
                               obs=sink, async_ckpt=True, **kw)
        assert _leaves_equal(blocking, asynced)
        events = load_events([path])
        kinds = {e.get("event") for e in events}
        assert "ckpt/snapshot" in kinds and "ckpt/write" in kinds
        assert "ckpt/save" not in kinds
        assert len([e for e in events if e.get("event") == "ckpt/write"]) \
            == 3
        rep = goodput_report(events)
        rep.check()
        assert rep.buckets["checkpoint"] >= 0

    def test_async_resume_after_preemption_is_bit_identical(
            self, devices, tmp_path):
        """Preempted mid-run with async saves: the drained barrier at
        the preemption point guarantees the successor finds the step
        published, and the supervised run finishes bit-identical to an
        uninterrupted async run."""
        from tpuscratch.ft.supervisor import RestartBudget, supervise_train

        cfg = _cfg()
        mesh = _mesh((2, 2))
        kw = dict(save_every=2, lr=0.005, seed=5, optimizer="adam",
                  batch=4, seq=16, async_ckpt=True)
        straight, _ = train(mesh, cfg, steps=8,
                            ckpt_dir=str(tmp_path / "st"), **kw)
        chaos = ChaosPlan(0, [Fault("train/preempt", at=(4,),
                                    kind="preempt")])
        params, rep = supervise_train(
            mesh, cfg, 8, str(tmp_path / "pre"),
            budget=RestartBudget(max_restarts=2, backoff_s=0.0),
            chaos=chaos, **kw,
        )
        assert rep.final_step == 8
        assert _leaves_equal(straight, params)


class TestElasticChunkRuntimes:
    def test_halo_driver_reshards_tiles_onto_smaller_mesh(
            self, devices, tmp_path):
        """The stencil's elastic resume: tiles cut for a 2x2 grid are
        reassembled and re-cut for a 1x2 grid mid-run; the computed
        cells are decomposition-invariant, so the result bit-matches the
        uninterrupted 2x2 run."""
        from tpuscratch.halo import driver
        from tpuscratch.runtime.mesh import make_mesh_2d

        rng = np.random.default_rng(5)
        world = rng.standard_normal((8, 8)).astype(np.float32)
        big = make_mesh_2d((2, 2))
        small = make_mesh_2d((1, 2))
        oracle = driver.checkpointed_stencil(
            world, 8, str(tmp_path / "full"), save_every=4, mesh=big)
        d = str(tmp_path / "elastic")
        driver.checkpointed_stencil(world, 4, d, save_every=4, mesh=big)
        # without reshard, the mismatched decomposition fails loudly
        with pytest.raises(ValueError, match="structure drifted"):
            driver.checkpointed_stencil(world, 8, d, save_every=4,
                                        mesh=small)
        out = driver.checkpointed_stencil(world, 8, d, save_every=4,
                                          mesh=small, reshard=True)
        np.testing.assert_array_equal(out, oracle)

    def test_solver_runner_reshards_and_replays_deterministically(
            self, devices, tmp_path):
        """The solver's elastic resume: cores cut for (2,2,1) re-cut
        for (1,1,1) mid-solve; the resumed solve completes and is
        bit-identical to its own replay (cross-mesh psum regroupings
        reassociate, so the ORACLE comparison is tolerance, the replay
        comparison exact)."""
        from tpuscratch.ft.chaos import Preempted
        from tpuscratch.solvers import checkpointed_mg3d_solve

        rng = np.random.default_rng(3)
        b = rng.standard_normal((16, 16, 16)).astype(np.float32)
        b -= b.mean()
        big = make_mesh((2, 2, 1), ("z", "row", "col"), jax.devices()[:4])
        small = make_mesh((1, 1, 1), ("z", "row", "col"),
                          jax.devices()[:1])
        kw = dict(tol=1e-6, max_cycles=20, chunk_cycles=4)
        x_full, _ = checkpointed_mg3d_solve(
            b, str(tmp_path / "full"), mesh=big, **kw)
        src = tmp_path / "src"
        chaos = ChaosPlan(0, [Fault("solver/preempt", at=(4,),
                                    kind="preempt")])
        with pytest.raises(Preempted):
            checkpointed_mg3d_solve(b, str(src), mesh=big, chaos=chaos,
                                    **kw)
        outs = []
        for tag in ("a", "b"):
            d = tmp_path / f"re_{tag}"
            shutil.copytree(src, d)
            x, rep = checkpointed_mg3d_solve(b, str(d), mesh=small,
                                             reshard=True, **kw)
            assert rep.resumed_at == 4 and rep.converged
            outs.append(x)
        np.testing.assert_array_equal(outs[0], outs[1])
        np.testing.assert_allclose(outs[0], x_full, rtol=1e-4, atol=1e-5)
