"""Traffic harness (ISSUE 17): trace-generator determinism laws,
bounded-memory TTFT reservoirs, replica-kill/stall chaos with
zero-loss re-admission and chaos-vs-clean bit-identity, the
byte-budgeted open loop, and the config-19 regress directions.

Overload-control layer (ISSUE 18, marker ``overload``): closed-loop
think-time clients with seeded retry storms (``run_traffic_closed``,
``sheds == retries + abandoned``), correlated ``Fault(domain=)`` rack
kills with a shared ignition budget, JSONL trace dump/replay
round-trip, disagg kill-mid-handoff zero-loss, the 8-combo
open/closed x shed x chaos counter-law sweep, the config-20 regress
directions, and the slow-marked full-storm acceptance + record
--check subprocess proof.

The fleet tests reuse test_serve_router's compile-light shapes (same
cfg/scfg values -> same jit cache entries within a tier-1 run)."""

import itertools
import json
import os
import subprocess
import sys

import pytest

jax = pytest.importorskip("jax")

from tpuscratch.bench.traffic import (
    ClosedLoopSpec,
    RetryPolicy,
    TenantSpec,
    TraceGenerator,
    TrafficConfig,
    _tenant_quotas,
    arrival_mix_requests,
    bench_overload,
    fold_output,
    odd_prefix_len,
    overload_setup,
    replay_jsonl,
    run_traffic,
    run_traffic_closed,
)
from tpuscratch.ft.chaos import ChaosPlan, Fault, rack_domains
from tpuscratch.models.transformer import TransformerConfig
from tpuscratch.obs import regress
from tpuscratch.obs.metrics import MetricsRegistry, Reservoir, percentile
from tpuscratch.runtime.mesh import make_mesh
from tpuscratch.serve import (
    DisaggEngine,
    FleetRouter,
    Request,
    RouterConfig,
    SLOClass,
    ServeConfig,
    ServeEngine,
)

pytestmark = pytest.mark.traffic

D = 32


def cfg_for(**kw):
    kw.setdefault("capacity_factor", 4.0)
    return TransformerConfig(
        d_model=D, n_heads=4, n_experts=4, d_ff=48, n_layers=1, **kw
    )


def scfg_for(**kw):
    kw.setdefault("n_slots", 4)
    kw.setdefault("n_pages", 16)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_seq", 24)
    kw.setdefault("vocab", 16)
    kw.setdefault("prefix_share", True)
    return ServeConfig(**kw)


def mesh_for(dims=(1, 1)):
    return make_mesh(dims, ("dp", "sp"),
                     jax.devices()[: dims[0] * dims[1]])


def tenant_requests(n=6, max_new=3):
    pre = {0: (1, 2, 3, 4, 5, 6, 7, 8, 9), 1: (9, 8, 7, 6, 5, 4, 3, 2, 1)}
    return [
        Request(rid=i, prompt=pre[i % 2] + (10 + i % 5,), max_new=max_new)
        for i in range(n)
    ]


def fleet(n=3, rcfg=None, chaos=None, disagg=False, **scfg_kw):
    cfg, scfg = cfg_for(), scfg_for(**scfg_kw)
    mesh = mesh_for()
    cls = DisaggEngine if disagg else ServeEngine
    return FleetRouter([cls(mesh, cfg, scfg) for _ in range(n)],
                       rcfg=rcfg, chaos=chaos)


def check_churn_law(rep):
    """The generalized fleet counter law (ISSUE 17): every submitted
    or re-admitted prompt token was computed or served from a page."""
    assert rep.prefill_tokens + rep.shared_tokens == \
        rep.submitted_prompt_tokens + rep.readmitted_tokens
    assert rep.dropped == 0


def trace_cfg(**kw):
    kw.setdefault("seed", 7)
    kw.setdefault("tenants", (
        TenantSpec("acme", cls="latency", weight=3.0),
        TenantSpec("globex", cls="batch", weight=1.0, n_prefixes=2),
    ))
    kw.setdefault("vocab", 16)
    kw.setdefault("prompt_len", 16)
    kw.setdefault("tail_cap", 3)
    kw.setdefault("out_cap", 3)
    kw.setdefault("base_rate", 2.0)
    kw.setdefault("burst_p", 0.05)
    kw.setdefault("burst_len", 8)
    kw.setdefault("burst_mult", 3.0)
    return TrafficConfig(**kw)


TWO_CLASSES = RouterConfig(classes=(SLOClass("latency", target="ttft"),
                                    SLOClass("batch")))


class TestReservoir:
    def test_exact_while_under_k(self):
        r = Reservoir(k=64, seed=0)
        vals = [float((7 * i) % 13) for i in range(50)]
        for v in vals:
            r.observe(v)
        assert r.exact and r.count == 50
        assert r.percentile(50) == percentile(vals, 50)
        assert r.percentile(99) == percentile(vals, 99)
        assert r.min == min(vals) and r.max == max(vals)
        assert abs(r.mean - sum(vals) / len(vals)) < 1e-12

    def test_bounded_memory_past_k(self):
        r = Reservoir(k=32, seed=3)
        for i in range(10_000):
            r.observe(float(i))
        assert not r.exact
        assert r.count == 10_000 and len(r.sample) == 32
        # min/max/total stay EXACT whatever the sample dropped
        assert r.min == 0.0 and r.max == 9999.0
        assert r.mean == sum(range(10_000)) / 10_000
        assert 0.0 <= r.percentile(50) <= 9999.0

    def test_deterministic(self):
        a, b = Reservoir(k=16, seed=5), Reservoir(k=16, seed=5)
        for i in range(1000):
            a.observe(float(i % 97))
            b.observe(float(i % 97))
        assert a.sample == b.sample

    def test_registry_accessor_and_snapshot(self):
        m = MetricsRegistry()
        r = m.reservoir("serve/ttft")
        assert m.reservoir("serve/ttft") is r
        r.observe(2.0)
        snap = r.snapshot()
        assert snap["kind"] == "reservoir" and snap["count"] == 1
        assert snap["p50"] == 2.0 and snap["exact"] is True

    def test_validates(self):
        with pytest.raises(ValueError):
            Reservoir(k=0)


class TestTraceDeterminism:
    def test_same_seed_byte_identical(self):
        cfg = trace_cfg()
        a = [i.encode() for i in TraceGenerator(cfg).stream(200)]
        b = [i.encode() for i in TraceGenerator(cfg).stream(200)]
        assert a == b
        assert TraceGenerator(cfg).digest(200) == \
            TraceGenerator(cfg).digest(200)

    def test_different_seed_differs(self):
        assert TraceGenerator(trace_cfg(seed=1)).digest(100) != \
            TraceGenerator(trace_cfg(seed=2)).digest(100)

    def test_tenant_streams_interleave_independent(self):
        """acme's k-th request content is keyed on (seed, acme, k) —
        reshaping the REST of the population (weights, extra tenants)
        must not change it."""
        base = trace_cfg()
        reshaped = trace_cfg(tenants=(
            TenantSpec("acme", cls="latency", weight=3.0),
            TenantSpec("globex", cls="batch", weight=9.0, n_prefixes=2),
            TenantSpec("initech", cls="batch", weight=2.0),
        ))

        def acme(cfg, n):
            it = (x for x in TraceGenerator(cfg).stream(100_000)
                  if x.tenant == "acme")
            return [(i.req.prompt, i.req.max_new)
                    for i in itertools.islice(it, n)]

        assert acme(base, 40) == acme(reshaped, 40)

    def test_lazy_stream(self):
        """A billion-request trace is one config object until
        iterated — islice materializes exactly what it takes."""
        it = TraceGenerator(trace_cfg()).stream(1_000_000_000)
        assert len(list(itertools.islice(it, 5))) == 5

    def test_arrivals_pure_function_of_tick(self):
        gen = TraceGenerator(trace_cfg())
        assert [gen.burst_active(t) for t in range(50)] == \
            [gen.burst_active(t) for t in range(50)]
        for t in (0, 17, 300):
            assert gen.rate_at(t) >= 0.0
            assert gen.rate_at(t) == TraceGenerator(trace_cfg()).rate_at(t)

    def test_length_caps_and_classes(self):
        cfg = trace_cfg()
        for item in TraceGenerator(cfg).stream(300):
            assert len(item.req.prompt) <= cfg.max_prompt_len
            assert len(item.req.prompt) >= odd_prefix_len(cfg.prompt_len)
            assert 1 <= item.req.max_new <= cfg.out_cap
            assert item.cls == ("latency" if item.tenant == "acme"
                                else "batch")

    def test_zipf_prefix_reuse(self):
        """The Zipf pool: rank-1 prefix takes at least as much traffic
        as the last rank (seeded draws — no statistical flake)."""
        cfg = trace_cfg(tenants=(
            TenantSpec("acme", cls="latency", n_prefixes=4, zipf_a=1.5),
        ))
        gen = TraceGenerator(cfg)
        pools = gen._pools["acme"]
        plen = odd_prefix_len(cfg.prompt_len)
        counts = {i: 0 for i in range(len(pools))}
        for item in gen.stream(400):
            counts[pools.index(item.req.prompt[:plen])] += 1
        assert counts[0] > counts[len(pools) - 1]
        assert sum(counts.values()) == 400

    def test_rids_unique_and_ordered(self):
        items = list(TraceGenerator(trace_cfg()).stream(100, rid_base=50))
        assert [i.req.rid for i in items] == list(range(50, 150))
        assert all(a.t <= b.t for a, b in zip(items, items[1:]))

    def test_validation(self):
        with pytest.raises(ValueError, match="duplicate tenant"):
            trace_cfg(tenants=(TenantSpec("a"), TenantSpec("a")))
        with pytest.raises(ValueError, match="diurnal_amp"):
            trace_cfg(diurnal_amp=1.0)
        with pytest.raises(ValueError, match="base_rate"):
            trace_cfg(base_rate=0.0)
        with pytest.raises(ValueError, match="weight"):
            TenantSpec("a", weight=0.0)

    def test_arrival_mix_delegate_unchanged(self):
        """The one-definition move: decode_bench's name must still
        produce the exact pre-move workload (config-17 rows are
        recorded against it)."""
        from tpuscratch.bench.decode_bench import (
            arrival_mix_requests as via_decode,
        )

        a = via_decode([("latency", 3.0), ("batch", 1.0)], 8, 21, 16)
        b = arrival_mix_requests([("latency", 3.0), ("batch", 1.0)],
                                 8, 21, 16)
        assert [(n, r.rid, r.prompt, r.max_new) for n, r in a] == \
            [(n, r.rid, r.prompt, r.max_new) for n, r in b]
        # the odd shared-prefix rule, now owned by traffic.py
        assert odd_prefix_len(21) == 15 and odd_prefix_len(21) % 2 == 1


class TestReplicaChaos:
    def _tagged(self, n=10, max_new=3):
        return [("latency" if i % 3 else "batch", r)
                for i, r in enumerate(tenant_requests(n, max_new))]

    def test_kill_zero_loss_and_bit_identity(self):
        """A replica killed mid-stream loses NO requests and changes
        NO tokens: the chaos drain's outputs equal the kill-free
        drain's, and the generalized counter law reconciles the
        re-prefilled legs exactly."""
        clean = fleet(3, rcfg=TWO_CLASSES).run(self._tagged())
        plan = ChaosPlan(seed=11, faults=(
            Fault(site="serve/replica", at=(1,), key=0, kind="kill",
                  down_ticks=4),
        ))
        chaos = fleet(3, rcfg=TWO_CLASSES, chaos=plan).run(self._tagged())
        assert chaos.outputs == clean.outputs
        assert chaos.kills == 1 and chaos.readmitted > 0
        check_churn_law(chaos)
        check_churn_law(clean)
        assert clean.readmitted == 0 and clean.readmitted_tokens == 0
        for c in clean.classes:
            assert c.goodput_frac == 1.0
        # the chaos drain recomputed work: SOME class paid for it
        assert any(c.goodput_frac < 1.0 or c.readmitted > 0
                   for c in chaos.classes) == (chaos.readmitted_tokens > 0
                                               or chaos.lost_tokens > 0
                                               or chaos.readmitted > 0)

    def test_stall_freezes_without_loss(self):
        plan = ChaosPlan(seed=3, faults=(
            Fault(site="serve/replica", at=(1,), key=0, kind="stall",
                  down_ticks=3),
        ))
        clean = fleet(3, rcfg=TWO_CLASSES).run(self._tagged())
        stalled = fleet(3, rcfg=TWO_CLASSES, chaos=plan).run(self._tagged())
        assert stalled.outputs == clean.outputs
        assert stalled.stalls == 1 and stalled.kills == 0
        # a stall loses no state: nothing re-admitted, nothing lost
        assert stalled.readmitted == 0 and stalled.lost_tokens == 0
        check_churn_law(stalled)

    def test_killed_replica_rejoins(self):
        """After the down window the killed replica takes new work
        again — the elastic re-join."""
        plan = ChaosPlan(seed=5, faults=(
            Fault(site="serve/replica", at=(1,), key=0, kind="kill",
                  down_ticks=2),
        ))
        router = fleet(2, rcfg=TWO_CLASSES, chaos=plan)
        first = router.run(self._tagged())
        assert first.kills == 1
        assert router._down == [0, 0]
        # distinct prompt families: no affinity pull, so least-loaded
        # spreads them — the re-joined replica must take its share
        more = [("batch", Request(rid=100 + i,
                                  prompt=(11 + i, 2 + i, 3, 4, 5),
                                  max_new=2)) for i in range(4)]
        second = router.run(more)
        assert second.completed == 4 and second.kills == 0
        assert second.dispatched[0] > 0  # the re-joined replica works

    def test_default_down_ticks_from_rcfg(self):
        plan = ChaosPlan(seed=5, faults=(
            Fault(site="serve/replica", at=(1,), key=0, kind="kill"),
        ))
        rcfg = RouterConfig(classes=TWO_CLASSES.classes, rejoin_ticks=3)
        router = fleet(2, rcfg=rcfg, chaos=plan)
        rep = router.run(self._tagged())
        assert rep.kills == 1
        check_churn_law(rep)

    def test_disagg_kill_mid_handoff_zero_loss_bit_identity(self):
        """ISSUE 18 satellite: a DisaggEngine replica killed while
        requests sit in every half — front queue, staged handoff,
        finish buffer — loses NOTHING: ``DisaggEngine.evacuate`` owes
        exact triples (a staged request's prompt is already counted in
        ``stage_prefill_tokens``, so its re-admission leg is the whole
        prompt), the router re-admits every victim, and the drain is
        bit-identical to the kill-free disagg fleet's."""
        clean = fleet(3, rcfg=TWO_CLASSES, disagg=True,
                      prefix_share=False).run(self._tagged())
        plan = ChaosPlan(seed=7, faults=(
            Fault(site="serve/replica", at=(1,), key=0, kind="kill",
                  down_ticks=4),
        ))
        chaos = fleet(3, rcfg=TWO_CLASSES, chaos=plan, disagg=True,
                      prefix_share=False).run(self._tagged())
        assert chaos.outputs == clean.outputs
        assert chaos.kills == 1 and chaos.dropped == 0
        assert chaos.readmitted > 0
        check_churn_law(chaos)
        check_churn_law(clean)

    def test_disagg_evacuate_accounting(self):
        """DisaggEngine.evacuate owes every seen rid exactly once
        across front queue, staging, finish buffer, and the inner
        engine — and leaves the replica empty but alive."""
        eng = DisaggEngine(mesh_for(), cfg_for(),
                           scfg_for(prefix_share=False))
        reqs = tenant_requests(6, max_new=3)
        for r in reqs:
            eng.submit(r)
        eng.step()  # prefill a wave into staging / the inner engine
        owed = eng.evacuate()
        assert sorted(rid for rid, _, _ in owed) == \
            sorted(r.rid for r in reqs)
        by_rid = {rid: (un, lost) for rid, un, lost in owed}
        for r in reqs:
            un, lost = by_rid[r.rid]
            # never-prefilled requests owe the whole prompt and can't
            # have lost output; staged/admitted ones owe no prompt
            assert un in (0, len(r.prompt))
            if un == len(r.prompt):
                assert lost == 0
        assert eng.n_active == 0 and eng.n_queued == 0
        assert eng.n_staged == 0
        # the evacuated replica survives as the re-join target
        eng.submit(Request(rid=99, prompt=(1, 2, 3), max_new=2))
        assert eng.run().completed == 1

    def test_evacuate_accounting(self):
        """ServeEngine.evacuate returns exact owed triples: queued
        requests owe their whole prompt, admitted slots owe nothing
        prompt-side but lose their generated tokens."""
        eng = ServeEngine(mesh_for(), cfg_for(), scfg_for())
        reqs = tenant_requests(6, max_new=4)
        for r in reqs:
            eng.submit(r)
        eng.step()  # admits up to n_slots, decodes one token each
        owed = eng.evacuate()
        assert sorted(rid for rid, _, _ in owed) == \
            sorted(r.rid for r in reqs)
        by_rid = {rid: (un, lost) for rid, un, lost in owed}
        n_active_owed = sum(1 for un, _ in by_rid.values() if un == 0)
        assert n_active_owed >= 1          # someone was admitted
        for r in reqs:
            un, lost = by_rid[r.rid]
            assert un in (0, len(r.prompt))
            if un == len(r.prompt):
                assert lost == 0           # never ran: nothing to lose
        assert eng.n_active == 0 and eng.n_queued == 0
        # the engine object survives as the re-join replica
        eng.submit(Request(rid=99, prompt=(1, 2, 3), max_new=2))
        out = eng.run()
        assert out.completed == 1


class TestOpenLoop:
    def test_chaos_vs_clean_digest_identity(self):
        tcfg = trace_cfg(seed=3)
        plan = ChaosPlan(seed=11, faults=(
            Fault(site="serve/replica", at=(3,), key=0, kind="kill",
                  down_ticks=5),
            Fault(site="serve/replica", at=(9,), key=1, kind="stall",
                  down_ticks=3),
        ))
        clean = run_traffic(fleet(3, rcfg=TWO_CLASSES),
                            TraceGenerator(tcfg), 40, open_budget=12)
        chaos = run_traffic(fleet(3, rcfg=TWO_CLASSES, chaos=plan),
                            TraceGenerator(tcfg), 40, open_budget=12)
        assert chaos.digest == clean.digest
        assert chaos.submitted == clean.submitted == 40
        assert chaos.report.dropped == 0
        assert chaos.report.kills == 1 and chaos.report.stalls == 1
        assert clean.peak_open <= 12 and chaos.peak_open <= 12
        for c in clean.report.classes:
            assert c.goodput_frac == 1.0

    def test_budget_of_one_serializes(self):
        tr = run_traffic(fleet(2, rcfg=TWO_CLASSES),
                         TraceGenerator(trace_cfg(seed=9)), 6,
                         open_budget=1)
        assert tr.peak_open == 1 and tr.submitted == 6

    def test_fold_output_order_independent(self):
        a = fold_output(fold_output(0, 1, (4, 5)), 2, (6,))
        b = fold_output(fold_output(0, 2, (6,)), 1, (4, 5))
        assert a == b
        assert fold_output(0, 1, (4, 5)) != fold_output(0, 1, (4, 6))

    def test_validates_budget(self):
        with pytest.raises(ValueError, match="open_budget"):
            run_traffic(fleet(1), TraceGenerator(trace_cfg()), 2,
                        open_budget=0)

    @pytest.mark.slow
    def test_100k_requests_under_replica_kill_chaos(self):
        """The ISSUE-17 acceptance run: a seeded 100k-request trace
        through a 3-replica fleet under a replica-kill ChaosPlan —
        zero dropped requests, outputs bit-identical (digest) to the
        chaos-free run, counter law exact under churn, memory bounded
        by the open budget."""
        cfg = cfg_for()
        scfg = scfg_for(n_slots=16, n_pages=128)
        tcfg = TrafficConfig(
            seed=100, tenants=(
                TenantSpec("acme", cls="latency", weight=3.0,
                           n_prefixes=8, zipf_a=1.3),
                TenantSpec("globex", cls="batch", weight=1.0,
                           n_prefixes=4),
            ), vocab=16, prompt_len=16, tail_cap=3, out_cap=3,
            base_rate=48.0, diurnal_period=512, diurnal_amp=0.5,
            burst_p=0.02, burst_len=16, burst_mult=2.0,
        )
        assert tcfg.max_total_len <= scfg.max_seq
        plan = ChaosPlan(seed=17, faults=(
            Fault(site="serve/replica", p=0.002, times=8, kind="kill",
                  down_ticks=20),
            Fault(site="serve/replica", p=0.001, times=4, kind="stall",
                  down_ticks=10),
        ))
        mesh = mesh_for()

        def router(chaos):
            return FleetRouter(
                [ServeEngine(mesh, cfg, scfg) for _ in range(3)],
                rcfg=TWO_CLASSES, chaos=chaos,
            )

        N = 100_000
        chaos = run_traffic(router(plan), TraceGenerator(tcfg), N,
                            open_budget=512, max_steps=10_000_000)
        clean = run_traffic(router(None), TraceGenerator(tcfg), N,
                            open_budget=512, max_steps=10_000_000)
        assert chaos.submitted == clean.submitted == N
        assert chaos.report.dropped == 0
        assert chaos.report.kills >= 1 and chaos.report.readmitted > 0
        assert chaos.digest == clean.digest
        check_churn_law(chaos.report)
        check_churn_law(clean.report)
        assert chaos.peak_open <= 512 and clean.peak_open <= 512
        # bounded-memory tails: 100k completions through a 4096-slot
        # reservoir — sampled, not silently truncated
        for c in chaos.report.classes:
            assert not c.ttft_exact
            assert c.ttft_p50_s <= c.ttft_p99_s


class TestConfig19Regress:
    ROW = {
        "config": 19, "metric": "traffic_chaos_tokens_per_s",
        "value": 44.9, "tokens_per_s_clean": 42.4, "readmitted": 36,
        "readmitted_tokens": 153, "dropped": 0, "kills": 2,
        "stalls": 1, "replicas": 3, "requests": 96, "peak_open": 24,
        "wall_s_chaos": 4.07, "wall_s_clean": 4.32,
        "ttft_p99_s_latency": 0.62, "goodput_frac_latency": 0.887,
        "ttft_p99_s_batch": 0.61, "goodput_frac_batch": 1.0,
        "platform": "cpu",
    }

    def test_field_directions(self):
        for name in ("ttft_p99_s_latency", "ttft_p50_s_batch",
                     "dropped"):
            assert regress.direction(name) == "lower", name
        for name in ("traffic_chaos_tokens_per_s", "tokens_per_s_clean",
                     "readmitted", "readmitted_tokens",
                     "goodput_frac_latency"):
            assert regress.direction(name) == "higher", name
        for name in ("kills", "stalls", "requests", "peak_open",
                     "wall_s_chaos", "wall_s_clean", "replicas"):
            assert name in regress._SKIP, name

    def test_canned_row_gates(self):
        base = regress.index_rows([self.ROW])
        ok = regress.index_rows([dict(self.ROW, value=43.0)])
        assert not regress.has_regression(
            regress.compare(base, ok, noise=0.1)
        )
        bad = regress.index_rows([dict(
            self.ROW, dropped=3, readmitted=0,
            goodput_frac_latency=0.40,
        )])
        bad_fields = {(f.metric, f.field) for f in
                      regress.compare(base, bad, noise=0.1)
                      if f.status == "regressed"}
        assert ("traffic_chaos_tokens_per_s", "dropped") in bad_fields
        assert ("traffic_chaos_tokens_per_s", "readmitted") in bad_fields
        assert ("traffic_chaos_tokens_per_s",
                "goodput_frac_latency") in bad_fields
        # raw walls are context, never gated
        wild = regress.index_rows([dict(self.ROW, wall_s_chaos=400.0)])
        assert not regress.has_regression(
            regress.compare(base, wild, noise=0.1)
        )

    def test_cli_subprocess_proof(self, tmp_path):
        """The acceptance gate as a subprocess: config-19 clean pair
        exits 0, injected dropped/goodput regression exits 1."""

        def write(name, rows):
            p = str(tmp_path / name)
            with open(p, "w") as f:
                for r in rows:
                    f.write(json.dumps(r) + "\n")
            return p

        base = write("base.json", [self.ROW])
        good = write("good.json", [dict(self.ROW, value=46.0,
                                        ttft_p99_s_latency=0.70)])
        bad = write("bad.json", [dict(self.ROW, dropped=5,
                                      goodput_frac_latency=0.35)])
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-m", "tpuscratch.obs.regress", base, good],
            capture_output=True, text=True, env=env, timeout=120,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        r = subprocess.run(
            [sys.executable, "-m", "tpuscratch.obs.regress", base, bad],
            capture_output=True, text=True, env=env, timeout=120,
        )
        assert r.returncode == 1, r.stdout + r.stderr
        assert "REGRESSED" in r.stdout


class TestCorrelatedDomains:
    """ISSUE 18: ``Fault.domain`` — one seeded ignition takes out every
    member of a fault domain (a rack) in the SAME tick."""

    def test_domain_fires_every_member_same_tick(self):
        plan = ChaosPlan(seed=3, faults=(
            Fault(site="serve/replica", at=(4,), domain=(0, 1),
                  kind="kill", times=1),
        ))
        fired = {(t, k): plan.should_fire("serve/replica", index=t,
                                          key=k) is not None
                 for t in (3, 4, 5) for k in (0, 1, 2)}
        # both rack members at tick 4, nobody else, ever
        assert fired[(4, 0)] and fired[(4, 1)]
        assert not any(v for (t, k), v in fired.items()
                       if not (t == 4 and k in (0, 1)))
        # ONE ignition consumed ONE budget, not one per member
        assert plan._left == [0]

    def test_domain_members_share_one_ignition_budget(self):
        # with times=1 a per-member budget would let only the first
        # member die; the whole rack must go down
        plan = ChaosPlan(seed=3, faults=(
            Fault(site="serve/replica", at=(2,), domain=(0, 1, 2),
                  kind="kill", times=1),
        ))
        assert all(
            plan.should_fire("serve/replica", index=2, key=k) is not None
            for k in (0, 1, 2)
        )

    def test_rack_domains_helper(self):
        assert rack_domains(5, 2) == ((0, 1), (2, 3), (4,))
        assert rack_domains(4, 4) == ((0, 1, 2, 3),)
        with pytest.raises(ValueError, match="rack_size"):
            rack_domains(4, 0)

    def test_key_and_domain_mutually_exclusive(self):
        with pytest.raises(ValueError, match="mutually exclusive"):
            Fault(site="serve/replica", key=0, domain=(0, 1))

    def test_rack_kill_readmits_both_replicas_work(self):
        """A 2-replica rack killed out of a 3-replica fleet mid-drain:
        both die in the same tick, everything re-admits through the
        survivor, outputs bit-identical to the kill-free fleet."""
        reqs = tenant_requests(8, max_new=3)
        clean = fleet(3, rcfg=TWO_CLASSES).run(
            [("batch", r) for r in reqs])
        plan = ChaosPlan(seed=5, faults=(
            Fault(site="serve/replica", at=(1,), domain=(0, 1),
                  kind="kill", down_ticks=6),
        ))
        chaos = fleet(3, rcfg=TWO_CLASSES, chaos=plan).run(
            [("batch", r) for r in reqs])
        assert chaos.kills == 2           # the whole rack, one tick
        assert chaos.outputs == clean.outputs
        assert chaos.dropped == 0
        check_churn_law(chaos)


class TestReplayJsonl:
    """ISSUE 18 satellite: dump_jsonl / replay_jsonl round trip."""

    def test_round_trip_digest_identical(self, tmp_path):
        gen = TraceGenerator(trace_cfg(seed=4))
        p = tmp_path / "trace.jsonl"
        assert gen.dump_jsonl(p, 20) == 20
        rp = replay_jsonl(p)
        assert rp.digest(20) == gen.digest(20)
        assert [i.encode() for i in rp.stream(20)] == \
            [i.encode() for i in gen.stream(20)]

    def test_replayed_run_bit_identical(self, tmp_path):
        gen = TraceGenerator(trace_cfg(seed=4))
        p = tmp_path / "trace.jsonl"
        gen.dump_jsonl(p, 12)
        a = run_traffic(fleet(2, rcfg=TWO_CLASSES), gen, 12,
                        open_budget=8)
        b = run_traffic(fleet(2, rcfg=TWO_CLASSES), replay_jsonl(p), 12,
                        open_budget=8)
        assert a.digest == b.digest
        assert a.submitted == b.submitted == 12

    def test_replay_prefix_and_blank_lines(self, tmp_path):
        gen = TraceGenerator(trace_cfg(seed=4))
        p = tmp_path / "trace.jsonl"
        gen.dump_jsonl(p, 8)
        with open(p, "a") as f:
            f.write("\n")                 # trailing blank tolerated
        rp = replay_jsonl(p)
        assert len(rp.items) == 8
        # a prefix read of a longer log is just the shorter trace
        assert rp.digest(5) == gen.digest(5)


SHED_TWO = RouterConfig(classes=(
    SLOClass("latency", target="ttft"),
    SLOClass("batch", shed_after_s=2.0, max_queue=1),
), tick_s=1.0)


class TestClosedLoop:
    """ISSUE 18: the closed-loop client harness — think-time clients,
    bounded concurrency, seeded retry."""

    def test_repeat_runs_bit_identical(self):
        def go():
            tr = run_traffic_closed(
                fleet(2, rcfg=TWO_CLASSES), TraceGenerator(trace_cfg()),
                12, spec=ClosedLoopSpec(concurrency=2, think_p=0.6))
            return (tr.digest, tr.submitted, tr.ticks, tr.sheds)
        assert go() == go()

    def test_open_set_bounded_by_client_population(self):
        tr = run_traffic_closed(
            fleet(1, rcfg=TWO_CLASSES), TraceGenerator(trace_cfg()), 10,
            spec=ClosedLoopSpec(concurrency=1, think_p=0.6))
        assert tr.peak_open <= 2          # 2 tenants x 1 client each
        assert tr.submitted == 10 and tr.abandoned == 0

    def test_quota_split_is_exact_and_proportional(self):
        tenants = trace_cfg().tenants
        spec = ClosedLoopSpec(concurrency=4,
                              per_tenant=(("globex", 12),))
        q = _tenant_quotas(tenants, spec, 100)
        assert sum(q.values()) == 100
        assert q["acme"] == 25 and q["globex"] == 75

    def test_retry_storm_conserves_requests(self):
        """Sheds either retry (same rid — same tokens) or abandon;
        every request ends exactly one way and the per-tick law holds
        throughout (asserted inside the harness)."""
        spec = ClosedLoopSpec(
            concurrency=1, per_tenant=(("globex", 6),), think_p=0.9,
            retry=RetryPolicy(max_attempts=2, backoff_ticks=1,
                              mult=1.0, jitter_ticks=0))
        tr = run_traffic_closed(
            fleet(1, rcfg=SHED_TWO), TraceGenerator(trace_cfg()), 16,
            spec=spec)
        assert tr.sheds > 0               # the storm materialized
        assert tr.retries > 0             # and the clients fought back
        # every shed leg was either re-submitted or terminal
        assert tr.sheds == tr.retries + tr.abandoned
        assert tr.submitted == 16

    def test_shed_exclusion_pairs_with_uncommitted_fleet(self):
        """The digest pairing law: a storm run's non-shed completions
        are bit-identical to the same trace on a fleet that never
        sheds, once the storm's terminally-shed rids are excluded."""
        gen = TraceGenerator(trace_cfg())
        spec = ClosedLoopSpec(concurrency=1,
                              per_tenant=(("globex", 6),), think_p=0.9)
        storm = run_traffic_closed(fleet(1, rcfg=SHED_TWO), gen, 16,
                                   spec=spec)
        assert storm.abandoned > 0        # retry=None: sheds terminal
        clean = run_traffic_closed(
            fleet(3, rcfg=TWO_CLASSES), gen, 16, spec=spec,
            exclude_rids=frozenset(storm.shed_rids))
        assert clean.sheds == 0
        assert clean.digest == storm.digest

    def test_validates(self):
        with pytest.raises(ValueError, match="concurrency"):
            ClosedLoopSpec(concurrency=0)
        with pytest.raises(ValueError, match="think_p"):
            ClosedLoopSpec(think_p=0.0)
        with pytest.raises(ValueError, match="max_attempts"):
            RetryPolicy(max_attempts=0)
        with pytest.raises(ValueError, match="jitter"):
            RetryPolicy(jitter_ticks=-1)


class TestCounterLawSweep:
    """ISSUE 18 satellite: the seeded property sweep — open/closed x
    shed on/off x chaos on/off.  The harnesses assert the request law
    ``submitted == finished + shed + open`` at EVERY tick and the token
    law ``prefill + shared == submitted + readmitted`` at drain
    (``check_law=True``); this pins the end state on every combo."""

    @pytest.mark.parametrize("closed", [False, True])
    @pytest.mark.parametrize("shed", [False, True])
    @pytest.mark.parametrize("chaos", [False, True])
    def test_laws_hold(self, closed, shed, chaos):
        rcfg = SHED_TWO if shed else TWO_CLASSES
        plan = ChaosPlan(seed=13, faults=(
            Fault(site="serve/replica", at=(2,), key=0, kind="kill",
                  down_ticks=3),
        )) if chaos else None
        router = fleet(2, rcfg=rcfg, chaos=plan)
        gen = TraceGenerator(trace_cfg(seed=21))
        if closed:
            tr = run_traffic_closed(
                router, gen, 12,
                spec=ClosedLoopSpec(
                    concurrency=2, think_p=0.7,
                    retry=RetryPolicy(max_attempts=2, backoff_ticks=1,
                                      mult=1.0, jitter_ticks=0)))
        else:
            tr = run_traffic(router, gen, 12, open_budget=6)
        assert router.open_requests == 0
        assert router.submitted_requests == \
            router.finished_requests + router.shed_requests
        check_churn_law(tr.report)
        if chaos:
            assert tr.report.kills == 1


class TestConfig20Regress:
    ROW = {
        "config": 20, "metric": "overload_survival_tokens_per_s",
        "value": 59.0, "tokens_per_s_clean": 39.5, "sheds": 7,
        "sheds_clean": 0, "retries": 7, "abandoned": 0,
        "shed_frac": 0.0, "readmitted": 8, "dropped": 0, "kills": 2,
        "replicas": 3, "requests": 160, "peak_open": 16,
        "completed_latency": 40, "completed_batch": 120,
        "ticks_storm": 42, "ticks_clean": 18, "wall_s_storm": 5.17,
        "wall_s_clean": 7.72, "ttft_p99_s_batch": 3.99,
        "goodput_frac_batch": 0.932, "sheds_batch": 7,
        "shed_frac_batch": 0.055, "ttft_p99_s_latency": 1.76,
        "goodput_frac_latency": 0.951, "sheds_latency": 0,
        "shed_frac_latency": 0.0, "platform": "cpu",
    }

    def test_field_directions(self):
        for name in ("sheds", "sheds_latency", "sheds_batch",
                     "shed_frac", "shed_frac_batch", "retries",
                     "abandoned", "dropped", "ttft_p99_s_latency"):
            assert regress.direction(name) == "lower", name
        for name in ("overload_survival_tokens_per_s",
                     "goodput_frac_latency", "readmitted"):
            assert regress.direction(name) == "higher", name
        for name in ("kills", "requests", "peak_open", "replicas",
                     "wall_s_storm", "wall_s_clean", "ticks_storm",
                     "ticks_clean", "completed_latency",
                     "completed_batch"):
            assert name in regress._SKIP, name

    def test_canned_row_gates(self):
        base = regress.index_rows([self.ROW])
        ok = regress.index_rows([dict(self.ROW, value=57.0)])
        assert not regress.has_regression(
            regress.compare(base, ok, noise=0.1)
        )
        bad = regress.index_rows([dict(
            self.ROW, sheds_latency=3, dropped=2, retries=25,
        )])
        bad_fields = {(f.metric, f.field) for f in
                      regress.compare(base, bad, noise=0.1)
                      if f.status == "regressed"}
        m = "overload_survival_tokens_per_s"
        assert (m, "sheds_latency") in bad_fields  # zero-top-shed gate
        assert (m, "dropped") in bad_fields
        assert (m, "retries") in bad_fields
        # workload shape and raw walls never gate
        wild = regress.index_rows([dict(self.ROW, wall_s_storm=500.0,
                                        ticks_storm=9999)])
        assert not regress.has_regression(
            regress.compare(base, wild, noise=0.1)
        )

    def test_cli_subprocess_proof(self, tmp_path):
        """The acceptance gate as a subprocess: config-20 clean pair
        exits 0, injected top-class-shed/drop regression exits 1."""

        def write(name, rows):
            p = str(tmp_path / name)
            with open(p, "w") as f:
                for r in rows:
                    f.write(json.dumps(r) + "\n")
            return p

        base = write("base.json", [self.ROW])
        good = write("good.json", [dict(self.ROW, value=61.0,
                                        ttft_p99_s_latency=1.9)])
        bad = write("bad.json", [dict(self.ROW, sheds_latency=4,
                                      dropped=3)])
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-m", "tpuscratch.obs.regress", base, good],
            capture_output=True, text=True, env=env, timeout=120,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        r = subprocess.run(
            [sys.executable, "-m", "tpuscratch.obs.regress", base, bad],
            capture_output=True, text=True, env=env, timeout=120,
        )
        assert r.returncode == 1, r.stdout + r.stderr
        assert "REGRESSED" in r.stdout


@pytest.mark.overload
class TestOverloadAcceptance:
    """The ISSUE-18 acceptance scenario: diurnal burst crest + rack
    kill + retry storm, survived with a bounded open queue, zero
    top-class sheds while the batch class sheds, and bit-identical
    digests for non-shed requests against the uncommitted fleet."""

    @pytest.mark.slow
    def test_full_storm_survival(self):
        cfg, scfg, mesh = cfg_for(), scfg_for(), mesh_for()
        setup = overload_setup(False, scfg.vocab)
        # the kill tick really sits inside a seeded burst window — the
        # storm hits at the crest by construction, not by luck
        assert TraceGenerator(setup["tcfg"]).burst_active(
            setup["kill_tick"])
        storm = bench_overload(mesh, cfg, scfg, setup, storm=True)
        again = bench_overload(mesh, cfg, scfg, setup, storm=True)
        assert again["digest"] == storm["digest"]
        assert again["shed_rids"] == storm["shed_rids"]
        assert again["sheds"] == storm["sheds"]
        clean = bench_overload(mesh, cfg, scfg, setup, storm=False,
                               exclude_rids=frozenset(storm["shed_rids"]))
        assert clean["digest"] == storm["digest"]
        # survival facts (bench_overload asserts them; pin them here)
        assert storm["kills"] == len(setup["rack"])
        assert storm["dropped"] == 0 and clean["dropped"] == 0
        assert storm["sheds"] > 0 and storm["retries"] > 0
        assert storm["classes"]["latency"]["sheds"] == 0
        assert clean["sheds"] == 0
        # bounded top-class tail: the latency p99 under the storm
        # stays within 4x the uncommitted fleet's
        assert storm["classes"]["latency"]["ttft_p99_s"] <= \
            4.0 * max(clean["classes"]["latency"]["ttft_p99_s"], 1e-3)

    @pytest.mark.slow
    def test_record_check_subprocess_proof(self, tmp_path):
        """``record.py --check`` wired to config 20: a self-pair exits
        0; an injected top-class-shed/drop regression exits 1."""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        base = str(tmp_path / "base.json")
        r = subprocess.run(
            [sys.executable, "-m", "tpuscratch.bench.record",
             "--configs", "20", "--json", base],
            capture_output=True, text=True, env=env, timeout=560,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        with open(base) as f:
            row = json.loads(f.readline())
        bad = str(tmp_path / "bad.json")
        with open(bad, "w") as f:
            f.write(json.dumps(dict(row, sheds_latency=5, dropped=3,
                                    sheds=0, retries=0)) + "\n")
        r = subprocess.run(
            [sys.executable, "-m", "tpuscratch.bench.record",
             "--configs", "20", "--check", base],
            capture_output=True, text=True, env=env, timeout=560,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        r = subprocess.run(
            [sys.executable, "-m", "tpuscratch.bench.record",
             "--configs", "20", "--check", bad],
            capture_output=True, text=True, env=env, timeout=560,
        )
        assert r.returncode == 1, r.stdout + r.stderr
