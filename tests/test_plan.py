"""ShardingPlan-composed 4D parallelism + comm/compute overlap (ISSUE 7
acceptance anchors):

- plan axes validate against the live mesh at construction (the error
  names the missing axis — no deep shard_map failure);
- a pp=1 plan's ``train()`` trajectory is BIT-identical to the legacy
  path (params and losses), with the overlap schedule on — the
  decomposed sync is pure scheduling, never arithmetic;
- a pp=2 plan trains on a 2x2 CPU mesh with ZeRO moments sharded over
  dp, tracking the pp=1 trajectory to f32 tolerance (exactly when the
  microbatch-dependent MoE aux term is removed);
- the obs ledger proves the overlap claim statically: the decomposed
  schedule changes the collective COUNT but not the total wire bytes;
- guard/rollback (ft) works under a pp plan, and a mismatched-plan
  resume raises the CommError contract.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuscratch.models.trainer import train
from tpuscratch.models.transformer import (
    TransformerConfig,
    init_params,
    nonexpert_size,
    stack_layers,
)
from tpuscratch.models.zero import (
    init_plan_zero_state,
    init_zero_adam_state,
    put_plan_state,
    train_step_plan,
    train_step_zero,
    zero_flat_size,
)
from tpuscratch.obs import ledger as obs_ledger
from tpuscratch.parallel import ShardingPlan
from tpuscratch.runtime.errors import CommError
from tpuscratch.runtime.mesh import make_mesh

pytestmark = pytest.mark.plan


def _cfg(n_experts=2, n_layers=2, aux_coef=0.01):
    return TransformerConfig(
        d_model=16, n_heads=2, n_experts=n_experts, d_ff=32,
        n_layers=n_layers, capacity_factor=2.0, aux_coef=aux_coef,
    )


def _mesh3(dp, sp, pp):
    return make_mesh((dp, sp, pp), ("dp", "sp", "pp"),
                     jax.devices()[:dp * sp * pp])


def _data(batch=4, seq=16, d=16, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((batch, seq, d)), jnp.float32)
    y = jnp.asarray(rng.standard_normal((batch, seq, d)), jnp.float32)
    return x, y


def _leaves_equal(a, b):
    return all(
        np.array_equal(np.asarray(p), np.asarray(q))
        for p, q in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


class TestPlanConstruction:
    def test_missing_axis_named_in_error(self, devices):
        mesh = make_mesh((2, 2), ("dp", "sp"), jax.devices()[:4])
        with pytest.raises(ValueError, match="pp='stage'"):
            ShardingPlan(mesh, pp="stage")
        with pytest.raises(ValueError, match="dp='data'"):
            ShardingPlan(mesh, dp="data")

    def test_n_micro_needs_pp_axis(self, devices):
        mesh = make_mesh((2, 2), ("dp", "sp"), jax.devices()[:4])
        with pytest.raises(ValueError, match="pp axis"):
            ShardingPlan(mesh, n_micro=2)

    def test_spec_resolves_logical_axes(self, devices):
        from jax.sharding import PartitionSpec as P

        mesh = _mesh3(2, 1, 2)
        plan = ShardingPlan(mesh, pp="pp")
        assert plan.spec("pp", "ep") == P("pp", "dp")  # ep rides dp
        assert plan.spec(("pp", "dp")) == P(("pp", "dp"))
        assert plan.spec(None, "sp") == P(None, "sp")
        assert plan.dp_size == 2 and plan.pp_size == 2
        assert not ShardingPlan(mesh, pp="pp", n_micro=1).pipelined or \
            plan.pp_size > 1  # pp=2 => pipelined
        assert plan.pipelined

    def test_tree_spec_maps_paths(self, devices):
        from jax.sharding import PartitionSpec as P

        mesh = _mesh3(2, 1, 2)
        plan = ShardingPlan(mesh, pp="pp")
        tree = {"a": jnp.zeros((2, 2)), "b": {"c": jnp.zeros((2,))}}
        spec = plan.tree_spec(
            tree,
            lambda path, leaf: ("pp",) if path[0].key == "a" else (),
        )
        assert spec == {"a": P("pp"), "b": {"c": P()}}

    def test_describe_normalizes_degenerate_plan(self, devices):
        mesh = _mesh3(2, 2, 1)
        plan = ShardingPlan(mesh, pp="pp", n_micro=1)
        assert plan.describe() == {"dp": 2, "sp": 2, "pp": 1,
                                   "n_micro": 1}
        assert not plan.pipelined


class TestPlanTrainer:
    def test_pp1_plan_bit_identical_to_legacy(self, devices, tmp_path):
        """The pp=1 plan routes to the EXACT legacy program (overlap on
        by default — the decomposed sync is bit-transparent), so losses
        AND params match bit for bit."""
        cfg = _cfg()
        kw = dict(save_every=3, lr=0.005, seed=5, optimizer="adam",
                  zero=True)
        legacy_mesh = make_mesh((2, 2), ("dp", "sp"), jax.devices()[:4])
        p_leg, rep_leg = train(legacy_mesh, cfg, steps=6,
                               ckpt_dir=str(tmp_path / "leg"), **kw)
        mesh = _mesh3(2, 2, 1)
        plan = ShardingPlan(mesh, pp="pp", n_micro=1)
        p_plan, rep_plan = train(mesh, cfg, steps=6,
                                 ckpt_dir=str(tmp_path / "plan"),
                                 plan=plan, **kw)
        assert rep_leg.losses == rep_plan.losses
        assert _leaves_equal(p_leg, p_plan)

    def test_pp2_matches_pp1_trajectory(self, devices, tmp_path):
        """pp=2 vs pp=1 on the same global batch: with the
        microbatch-dependent MoE aux term off, the only difference is
        schedule reassociation — f32 tolerance."""
        cfg = _cfg(aux_coef=0.0)
        kw = dict(save_every=3, lr=0.005, seed=5, optimizer="adam",
                  zero=True, batch=4, seq=16)
        mesh1 = _mesh3(2, 2, 1)
        _, rep1 = train(mesh1, cfg, steps=6,
                        ckpt_dir=str(tmp_path / "p1"),
                        plan=ShardingPlan(mesh1, pp="pp"), **kw)
        mesh2 = _mesh3(2, 1, 2)
        plan2 = ShardingPlan(mesh2, pp="pp", n_micro=2)
        _, rep2 = train(mesh2, cfg, steps=6,
                        ckpt_dir=str(tmp_path / "p2"), plan=plan2, **kw)
        np.testing.assert_allclose(rep2.losses, rep1.losses, rtol=1e-4,
                                   atol=1e-6)
        assert rep2.losses[-1] < rep2.losses[0]

    def test_pp2_zero_trains_and_resumes_bit_identical(self, devices,
                                                       tmp_path):
        """THE acceptance row: train(plan=...) with pp=2 on a 2x2 CPU
        mesh (dp=2 x pp=2), ZeRO moments sharded over dp, resuming a
        killed run bit-identically."""
        cfg = _cfg()
        mesh = _mesh3(2, 1, 2)
        plan = ShardingPlan(mesh, pp="pp", n_micro=2)
        kw = dict(save_every=3, lr=0.005, seed=5, optimizer="adam",
                  zero=True, batch=4, seq=16, plan=plan)
        straight, rep = train(mesh, cfg, steps=6,
                              ckpt_dir=str(tmp_path / "s"), **kw)
        assert rep.losses[-1] < rep.losses[0]
        inter = str(tmp_path / "i")
        train(mesh, cfg, steps=3, ckpt_dir=inter, **kw)
        resumed, rep2 = train(mesh, cfg, steps=6, ckpt_dir=inter, **kw)
        assert rep2.steps_run == 3
        assert _leaves_equal(straight, resumed)

    def test_plan_zero_moments_shard_over_dp(self, devices):
        """Under a pp plan the flat Adam moments live (pp, dp)-sharded:
        each rank holds 1/(|pp|*|dp|) of the non-expert moment
        elements."""
        cfg = _cfg()
        mesh = _mesh3(2, 1, 2)
        plan = ShardingPlan(mesh, pp="pp", n_micro=2)
        stacked = stack_layers(init_params(0, cfg))
        state = put_plan_state(init_plan_zero_state(stacked, plan),
                               plan, cfg)
        per_stage = nonexpert_size(stacked) // 2
        flat = zero_flat_size(per_stage, 2)
        for leaf in (state["mu_flat"], state["nu_flat"]):
            assert leaf.shape == (2 * flat,)
            shard_shapes = {s.data.shape for s in leaf.addressable_shards}
            assert shard_shapes == {(flat // 2,)}

    def test_mismatched_plan_resume_raises_commerror(self, devices,
                                                     tmp_path):
        cfg = _cfg()
        mesh = _mesh3(2, 1, 2)
        plan = ShardingPlan(mesh, pp="pp", n_micro=2)
        kw = dict(save_every=2, lr=0.005, seed=5, optimizer="adam",
                  batch=4, seq=16)
        d = str(tmp_path / "mm")
        train(mesh, cfg, steps=2, ckpt_dir=d, plan=plan, **kw)
        # non-zero run so the plan gate itself (not the ZeRO mesh_shape
        # gate) is what fires on the legacy re-invocation
        mesh1 = _mesh3(2, 1, 1)
        with pytest.raises(CommError, match="plan"):
            train(mesh1, cfg, steps=4, ckpt_dir=d,
                  plan=ShardingPlan(mesh1, pp="pp"), **kw)
        # and a legacy (pre-plan) checkpoint refuses a pipelined resume
        d2 = str(tmp_path / "legacy")
        legacy_mesh = make_mesh((2, 1), ("dp", "sp"), jax.devices()[:2])
        train(legacy_mesh, cfg, steps=2, ckpt_dir=d2, **kw)
        import json
        import pathlib

        for man in pathlib.Path(d2).glob("step_*/manifest.json"):
            m = json.loads(man.read_text())
            m["metadata"].pop("plan")
            man.write_text(json.dumps(m))
        with pytest.raises(CommError, match="plan"):
            train(mesh, cfg, steps=4, ckpt_dir=d2, plan=plan, **kw)


class TestOverlapLedger:
    def test_overlap_changes_schedule_not_wire_bytes(self, devices):
        """The comm claim, statically: the decomposed schedule holds
        ``blocks`` reduce-scatters and ``blocks`` all-gathers where the
        serial schedule holds one of each, at EXACTLY the same total
        wire bytes (k transfers of shard/k)."""
        cfg = _cfg()
        mesh = make_mesh((2, 2), ("dp", "sp"), jax.devices()[:4])
        params = init_params(0, cfg)
        x = jnp.zeros((4, 16, 16), jnp.float32)
        leds = {}
        for blocks in (0, 4):
            leds[blocks] = obs_ledger.analyze(
                train_step_zero(mesh, cfg, donate=False,
                                overlap_blocks=blocks),
                params, init_zero_adam_state(params, 2), x, x,
            )
        c0, c4 = leds[0].counts(), leds[4].counts()
        assert c0.get("reduce-scatter") == 1 and c0.get("all-gather") == 1
        assert c4.get("reduce-scatter") == 4 and c4.get("all-gather") == 4
        w0, w4 = leds[0].wire_bytes(), leds[4].wire_bytes()
        assert w4["reduce-scatter"] == w0["reduce-scatter"]
        assert w4["all-gather"] == w0["all-gather"]
        assert leds[4].total_wire_bytes() == leds[0].total_wire_bytes()

    def test_pp_plan_overlap_wire_bytes_equal(self, devices):
        """Same proof through the pipelined plan step: per-stage chains
        decompose, bytes stay put."""
        cfg = _cfg()
        mesh = _mesh3(2, 1, 2)
        stacked = stack_layers(init_params(0, cfg))
        x = jnp.zeros((4, 16, 16), jnp.float32)
        leds = {}
        for ov in (False, True):
            plan = ShardingPlan(mesh, pp="pp", n_micro=2, overlap=ov)
            leds[ov] = obs_ledger.analyze(
                train_step_plan(plan, cfg, donate=False), stacked,
                init_plan_zero_state(stacked, plan), x, x,
            )
        assert (leds[True].counts()["reduce-scatter"]
                > leds[False].counts()["reduce-scatter"])
        assert (leds[True].total_wire_bytes()
                == leds[False].total_wire_bytes())

    def test_overlap_is_bit_transparent(self, devices):
        """Overlap on/off produce BIT-identical params, losses, and
        moments — the strided block layout preserves every rank's
        elements, so the ablation isolates pure scheduling."""
        from tpuscratch.models.zero import put_zero_state

        cfg = _cfg()
        mesh = make_mesh((2, 2), ("dp", "sp"), jax.devices()[:4])
        x, y = _data()

        def run(blocks):
            params = init_params(0, cfg)
            opt = put_zero_state(init_zero_adam_state(params, 2), mesh,
                                 cfg)
            fn = train_step_zero(mesh, cfg, lr=0.01, donate=False,
                                 overlap_blocks=blocks)
            losses = []
            for _ in range(3):
                params, opt, loss = fn(params, opt, x, y)
                losses.append(float(loss))
            return losses, params, opt

        l0, p0, o0 = run(0)
        l4, p4, o4 = run(4)
        assert l0 == l4
        assert _leaves_equal(p0, p4)
        assert _leaves_equal(o0, o4)


class TestPlanGuard:
    def test_guarded_pp_step_skips_nan_and_freezes_state(self, devices):
        """The ft guard composes with the pipelined plan: a NaN batch
        skips the step with the stacked params AND the (pp, dp)-sharded
        moments passed through bit-identically."""
        from tpuscratch.ft.guards import STATUS_OK, STATUS_SKIPPED

        cfg = _cfg()
        mesh = _mesh3(2, 1, 2)
        plan = ShardingPlan(mesh, pp="pp", n_micro=2)
        x, y = _data()
        stacked = stack_layers(init_params(0, cfg))
        opt = put_plan_state(init_plan_zero_state(stacked, plan), plan,
                             cfg)
        fn = train_step_plan(plan, cfg, lr=0.01, guard=(1e30, 1e30),
                             donate=False)
        nan_ref = jnp.asarray(float("nan"), jnp.float32)
        new_p, new_o, loss, gnorm, st = fn(stacked, opt, x, y, nan_ref)
        assert int(st) == STATUS_OK
        assert float(gnorm) > 0 and np.isfinite(float(loss))
        assert not _leaves_equal(new_p, stacked)

        bad = x.at[0, 0, 0].set(jnp.nan)
        p2, o2, loss2, _, st2 = fn(stacked, opt, bad, y, nan_ref)
        assert int(st2) == STATUS_SKIPPED
        assert _leaves_equal(p2, stacked)
        assert _leaves_equal(o2, opt)

    @pytest.mark.chaos
    def test_guard_rollback_under_pp_plan(self, devices, tmp_path):
        """Rollback under a pp plan: a chaos-poisoned chunk rolls the
        stacked params + sharded moments back to the last checkpoint
        and the run completes, bit-identical to the fault-free run."""
        from tpuscratch.ft.chaos import ChaosPlan, Fault
        from tpuscratch.ft.guards import GuardPolicy

        cfg = _cfg()
        mesh = _mesh3(2, 1, 2)
        plan = ShardingPlan(mesh, pp="pp", n_micro=2)
        kw = dict(save_every=4, lr=0.005, seed=7, optimizer="adam",
                  zero=True, batch=4, seq=16, plan=plan)
        clean, _ = train(mesh, cfg, steps=8,
                         ckpt_dir=str(tmp_path / "clean"), **kw)
        chaos = ChaosPlan(seed=3, faults=[
            Fault(site="train/grad", at=[5, 6], times=2, kind="nan"),
        ])
        guard = GuardPolicy(max_skips=1, max_rollbacks=2)
        healed, rep = train(mesh, cfg, steps=8,
                            ckpt_dir=str(tmp_path / "chaos"),
                            chaos=chaos, guard=guard, **kw)
        assert rep.rollbacks >= 1
        assert _leaves_equal(clean, healed)


def test_bench_program_runs_plan(devices):
    """The bench plumbing: the plan-composed throughput program (3-axis
    scan, in-program state) produces finite losses with overlap on and
    off, and the legacy-shaped zero program accepts overlap blocks."""
    from tpuscratch.bench.train_bench import bench_train

    cfg = _cfg()
    mesh = _mesh3(2, 1, 2)
    for ov in (False, True):
        plan = ShardingPlan(mesh, pp="pp", n_micro=2, overlap=ov)
        r = bench_train(plan=plan, cfg=cfg, batch=4, seq=16, steps=2,
                        iters=1, fence="block", optimizer="adam",
                        zero=True)
        assert r.items_per_s > 0
        assert ("ov4" if ov else "serial") in r.name
