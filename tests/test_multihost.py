"""Multi-host rendezvous, actually executed.

The reference never runs as one process: mpiexec spawns N OS processes
that rendezvous inside MPI_Init (/root/reference/mpi_pbs_sample.sh:18).
The framework's equivalent — ``initialize()``'s
``jax.distributed.initialize`` branch (runtime/context.py) — is
exercised here the same way the reference exercises multi-node MPI on
one box (SURVEY.md §4.2): two real OS processes on localhost, each
owning one virtual CPU device, meeting at a coordinator, then running a
cross-process ``psum`` whose result proves the data plane spans both.
"""

import os
import pathlib
import socket
import subprocess
import sys

import pytest

WORKER = pathlib.Path(__file__).parent / "_multihost_worker.py"
REPO = pathlib.Path(__file__).parent.parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(script: pathlib.Path, nprocs: int, timeout: float) -> list[str]:
    """Spawn nprocs worker processes on a fresh coordinator port, wait,
    assert zero exit, return each worker's combined output."""
    port = _free_port()
    env = dict(os.environ)
    # repo root importable in the workers; APPEND so the environment's
    # own entries (e.g. the axon site dir) survive
    env["PYTHONPATH"] = os.pathsep.join(
        [str(REPO)] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else [])
    )
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(port), str(rank), str(nprocs)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            cwd=str(REPO),
            env=env,
        )
        for rank in range(nprocs)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
    return outs


class TestMultiHostInitialize:
    @pytest.mark.parametrize("nprocs", [2])
    def test_two_process_rendezvous_and_psum(self, nprocs):
        outs = _run_workers(WORKER, nprocs, timeout=180)
        for rank, out in enumerate(outs):
            assert f"WORKER{rank} OK process_count={nprocs}" in out, out
            assert "psum=3.0" in out, out
        # both ranks printed the mpi1-style hello with the global view
        assert all(f"of {nprocs} on" in o for o in outs), outs


TRAIN_WORKER = pathlib.Path(__file__).parent / "_multihost_train_worker.py"


class TestMultiHostTraining:
    def test_composed_train_step_spans_two_processes(self):
        """The full dp x sp train step (ring attention + MoE all_to_all +
        grad + SGD) with the sp ring collectives crossing a REAL process
        boundary — the pod-slice training shape on localhost."""
        outs = _run_workers(TRAIN_WORKER, 2, timeout=300)
        for rank, out in enumerate(outs):
            assert f"WORKER{rank} TRAIN OK" in out, out
            assert "devices=4" in out, out
