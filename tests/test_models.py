"""Composed multi-axis training step (models/transformer.py).

The correctness anchors:
- sharding invariance: the same global batch gives the same loss on a
  1x1 mesh (no communication at all) and on a dp x sp mesh (ring
  attention hops + expert all_to_all + grad psums), up to fp reordering
  — capacity_factor is set so no token is ever dropped, making the math
  sharding-independent;
- optimization sanity: the jitted step actually descends;
- impl equivalence: flash-kernel attention hops match the dense path.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from tpuscratch.models import TransformerConfig, init_params, train_step
from tpuscratch.models.transformer import param_spec
from tpuscratch.runtime.mesh import make_mesh

B, S, D = 4, 16, 32


def cfg_for(n_experts=4, **kw):
    # capacity_factor=n_experts => per-expert capacity == local token
    # count: nothing is ever dropped, so loss is sharding-invariant
    kw.setdefault("capacity_factor", float(n_experts))
    return TransformerConfig(
        d_model=D, n_heads=2, n_experts=n_experts, d_ff=48, **kw
    )


def data(seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((B, S, D)).astype(np.float32))
    y = jnp.asarray(rng.standard_normal((B, S, D)).astype(np.float32))
    return x, y


class TestComposedTrainStep:
    @pytest.mark.parametrize("dims", [(2, 1), (1, 4), (2, 4)])
    def test_sharding_invariance(self, dims):
        # the degenerate axes matter independently: (2,1) = pure dp
        # (multi-expert-shard, no ring hops), (1,4) = pure sp (ring hops,
        # single expert shard), (2,4) = both
        cfg = cfg_for()
        x, y = data()
        params = init_params(1, cfg)

        single = train_step(
            make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1]), cfg
        )
        n = dims[0] * dims[1]
        multi = train_step(
            make_mesh(dims, ("dp", "sp"), jax.devices()[:n]), cfg
        )
        p1, l1 = single(params, x, y)
        pn, ln = multi(params, x, y)
        assert abs(float(l1) - float(ln)) < 1e-4
        for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(pn)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4
            )

    def test_loss_decreases(self):
        cfg = cfg_for()
        x, y = data(3)
        params = init_params(2, cfg)
        step = train_step(
            make_mesh((2, 4), ("dp", "sp"), jax.devices()[:8]), cfg, lr=0.05
        )
        losses = []
        for _ in range(5):
            params, loss = step(params, x, y)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0], losses

    def test_flash_hops_match_dense_forward(self):
        # the composed FORWARD must agree across impls. sp=2 keeps the
        # local sequence block >= the kernel's 8-row quantum.
        from jax.sharding import PartitionSpec as P

        from tpuscratch.comm import run_spmd
        from tpuscratch.models import model_apply

        x, _ = data(5)
        params = init_params(4, cfg_for())
        mesh = make_mesh((2, 2), ("dp", "sp"), jax.devices()[:4])
        outs = {}
        for impl in ("xla", "pallas"):
            cfg = cfg_for(attn_impl=impl)
            f = run_spmd(
                mesh,
                lambda p, v, c=cfg: model_apply(p, v, c)[0],
                (param_spec(cfg), P("dp", "sp")),
                P("dp", "sp"),
            )
            outs[impl] = np.asarray(f(params, x))
        np.testing.assert_allclose(
            outs["xla"], outs["pallas"], rtol=1e-4, atol=1e-5
        )

    @pytest.mark.parametrize("impl", ["pallas", "ulysses-pallas"])
    def test_flash_training_matches_xla(self, impl):
        # both flash training paths - ring hops with the custom-VJP ring
        # backward, and Ulysses with the differentiable kernel - must
        # produce the same train step as the dense ring path. sp=2 keeps
        # local seq blocks >= the kernel's 8-row quantum and n_heads=2
        # divisible by sp.
        x, y = data(9)
        params = init_params(8, cfg_for())
        mesh = make_mesh((2, 2), ("dp", "sp"), jax.devices()[:4])
        p_x, l_x = train_step(mesh, cfg_for(attn_impl="xla"))(params, x, y)
        p_f, l_f = train_step(mesh, cfg_for(attn_impl=impl))(params, x, y)
        assert abs(float(l_x) - float(l_f)) < 1e-4
        for a, b in zip(jax.tree.leaves(p_x), jax.tree.leaves(p_f)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4
            )

    def test_expert_divisibility_enforced(self):
        mesh = make_mesh((2, 4), ("dp", "sp"), jax.devices()[:8])
        with pytest.raises(ValueError, match="not divisible by dp"):
            train_step(mesh, cfg_for(n_experts=3))

    def test_param_spec_marks_expert_leaves(self):
        cfg = cfg_for()
        spec = param_spec(cfg)
        layer = spec["layers"][0]
        assert layer["w_in"] == jax.sharding.PartitionSpec("dp")
        assert layer["w_out"] == jax.sharding.PartitionSpec("dp")
        assert layer["wq"] == jax.sharding.PartitionSpec()

    def test_n_layers_stack(self):
        cfg = cfg_for(n_layers=2)
        x, y = data(7)
        params = init_params(6, cfg)
        assert len(params["layers"]) == 2
        step = train_step(
            make_mesh((2, 4), ("dp", "sp"), jax.devices()[:8]), cfg
        )
        _, loss = step(params, x, y)
        assert np.isfinite(float(loss))


def test_bf16_compute_dtype_trains(devices):
    """Mixed precision: bf16 forward/backward under f32 master params
    must still descend, and params must stay f32."""
    import dataclasses

    import jax

    from tpuscratch.models import TransformerConfig, init_params, train_step
    from tpuscratch.runtime.mesh import make_mesh

    mesh = make_mesh((2, 2), ("dp", "sp"))
    cfg = TransformerConfig(
        d_model=16, n_heads=2, n_experts=2, d_ff=32, capacity_factor=2.0,
        compute_dtype="bfloat16",
    )
    step = train_step(mesh, cfg, lr=0.05)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((4, 16, 16)).astype(np.float32))
    y = 0.5 * x
    params = init_params(0, cfg)
    losses = []
    for _ in range(8):
        params, loss = step(params, x, y)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    assert all(
        leaf.dtype == jnp.float32 for leaf in jax.tree.leaves(params)
    ), "master params must remain f32"


def test_adam_step_sharding_invariance(devices):
    """The Adam step is mesh-shape-invariant: 1x1 and 2x2 meshes produce
    the same params and moments (the moments genuinely shard over dp on
    the expert axis — elementwise updates compose with the sharding)."""
    import jax

    from tpuscratch.models import (
        TransformerConfig,
        init_adam_state,
        init_params,
        train_step_adam,
    )
    from tpuscratch.runtime.mesh import make_mesh

    cfg = TransformerConfig(
        d_model=16, n_heads=2, n_experts=2, d_ff=32, capacity_factor=2.0
    )
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((4, 16, 16)).astype(np.float32))
    y = 0.5 * x
    outs = []
    for dims in ((1, 1), (2, 2)):
        params = init_params(9, cfg)
        opt = init_adam_state(params)
        step = train_step_adam(make_mesh(dims, ("dp", "sp")), cfg, lr=1e-3)
        for _ in range(3):
            params, opt, loss = step(params, opt, x, y)
        outs.append((params, opt, float(loss)))
    (p1, o1, l1), (p2, o2, l2) = outs
    # looser than the SGD invariance test: Adam's m/(sqrt(v)+eps) with
    # tiny early v amplifies the f32 psum reduction-order differences
    # between mesh shapes by orders of magnitude (measured ~4e-5 on the
    # loss after 3 steps); the check is about routing, not ulp parity
    assert abs(l1 - l2) < 3e-4
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=3e-3, atol=1e-4
        )
    for a, b in zip(jax.tree.leaves(o1), jax.tree.leaves(o2)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=3e-3, atol=1e-4
        )


class TestPipelineComposedStep:
    """The 3-axis (dp x sp x stage) trainer: GPipe microbatching over
    the stage axis wrapping the dp x sp block — PP composed with the
    other three strategies, not tested alone."""

    def test_pp_stage1_micro1_equals_plain_step(self, devices):
        # degenerate schedule (1 stage, 1 microbatch) must reproduce the
        # plain dp x sp step exactly — same ops modulo the stack reshape
        from tpuscratch.models.transformer import (
            stack_layers, train_step_pp, unstack_layers,
        )

        cfg = cfg_for(n_layers=2)
        x, y = data()
        params = init_params(5, cfg)
        plain = train_step(
            make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1]), cfg
        )
        pp = train_step_pp(
            make_mesh((1, 1, 1), ("dp", "sp", "stage"), jax.devices()[:1]),
            cfg, n_micro=1,
        )
        p1, l1 = plain(params, x, y)
        ps, ls = pp(stack_layers(params), x, y)
        assert abs(float(l1) - float(ls)) < 1e-6
        pu = unstack_layers(jax.tree.map(np.asarray, ps))
        for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(pu)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            )

    @pytest.mark.parametrize("dims", [(2, 2, 2), (1, 2, 2), (2, 1, 2)])
    def test_pp_sharding_invariance(self, devices, dims):
        # same global batch, same microbatch count: the 1x1x1 and the
        # dp x sp x stage meshes must land the same loss and params
        from tpuscratch.models.transformer import stack_layers, train_step_pp

        cfg = cfg_for(n_layers=2)
        x, y = data(2)
        stacked = stack_layers(init_params(6, cfg))
        single = train_step_pp(
            make_mesh((1, 1, 1), ("dp", "sp", "stage"), jax.devices()[:1]),
            cfg, n_micro=2,
        )
        n = dims[0] * dims[1] * dims[2]
        multi = train_step_pp(
            make_mesh(dims, ("dp", "sp", "stage"), jax.devices()[:n]),
            cfg, n_micro=2,
        )
        p1, l1 = single(stacked, x, y)
        pn, ln = multi(stacked, x, y)
        # the stage axis is BIT-identical invariant (measured: 1x1x1 ==
        # 1x1x2, 2x2x1 == 2x2x2); the residual is the dp/sp
        # routing-group nonlinearity of the MoE aux loss (smaller token
        # groups per router call), the same effect the plain step's
        # invariance test absorbs at 1e-4 — microbatching halves the
        # groups again, hence the slightly wider band
        assert abs(float(l1) - float(ln)) < 5e-4, (float(l1), float(ln))
        # atol 5e-4: the gate's aux-loss gradient differentiates through
        # per-group token fractions, so smaller routing groups shift it
        # by a few 1e-4 in absolute terms (tiny vs the 0.02-scale gate).
        # The stage axis itself is BIT-identical invariant (asserted by
        # the dryrun check at atol 1e-5); the band here absorbs only the
        # dp/sp group effects the plain invariance test also absorbs
        for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(pn)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=5e-4
            )

    def test_pp_loss_decreases(self, devices):
        from tpuscratch.models.transformer import stack_layers, train_step_pp

        cfg = cfg_for(n_layers=2)
        x, y = data(3)
        stacked = stack_layers(init_params(7, cfg))
        step = train_step_pp(
            make_mesh((2, 2, 2), ("dp", "sp", "stage"), jax.devices()[:8]),
            cfg, lr=0.05, n_micro=2,
        )
        losses = []
        for _ in range(4):
            stacked, loss = step(stacked, x, y)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0], losses

    def test_pp_rejects_indivisible_layers(self, devices):
        from tpuscratch.models.transformer import train_step_pp

        cfg = cfg_for(n_layers=3)
        with pytest.raises(ValueError, match="n_layers"):
            train_step_pp(
                make_mesh((1, 1, 2), ("dp", "sp", "stage"),
                          jax.devices()[:2]), cfg,
            )


class TestPipelineAdam:
    """Adam on the 3-axis step: stacked moments shard like the stacked
    params; the degenerate schedule must reproduce the plain dp x sp
    Adam step exactly."""

    def test_pp_adam_stage1_micro1_equals_plain_adam(self, devices):
        from tpuscratch.models.transformer import (
            init_adam_state, stack_layers, train_step_adam,
            train_step_pp_adam, unstack_layers,
        )

        cfg = cfg_for(n_layers=2)
        x, y = data()
        params = init_params(8, cfg)
        plain = train_step_adam(
            make_mesh((1, 1), ("dp", "sp"), jax.devices()[:1]), cfg
        )
        pp = train_step_pp_adam(
            make_mesh((1, 1, 1), ("dp", "sp", "stage"), jax.devices()[:1]),
            cfg, n_micro=1,
        )
        p1, o1, l1 = plain(params, init_adam_state(params), x, y)
        stacked = stack_layers(params)
        ps, os_, ls = pp(stacked, init_adam_state(stacked), x, y)
        assert abs(float(l1) - float(ls)) < 1e-5  # fp reordering only
        pu = unstack_layers(jax.tree.map(np.asarray, ps))
        for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(pu)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            )
        assert int(os_["t"]) == 1

    def test_pp_adam_loss_decreases_3axis(self, devices):
        from tpuscratch.models.transformer import (
            init_adam_state, stack_layers, train_step_pp_adam,
        )

        cfg = cfg_for(n_layers=2)
        x, y = data(4)
        stacked = stack_layers(init_params(9, cfg))
        opt = init_adam_state(stacked)
        step = train_step_pp_adam(
            make_mesh((2, 2, 2), ("dp", "sp", "stage"), jax.devices()[:8]),
            cfg, lr=0.01, n_micro=2,
        )
        losses = []
        for _ in range(4):
            stacked, opt, loss = step(stacked, opt, x, y)
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0], losses
