"""ZeRO-sharded training path (ISSUE 4 acceptance anchors):

- the ZeRO step's loss trajectory matches the replicated Adam baseline
  (bit-identical on 1x1 with the elementwise shard update — the
  sharding math adds nothing — and to f32 tolerance on 2x2, where the
  reduce-scatter-then-sp-psum reassociates the copy-axis sums, and with
  the fused kernel, which fma-reassociates within a lane);
- the obs ledger statically proves the comm claim: the compiled ZeRO
  step holds exactly ONE reduce-scatter (+ one trailing all-gather)
  whose wire bytes equal the analytic ``(n-1)*shard`` /
  ``(n-1)/n*result`` forms, its gradient leg is <= 0.55x the
  replicated step's at dp=4 (the regression guard that fails if a full
  gradient all-reduce sneaks back in), and accumulation keeps the
  count at one regardless of ``accum_steps``;
- per-rank optimizer state divides by |dp| (live shard shapes);
- the trainer round-trips dp-sharded optimizer leaves through the
  checkpoint bit-identically, and a mismatched-mesh restore raises a
  CommError at both the trainer and the checkpoint layer.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpuscratch.models.transformer import (
    TransformerConfig,
    init_adam_state,
    init_params,
    nonexpert_size,
    train_step_adam,
)
from tpuscratch.models.trainer import train
from tpuscratch.models.zero import (
    init_zero_adam_state,
    put_zero_state,
    train_step_zero,
    zero_flat_size,
    zero_state_bytes_per_rank,
)
from tpuscratch.obs import ledger as obs_ledger
from tpuscratch.runtime.errors import CommError
from tpuscratch.runtime.mesh import make_mesh

pytestmark = pytest.mark.zero


def _cfg(n_experts=2):
    return TransformerConfig(
        d_model=16, n_heads=2, n_experts=n_experts, d_ff=32,
        capacity_factor=2.0,
    )


def _mesh(shape):
    return make_mesh(shape, ("dp", "sp"),
                     jax.devices()[:shape[0] * shape[1]])


def _data(batch=4, seq=16, d=16, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((batch, seq, d)), jnp.float32)
    y = jnp.asarray(rng.standard_normal((batch, seq, d)), jnp.float32)
    return x, y


def _run_replicated(mesh, cfg, steps, x, y, lr=0.01):
    params = init_params(0, cfg)
    opt = init_adam_state(params)
    fn = train_step_adam(mesh, cfg, lr=lr)
    losses = []
    for _ in range(steps):
        params, opt, loss = fn(params, opt, x, y)
        losses.append(float(loss))
    return np.asarray(losses), params


def _run_zero(mesh, cfg, steps, x, y, lr=0.01, **kw):
    params = init_params(0, cfg)
    opt = put_zero_state(
        init_zero_adam_state(params, mesh.shape["dp"]), mesh, cfg
    )
    fn = train_step_zero(mesh, cfg, lr=lr, **kw)
    losses = []
    for _ in range(steps):
        params, opt, loss = fn(params, opt, x, y)
        losses.append(float(loss))
    return np.asarray(losses), params


def _leaves_equal(a, b):
    return all(
        np.array_equal(np.asarray(p), np.asarray(q))
        for p, q in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


class TestZeroStep:
    def test_bit_identical_to_replicated_on_1x1(self, devices):
        """With the elementwise shard update the ZeRO decomposition is
        pure data movement: on one device (scatter and gather are
        identity) params and losses are BIT-identical to the replicated
        Adam step at accum_steps=1, f32."""
        mesh, cfg = _mesh((1, 1)), _cfg()
        x, y = _data()
        want, want_p = _run_replicated(mesh, cfg, 5, x, y)
        got, got_p = _run_zero(mesh, cfg, 5, x, y, fused=False,
                               donate=False)
        assert np.array_equal(want, got)
        assert _leaves_equal(want_p, got_p)

    @pytest.mark.parametrize("shape", [(1, 1), (2, 2)])
    def test_trajectory_matches_replicated(self, devices, shape):
        """The default (fused-kernel) ZeRO step tracks the replicated
        baseline to f32 tolerance on both mesh shapes and keeps
        descending."""
        mesh, cfg = _mesh(shape), _cfg()
        x, y = _data()
        want, _ = _run_replicated(mesh, cfg, 8, x, y)
        got, _ = _run_zero(mesh, cfg, 8, x, y)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-7)
        assert got[-1] < got[0]

    def test_accum_program_defers_to_one_reduce_scatter(self, devices):
        """The deferred-sync contract, statically: the compiled
        accum_steps=k program holds exactly ONE reduce-scatter and ONE
        all-gather — same counts as k=1, so sync count per update is cut
        k-fold, not merely amortized."""
        mesh, cfg = _mesh((2, 2)), _cfg()
        params = init_params(0, cfg)
        x = jnp.zeros((4, 16, 16), jnp.float32)
        for k in (1, 4):
            xk = jnp.zeros((k, 4, 16, 16), jnp.float32) if k > 1 else x
            led = obs_ledger.analyze(
                train_step_zero(mesh, cfg, accum_steps=k, donate=False),
                params, init_zero_adam_state(params, 2), xk, xk,
            )
            counts = led.counts()
            assert counts.get("reduce-scatter") == 1, (k, counts)
            assert counts.get("all-gather") == 1, (k, counts)

    def test_accum_trains_and_differs_only_by_batching(self, devices):
        """accum_steps=2 on identical microbatches equals a single
        microbatch step exactly (mean of two equal gradient sums), so
        the scan accumulation itself introduces no drift."""
        mesh, cfg = _mesh((2, 2)), _cfg()
        x, y = _data()
        want, _ = _run_zero(mesh, cfg, 4, x, y, fused=False, donate=False)
        xk = jnp.stack([x, x])
        yk = jnp.stack([y, y])
        got, _ = _run_zero(mesh, cfg, 4, xk, yk, accum_steps=2,
                           fused=False, donate=False)
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)

    def test_guarded_zero_step_skips_nan_and_freezes_state(self, devices):
        """The guard composes with the sharded layout: a NaN batch skips
        the step with params AND the dp-sharded moments passed through
        bit-identically (the where-select covers the flat shards)."""
        from tpuscratch.ft.guards import STATUS_OK, STATUS_SKIPPED

        mesh, cfg = _mesh((2, 2)), _cfg()
        x, y = _data()
        params = init_params(0, cfg)
        opt = put_zero_state(init_zero_adam_state(params, 2), mesh, cfg)
        fn = train_step_zero(mesh, cfg, lr=0.01, guard=(1e30, 1e30),
                             donate=False)
        nan_ref = jnp.asarray(float("nan"), jnp.float32)
        new_p, new_o, loss, gnorm, st = fn(params, opt, x, y, nan_ref)
        assert int(st) == STATUS_OK
        assert float(gnorm) > 0 and np.isfinite(float(loss))
        assert not _leaves_equal(new_p, params)

        bad = x.at[0, 0, 0].set(jnp.nan)
        p2, o2, loss2, _, st2 = fn(params, opt, bad, y, nan_ref)
        assert int(st2) == STATUS_SKIPPED
        assert _leaves_equal(p2, params)
        assert _leaves_equal(o2, opt)


class TestZeroLedger:
    def test_wire_bytes_match_analytic_forms_2x2(self, devices):
        """The ZeRO step's reduce-scatter and all-gather wire bytes are
        EXACTLY the analytic ``(n-1)*shard`` and ``(n-1)/n*result``
        forms on a 2x2 mesh — the obs/ledger hook the tentpole's comm
        claim rests on."""
        mesh, cfg = _mesh((2, 2)), _cfg()
        n_dp = 2
        params = init_params(0, cfg)
        x = jnp.zeros((4, 16, 16), jnp.float32)
        led = obs_ledger.analyze(
            train_step_zero(mesh, cfg, donate=False), params,
            init_zero_adam_state(params, n_dp), x, x,
        )
        flat = zero_flat_size(nonexpert_size(params), n_dp)
        shard_bytes = flat // n_dp * 4
        wire = led.wire_bytes()
        assert wire["reduce-scatter"] == obs_ledger.reduce_scatter_wire_bytes(
            n_dp, shard_bytes
        ) == (n_dp - 1) * shard_bytes
        assert wire["all-gather"] == obs_ledger.all_gather_wire_bytes(
            n_dp, shard_bytes
        ) == (n_dp - 1) * shard_bytes
        gs = obs_ledger.grad_sync_wire_bytes(led)
        assert gs.reduce_scatter == wire["reduce-scatter"]
        assert gs.all_gather == wire["all-gather"]
        assert gs.total == gs.grad + gs.all_gather
        assert gs.per_microbatch(4) == gs.total / 4

    def test_grad_sync_regression_guard_dp4(self, devices):
        """THE regression guard: at dp=4 the ZeRO step's gradient-leg
        wire bytes must stay <= 0.55x the replicated step's (analytic
        ratio 0.5: one (n-1)/n reduce-scatter pass vs the all-reduce's
        2(n-1)/n).  Reintroducing a full gradient all-reduce doubles
        the leg and fails this test."""
        cfg = _cfg(n_experts=4)
        mesh = _mesh((4, 1))
        params = init_params(0, cfg)
        x = jnp.zeros((8, 8, 16), jnp.float32)
        rep = obs_ledger.grad_sync_wire_bytes(obs_ledger.analyze(
            train_step_adam(mesh, cfg), params, init_adam_state(params),
            x, x,
        ))
        zero = obs_ledger.grad_sync_wire_bytes(obs_ledger.analyze(
            train_step_zero(mesh, cfg, donate=False), params,
            init_zero_adam_state(params, 4), x, x,
        ))
        assert rep.grad > 0 and zero.reduce_scatter > 0
        assert zero.grad <= 0.55 * rep.grad, (
            f"ZeRO grad-sync leg {zero.grad} B vs replicated "
            f"{rep.grad} B — a full gradient all-reduce crept back in"
        )

    def test_optimizer_state_divides_by_dp(self, devices):
        """Per-rank optimizer HBM ÷ |dp|: the committed flat moment
        shards are 1/|dp| of the global vector on every device, and the
        static per-rank accounting agrees with the live shard shapes."""
        cfg = _cfg(n_experts=4)
        mesh = _mesh((4, 1))
        n_dp = 4
        params = init_params(0, cfg)
        state = put_zero_state(init_zero_adam_state(params, n_dp), mesh,
                               cfg)
        flat = zero_flat_size(nonexpert_size(params), n_dp)
        per_rank = 0
        for leaf in (state["mu_flat"], state["nu_flat"]):
            assert leaf.shape == (flat,)
            shard_shapes = {
                s.data.shape for s in leaf.addressable_shards
            }
            assert shard_shapes == {(flat // n_dp,)}
            per_rank += flat // n_dp * 4
        for leaf in state["mu_exp"] + state["nu_exp"]:
            shard = leaf.addressable_shards[0].data
            assert shard.shape[0] == leaf.shape[0] // n_dp
            per_rank += shard.size * shard.dtype.itemsize
        assert per_rank == zero_state_bytes_per_rank(cfg, params, n_dp)
        # the replicated layout stores the FULL moments on every rank
        repl = init_adam_state(params)
        repl_bytes = sum(
            x.size * x.dtype.itemsize for x in jax.tree.leaves(repl)
        ) - 4  # minus the step counter
        # per-rank ZeRO state ~= replicated / |dp| (padding + the
        # already-sharded expert moments keep it at-or-below the bound)
        assert per_rank <= repl_bytes / n_dp + flat // n_dp * 4


class TestZeroTrainer:
    def test_trains_and_resumes_bit_identical(self, devices, tmp_path):
        """The flagship contract extended to sharded state: dp-sharded
        flat moments round-trip through the checkpoint and a killed run
        resumes to BIT-identical params."""
        mesh, cfg = _mesh((2, 2)), _cfg()
        kw = dict(save_every=5, lr=0.005, seed=5, optimizer="adam",
                  zero=True)
        straight, rep = train(
            mesh, cfg, steps=20, ckpt_dir=str(tmp_path / "zs"), **kw
        )
        assert rep.losses[-1] < rep.losses[0]
        inter = str(tmp_path / "zi")
        train(mesh, cfg, steps=10, ckpt_dir=inter, **kw)
        resumed, rep2 = train(mesh, cfg, steps=20, ckpt_dir=inter, **kw)
        assert rep2.steps_run == 10
        assert _leaves_equal(straight, resumed)

    def test_matches_replicated_trainer_trajectory(self, devices,
                                                   tmp_path):
        mesh, cfg = _mesh((2, 2)), _cfg()
        kw = dict(save_every=5, lr=0.005, seed=5, optimizer="adam")
        _, rep = train(mesh, cfg, steps=10,
                       ckpt_dir=str(tmp_path / "r"), **kw)
        _, repz = train(mesh, cfg, steps=10, ckpt_dir=str(tmp_path / "z"),
                        zero=True, **kw)
        np.testing.assert_allclose(repz.losses, rep.losses, rtol=1e-5,
                                   atol=1e-7)

    def test_accum_trains_and_resumes(self, devices, tmp_path):
        mesh, cfg = _mesh((2, 2)), _cfg()
        kw = dict(save_every=5, lr=0.005, seed=5, optimizer="adam",
                  zero=True, accum_steps=2)
        straight, rep = train(
            mesh, cfg, steps=10, ckpt_dir=str(tmp_path / "as"), **kw
        )
        assert rep.losses[-1] < rep.losses[0]
        inter = str(tmp_path / "ai")
        train(mesh, cfg, steps=5, ckpt_dir=inter, **kw)
        resumed, _ = train(mesh, cfg, steps=10, ckpt_dir=inter, **kw)
        assert _leaves_equal(straight, resumed)
        # accum_steps diverts the data stream: part of the resume identity
        with pytest.raises(ValueError, match="resume mismatch"):
            train(mesh, cfg, steps=15, ckpt_dir=inter,
                  save_every=5, lr=0.005, seed=5, optimizer="adam",
                  zero=True, accum_steps=4)

    def test_mismatched_mesh_restore_raises_commerror(self, devices,
                                                      tmp_path):
        """dp-sharded moments are laid out for ONE |dp|: resuming on a
        different mesh fails as a clear CommError, at the trainer AND
        at the checkpoint layer."""
        from tpuscratch.runtime import checkpoint

        cfg = _cfg(n_experts=4)
        kw = dict(save_every=5, lr=0.005, seed=5, optimizer="adam",
                  zero=True, batch=4, seq=16)
        d = str(tmp_path / "mm")
        train(_mesh((2, 2)), cfg, steps=5, ckpt_dir=d, **kw)
        with pytest.raises(CommError, match="sharded for mesh"):
            train(_mesh((4, 1)), cfg, steps=10, ckpt_dir=d, **kw)

        params = init_params(5, cfg)
        ex = {"params": params, "opt": init_zero_adam_state(params, 2)}
        with pytest.raises(CommError, match="sharded for mesh"):
            checkpoint.restore(d, ex, mesh_shape={"dp": 4, "sp": 1})
        # the matching mesh loads fine
        state, step, _ = checkpoint.restore(d, ex,
                                            mesh_shape={"dp": 2, "sp": 2})
        assert step == 5

    def test_zero_requires_adam_and_accum_requires_zero(self, devices,
                                                        tmp_path):
        mesh, cfg = _mesh((1, 1)), _cfg()
        with pytest.raises(ValueError, match="optimizer"):
            train(mesh, cfg, steps=1, ckpt_dir=str(tmp_path / "a"),
                  zero=True, optimizer="sgd")
        with pytest.raises(ValueError, match="zero=True"):
            train(mesh, cfg, steps=1, ckpt_dir=str(tmp_path / "b"),
                  accum_steps=2)


def test_bench_program_runs_zero_and_accum(devices):
    """The bench plumbing: the scanned ZeRO throughput program (state
    carried through the scan, initialized in-program) produces finite
    losses with and without accumulation."""
    from tpuscratch.bench.train_bench import bench_train

    mesh = _mesh((2, 2))
    cfg = _cfg()
    r = bench_train(mesh=mesh, cfg=cfg, batch=4, seq=16, steps=2,
                    iters=1, fence="block", optimizer="adam", zero=True)
    assert r.items_per_s > 0
    r2 = bench_train(mesh=mesh, cfg=cfg, batch=4, seq=16, steps=2,
                     iters=1, fence="block", optimizer="adam", zero=True,
                     accum_steps=2)
    assert r2.items_per_s > 0
    assert "zero-adam-accum2" in r2.name
