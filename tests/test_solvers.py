"""Distributed CG solver: dense-oracle and self-consistency tests.

The solver composes the two reference flagships (halo exchange +
allreduced dot product, SURVEY.md §2.3/§2.4) into one algorithm; the
tests check it against a dense numpy factorization of the same operator
— the reference's CPU-oracle pattern (SURVEY.md §4.2) at solver scale.
"""

import numpy as np
import pytest

from tpuscratch.runtime.mesh import make_mesh_1d, make_mesh_2d
from tpuscratch.solvers import poisson_solve
from tpuscratch.solvers.cg import laplacian_apply_np

pytestmark = pytest.mark.solvers


def dense_laplacian(h: int, w: int) -> np.ndarray:
    """Dense (h*w, h*w) matrix of the zero-Dirichlet 5-point operator."""
    n = h * w
    a = np.zeros((n, n), dtype=np.float64)
    for i in range(h):
        for j in range(w):
            k = i * w + j
            a[k, k] = 4.0
            for di, dj in ((-1, 0), (1, 0), (0, -1), (0, 1)):
                ii, jj = i + di, j + dj
                if 0 <= ii < h and 0 <= jj < w:
                    a[k, ii * w + jj] = -1.0
    return a


def test_matvec_oracle_matches_dense():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((6, 9))
    a = dense_laplacian(6, 9)
    assert np.allclose(laplacian_apply_np(x), (a @ x.ravel()).reshape(6, 9))


@pytest.mark.parametrize("mesh_shape", [(1, 1), (2, 2), (2, 4)])
def test_poisson_solve_matches_dense_solve(mesh_shape):
    h = w = 16
    rng = np.random.default_rng(1)
    b = rng.standard_normal((h, w)).astype(np.float32)
    x, iters, relres = poisson_solve(
        b, make_mesh_2d(mesh_shape), tol=1e-6, max_iters=h * w
    )
    expect = np.linalg.solve(dense_laplacian(h, w), b.astype(np.float64).ravel())
    assert relres <= 1e-6
    assert 0 < iters < h * w
    assert np.allclose(x.ravel(), expect, rtol=0, atol=5e-4 * np.abs(expect).max())


def test_poisson_solve_residual_and_mesh_invariance():
    h, w = 24, 16
    rng = np.random.default_rng(2)
    x_true = rng.standard_normal((h, w)).astype(np.float32)
    b = laplacian_apply_np(x_true.astype(np.float64)).astype(np.float32)
    x1, _, rel1 = poisson_solve(b, make_mesh_2d((1, 1)), tol=1e-6)
    x2, _, rel2 = poisson_solve(b, make_mesh_2d((4, 2)), tol=1e-6)
    for x, rel in ((x1, rel1), (x2, rel2)):
        assert rel <= 1e-6
        resid = laplacian_apply_np(x.astype(np.float64)) - b
        assert np.linalg.norm(resid) <= 2e-5 * np.linalg.norm(b)
        # well-conditioned at this size: the solution itself is recovered
        assert np.abs(x - x_true).max() <= 1e-3
    # decomposition must not change the math beyond roundoff
    assert np.abs(x1 - x2).max() <= 1e-4


def test_zero_rhs_returns_zero_without_iterating():
    b = np.zeros((8, 8), dtype=np.float32)
    x, iters, relres = poisson_solve(b, make_mesh_2d((2, 2)))
    assert iters == 0 and relres == 0.0 and not x.any()


class TestMultigrid:
    """Periodic-torus V-cycle: O(1) cycles, adjoint transfers, oracles."""

    def test_cycle_count_is_grid_size_independent(self, devices):
        from tpuscratch.solvers.multigrid import mg_poisson_solve
        from tpuscratch.solvers.spectral import periodic_laplacian_np

        rng = np.random.default_rng(0)
        counts = {}
        for n, shape in ((32, (2, 2)), (64, (2, 4)), (128, (2, 4))):
            b = rng.standard_normal((n, n)).astype(np.float32)
            b -= b.mean()
            x, cycles, relres = mg_poisson_solve(
                b, make_mesh_2d(shape), tol=1e-6
            )
            # the f32 residual floor sits near tol here; the stagnation
            # guard can stop a shade above it (~1.6e-6 with rbgs)
            assert relres <= 2.5e-6
            resid = periodic_laplacian_np(x.astype(np.float64)) - b
            assert np.abs(resid).max() < 1e-4
            counts[n] = cycles
        # the multigrid property: iterations don't grow with the grid
        assert all(4 <= c <= 14 for c in counts.values()), counts

    def test_matches_spectral_solver(self, devices):
        from tpuscratch.solvers import periodic_poisson_fft
        from tpuscratch.solvers.multigrid import mg_poisson_solve

        rng = np.random.default_rng(1)
        b = rng.standard_normal((64, 64)).astype(np.float32)
        b -= b.mean()
        x_mg, _, _ = mg_poisson_solve(b, make_mesh_2d((2, 4)), tol=1e-6)
        x_sp = periodic_poisson_fft(b, make_mesh_1d("x", 8))
        assert abs(x_mg.mean()) < 1e-5
        assert np.abs(x_mg - x_sp).max() < 1e-3

    def test_mesh_invariance(self, devices):
        from tpuscratch.solvers.multigrid import mg_poisson_solve

        rng = np.random.default_rng(2)
        b = rng.standard_normal((64, 64)).astype(np.float32)
        b -= b.mean()
        x1, c1, _ = mg_poisson_solve(b, make_mesh_2d((1, 1)), tol=1e-6)
        x2, c2, _ = mg_poisson_solve(b, make_mesh_2d((2, 2)), tol=1e-6)
        # same math, different decomposition; psum ordering can move rs
        # across the stopping threshold by one cycle
        assert abs(c1 - c2) <= 1
        assert np.abs(x1 - x2).max() < 1e-4

    def test_transfers_are_adjoint(self, devices):
        """<P e, r>_fine == 4 <e, R r>_coarse (R = P^T / 4)."""
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from tpuscratch.comm import run_spmd
        from tpuscratch.halo.layout import TileLayout
        from tpuscratch.runtime.mesh import topology_of
        from tpuscratch.solvers.multigrid import (
            level_specs,
            prolong_bilinear,
            restrict_fw,
        )

        mesh = make_mesh_2d((1, 1))
        topo = topology_of(mesh, periodic=True)
        specs = level_specs(TileLayout(16, 16, 1, 1), topo, ("row", "col"), 2)
        rng = np.random.default_rng(3)
        e = rng.standard_normal((8, 8)).astype(np.float32)
        r = rng.standard_normal((16, 16)).astype(np.float32)

        def body(et, rt):
            ec, rf = et[0, 0], rt[0, 0]
            lhs = jnp.sum(prolong_bilinear(ec, specs[1]) * rf)
            rhs = 4.0 * jnp.sum(ec * restrict_fw(rf, specs[0]))
            return lhs, rhs

        prog = run_spmd(
            mesh, body,
            (P("row", "col", None, None), P("row", "col", None, None)),
            (P(), P()),
        )
        lhs, rhs = prog(jnp.asarray(e)[None, None], jnp.asarray(r)[None, None])
        assert np.isclose(float(lhs), float(rhs), rtol=1e-5)


class TestPCG:
    """Multigrid-preconditioned CG: the two solver families composed."""

    def test_beats_both_parents_and_solves(self, devices):
        from tpuscratch.solvers.multigrid import (
            mg_poisson_solve,
            pcg_poisson_solve,
        )
        from tpuscratch.solvers.spectral import periodic_laplacian_np

        rng = np.random.default_rng(0)
        for n in (64, 128):
            b = rng.standard_normal((n, n)).astype(np.float32)
            b -= b.mean()
            x, iters, relres = pcg_poisson_solve(
                b, make_mesh_2d((2, 4)), tol=1e-6
            )
            assert relres <= 1e-6
            resid = periodic_laplacian_np(x.astype(np.float64)) - b
            assert np.abs(resid).max() < 1e-3
            _, cycles, _ = mg_poisson_solve(b, make_mesh_2d((2, 4)), tol=1e-6)
            # Krylov acceleration: fewer PCG iterations than V-cycles,
            # and flat in grid size
            assert iters < cycles, (n, iters, cycles)
            assert iters <= 10

    def test_matches_spectral(self, devices):
        from tpuscratch.solvers import periodic_poisson_fft
        from tpuscratch.solvers.multigrid import pcg_poisson_solve

        rng = np.random.default_rng(1)
        b = rng.standard_normal((64, 64)).astype(np.float32)
        b -= b.mean()
        x, _, _ = pcg_poisson_solve(b, make_mesh_2d((2, 2)), tol=1e-6)
        x_sp = periodic_poisson_fft(b, make_mesh_1d("x", 4))
        assert np.abs(x - x_sp).max() < 1e-3


class TestSmoothers:
    def test_rbgs_beats_jacobi_and_both_solve(self, devices):
        from tpuscratch.solvers.multigrid import mg_poisson_solve
        from tpuscratch.solvers.spectral import periodic_laplacian_np

        rng = np.random.default_rng(4)
        b = rng.standard_normal((64, 64)).astype(np.float32)
        b -= b.mean()
        cycles = {}
        for sm in ("jacobi", "rbgs"):
            x, c, rel = mg_poisson_solve(
                b, make_mesh_2d((2, 4)), tol=1e-6, smoother=sm
            )
            assert rel <= 2.5e-6  # f32 stagnation floor, see above
            resid = periodic_laplacian_np(x.astype(np.float64)) - b
            assert np.abs(resid).max() < 1e-4
            cycles[sm] = c
        assert cycles["rbgs"] <= cycles["jacobi"]

    def test_rbgs_vcycle_is_symmetric(self, devices):
        """<M u, v> == <u, M v> — what PCG requires of its preconditioner
        (pre-smooth red-first, post-smooth black-first)."""
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from tpuscratch.comm import run_spmd
        from tpuscratch.halo.layout import TileLayout
        from tpuscratch.runtime.mesh import topology_of
        from tpuscratch.solvers.multigrid import level_specs, v_cycle

        mesh = make_mesh_2d((1, 1))
        topo = topology_of(mesh, periodic=True)
        specs = level_specs(TileLayout(16, 16, 1, 1), topo, ("row", "col"), 3)
        rng = np.random.default_rng(5)
        u = rng.standard_normal((16, 16)).astype(np.float32)
        v = rng.standard_normal((16, 16)).astype(np.float32)

        def body(ut, vt):
            uu, vv = ut[0, 0], vt[0, 0]
            m = lambda r: v_cycle(  # noqa: E731
                jnp.zeros_like(r), r, specs, 0, 2, 8, 0.8, "rbgs"
            )
            return jnp.sum(m(uu) * vv), jnp.sum(uu * m(vv))

        prog = run_spmd(
            mesh, body,
            (P("row", "col", None, None), P("row", "col", None, None)),
            (P(), P()),
        )
        lhs, rhs = prog(jnp.asarray(u)[None, None], jnp.asarray(v)[None, None])
        assert np.isclose(float(lhs), float(rhs), rtol=1e-4)


class TestMultigrid3D:
    """3D V-cycle over the 26-neighbor exchange: O(1) cycles + adjoints."""

    @staticmethod
    def _lap3(x):
        return 6 * x - sum(
            np.roll(x, s, a) for a in range(3) for s in (1, -1)
        )

    def test_cycle_count_flat_and_solves(self, devices):
        from tpuscratch.runtime.mesh import make_mesh
        from tpuscratch.solvers.multigrid3d import mg_poisson3d_solve

        rng = np.random.default_rng(0)
        counts = {}
        for n in (16, 32):
            b = rng.standard_normal((n, n, n)).astype(np.float32)
            b -= b.mean()
            x, cycles, relres = mg_poisson3d_solve(
                b, make_mesh((2, 2, 2), ("z", "row", "col")), tol=1e-6
            )
            assert relres <= 2.5e-6
            assert np.abs(self._lap3(x.astype(np.float64)) - b).max() < 1e-4
            assert abs(x.mean()) < 1e-5
            counts[n] = cycles
        assert all(4 <= c <= 14 for c in counts.values()), counts

    def test_mesh_invariance(self, devices):
        from tpuscratch.runtime.mesh import make_mesh
        from tpuscratch.solvers.multigrid3d import mg_poisson3d_solve

        rng = np.random.default_rng(1)
        b = rng.standard_normal((16, 16, 16)).astype(np.float32)
        b -= b.mean()
        x1, c1, _ = mg_poisson3d_solve(
            b, make_mesh((1, 1, 1), ("z", "row", "col")), tol=1e-6
        )
        x2, c2, _ = mg_poisson3d_solve(
            b, make_mesh((2, 2, 2), ("z", "row", "col")), tol=1e-6
        )
        assert abs(c1 - c2) <= 1
        assert np.abs(x1 - x2).max() < 1e-4

    @pytest.mark.parametrize("mesh_dims", [(1, 1, 1), (2, 1, 1)])
    def test_jacobi_stream_smoother_converges_like_jacobi(self, devices,
                                                          mesh_dims):
        # the streamed smoother (fine levels fold nu sweeps into one
        # manual-DMA pass, ops/stencil_stream rhs mode) must reproduce
        # plain damped Jacobi: same solution, cycle count within +-1
        from tpuscratch.runtime.mesh import make_mesh
        from tpuscratch.solvers.multigrid3d import mg_poisson3d_solve

        rng = np.random.default_rng(7)
        # cx = 128: the streamed smoother needs full-lane-tile planes
        # (chip rule — see _stream_smoothable), so the finest level
        # must be wide enough to actually exercise the streamed path
        b = rng.standard_normal((64, 16, 128)).astype(np.float32)
        b -= b.mean()
        mesh = make_mesh(mesh_dims, ("z", "row", "col"))
        xj, cj, rj = mg_poisson3d_solve(b, mesh, tol=1e-6,
                                        smoother="jacobi")
        xs, cs, rs = mg_poisson3d_solve(b, mesh, tol=1e-6,
                                        smoother="jacobi-stream")
        assert rs <= 2.5e-6
        assert abs(cs - cj) <= 1, (cs, cj)
        assert np.abs(xs - xj).max() < 1e-4

    def test_3d_transfers_are_adjoint(self, devices):
        """<P e, r>_fine == 8 <e, R r>_coarse (R = P^T / 8)."""
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from tpuscratch.comm import run_spmd
        from tpuscratch.halo.halo3d import TileLayout3D
        from tpuscratch.runtime.mesh import make_mesh, topology_of
        from tpuscratch.solvers.multigrid3d import (
            level_specs3,
            prolong_trilinear,
            restrict_fw3,
        )

        mesh = make_mesh((1, 1, 1), ("z", "row", "col"))
        topo = topology_of(mesh, periodic=True)
        specs = level_specs3(
            TileLayout3D((8, 8, 8)), topo, ("z", "row", "col"), 2
        )
        rng = np.random.default_rng(2)
        e = rng.standard_normal((4, 4, 4)).astype(np.float32)
        r = rng.standard_normal((8, 8, 8)).astype(np.float32)

        def body(et, rt):
            ec, rf = et[0, 0, 0], rt[0, 0, 0]
            lhs = jnp.sum(prolong_trilinear(ec, specs[1][1]) * rf)
            rhs = 8.0 * jnp.sum(ec * restrict_fw3(rf, specs[0][1]))
            return lhs, rhs

        spec6 = P("z", "row", "col", None, None, None)
        prog = run_spmd(mesh, body, (spec6, spec6), (P(), P()))
        lhs, rhs = prog(
            jnp.asarray(e)[None, None, None], jnp.asarray(r)[None, None, None]
        )
        assert np.isclose(float(lhs), float(rhs), rtol=1e-5)

    def test_3d_pcg_beats_vcycle_iteration(self, devices):
        from tpuscratch.runtime.mesh import make_mesh
        from tpuscratch.solvers.multigrid3d import (
            mg_poisson3d_solve,
            pcg_poisson3d_solve,
        )

        rng = np.random.default_rng(3)
        b = rng.standard_normal((16, 16, 16)).astype(np.float32)
        b -= b.mean()
        mesh = make_mesh((2, 2, 2), ("z", "row", "col"))
        x, iters, relres = pcg_poisson3d_solve(b, mesh, tol=1e-6)
        assert relres <= 1e-6 and iters <= 8
        _, cycles, _ = mg_poisson3d_solve(b, mesh, tol=1e-6)
        assert iters < cycles
        resid = np.abs(self._lap3(x.astype(np.float64)) - b).max()
        assert resid < 1e-4


class TestUnconvergedWarning:
    """An unconverged return must not look like success (ADVICE r2)."""

    def test_mg_warns_when_cycle_cap_hit(self, devices):
        import warnings

        from tpuscratch.runtime.mesh import make_mesh_2d
        from tpuscratch.solvers.multigrid import mg_poisson_solve

        b = np.random.default_rng(7).standard_normal((64, 64)).astype(
            np.float32
        )
        b -= b.mean()
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            _, _, relres = mg_poisson_solve(
                b, make_mesh_2d((1, 1)), tol=1e-12, max_cycles=1
            )
        assert relres > 1e-12
        assert any(
            issubclass(x.category, RuntimeWarning)
            and "did not reach tol" in str(x.message)
            for x in w
        )

    def test_mg_silent_when_converged(self, devices):
        import warnings

        from tpuscratch.runtime.mesh import make_mesh_2d
        from tpuscratch.solvers.multigrid import mg_poisson_solve

        b = np.random.default_rng(7).standard_normal((64, 64)).astype(
            np.float32
        )
        b -= b.mean()
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            _, _, relres = mg_poisson_solve(b, make_mesh_2d((1, 1)), tol=1e-5)
        assert relres <= 1e-5
        assert not [x for x in w if issubclass(x.category, RuntimeWarning)]


def _smoother_prog(mesh, fn):
    """Two-tile -> one-tile SPMD program for smoother equivalence tests."""
    import jax.numpy as jnp  # noqa: F401
    from jax.sharding import PartitionSpec as P

    from tpuscratch.comm import run_spmd

    sp = P(*mesh.axis_names, None, None, None)
    return run_spmd(
        mesh,
        lambda a, b: fn(a[0, 0, 0], b[0, 0, 0])[None, None, None],
        (sp, sp), sp,
    )


class TestPipelinedCG:
    """Ghysels–Vanroose single-reduction CG: tolerance-gated equivalence
    to classic CG, and the one-psum-per-iteration claim proven
    STATICALLY off the compiled HLO (a while_loop body appears exactly
    once, so instruction counts ARE per-iteration counts plus setup)."""

    @pytest.mark.parametrize("mesh_shape", [(1, 1), (2, 2)])
    def test_matches_classic_within_tolerance(self, mesh_shape):
        rng = np.random.default_rng(1)
        b = rng.standard_normal((16, 16)).astype(np.float32)
        xc, kc, rc = poisson_solve(
            b, make_mesh_2d(mesh_shape), tol=1e-5, max_iters=256
        )
        xp, kp, rp = poisson_solve(
            b, make_mesh_2d(mesh_shape), tol=1e-5, max_iters=256,
            method="pipelined",
        )
        assert rc <= 1e-5 and rp <= 1e-5
        # same Krylov space, same convergence rate: iteration counts
        # match to a couple of recurrence-rounding iterations
        assert abs(kp - kc) <= 3, (kp, kc)
        # solutions agree at the tolerance's scale
        assert np.abs(xp - xc).max() <= 1e-3 * max(1.0, np.abs(xc).max())
        # the recurrence residual can undershoot the TRUE one (the
        # documented pipelined-CG drift); the true residual still honors
        # a small multiple of the gate
        resid = laplacian_apply_np(xp.astype(np.float64)) - b
        assert np.linalg.norm(resid) <= 10 * 1e-5 * np.linalg.norm(b)

    def test_exact_collective_counts_ledger(self):
        """THE communication claim, statically: classic CG compiles to 3
        all-reduces (1 init + 2 per iteration — the fused rz/rs stack
        and the data-dependent p.Ap), pipelined to 2 (1 init + ONE per
        iteration); the matvec's 4 face ppermutes appear once per
        matvec SITE: classic has 1 (body), pipelined 4 (init w0, body
        n, and the restart-refresh branch's 2 — present statically,
        fired once per replace_every segment)."""
        import jax.numpy as jnp

        from tpuscratch.halo.driver import _setup
        from tpuscratch.obs import ledger as obs_ledger
        from tpuscratch.solvers.cg import _poisson_program

        mesh, topo, layout, spec = _setup(
            (16, 16), make_mesh_2d((2, 2)), (1, 1), periodic=False,
            neighbors=4,
        )
        arg = jnp.zeros((2, 2, 8, 8), jnp.float32)
        counts = {}
        for method in ("cg", "pipelined"):
            led = obs_ledger.analyze(
                _poisson_program(mesh, spec, 1e-5, 64, method), arg
            )
            counts[method] = (led.count("all-reduce"),
                              led.count("collective-permute"))
        assert counts["cg"] == (3, 4), counts
        assert counts["pipelined"] == (2, 16), counts

    def test_classic_unpreconditioned_uses_fused_stack(self):
        """The satellite contract: even plain CG's two per-iteration
        scalars ship as 2 (not 3) all-reduces — the rz/rs pair is ONE
        stacked psum unconditionally."""
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from tpuscratch.comm import run_spmd
        from tpuscratch.halo.driver import _setup
        from tpuscratch.obs import ledger as obs_ledger
        from tpuscratch.solvers.cg import cg, dirichlet_laplacian

        mesh, topo, layout, spec = _setup(
            (16, 16), make_mesh_2d((2, 2)), (1, 1), periodic=False,
            neighbors=4,
        )

        def local(bt):
            x, k, rel = cg(
                lambda p: dirichlet_laplacian(p, spec), bt[0, 0],
                ("row", "col"), tol=1e-5, max_iters=64,
            )
            return x[None, None], k, rel

        prog = run_spmd(mesh, local, P("row", "col", None, None),
                        (P("row", "col", None, None), P(), P()))
        led = obs_ledger.analyze(prog, jnp.zeros((2, 2, 8, 8), jnp.float32))
        assert led.count("all-reduce") == 3  # 1 init + 2 per iteration

class TestDeepHaloSmoothing:
    """s-step smoothing: s sweeps per (deep, axis-sequential) exchange,
    BIT-identical to the exchange-every-sweep smoother, with the
    collective count and wire bytes ledger-asserted exactly."""

    def _setup3(self, core=(8, 8, 8)):
        from tpuscratch.halo.halo3d import HaloSpec3D, TileLayout3D
        from tpuscratch.runtime.mesh import make_mesh, topology_of

        mesh = make_mesh((2, 2, 2), ("z", "row", "col"))
        topo = topology_of(mesh, periodic=True)
        spec = HaloSpec3D(
            layout=TileLayout3D(core, (1, 1, 1)), topology=topo,
            axes=("z", "row", "col"), neighbors=6,
        )
        return mesh, spec

    def _tiles(self, n, seed=0):
        from tpuscratch.halo.halo3d import decompose3d_cores

        rng = np.random.default_rng(seed)
        u = rng.standard_normal((n, n, n)).astype(np.float32)
        f = rng.standard_normal((n, n, n)).astype(np.float32)
        import jax.numpy as jnp

        return (jnp.asarray(decompose3d_cores(u, (2, 2, 2))),
                jnp.asarray(decompose3d_cores(f, (2, 2, 2))))

    @pytest.mark.parametrize("sweeps,s", [(4, 2), (5, 2), (4, 4), (3, 3)])
    def test_jacobi_deep_bit_identical(self, devices, sweeps, s):
        from tpuscratch.solvers.multigrid3d import (
            jacobi_smooth3,
            jacobi_smooth3_deep,
        )

        mesh, spec = self._setup3()
        ut, ft = self._tiles(16)
        shal = _smoother_prog(
            mesh, lambda a, b: jacobi_smooth3(a, b, spec, 6 / 7, sweeps)
        )(ut, ft)
        deep = _smoother_prog(
            mesh,
            lambda a, b: jacobi_smooth3_deep(a, b, spec, 6 / 7, sweeps, s),
        )(ut, ft)
        assert np.array_equal(np.asarray(shal), np.asarray(deep))

    @pytest.mark.parametrize("sweeps,s,rev", [(4, 2, False), (3, 2, True),
                                              (4, 3, False)])
    def test_rbgs_deep_bit_identical(self, devices, sweeps, s, rev):
        from tpuscratch.solvers.multigrid3d import (
            rbgs_smooth3,
            rbgs_smooth3_deep,
        )

        mesh, spec = self._setup3()
        ut, ft = self._tiles(16, seed=1)
        shal = _smoother_prog(
            mesh, lambda a, b: rbgs_smooth3(a, b, spec, sweeps, rev)
        )(ut, ft)
        deep = _smoother_prog(
            mesh, lambda a, b: rbgs_smooth3_deep(a, b, spec, sweeps, s, rev)
        )(ut, ft)
        assert np.array_equal(np.asarray(shal), np.asarray(deep))

    def test_exchange_count_and_wire_bytes_ledger(self, devices):
        """Exactly ceil(sweeps/s) state exchanges of 6 ppermutes each
        (the rounds are python-unrolled so the static HLO count IS the
        dynamic launch count) plus ONE rhs ghost fill per smooth call;
        wire bytes match the axis-sequential plan's analytic formula
        EXACTLY, and the per-sweep bytes obey the trapezoid law:
        <= (1+eps)/s of exchanging the depth-s shell every sweep."""
        import math

        from tpuscratch.halo.halo3d import (
            HaloSpec3D,
            TileLayout3D,
            seq_exchange_wire_bytes,
        )
        from tpuscratch.obs import ledger as obs_ledger
        from tpuscratch.solvers.multigrid3d import jacobi_smooth3_deep

        sweeps, s = 4, 2
        mesh, spec = self._setup3()
        ut, ft = self._tiles(16)
        led = obs_ledger.analyze(
            _smoother_prog(
                mesh,
                lambda a, b: jacobi_smooth3_deep(a, b, spec, 6 / 7,
                                                 sweeps, s),
            ),
            ut, ft,
        )
        rounds = math.ceil(sweeps / s)
        # 6 ppermutes per state exchange + 6 for the one rhs fill
        assert led.count("collective-permute") == 6 * (rounds + 1)

        def seq_bytes(depth):
            dspec = HaloSpec3D(
                layout=TileLayout3D(spec.layout.core, (depth,) * 3),
                topology=spec.topology, axes=spec.axes, neighbors=6,
            )
            return seq_exchange_wire_bytes(dspec)

        analytic = rounds * seq_bytes(s) + seq_bytes(s - 1)
        assert led.wire_bytes()["collective-permute"] == analytic
        # the 1/s law vs the depth-s-every-sweep baseline (eps = 0.5
        # covers the rhs leg and the edge bands at this core size)
        per_sweep = analytic / sweeps
        assert per_sweep <= (1 + 0.5) * seq_bytes(s) / s

    def test_mg_s_step_same_cycles_and_solution(self, devices):
        from tpuscratch.runtime.mesh import make_mesh
        from tpuscratch.solvers.multigrid3d import mg_poisson3d_solve

        rng = np.random.default_rng(0)
        b = rng.standard_normal((16, 16, 16)).astype(np.float32)
        b -= b.mean()
        mesh = make_mesh((2, 2, 2), ("z", "row", "col"))
        x1, c1, r1 = mg_poisson3d_solve(b, mesh, tol=1e-6)
        x2, c2, r2 = mg_poisson3d_solve(b, mesh, tol=1e-6, s_step=2)
        # the smoothers are bit-identical (tests above); the composed
        # program may re-round through fusion, so cycle count matches
        # exactly and the solutions to roundoff
        assert c1 == c2
        assert r2 <= 2.5e-6
        assert np.abs(x1 - x2).max() <= 1e-5

    def test_deep_smoother_rejects_open_boundaries(self, devices):
        from tpuscratch.halo.halo3d import HaloSpec3D, TileLayout3D
        from tpuscratch.runtime.mesh import make_mesh, topology_of
        from tpuscratch.solvers.multigrid3d import jacobi_smooth3_deep

        mesh = make_mesh((2, 2, 2), ("z", "row", "col"))
        topo = topology_of(mesh, periodic=False)
        spec = HaloSpec3D(
            layout=TileLayout3D((8, 8, 8), (1, 1, 1)), topology=topo,
            axes=("z", "row", "col"), neighbors=6,
        )
        import jax.numpy as jnp

        with pytest.raises(ValueError, match="periodic-only"):
            jacobi_smooth3_deep(
                jnp.zeros((8, 8, 8)), jnp.zeros((8, 8, 8)), spec, 6 / 7,
                4, 2,
            )


class TestSupervisedRunner:
    """The solver on the production machinery: chunked, checkpointed,
    chaos-tested, goodput-accounted."""

    def _b(self, n=16, seed=0):
        rng = np.random.default_rng(seed)
        b = rng.standard_normal((n, n, n)).astype(np.float32)
        return b - b.mean()

    def test_chunked_matches_whole_solve(self, devices, tmp_path):
        from tpuscratch.runtime.mesh import make_mesh
        from tpuscratch.solvers import (
            checkpointed_mg3d_solve,
            mg_poisson3d_solve,
        )

        b = self._b()
        mesh = make_mesh((2, 2, 2), ("z", "row", "col"))
        x, rep = checkpointed_mg3d_solve(
            b, str(tmp_path / "ck"), mesh=mesh, tol=1e-6, chunk_cycles=3
        )
        xref, cycles, relres = mg_poisson3d_solve(b, mesh, tol=1e-6)
        assert rep.converged and rep.cycles == cycles
        assert abs(rep.relres - relres) <= 1e-8
        assert np.abs(x - xref).max() <= 1e-6

    def test_preempted_resume_bit_identical(self, devices, tmp_path):
        """The trainer/halo-driver contract extended to solvers: a run
        preempted at a chunk boundary AND hit by a transient CommError,
        restarted by the supervisor, finishes BIT-identical to an
        uninterrupted run."""
        from tpuscratch.ft import ChaosPlan, Fault
        from tpuscratch.obs.metrics import MetricsRegistry
        from tpuscratch.runtime.mesh import make_mesh
        from tpuscratch.solvers import (
            checkpointed_mg3d_solve,
            supervised_mg3d_solve,
        )

        b = self._b()
        mesh = make_mesh((2, 2, 2), ("z", "row", "col"))
        clean, rep1 = checkpointed_mg3d_solve(
            b, str(tmp_path / "clean"), mesh=mesh, tol=1e-6, chunk_cycles=3
        )
        plan = ChaosPlan(0, [
            Fault("solver/preempt", at=(3,), kind="preempt"),
            Fault("comm/solver_chunk", at=(6,)),
        ])
        metrics = MetricsRegistry()
        chaos, rep2 = supervised_mg3d_solve(
            b, str(tmp_path / "chaos"), mesh=mesh, tol=1e-6,
            chunk_cycles=3, chaos=plan, metrics=metrics,
        )
        assert sum(plan.stats().values()) == 2
        assert int(metrics.counter("ft/restarts").value) == 2
        assert rep2.resumed_at > 0 and rep2.converged
        assert rep2.cycles == rep1.cycles
        assert np.array_equal(clean, chaos)

    def test_goodput_report_sums_and_books_solver_chunks(self, devices,
                                                         tmp_path):
        from tpuscratch.obs.goodput import goodput_report
        from tpuscratch.obs.report import load_events
        from tpuscratch.obs.sink import open_sink
        from tpuscratch.runtime.mesh import make_mesh
        from tpuscratch.solvers import checkpointed_mg3d_solve

        b = self._b()
        path = str(tmp_path / "obs.jsonl")
        sink = open_sink(path)
        # chunk_cycles=2 is a FRESH program config in this process, so
        # the first chunk's bracket is compile-dominated (a cached
        # config would book zero compile — the restart-reuse behavior)
        checkpointed_mg3d_solve(
            b, str(tmp_path / "ck"), mesh=make_mesh((2, 2, 2),
                                                    ("z", "row", "col")),
            tol=1e-6, chunk_cycles=2, sink=sink,
        )
        rep = goodput_report(load_events([path]))
        rep.check()  # buckets sum to wall exactly, by construction
        assert rep.buckets["step"] > 0
        assert rep.buckets["checkpoint"] > 0
        assert rep.buckets["compile"] > 0  # first chunk's bracket

    def test_overstepped_checkpoint_refused(self, devices, tmp_path):
        from tpuscratch.runtime.mesh import make_mesh
        from tpuscratch.solvers import checkpointed_mg3d_solve

        b = self._b()
        mesh = make_mesh((2, 2, 2), ("z", "row", "col"))
        checkpointed_mg3d_solve(b, str(tmp_path / "ck"), mesh=mesh,
                                tol=1e-6, chunk_cycles=4)
        with pytest.raises(ValueError, match="beyond"):
            checkpointed_mg3d_solve(b, str(tmp_path / "ck"), mesh=mesh,
                                    tol=1e-6, chunk_cycles=4, max_cycles=2)
