"""Distributed CG solver: dense-oracle and self-consistency tests.

The solver composes the two reference flagships (halo exchange +
allreduced dot product, SURVEY.md §2.3/§2.4) into one algorithm; the
tests check it against a dense numpy factorization of the same operator
— the reference's CPU-oracle pattern (SURVEY.md §4.2) at solver scale.
"""

import numpy as np
import pytest

from tpuscratch.runtime.mesh import make_mesh_2d
from tpuscratch.solvers import poisson_solve
from tpuscratch.solvers.cg import laplacian_apply_np


def dense_laplacian(h: int, w: int) -> np.ndarray:
    """Dense (h*w, h*w) matrix of the zero-Dirichlet 5-point operator."""
    n = h * w
    a = np.zeros((n, n), dtype=np.float64)
    for i in range(h):
        for j in range(w):
            k = i * w + j
            a[k, k] = 4.0
            for di, dj in ((-1, 0), (1, 0), (0, -1), (0, 1)):
                ii, jj = i + di, j + dj
                if 0 <= ii < h and 0 <= jj < w:
                    a[k, ii * w + jj] = -1.0
    return a


def test_matvec_oracle_matches_dense():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((6, 9))
    a = dense_laplacian(6, 9)
    assert np.allclose(laplacian_apply_np(x), (a @ x.ravel()).reshape(6, 9))


@pytest.mark.parametrize("mesh_shape", [(1, 1), (2, 2), (2, 4)])
def test_poisson_solve_matches_dense_solve(mesh_shape):
    h = w = 16
    rng = np.random.default_rng(1)
    b = rng.standard_normal((h, w)).astype(np.float32)
    x, iters, relres = poisson_solve(
        b, make_mesh_2d(mesh_shape), tol=1e-6, max_iters=h * w
    )
    expect = np.linalg.solve(dense_laplacian(h, w), b.astype(np.float64).ravel())
    assert relres <= 1e-6
    assert 0 < iters < h * w
    assert np.allclose(x.ravel(), expect, rtol=0, atol=5e-4 * np.abs(expect).max())


def test_poisson_solve_residual_and_mesh_invariance():
    h, w = 24, 16
    rng = np.random.default_rng(2)
    x_true = rng.standard_normal((h, w)).astype(np.float32)
    b = laplacian_apply_np(x_true.astype(np.float64)).astype(np.float32)
    x1, _, rel1 = poisson_solve(b, make_mesh_2d((1, 1)), tol=1e-6)
    x2, _, rel2 = poisson_solve(b, make_mesh_2d((4, 2)), tol=1e-6)
    for x, rel in ((x1, rel1), (x2, rel2)):
        assert rel <= 1e-6
        resid = laplacian_apply_np(x.astype(np.float64)) - b
        assert np.linalg.norm(resid) <= 2e-5 * np.linalg.norm(b)
        # well-conditioned at this size: the solution itself is recovered
        assert np.abs(x - x_true).max() <= 1e-3
    # decomposition must not change the math beyond roundoff
    assert np.abs(x1 - x2).max() <= 1e-4


def test_zero_rhs_returns_zero_without_iterating():
    b = np.zeros((8, 8), dtype=np.float32)
    x, iters, relres = poisson_solve(b, make_mesh_2d((2, 2)))
    assert iters == 0 and relres == 0.0 and not x.any()
