"""Tests for the benchmark harnesses (correctness, not performance)."""

import pytest
import jax.numpy as jnp
import numpy as np

from tpuscratch.bench.dot_bench import bench_dot
from tpuscratch.bench.pingpong import host_staging_roundtrip, sweep, verify_echo
from tpuscratch.bench.stencil_bench import bench_stencil
from tpuscratch.halo.driver import assemble, decompose, distributed_stencil
from tpuscratch.halo.layout import TileLayout
from tpuscratch.runtime.mesh import make_mesh_1d, make_mesh_2d
from tpuscratch.runtime.topology import CartTopology


class TestDriver:
    def test_decompose_assemble_roundtrip(self):
        topo = CartTopology((2, 4), (True, True))
        lay = TileLayout(4, 8, 1, 1)
        world = np.arange(8 * 32, dtype=np.float32).reshape(8, 32)
        tiles = decompose(world, topo, lay)
        assert tiles.shape == (2, 4, 6, 10)
        np.testing.assert_array_equal(assemble(tiles, topo, lay), world)

    def test_distributed_stencil_matches_roll(self):
        rng = np.random.default_rng(4)
        world = rng.standard_normal((16, 16)).astype(np.float32)
        got = distributed_stencil(world, steps=2)
        expect = world
        for _ in range(2):
            expect = 0.25 * (
                np.roll(expect, 1, 0) + np.roll(expect, -1, 0)
                + np.roll(expect, 1, 1) + np.roll(expect, -1, 1)
            )
        np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)

    def test_single_device_mesh_self_wrap(self):
        # 1x1 mesh: periodic halo wraps to self — single-chip path of bench.py
        rng = np.random.default_rng(9)
        world = rng.standard_normal((8, 8)).astype(np.float32)
        got = distributed_stencil(world, steps=1, mesh=make_mesh_2d((1, 1)))
        expect = 0.25 * (
            np.roll(world, 1, 0) + np.roll(world, -1, 0)
            + np.roll(world, 1, 1) + np.roll(world, -1, 1)
        )
        np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)


class TestPingpong:
    def test_echo_verifies(self):
        mesh = make_mesh_1d("x")
        assert verify_echo(mesh, "x", 256)

    def test_sweep_small(self):
        mesh = make_mesh_1d("x")
        results = sweep(mesh, sizes_bytes=(8, 128), iters=2)
        assert len(results) == 2
        assert all(r.p50 > 0 for r in results)
        assert results[1].bytes_moved == 2 * 32 * 4

    def test_host_staging(self):
        res = host_staging_roundtrip(1024, iters=2)
        assert res.p50 > 0


class TestBenchPrograms:
    def test_dot_bench_self_check(self):
        mesh = make_mesh_1d("x")
        res = bench_dot(mesh, n_elems=8 * 4096, iters=2, check=True)
        assert res.items == 8 * 4096

    @pytest.mark.parametrize("method", ["full", "partials", "xla"])
    def test_dot_bench_scanned_rounds(self, method):
        # the rounds>1 scan path for every strategy: self-check still
        # exact (the anti-hoisting perturbation is below f32
        # resolution), and items/bytes scale by rounds
        mesh = make_mesh_1d("x")
        n = 8 * 4096
        res = bench_dot(
            mesh, n_elems=n, iters=2, check=True, rounds=3, method=method,
            max_gbps=float("inf"),  # tiny problem; CPU cache could beat 1 TB/s
        )
        assert res.items == n * 3
        assert res.bytes_moved == 2 * 4 * n * 3

    def test_dot_bench_implausible_rate_rejected(self):
        # tiny problem + absurdly low bound => the roofline guard trips
        mesh = make_mesh_1d("x")
        with pytest.raises(AssertionError, match="implausible"):
            bench_dot(
                mesh, n_elems=8 * 4096, iters=2, check=False, rounds=2,
                max_gbps=1e-12,
            )

    def test_stencil_bench_runs(self):
        res = bench_stencil(grid=(32, 32), steps=2, iters=2)
        assert res.items == 32 * 32 * 2
        assert res.items_per_s > 0

    def test_attention_bench_runs(self):
        from tpuscratch.bench.attention_bench import bench_attention

        res = bench_attention(
            S=16, H=2, D=8, causal=True, rounds=2, iters=2, fence="block"
        )
        assert res.items == 2 * int(4 * 16 * 16 * 2 * 8 * 0.5)
        assert res.items_per_s > 0

    def test_attention_bench_implausible_rate_rejected(self):
        from tpuscratch.bench.attention_bench import bench_attention

        with pytest.raises(AssertionError, match="implausible"):
            bench_attention(
                S=16, H=2, D=8, causal=True, rounds=2, iters=2,
                fence="block", max_tflops=1e-12,
            )


class TestImplStrings:
    def test_deep_impl_string(self):
        res = bench_stencil(grid=(32, 32), steps=4, impl="deep:2", iters=2)
        assert "deep:2" in res.name
        assert res.items == 32 * 32 * 4

    def test_unroll_impl_string(self):
        res = bench_stencil(grid=(32, 32), steps=2, impl="xla+unroll", iters=2)
        assert res.items_per_s > 0


class TestWeakScaling:
    def test_efficiency_and_report(self):
        from tpuscratch.bench.weak_scaling import bench_weak_scaling, efficiency, report

        pts = bench_weak_scaling(
            per_chip=(8, 8), steps=2, device_counts=(1, 4), iters=2
        )
        assert [p.n_devices for p in pts] == [1, 4]
        assert pts[1].grid == (16, 16)  # 2x2 mesh of 8x8 tiles
        eff = efficiency(pts)
        assert eff[1] == 1.0 and eff[4] > 0
        assert "eff" in report(pts)


class TestPipelineBubbleBench:
    def test_reports_measured_vs_analytic(self):
        from tpuscratch.bench.pipeline_bench import bench_pipeline_bubble

        r = bench_pipeline_bubble(n_micro=4, feature=64, iters=3)
        # on the virtual CPU mesh this is a labeled proxy; assert the
        # harness structure, not CPU timing fidelity
        assert r.proxy is True
        assert r.n_stages >= 2
        assert r.analytic_bubble == pytest.approx(
            (r.n_stages - 1) / (4 + r.n_stages - 1)
        )
        assert r.wall_s > 0 and r.tick_s > 0
        # CPU-mesh timing is noisy enough that the measured value can
        # stray far outside [0, 1] (observed beyond 10 when another test
        # run shares the cores); assert it is finite only
        assert np.isfinite(r.measured_bubble)
        assert "bubble measured" in r.summary()
        assert "[cpu-mesh proxy]" in r.summary()


class TestHaloTraffic:
    def test_analytic_halo_bytes(self):
        from tpuscratch.bench.weak_scaling import halo_traffic_per_chip

        # 1x1 torus: all transfers self-wrap, zero ICI bytes
        b, cells = halo_traffic_per_chip((1, 1), (64, 64))
        assert b == 0.0 and cells == 64 * 64
        # 2x2 torus, halo 1, f32: every rank sends 2 rows + 2 cols + 4
        # corner cells off-chip (all 8 neighbors are remote on a 2x2
        # torus) = (2*64 + 2*64 + 4) * 4 B
        b, cells = halo_traffic_per_chip((2, 2), (64, 64))
        assert b == (2 * 64 + 2 * 64 + 4) * 4
        # 1x4 ring: N/S wrap on-chip, only E/W + corners leave
        b, _ = halo_traffic_per_chip((1, 4), (64, 64))
        assert b == (2 * 64 + 4) * 4
        # deep:4 amortizes a 4-deep halo over 4 steps: 2 N/S strips of
        # 4x64 + 2 E/W strips of 64x4 + 4 corners of 4x4, f32, / 4 steps
        b4, _ = halo_traffic_per_chip((2, 2), (64, 64), impl="deep:4")
        assert b4 == ((2 * 4 * 64 + 2 * 64 * 4 + 4 * 4 * 4) * 4) / 4

    def test_analytic_halo3d_bytes(self):
        from tpuscratch.bench.weak_scaling import halo3d_traffic_per_chip

        # 1x1x1 torus: everything self-wraps, zero ICI bytes
        b, cells = halo3d_traffic_per_chip((1, 1, 1), (16, 16, 16))
        assert b == 0.0 and cells == 16 ** 3
        # 2x2x2 torus, faces-only, halo 1, f32: 6 face slabs of 16x16
        b, _ = halo3d_traffic_per_chip((2, 2, 2), (16, 16, 16))
        assert b == 6 * 16 * 16 * 4
        # 2x1x1 slab mesh: only the z faces leave the rank
        b, _ = halo3d_traffic_per_chip((2, 1, 1), (16, 16, 16))
        assert b == 2 * 16 * 16 * 4
        # axis-sequential deep exchange at depth s: z slabs carry core
        # extents, y slabs the z-padded extent, x slabs both paddings —
        # amortized over s sweeps (the s-step smoother's accounting)
        s, c = 2, 16
        b, _ = halo3d_traffic_per_chip((2, 2, 2), (c, c, c), depth=s,
                                       sweeps_per_exchange=s)
        expect = (2 * s * c * c
                  + 2 * s * (c + 2 * s) * c
                  + 2 * s * (c + 2 * s) * (c + 2 * s)) * 4
        assert b == expect / s


class TestCollectiveBench:
    def test_verify_all_collectives(self, devices):
        from tpuscratch.bench.collective_bench import verify
        from tpuscratch.runtime.mesh import make_mesh_1d

        assert verify(make_mesh_1d("x", 8))

    def test_sweep_point_shapes_and_busbw(self, devices):
        from tpuscratch.bench.collective_bench import (
            COLLECTIVES,
            _bus_bytes,
            sweep,
        )
        from tpuscratch.runtime.mesh import make_mesh_1d

        mesh = make_mesh_1d("x", 8)
        rs = sweep(mesh, sizes_bytes=(4096,), rounds=2, iters=2)
        assert len(rs) == len(COLLECTIVES)
        for r in rs:
            assert r.p50 > 0 and r.bytes_moved > 0
        # nccl-tests conventions: allreduce moves 2(n-1)/n, ring moves 1x,
        # all_gather's (n-1)/n applies to the GATHERED total (n * shard)
        assert _bus_bytes("psum", 8, 4096, 1) == 2 * 7 * 4096 // 8
        assert _bus_bytes("ppermute", 8, 4096, 1) == 4096
        assert _bus_bytes("all_to_all", 8, 4096, 1) == 7 * 4096 // 8
        assert _bus_bytes("all_gather", 8, 4096, 1) == 7 * 4096


class TestFftBench:
    def test_dft_roundtrip_program_and_accounting(self, devices):
        from tpuscratch.bench.fft_bench import bench_dft
        from tpuscratch.runtime.mesh import make_mesh_1d

        r = bench_dft(n=32, rounds=2, iters=2, mesh=make_mesh_1d("x", 4),
                      fence="block")
        assert r.p50 > 0
        assert r.items == 32 * 32**3 * 2  # 16 N^3 FLOPs per direction, fwd+inv
