// Native pooled host-staging allocator.
//
// The reference's host_allocator.h is a std-compliant allocator over
// cudaMallocHost/cudaFreeHost (host_allocator.h:72-83): page-locked host
// memory so staged transfers DMA at full rate, exercised by the pingpong
// PAGE_LOCKED ablation (test-benchmark/mpi-pingpong-gpu-async.cpp:43-49).
// This is its TPU-host counterpart: page-aligned buffers, optional
// mlock(2) page-locking with graceful fallback (RLIMIT_MEMLOCK is often
// tiny in containers), power-of-two size-class free lists so repeated
// staging reuses buffers instead of round-tripping the OS, and the
// capacity accounting the reference probes by crashing into cudaMalloc
// failures (mpicuda2.cu:44-47) — here an explicit stats surface.
//
// Flat C ABI over an opaque pool handle; bound from Python via ctypes
// (tpuscratch/native/hostpool.py).

#include <cstdint>
#include <cstdlib>
#include <mutex>
#include <unordered_map>
#include <vector>

#if defined(__unix__) || defined(__APPLE__)
#include <sys/mman.h>
#include <unistd.h>
#define TS_HAVE_MLOCK 1
#else
#define TS_HAVE_MLOCK 0
#endif

namespace {

constexpr size_t kMinClass = 4096;  // one page: also the alignment

struct Pool {
  std::mutex mu;
  bool lock_pages = false;
  // size-class -> free buffers of exactly that class size
  std::unordered_map<size_t, std::vector<void*>> cache;
  // outstanding ptr -> its class size
  std::unordered_map<void*, size_t> live;
  // ptrs that mlock succeeded on (must munlock before free)
  std::unordered_map<void*, bool> locked;
  uint64_t bytes_in_use = 0;
  uint64_t bytes_cached = 0;
  uint64_t high_water = 0;
  uint64_t alloc_calls = 0;
  uint64_t reuse_hits = 0;
  uint64_t locked_bytes = 0;
  uint64_t lock_failures = 0;
};

// 0 = unserviceable (so the alloc fails cleanly instead of the shift
// wrapping past 2^63 and spinning forever)
size_t size_class(uint64_t n) {
  if (n > (uint64_t{1} << 62)) return 0;
  size_t c = kMinClass;
  while (c < n) c <<= 1;
  return c;
}

void release_buffer(Pool* p, void* ptr, size_t cls) {
#if TS_HAVE_MLOCK
  auto it = p->locked.find(ptr);
  if (it != p->locked.end()) {
    if (it->second) {
      munlock(ptr, cls);
      p->locked_bytes -= cls;
    }
    p->locked.erase(it);
  }
#else
  (void)cls;
  p->locked.erase(ptr);
#endif
  std::free(ptr);
}

}  // namespace

extern "C" {

void* ts_pool_create(int32_t lock_pages) {
  Pool* p = new (std::nothrow) Pool;
  if (p) p->lock_pages = lock_pages != 0;
  return p;
}

// Page-aligned buffer of at least `size` bytes (rounded up to its
// power-of-two size class). NULL on exhaustion or size 0.
void* ts_pool_alloc(void* pool, uint64_t size) {
  Pool* p = static_cast<Pool*>(pool);
  if (!p || size == 0) return nullptr;
  const size_t cls = size_class(size);
  if (cls == 0) return nullptr;
  std::lock_guard<std::mutex> g(p->mu);
  p->alloc_calls++;
  void* ptr = nullptr;
  auto it = p->cache.find(cls);
  if (it != p->cache.end() && !it->second.empty()) {
    ptr = it->second.back();
    it->second.pop_back();
    p->bytes_cached -= cls;
    p->reuse_hits++;
  } else {
    if (posix_memalign(&ptr, kMinClass, cls) != 0) return nullptr;
    if (p->lock_pages) {
#if TS_HAVE_MLOCK
      if (mlock(ptr, cls) == 0) {
        p->locked[ptr] = true;
        p->locked_bytes += cls;
      } else {
        p->locked[ptr] = false;
        p->lock_failures++;
      }
#else
      p->lock_failures++;
#endif
    }
  }
  p->live[ptr] = cls;
  p->bytes_in_use += cls;
  if (p->bytes_in_use > p->high_water) p->high_water = p->bytes_in_use;
  return ptr;
}

// Return a buffer to the free list. Unknown/double-freed pointers are
// ignored (counted nowhere: the Python binding owns pointer discipline).
void ts_pool_free(void* pool, void* ptr) {
  Pool* p = static_cast<Pool*>(pool);
  if (!p || !ptr) return;
  std::lock_guard<std::mutex> g(p->mu);
  auto it = p->live.find(ptr);
  if (it == p->live.end()) return;
  const size_t cls = it->second;
  p->live.erase(it);
  p->bytes_in_use -= cls;
  p->cache[cls].push_back(ptr);
  p->bytes_cached += cls;
}

// Release every cached (free-listed) buffer back to the OS.
void ts_pool_trim(void* pool) {
  Pool* p = static_cast<Pool*>(pool);
  if (!p) return;
  std::lock_guard<std::mutex> g(p->mu);
  for (auto& kv : p->cache)
    for (void* ptr : kv.second) release_buffer(p, ptr, kv.first);
  p->cache.clear();
  p->bytes_cached = 0;
}

// out[8] = {bytes_in_use, bytes_cached, high_water, alloc_calls,
//           reuse_hits, locked_bytes, lock_failures, page_class}
void ts_pool_stats(void* pool, uint64_t* out) {
  Pool* p = static_cast<Pool*>(pool);
  if (!p || !out) return;
  std::lock_guard<std::mutex> g(p->mu);
  out[0] = p->bytes_in_use;
  out[1] = p->bytes_cached;
  out[2] = p->high_water;
  out[3] = p->alloc_calls;
  out[4] = p->reuse_hits;
  out[5] = p->locked_bytes;
  out[6] = p->lock_failures;
  out[7] = kMinClass;
}

// Free everything — cached AND outstanding — then the pool itself.
void ts_pool_destroy(void* pool) {
  Pool* p = static_cast<Pool*>(pool);
  if (!p) return;
  {
    std::lock_guard<std::mutex> g(p->mu);
    for (auto& kv : p->cache)
      for (void* ptr : kv.second) release_buffer(p, ptr, kv.first);
    p->cache.clear();
    for (auto& kv : p->live) release_buffer(p, kv.first, kv.second);
    p->live.clear();
  }
  delete p;
}

}  // extern "C"
