// Native halo-exchange planner: topology + region geometry + plan builder.
//
// The reference's one true library is native C++ (the header-only templated
// stencil2D.h: cartesian neighbor math at :232-299, 13-case region geometry
// at :107-201, transfer-plan construction at :319-437). This is its
// counterpart for the XLA backend: the same trace-time planning work —
// neighbor tables, send/recv rectangles, ppermute permutations — done in
// compiled code and handed to Python over a flat C ABI (ctypes). The hot
// DATA path stays in XLA; this is the hot PLANNING path for large meshes,
// where building 8 permutation tables for thousands of ranks in Python
// is measurable at trace time.
//
// Conventions (must match tpuscratch/runtime/topology.py and
// tpuscratch/halo/layout.py exactly; tests cross-check):
//   - row-major ranks over (rows, cols); coords (r, c); row 0 = top
//   - direction = (dr, dc) in {-1,0,1}^2 \ {(0,0)}
//   - rect = {oy, ox, h, w} in padded-tile coordinates
//   - missing neighbor (open boundary) = -1

#include <cstdint>

extern "C" {

// Bumped whenever an exported signature changes; the Python binding
// refuses libraries older than it expects (a stale .so called through a
// newer ctypes prototype would silently read garbage arguments).
int32_t ts_abi_version() { return 2; }

// Rank at coords + (dr,dc), honoring per-axis periodicity; -1 if off-grid.
int32_t ts_neighbor(int32_t rows, int32_t cols, int32_t per_r, int32_t per_c,
                    int32_t rank, int32_t dr, int32_t dc) {
  if (rows <= 0 || cols <= 0 || rank < 0 || rank >= rows * cols) return -1;
  int32_t r = rank / cols + dr;
  int32_t c = rank % cols + dc;
  if (r < 0 || r >= rows) {
    if (!per_r) return -1;
    r = ((r % rows) + rows) % rows;
  }
  if (c < 0 || c >= cols) {
    if (!per_c) return -1;
    c = ((c % cols) + cols) % cols;
  }
  return r * cols + c;
}

// (src, dst) pairs where every rank sends toward (dr,dc). Returns the pair
// count; src/dst must hold rows*cols entries.
int32_t ts_send_permutation(int32_t rows, int32_t cols, int32_t per_r,
                            int32_t per_c, int32_t dr, int32_t dc,
                            int32_t* src, int32_t* dst) {
  int32_t n = 0;
  for (int32_t rank = 0; rank < rows * cols; ++rank) {
    int32_t nb = ts_neighbor(rows, cols, per_r, per_c, rank, dr, dc);
    if (nb >= 0) {
      src[n] = rank;
      dst[n] = nb;
      ++n;
    }
  }
  return n;
}

// The ghost-border piece in direction (dr,dc) — the receive landing zone.
void ts_halo_rect(int32_t core_h, int32_t core_w, int32_t hy, int32_t hx,
                  int32_t dr, int32_t dc, int32_t* rect) {
  rect[0] = dr < 0 ? 0 : (dr > 0 ? hy + core_h : hy);  // oy
  rect[2] = dr == 0 ? core_h : hy;                     // h
  rect[1] = dc < 0 ? 0 : (dc > 0 ? hx + core_w : hx);  // ox
  rect[3] = dc == 0 ? core_w : hx;                     // w
}

// The core strip adjacent to edge (dr,dc) — what travels to that neighbor.
void ts_send_rect(int32_t core_h, int32_t core_w, int32_t hy, int32_t hx,
                  int32_t dr, int32_t dc, int32_t* rect) {
  rect[0] = dr > 0 ? core_h : hy;   // oy (dr>0: bottom strip starts at
                                    //     hy + core_h - hy == core_h)
  rect[2] = dr == 0 ? core_h : hy;  // h
  rect[1] = dc > 0 ? core_w : hx;   // ox
  rect[3] = dc == 0 ? core_w : hx;  // w
}

// Full plan: for each direction d of the 8 (or 4 edge-only), the data
// arriving in my d-halo flows toward opposite(d). Outputs, per direction i:
//   dirs[2i..] = (dr, dc) of the halo piece
//   send_rects[4i..] / recv_rects[4i..]
//   perm pairs at perm_src/dst[i*rows*cols ..], count in perm_counts[i]
// Returns the direction count, or -1 on invalid input.
int32_t ts_build_plan(int32_t rows, int32_t cols, int32_t per_r, int32_t per_c,
                      int32_t core_h, int32_t core_w, int32_t hy, int32_t hx,
                      int32_t neighbors, int32_t* dirs, int32_t* send_rects,
                      int32_t* recv_rects, int32_t* perm_src, int32_t* perm_dst,
                      int32_t* perm_counts) {
  if (rows <= 0 || cols <= 0 || core_h <= 0 || core_w <= 0 || hy < 0 ||
      hx < 0 || hy > core_h || hx > core_w)
    return -1;
  if (neighbors != 4 && neighbors != 8) return -1;
  // Same stable order as topology.ALL_DIRECTIONS: edges then corners.
  static const int32_t kDirs[8][2] = {{-1, 0}, {1, 0},  {0, -1}, {0, 1},
                                      {-1, -1}, {-1, 1}, {1, -1}, {1, 1}};
  const int32_t ndirs = neighbors == 8 ? 8 : 4;
  const int32_t stride = rows * cols;
  for (int32_t i = 0; i < ndirs; ++i) {
    const int32_t dr = kDirs[i][0], dc = kDirs[i][1];
    dirs[2 * i] = dr;
    dirs[2 * i + 1] = dc;
    // flow direction is opposite(d): my d-neighbor sends toward -d
    ts_send_rect(core_h, core_w, hy, hx, -dr, -dc, send_rects + 4 * i);
    ts_halo_rect(core_h, core_w, hy, hx, dr, dc, recv_rects + 4 * i);
    perm_counts[i] =
        ts_send_permutation(rows, cols, per_r, per_c, -dr, -dc,
                            perm_src + i * stride, perm_dst + i * stride);
  }
  return ndirs;
}

// ---------------------------------------------------------------------------
// 3D face-only planner (mirrors tpuscratch/halo/halo3d.py). Rank layout is
// row-major over (dz, dy, dx); rect = {o0, o1, o2, e0, e1, e2} in padded
// coords; the 6 faces use the same stable order as halo3d.FACES.
// ---------------------------------------------------------------------------

// Rank at coords + off, honoring per-axis periodicity; -1 if off-grid.
int32_t ts_neighbor3d(int32_t dz, int32_t dy, int32_t dx, int32_t per_z,
                      int32_t per_y, int32_t per_x, int32_t rank, int32_t oz,
                      int32_t oy, int32_t ox) {
  if (dz <= 0 || dy <= 0 || dx <= 0 || rank < 0 || rank >= dz * dy * dx)
    return -1;
  int32_t dims[3] = {dz, dy, dx};
  int32_t per[3] = {per_z, per_y, per_x};
  int32_t off[3] = {oz, oy, ox};
  int32_t c[3] = {rank / (dy * dx), (rank / dx) % dy, rank % dx};
  for (int a = 0; a < 3; ++a) {
    c[a] += off[a];
    if (c[a] < 0 || c[a] >= dims[a]) {
      if (!per[a]) return -1;
      c[a] = ((c[a] % dims[a]) + dims[a]) % dims[a];
    }
  }
  return (c[0] * dy + c[1]) * dx + c[2];
}

// Full plan over `neighbors` (6 face-only or all 26) directions.
// Outputs, per direction i:
//   offs[3i..]   = the offset (halo side)
//   send_rects[6i..] / recv_rects[6i..] = {o0,o1,o2,e0,e1,e2}
//   perm pairs at perm_src/dst[i*nranks ..], count in perm_counts[i]
// Returns the direction count, or -1 on invalid input.
int32_t ts_build_plan3d(int32_t dz, int32_t dy, int32_t dx, int32_t per_z,
                        int32_t per_y, int32_t per_x, int32_t cz, int32_t cy,
                        int32_t cx, int32_t hz, int32_t hy, int32_t hx,
                        int32_t neighbors, int32_t* offs, int32_t* send_rects,
                        int32_t* recv_rects, int32_t* perm_src,
                        int32_t* perm_dst, int32_t* perm_counts) {
  if (dz <= 0 || dy <= 0 || dx <= 0 || cz <= 0 || cy <= 0 || cx <= 0 ||
      hz < 0 || hy < 0 || hx < 0 || hz > cz || hy > cy || hx > cx)
    return -1;
  if (neighbors != 6 && neighbors != 26) return -1;
  // Same stable order as halo3d.OFFSETS26: faces, then edges, then
  // corners, each block sorted lexicographically.
  static const int32_t kDirs[26][3] = {
      {-1, 0, 0},  {1, 0, 0},   {0, -1, 0},  {0, 1, 0},   {0, 0, -1},
      {0, 0, 1},   {-1, -1, 0}, {-1, 0, -1}, {-1, 0, 1},  {-1, 1, 0},
      {0, -1, -1}, {0, -1, 1},  {0, 1, -1},  {0, 1, 1},   {1, -1, 0},
      {1, 0, -1},  {1, 0, 1},   {1, 1, 0},   {-1, -1, -1}, {-1, -1, 1},
      {-1, 1, -1}, {-1, 1, 1},  {1, -1, -1}, {1, -1, 1},  {1, 1, -1},
      {1, 1, 1}};
  const int32_t core[3] = {cz, cy, cx};
  const int32_t halo[3] = {hz, hy, hx};
  const int32_t nranks = dz * dy * dx;
  for (int32_t i = 0; i < neighbors; ++i) {
    const int32_t* d = kDirs[i];
    for (int a = 0; a < 3; ++a) {
      offs[3 * i + a] = d[a];
      const int32_t o = d[a], c = core[a], h = halo[a];
      // send slab travels toward flow = -d (the neighbor feeding my d halo)
      const int32_t f = -o;
      send_rects[6 * i + a] = f > 0 ? c : h;       // start (f>0: h+c-h == c)
      send_rects[6 * i + 3 + a] = f == 0 ? c : h;  // extent
      recv_rects[6 * i + a] = o < 0 ? 0 : (o > 0 ? h + c : h);
      recv_rects[6 * i + 3 + a] = o == 0 ? c : h;
    }
    int32_t n = 0;
    for (int32_t rank = 0; rank < nranks; ++rank) {
      int32_t nb = ts_neighbor3d(dz, dy, dx, per_z, per_y, per_x, rank,
                                 -d[0], -d[1], -d[2]);
      if (nb >= 0) {
        perm_src[i * nranks + n] = rank;
        perm_dst[i * nranks + n] = nb;
        ++n;
      }
    }
    perm_counts[i] = n;
  }
  return neighbors;
}

}  // extern "C"
