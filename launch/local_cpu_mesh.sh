#!/usr/bin/env bash
# Single-box dev path: run any workload on a virtual N-device CPU mesh.
#
# The reference validates multi-node code by running many MPI ranks on one
# node (mpicuda2.cu:31-32); this is the same loop for the XLA backend.
#
# Usage: ./launch/local_cpu_mesh.sh [-n devices] script.py [args...]
set -euo pipefail

N=8
if [ "${1:-}" = "-n" ]; then N="$2"; shift 2; fi
WORKLOAD="${1:?usage: local_cpu_mesh.sh [-n devices] <script.py> [args...]}"
shift || true

exec env -u PYTHONPATH \
  JAX_PLATFORMS=cpu \
  XLA_FLAGS="--xla_force_host_platform_device_count=${N}" \
  python "$WORKLOAD" "$@"
