#!/usr/bin/env bash
# Run a tpuscratch workload on every worker of a TPU-VM slice.
#
# Replaces the reference's PBS/SLURM + mpiexec.hydra job scripts
# (mpi_pbs_sample.sh, job_9_1_1_cuda-2d-stencil-subarray.slurm): the slice
# plays the scheduler's role, --worker=all plays mpiexec's.
#
# Usage:
#   TPU_NAME=my-slice ZONE=us-central1-a ./launch/tpu_slice_run.sh \
#       examples/ex09_stencil2d.py
set -euo pipefail

TPU_NAME="${TPU_NAME:?set TPU_NAME to the slice name}"
ZONE="${ZONE:?set ZONE}"
PROJECT="${PROJECT:-}"
WORKLOAD="${1:?usage: tpu_slice_run.sh <script.py> [args...]}"
shift || true

PROJ_FLAG=()
[ -n "$PROJECT" ] && PROJ_FLAG=(--project "$PROJECT")

# One process per host; jax's TPU auto-detection performs the rendezvous
# (the MPI_Init equivalent) across workers.
exec gcloud compute tpus tpu-vm ssh "$TPU_NAME" \
  --zone "$ZONE" "${PROJ_FLAG[@]}" \
  --worker=all \
  --command "cd ~/tpuscratch && TPUSCRATCH_ON_DEVICE=1 python $WORKLOAD $*"
