"""ctypes binding for the native pooled host-staging allocator
(native/src/host_pool.cpp) — the TPU-host counterpart of the reference's
pinned ``host_allocator<T>`` (host_allocator.h:58-93).

Page-aligned, size-class-pooled host buffers with optional mlock(2)
page-locking. Used by the pingpong staging ablations (the role
host_allocator plays in mpi-pingpong-gpu-async.cpp:43-49) and available
to any host-staging path (checkpoint serialization, decompose/assemble).

``HostBuffer.view()`` exposes the buffer as a zero-copy numpy array, so
staging is ``view[:] = np.asarray(device_arr)`` in and
``jax.device_put(view)`` out.
"""

from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np

from tpuscratch import native

_STATS_FIELDS = (
    "bytes_in_use",
    "bytes_cached",
    "high_water",
    "alloc_calls",
    "reuse_hits",
    "locked_bytes",
    "lock_failures",
    "page_class",
)

_configured = False


def _lib():
    lib = native.load()
    if lib is None:
        return None
    global _configured
    if not _configured:
        u64 = ctypes.c_uint64
        vp = ctypes.c_void_p
        lib.ts_pool_create.restype = vp
        lib.ts_pool_create.argtypes = [ctypes.c_int32]
        lib.ts_pool_alloc.restype = vp
        lib.ts_pool_alloc.argtypes = [vp, u64]
        lib.ts_pool_free.restype = None
        lib.ts_pool_free.argtypes = [vp, vp]
        lib.ts_pool_trim.restype = None
        lib.ts_pool_trim.argtypes = [vp]
        lib.ts_pool_stats.restype = None
        lib.ts_pool_stats.argtypes = [vp, ctypes.POINTER(u64)]
        lib.ts_pool_destroy.restype = None
        lib.ts_pool_destroy.argtypes = [vp]
        _configured = True
    return lib


def available() -> bool:
    return _lib() is not None


class HostBuffer:
    """One pooled buffer. Returns to the pool on ``free()``/``with`` exit;
    views become invalid afterwards (the buffer may be reused)."""

    def __init__(self, pool: "HostPool", ptr: int, nbytes: int):
        self._pool = pool
        self._ptr: Optional[int] = ptr
        self.nbytes = nbytes

    @property
    def ptr(self) -> int:
        if self._ptr is None:
            raise ValueError("buffer already returned to the pool")
        return self._ptr

    def view(self, dtype=np.uint8, shape: Optional[tuple] = None) -> np.ndarray:
        """Zero-copy numpy view of (a prefix of) the buffer."""
        dtype = np.dtype(dtype)
        if shape is None:
            shape = (self.nbytes // dtype.itemsize,)
        need = int(np.prod(shape)) * dtype.itemsize
        if need > self.nbytes:
            raise ValueError(f"view of {need} B exceeds buffer {self.nbytes} B")
        raw = (ctypes.c_byte * need).from_address(self.ptr)
        return np.frombuffer(raw, dtype=dtype).reshape(shape)

    def free(self) -> None:
        if self._ptr is not None:
            self._pool._free(self._ptr)
            self._ptr = None

    def __enter__(self) -> "HostBuffer":
        return self

    def __exit__(self, *exc) -> None:
        self.free()


class HostPool:
    """Pooled page-aligned (optionally page-locked) host buffers."""

    def __init__(self, lock_pages: bool = True):
        lib = _lib()
        if lib is None:
            raise RuntimeError(
                "native library unavailable — tpuscratch.native.build() "
                "or `make -C native` first"
            )
        self._handle = lib.ts_pool_create(1 if lock_pages else 0)
        if not self._handle:
            raise MemoryError("ts_pool_create failed")

    def alloc(self, nbytes: int) -> HostBuffer:
        if nbytes <= 0:
            raise ValueError(f"alloc of {nbytes} bytes")
        ptr = _lib().ts_pool_alloc(self._handle, nbytes)
        if not ptr:
            raise MemoryError(f"host pool exhausted allocating {nbytes} B")
        return HostBuffer(self, ptr, nbytes)

    def _free(self, ptr: int) -> None:
        if self._handle:
            _lib().ts_pool_free(self._handle, ptr)

    def trim(self) -> None:
        """Release cached (free-listed) buffers back to the OS."""
        _lib().ts_pool_trim(self._handle)

    def stats(self) -> dict:
        out = (ctypes.c_uint64 * len(_STATS_FIELDS))()
        _lib().ts_pool_stats(self._handle, out)
        return dict(zip(_STATS_FIELDS, (int(v) for v in out)))

    def close(self) -> None:
        if self._handle:
            _lib().ts_pool_destroy(self._handle)
            self._handle = None

    def __enter__(self) -> "HostPool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


_default: Optional[HostPool] = None


def default_pool() -> HostPool:
    """Process-wide pool (page-locking on, falling back silently where
    RLIMIT_MEMLOCK forbids — see ``stats()['lock_failures']``)."""
    global _default
    if _default is None or _default._handle is None:
        _default = HostPool(lock_pages=True)
    return _default
