"""Runtime bring-up layer: topology, mesh, config, errors, logging.

TPU-native replacement for the reference's L2 runtime layer
(``mpierr.h``, ``cuda_error_handler.h``, device binding and cartesian
communicator setup — see SURVEY.md §1).
"""

from tpuscratch.runtime.topology import CartTopology, Direction  # noqa: F401
from tpuscratch.runtime.mesh import make_mesh, make_mesh_1d, make_mesh_2d  # noqa: F401
from tpuscratch.runtime.config import Config  # noqa: F401
from tpuscratch.runtime.errors import CommError, ErrorPolicy, guarded  # noqa: F401
from tpuscratch.runtime.context import RuntimeContext, initialize  # noqa: F401
from tpuscratch.runtime.log import RankLogger  # noqa: F401
from tpuscratch.runtime.memory import (  # noqa: F401
    donate,
    live_bytes,
    memory_stats,
    pin_to_host,
    to_device,
)
