"""Memory placement, staging, and introspection — the allocator layer.

The reference's ``host_allocator.h`` is a std-compliant allocator over
``cudaMallocHost``/``cudaFreeHost`` (host_allocator.h:72-83): page-locked
host memory so staged transfers DMA at full speed, used by the pingpong
PAGE_LOCKED ablation (test-benchmark/mpi-pingpong-gpu-async.cpp:43-49).

TPU-natively the same capability is a *placement* property, not an
allocator: every ``jax.Array`` lives in an XLA memory space — ``device``
(HBM), ``pinned_host`` (page-locked host RAM, DMA-capable), or
``unpinned_host`` — carried by its sharding's ``memory_kind``. Moving an
array between spaces is ``jax.device_put`` with the same sharding under a
different memory kind, which preserves the distributed layout. Manual
buffer reuse (the other thing a CUDA allocator is for) becomes jit
donation. This module wraps those idioms behind small named helpers and
adds live-memory introspection in the spirit of the reference's capacity
probing (mpicuda2.cu:44-47: cudaMalloc failures at 16 ranks).
"""

from __future__ import annotations

from typing import Callable, Optional

DEVICE = "device"
PINNED_HOST = "pinned_host"
UNPINNED_HOST = "unpinned_host"


def _default_device():
    import jax

    return jax.devices()[0]


def memory_kinds(device=None) -> tuple[str, ...]:
    """Memory spaces addressable from ``device`` (e.g. device/pinned_host)."""
    device = device if device is not None else _default_device()
    return tuple(m.kind for m in device.addressable_memories())


def supports_kind(kind: str, device=None) -> bool:
    return kind in memory_kinds(device)


def put(x, kind: str = DEVICE):
    """Place ``x`` in memory space ``kind``, preserving its sharding.

    The analogue of choosing the allocator in the reference: the array's
    logical layout (shape, sharding over the mesh) is untouched; only the
    memory space backing each shard changes.
    """
    import jax

    sharding = x.sharding if hasattr(x, "sharding") else None
    if sharding is None:  # numpy / python input: single-device placement
        import jax.numpy as jnp

        x = jnp.asarray(x)
        sharding = x.sharding
    return jax.device_put(x, sharding.with_memory_kind(kind))


def pin_to_host(x):
    """Stage ``x`` into page-locked host memory (cudaMallocHost analogue)."""
    return put(x, PINNED_HOST)


def to_device(x):
    """Bring ``x`` (back) into device HBM."""
    return put(x, DEVICE)


def host_roundtrip(x, pinned: bool = True):
    """Device -> host -> device staging pass; the HOST_COPY/PAGE_LOCKED
    ablation pair (mpi-pingpong-gpu-async.cpp:59-70,43-49): ``pinned``
    selects page-locked vs pageable host memory."""
    kind = PINNED_HOST if pinned else UNPINNED_HOST
    return to_device(put(x, kind))


def donate(fn: Callable, argnums=0, **jit_kwargs):
    """jit ``fn`` with donated input buffers — the TPU-native form of the
    reference's in-place buffer reuse (send buffer == recv buffer patterns).
    Donated inputs' HBM is handed to the outputs; callers must not reuse
    the donated arrays afterwards."""
    import jax

    argnums = (argnums,) if isinstance(argnums, int) else tuple(argnums)
    return jax.jit(fn, donate_argnums=argnums, **jit_kwargs)


def live_bytes(device=None, kind: Optional[str] = None) -> int:
    """Bytes held by live jax.Arrays on ``device`` (all devices if None),
    optionally filtered by memory kind. A backend-independent census for
    capacity probing where ``memory_stats`` is unavailable."""
    import math

    import jax

    total = 0
    for arr in jax.live_arrays():
        try:
            if kind is not None and arr.sharding.memory_kind != kind:
                continue
            devs = arr.sharding.device_set
            if device is not None and device not in devs:
                continue
            if arr.is_deleted():
                continue
            # actual per-device footprint: one shard's bytes (replication
            # means every device holds a full shard, so count each device)
            shard_elems = math.prod(
                arr.sharding.shard_shape(arr.shape)
            )
            shard_bytes = shard_elems * arr.dtype.itemsize
            n_holding = 1 if device is not None else len(devs)
            total += shard_bytes * n_holding
        except Exception:  # array mid-deletion during iteration
            continue
    return total


def memory_stats(device=None) -> dict:
    """The backend's allocator stats (bytes_in_use etc.) when it reports
    them, else a census dict built from live arrays."""
    device = device if device is not None else _default_device()
    stats = device.memory_stats() or {}
    if stats:
        return dict(stats)
    return {
        "bytes_in_use": live_bytes(device),
        "source": "live_arrays_census",
    }
