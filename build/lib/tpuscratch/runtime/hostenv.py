"""Host-environment control: pinning a process to a virtual CPU device mesh.

The framework's answer to the reference's trick of exercising multi-node code
with N MPI ranks on one box (/root/reference/mpicuda2.cu:31-32): an N-device
virtual CPU mesh via ``--xla_force_host_platform_device_count``. The only
subtlety is environments where an accelerator PJRT plugin monkey-patches
jax's backend lookup (e.g. the axon TPU tunnel in this image) so that ANY
``jax.devices()`` call tries to claim the real chip — which hangs or wastes
the single-chip session during CPU-only test runs. ``force_cpu_devices``
defuses that by dropping the plugin's backend factory before first backend
initialization.

Must be called BEFORE any jax computation / ``jax.devices()`` in the process.
"""

from __future__ import annotations

import os
import re


def force_cpu_devices(n: int = 8) -> None:
    """Make this process see exactly ``n`` virtual CPU devices.

    Safe to call only before jax initializes its backends; raises if a
    backend already exists with the wrong platform.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    flag = f"--xla_force_host_platform_device_count={n}"
    if "xla_force_host_platform_device_count" in flags:
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+", flag, flags
        )
        os.environ["XLA_FLAGS"] = flags
    else:
        os.environ["XLA_FLAGS"] = (flags + " " + flag).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    from jax._src import xla_bridge as xb

    # Accelerator plugins registered via sitecustomize (axon) both add a
    # backend factory and may override the platforms config; drop the
    # factory and pin the config so backends() never dials the chip.
    for plugin in ("axon",):
        try:
            xb._backend_factories.pop(plugin, None)  # noqa: SLF001
        except Exception:  # pragma: no cover - registry layout changed
            pass

    import jax

    jax.config.update("jax_platforms", "cpu")

    if xb._default_backend is not None and xb._default_backend.platform != "cpu":  # noqa: SLF001
        raise RuntimeError(
            "force_cpu_devices() called after jax already initialized a "
            f"non-CPU backend ({xb._default_backend.platform})"  # noqa: SLF001
        )


_TRUTHY = ("1", "true", "yes", "on")


def on_device_requested() -> bool:
    """True when TPUSCRATCH_ON_DEVICE asks for the real hardware mesh."""
    return os.environ.get("TPUSCRATCH_ON_DEVICE", "").strip().lower() in _TRUTHY


def ensure_devices(n: int = 8):
    """Return jax with >= n visible devices (virtual CPU mesh unless opted out).

    The single bring-up helper shared by examples and driver entry points:
    unless TPUSCRATCH_ON_DEVICE requests real hardware, pins an n-device
    virtual CPU mesh (only possible before jax's first backend init).
    """
    if not on_device_requested():
        from jax._src import xla_bridge as xb

        if xb._default_backend is None:  # noqa: SLF001
            force_cpu_devices(n)
        elif xb._default_backend.platform != "cpu":  # noqa: SLF001
            raise RuntimeError(
                "jax already initialized on platform "
                f"'{xb._default_backend.platform}' without "  # noqa: SLF001
                "TPUSCRATCH_ON_DEVICE=1 — refusing to run the CPU dev/test "
                "path on real hardware; set TPUSCRATCH_ON_DEVICE=1 to opt "
                "in, or call ensure_devices() before any jax use"
            )
    import jax

    if len(jax.devices()) < n:
        raise RuntimeError(
            f"{len(jax.devices())} device(s) visible but {n} needed — jax "
            "was already initialized (or TPUSCRATCH_ON_DEVICE is set) on a "
            "smaller platform; call force_cpu_devices(n) before any jax "
            "use, or run on a larger host"
        )
    return jax


def on_tpu() -> bool:
    """True when the default jax backend is a TPU (initializes backends)."""
    import jax

    return jax.default_backend() == "tpu"
