"""Rank-aware logging with the buffer-then-single-write discipline.

The reference avoids interleaved stdout across ranks by accumulating into a
``std::ostringstream`` and writing once (/root/reference/mpi7.cpp:56-62), and
silences output entirely under ``NO_LOG`` (mpicuda2.cu:183-188). RankLogger
reproduces both: messages are prefixed with rank/coords identity, optionally
buffered and flushed as one write, and the whole logger can be disabled.
Per-rank file output keyed by grid coordinates mirrors the stencil drivers'
``<x>_<y>`` dump files (mpi-2d-stencil-subarray.cpp:60-62).
"""

from __future__ import annotations

import io
import sys
from typing import Optional, Sequence, TextIO


class RankLogger:
    def __init__(
        self,
        rank: Optional[int] = None,
        coords: Optional[Sequence[int]] = None,
        enabled: bool = True,
        buffered: bool = False,
        stream: Optional[TextIO] = None,
    ):
        self.rank = rank
        self.coords = tuple(coords) if coords is not None else None
        self.enabled = enabled
        self.buffered = buffered
        self._stream = stream if stream is not None else sys.stdout
        self._buf = io.StringIO()

    @property
    def prefix(self) -> str:
        parts = []
        if self.rank is not None:
            parts.append(f"rank {self.rank}")
        if self.coords is not None:
            parts.append("(" + ",".join(map(str, self.coords)) + ")")
        return f"[{' '.join(parts)}] " if parts else ""

    def log(self, *values) -> None:
        if not self.enabled:
            return
        line = self.prefix + " ".join(str(v) for v in values) + "\n"
        if self.buffered:
            self._buf.write(line)
        else:
            self._stream.write(line)
            self._stream.flush()

    __call__ = log

    def log0(self, *values) -> None:
        """Log only on rank 0 (the reference's root-only printouts)."""
        if self.rank in (None, 0):
            self.log(*values)

    def flush(self) -> None:
        """Single write of everything buffered (ostringstream pattern)."""
        text = self._buf.getvalue()
        if text:
            self._stream.write(text)
            self._stream.flush()
            self._buf = io.StringIO()

    def __enter__(self) -> "RankLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.flush()


def coord_filename(coords: Sequence[int], prefix: str = "") -> str:
    """Per-rank output filename keyed by grid coordinates: '0_1', '2_2'...
    exactly as the stencil drivers name their dumps
    (mpi-2d-stencil-subarray.cpp:60-62, sample-output/0_0...2_2)."""
    return prefix + "_".join(str(c) for c in coords)
