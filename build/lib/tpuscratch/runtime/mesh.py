"""Device-mesh construction: the framework's communicator factory.

TPU-native replacement for ``MPI_COMM_WORLD`` + sub-communicators + device
binding. Where the reference binds each MPI rank to a GPU before MPI_Init
(/root/reference/stencil2d/mpi-2d-stencil-subarray-cuda.cu:40-73) and builds
cartesian communicators over ranks, here a ``jax.sharding.Mesh`` names the
device axes once and every collective is addressed by axis name. A
sub-communicator (``MPI_Comm_create`` in /root/reference/mpi9.cpp:27-44) is
just a second mesh axis: collectives over one named axis run concurrently
within each slice of the other, with no group objects to free.

Device order contract: ``make_mesh(shape)`` reshapes ``jax.devices()``
row-major, so mesh position == ``CartTopology`` rank == flat device index.
All permutation tables built from ``CartTopology`` are therefore directly
valid for ``lax.ppermute`` inside ``shard_map`` over these meshes.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from tpuscratch.runtime.topology import CartTopology, factor2d


def device_count(backend: Optional[str] = None) -> int:
    return len(jax.devices(backend))


def make_mesh(
    shape: Sequence[int],
    axis_names: Sequence[str],
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh of the given shape over the first prod(shape) devices."""
    shape = tuple(shape)
    names = tuple(axis_names)
    if len(shape) != len(names):
        raise ValueError(f"shape {shape} and axis_names {names} length mismatch")
    devs = list(devices) if devices is not None else jax.devices()
    need = math.prod(shape)
    if need > len(devs):
        raise ValueError(f"mesh {shape} needs {need} devices, have {len(devs)}")
    grid = np.array(devs[:need], dtype=object).reshape(shape)
    return Mesh(grid, names)


def make_mesh_1d(
    name: str = "x",
    n: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """1D mesh over all (or the first n) devices — the MPI_COMM_WORLD analogue."""
    devs = list(devices) if devices is not None else jax.devices()
    n = len(devs) if n is None else n
    return make_mesh((n,), (name,), devs)


def make_mesh_2d(
    shape: Optional[tuple[int, int]] = None,
    axis_names: tuple[str, str] = ("row", "col"),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """2D mesh; defaults to the most-square factorization of all devices.

    The cartesian-communicator analogue (/root/reference/mpi10.cpp:27). A
    square device count gives the reference drivers' sqrt(N) x sqrt(N) layout.
    """
    devs = list(devices) if devices is not None else jax.devices()
    if shape is None:
        shape = factor2d(len(devs))
    return make_mesh(shape, axis_names, devs)


def topology_of(mesh: Mesh, periodic: bool | Sequence[bool] = True) -> CartTopology:
    """The CartTopology matching a mesh's shape (rank == flat device index)."""
    dims = tuple(mesh.devices.shape)
    if isinstance(periodic, bool):
        per = tuple(periodic for _ in dims)
    else:
        per = tuple(periodic)
    return CartTopology(dims, per)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def shard_along(mesh: Mesh, *axis_names: Optional[str]) -> NamedSharding:
    """NamedSharding partitioning array dim i along mesh axis axis_names[i]."""
    return NamedSharding(mesh, PartitionSpec(*axis_names))
