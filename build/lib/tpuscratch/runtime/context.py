"""Runtime bring-up: the framework's MPI_Init / rank / size / hostname layer.

Reference prologue (every program): MPI_Init, MPI_Comm_size, MPI_Comm_rank,
MPI_Get_processor_name (/root/reference/mpi1.cpp:11-14), error-handler
installation (mpi2.cpp:32), and — for GPU programs — binding the process to a
device from launcher env vars BEFORE init
(/root/reference/stencil2d/mpi-2d-stencil-subarray-cuda.cu:40-73).

TPU-native version: a single ``initialize()`` that (a) on multi-host slices
calls ``jax.distributed.initialize`` (the rendezvous MPI_Init performs),
(b) introspects process index/count, local/global devices and hostname, and
(c) returns an immutable RuntimeContext. Device binding needs no env-var
gymnastics: each jax process owns its local devices by construction — the
property the reference's BindDevice hand-rolls with
MV2_COMM_WORLD_LOCAL_RANK % device_count.
"""

from __future__ import annotations

import dataclasses
import socket
from typing import Optional, Sequence

import jax

from tpuscratch.runtime.errors import ErrorPolicy, guarded

_initialized_distributed = False


@dataclasses.dataclass(frozen=True)
class RuntimeContext:
    """Identity of this process within the job (MPI rank/size analogue)."""

    process_index: int
    process_count: int
    hostname: str
    backend: str
    local_devices: tuple
    global_devices: tuple

    @property
    def is_root(self) -> bool:
        return self.process_index == 0

    @property
    def local_device_count(self) -> int:
        return len(self.local_devices)

    @property
    def global_device_count(self) -> int:
        return len(self.global_devices)

    def hello(self) -> str:
        """'task N of M on HOST' — the mpi1 hello line (mpi1.cpp:15-16),
        extended with the device identity the GPU programs log at startup
        (mpicuda2.cu:203-209)."""
        return (
            f"process {self.process_index} of {self.process_count} on "
            f"{self.hostname}: {self.local_device_count} local / "
            f"{self.global_device_count} global {self.backend} device(s)"
        )


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    error_policy: ErrorPolicy = ErrorPolicy.RAISE,
) -> RuntimeContext:
    """Bring up the distributed runtime and return this process's identity.

    Single-host (tests, one TPU VM): pure introspection, no rendezvous.
    Multi-host (TPU pod slice): pass any of coordinator_address /
    num_processes / process_id (TPU pods auto-fill the rest); this performs
    the collective rendezvous that MPI_Init performs under mpiexec.
    """
    global _initialized_distributed
    with guarded("runtime initialize", error_policy):
        wants_distributed = any(
            a is not None for a in (coordinator_address, num_processes, process_id)
        )
        if wants_distributed and not _initialized_distributed:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
            _initialized_distributed = True
        return RuntimeContext(
            process_index=jax.process_index(),
            process_count=jax.process_count(),
            hostname=socket.gethostname(),
            backend=jax.default_backend(),
            local_devices=tuple(jax.local_devices()),
            global_devices=tuple(jax.devices()),
        )


def node_census(ctx: RuntimeContext) -> int:
    """Number of distinct hosts in the job.

    The reference discovers this by rank 0 collecting every rank's hostname
    into a std::set then broadcasting the count (mpicuda2.cu:118-156), to
    implement round-robin GPU binding. jax already knows: process_count is
    the host count on TPU pods (one process per host)."""
    return ctx.process_count
