"""One typed configuration object replacing the reference's three config tiers.

The reference configures behavior through (a) compile-time ``#define``
switches — GPU, NO_LOG, REDUCE_CPU/REDUCE_GPU, DOUBLE_, MPI_RROBIN_,
NO_GPU_MALLOC_TIME, HOST_COPY, PAGE_LOCKED, MPI_ERR_USE_EXCEPTIONS
(/root/reference/mpicuda3.cu:18-24, mpi-pingpong-gpu-async.cpp:43-49,
mpierr.h:48) — (b) argv for sizes (mpi-pingpong-gpu.cpp:31,
mpi-2d-stencil-subarray-cuda.cu:131-138), and (c) env vars for runtime
discovery (MV2_COMM_WORLD_LOCAL_RANK etc., -cuda.cu:46-69). Here all of it
is one frozen dataclass, parseable from argv and env, passed explicitly.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence

import jax.numpy as jnp

from tpuscratch.runtime.errors import ErrorPolicy

_DTYPES = {
    "float32": jnp.float32,
    "bfloat16": jnp.bfloat16,
    "float64": jnp.float64,  # requires jax_enable_x64; fp64 parity w/ DOUBLE_
    "int32": jnp.int32,
}


@dataclasses.dataclass(frozen=True)
class Config:
    # -- compute path ----------------------------------------------------
    dtype: str = "float32"           # DOUBLE_ switch parity, but runtime-typed
    use_pallas: bool = True          # GPU vs host-loop switch parity: pallas
    #                                  kernel vs plain jnp reference path
    block_rows: int = 512            # kernel block shape (BLOCK_SIZE parity,
    #                                  mpicuda3.cu:65 raised 256->512)
    reduce_on_device: bool = True    # REDUCE_GPU vs host-accumulate parity
    # -- mesh ------------------------------------------------------------
    mesh_shape: Optional[tuple[int, ...]] = None  # None = auto (all devices)
    periodic: bool = True
    # -- problem sizes (argv tier) ---------------------------------------
    tile_width: int = 16             # reference default tile (subarray.cpp:71)
    tile_height: int = 16
    stencil_width: int = 5           # reference default 5x5 stencil
    stencil_height: int = 5
    elements: int = 1 << 20          # message/vector size (argv parity)
    # -- instrumentation -------------------------------------------------
    log: bool = True                 # NO_LOG parity
    include_setup_time: bool = True  # NO_GPU_MALLOC_TIME parity
    error_policy: ErrorPolicy = ErrorPolicy.RAISE  # MPI_ERR_USE_EXCEPTIONS

    # ---- derived -------------------------------------------------------

    @property
    def jnp_dtype(self):
        try:
            return _DTYPES[self.dtype]
        except KeyError:
            raise ValueError(
                f"unknown dtype {self.dtype!r}; choose from {sorted(_DTYPES)}"
            ) from None

    @property
    def halo_width(self) -> int:
        # ghost depth = stencil//2, as in stencil2D.h:116-117
        return self.stencil_width // 2

    @property
    def halo_height(self) -> int:
        return self.stencil_height // 2

    # ---- construction --------------------------------------------------

    @classmethod
    def from_argv(cls, argv: Sequence[str], **overrides) -> "Config":
        """CLI parity with the reference drivers: positional
        ``[tile_w tile_h [stencil_w stencil_h]]`` (-cuda.cu:131-138, including
        fixing its stencilHeight self-assignment bug) or ``elements`` for the
        benchmarks (mpi-pingpong-gpu.cpp:31)."""
        fields = dict(overrides)
        args = [a for a in argv if not a.startswith("-")]
        if len(args) == 1:
            fields.setdefault("elements", int(args[0]))
        elif len(args) >= 2:
            fields.setdefault("tile_width", int(args[0]))
            fields.setdefault("tile_height", int(args[1]))
            if len(args) >= 3:
                fields.setdefault("stencil_width", int(args[2]))
            if len(args) >= 4:
                fields.setdefault("stencil_height", int(args[3]))
        return cls(**fields)

    @classmethod
    def from_env(cls, env: Optional[dict] = None, **overrides) -> "Config":
        """Env tier: TPUSCRATCH_* variables (runtime discovery only)."""
        env = dict(os.environ if env is None else env)
        fields = dict(overrides)
        if "TPUSCRATCH_DTYPE" in env:
            fields.setdefault("dtype", env["TPUSCRATCH_DTYPE"])
        if "TPUSCRATCH_NO_LOG" in env:
            fields.setdefault("log", env["TPUSCRATCH_NO_LOG"] not in ("1", "true"))
        if "TPUSCRATCH_MESH" in env:  # e.g. "2x4"
            fields.setdefault(
                "mesh_shape", tuple(int(x) for x in env["TPUSCRATCH_MESH"].split("x"))
            )
        if env.get("TPUSCRATCH_ABORT_ON_ERROR", "") in ("1", "true", "yes"):
            fields.setdefault("error_policy", ErrorPolicy.ABORT)
        return cls(**fields)

    def replace(self, **kw) -> "Config":
        return dataclasses.replace(self, **kw)
