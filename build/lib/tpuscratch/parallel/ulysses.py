"""Ulysses-style sequence parallelism: all-to-all head/sequence re-sharding.

Alternative to the ring scheme: instead of rotating KV blocks, one
``all_to_all`` re-shards the activations from sequence-sharded to
head-sharded, each rank runs exact attention for its head subset over the
FULL sequence, and a second all_to_all restores sequence sharding.
Two collectives total (vs n-1 ring hops) at the cost of requiring
``n_heads % axis_size == 0`` and O(seq) memory for the gathered K/V of the
local heads.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from tpuscratch.comm.collectives import all_to_all
from tpuscratch.parallel.scores import masked_scores


def _attn(q, k, v, causal: bool) -> jax.Array:
    """Exact attention: q,k,v (S, H, D) -> (S, H, D), fp32 accumulation.

    Materializes the (H, S, T) score block — fine for short sequences and
    the CPU-mesh tests; the ``impl='pallas'`` path below avoids it."""
    S, T = q.shape[0], k.shape[0]
    if causal:
        mask = jnp.arange(S)[:, None] >= jnp.arange(T)[None, :]
    else:
        mask = jnp.ones((S, T), dtype=bool)
    p = jax.nn.softmax(masked_scores(q, k, mask), axis=-1)
    return jnp.einsum("hst,thd->shd", p, v.astype(jnp.float32)).astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis: str,
    causal: bool = False,
    impl: str = "xla",
) -> jax.Array:
    """Exact attention, sequence sharded over ``axis`` via all-to-all.

    q, k, v: (S, H, D) blocks of a global (n*S, H, D) sequence with
    n_heads H divisible by the axis size. Returns the (S, H, D) output
    block. Call inside shard_map.

    ``impl``: 'xla' materializes the local score block (simple, fine for
    modest sequences); 'pallas' runs the flash-attention kernel
    (ops.attention) — the local attention here covers the FULL global
    sequence for this rank's head slice, so it is exactly where the
    O(S^2) score materialization stops fitting and the blockwise kernel
    matters (measured ~99 TFLOP/s non-causal / ~69 causal on v5e at
    S=4096, H=8, D=128).
    """
    if q.ndim != 3 or q.shape != k.shape or q.shape != v.shape:
        raise ValueError(f"expected equal (S,H,D) blocks, got {q.shape}/{k.shape}/{v.shape}")
    S, H, D = q.shape
    n = lax.axis_size(axis)
    if H % n:
        raise ValueError(f"n_heads {H} not divisible by axis size {n}")

    def seq_to_heads(x):
        # (S, H, D) seq-sharded -> (n*S, H/n, D) head-sharded
        return all_to_all(x, axis, split_axis=1, concat_axis=0, tiled=True)

    def heads_to_seq(x):
        return all_to_all(x, axis, split_axis=0, concat_axis=1, tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    if impl == "pallas":
        from tpuscratch.ops.attention import flash_attention

        out = flash_attention(qh, kh, vh, causal=causal)
    elif impl == "xla":
        out = _attn(qh, kh, vh, causal)
    else:
        raise ValueError(f"unknown ulysses impl {impl!r}")
    return heads_to_seq(out)
