"""Ring attention: exact attention over a sequence sharded around a ring.

Each rank holds one block of the sequence: Q stays put, the (K, V) block
rotates around the mesh axis; every hop combines the incoming KV block
into a running online-softmax state (max, normalizer, weighted sum), so
the full (seq x seq) score matrix never materializes and per-chip memory
stays O(seq/n). The rotation is the framework's ring primitive
(parallel.ring.ring_scan -> lax.ppermute over ICI); the accumulation is
the blockwise-reduction pattern of the reference's partial-sums kernels
(SURVEY.md §2.7 maps both skeletons).

Causal masking works on global positions: rank r's Q block covers rows
[r*S, (r+1)*S); the block arriving at hop i originated on rank
(r - i) mod n and covers the matching K rows. Fully-masked hops contribute
exp(-inf)=0 via the running max, so no special-casing per hop.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from tpuscratch.parallel.ring import ring_scan
from tpuscratch.parallel.scores import NEG_INF, masked_scores


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis: str,
    causal: bool = False,
    impl: str = "xla",
) -> jax.Array:
    """Exact multi-head attention, sequence sharded over ``axis``.

    q, k, v: (S, H, D) — this rank's block of a global (n*S, H, D)
    sequence. Returns this rank's (S, H, D) block of the attention output,
    bit-equivalent (up to fp assoc.) to attention on the gathered sequence.
    Call inside shard_map with the sequence dimension sharded over
    ``axis``.

    ``impl``: 'xla' computes each hop's block scores densely; 'pallas'
    runs the flash-attention kernel (ops.attention) per hop with
    ``return_state=True`` and softmax-merges the per-hop (out, m, l) —
    same math, MXU-scheduled, and the per-hop (H, S, S) score block never
    materializes (the long-block regime).
    """
    if q.ndim != 3 or q.shape != k.shape or q.shape != v.shape:
        raise ValueError(f"expected equal (S,H,D) blocks, got {q.shape}/{k.shape}/{v.shape}")
    if impl not in ("xla", "pallas"):
        raise ValueError(f"unknown ring attention impl {impl!r}")
    S, H, D = q.shape
    n = lax.axis_size(axis)
    me = lax.axis_index(axis)
    q32 = q.astype(jnp.float32)

    rows = me * S + jnp.arange(S)  # global Q positions

    # online-softmax state: running max m, normalizer l, weighted sum o
    init = (
        jnp.full((H, S), NEG_INF, dtype=jnp.float32),
        jnp.zeros((H, S), dtype=jnp.float32),
        jnp.zeros((S, H, D), dtype=jnp.float32),
    )

    def combine_xla(state, kv_block, hop):
        m, l, o = state
        kb, vb = kv_block
        src = (me - hop) % n  # origin rank of this KV block
        cols = src * S + jnp.arange(S)  # global K positions
        if causal:
            mask = rows[:, None] >= cols[None, :]
        else:
            mask = jnp.ones((S, S), dtype=bool)
        s = masked_scores(q32, kb, mask)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, :, None])          # (H, S, T)
        # guard: when every score so far is masked, s - m_new == 0 for
        # masked entries and exp would count them; zero them explicitly so
        # correctness doesn't depend on the self-block arriving first
        p = jnp.where(s > NEG_INF * 0.5, p, 0.0)
        corr = jnp.exp(m - m_new)                   # (H, S)
        l = l * corr + p.sum(axis=-1)
        pv = jnp.einsum("hst,thd->shd", p, vb.astype(jnp.float32))
        o = o * corr.T[:, :, None] + pv
        return (m_new, l, o)

    def combine_pallas(state, kv_block, hop):
        from tpuscratch.ops.attention import flash_attention

        m, l, o = state
        kb, vb = kv_block
        src = (me - hop) % n
        # per-hop flash over this KV block, in global coordinates;
        # acc_i is the hop's raw fp32 weighted sum (no normalization)
        acc_i, m_i, l_i = flash_attention(
            q, kb, vb, causal=causal,
            q_offset=me * S, kv_offset=src * S, return_state=True,
        )
        # exact softmax-merge: rescale both sides to the new running max
        m_new = jnp.maximum(m, m_i)
        c_old = jnp.exp(m - m_new)                   # (H, S)
        c_new = jnp.exp(m_i - m_new)
        l_new = l * c_old + l_i * c_new
        o_new = o * c_old.T[:, :, None] + acc_i * c_new.T[:, :, None]
        return (m_new, l_new, o_new)

    combine = combine_pallas if impl == "pallas" else combine_xla

    # return_payload=False: the KV pair is discarded after the last hop, so
    # the homeward rotation (one extra 2*S*H*D transfer) is skipped
    (m, l, o), _ = ring_scan(combine, init, (k, v), axis, return_payload=False)
    out = o / l.T[:, :, None]
    return out.astype(q.dtype)
