"""Shared attention-score math for the sequence-parallel schemes.

One definition of the scale, the mask sentinel, and the fp32 einsum so the
ring and Ulysses paths (which tests assert agree) cannot silently diverge.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def masked_scores(q: jax.Array, k: jax.Array, mask: jax.Array) -> jax.Array:
    """Masked scaled scores (H, S, T) in fp32.

    q: (S, H, D), k: (T, H, D), mask: (S, T) boolean (True = attend).
    """
    d = q.shape[-1]
    s = jnp.einsum(
        "shd,thd->hst", q.astype(jnp.float32), k.astype(jnp.float32)
    ) / jnp.sqrt(jnp.asarray(d, jnp.float32))
    return jnp.where(mask[None, :, :], s, NEG_INF)
