"""Expert parallelism: routed MoE feed-forward over an expert mesh axis.

Beyond-parity capability (the reference has no expert routing anywhere —
SURVEY.md §2.7 lists EP as absent), but its structural ancestors are the
same ones the reference exercises: the scatter of typed records to ranks
(/root/reference/mpi8.cpp:53 struct scatter) and sub-communicator
reduction (/root/reference/mpi9.cpp:51-54). Here tokens are the records,
experts the ranks, and the transport is one ``all_to_all`` over ICI in
each direction — the TPU-native replacement for per-pair Isend/Irecv.

Scheme (Switch-Transformer style, einsum dispatch/combine so everything
is static-shaped for XLA):

1. route: a linear gate scores every local token against all experts;
   top-k selection with per-(rank, expert) capacity ``C`` — tokens past
   capacity are dropped (their combine weight is zero), keeping shapes
   static.
2. dispatch: ``einsum('tec,td->ecd')`` packs tokens into per-expert
   capacity slots; ``all_to_all`` over the expert axis hands each rank
   the slots of ITS experts from every rank.
3. expert compute: each rank applies its local experts' FFN to its
   (E_local, n*C, D) batch — a large static matmul per expert, MXU-shaped.
4. combine: reverse ``all_to_all``, then ``einsum('tec,ecd->td')``
   weighted by the gate probability restores token order.

The load-balance auxiliary loss (mean fraction-routed x mean gate mass,
scaled by E) is returned alongside — it is what keeps routing from
collapsing onto one expert/rank.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from tpuscratch.comm.collectives import all_to_all


class Routing(NamedTuple):
    """Static-shaped routing plan for one rank's tokens.

    dispatch: (T, E, C) 0/1 — token t occupies slot c of expert e.
    combine:  (T, E, C) float — dispatch weighted by the gate probability.
    aux_loss: scalar load-balance loss (1.0 == perfectly uniform top-1).
    """

    dispatch: jax.Array
    combine: jax.Array
    aux_loss: jax.Array


def capacity(tokens: int, n_experts: int, factor: float = 1.25) -> int:
    """Per-expert capacity slots for ``tokens`` local tokens: the expected
    even share times ``factor``, at least 1."""
    return max(1, int(tokens * factor / n_experts))


def topk_routing(logits: jax.Array, cap: int, k: int = 1) -> Routing:
    """Top-k capacity routing from gate ``logits`` (T, E).

    Experts are chosen greedily (iterated masked top-1, the standard
    static-shaped formulation); each choice claims the next free capacity
    slot of its expert, and choices past slot ``cap`` are dropped —
    dropped tokens simply contribute zero to the combine, mirroring how
    the reference keeps buffers fixed-size and probe-sized rather than
    reallocating (/root/reference/mpi3.cpp:28-32).
    """
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    remaining = probs
    dispatch = jnp.zeros((T, E, cap), dtype=jnp.float32)
    combine = jnp.zeros((T, E, cap), dtype=jnp.float32)
    # slots already claimed per expert accumulate across the k rounds
    used = jnp.zeros((E,), dtype=jnp.int32)
    top1_frac = None
    for _ in range(k):
        choice = jnp.argmax(remaining, axis=-1)  # (T,)
        gate = jnp.take_along_axis(remaining, choice[:, None], axis=-1)[:, 0]
        onehot = jax.nn.one_hot(choice, E, dtype=jnp.int32)  # (T, E)
        if top1_frac is None:
            top1_frac = onehot.astype(jnp.float32).mean(axis=0)  # (E,)
        # slot index = tokens for the same expert ahead of me + already used
        ahead = jnp.cumsum(onehot, axis=0) - onehot  # (T, E)
        slot = (ahead + used[None, :]) * onehot  # valid where onehot
        kept = (slot < cap) & (onehot == 1)
        slot_1h = jax.nn.one_hot(
            jnp.sum(slot, axis=-1), cap, dtype=jnp.float32
        )  # (T, C)
        sel = kept.astype(jnp.float32)  # (T, E)
        dispatch = dispatch + sel[:, :, None] * slot_1h[:, None, :]
        combine = combine + (gate[:, None] * sel)[:, :, None] * slot_1h[:, None, :]
        used = used + jnp.sum(kept.astype(jnp.int32), axis=0)
        remaining = remaining * (1 - onehot)  # mask chosen expert, next round
    # Switch load-balance loss: E * <frac routed to e> . <mean gate prob e>
    aux = E * jnp.sum(top1_frac * probs.mean(axis=0))
    return Routing(dispatch, combine, aux)


def expert_ffn(x: jax.Array, w_in: jax.Array, w_out: jax.Array) -> jax.Array:
    """The per-expert MLP: (E, C', D) x (E, D, F) -> relu -> (E, C', D).

    One batched einsum per layer — E experts' matmuls fused into a single
    MXU-shaped contraction (vs the reference's one-kernel-per-rank
    compute, /root/reference/mpicuda2.cu:265-275)."""
    h = jax.nn.relu(jnp.einsum("ecd,edf->ecf", x, w_in))
    return jnp.einsum("ecf,efd->ecd", h, w_out).astype(x.dtype)


def expert_parallel_ffn(
    x: jax.Array,
    gate_w: jax.Array,
    w_in: jax.Array,
    w_out: jax.Array,
    axis: str,
    capacity_factor: float = 1.25,
    k: int = 1,
) -> tuple[jax.Array, jax.Array]:
    """Routed MoE layer, experts sharded over mesh ``axis``. Call inside
    shard_map.

    x: (T, D) local tokens. gate_w: (D, E_total) replicated gate.
    w_in/w_out: (E_local, D, F)/(E_local, F, D) THIS rank's experts.
    Returns (out (T, D), aux_loss scalar). E_total = axis_size * E_local.
    """
    n = lax.axis_size(axis)
    T, D = x.shape
    e_local = w_in.shape[0]
    e_total = n * e_local
    if gate_w.shape != (D, e_total):
        raise ValueError(
            f"gate_w {gate_w.shape} != ({D}, {e_total}) for "
            f"{e_local} local experts on a {n}-way axis"
        )
    cap = capacity(T, e_total, capacity_factor)
    route = topk_routing(x @ gate_w, cap, k=k)
    # pack: (T, E_total, C) x (T, D) -> (E_total, C, D)
    packed = jnp.einsum("tec,td->ecd", route.dispatch, x.astype(jnp.float32))
    # route out: split experts across ranks, gather every rank's slots for
    # mine -> (E_local, n*C, D)
    routed = all_to_all(packed, axis, split_axis=0, concat_axis=1, tiled=True)
    y = expert_ffn(routed, w_in.astype(jnp.float32), w_out.astype(jnp.float32))
    # route back: inverse all_to_all -> (E_total, C, D), slots back at the
    # rank whose tokens filled them
    back = all_to_all(y, axis, split_axis=1, concat_axis=0, tiled=True)
    out = jnp.einsum("tec,ecd->td", route.combine, back)
    return out.astype(x.dtype), route.aux_loss
