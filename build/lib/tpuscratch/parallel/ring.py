"""The generic ring pipeline: rotate a payload, combine at every hop.

Structure: ``carry_{i+1} = combine(carry_i, payload from rank (me - i))``,
with the payload moving one ring hop between combines. After axis-size
hops every rank has combined every rank's payload exactly once, while only
ever holding one block — the O(1)-memory property ring attention and ring
allreduce share. The reference's structural ancestor is the mpi5 neighbor
ring + the blockwise reduction of mpicuda4 (SURVEY.md §2.7).

Compiled as one ``lax.scan``: n hops, each a ppermute + combine, which XLA
can overlap (hop i's transfer runs while hop i-1's combine computes —
communication/computation overlap over ICI).
"""

from __future__ import annotations

from typing import Any, Callable, TypeVar

import jax
from jax import lax

from tpuscratch.comm.p2p import ring_perm

Carry = TypeVar("Carry")
Combine = Callable[[Carry, Any, Any], Carry]


def ring_scan(
    combine: Combine,
    init_carry: Carry,
    payload,
    axis: str,
    reverse: bool = False,
    return_payload: bool = True,
):
    """Run the rotate-and-combine pipeline over ``axis``.

    ``combine(carry, block, hop) -> carry`` sees, at hop i, the payload
    that started on rank ``(me - i) % n`` (or ``(me + i) % n`` when
    ``reverse``). ``payload`` may be any pytree. Returns
    (final_carry, payload): with ``return_payload`` the payload makes the
    full n hops and arrives back home; without it the final (homeward)
    rotation is skipped — one less block transfer per call, the right
    choice when the caller discards the payload — and None is returned in
    its place.
    """
    n = lax.axis_size(axis)
    perm = ring_perm(n, -1 if reverse else 1, periodic=True)

    def hop(state, i):
        carry, block = state
        carry = combine(carry, block, i)
        block = jax.tree.map(lambda b: lax.ppermute(b, axis, perm), block)
        return (carry, block), ()

    if return_payload:
        (carry, payload), _ = lax.scan(
            hop, (init_carry, payload), jax.numpy.arange(n)
        )
        return carry, payload
    if n > 1:
        (init_carry, payload), _ = lax.scan(
            hop, (init_carry, payload), jax.numpy.arange(n - 1)
        )
    carry = combine(init_carry, payload, jax.numpy.asarray(n - 1))
    return carry, None
