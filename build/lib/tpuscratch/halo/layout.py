"""Tile layout and region geometry — pure functions, no devices.

The reference keeps data and layout deliberately separate (``Array2D`` holds
extents/offsets/stride, never memory — stencil2D.h:30-42) and derives every
communication region with one 13-case geometric function
(``SubArrayRegion``, stencil2D.h:107-201) that is unit-testable without MPI
or CUDA (TestSubRegionExtraction, stencil2D.h:441-510). Both properties are
kept: ``TileLayout`` is a frozen value object, and all region math returns
``SubarraySpec`` values (tpuscratch.dtypes) usable on any array.

Geometry conventions (row-major, row 0 = top):
- A padded tile is ``(2*halo_y + core_h, 2*halo_x + core_w)``.
- Halo width = stencil_extent // 2 per axis (ghost depth, stencil2D.h:116).
- The border partition: 4 edge strips of core width/height + 4 corners,
  which exactly tile the ghost border — each piece is filled by one
  neighbor, so 8 transfers cover everything (periodic corners included).
"""

from __future__ import annotations

import dataclasses
import enum

from tpuscratch.dtypes import SubarraySpec
from tpuscratch.runtime.topology import Direction


class Region(enum.Enum):
    """The 13-region taxonomy: 9 border/center pieces of a bordered
    rectangle plus 4 full-length strips (stencil2D.h:79-82 equivalent)."""

    CENTER = "center"
    TOP = "top"
    BOTTOM = "bottom"
    LEFT = "left"
    RIGHT = "right"
    TOP_LEFT = "top_left"
    TOP_RIGHT = "top_right"
    BOTTOM_LEFT = "bottom_left"
    BOTTOM_RIGHT = "bottom_right"
    TOP_STRIP = "top_strip"     # full width, corners included
    BOTTOM_STRIP = "bottom_strip"
    LEFT_STRIP = "left_strip"   # full height, corners included
    RIGHT_STRIP = "right_strip"


def sub_region(base: SubarraySpec, halo_y: int, halo_x: int, region: Region) -> SubarraySpec:
    """The rectangle of ``region`` within ``base``, for border thickness
    (halo_y, halo_x). Composable: apply to a grid to get its core
    (CENTER), then to the core to get its interior pieces — the same
    double application the reference uses (stencil2D.h:353-355)."""
    oy, ox = base.offsets
    h, w = base.shape
    ih, iw = h - 2 * halo_y, w - 2 * halo_x  # interior extents
    if ih <= 0 or iw <= 0:
        raise ValueError(f"halo ({halo_y},{halo_x}) swallows base {base.shape}")

    rows = {
        "top": (oy, halo_y),
        "mid": (oy + halo_y, ih),
        "bot": (oy + h - halo_y, halo_y),
        "all": (oy, h),
    }
    cols = {
        "left": (ox, halo_x),
        "mid": (ox + halo_x, iw),
        "right": (ox + w - halo_x, halo_x),
        "all": (ox, w),
    }
    table = {
        Region.CENTER: ("mid", "mid"),
        Region.TOP: ("top", "mid"),
        Region.BOTTOM: ("bot", "mid"),
        Region.LEFT: ("mid", "left"),
        Region.RIGHT: ("mid", "right"),
        Region.TOP_LEFT: ("top", "left"),
        Region.TOP_RIGHT: ("top", "right"),
        Region.BOTTOM_LEFT: ("bot", "left"),
        Region.BOTTOM_RIGHT: ("bot", "right"),
        Region.TOP_STRIP: ("top", "all"),
        Region.BOTTOM_STRIP: ("bot", "all"),
        Region.LEFT_STRIP: ("all", "left"),
        Region.RIGHT_STRIP: ("all", "right"),
    }
    (ry, sh), (rx, sw) = (rows[table[region][0]], cols[table[region][1]])
    return SubarraySpec(offsets=(ry, rx), shape=(sh, sw))


_DIR_TO_REGION = {
    Direction.TOP: Region.TOP,
    Direction.BOTTOM: Region.BOTTOM,
    Direction.LEFT: Region.LEFT,
    Direction.RIGHT: Region.RIGHT,
    Direction.TOP_LEFT: Region.TOP_LEFT,
    Direction.TOP_RIGHT: Region.TOP_RIGHT,
    Direction.BOTTOM_LEFT: Region.BOTTOM_LEFT,
    Direction.BOTTOM_RIGHT: Region.BOTTOM_RIGHT,
}


@dataclasses.dataclass(frozen=True)
class TileLayout:
    """One rank's tile: core extent + ghost-border widths."""

    core_h: int
    core_w: int
    halo_y: int
    halo_x: int

    def __post_init__(self):
        if self.core_h <= 0 or self.core_w <= 0:
            raise ValueError(f"bad core {self.core_h}x{self.core_w}")
        if self.halo_y < 0 or self.halo_x < 0:
            raise ValueError(f"bad halo {self.halo_y},{self.halo_x}")
        if self.halo_y > self.core_h or self.halo_x > self.core_w:
            raise ValueError("halo deeper than core: neighbor strips overlap")

    @classmethod
    def for_stencil(cls, core_h: int, core_w: int, stencil_h: int, stencil_w: int) -> "TileLayout":
        """Ghost depth = stencil extent // 2 (stencil2D.h:116-117)."""
        return cls(core_h, core_w, stencil_h // 2, stencil_w // 2)

    @property
    def padded_shape(self) -> tuple[int, int]:
        return (self.core_h + 2 * self.halo_y, self.core_w + 2 * self.halo_x)

    @property
    def whole(self) -> SubarraySpec:
        return SubarraySpec((0, 0), self.padded_shape)

    @property
    def core(self) -> SubarraySpec:
        return sub_region(self.whole, self.halo_y, self.halo_x, Region.CENTER)

    def halo_region(self, d: Direction) -> SubarraySpec:
        """The ghost-border piece in direction ``d`` — the RECEIVE landing
        zone for data arriving from the ``d`` neighbor."""
        return sub_region(self.whole, self.halo_y, self.halo_x, _DIR_TO_REGION[d])

    def send_region(self, d: Direction) -> SubarraySpec:
        """The core strip adjacent to edge ``d`` — what travels TO the
        ``d`` neighbor (landing in their ``opposite(d)`` halo).

        Edge strips span the FULL core width/height (not the 13-region
        interior piece): the border partition pairs each full-length core
        edge with the equally-sized halo edge on the receiving side, and
        corners pair with corners, so the 8 pieces tile the whole border.
        """
        dr, dc = d.offset
        oy, ox = self.halo_y, self.halo_x  # core origin in padded coords
        if dr < 0:
            ry, sh = oy, self.halo_y
        elif dr > 0:
            ry, sh = oy + self.core_h - self.halo_y, self.halo_y
        else:
            ry, sh = oy, self.core_h
        if dc < 0:
            rx, sw = ox, self.halo_x
        elif dc > 0:
            rx, sw = ox + self.core_w - self.halo_x, self.halo_x
        else:
            rx, sw = ox, self.core_w
        return SubarraySpec(offsets=(ry, rx), shape=(sh, sw))
