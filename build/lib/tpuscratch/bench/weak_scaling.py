"""Weak-scaling stencil benchmark (BASELINE config 5).

Fixed per-chip tile, growing device mesh: ideal scaling keeps per-chip
cell-updates/s constant, so efficiency(N) = rate_per_chip(N) /
rate_per_chip(1). The reference has no weak-scaling harness — its scaling
story is the qualitative capacity note at
/root/reference/mpicuda2.cu:44-47 — so this establishes the methodology
the reference lacks: same program, same per-rank work, mesh as the only
variable. On one host the mesh is virtual CPU devices (the reference's
N-ranks-on-one-box trick, mpicuda2.cu:31-32); on a slice it is the real
chip grid.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax

from tpuscratch.bench.stencil_bench import bench_stencil
from tpuscratch.bench.timing import BenchResult
from tpuscratch.runtime.mesh import make_mesh_2d
from tpuscratch.runtime.topology import factor2d


@dataclasses.dataclass(frozen=True)
class WeakScalingPoint:
    n_devices: int
    dims: tuple[int, int]
    grid: tuple[int, int]
    result: BenchResult

    @property
    def per_chip_rate(self) -> float:
        return self.result.items_per_s / self.n_devices


def bench_weak_scaling(
    per_chip: tuple[int, int] = (1024, 1024),
    steps: int = 10,
    device_counts: Optional[Sequence[int]] = None,
    impl: str = "xla",
    iters: int = 5,
    fence: str = "block",
) -> list[WeakScalingPoint]:
    """One point per device count; global grid grows with the mesh."""
    avail = len(jax.devices())
    if device_counts is None:
        device_counts = [n for n in (1, 2, 4, 8, 16) if n <= avail]
    points = []
    for n in sorted(device_counts):
        if n > avail:
            raise ValueError(f"{n} devices requested, {avail} visible")
        rows, cols = factor2d(n)
        grid = (rows * per_chip[0], cols * per_chip[1])
        mesh = make_mesh_2d((rows, cols), devices=jax.devices()[:n])
        points.append(
            WeakScalingPoint(
                n_devices=n,
                dims=(rows, cols),
                grid=grid,
                result=bench_stencil(
                    grid, steps, mesh=mesh, impl=impl, iters=iters, fence=fence
                ),
            )
        )
    return points


def efficiency(points: Sequence[WeakScalingPoint]) -> dict[int, float]:
    """Per-chip-rate ratio vs the smallest-mesh point."""
    if not points:
        raise ValueError("no points")
    base = min(points, key=lambda p: p.n_devices).per_chip_rate
    return {p.n_devices: p.per_chip_rate / base for p in points}


def report(points: Sequence[WeakScalingPoint]) -> str:
    eff = efficiency(points)
    lines = []
    for p in points:
        lines.append(
            f"{p.n_devices:3d} dev {p.dims[0]}x{p.dims[1]}  grid "
            f"{p.grid[0]}x{p.grid[1]}  {p.per_chip_rate:.3e} cells/s/chip  "
            f"eff {eff[p.n_devices] * 100:5.1f}%"
        )
    return "\n".join(lines)
