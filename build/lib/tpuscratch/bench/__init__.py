"""Benchmark harnesses: timing conventions, pingpong, dot, stencil."""

from tpuscratch.bench.timing import (  # noqa: F401
    BenchResult,
    percentile,
    span_max_min,
    time_device,
)
