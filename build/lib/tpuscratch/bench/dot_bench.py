"""Distributed dot-product benchmark (mpicuda3/4 timing parity).

End-to-end: shard two vectors over the mesh, per-shard Pallas reduction,
one psum, report elements/s. The reference's wall-time convention —
every rank stamps begin/end, span = max(end)-min(begin) across ranks
(mpicuda3.cu:315-325) — collapses in a single-process mesh to a
block_until_ready bracket (all shards complete before the bracket closes);
on multi-process slices use ``timing.span_max_min`` over per-process
stamps. The NO_GPU_MALLOC_TIME carve-out is the warmup exclusion.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpuscratch.bench.timing import BenchResult, time_device
from tpuscratch.comm import run_spmd
from tpuscratch.ops.reduction import local_dot_psum


def dot_program(
    mesh: Mesh,
    axis: str = "x",
    method: str = "full",
    block_rows: int = 512,
    rounds: int = 1,
):
    """Compiled distributed dot; ``rounds`` > 1 folds that many dots into
    one ``lax.scan`` so a fenced invocation amortizes fixed dispatch/
    transport cost (the same treatment the stencil bench applies).

    Each round perturbs the input by ``1e-30 * acc`` (loop-carried, so
    XLA cannot hoist the otherwise loop-invariant dot out of the scan)
    — far below f32 resolution for O(1) data, so the result is
    unchanged while every round honestly re-reads both vectors from HBM.
    The perturbation rides the kernels' in-kernel ``offset`` scalar
    (ops.reduction._offset_arg): adding it to a materialized ``a + eps``
    instead would cost every round an extra read+write of the whole
    vector outside the opaque pallas_call (~3x measured slowdown).
    """

    from tpuscratch.ops import reduction

    def one(a, b, offset=None):
        return local_dot_psum(
            a, b, axis, method=method, block_rows=block_rows, offset=offset
        )

    if rounds == 1:
        return run_spmd(mesh, one, (P(axis), P(axis)), P())

    def repeated(a, b):
        # Prep (pad/reshape to lane blocks) ONCE outside the scan for the
        # Pallas methods: XLA does not hoist it out of the loop body, and
        # paying it per round triples the measured traffic.
        if method == "xla":
            def step(acc, _):
                return one(a, b, offset=acc * jnp.float32(1e-30)), None
        else:
            x2, y2, _, block = reduction.prep(a, b, block_rows)

            def step(acc, _):
                s = reduction.dot_prepped(
                    x2, y2, block, method, offset=acc * jnp.float32(1e-30)
                )
                return lax.psum(s, axis), None

        acc, _ = lax.scan(step, jnp.float32(0.0), None, length=rounds)
        return acc

    return run_spmd(mesh, repeated, (P(axis), P(axis)), P())


def bench_dot(
    mesh: Mesh,
    n_elems: int = 100_000_000,
    axis: str = "x",
    method: str = "full",
    iters: int = 5,
    check: bool = True,
    fence: str = "block",
    rounds: int = 1,
    max_gbps: float = 1000.0,
) -> BenchResult:
    """Time ``rounds`` distributed dots of ``n_elems`` f32 (BASELINE
    config 2). ``rounds=1`` measures single-invocation latency; large
    ``rounds`` measures HBM-roofline throughput.

    ``max_gbps`` is a physical-plausibility bound: if a multi-round
    measurement beats it, the anti-hoisting perturbation has stopped
    working (e.g. a compiler rewrite distributed ``dot(x+o, y)`` into
    ``dot(x,y) + o*sum(y)`` and hoisted the invariant parts) and the
    number is rejected rather than recorded. The default is tuned just
    above v5e-class HBM (~820 GB/s) so even PARTIAL hoisting (one of the
    two operand streams skipped → apparent 2x) trips it; on parts with
    faster HBM per core (e.g. v5p ~2.7 TB/s) callers must raise it to
    ~1.3x that part's roofline to keep the same sensitivity."""
    n_dev = mesh.devices.size
    n_elems = (n_elems // n_dev) * n_dev  # even shards
    x = jnp.ones(n_elems, dtype=jnp.float32)
    f = dot_program(mesh, axis, method, rounds=rounds)
    if check:
        got = float(f(x, x))
        if abs(got - n_elems) > 1e-3 * n_elems:
            raise AssertionError(f"dot self-check FAILED: {got} != {n_elems}")
    res = time_device(
        f, x, x,
        iters=iters, warmup=2, fence=fence,
        name=f"dot {n_elems:.0e} f32 ({method}) x{rounds}",
        items=n_elems * rounds,
        bytes_moved=2 * 4 * n_elems * rounds,
    )
    if rounds > 1 and res.gbps > max_gbps:
        raise AssertionError(
            f"implausible {res.gbps:.0f} GB/s (> {max_gbps:.0f}): the scanned "
            "dot was likely hoisted out of the loop; fix dot_program's "
            "perturbation before trusting this benchmark"
        )
    return res
