"""The slice-spec algebra: indexed, subarray, struct, hindexed composition.

Parity map (reference -> here):
- ``MPI_Type_indexed`` 2 blocks (len 4 @ disp 5, len 2 @ disp 12) of a
  16-float array (/root/reference/mpi7.cpp:36-41) -> ``IndexedSpec(((5, 4),
  (12, 2)))``; the receiver's "6 plain floats" is exactly ``pack``'s output.
- ``MPI_Type_create_subarray`` (/root/reference/stencil2D.h:210-228,
  mpi-complex-types.cpp:35) -> ``SubarraySpec(offsets, shape)``; strided
  2D slices travel without manual packing, as in the reference.
- ``MPI_Type_create_struct`` over Particle {4 float; 2 int}
  (/root/reference/mpi8.cpp:13-17,53) -> ``StructSpec``: a pytree of
  same-leading-dim arrays; jax collectives already map over pytrees, so a
  "struct type" only needs to validate and split/join records.
- ``MPI_Type_create_hindexed`` over subarrays of *separately allocated*
  arrays (/root/reference/mpi-complex-types.cpp:49,88) -> ``HIndexedSpec``:
  a sequence of (array index, spec) pairs packed into one payload. Runtime
  pointer-difference displacements (:38-40) become plain list indices —
  addresses are not a concept the functional model needs.

All extents/offsets are static Python ints: the trace-time equivalent of
Type_commit. A spec is hashable and reusable across any number of
exchanges, like a committed datatype, but needs no free.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax


def _check_payload(flat, size: int) -> None:
    """Static shape check: jnp slicing clips out-of-range silently, so a
    wrong-sized payload would otherwise scatter partially — the one failure
    mode MPI's typed recv would catch that static shapes alone don't."""
    if flat.ndim != 1 or flat.shape[0] != size:
        raise ValueError(f"payload shape {flat.shape} != spec size ({size},)")


@dataclasses.dataclass(frozen=True)
class IndexedSpec:
    """Blocks of a 1D array: ((start, length), ...) — MPI_Type_indexed."""

    blocks: tuple[tuple[int, int], ...]

    def __post_init__(self):
        object.__setattr__(
            self, "blocks", tuple((int(s), int(l)) for s, l in self.blocks)
        )
        for start, length in self.blocks:
            if start < 0 or length <= 0:
                raise ValueError(f"bad block ({start}, {length})")

    @property
    def size(self) -> int:
        return sum(l for _, l in self.blocks)

    def pack(self, x: jax.Array) -> jax.Array:
        return jnp.concatenate([x[s : s + l] for s, l in self.blocks])

    def unpack(self, flat: jax.Array, x: jax.Array) -> jax.Array:
        _check_payload(flat, self.size)
        out = x
        pos = 0
        for start, length in self.blocks:
            out = lax.dynamic_update_slice(out, flat[pos : pos + length], (start,))
            pos += length
        return out


@dataclasses.dataclass(frozen=True)
class SubarraySpec:
    """A rectangular region of an N-D array — MPI_Type_create_subarray."""

    offsets: tuple[int, ...]
    shape: tuple[int, ...]

    def __post_init__(self):
        object.__setattr__(self, "offsets", tuple(int(o) for o in self.offsets))
        object.__setattr__(self, "shape", tuple(int(s) for s in self.shape))
        if len(self.offsets) != len(self.shape):
            raise ValueError(f"rank mismatch {self.offsets} vs {self.shape}")
        if any(o < 0 for o in self.offsets) or any(s <= 0 for s in self.shape):
            raise ValueError(f"bad subarray {self.offsets}/{self.shape}")

    @property
    def size(self) -> int:
        return math.prod(self.shape)

    def region(self, x: jax.Array) -> jax.Array:
        """The subarray itself, in its N-D shape."""
        idx = tuple(slice(o, o + s) for o, s in zip(self.offsets, self.shape))
        return x[idx]

    def pack(self, x: jax.Array) -> jax.Array:
        return self.region(x).reshape(-1)

    def unpack(self, flat: jax.Array, x: jax.Array) -> jax.Array:
        _check_payload(flat, self.size)
        return lax.dynamic_update_slice(
            x, flat.reshape(self.shape), self.offsets
        )


@dataclasses.dataclass(frozen=True)
class StructSpec:
    """Records spread across a pytree of arrays (struct-of-arrays layout).

    The reference's array-of-structs Particle buffer (mpi8.cpp:13-17) is a
    layout forced by C memory; the TPU-native layout for the same records is
    struct-of-arrays, which keeps each field contiguous for vector loads.
    ``fields`` names the leaves; all leaves share leading dim = record count.
    """

    fields: tuple[str, ...]

    def validate(self, tree: dict) -> int:
        if set(tree.keys()) != set(self.fields):
            raise ValueError(f"fields {sorted(tree)} != spec {sorted(self.fields)}")
        counts = {k: tree[k].shape[0] for k in self.fields}
        n = next(iter(counts.values()))
        if any(c != n for c in counts.values()):
            raise ValueError(f"ragged record counts {counts}")
        return n

    def records(self, tree: dict, start: int, count: int) -> dict:
        """A contiguous run of records — e.g. one rank's scatter share."""
        self.validate(tree)
        return {k: lax.dynamic_slice_in_dim(tree[k], start, count, 0) for k in self.fields}

    def concat(self, trees: Sequence[dict]) -> dict:
        for t in trees:
            self.validate(t)
        return {
            k: jnp.concatenate([t[k] for t in trees], axis=0) for k in self.fields
        }


@dataclasses.dataclass(frozen=True)
class HIndexedSpec:
    """Regions of several separately-allocated arrays in one message.

    ``parts[i] = (array_index, spec)``: which input array, and which region
    of it. mpi-complex-types parity: 3-element blocks of 3 separate arrays
    sent as one payload.
    """

    parts: tuple[tuple[int, "IndexedSpec | SubarraySpec"], ...]

    def __post_init__(self):
        object.__setattr__(self, "parts", tuple(self.parts))

    @property
    def size(self) -> int:
        return sum(spec.size for _, spec in self.parts)

    def pack(self, arrays: Sequence[jax.Array]) -> jax.Array:
        return jnp.concatenate(
            [spec.pack(arrays[i]) for i, spec in self.parts]
        )

    def unpack(self, flat: jax.Array, arrays: Sequence[jax.Array]) -> list[jax.Array]:
        _check_payload(flat, self.size)
        out = list(arrays)
        pos = 0
        for i, spec in self.parts:
            out[i] = spec.unpack(flat[pos : pos + spec.size], out[i])
            pos += spec.size
        return out


def exchange_packed(spec, x, axis, perm, dest_spec=None):
    """pack -> ppermute -> unpack: a structured region travels to the
    permutation's destination and lands in ``dest_spec``'s region there
    (defaults to the send region). The one-line equivalent of commit +
    Isend/Irecv with a derived datatype on both sides.
    """
    payload = spec.pack(x)
    arrived = lax.ppermute(payload, axis, list(perm))
    return (dest_spec or spec).unpack(arrived, x)
