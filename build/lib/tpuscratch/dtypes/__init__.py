"""Structured slice specs — MPI derived datatypes, functionally.

An MPI derived datatype describes which bytes of a buffer form a message
(indexed / struct / subarray / hindexed, SURVEY.md §2.2). Under XLA there
are no buffers-with-layouts to describe — but the same *selection algebra*
is still needed: "these blocks of that array travel together". Here a spec
is an immutable value with two pure functions:

- ``pack(arrays)``   -> flat contiguous vector (the message payload)
- ``unpack(flat, arrays)`` -> arrays with the payload scattered back in

Both are jit-compatible with static shapes, so ``pack -> ppermute ->
unpack`` inside ``shard_map`` is the exact analogue of committing a
datatype and passing it to Isend/Irecv — except XLA fuses the gather into
the transfer and there is nothing to commit or free.
"""

from tpuscratch.dtypes.specs import (  # noqa: F401
    HIndexedSpec,
    IndexedSpec,
    StructSpec,
    SubarraySpec,
    exchange_packed,
)
