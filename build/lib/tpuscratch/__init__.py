"""tpuscratch — a TPU-native distributed-computing framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of the CUDA+MPI
scratchpad ``ugovaretto-accel/cuda-mpi-scratch`` (surveyed in ``SURVEY.md``):

- **runtime**  — mesh/topology bring-up, typed config, error policies,
  rank-prefixed logging (replaces ``MPI_Init``/``mpierr.h``/cartesian setup).
- **comm**     — named collectives and point-to-point patterns over mesh axes
  (replaces the raw ``MPI_*`` call surface: psum/ppermute/all_gather/...).
- **dtypes**   — structured slice specs, the functional equivalent of MPI
  derived datatypes (indexed / struct / subarray / hindexed).
- **halo**     — the flagship: a generic 2D domain-decomposition library with
  8-neighbor periodic ghost-cell exchange (replaces ``stencil2D.h``).
- **ops**      — Pallas TPU kernels: reductions, stencil compute, fills
  (replaces the CUDA ``__global__`` kernels).
- **bench**    — timing harnesses: pingpong latency/BW, distributed dot,
  stencil throughput (replaces ``test-benchmark/``).

Everything is runnable on a single host via a CPU device mesh
(``XLA_FLAGS=--xla_force_host_platform_device_count=N``), mirroring how the
reference validates multi-node behavior with many ranks on one box.
"""

__version__ = "0.1.0"

from tpuscratch.runtime.topology import CartTopology, Direction  # noqa: F401
from tpuscratch.runtime.mesh import make_mesh, make_mesh_1d, make_mesh_2d  # noqa: F401
from tpuscratch.runtime.config import Config  # noqa: F401
from tpuscratch.runtime.context import RuntimeContext, initialize  # noqa: F401
