"""Pallas flash-attention kernel — blockwise exact attention, MXU path.

The reference has no attention anywhere (SURVEY.md §2.7: no sequence
dimension exists); this kernel is part of the framework's long-context
surface, beyond reference parity. The sequence-parallel schemes in
``tpuscratch.parallel`` bound *cross-chip* memory by sharding the
sequence; this kernel bounds *on-chip* memory for the local attention
those schemes still compute — most importantly the Ulysses path, whose
all-to-all hands every rank the FULL global sequence for its head slice
(parallel/ulysses.py), where a naive (S, S) score materialization is
exactly the memory blowup flash attention exists to avoid.

Shape contract matches ``parallel.scores.masked_scores`` semantics:
q (S, H, D), k/v (T, H, D), fp32 online-softmax accumulation, causal
masking on global positions via ``q_offset``/``kv_offset`` (scalars, so
ring-attention hops can reuse the kernel with rotated K origins).

Kernel structure (the canonical TPU flash schedule):
- grid (H, S/block_q, T/block_k); the KV axis is the innermost,
  sequential ("arbitrary") dimension — the VMEM scratch carrying the
  online-softmax state (running max, normalizer, fp32 accumulator) is
  revisited across KV steps, initialized at the first step, and the
  normalized output is emitted at the last.
- both matmuls (scores = q @ k^T, update = p @ v) hit the MXU with
  ``preferred_element_type=float32``; the VPU handles the softmax
  bookkeeping in between.
- the running max / normalizer live in (block_q, 128) VMEM scratch with
  values broadcast across lanes: Mosaic wants lane-complete vector
  stores, and a broadcast store + column-0 read is free compared to the
  relayouts a (block_q, 1) slice store would trigger.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpuscratch.ops.common import use_interpret
from tpuscratch.parallel.scores import NEG_INF

_LANE = 128


def _score_block(
    q_ref, k_ref, qoff_ref, koff_ref, i, j,
    *, scale: float, causal: bool, block_q: int, block_k: int,
):
    """Scaled (and causally masked) score block + the masked-p guard.

    THE one definition shared by the forward and both backward kernels —
    a masking fix applied here cannot leave forward and gradient
    inconsistent. Returns (s, guard) where ``p`` values must be passed
    through ``jnp.where(guard, p, 0.0)`` after exponentiation (rows whose
    every score is masked otherwise exponentiate s - m == 0)."""
    q = q_ref[0].astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32)
    s = lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    if causal:
        rows = qoff_ref[0] + i * block_q + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        cols = koff_ref[0] + j * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        s = jnp.where(rows >= cols, s, NEG_INF)
    return s, s > NEG_INF * 0.5


def _flash_kernel(
    qoff_ref, koff_ref, q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
    *, scale: float, causal: bool, block_q: int, block_k: int, nk: int,
    m_ref=None, l_ref=None,
):
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    if causal:
        # block-level causal skip: a KV block strictly above this Q
        # block's last row contributes nothing — skip its MXU/VPU work
        # entirely (~2x for long sequences; the DMA still happens, which
        # is what keeps the skip correct under Mosaic's static pipeline)
        first_masked_col = qoff_ref[0] + (i + 1) * block_q
        block_needed = koff_ref[0] + j * block_k < first_masked_col
    else:
        block_needed = True

    @pl.when(block_needed)
    def _compute():
        s, guard = _score_block(
            q_ref, k_ref, qoff_ref, koff_ref, i, j,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        )
        m_prev = m_scr[:, 0]                       # (block_q,)
        l_prev = l_scr[:, 0]
        m_new = jnp.maximum(m_prev, s.max(axis=1))
        p = jnp.exp(s - m_new[:, None])
        # fully-masked rows keep m_new == NEG_INF, making s - m_new == 0
        # for masked entries; zero them so correctness is hop-order
        # independent (same guard as parallel/ring_attention.py)
        p = jnp.where(guard, p, 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + p.sum(axis=1)
        acc_scr[...] = acc_scr[...] * corr[:, None] + lax.dot(
            p, v_ref[0].astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new[:, None], l_scr.shape)

    @pl.when(j == nk - 1)
    def _emit():
        if m_ref is None:
            l_fin = l_scr[:, 0]
            safe = jnp.where(l_fin > 0.0, l_fin, 1.0)  # fully-masked row->0
            o_ref[0] = (acc_scr[...] / safe[:, None]).astype(o_ref.dtype)
        else:
            # state mode: emit the RAW fp32 accumulator (no divide, no
            # dtype cast — the caller's softmax-merge stays exact) plus
            # the running max / normalizer broadcast over an 8-lane
            # plane. Mosaic requires lane-complete block stores and a
            # sublane-divisible block shape, which rules out both a bare
            # (1, block_q) state row and the full 128-lane broadcast;
            # 8 lanes is the narrowest legal layout (column 0 is read
            # back outside).
            o_ref[0] = acc_scr[...]
            m_ref[0] = m_scr[:, :8]
            l_ref[0] = l_scr[:, :8]


def _flash_kernel_state(
    qoff_ref, koff_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
    m_scr, l_scr, acc_scr, **kw,
):
    """Positional reordering for the three-output variant: pallas passes
    (inputs..., outputs..., scratch...); the base kernel wants the state
    outputs as keywords."""
    _flash_kernel(
        qoff_ref, koff_ref, q_ref, k_ref, v_ref, o_ref,
        m_scr, l_scr, acc_scr, m_ref=m_ref, l_ref=l_ref, **kw,
    )


def _pick_block(n: int, want: int, name: str) -> int:
    """Largest power-of-two block <= want that divides n.

    Refuses blocks below the 8-row sublane quantum (unless the dimension
    itself is smaller): a sequence length with no power-of-two divisor
    would silently degrade to per-row grid steps, orders of magnitude
    slower than the dense fallback — pad the sequence instead."""
    b = want
    while b > 1 and n % b:
        b //= 2
    if b < 8 and n >= 8:
        raise ValueError(
            f"{name}={n} has no power-of-two block divisor >= 8; pad the "
            "sequence to a multiple of 8 (or use the dense xla path)"
        )
    return max(b, 1)


def _dq_kernel(
    qoff_ref, koff_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dq_ref, dq_scr,
    *, scale: float, causal: bool, block_q: int, block_k: int, nk: int,
):
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    if causal:
        first_masked_col = qoff_ref[0] + (i + 1) * block_q
        block_needed = koff_ref[0] + j * block_k < first_masked_col
    else:
        block_needed = True

    @pl.when(block_needed)
    def _compute():
        s, guard = _score_block(
            q_ref, k_ref, qoff_ref, koff_ref, i, j,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        )
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, 0]
        p = jnp.exp(s - lse[:, None])
        p = jnp.where(guard, p, 0.0)  # fully-masked-row guard
        dp = lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0][:, 0][:, None])
        dq_scr[...] += scale * lax.dot(
            ds, k, preferred_element_type=jnp.float32
        )

    @pl.when(j == nk - 1)
    def _emit():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(
    qoff_ref, koff_ref, k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
    dk_ref, dv_ref, dk_scr, dv_scr,
    *, scale: float, causal: bool, block_q: int, block_k: int, nq: int,
):
    j = pl.program_id(1)  # kv block
    i = pl.program_id(2)  # q block (innermost, sequential)

    @pl.when(i == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    if causal:
        first_masked_col = qoff_ref[0] + (i + 1) * block_q
        block_needed = koff_ref[0] + j * block_k < first_masked_col
    else:
        block_needed = True

    @pl.when(block_needed)
    def _compute():
        s, guard = _score_block(
            q_ref, k_ref, qoff_ref, koff_ref, i, j,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        )
        q = q_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, 0]
        p = jnp.exp(s - lse[:, None])
        p = jnp.where(guard, p, 0.0)
        # dv += p^T @ do ; ds = p * (do v^T - delta) ; dk += ds^T @ q
        dv_scr[...] += lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0][:, 0][:, None])
        dk_scr[...] += scale * lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(i == nq - 1)
    def _emit():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _plane(x):  # (H, S) -> (H, S, 8) lane-broadcast input plane
    return jnp.broadcast_to(x[:, :, None], (*x.shape, 8))


def _flash_bwd_call(q, k, v, do, lse, delta, qoff, koff, causal, bq, bk):
    """dq/dk/dv via the two backward kernels. All of q/k/v/do are
    (H, SorT, D) head-major; lse/delta are (H, S)."""
    H, S, D = q.shape
    T = k.shape[1]
    nq, nk = S // bq, T // bk
    scale = 1.0 / float(D) ** 0.5
    interpret = use_interpret()
    params = {}
    if not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        )
    lse_p, delta_p = _plane(lse), _plane(delta)
    qspec = pl.BlockSpec((1, bq, D), lambda h, a, b: (h, a, 0))
    kspec = pl.BlockSpec((1, bk, D), lambda h, a, b: (h, b, 0))
    rowspec = pl.BlockSpec((1, bq, 8), lambda h, a, b: (h, a, 0))
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, causal=causal,
            block_q=bq, block_k=bk, nk=nk,
        ),
        grid=(H, nq, nk),
        in_specs=[smem, smem, qspec, kspec, kspec, qspec, rowspec, rowspec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((H, S, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
        **params,
    )(qoff, koff, q, k, v, do, lse_p, delta_p)
    # dkv grid: (h, kv block, q block); q-side specs index by the LAST
    # grid axis now
    qspec2 = pl.BlockSpec((1, bq, D), lambda h, b, a: (h, a, 0))
    kspec2 = pl.BlockSpec((1, bk, D), lambda h, b, a: (h, b, 0))
    rowspec2 = pl.BlockSpec((1, bq, 8), lambda h, b, a: (h, a, 0))
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, causal=causal,
            block_q=bq, block_k=bk, nq=nq,
        ),
        grid=(H, nk, nq),
        in_specs=[smem, smem, kspec2, kspec2, qspec2, qspec2,
                  rowspec2, rowspec2],
        out_specs=[kspec2, kspec2],
        out_shape=[
            jax.ShapeDtypeStruct((H, T, D), k.dtype),
            jax.ShapeDtypeStruct((H, T, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        interpret=interpret,
        **params,
    )(qoff, koff, k, v, q, do, lse_p, delta_p)
    return dq, dk, dv


def _flash_fwd_call(qh, kh, vh, qoff, koff, causal, bq, bk, return_state):
    """The forward pallas_call, head-major: qh (H, S, D), kh/vh (H, T, D).
    Plain: out (H, S, D). State: (acc (H, S, D) f32, m (H, S), l (H, S))."""
    H, S, D = qh.shape
    T = kh.shape[1]
    nq, nk = S // bq, T // bk
    scale = 1.0 / float(D) ** 0.5
    kern = functools.partial(
        _flash_kernel_state if return_state else _flash_kernel,
        scale=scale, causal=causal, block_q=bq, block_k=bk, nk=nk,
    )
    interpret = use_interpret()
    params = {}
    if not interpret:
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        )
    out_specs = [pl.BlockSpec((1, bq, D), lambda h, i, j: (h, i, 0))]
    out_shape = [jax.ShapeDtypeStruct((H, S, D), qh.dtype)]
    if return_state:
        # raw fp32 accumulator + 8-lane state planes (column 0 = value)
        out_shape[0] = jax.ShapeDtypeStruct((H, S, D), jnp.float32)
        out_specs += [pl.BlockSpec((1, bq, 8), lambda h, i, j: (h, i, 0))] * 2
        out_shape += [jax.ShapeDtypeStruct((H, S, 8), jnp.float32)] * 2
    res = pl.pallas_call(
        kern,
        grid=(H, nq, nk),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, bq, D), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec((1, bk, D), lambda h, i, j: (h, j, 0)),
            pl.BlockSpec((1, bk, D), lambda h, i, j: (h, j, 0)),
        ],
        out_specs=out_specs if return_state else out_specs[0],
        out_shape=out_shape if return_state else out_shape[0],
        scratch_shapes=[
            pltpu.VMEM((bq, _LANE), jnp.float32),
            pltpu.VMEM((bq, _LANE), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        interpret=interpret,
        **params,
    )(qoff, koff, qh, kh, vh)
    if return_state:
        acc, m, l = res
        return acc, m[..., 0], l[..., 0]
    return res


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _flash_diff(qh, kh, vh, qoff, koff, causal, bq, bk):
    """Differentiable head-major flash attention (the custom-vjp seam)."""
    return _flash_fwd_call(qh, kh, vh, qoff, koff, causal, bq, bk, False)


def _flash_diff_fwd(qh, kh, vh, qoff, koff, causal, bq, bk):
    acc, m, l = _flash_fwd_call(qh, kh, vh, qoff, koff, causal, bq, bk, True)
    l_safe = jnp.maximum(l, 1e-30)
    o = (acc / l_safe[:, :, None]).astype(qh.dtype)
    lse = m + jnp.log(l_safe)  # log-sum-exp: all the backward needs
    # o saved in the INPUT dtype (FlashAttention-2's choice): for bf16
    # training the residual costs half the fp32 accumulator; delta still
    # accumulates in fp32 from the casts
    return o, (qh, kh, vh, qoff, koff, o, lse)


def _flash_diff_bwd(causal, bq, bk, res, do):
    import numpy as np

    qh, kh, vh, qoff, koff, o, lse = res
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    )  # (H, S)
    dq, dk, dv = _flash_bwd_call(
        qh, kh, vh, do, lse, delta, qoff, koff, causal, bq, bk
    )
    # integer offsets are non-differentiable: float0 cotangents
    zero = np.zeros(qoff.shape, dtype=jax.dtypes.float0)
    return dq, dk, dv, zero, zero


_flash_diff.defvjp(_flash_diff_fwd, _flash_diff_bwd)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "return_state"),
)
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    q_offset=0,
    kv_offset=0,
    block_q: int = 512,
    block_k: int = 1024,
    return_state: bool = False,
):
    """Exact attention with O(S·D) memory per head: q (S, H, D),
    k/v (T, H, D) -> (S, H, D). Offsets place the blocks in global
    coordinates for causal masking (both default 0: a self-contained
    sequence).

    Differentiable: a custom VJP recomputes score blocks from the saved
    log-sum-exp (the standard flash backward — two Pallas kernels
    producing dq and dk/dv, never materializing the (S, T) score
    matrix).

    ``return_state=True`` changes the contract for cross-block merging
    (ring attention's hops): returns ``(acc, m, l)`` where ``acc`` is the
    UNNORMALIZED fp32 weighted sum (S, H, D) and ``m``/``l`` are the
    running max / normalizer, each (H, S) fp32. The caller merges blocks
    with ``acc*exp(m-m')`` algebra and divides by the merged ``l`` once
    at the end — exact, with no per-hop normalize/un-normalize round
    trip through the input dtype. The state mode is forward-only."""
    if q.ndim != 3 or k.shape != v.shape or q.shape[1:] != k.shape[1:]:
        raise ValueError(f"bad attention shapes {q.shape}/{k.shape}/{v.shape}")
    S, H, D = q.shape
    T = k.shape[0]
    bq = _pick_block(S, block_q, "S")
    bk = _pick_block(T, block_k, "T")

    qh = jnp.swapaxes(q, 0, 1)  # (H, S, D)
    kh = jnp.swapaxes(k, 0, 1)
    vh = jnp.swapaxes(v, 0, 1)
    qoff = jnp.asarray(q_offset, jnp.int32).reshape(1)
    koff = jnp.asarray(kv_offset, jnp.int32).reshape(1)

    if return_state:
        acc, m, l = _flash_fwd_call(
            qh, kh, vh, qoff, koff, causal, bq, bk, True
        )
        return jnp.swapaxes(acc, 0, 1), m, l
    out = _flash_diff(qh, kh, vh, qoff, koff, causal, bq, bk)
    return jnp.swapaxes(out, 0, 1)
